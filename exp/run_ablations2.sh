#!/bin/bash
cd "$(dirname "$0")/.."
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
run() {
  echo "=== $1 ($(date +%H:%M:%S)) ==="
  timeout 600 python exp/mfu_ablate.py "$1" 2>&1 | tail -3
}
run '{"name": "fwd", "batch": 8, "mode": "fwd"}'
run '{"name": "fwd_bwd", "batch": 8, "mode": "fwd_bwd"}'
run '{"name": "nodrop", "batch": 8, "dropout": 0.0}'
run '{"name": "loss_sum", "batch": 8, "mode": "loss_sum"}'
run '{"name": "noflash_b4", "batch": 4, "flash": false}'
run '{"name": "nodrop_rbg", "batch": 8, "dropout": 0.0, "prng_impl": "rbg"}'
echo "=== DONE ($(date +%H:%M:%S)) ==="
