"""Preemption-safe `exp/` runs, on by default.

Every long-running exp/ entry point wraps its train state in an
:class:`ExpRunGuard`:

    guard = ExpRunGuard("mfu_ablate_" + NAME)
    state, start = guard.restore({"params": params, "opt": opt_state})
    for i in range(start, ITERS):
        ... run one step ...
        guard.update(i + 1, state)   # in-memory handoff, no disk I/O
    guard.finish()                   # completed: drop the resume dir

Semantics:

 - A SIGTERM (cloud preemption notice) triggers ONE synchronous
   CheckpointManager save of the newest state handed to ``update``,
   then exit 143; a failed save exits 75 (EX_TEMPFAIL) so the operator
   can tell the difference (see fleet.elastic.preemption).  The
   relaunched run's ``restore`` resumes from the newest committed step.
 - ``update`` itself only swaps in-memory references (a benchmark's
   step timing must not absorb checkpoint I/O); pass ``every=N`` to
   also commit periodically — that's the SIGKILL story, where no
   handler gets to run.  Note the donation caveat: if SIGTERM lands
   while a donating compiled step is executing, the held references
   point at donated buffers and the save fails — that's the 75 path,
   and the relaunch falls back to the last committed step.
 - Opt out with ``EXP_CKPT=0`` (every method no-ops); redirect the
   checkpoint root with ``EXP_CKPT_DIR`` (default
   ``exp/ckpt/<name>``).  Crash debris from earlier preempted runs is
   janitored by the manager's startup sweep.
"""
from __future__ import annotations

import logging
import os

from paddle_tpu.distributed.checkpoint_manager import CheckpointManager
from paddle_tpu.distributed.fleet.elastic.preemption import (
    clear_preemption_handler, on_preemption)

__all__ = ["ExpRunGuard"]

logger = logging.getLogger(__name__)


def _tracer():
    """The step tracer, or None — the guard must keep working when
    observability is stripped, and a broken import must never turn a
    preemption save into a crash."""
    try:
        from paddle_tpu.observability.trace import get_tracer
        return get_tracer()
    except Exception:
        return None


class ExpRunGuard:
    def __init__(self, name, root=None, enabled=None, every=None,
                 keep_last_n=2):
        if enabled is None:
            enabled = os.environ.get("EXP_CKPT", "1") != "0"
        self.enabled = enabled
        self.every = every
        self._step = 0
        self._state = None
        self._mgr = None
        if not enabled:
            return
        if root is None:
            base = os.environ.get(
                "EXP_CKPT_DIR",
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "ckpt"))
            root = os.path.join(base, name)
        self.root = root
        self._mgr = CheckpointManager(root, keep_last_n=keep_last_n,
                                      durable=True)
        on_preemption(self._save_now)

    def _save_now(self):
        tr = _tracer()
        if tr is not None and tr.enabled:
            # the flight recorder's SIGTERM trigger: dump the span window
            # BEFORE the save — if the save fails (donated buffers, full
            # disk) the recorder still has the run's last moments
            tr.flight_dump(reason="sigterm")
        if self._mgr is None or self._state is None:
            return
        logger.warning("preemption: committing step %d to %s",
                       self._step, self.root)
        if tr is not None and tr.enabled:
            with tr.phase("checkpoint"):
                self._mgr.save(self._step, self._state, block=True)
        else:
            self._mgr.save(self._step, self._state, block=True)

    def restore(self, template):
        """Resume point: ``(state, start_step)`` — ``(template, 0)`` on
        a fresh run or when disabled."""
        if self._mgr is None:
            return template, 0
        state, step = self._mgr.restore_latest(template=template)
        if step is not None:
            logger.warning("resuming %s from preempted step %d",
                           self.root, step)
        return state, step or 0

    def update(self, step, state):
        """Hand the guard the newest state (cheap: reference swap)."""
        self._step, self._state = int(step), state
        if self._mgr is not None and self.every \
                and step % self.every == 0:
            tr = _tracer()
            if tr is not None and tr.enabled:
                with tr.phase("checkpoint"):
                    self._mgr.save(step, state, block=True)
            else:
                self._mgr.save(step, state, block=True)

    def finish(self):
        """The run completed: uninstall the handler and remove the
        resume directory — a finished experiment must not be 'resumed'
        past its end by the next launch."""
        if self._mgr is None:
            return
        clear_preemption_handler()
        self._mgr.close()
        import shutil
        shutil.rmtree(self.root, ignore_errors=True)
        self._state = None
