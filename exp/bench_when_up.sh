#!/bin/bash
# Wait for the TPU tunnel to recover, then run the full bench.
cd /root/repo
for i in $(seq 1 40); do
  echo "=== probe attempt $i ($(date +%H:%M:%S))"
  if timeout 120 python -c "
import jax
x = jax.numpy.ones((128,128), jax.numpy.bfloat16)
print('tunnel ok', float((x@x).sum()))"; then
    echo "=== tunnel up, running bench ($(date +%H:%M:%S))"
    python /root/repo/bench.py > /tmp/bench_full.log 2>&1
    echo "=== bench rc=$? ($(date +%H:%M:%S))"
    exit 0
  fi
  sleep 120
done
echo "=== gave up"
exit 1
