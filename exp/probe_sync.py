"""Per-step SYNCED timing probe: forces a scalar readback every step so
async-dispatch artifacts can't fake throughput. Compares dropout on/off in
one process. Usage: python exp/probe_sync.py [batch]
"""
import json
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 8
SEQ = 1024
STEPS = 12

import paddle_tpu as pt  # noqa: E402
from paddle_tpu.jit.api import functional_call  # noqa: E402
from paddle_tpu.tensor import Tensor  # noqa: E402
from paddle_tpu.incubate.models import (GPTForCausalLM,  # noqa: E402
                                        GPTPretrainingCriterion, gpt_345m)


def build(dropout):
    pt.seed(0)
    cfg = gpt_345m(tensor_parallel=False, use_recompute=False,
                   max_position_embeddings=SEQ,
                   hidden_dropout_prob=dropout,
                   attention_probs_dropout_prob=dropout)
    model = GPTForCausalLM(cfg)
    pt.amp.decorate(model, level="O2", dtype="bfloat16")
    crit = GPTPretrainingCriterion()
    opt = pt.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=True)
    params = {k: p._data for k, p in model.named_parameters()}
    buffers = {k: b._data for k, b in model.named_buffers()}
    opt_state = opt.init_state_tree(params)
    fwd = getattr(model, "_orig_forward", model.forward)

    def step_fn(params, opt_state, ids, labels):
        def loss_of(p):
            out, _ = functional_call(model, p, buffers, (Tensor(ids),),
                                     training=True, forward_fn=fwd)
            return crit(out, Tensor(labels))._data.astype(jnp.float32), None
        (loss, _), grads = jax.value_and_grad(loss_of, has_aux=True)(params)
        new_params, new_opt = opt.apply_gradients_tree(params, grads,
                                                       opt_state)
        return loss, new_params, new_opt

    step = jax.jit(step_fn, donate_argnums=(0, 1))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (BATCH, SEQ))
                      .astype(np.int32))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (BATCH, SEQ))
                         .astype(np.int32))
    t0 = time.perf_counter()
    compiled = step.lower(params, opt_state, ids, labels).compile()
    csec = time.perf_counter() - t0
    return compiled, params, opt_state, ids, labels, csec


for dropout in ([float(sys.argv[2])] if len(sys.argv) > 2 else (0.1, 0.0)):
    compiled, params, opt_state, ids, labels, csec = build(dropout)
    times, losses = [], []
    state = (params, opt_state)
    for i in range(STEPS):
        t0 = time.perf_counter()
        loss, p2, o2 = compiled(*state, ids, labels)
        lv = float(np.asarray(loss))  # hard sync: host readback
        times.append(time.perf_counter() - t0)
        losses.append(round(lv, 4))
        state = (p2, o2)
    times_ms = [round(t * 1000, 1) for t in times]
    print(json.dumps({
        "dropout": dropout, "batch": BATCH, "compile_sec": round(csec, 1),
        "per_step_ms": times_ms,
        "median_ms": round(sorted(times_ms)[len(times_ms) // 2], 1),
        "losses": losses}))
