"""One GPT-345M MFU ablation on the real chip. Usage:

    python exp/mfu_ablate.py '{"name": "base", "batch": 8, ...}'

Config fields (all optional except name):
  batch (8), seq (1024), dropout (None -> model default 0.1),
  recompute (False), policy (None), mode ("step"|"fwd_bwd"|"fwd"|"loss_sum"),
  flash (True), prng_impl (None|"rbg"|"unsafe_rbg"), iters (10), warmup (2)

Prints ONE json line and appends it to exp/results.jsonl.
"""
from __future__ import annotations

import json
import os
import sys
import time

cfg = json.loads(sys.argv[1]) if len(sys.argv) > 1 else {}
NAME = cfg.get("name", "base")
BATCH = int(cfg.get("batch", 8))
SEQ = int(cfg.get("seq", 1024))
DROPOUT = cfg.get("dropout")
RECOMPUTE = bool(cfg.get("recompute", False))
POLICY = cfg.get("policy")
MODE = cfg.get("mode", "step")
FLASH = bool(cfg.get("flash", True))
PRNG = cfg.get("prng_impl")
ITERS = int(cfg.get("iters", 10))
WARMUP = int(cfg.get("warmup", 2))

import jax  # noqa: E402

if PRNG:
    jax.config.update("jax_default_prng_impl", PRNG)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import paddle_tpu as pt  # noqa: E402
from paddle_tpu.jit.api import functional_call  # noqa: E402
from paddle_tpu.tensor import Tensor  # noqa: E402
from paddle_tpu.framework import flags as _flags  # noqa: E402
from paddle_tpu.incubate.models import (GPTForCausalLM,  # noqa: E402
                                        GPTPretrainingCriterion, gpt_345m)

if not FLASH:
    _flags.set_flags({"flash_min_seq": 1 << 30})

pt.seed(0)
kw = dict(tensor_parallel=False, use_recompute=RECOMPUTE,
          recompute_policy=POLICY, max_position_embeddings=SEQ)
if DROPOUT is not None:
    kw.update(hidden_dropout_prob=DROPOUT,
              attention_probs_dropout_prob=DROPOUT)
mcfg = gpt_345m(**kw)
model = GPTForCausalLM(mcfg)
pt.amp.decorate(model, level="O2", dtype="bfloat16")
crit = GPTPretrainingCriterion()
opt = pt.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                         multi_precision=True)
params = {k: p._data for k, p in model.named_parameters()}
buffers = {k: b._data for k, b in model.named_buffers()}
opt_state = opt.init_state_tree(params)
fwd = getattr(model, "_orig_forward", model.forward)
n_params = sum(int(np.prod(p.shape)) for p in params.values())


def loss_of(p, ids, labels):
    out, new_buffers = functional_call(model, p, buffers, (Tensor(ids),),
                                       training=True, forward_fn=fwd)
    if MODE == "loss_sum":
        return out._data.astype(jnp.float32).mean(), new_buffers
    loss = crit(out, Tensor(labels))
    return loss._data.astype(jnp.float32), new_buffers


if MODE == "fwd":
    def step_fn(params, opt_state, ids, labels):
        loss, _ = loss_of(params, ids, labels)
        return (loss,)
    donate = ()
    n_state = 0
elif MODE in ("fwd_bwd",):
    def step_fn(params, opt_state, ids, labels):
        (loss, _), grads = jax.value_and_grad(
            loss_of, has_aux=True)(params, ids, labels)
        return loss, grads
    donate = ()
    n_state = 0
else:  # step / loss_sum: full train step
    def step_fn(params, opt_state, ids, labels):
        (loss, _), grads = jax.value_and_grad(
            loss_of, has_aux=True)(params, ids, labels)
        new_params, new_opt = opt.apply_gradients_tree(params, grads,
                                                       opt_state)
        return loss, new_params, new_opt
    donate = (0, 1)
    n_state = 2

step = jax.jit(step_fn, donate_argnums=donate)

# preemption-safe by default: SIGTERM commits {params, opt} and exits
# 143; a relaunch resumes from the newest committed iteration.  Only
# stateful modes carry anything worth resuming. Opt out: EXP_CKPT=0.
from _preempt import ExpRunGuard  # noqa: E402

guard = None
done = 0
if n_state:
    guard = ExpRunGuard(f"mfu_ablate_{NAME}")
    restored, done = guard.restore({"params": params, "opt": opt_state})
    params, opt_state = restored["params"], restored["opt"]

rng = np.random.RandomState(0)
ids = jnp.asarray(rng.randint(0, mcfg.vocab_size, (BATCH, SEQ))
                  .astype(np.int32))
labels = jnp.asarray(rng.randint(0, mcfg.vocab_size, (BATCH, SEQ))
                     .astype(np.int32))

res = {"name": NAME, "cfg": cfg, "n_params": n_params}
t0 = time.perf_counter()
compiled = step.lower(params, opt_state, ids, labels).compile()
res["compile_sec"] = round(time.perf_counter() - t0, 2)
try:
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    res["flops"] = float(ca.get("flops", 0.0))
except Exception:
    res["flops"] = None
try:
    ma = compiled.memory_analysis()
    res["mem"] = {"arg": int(ma.argument_size_in_bytes),
                  "temp": int(ma.temp_size_in_bytes)}
except Exception:
    pass

state = [params, opt_state][:n_state]
rest = [params, opt_state][n_state:] + [ids, labels]
out = None
for _ in range(max(0, WARMUP - done)):
    out = compiled(*state, *rest)
    if n_state:
        state = list(out[1:1 + n_state])
        done += 1
        guard.update(done, {"params": state[0], "opt": state[1]})
if out is not None:
    jax.block_until_ready(out)
# a resumed run times only the remaining iterations (step_ms math below
# divides by the count actually executed, so the rate stays honest)
timed = max(1, WARMUP + ITERS - done) if n_state else ITERS
t0 = time.perf_counter()
for _ in range(timed):
    out = compiled(*state, *rest)
    if n_state:
        state = list(out[1:1 + n_state])
        done += 1
        guard.update(done, {"params": state[0], "opt": state[1]})
jax.block_until_ready(out)
dt = time.perf_counter() - t0
if guard is not None:
    guard.finish()
# read back the loss: proves the steps really executed on-device (a
# too-good-to-be-true step time with a NaN/garbage loss = broken run)
res["final_loss"] = float(np.asarray(out[0]))

res["step_ms"] = round(dt / timed * 1000, 2)
tps = BATCH * SEQ * timed / dt
res["tokens_per_sec"] = round(tps, 1)
per_token = 6 * n_params + 6 * mcfg.num_layers * SEQ * mcfg.hidden_size
res["mfu_model"] = round(tps * per_token / 197e12, 4)

line = json.dumps(res)
print(line)
with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "results.jsonl"), "a") as f:
    f.write(line + "\n")
