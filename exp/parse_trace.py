"""Parse the captured xplane and print per-line structure + category and
per-op aggregates for the device plane. Usage:
    python exp/parse_trace.py [xplane.pb path]
"""
import collections
import glob
import re
import sys

from jax.profiler import ProfileData

path = sys.argv[1] if len(sys.argv) > 1 else sorted(
    glob.glob("/tmp/jaxtrace/**/*.xplane.pb", recursive=True))[-1]
pd = ProfileData.from_file(path)

STEPS = 3

for plane in pd.planes:
    if plane.name != "/device:TPU:0":
        continue
    for line in plane.lines:
        events = list(line.events)
        total = sum(e.duration_ns for e in events)
        print(f"line {line.name!r}: {len(events)} events, "
              f"{total/1e6:.1f} ms total")
    for line in plane.lines:
        if "XLA Ops" not in line.name and "Ops" not in line.name:
            continue
        agg = collections.defaultdict(float)
        cat = collections.defaultdict(float)
        for ev in line.events:
            name = ev.name
            agg[name] += ev.duration_ns
            m = re.match(r"%?([a-zA-Z][a-zA-Z0-9_-]*)", name)
            prefix = m.group(1).rstrip("0123456789.") if m else name[:20]
            cat[prefix] += ev.duration_ns
        total = sum(agg.values())
        print(f"\n== line {line.name!r}: total {total/STEPS/1e6:.1f} ms/step")
        print("-- by category:")
        for k, v in sorted(cat.items(), key=lambda kv: -kv[1])[:20]:
            print(f"  {v/STEPS/1e6:9.2f} ms/step {100*v/total:5.1f}%  {k}")
        print("-- top ops:")
        for k, v in sorted(agg.items(), key=lambda kv: -kv[1])[:30]:
            print(f"  {v/STEPS/1e6:9.2f} ms/step {100*v/total:5.1f}%  {k[:140]}")
