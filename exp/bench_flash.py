"""Microbench the flash kernel on the real chip: fwd and fwd+bwd at the
GPT-345M shape, vs XLA attention, at several block configs.
Usage: python exp/bench_flash.py
"""
import json
import time

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas_ops import mha

B, H, S, D = 8, 16, 1024, 64
rng = np.random.RandomState(0)
q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)).astype(jnp.bfloat16)
k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)).astype(jnp.bfloat16)
v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)).astype(jnp.bfloat16)


def _chain(fn, q0, k0, v0, iters):
    """Serially-dependent chain of fn calls ending in a HOST READBACK —
    on the axon tunnel block_until_ready does not synchronize and
    identical repeated executions are served from a cache, so the chain
    must thread outputs forward and the only trustworthy fence is
    pulling a scalar to the host."""
    qq = q0
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(qq, k0, v0)
        first = out[0] if isinstance(out, tuple) else out
        qq = (first.astype(jnp.float32) * 1e-3).astype(q0.dtype).reshape(
            q0.shape)
    float(jnp.sum(qq.astype(jnp.float32)))  # sync
    return time.perf_counter() - t0


def timeit(fn, q0, k0, v0, iters=40):
    _chain(fn, q0, k0, v0, 2)  # warm
    t_short = _chain(fn, q0, k0, v0, 5)
    t_long = _chain(fn, q0, k0, v0, 5 + iters)
    return (t_long - t_short) / iters * 1000


def xla_attn(q, k, v):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * (D ** -0.5)
    mask = jnp.tril(jnp.ones((S, S), bool))
    s = jnp.where(mask, s, -1e9)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


results = {}
for name, fn in [
    ("xla", jax.jit(xla_attn)),
    ("flash512_512", jax.jit(lambda a, b_, c: mha(a, b_, c, causal=True,
                                                  block_q=512, block_k=512))),
    ("flash1024_256", jax.jit(lambda a, b_, c: mha(
        a, b_, c, causal=True, block_q=1024, block_k=256))),
    ("flash1024_512", jax.jit(lambda a, b_, c: mha(
        a, b_, c, causal=True, block_q=1024, block_k=512))),
    ("flash256_512", jax.jit(lambda a, b_, c: mha(
        a, b_, c, causal=True, block_q=256, block_k=512))),
]:
    try:
        results[f"{name}_fwd_ms"] = round(timeit(fn, q, k, v), 3)
    except Exception as e:
        results[f"{name}_fwd_ms"] = str(e)[:120]

for name, fn in [
    ("xla", xla_attn),
    ("flash512_512", lambda a, b_, c: mha(a, b_, c, causal=True,
                                          block_q=512, block_k=512)),
    ("flash1024_256", lambda a, b_, c: mha(a, b_, c, causal=True,
                                           block_q=1024, block_k=256)),
    ("flash1024_512", lambda a, b_, c: mha(a, b_, c, causal=True,
                                           block_q=1024, block_k=512)),
    ("flash256_512", lambda a, b_, c: mha(a, b_, c, causal=True,
                                          block_q=256, block_k=512)),
]:
    def loss(a, b_, c, fn=fn):
        return fn(a, b_, c).astype(jnp.float32).sum()
    # one compile per attention variant is the point of the benchmark
    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))  # tpu-lint: disable=TPU001
    try:
        results[f"{name}_fwdbwd_ms"] = round(timeit(g, q, k, v), 3)
    except Exception as e:
        results[f"{name}_fwdbwd_ms"] = str(e)[:120]

# correctness cross-check on-chip
o_flash = mha(q, k, v, causal=True)
o_xla = xla_attn(q, k, v)
results["max_abs_diff"] = float(jnp.max(jnp.abs(
    o_flash.astype(jnp.float32) - o_xla.astype(jnp.float32))))
print(json.dumps(results))
