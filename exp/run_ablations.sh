#!/bin/bash
# Serial MFU ablation ladder on the real chip. Each config is a fresh
# process (clean compile). Results accumulate in exp/results.jsonl.
cd "$(dirname "$0")/.."
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
run() {
  echo "=== $1 ($(date +%H:%M:%S)) ==="
  timeout 600 python exp/mfu_ablate.py "$1" 2>&1 | tail -2
}
run '{"name": "base", "batch": 8}'
run '{"name": "fwd", "batch": 8, "mode": "fwd"}'
run '{"name": "fwd_bwd", "batch": 8, "mode": "fwd_bwd"}'
run '{"name": "nodrop", "batch": 8, "dropout": 0.0}'
run '{"name": "loss_sum", "batch": 8, "mode": "loss_sum"}'
run '{"name": "noflash", "batch": 8, "flash": false}'
run '{"name": "b16_dots", "batch": 16, "recompute": true, "policy": "dots"}'
run '{"name": "s2048_b4", "batch": 4, "seq": 2048}'
run '{"name": "nodrop_rbg", "batch": 8, "dropout": 0.0, "prng_impl": "rbg"}'
echo "=== DONE ($(date +%H:%M:%S)) ==="
