"""Capture a device trace of the GPT-345M train step and print the top
op-time sinks, using jax.profiler + ProfileData (no tensorboard needed).
Usage: python exp/profile_step.py [dropout]
"""
import collections
import glob
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

DROPOUT = float(sys.argv[1]) if len(sys.argv) > 1 else 0.1
BATCH, SEQ = 8, 1024

import paddle_tpu as pt  # noqa: E402
from paddle_tpu.jit.api import functional_call  # noqa: E402
from paddle_tpu.tensor import Tensor  # noqa: E402
from paddle_tpu.incubate.models import (GPTForCausalLM,  # noqa: E402
                                        GPTPretrainingCriterion, gpt_345m)

pt.seed(0)
cfg = gpt_345m(tensor_parallel=False, use_recompute=False,
               max_position_embeddings=SEQ, hidden_dropout_prob=DROPOUT,
               attention_probs_dropout_prob=DROPOUT)
model = GPTForCausalLM(cfg)
pt.amp.decorate(model, level="O2", dtype="bfloat16")
crit = GPTPretrainingCriterion()
opt = pt.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                         multi_precision=True)
params = {k: p._data for k, p in model.named_parameters()}
buffers = {k: b._data for k, b in model.named_buffers()}
opt_state = opt.init_state_tree(params)
fwd = getattr(model, "_orig_forward", model.forward)


def step_fn(params, opt_state, ids, labels):
    def loss_of(p):
        out, _ = functional_call(model, p, buffers, (Tensor(ids),),
                                 training=True, forward_fn=fwd)
        return crit(out, Tensor(labels))._data.astype(jnp.float32), None
    (loss, _), grads = jax.value_and_grad(loss_of, has_aux=True)(params)
    new_params, new_opt = opt.apply_gradients_tree(params, grads, opt_state)
    return loss, new_params, new_opt


step = jax.jit(step_fn, donate_argnums=(0, 1))
rng = np.random.RandomState(0)
ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (BATCH, SEQ))
                  .astype(np.int32))
labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (BATCH, SEQ))
                     .astype(np.int32))
# preemption-safe by default (EXP_CKPT=0 opts out): SIGTERM saves
# {params, opt} and exits 143; a relaunch resumes the warmed-up state
from _preempt import ExpRunGuard  # noqa: E402

guard = ExpRunGuard(f"profile_step_d{DROPOUT}")
restored, done = guard.restore({"params": params, "opt": opt_state})
params, opt_state = restored["params"], restored["opt"]

print("compiling...", flush=True)
compiled = step.lower(params, opt_state, ids, labels).compile()
state = (params, opt_state)
for _ in range(max(0, 2 - done)):
    out = compiled(*state, ids, labels)
    state = (out[1], out[2])
    done += 1
    guard.update(done, {"params": state[0], "opt": state[1]})
jax.block_until_ready(state[0])

logdir = "/tmp/jaxtrace"
os.system(f"rm -rf {logdir}")
print("tracing...", flush=True)
with jax.profiler.trace(logdir):
    for _ in range(3):
        out = compiled(*state, ids, labels)
        state = (out[1], out[2])
        done += 1
        guard.update(done, {"params": state[0], "opt": state[1]})
    jax.block_until_ready(out[0])
guard.finish()

files = glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True)
print("xplane files:", files, flush=True)
if not files:
    sys.exit(1)
from jax.profiler import ProfileData
pd = ProfileData.from_file(files[0])
agg = collections.defaultdict(float)
plane_names = []
for plane in pd.planes:
    plane_names.append(plane.name)
    if "TPU" not in plane.name and "Device" not in plane.name \
            and "/device" not in plane.name.lower():
        continue
    for line in plane.lines:
        for ev in line.events:
            dur = ev.duration_ns
            name = ev.name
            agg[name] += dur
print("planes:", plane_names)
top = sorted(agg.items(), key=lambda kv: -kv[1])[:40]
total = sum(agg.values())
print(f"total device ns (3 steps): {total:.3e}")
for name, ns in top:
    print(f"{ns/3/1e6:9.2f} ms/step  {100*ns/total:5.1f}%  {name[:120]}")
