"""Numerics health sentinels + goodput ledger contract tests.

The observability contract applies throughout: everything is inert
until enabled, the hot path never syncs the device (the monitor reads
the health packet from the PREVIOUS step at cadence boundaries, a full
dispatch behind), a tripped sentinel names the offending tensor by
parameter path, and the monitored captured step stays at exactly ONE
compile with bit-identical losses — the health outputs ride inside the
same program.  The goodput half is pure span arithmetic: the
acceptance test hand-computes a wall-clock decomposition and pins
``pt_goodput_fraction`` to it.
"""
from __future__ import annotations

import numpy as np
import pytest

import paddle_tpu.observability as obs
from paddle_tpu.observability.goodput import (
    decompose_spans, get_goodput, reset_goodput,
)
from paddle_tpu.observability.numerics import (
    NumericsHaltError, current_monitor, get_monitor, health_outputs,
    reset_monitor,
)
from paddle_tpu.observability.trace import Span, get_tracer, reset_tracer


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    for var in ("PT_TELEMETRY", "PT_TELEMETRY_DIR", "PT_METRICS_PORT",
                "PT_NUMERICS", "PT_NUMERICS_CADENCE", "PT_NUMERICS_STATS",
                "PT_NUMERICS_HALT", "PT_GOODPUT", "PT_TRACE",
                "PT_TRACE_DIR", "PT_FLIGHT_RECORDER", "PT_PROCESS_INDEX",
                "PT_RUN_ID"):
        monkeypatch.delenv(var, raising=False)
    obs.reset()
    reset_tracer()
    yield
    obs.reset()
    reset_tracer()


def _packet(n_tensors=1, bad=(), loss=1.0, norm_sq=1.0):
    """A materialized health packet the monitor can inspect without a
    device in sight — names + plain numpy arrays."""
    names = tuple(f"p{i}" for i in range(n_tensors)) + ("loss",)
    flags = np.array([names[i] in bad for i in range(len(names))])
    health = {"flags": flags,
              "grad_norm_sq": np.float32(norm_sq),
              "loss": np.float32(loss)}
    return names, health


# -- health_outputs: the in-graph half --------------------------------------

def test_health_outputs_flags_norm_and_loss():
    import jax.numpy as jnp

    named = {"b": jnp.array([1.0, 2.0]),
             "a": jnp.array([3.0, jnp.nan]),
             "count": jnp.array([4], dtype=jnp.int32)}  # non-inexact
    names, health = health_outputs(named, loss=jnp.float32(0.5))
    assert names == ("a", "b", "count", "loss")
    flags = np.asarray(health["flags"])
    assert flags.tolist() == [True, False, False, False]
    # the poisoned tensor's nan propagates through the squared norm
    assert not np.isfinite(float(np.asarray(health["grad_norm_sq"])))
    assert float(np.asarray(health["loss"])) == 0.5
    assert "stats" not in health


def test_health_outputs_stats_block():
    import jax.numpy as jnp

    named = {"w": jnp.array([1.0, -3.0, 2.0, 0.0])}
    names, health = health_outputs(named, with_stats=True)
    stats = np.asarray(health["stats"])
    assert stats.shape == (1, 4)
    mean, std, max_abs, underflow = stats[0]
    assert mean == pytest.approx(0.0)
    assert max_abs == pytest.approx(3.0)
    assert 0.0 <= underflow <= 1.0


# -- the monitor: cadence reads, detectors, halt ----------------------------

def test_watch_reads_previous_packet_at_cadence_and_flush_drains():
    mon = get_monitor().enable(cadence=4)
    for s in range(10):
        mon.watch(s, *_packet())
    snap = mon.snapshot()
    # inspected packets: step 0 (first boundary), 4, 8 — each read one
    # call AFTER its dispatch, so it never blocks the live step
    assert snap["reads"] == 3
    assert snap["steps_observed"] == 10
    mon.flush()  # end-of-run: the held packet (step 9) is read now
    assert mon.snapshot()["reads"] == 4
    assert mon.anomaly_count() == 0


def test_nonfinite_trip_names_tensor_once():
    mon = get_monitor().enable(cadence=1)
    mon.watch(0, *_packet(n_tensors=2))
    for s in (1, 2, 3):
        mon.watch(s, *_packet(n_tensors=2, bad=("p1",)))
    mon.flush()
    # p1 tripped in three inspected packets but is booked exactly once
    assert mon.anomaly_count("nonfinite") == 1
    snap = mon.snapshot()
    assert snap["last_anomaly"]["kind"] == "nonfinite"
    assert snap["last_anomaly"]["tensor"] == "p1"
    assert snap["tripped"] == ["p1"]


def test_ewma_loss_spike_and_grad_explosion_detectors():
    mon = get_monitor().enable(cadence=1, spike_factor=10.0)
    step = 0
    for _ in range(6):  # build a warm, calm baseline
        mon.watch(step, *_packet(loss=1.0, norm_sq=1.0))
        step += 1
    mon.watch(step, *_packet(loss=100.0, norm_sq=1.0))
    step += 1
    mon.watch(step, *_packet(loss=1.0, norm_sq=1.0))  # reads the spike
    assert mon.anomaly_count("loss_spike") == 1
    # the spike never contaminated the EWMA baseline
    assert mon.snapshot()["loss_ewma"] == pytest.approx(1.0, abs=0.05)
    mon.watch(step + 1, *_packet(loss=1.0, norm_sq=1.0e6))  # norm 1000
    mon.watch(step + 2, *_packet(loss=1.0, norm_sq=1.0))
    assert mon.anomaly_count("grad_explosion") == 1


def test_halt_mode_raises_from_the_read():
    mon = get_monitor().enable(cadence=1, halt=True)
    mon.watch(0, *_packet())
    mon.watch(1, *_packet(bad=("p0",)))
    with pytest.raises(NumericsHaltError, match="p0"):
        mon.watch(2, *_packet())  # this call inspects the poisoned one
    # spike detectors never halt: only hard non-finite trips do
    reset_monitor()
    mon2 = get_monitor().enable(cadence=1, halt=True)
    for s in range(6):
        mon2.watch(s, *_packet(loss=1.0))
    mon2.watch(6, *_packet(loss=500.0))
    mon2.watch(7, *_packet(loss=1.0))
    assert mon2.anomaly_count("loss_spike") == 1


def test_disabled_monitor_is_inert_but_counts_host_anomalies():
    mon = get_monitor()
    assert not mon.enabled
    mon.watch(0, *_packet(bad=("p0",)))
    mon.flush()
    assert mon.snapshot()["steps_observed"] == 0
    assert mon.anomaly_count() == 0
    # the scaler-skip path books through here even while disabled
    mon.record_anomaly("scaler_skip", tensor="w", halt_ok=False)
    assert mon.anomaly_count("scaler_skip") == 1


def test_env_enablement(monkeypatch):
    monkeypatch.setenv("PT_NUMERICS", "1")
    monkeypatch.setenv("PT_NUMERICS_CADENCE", "7")
    monkeypatch.setenv("PT_NUMERICS_HALT", "1")
    reset_monitor()
    mon = get_monitor()
    assert mon.enabled and mon.cadence == 7 and mon.halt
    assert current_monitor() is mon
    monkeypatch.setenv("PT_GOODPUT", "1")
    reset_goodput()
    assert get_goodput().enabled


# -- GradScaler: skipped steps are classified anomalies ---------------------

def test_scaler_skip_books_anomaly_with_param_name():
    import jax.numpy as jnp
    from paddle_tpu.amp.grad_scaler import GradScaler

    class _Grad:
        def __init__(self, data):
            self._data = data

    class _Param:
        def __init__(self, name, data):
            self.name = name
            self.grad = _Grad(data)

    class _Opt:
        def __init__(self, params):
            self._parameter_list = params
            self.stepped = 0

        def step(self):
            self.stepped += 1

    scaler = GradScaler(init_loss_scaling=16.0)
    opt = _Opt([_Param("good", jnp.ones(2)),
                _Param("w::bad", jnp.array([1.0, jnp.inf]))])
    scaler.step(opt)
    scaler.update()
    assert opt.stepped == 0  # the skip IS the recovery
    mon = get_monitor()
    assert mon.anomaly_count("scaler_skip") == 1
    last = mon.snapshot()["last_anomaly"]
    assert last["tensor"] == "w::bad"
    assert scaler.get_loss_scaling() == 8.0  # dynamic backoff ran
    # a clean step books nothing
    opt2 = _Opt([_Param("good", jnp.ones(2))])
    scaler.step(opt2)
    assert opt2.stepped == 1
    assert mon.anomaly_count("scaler_skip") == 1


# -- goodput: the span ledger -----------------------------------------------

def test_decompose_spans_matches_hand_computation():
    S = 1_000_000_000  # 1s in ns
    spans = [
        Span("step", "compute", 0 * S, 1 * S, 0),
        Span("step", "compute", 2 * S, 3 * S, 0),
        # collective 1s long, 0.5s hidden under compute -> 0.5 exposed
        Span("allreduce", "collective", S // 2, 3 * S // 2, 0),
        Span("compile:step", "host", 3 * S, 5 * S, 0),
        Span("data_wait", "host", 5 * S, 11 * S // 2, 0),
        Span("checkpoint", "host", 11 * S // 2, 23 * S // 4, 0),
    ]
    d = decompose_spans(spans)
    # hand decomposition: productive 2.0; badput = compile 2.0 +
    # data_wait 0.5 + checkpoint 0.25 + collective_exposed 0.5 = 3.25
    assert d["productive_seconds"] == pytest.approx(2.0)
    bp = d["badput_seconds"]
    assert bp["compile"] == pytest.approx(2.0)
    assert bp["data_wait"] == pytest.approx(0.5)
    assert bp["checkpoint"] == pytest.approx(0.25)
    assert bp["collective_exposed"] == pytest.approx(0.5)
    assert d["badput_total_seconds"] == pytest.approx(3.25)
    assert d["goodput_fraction"] == pytest.approx(2.0 / 5.25)


def test_decompose_overlapping_compute_merges_before_counting():
    S = 1_000_000_000
    spans = [  # two overlapping dispatch spans must not double-count
        Span("a", "compute", 0, 2 * S, 0),
        Span("b", "compute", S, 3 * S, 0),
    ]
    d = decompose_spans(spans)
    assert d["productive_seconds"] == pytest.approx(3.0)
    assert d["goodput_fraction"] == pytest.approx(1.0)


def test_ledger_refresh_reads_tracer_and_feeds_restart_replay():
    tr = get_tracer().enable()
    S = 1_000_000_000
    tr.phase_record("backward", 0, 4 * S)
    tr.phase_record("data_wait", 4 * S, 5 * S)
    gp = get_goodput().enable()
    gp.record_restart_replay(1.0)
    snap = gp.snapshot()
    assert snap["enabled"]
    assert snap["productive_seconds"] == pytest.approx(4.0)
    assert snap["badput_seconds"]["data_wait"] == pytest.approx(1.0)
    assert snap["badput_seconds"]["restart_replay"] == pytest.approx(1.0)
    assert snap["goodput_fraction"] == pytest.approx(4.0 / 6.0)


def test_disabled_ledger_is_inert():
    gp = get_goodput()
    assert not gp.enabled
    gp.record_restart_replay(5.0)
    snap = gp.snapshot()
    assert snap["enabled"] is False


# -- capture integration: monitors inside the SAME program ------------------

def _mlp(seed=0):
    import paddle_tpu as pt
    import paddle_tpu.nn as nn

    np.random.seed(seed)
    pt.seed(seed)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                parameters=model.parameters())
    return model, opt


def _captured_step(model, opt):
    import paddle_tpu as pt
    import paddle_tpu.nn as nn

    mse = nn.MSELoss()

    @pt.jit.capture_step
    def step(x, y):
        loss = mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    return step


def _run_10(monitored, cadence=3):
    import paddle_tpu as pt

    reset_monitor()
    if monitored:
        get_monitor().enable(cadence=cadence)
    model, opt = _mlp(seed=7)
    step = _captured_step(model, opt)
    rng = np.random.RandomState(3)
    x = pt.to_tensor(rng.randn(4, 8).astype(np.float32))
    y = pt.to_tensor(rng.randn(4, 1).astype(np.float32))
    losses = [np.asarray(step(x, y)._data).tobytes() for _ in range(10)]
    return losses, step.stats


def test_monitored_capture_bitwise_identical_one_compile():
    base, base_stats = _run_10(monitored=False)
    mon_losses, mon_stats = _run_10(monitored=True)
    # monitors ride inside the same program: one compile, no fallback
    assert mon_stats["compiles"] == 1 and mon_stats["hits"] == 9
    assert not mon_stats["fallback"]
    # and they never perturb the math: losses are bit-identical
    assert mon_losses == base
    mon = get_monitor()
    assert mon.anomaly_count() == 0  # sentinel quiet on healthy training
    assert mon.snapshot()["reads"] >= 2
    assert mon.snapshot()["last_grad_norm"] is not None
    reset_monitor()


def test_monitored_capture_detects_poisoned_input():
    import paddle_tpu as pt

    reset_monitor()
    get_monitor().enable(cadence=2)
    model, opt = _mlp(seed=1)
    step = _captured_step(model, opt)
    rng = np.random.RandomState(5)
    x = rng.randn(4, 8).astype(np.float32)
    y = pt.to_tensor(rng.randn(4, 1).astype(np.float32))
    for s in range(8):
        xb = x.copy()
        if s == 4:
            xb[0, 0] = np.nan
        step(pt.to_tensor(xb), y)
    get_monitor().flush()
    mon = get_monitor()
    assert step.stats["compiles"] == 1  # the poison never retraced
    assert mon.anomaly_count("nonfinite") >= 1
    tripped = mon.snapshot()["tripped"]
    assert any(t.startswith("model::") for t in tripped)
    reset_monitor()


def test_monitored_capture_with_stats_sampling():
    import paddle_tpu as pt

    reset_monitor()
    get_monitor().enable(cadence=2, stats=True)
    model, opt = _mlp(seed=2)
    step = _captured_step(model, opt)
    rng = np.random.RandomState(6)
    x = pt.to_tensor(rng.randn(4, 8).astype(np.float32))
    y = pt.to_tensor(rng.randn(4, 1).astype(np.float32))
    for _ in range(6):
        step(x, y)
    get_monitor().flush()
    stats = get_monitor().snapshot().get("tensor_stats")
    assert stats and any(k.startswith("model::") for k in stats)
    for entry in stats.values():
        assert set(entry) == {"mean", "std", "max_abs", "underflow_frac"}
    reset_monitor()


def test_hapi_train_batch_feeds_the_monitor():
    import paddle_tpu as pt

    reset_monitor()
    get_monitor().enable(cadence=2)
    pt.seed(0)
    net = pt.nn.Sequential(pt.nn.Linear(8, 4), pt.nn.ReLU(),
                           pt.nn.Linear(4, 2))
    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.SGD(learning_rate=0.05,
                                   parameters=net.parameters()),
        loss=pt.nn.CrossEntropyLoss())
    rng = np.random.RandomState(2)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 2, size=(16, 1)).astype(np.int64)
    for _ in range(6):
        model.train_batch([x], [y])
    mon = get_monitor()
    assert mon.snapshot()["reads"] >= 2
    assert mon.anomaly_count() == 0
    reset_monitor()


# -- telemetry snapshot carries both blocks ---------------------------------

def test_telemetry_snapshot_numerics_and_goodput_blocks():
    tel = obs.get_telemetry().enable()
    get_monitor().enable(cadence=1)
    tr = get_tracer().enable()
    S = 1_000_000_000
    tr.phase_record("backward", 0, 3 * S)
    tr.phase_record("data_wait", 3 * S, 4 * S)
    get_goodput().enable()
    tel.observe_step(0.01, mode="train")
    snap = tel.snapshot()
    assert snap["numerics"]["enabled"] is True
    assert snap["numerics"]["anomalies_total"] == 0
    assert snap["goodput"]["goodput_fraction"] == pytest.approx(0.75)
    assert snap["goodput"]["badput_seconds"]["data_wait"] == \
        pytest.approx(1.0)
