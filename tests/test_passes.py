"""Distributed pass framework (ref: distributed/passes/pass_base.py):
registry, conflict/ordering rules, built-in amp/recompute rewrites, and
a custom user pass mutating a traced program."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.static as static
from paddle_tpu.distributed.passes import (PassBase, PassContext, PassType,
                                           new_pass, register_pass)


def _build_program():
    pt.seed(0)
    pt.enable_static()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 8], "float32")
        w = pt.create_parameter([8, 8], "float32")
        h = pt.matmul(x, w)
        y = pt.tanh(h)
        out = pt.matmul(y, w)
        loss = pt.mean(out)
    return main, startup, loss


def _run(main, startup, loss):
    exe = static.Executor()
    exe.run(startup)
    out = exe.run(main, feed={"x": np.ones((4, 8), np.float32)},
                  fetch_list=[loss])
    pt.disable_static()
    return float(np.asarray(out[0]))


def test_amp_pass_rewrites_matmuls_only():
    main, startup, loss = _build_program()
    ref = None
    try:
        p = new_pass("auto_parallel_amp", {"dtype": "bfloat16"})
        ctx = p.apply([main], [startup])
        assert ctx.get_attr("amp_nodes_rewritten") == 2  # both matmuls
        assert [type(q).name for q in ctx.passes] == ["auto_parallel_amp"]
        got = _run(main, startup, loss)
    finally:
        pt.disable_static()
    # bf16 matmuls still produce a close loss on this tiny program
    main2, startup2, loss2 = _build_program()
    ref = _run(main2, startup2, loss2)
    assert abs(got - ref) < 0.05 * (abs(ref) + 1e-3)


def test_recompute_pass_wraps_and_preserves_values():
    main, startup, loss = _build_program()
    try:
        p = new_pass("auto_parallel_recompute")
        ctx = p.apply([main], [startup])
        assert ctx.get_attr("recompute_nodes_rewritten") >= 2
        got = _run(main, startup, loss)
    finally:
        pt.disable_static()
    main2, startup2, loss2 = _build_program()
    ref = _run(main2, startup2, loss2)
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_custom_user_pass_mutates_traced_program():
    """The user-extension point the reference provides via
    register_pass (pass_base.py:121): scale every tanh node's output."""

    @register_pass("test_scale_tanh")
    class ScaleTanh(PassBase):
        def _check_self(self):
            return True

        def _check_conflict(self, other):
            return True

        def _apply_single_impl(self, main, startup, context):
            for node in main.nodes:
                if node.name == "tanh":
                    inner = node.fn
                    node.fn = (lambda *a, _i=inner:
                               _i(*a) * self.get_attr("scale", 2.0))
                    context.set_attr("scaled", True)

    main, startup, loss = _build_program()
    try:
        ref_main, ref_startup, ref_loss = _build_program()
    finally:
        pass
    ctx = new_pass("test_scale_tanh", {"scale": 3.0}).apply([main], [startup])
    assert ctx.get_attr("scaled") is True
    got = _run(main, startup, loss)
    ref = _run(ref_main, ref_startup, ref_loss)
    assert abs(got - 3.0 * ref) < 1e-4  # linear head => loss scales by 3


def test_conflict_and_ordering_rules():
    @register_pass("test_fusion_last")
    class Fusion(PassBase):
        def _check_self(self):
            return True

        def _check_conflict(self, other):
            return True

        def _type(self):
            return PassType.FUSION_OPT

        def _apply_single_impl(self, main, startup, context):
            context.set_attr("fusion_applied", True)

    main, startup, _ = _build_program()
    pt.disable_static()
    ctx = PassContext()
    new_pass("test_fusion_last").apply([main], [startup], ctx)
    # a CALC_OPT pass after a fusion pass is refused (fusion-last rule)
    before = len(ctx.passes)
    new_pass("auto_parallel_amp").apply([main], [startup], ctx)
    assert len(ctx.passes) == before
    # amp twice: second application refused by its own conflict rule
    ctx2 = PassContext()
    new_pass("auto_parallel_amp").apply([main], [startup], ctx2)
    new_pass("auto_parallel_amp").apply([main], [startup], ctx2)
    assert [type(q).name for q in ctx2.passes] == ["auto_parallel_amp"]


def test_new_pass_unknown_name_raises():
    with pytest.raises(ValueError, match="not registered"):
        new_pass("definitely_not_a_pass")


def test_pass_after_run_invalidates_compile_cache():
    """A pass applied AFTER the program already executed must take
    effect on the next run (the executor caches on program.version)."""

    @register_pass("test_double_output")
    class DoubleOut(PassBase):
        def _check_self(self):
            return True

        def _check_conflict(self, other):
            return True

        def _apply_single_impl(self, main, startup, context):
            for node in main.nodes:
                if node.name == "matmul":
                    inner = node.fn
                    node.fn = lambda *a, _i=inner: _i(*a) * 2.0

    main, startup, loss = _build_program()
    exe = static.Executor()
    exe.run(startup)
    feed = {"x": np.ones((4, 8), np.float32)}
    before = float(np.asarray(exe.run(main, feed=feed,
                                      fetch_list=[loss])[0]))
    new_pass("test_double_output").apply([main], [startup])
    after = float(np.asarray(exe.run(main, feed=feed,
                                     fetch_list=[loss])[0]))
    pt.disable_static()
    assert abs(after) > abs(before) * 1.5, (before, after)


def test_recompute_refuses_double_application():
    main, startup, _ = _build_program()
    pt.disable_static()
    ctx = PassContext()
    new_pass("auto_parallel_recompute").apply([main], [startup], ctx)
    new_pass("auto_parallel_recompute").apply([main], [startup], ctx)
    assert [type(q).name for q in ctx.passes] == ["auto_parallel_recompute"]


def test_apply_rejects_bare_program_even_when_check_fails():
    main, startup, _ = _build_program()
    pt.disable_static()
    with pytest.raises(TypeError, match="LISTS"):
        new_pass("auto_parallel_amp", {"dtype": "float32"}).apply(
            main, startup)
