"""static.nn control flow (ref: python/paddle/static/nn/control_flow.py
cond/while_loop/case/switch_case) — eager Python-branch semantics plus
lax.cond/while_loop/switch under trace."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.static import nn as snn
from paddle_tpu.tensor import Tensor


def test_cond_eager_differentiable():
    x = pt.to_tensor(np.array([2.0], np.float32))
    x.stop_gradient = False
    out = snn.cond(pt.to_tensor(True),
                   lambda: x * 3.0, lambda: x * 5.0)
    out.backward()
    assert float(x.grad) == 3.0
    out2 = snn.cond(pt.to_tensor(False),
                    lambda: x * 3.0, lambda: x * 5.0)
    assert float(out2) == 10.0


def test_cond_traced_lowers_to_lax_cond():
    from paddle_tpu.jit.api import to_static

    @to_static
    def f(x):
        return snn.cond(x.sum() > 0,
                        lambda: x * 2.0, lambda: x - 1.0)

    a = np.ones((3,), np.float32)
    np.testing.assert_allclose(np.asarray(f(pt.to_tensor(a))._data), a * 2)
    np.testing.assert_allclose(
        np.asarray(f(pt.to_tensor(-a))._data), -a - 1)


def test_while_loop_compiles_single_while_op():
    i = pt.to_tensor(np.int32(0))
    acc = pt.to_tensor(np.float32(1.0))
    if_, acc_f = snn.while_loop(
        lambda i, a: i < 5,
        lambda i, a: [i + 1, a * 2.0],
        [i, acc])
    assert int(if_) == 5 and float(acc_f) == 32.0


def test_while_loop_under_jit():
    from paddle_tpu.jit.api import to_static

    @to_static
    def f(n):
        i = pt.to_tensor(np.int32(0))
        s = pt.to_tensor(np.float32(0.0))
        _, out = snn.while_loop(lambda i, s: i < n,
                                lambda i, s: [i + 1, s + 2.0],
                                [i, s])
        return out

    assert float(f(pt.to_tensor(np.int32(4)))) == 8.0
    assert float(f(pt.to_tensor(np.int32(7)))) == 14.0


def test_case_and_switch_case():
    x = pt.to_tensor(np.float32(3.0))
    out = snn.case([(pt.to_tensor(False), lambda: x * 10),
                    (pt.to_tensor(True), lambda: x + 1)],
                   default=lambda: x)
    assert float(out) == 4.0

    out2 = snn.switch_case(pt.to_tensor(np.int32(1)),
                           [lambda: x * 2, lambda: x * 3, lambda: x * 4])
    assert float(out2) == 9.0
    out3 = snn.switch_case(pt.to_tensor(np.int32(9)),
                           {0: lambda: x, 1: lambda: x * 2},
                           default=lambda: x * 100)
    assert float(out3) == 300.0


def test_switch_case_traced():
    from paddle_tpu.jit.api import to_static
    x = pt.to_tensor(np.float32(2.0))

    @to_static
    def f(i):
        return snn.switch_case(i, [lambda: x * 2, lambda: x * 3,
                                   lambda: x * 4])

    assert float(f(pt.to_tensor(np.int32(0)))) == 4.0
    assert float(f(pt.to_tensor(np.int32(2)))) == 8.0


def test_cond_unselected_branch_never_executes():
    """A domain-guarded op in the unselected branch must not poison
    gradients (both branches trace INSIDE lax.cond)."""
    from paddle_tpu import autograd
    from paddle_tpu.jit.api import to_static

    @to_static
    def f(x):
        # pred False selects the safe branch; sqrt of the NEGATIVE input
        # sits in the UNSELECTED branch and must contribute nothing
        out = snn.cond(x.sum() > 0,
                       lambda: pt.sqrt(x),
                       lambda: x * 2.0)
        return out.sum()

    x = pt.to_tensor(np.array([-4.0, -9.0], np.float32))
    x.stop_gradient = False
    y = f(x)
    (g,) = autograd.grad(y, x)
    assert float(y) == -26.0
    np.testing.assert_allclose(np.asarray(g._data), [2.0, 2.0])
    assert np.all(np.isfinite(np.asarray(g._data)))


def test_switch_case_traced_out_of_range_uses_default():
    from paddle_tpu.jit.api import to_static
    x = pt.to_tensor(np.float32(2.0))

    @to_static
    def f(i):
        return snn.switch_case(i, [lambda: x * 2, lambda: x * 3],
                               default=lambda: x * 100)

    assert float(f(pt.to_tensor(np.int32(-1)))) == 200.0
    assert float(f(pt.to_tensor(np.int32(5)))) == 200.0
    assert float(f(pt.to_tensor(np.int32(1)))) == 6.0
