"""Elastic fault tolerance exercised with REAL processes (ref:
``fleet/elastic/manager.py:124`` watch ``:604``, re-match ``:417``,
relaunch via ``LauncherInterface :54``; reference test strategy
``test/collective/multinode/``).

Scenario: two logical nodes on localhost, each supervised by
ElasticManager.supervise driving a real trainer subprocess
(tests/elastic_worker.py). The test SIGKILLs one trainer mid-run; its
supervisor relaunches it and the replacement resumes from the sharded
checkpoint written by rank 0. A separate scenario proves lease-expiry
membership detection + rank re-mapping, and the SIGTERM preemption hook
saving a checkpoint on the way out."""
import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from paddle_tpu import core
from paddle_tpu.distributed.fleet.elastic import (
    ElasticManager, ElasticStatus, LauncherInterface)

_PREFIX = "elastic/nodes/"


def _read_log(path):
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(l) for l in f if l.strip()]


def _wait_for(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.mark.slow
def test_kill_and_relaunch_resumes_from_checkpoint(tmp_path):
    master = core.TCPStore(is_master=True)
    log_path = str(tmp_path / "progress.jsonl")
    ckpt_dir = str(tmp_path / "ckpt")
    env_base = {
        "ELASTIC_STORE_PORT": str(master.port),
        "ELASTIC_CKPT": ckpt_dir,
        "ELASTIC_LOG": log_path,
        "ELASTIC_TOTAL_STEPS": "30",
        "ELASTIC_STEP_SECS": "0.05",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
    }
    worker = os.path.join(os.path.dirname(__file__), "elastic_worker.py")

    results = {}

    def supervise(host):
        store = core.TCPStore("127.0.0.1", master.port)
        man = ElasticManager(store, host, np="1:2",
                             heartbeat_interval=0.2, lease_ttl=2.0)
        man.register()

        def make_launcher(hosts, rank):
            env = dict(os.environ, **env_base, ELASTIC_HOST=host,
                       ELASTIC_RANK=str(rank),
                       ELASTIC_WORLD=",".join(hosts))
            return _EnvLauncher([sys.executable, worker], env)

        results[host] = man.supervise(make_launcher, max_restarts=10,
                                      poll=0.25, hold_timeout=30.0)
        man.exit()

    class _EnvLauncher(LauncherInterface):
        def __init__(self, args, env):
            super().__init__(args)
            self._env = env

        def launch(self, extra_env=None):
            return super().launch(extra_env={**self._env,
                                             **(extra_env or {})})

    threads = [threading.Thread(target=supervise, args=(h,), daemon=True)
               for h in ("nodeA", "nodeB")]
    for t in threads:
        t.start()

    # both trainers up
    _wait_for(lambda: len([e for e in _read_log(log_path)
                           if e["event"] == "start"]) >= 2,
              60, "both workers to start")
    # let rank 0 commit a few checkpoints, then SIGKILL nodeB's trainer
    _wait_for(lambda: glob.glob(os.path.join(ckpt_dir, "step_*",
                                             "COMMIT.*")),
              30, "first committed checkpoint")
    starts = [e for e in _read_log(log_path) if e["event"] == "start"]
    victim = next(e for e in starts if e["host"] == "nodeB")
    os.kill(victim["pid"], signal.SIGKILL)

    # supervisor must relaunch nodeB and the replacement must RESUME
    _wait_for(lambda: any(e["event"] == "start" and e["host"] == "nodeB"
                          and e["pid"] != victim["pid"]
                          for e in _read_log(log_path)),
              60, "nodeB relaunch")
    for t in threads:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in threads), "supervisors hung"

    events = _read_log(log_path)
    relaunch = [e for e in events if e["event"] == "start"
                and e["host"] == "nodeB" and e["pid"] != victim["pid"]]
    assert relaunch and relaunch[0]["resumed_from"] > 0, \
        f"replacement did not resume from checkpoint: {relaunch}"
    dones = [e for e in events if e["event"] == "done"]
    assert any(d["final_step"] == 30 for d in dones), dones
    assert results.get("nodeA") == ElasticStatus.COMPLETED
    assert results.get("nodeB") == ElasticStatus.COMPLETED


def test_lease_expiry_detection_and_rank_remap():
    """A vanished peer (no more heartbeats) triggers watch() and the rank
    map re-computes — the _match/:417 + watch/:604 semantics."""
    master = core.TCPStore(is_master=True)
    store = core.TCPStore("127.0.0.1", master.port)
    man = ElasticManager(store, "host1", np="1:2",
                         heartbeat_interval=0.15, lease_ttl=0.8)
    man.register()
    # fake peer host0 joins (sorts before host1)
    slot = store.add("elastic/nslots", 1)
    store.set(f"elastic/slot/{slot}", "host0")
    store.set(_PREFIX + "host0", json.dumps({"ts": time.time()}))

    ok, hosts, rank = man.match()
    assert ok and hosts == ["host0", "host1"] and rank == 1

    assert man.watch(timeout=0.5) == ElasticStatus.COMPLETED  # stable
    # host0 stops heartbeating; its lease expires -> membership change
    status = man.watch(timeout=5.0)
    assert status == ElasticStatus.RESTART  # np still in [1,2]
    ok, hosts, rank = man.match()
    assert ok and hosts == ["host1"] and rank == 0  # re-mapped to rank 0
    man.exit()


def test_heartbeat_lease_expiry_drops_silent_node():
    """A node that stops heartbeating falls out of alive_nodes() after
    one lease TTL — the eviction primitive the watcher builds on."""
    master = core.TCPStore(is_master=True)
    store = core.TCPStore("127.0.0.1", master.port)
    # heartbeat interval far beyond the lease: only register()'s initial
    # beats land, then the node goes silent
    man = ElasticManager(store, "hostA", np="1:2",
                         heartbeat_interval=30.0, lease_ttl=0.5)
    man.register()
    assert man.alive_nodes() == ["hostA"]
    _wait_for(lambda: man.alive_nodes() == [], 5.0,
              "silent node to age out of the lease")
    man.exit()


_PREEMPT_STUB = r"""
import os, signal, sys, time
sys.path.insert(0, sys.argv[1])
from paddle_tpu.distributed.fleet.elastic import on_preemption

mode, flag = sys.argv[2], sys.argv[3]

def slow_save():
    open(flag, "w").write("saving")
    time.sleep(60)   # wedged save: only a second signal can end this

def bad_save():
    raise RuntimeError("disk full")

on_preemption(slow_save if mode == "slow" else bad_save)
open(flag + ".ready", "w").write("ready")
while True:
    time.sleep(0.1)
"""


def _spawn_preempt_stub(tmp_path, mode):
    flag = str(tmp_path / "flag")
    stub = str(tmp_path / "stub.py")
    with open(stub, "w") as f:
        f.write(_PREEMPT_STUB)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, stub, root, mode, flag],
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    _wait_for(lambda: os.path.exists(flag + ".ready"), 60,
              "preemption stub ready")
    return proc, flag


def test_double_signal_force_exits_wedged_save(tmp_path):
    """SIGTERM starts a save that never finishes; a second SIGTERM must
    force-exit immediately via os._exit instead of hanging until the
    platform's SIGKILL."""
    proc, flag = _spawn_preempt_stub(tmp_path, "slow")
    try:
        proc.send_signal(signal.SIGTERM)
        _wait_for(lambda: os.path.exists(flag), 30, "save_fn to start")
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=15)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == 143, rc


def test_failed_preemption_save_exits_distinct_code(tmp_path):
    """A raising save_fn must not be swallowed into a clean 143 exit:
    the worker exits SAVE_FAILED_EXIT_CODE so the operator can tell
    'saved then exited' from 'save failed'."""
    from paddle_tpu.distributed.fleet.elastic import SAVE_FAILED_EXIT_CODE
    proc, _ = _spawn_preempt_stub(tmp_path, "bad")
    try:
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == SAVE_FAILED_EXIT_CODE, rc


@pytest.mark.slow
def test_sigterm_preemption_saves_checkpoint(tmp_path):
    """SIGTERM (TPU preemption notice) triggers the on_preemption hook:
    the worker snapshots a sharded checkpoint and exits 143."""
    master = core.TCPStore(is_master=True)
    log_path = str(tmp_path / "p.jsonl")
    ckpt_dir = str(tmp_path / "ckpt")
    env = dict(
        os.environ,
        ELASTIC_STORE_PORT=str(master.port), ELASTIC_HOST="solo",
        ELASTIC_CKPT=ckpt_dir, ELASTIC_LOG=log_path,
        ELASTIC_TOTAL_STEPS="2000", ELASTIC_STEP_SECS="0.05",
        JAX_PLATFORMS="cpu",
        PYTHONPATH=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    worker = os.path.join(os.path.dirname(__file__), "elastic_worker.py")
    proc = subprocess.Popen([sys.executable, worker], env=env)
    try:
        _wait_for(lambda: any(e["event"] == "start"
                              for e in _read_log(log_path)),
                  60, "worker start")
        _wait_for(lambda: glob.glob(
            os.path.join(ckpt_dir, "step_*", "COMMIT.*")),
            30, "first committed ckpt")
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == 143, rc
    events = _read_log(log_path)
    assert any(e["event"] == "preempt_save" for e in events), events
    assert glob.glob(os.path.join(ckpt_dir, "step_*", "COMMIT.*"))
