"""Serving chaos drills: a REAL serving engine subprocess
(``python -m paddle_tpu.serving``) is SIGKILLed mid-decode,
deadline-stormed, abandoned by a disconnecting client, and SIGTERMed
under load — and every resilience invariant holds.

The drill's oracle is an in-process engine built from the same
ModelSpec + seed (``init_params`` is deterministic) decoding each
prompt SOLO: surviving/relaunched generations must answer
bit-identically, proving recovery changed nothing about the math.

Tier-1 acceptance chain (one drill run — cold starts dominate, so the
legs share two engine generations):

 - generation 1 SIGKILLed while /healthz shows active sequences;
 - generation 2 relaunches with a consistent EMPTY page pool, serves
   bit-identically to the solo oracle, books ZERO request-path
   compiles;
 - a deadline storm is fully shed (429 + Retry-After, reason
   ``deadline_infeasible``), a generous request rides through it
   bit-identically, and the pool returns to zero used/reserved pages
   (no leaks);
 - a client that drops its socket mid-request is cancelled
   (``pt_serve_cancelled_total{cause="disconnect"}``);
 - SIGTERM under load: every in-flight request completes IN FULL
   (bit-identical — no partial responses), a request posted during
   the drain window is refused 503, and the process exits 143.
"""
from __future__ import annotations

import os

import pytest

from paddle_tpu.distributed.drill import run_serve_chaos_drill

pytestmark = pytest.mark.skipif(os.name != "posix",
                                reason="drills SIGKILL real processes")


def test_serve_chaos_drill(tmp_path):
    logs = str(tmp_path / "logs")
    os.makedirs(logs, exist_ok=True)
    report = run_serve_chaos_drill(str(tmp_path), log_dir=logs)
    assert report["gen1_rc"] == -9
    assert report["gen2_recovered"] is True
    assert report["storm_shed"] == 6
    assert report["disconnect_cancelled"] is True
    assert report["drain_rc"] == 143
    assert report["drain_responses"] == 3


def test_serve_chaos_drill_int8(tmp_path, monkeypatch):
    """The full resilience drill holds at int8: the serve subprocess
    inherits PT_SERVE_PRECISION=int8 (quantized weights, int8 KV pool)
    and the oracle quantizes identically, so recovery/drain legs still
    compare bit-identical token streams."""
    monkeypatch.setenv("PT_SERVE_PRECISION", "int8")
    logs = str(tmp_path / "logs")
    os.makedirs(logs, exist_ok=True)
    report = run_serve_chaos_drill(str(tmp_path), log_dir=logs)
    assert report["gen1_rc"] == -9
    assert report["gen2_recovered"] is True
    assert report["storm_shed"] == 6
    assert report["disconnect_cancelled"] is True
    assert report["drain_rc"] == 143
    assert report["drain_responses"] == 3
