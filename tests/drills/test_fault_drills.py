"""Multi-process fault drills: REAL subprocess SIGKILL at scripted
phases of a checkpoint save, recovery proven bit-for-bit.

Each drill spawns a fleet of real ``drill.worker`` subprocesses
(TCPStore-coordinated, JAX_PLATFORMS=cpu), SIGKILLs a victim at a
scripted phase, asserts the survivors fail cleanly (exit 17 after the
commit barrier names the dead rank), then relaunches — possibly at a
different world size — and checks the run completes with every
committed step CRC-verified and byte-identical to a replayed oracle.

One fast deterministic drill (2 procs, kill-mid-marker) stays in
tier-1; the full phase/elastic matrix is ``@pytest.mark.slow``.
Rerun-safety: every drill uses a pytest tmp_path and the conftest
reaper guarantees no leaked children.
"""
from __future__ import annotations

import json
import os

import pytest

from paddle_tpu.distributed.drill import KillSpec, run_drill

pytestmark = pytest.mark.skipif(os.name != "posix",
                                reason="drills SIGKILL real processes")


def _drill(tmp_path, generations, total_steps=5, **kw):
    root = str(tmp_path / "ckpt")
    logs = str(tmp_path / "logs")
    os.makedirs(logs, exist_ok=True)
    report = run_drill(root, generations, total_steps,
                       barrier_timeout=6.0, log_dir=logs, **kw)
    return root, logs, report


def test_kill_mid_marker_2proc_recovers(tmp_path):
    """Tier-1 drill: rank 1 SIGKILLed while its COMMIT marker bytes are
    half-written at step 3 → step 3 never promotes, survivor exits
    cleanly, relaunch resumes from step 2 and finishes bit-for-bit —
    and the armed flight recorder leaves a parseable dump for the
    victim (SIGKILL runs no handlers; the arm-time dump must)."""
    flight_dir = str(tmp_path / "flight")
    root, logs, report = _drill(
        tmp_path,
        [(2, KillSpec("mid-marker", 3, rank=1)), (2, None)],
        flight_dir=flight_dir)
    assert report[0]["latest"] == 2
    assert report[1]["latest"] == 5
    assert report[1]["rcs"] == [0, 0]
    # the survivor's one log line names exactly the dead rank
    log0 = open(os.path.join(logs, "gen0_rank0.log")).read()
    assert "missing ranks [1]" in log0
    assert "arrived: [0]" in log0
    # run_drill already validated the victim's flight dump; pin the
    # identity fields here too
    with open(report[0]["flight"]) as f:
        flight = json.load(f)
    assert flight["process_index"] == 1
    assert flight["run_id"] == report[0]["run_id"]


@pytest.mark.slow
@pytest.mark.parametrize("phase,expected", [
    ("mid-stage", 2),    # torn data file in staging
    ("pre-marker", 2),   # all data staged, no marker
    ("mid-barrier", 3),  # victim sealed + arrived: rank 0 promotes
])
def test_kill_phases_2proc(tmp_path, phase, expected):
    root, logs, report = _drill(
        tmp_path, [(2, KillSpec(phase, 3, rank=1)), (2, None)])
    assert report[0]["latest"] == expected
    assert report[1]["latest"] == 5


@pytest.mark.slow
def test_kill_rank0_mid_barrier_never_promotes(tmp_path):
    """Rank 0 arriving then dying is the one mid-barrier case where the
    step must NOT commit: nobody is left to promote the staging dir."""
    root, logs, report = _drill(
        tmp_path, [(2, KillSpec("mid-barrier", 3, rank=0)), (2, None)])
    assert report[0]["latest"] == 2
    assert report[1]["latest"] == 5


@pytest.mark.slow
@pytest.mark.parametrize("m,n,kill", [
    (2, 1, KillSpec("mid-stage", 3, rank=1)),
    (1, 2, KillSpec("mid-stage", 3, rank=0)),
    (3, 2, KillSpec("mid-marker", 3, rank=2)),
])
def test_elastic_relaunch_across_world_sizes(tmp_path, m, n, kill):
    """A fleet of M writes the checkpoint, dies, and a fleet of N
    resumes it: the coverage-window stitching must hand every new rank
    its rows regardless of the old partitioning."""
    root, logs, report = _drill(tmp_path, [(m, kill), (n, None)])
    assert report[0]["latest"] == 2
    assert report[1]["world"] == n
    assert report[1]["latest"] == 5


@pytest.mark.slow
def test_janitor_sweeps_older_crash_debris(tmp_path):
    """Two crashed generations leave two staging orphans; the startup
    janitor (orphan_age=0) sweeps the older one and — by the
    never-touch-the-newest rule — spares the most recent nonce."""
    root = str(tmp_path / "ckpt")
    logs = str(tmp_path / "logs")
    os.makedirs(logs)
    run_drill(root, [(2, KillSpec("mid-stage", 2, rank=1))], 5,
              barrier_timeout=6.0, log_dir=logs)
    debris_a = [n for n in os.listdir(root) if ".tmp." in n]
    assert debris_a, "mid-stage kill must leave staging debris"
    run_drill(root, [(2, KillSpec("mid-stage", 3, rank=1)), (2, None)],
              5, barrier_timeout=6.0, log_dir=logs, orphan_age=0.0)
    left = [n for n in os.listdir(root) if ".tmp." in n]
    for n in debris_a:
        assert n not in left, f"janitor left aged debris {n}"
    assert len(left) <= 1
