"""Drill-suite fixtures: the no-leaked-children guarantee.

Every subprocess a drill spawns — worker ranks, store-master
processes (including masters RESPAWNED mid-drill by the failover
supervisor) AND cluster-observability aggregators — is registered in
``paddle_tpu.distributed.drill.runner._LIVE``; this autouse reaper
SIGKILLs and waits any stragglers after EVERY test in this directory,
no matter how the test failed — a hung drill or an orphaned respawned
master must never outlive its test or poison a rerun with a stale
endpoint file pointing at a live port."""
import pytest

from paddle_tpu.distributed.drill import runner as _runner


@pytest.fixture(autouse=True)
def _reap_drill_children():
    yield
    _runner.reap_all()
