"""Drill-suite fixtures: the no-leaked-children guarantee.

Every worker subprocess a drill spawns is registered in
``paddle_tpu.distributed.drill.runner._LIVE``; this autouse reaper
SIGKILLs and waits any stragglers after EVERY test in this directory,
no matter how the test failed — a hung drill must never outlive its
test or poison a rerun."""
import pytest

from paddle_tpu.distributed.drill import runner as _runner


@pytest.fixture(autouse=True)
def _reap_drill_children():
    yield
    _runner.reap_all()
