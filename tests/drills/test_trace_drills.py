"""Multi-process step-tracing drills: 2 REAL workers emit spans, the
merge CLI stitches one schema-valid Chrome trace, overlap measured.

Each drill spawns ``world`` drill workers in tracing mode
(``DRILL_TRACE=1``, storeless): every rank enables the real tracer,
records a deterministic staggered compute/collective step profile
(synthetic timestamps — no sleeping, so the analytic overlap fraction
is exactly 0.6 on every rank), exports its per-rank Chrome trace and a
flight dump, and writes a report JSON with the tracer snapshot.  The
runner then runs ``python -m paddle_tpu.observability.merge --trace``
as a REAL subprocess and asserts ONE cluster timeline: every rank
present as a pid with process_name metadata, "X" events complete and
time-ordered, and the per-rank measured overlap strictly positive —
the measurement half of the GC3 compute↔collective overlap item.
"""
from __future__ import annotations

import json
import os

import pytest

from paddle_tpu.distributed.drill import run_trace_drill

pytestmark = pytest.mark.skipif(os.name != "posix",
                                reason="drills spawn real processes")


def test_trace_drill_merges_cluster_timeline(tmp_path):
    """Tier-1 acceptance drill: 2 workers x 6 steps -> merged Chrome
    trace with pids {0, 1}, 2x6x4 complete events, overlap == 0.6."""
    logs = str(tmp_path / "logs")
    os.makedirs(logs, exist_ok=True)
    report = run_trace_drill(str(tmp_path), world=2, steps=6,
                             log_dir=logs)
    assert report["rcs"] == [0, 0]
    # the scripted stagger: collective [0.4, 0.9) of the step, compute
    # [0.1, 0.7) -> 0.3/0.5 of collective time overlapped, every rank
    for ov in report["overlaps"]:
        assert abs(ov - 0.6) < 0.01
    assert report["merged_events"] == 2 * 6 * 4
    # the merged doc really is one valid Chrome trace document
    with open(report["merged_path"]) as f:
        doc = json.load(f)
    assert doc["displayTimeUnit"] == "ms"
    pids = {ev["pid"] for ev in doc["traceEvents"]}
    assert pids == {0, 1}


def test_trace_drill_per_rank_artifacts(tmp_path):
    """Every rank leaves its own trace-<run>-<rank>.json Chrome export,
    a flight dump with spans, and a snapshot report with phase
    percentiles for all four scripted phases."""
    report = run_trace_drill(str(tmp_path), world=2, steps=4)
    run_id = report["run_id"]
    for r in range(2):
        tpath = os.path.join(str(tmp_path), "traces",
                             f"trace-{run_id}-{r}.json")
        with open(tpath) as f:
            doc = json.load(f)
        # per-rank export: every event already stamped with pid=rank
        assert {ev["pid"] for ev in doc["traceEvents"]} == {r}
        cats = {ev.get("cat") for ev in doc["traceEvents"]
                if ev.get("ph") == "X"}
        assert cats == {"host", "compute", "collective"}
        rep = os.path.join(str(tmp_path), "traces",
                           f"trace_report-{r}.json")
        with open(rep) as f:
            snap = json.load(f)
        assert set(snap["phase_ms"]) == {"data_wait", "backward",
                                         "collective", "optimizer"}
        assert snap["process_index"] == r
        fpath = os.path.join(str(tmp_path), "flight",
                             f"flight-{run_id}-{r}.json")
        with open(fpath) as f:
            flight = json.load(f)
        assert flight["reason"] == "drill-exit"
        assert len(flight["spans"]) == 4 * 4  # 4 phases x 4 steps


def test_overlap_drill_bucketing_raises_overlap(tmp_path):
    """GC3 optimization acceptance: on the same synthetic model the
    bucketed reduction's measured overlap fraction is strictly above
    the monolithic reduction's (which is exactly 0 — the single
    all-reduce has no compute left to hide under)."""
    from paddle_tpu.distributed.drill import run_overlap_drill
    report = run_overlap_drill(str(tmp_path / "overlap"))
    assert report["overlap_unbucketed"] == 0.0
    assert report["overlap_bucketed"] > 0.5
    assert report["n_buckets"] >= 2
    with open(report["report_path"], "r", encoding="utf-8") as f:
        assert json.load(f)["overlap_bucketed"] == \
            report["overlap_bucketed"]


def test_overlap_drill_rejects_single_bucket(tmp_path):
    """A target so large everything lands in one bucket can't show
    overlap — the drill must refuse, not vacuously pass."""
    from paddle_tpu.distributed.drill import run_overlap_drill
    from paddle_tpu.distributed.drill.runner import DrillFailure
    with pytest.raises(DrillFailure):
        run_overlap_drill(str(tmp_path / "overlap1"),
                          bucket_kb=1 << 20)


def test_sharded_overlap_drill_scheduled_buckets_raise_overlap(tmp_path):
    """ZeRO dp×sharding acceptance: vs the GSPMD monolithic reduction
    (overlap exactly 0 — nothing left to hide one post-backward op
    under) the planned per-bucket reduce_scatter → all_reduce →
    all_gather chains lift the measured overlap above the 0.5 bar the
    multichip dryrun reports for sharded configs."""
    from paddle_tpu.distributed.drill import run_sharded_overlap_drill
    report = run_sharded_overlap_drill(str(tmp_path / "sh_overlap"))
    assert report["overlap_unbucketed"] == 0.0
    assert report["overlap_scheduled"] > 0.5
    assert report["overlap_scheduled"] > report["overlap_unbucketed"]
    assert report["schedule"] == ("reduce_scatter(sharding:4) -> "
                                  "all_reduce(dp:2) -> "
                                  "all_gather(sharding:4)")
    with open(report["report_path"], "r", encoding="utf-8") as f:
        assert json.load(f)["overlap_scheduled"] == \
            report["overlap_scheduled"]


def test_sharded_overlap_drill_rejects_unscatterable_mesh(tmp_path):
    """A sharding degree of 1 has no scatter schedule to replay — the
    drill must refuse, not vacuously pass."""
    from paddle_tpu.distributed.drill import run_sharded_overlap_drill
    from paddle_tpu.distributed.drill.runner import DrillFailure
    with pytest.raises(DrillFailure):
        run_sharded_overlap_drill(str(tmp_path / "sh1"), n_shard=1)
