"""Store-failover drills: SIGKILL the TCPStore MASTER mid-save, prove
the fleet recovers through the WAL or degrades cleanly.

Inverts the victim of tests/drills/test_fault_drills.py: the worker
ranks survive and the coordination master dies.  Each drill spawns a
real durable store-master subprocess (``drill/store_master.py``), a
fleet of ``drill.worker`` ranks connected through ``ResilientStore``
(endpoint-file resolution), rendezvouses every rank inside the kill
window, SIGKILLs the master there, and asserts:

 - respawned WITH its WAL → the new master replays keys, counters and
   barrier arrivals, clients reconnect (generation bumped, fence
   passes), the in-flight staged commit completes, and a relaunch
   resumes bit-for-bit (tier-1);
 - respawned WITHOUT the WAL → the generation fence trips and every
   rank exits ``EXIT_STORE_LOST`` (StoreUnavailableError naming the
   master endpoint) within its deadline — never a hang (tier-1);
 - a mid-heartbeat kill/respawn must not cost an ElasticManager node
   its lease when the reconnect lands within the TTL (tier-1);
 - the pre-save phase and the never-respawned master are the ``@slow``
   matrix.
"""
from __future__ import annotations

import os
import time

import pytest

from paddle_tpu.distributed.drill import run_store_kill_drill

pytestmark = pytest.mark.skipif(os.name != "posix",
                                reason="drills SIGKILL real processes")


def _roots(tmp_path):
    root = str(tmp_path / "ckpt")
    logs = str(tmp_path / "logs")
    os.makedirs(root, exist_ok=True)
    os.makedirs(logs, exist_ok=True)
    return root, logs


def test_store_master_kill_mid_barrier_recovers(tmp_path):
    """Tier-1 acceptance drill: master SIGKILLed while both ranks are
    mid-barrier at step 3 → respawn replays the WAL (arrivals
    included), generation bumps to 2, the commit completes, the run
    finishes, and a relaunched fleet resumes bit-for-bit."""
    root, logs = _roots(tmp_path)
    report = run_store_kill_drill(
        root, world=2, total_steps=5, kill_step=3, phase="mid-barrier",
        relaunch_extra_steps=2, log_dir=logs)
    assert report["rcs"] == [0, 0]
    assert report["latest"] == 5
    assert report["generation"] == 2  # WAL replay bumped it
    assert report["relaunch_rcs"] == [0, 0]
    assert report["relaunch_latest"] == 7
    # the respawned master really is a different process on (almost
    # certainly) a different port: two endpoints were published
    assert len(report["endpoints"]) == 2
    # a worker log shows the reconnect riding through the outage
    log0 = open(os.path.join(logs, "storekill_rank0.log")).read()
    assert "storekill rendezvous released" in log0
    assert "committed step 5" in log0


def test_store_master_amnesiac_respawn_fails_clean(tmp_path):
    """Tier-1 fencing drill: same kill, but the respawned master has no
    WAL → it advertises no generation, the clients' fence trips, and
    every rank exits EXIT_STORE_LOST with a StoreUnavailableError
    naming the master endpoint — well before the barrier deadline
    could be mistaken for a hang."""
    root, logs = _roots(tmp_path)
    t0 = time.monotonic()
    report = run_store_kill_drill(
        root, world=2, total_steps=5, kill_step=3, phase="mid-barrier",
        respawn_with_wal=False, store_deadline=4.0, barrier_timeout=6.0,
        log_dir=logs)
    elapsed = time.monotonic() - t0
    assert report["rcs"] == [19, 19]
    assert report["latest"] == 2  # step 3 must never have promoted
    assert elapsed < 60, f"clean failure took {elapsed:.0f}s — a hang"
    log0 = open(os.path.join(logs, "storekill_rank0.log")).read()
    assert "store lost during save of step 3" in log0
    assert "amnesiac master" in log0
    # the error names the master endpoint (host:port)
    host, port = report["endpoints"][1]
    assert f"{host}:{port}" in log0


def test_elastic_lease_survives_master_respawn(tmp_path):
    """Mid-heartbeat kill: an ElasticManager heartbeating through a
    ResilientStore keeps its lease across a master SIGKILL + WAL
    respawn — the reconnect lands within the TTL, the slot keys are
    replayed, and alive_nodes() never loses the host."""
    from paddle_tpu.distributed.drill.runner import (_LIVE,
                                                     spawn_store_master)
    from paddle_tpu.distributed.fleet.elastic.manager import \
        ElasticManager
    from paddle_tpu.distributed.resilient_store import ResilientStore

    root, logs = _roots(tmp_path)
    endpoint_file = os.path.join(root, "store.endpoint")
    wal_path = os.path.join(root, "store.wal")
    master, _ep = spawn_store_master(
        endpoint_file=endpoint_file, wal_path=wal_path,
        log_path=os.path.join(logs, "master.log"))
    store = ResilientStore(endpoint_file=endpoint_file, deadline=3.0)
    mgr = ElasticManager(store, "nodeA", np="1",
                         heartbeat_interval=0.2, lease_ttl=4.0)
    try:
        mgr.register()
        assert mgr.alive_nodes() == ["nodeA"]
        # kill the master mid-heartbeat, respawn from WAL
        master.kill()
        master.wait(timeout=30)
        _LIVE.discard(master)
        master, _ep2 = spawn_store_master(
            endpoint_file=endpoint_file, wal_path=wal_path,
            log_path=os.path.join(logs, "master2.log"))
        # within one TTL the lease must still hold: the slot keys were
        # replayed and a reconnected beat refreshed the heartbeat key
        deadline = time.monotonic() + mgr.ttl
        while time.monotonic() < deadline:
            assert mgr.alive_nodes() == ["nodeA"], \
                "node lost its lease across a master respawn"
            time.sleep(0.3)
        assert store.generation == 2
    finally:
        mgr.exit()
        store.close()


@pytest.mark.slow
def test_store_master_kill_pre_save_recovers(tmp_path):
    """The pre-save phase: the master dies before the nonce exchange —
    the whole staged-commit protocol (nonce publish, barrier, promote
    flag) then runs against the respawned master."""
    root, logs = _roots(tmp_path)
    report = run_store_kill_drill(
        root, world=2, total_steps=5, kill_step=3, phase="pre-save",
        relaunch_extra_steps=2, log_dir=logs)
    assert report["rcs"] == [0, 0]
    assert report["latest"] == 5
    assert report["relaunch_latest"] == 7


@pytest.mark.slow
def test_store_master_never_respawned_fails_within_deadline(tmp_path):
    """No supervisor: the master stays dead.  Every rank must exhaust
    its client deadline and exit EXIT_STORE_LOST — bounded, clean,
    step ``kill_step`` never committed."""
    root, logs = _roots(tmp_path)
    t0 = time.monotonic()
    report = run_store_kill_drill(
        root, world=2, total_steps=5, kill_step=3, phase="pre-save",
        respawn=False, store_deadline=3.0, barrier_timeout=5.0,
        storekill_timeout=10.0, gen_timeout=60.0, log_dir=logs)
    assert report["rcs"] == [19, 19]
    assert report["latest"] == 2
    assert time.monotonic() - t0 < 60


@pytest.mark.slow
def test_store_master_kill_3proc_recovers(tmp_path):
    """Same mid-barrier failover at world=3: three ranks' arrivals must
    all come back from the WAL for the respawned master to seal."""
    root, logs = _roots(tmp_path)
    report = run_store_kill_drill(
        root, world=3, total_steps=5, kill_step=3, phase="mid-barrier",
        relaunch_extra_steps=1, log_dir=logs)
    assert report["rcs"] == [0, 0, 0]
    assert report["latest"] == 5
    assert report["relaunch_latest"] == 6
