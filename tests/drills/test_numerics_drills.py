"""NaN-injection numerics drills: REAL workers train a captured MLP,
one rank's input is poisoned, the device-side sentinel must name it.

Each drill spawns ``world`` drill workers in numerics mode
(``DRILL_NUMERICS=1``, storeless): every rank trains a real captured
MLP on CPU with the numerics monitor armed; the poison rank overwrites
one input element with NaN at a scripted step — same shape and dtype,
so the capture cache must NOT retrace — which floods that step's loss
and grads with non-finite values.  The runner asserts from the
per-rank reports that the poisoned rank detected the trip within ONE
cadence window of the injection, that the flight-recorder dump pins a
real parameter path (not just the aggregate ``loss``), that every
clean rank stayed quiet, and that every captured step compiled exactly
once (the monitor folds into the SAME program).  The ``@slow`` matrix
adds the PT_NUMERICS_HALT variant (clean ``EXIT_NUMERICS_HALT``),
a 3-rank fleet, and a cadence-1 immediate-read run.
"""
from __future__ import annotations

import json
import os

import pytest

from paddle_tpu.distributed.drill import run_numerics_drill
from paddle_tpu.distributed.drill.worker import EXIT_NUMERICS_HALT

pytestmark = pytest.mark.skipif(os.name != "posix",
                                reason="drills spawn real processes")


def test_numerics_drill_detects_injected_nan(tmp_path):
    """Tier-1 acceptance drill: 2 workers x 12 steps, rank 1 poisoned
    at step 5, cadence 4 -> detection within one cadence window, the
    flight dump naming a parameter path, clean rank silent, exactly
    one compile per rank."""
    logs = str(tmp_path / "logs")
    os.makedirs(logs, exist_ok=True)
    report = run_numerics_drill(str(tmp_path), world=2, steps=12,
                                poison_step=5, poison_rank=1,
                                cadence=4, log_dir=logs)
    assert report["rcs"] == [0, 0]
    # the detection-latency contract: at most one cadence window late
    assert 5 <= report["detected_step"] <= 5 + 4
    # the sentinel named a real parameter path, not just "loss"
    assert report["named_tensor"].startswith("model::")
    assert report["flight_reason"] == (
        "numerics:nonfinite:" + report["named_tensor"])
    poisoned = report["ranks"][1]
    assert poisoned["anomalies"]["nonfinite"] >= 1
    assert "loss" in poisoned["tripped"]
    # monitors fold into the SAME captured program: one compile, ever
    for r in range(2):
        assert report["ranks"][r]["compiles"] == 1
    clean = report["ranks"][0]
    assert clean["anomalies"] == {}
    assert clean["detected_step"] is None
    # the dump itself is a parseable flight-recorder artifact carrying
    # the poisoned rank's identity
    with open(poisoned["flight"]) as f:
        flight = json.load(f)
    assert flight["process_index"] == 1
    assert flight["reason"].startswith("numerics:nonfinite:model::")


@pytest.mark.slow
def test_numerics_drill_halt_variant(tmp_path):
    """@slow: PT_NUMERICS_HALT=1 converts the sentinel trip into a
    clean EXIT_NUMERICS_HALT exit on the poisoned rank — report still
    written, clean ranks finish 0."""
    report = run_numerics_drill(str(tmp_path), world=2, steps=12,
                                poison_step=5, poison_rank=1,
                                cadence=4, halt=True)
    assert report["rcs"] == [0, EXIT_NUMERICS_HALT]
    assert report["ranks"][1]["halted"] is True
    assert 5 <= report["detected_step"] <= 5 + 4
    assert report["named_tensor"].startswith("model::")


@pytest.mark.slow
def test_numerics_drill_three_ranks_cadence_one(tmp_path):
    """@slow: a 3-rank fleet at cadence 1 — reads every step, so the
    detection lag is exactly the one-step dispatch pipeline; both
    clean ranks stay quiet."""
    report = run_numerics_drill(str(tmp_path), world=3, steps=8,
                                poison_step=3, poison_rank=2,
                                cadence=1)
    assert report["rcs"] == [0, 0, 0]
    assert 3 <= report["detected_step"] <= 3 + 1
    for r in (0, 1):
        assert report["ranks"][r]["anomalies"] == {}
