"""Silent-data-corruption drills: REAL dp-replica workers, a real bit
flip, consensus attribution, supervisor quarantine, restore refusal.

Each drill spawns ``world`` drill workers in SDC mode (``DRILL_SDC=1``)
— every rank trains the SAME captured MLP from the SAME seed, so the
fleet is bit-identical by construction and the only divergence the
drill can produce is the one it injects: the victim flips ONE mantissa
bit of its first captured parameter mid-run, a corruption that is
finite everywhere and invisible to the numerics sentinel.  The
fingerprint exchange runs over a real TCPStore; the majority vote must
finger exactly the victim within one cadence window, name a divergent
tensor, pin a flight dump, and halt the victim into ``EXIT_SDC`` (25).
The ``@slow`` matrix adds the supervisor quarantine scenario (two
verdicts -> RankQuarantine -> elastic downsize -> clean relaunch), the
bit-poisoned-checkpoint restore refusal, and the no-poison control.
"""
from __future__ import annotations

import json
import os

import pytest

from paddle_tpu.distributed.drill import run_sdc_drill
from paddle_tpu.distributed.drill.worker import EXIT_SDC

pytestmark = pytest.mark.skipif(os.name != "posix",
                                reason="drills spawn real processes")


def test_sdc_drill_consensus_fingers_the_flipped_rank(tmp_path):
    """Tier-1 acceptance drill: 3 dp replicas x 12 steps, rank 1 flips
    one parameter bit at step 5, cadence 4 -> consensus fingers rank 1
    within one cadence window, names a fingerprinted tensor path, pins
    a flight dump, and the victim exits EXIT_SDC while both clean
    ranks attribute the verdict to rank 1 and finish 0 with exactly
    one compile each."""
    logs = str(tmp_path / "logs")
    os.makedirs(logs, exist_ok=True)
    report = run_sdc_drill(str(tmp_path), world=3, steps=12,
                           poison_step=5, poison_rank=1, cadence=4,
                           log_dir=logs)
    assert report["rcs"] == [0, EXIT_SDC, 0]
    # detection-latency contract: at most one cadence window late
    assert 5 < report["detected_step"] <= 5 + 4
    # the vote names a tensor that lives in the fingerprint vector
    assert report["named_tensor"].startswith(("param::", "opt"))
    assert report["flight_reason"] == (
        "sdc:divergence:" + report["named_tensor"])
    victim = report["ranks"][1]
    assert victim["halted"] is True
    assert victim["poisoned_tensor"].startswith("param::")
    assert victim["last_divergence"]["rank"] == 1
    # fingerprints fold into the SAME captured program: 1 compile, ever
    for r in range(3):
        assert report["ranks"][r]["compiles"] == 1
    # clean ranks: correct attribution, against the victim and nobody
    # else, and a clean run to completion
    for r in (0, 2):
        clean = report["ranks"][r]
        assert clean["halted"] is False
        assert list(clean["divergences"]) == ["1"]
        assert clean["last_divergence"]["rank"] == 1
    # the dump itself is a parseable flight-recorder artifact carrying
    # the fingered rank's identity
    with open(victim["flight"]) as f:
        flight = json.load(f)
    assert flight["process_index"] == 1
    assert flight["reason"].startswith("sdc:divergence:")


@pytest.mark.slow
def test_sdc_drill_supervisor_quarantines_the_bad_host(tmp_path):
    """@slow: the same poisoned fleet under a real Supervisor — two
    consensus verdicts charge the hardware ledger (never the
    code-crash budget), quarantine rank 1, downsize 3 -> 2, and the
    downsized generation finishes cleanly."""
    report = run_sdc_drill(str(tmp_path), scenario="quarantine",
                           world=3, steps=12, poison_step=5,
                           poison_rank=1, cadence=4,
                           quarantine_threshold=2)
    snap = report["supervision"]
    assert snap["quarantined_ranks"] == [1]
    assert snap["sdc_verdicts"] == {"1": 2}
    assert snap["restarts_by_cause"] == {"sdc": 2}
    assert snap["world"] == 2
    assert all(rc == 0 for rc in snap["final_rcs"].values())
    assert [rz for rz in snap["resizes"] if rz.get("quarantined")]


@pytest.mark.slow
def test_sdc_drill_restore_refuses_poisoned_checkpoint(tmp_path):
    """@slow: a bit flip sealed UNDER the manifest CRC passes file
    verification but fails the per-leaf content digest; the resuming
    worker must exit EXIT_SDC instead of training on corrupt state."""
    report = run_sdc_drill(str(tmp_path), scenario="restore", steps=4)
    assert report["resume_rc"] == EXIT_SDC
    assert "content digest" in report["refusal"]
    assert "silent corruption" in report["refusal"]


@pytest.mark.slow
def test_sdc_drill_control_run_stays_quiet(tmp_path):
    """@slow: no injection — bit-identical replicas must produce zero
    verdicts over the whole run (the false-positive guard for the
    consensus fingerprints)."""
    report = run_sdc_drill(str(tmp_path), world=3, steps=12,
                           poison_rank=-1, cadence=4)
    assert report["rcs"] == [0, 0, 0]
    for r in range(3):
        assert report["ranks"][r]["divergences_total"] == 0
        assert report["ranks"][r]["votes"] >= 1
