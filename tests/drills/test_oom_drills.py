"""OOM-postmortem drills: REAL workers train a captured MLP, one
rank's allocator "fails", the flight dump must name the top buffer.

Each drill spawns ``world`` drill workers in OOM mode (``DRILL_OOM=1``,
storeless): every rank trains a real captured MLP on CPU with the
memory monitor armed; the victim rank swaps its compiled cache entry
for a callable raising ``RESOURCE_EXHAUSTED`` at a scripted step —
exactly what an allocator exhaustion looks like to the capture replay.
The runner asserts that the intercept booked ONE postmortem whose
flight-recorder reason pins ``oom:<program>:<parameter path>`` (the
drill model's first weight dominates every live buffer by
construction), that the ``extra.memory`` payload carries the census,
per-program footprints and watermark history, that the victim exited
``EXIT_OOM`` cleanly while clean ranks booked nothing — and, replaying
the per-rank metrics expositions through a LOCAL aggregator, that the
fleet view derives the cross-rank memory skew and trips the near-OOM
health alarm at the scripted threshold.
"""
from __future__ import annotations

import json
import os

import pytest

from paddle_tpu.distributed.drill import run_oom_drill
from paddle_tpu.distributed.drill.worker import EXIT_OOM

pytestmark = pytest.mark.skipif(os.name != "posix",
                                reason="drills spawn real processes")


def test_oom_drill_books_postmortem_and_fleet_skew(tmp_path):
    """Tier-1 acceptance drill: 2 workers x 8 steps, rank 1's compiled
    entry raises RESOURCE_EXHAUSTED at step 5 -> flight dump pinning a
    parameter path, EXIT_OOM, clean rank silent, one compile per rank,
    fleet skew + near-OOM alarm through the aggregator replay."""
    logs = str(tmp_path / "logs")
    os.makedirs(logs, exist_ok=True)
    report = run_oom_drill(str(tmp_path), world=2, steps=8,
                           oom_step=5, oom_rank=1,
                           mem_bytes=1_000_000, log_dir=logs)
    assert report["rcs"] == [0, EXIT_OOM]
    # the postmortem names the dominant buffer BY PARAMETER PATH
    assert report["named_buffer"].startswith("param::")
    assert report["flight_reason"] == (
        "oom:captured_step(step):" + report["named_buffer"])
    assert "param" in report["census_categories"]
    victim = report["ranks"][1]
    assert victim["oom_events"] == 1
    assert victim["last_oom"]["top_buffer"] == report["named_buffer"]
    assert "RESOURCE_EXHAUSTED" in victim["caught"]
    # the armed failure replays a cache HIT: one compile, ever
    for r in range(2):
        assert report["ranks"][r]["compiles"] == 1
        assert not report["ranks"][r]["fallback"]
    clean = report["ranks"][0]
    assert clean["oom_events"] == 0 and clean["caught"] is None
    # the flight dump itself carries the full evidence payload
    with open(victim["flight"]) as f:
        flight = json.load(f)
    assert flight["process_index"] == 1
    mem = flight["extra"]["memory"]
    assert mem["top_buffer"] == report["named_buffer"]
    assert mem["census"]["total_bytes"] > 0
    assert "captured_step(step)" in mem["programs"]
    assert len(mem["watermarks"]) == victim["watermark_samples"] > 0
    # fleet view from the exposition replay: rank r published
    # mem_bytes * (1 + r), so skew == mem_bytes and the default
    # threshold (mem_bytes * world) trips exactly
    assert report["fleet_skew_bytes"] == 1_000_000.0
    assert report["mem_alarm"] is True
    assert report["healthz"]["ok"] is False
    assert report["healthz"]["memory"]["bytes_in_use_max"] == 2_000_000
    assert report["oom_events_total"] == 1


@pytest.mark.slow
def test_oom_drill_three_ranks_no_alarm_below_threshold(tmp_path):
    """@slow: a 3-rank fleet with the threshold ABOVE every rank's
    watermark — the skew gauge still derives, but the near-OOM alarm
    must stay down and health stays ok-modulo-the-victim."""
    report = run_oom_drill(str(tmp_path), world=3, steps=8,
                           oom_step=4, oom_rank=2,
                           mem_bytes=1_000_000,
                           mem_threshold=100_000_000)
    assert report["rcs"] == [0, 0, EXIT_OOM]
    assert report["named_buffer"].startswith("param::")
    assert report["fleet_skew_bytes"] == 2_000_000.0
    assert report["mem_alarm"] is False
    for r in (0, 1):
        assert report["ranks"][r]["oom_events"] == 0
