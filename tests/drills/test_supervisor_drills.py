"""Supervisor drills: the self-healing supervisor on trial with real
subprocesses (tier-1 acceptance for the elastic launch mode).

Three scenarios, each driven by
:func:`paddle_tpu.distributed.drill.run_supervisor_drill`:

 - ``worker-kill``: a scripted mid-barrier SIGKILL of one rank costs
   exactly one budgeted fleet relaunch and the final checkpoint still
   verifies bit-for-bit against the replayed oracle (tier-1);
 - ``store-kill``: the TCPStore MASTER is SIGKILLed mid-run — the
   supervisor's hot standby (a StoreFollower tailing the WAL) is
   promoted, the endpoint atomically republished, the workers ride
   through with ZERO exits, and the promoted master advertises
   generation >= 2 (tier-1);
 - ``crash-loop``: a deterministically crashing rank exhausts its
   restart budget; the failure names the rank AND its quarantined
   data shard, because every failure correlated with that shard
   (tier-1).
"""
from __future__ import annotations

import os
import time

import pytest

from paddle_tpu.distributed.drill import run_supervisor_drill

pytestmark = pytest.mark.skipif(os.name != "posix",
                                reason="drills SIGKILL real processes")


def _roots(tmp_path):
    root = str(tmp_path / "drill")
    logs = str(tmp_path / "logs")
    os.makedirs(root, exist_ok=True)
    os.makedirs(logs, exist_ok=True)
    return root, logs


def test_supervisor_relaunches_sigkilled_worker_bit_for_bit(tmp_path):
    """Tier-1 acceptance: rank 1 SIGKILLed mid-barrier at step 3 →
    the supervisor books exactly one 'killed' restart, relaunches the
    fleet at a fresh run id, and the step-6 checkpoint is bit-identical
    to an uninterrupted oracle (proven inside the drill)."""
    root, logs = _roots(tmp_path)
    report = run_supervisor_drill(root, scenario="worker-kill", world=2,
                                  total_steps=6, kill_step=3,
                                  log_dir=logs)
    snap = report["supervision"]
    assert report["latest"] == 6
    assert snap["restarts_by_cause"].get("killed", 0) >= 1
    assert snap["generations"] >= 2
    assert snap["quarantined_shards"] == []
    # the outage was booked as replay badput, not silently eaten
    assert snap["restart_replay_seconds"] > 0
    # generation-0 log shows the victim going down, generation-1 log
    # shows the relaunch finishing the run
    g0 = open(os.path.join(logs, "sup_worker-kill_g0_rank0.log")).read()
    g1 = open(os.path.join(logs, "sup_worker-kill_g1_rank0.log")).read()
    assert "committed step 6" not in g0
    assert "committed step 6" in g1


def test_supervisor_promotes_standby_store_with_zero_worker_exits(
        tmp_path):
    """Tier-1 acceptance: the store MASTER is SIGKILLed mid-run — the
    hot standby is promoted (generation >= 2), the endpoint republished,
    and the workers finish with zero exits and zero restarts spent."""
    root, logs = _roots(tmp_path)
    t0 = time.monotonic()
    report = run_supervisor_drill(root, scenario="store-kill", world=2,
                                  total_steps=8, log_dir=logs)
    snap = report["supervision"]
    assert report["latest"] == 8
    assert snap["restarts_total"] == 0
    assert snap["promotions"] >= 1
    assert report["generation"] >= 2
    assert time.monotonic() - t0 < 180, "promotion path hung"


def test_supervisor_crash_loop_exhausts_budget_naming_rank_and_shard(
        tmp_path):
    """Tier-1 acceptance: rank 1 crashes deterministically at step 3
    every generation → the restart budget (2) is exhausted and the
    failure names both the rank and its quarantined data shard."""
    root, logs = _roots(tmp_path)
    report = run_supervisor_drill(root, scenario="crash-loop", world=2,
                                  total_steps=6, kill_step=3,
                                  max_restarts=2, quarantine_threshold=2,
                                  log_dir=logs)
    ex = report["exhausted"]
    assert ex["rank"] == 1
    assert ex["shard"] == "shard-1"
    assert "rank 1" in ex["message"]
    assert "shard-1" in ex["message"]
    assert "quarantined" in ex["message"]
    snap = report["supervision"]
    assert "shard-1" in snap["quarantined_shards"]
    # budget of 2 → exactly 3 generations ran (0, 1, 2)
    assert snap["restarts_total"] == 2
