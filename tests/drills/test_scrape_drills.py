"""Cluster-observability scrape drills: 3 REAL workers + a REAL
aggregator subprocess, scrape -> merge -> skew end-to-end.

Each drill spawns a durable store master, ``world`` drill workers in
observability mode (real telemetry enabled, /metrics endpoint
published into the store under ``obs/<run_id>/endpoint/<rank>``), and
the cluster aggregator (``python -m paddle_tpu.observability.aggregator``)
discovering the fleet through the same store.  The tier-1 drill
asserts the full acceptance chain:

 - counters summed and histogram buckets merged across ranks
   (``pt_step_time_seconds_count == world * steps``);
 - a NONZERO ``pt_step_time_skew_seconds`` (each rank's synthetic step
   profile is ``step_base * (1 + rank)``);
 - the recompile-storm alarm tripping on the CROSS-RANK aggregate
   (each rank trips its local sentinel once; threshold == world);
 - a SIGKILLed rank marked stale within bounded polls — never a hang;
 - the merge CLI stitching the per-rank telemetry JSONL files into one
   time-ordered rank-labeled stream, validated line-for-line.

The ``@slow`` matrix adds the aggregator-restart and store-master
respawn legs (discovery must survive both).
"""
from __future__ import annotations

import os

import pytest

from paddle_tpu.distributed.drill import run_scrape_drill

pytestmark = pytest.mark.skipif(os.name != "posix",
                                reason="drills SIGKILL real processes")


def test_scrape_merge_skew_drill(tmp_path):
    """Tier-1 acceptance drill: 3 workers + aggregator -> summed
    counters, merged histograms, nonzero skew, cross-rank storm alarm
    (healthz 503), kill -> stale, merge CLI one ordered stream."""
    logs = str(tmp_path / "logs")
    os.makedirs(logs, exist_ok=True)
    report = run_scrape_drill(
        str(tmp_path), world=3, steps=10, kill_rank=2, storm=True,
        log_dir=logs)
    assert report["skew_seconds"] > 0.0
    assert report["straggler_ratio"] > 1.0
    assert report["merged_steps"] == 30.0  # 3 ranks x 10 steps summed
    assert report["storms_total"] == 3.0
    assert report["storm_alarm"] == 1.0
    assert report["healthz"]["storm_alarm"] is True
    assert report["healthz"]["ranks_up"] == 3
    assert report["stale_after_kill"] is True
    assert report["rcs"][2] == -9 and report["rcs"][:2] == [0, 0]
    assert report["merge_lines"] == report["expected_lines"] > 0
    # per-rank step-time percentiles made it into the cluster health
    ranks = report["healthz"]["ranks"]
    assert set(ranks) == {"0", "1", "2"}
    p95s = [ranks[r]["step_time"]["train"]["p95_ms"] for r in ranks]
    assert max(p95s) > min(p95s)  # the skew is visible per-rank too
    # fleet goodput derived from every rank's pt_goodput_fraction:
    # the scripted span profile (1/5 data_wait, 4/5 compute) pins
    # min == mean == 0.8 exactly
    assert abs(report["cluster_goodput"]["min"] - 0.8) < 1e-6
    assert abs(report["cluster_goodput"]["mean"] - 0.8) < 1e-6
    assert report["healthz"]["cluster_goodput"]["min"] == 0.8
    # no scripted anomalies or divergences -> those alarms stay down
    assert report["anomaly_alarm"] in (0.0, None)
    assert report["healthz"]["anomaly_alarm"] is False
    assert report["sdc_alarm"] in (0.0, None)
    assert report["healthz"]["sdc_alarm"] is False


def test_scrape_drill_anomaly_storm(tmp_path):
    """A fleet-wide numerics-anomaly burst (each rank books 3 scripted
    trips) crosses the cluster threshold: summed counter, alarm gauge,
    per-rank counts in health, and /healthz flipped to 503 — with NO
    recompile storm in sight."""
    report = run_scrape_drill(
        str(tmp_path), world=2, steps=6, kill_rank=None, storm=False,
        anomalies=3)
    assert report["anomalies_total"] == 6.0
    assert report["anomaly_alarm"] == 1.0
    health = report["healthz"]
    assert health["ok"] is False
    assert health["anomaly_alarm"] is True
    assert health["numerics_anomalies_total"] == 6.0
    assert health["storm_alarm"] is False
    for r in ("0", "1"):
        assert health["ranks"][r]["numerics_anomalies"] == 3.0
    # goodput is orthogonal to the anomaly burst: still 0.8
    assert abs(report["cluster_goodput"]["mean"] - 0.8) < 1e-6


def test_scrape_drill_sdc_alarm_503(tmp_path):
    """Each rank books 2 scripted SDC consensus verdicts (fingering a
    fixed peer, halt disarmed); the aggregator sums the per-rank
    ``pt_sdc_divergence_total`` counters to exactly world * 2, trips
    ``pt_cluster_sdc_alarm`` at its threshold, and the corruption
    signal alone flips /healthz to 503 — no recompile storm, no
    numerics anomalies."""
    report = run_scrape_drill(
        str(tmp_path), world=2, steps=6, kill_rank=None, storm=False,
        sdc_verdicts=2)
    assert report["sdc_divergences_total"] == 4.0
    assert report["sdc_alarm"] == 1.0
    health = report["healthz"]
    assert health["ok"] is False
    assert health["sdc_alarm"] is True
    assert health["sdc_divergences_total"] == 4.0
    assert health["sdc_threshold"] == 4
    # orthogonal alarms stay down; per-rank verdicts land in health
    assert health["storm_alarm"] is False
    assert health["anomaly_alarm"] is False
    for r in ("0", "1"):
        assert health["ranks"][r]["sdc_divergences"] == 2.0


def test_scrape_drill_memory_near_oom_503(tmp_path):
    """Each rank feeds a rank-scaled synthetic allocator watermark
    (rank r exports 5 MB * (1 + r)); the aggregator derives the exact
    cross-rank skew, and with the near-OOM threshold at the fleet max
    the memory alarm alone must flip /healthz to 503 — no recompile
    storm, no anomalies."""
    report = run_scrape_drill(
        str(tmp_path), world=2, steps=6, kill_rank=None, storm=False,
        mem_bytes=5_000_000, mem_threshold=10_000_000)
    assert report["memory_skew_bytes"] == 5_000_000.0
    assert report["memory_alarm"] == 1.0
    health = report["healthz"]
    assert health["ok"] is False
    mem = health["memory"]
    assert mem["mem_alarm"] is True
    assert mem["bytes_in_use_max"] == 10_000_000
    assert mem["skew_bytes"] == 5_000_000
    assert mem["mem_threshold"] == 10_000_000
    # orthogonal alarms stay down; per-rank bytes land in health
    assert health["storm_alarm"] is False
    assert health["anomaly_alarm"] is False
    for r in ("0", "1"):
        assert health["ranks"][r]["memory_bytes_in_use"] == \
            5_000_000 * (1 + int(r))


def test_scrape_drill_shed_storm_503(tmp_path):
    """Each rank scripts a serve admission profile of 3 sheds to 1
    accepted request; the aggregator derives the exact fleet shed
    ratio (0.75), and with the shed-storm threshold at 0.5 the
    load-shedding signal alone must flip /healthz to 503 — no
    recompile storm, no anomalies, no memory pressure."""
    report = run_scrape_drill(
        str(tmp_path), world=2, steps=6, kill_rank=None, storm=False,
        shed=3, served=1, shed_threshold=0.5)
    assert report["shed_total"] == 6.0
    assert abs(report["shed_ratio"] - 0.75) < 1e-6
    assert report["shed_alarm"] == 1.0
    health = report["healthz"]
    assert health["ok"] is False
    serve = health["serve"]
    assert serve["shed_alarm"] is True
    assert serve["shed_total"] == 6
    assert abs(serve["shed_ratio"] - 0.75) < 1e-6
    assert serve["shed_threshold"] == 0.5
    # orthogonal alarms stay down
    assert health["storm_alarm"] is False
    assert health["anomaly_alarm"] is False


def test_scrape_drill_shed_below_threshold_stays_healthy(tmp_path):
    """Light shedding below the storm threshold is accounted (ratio
    exported) but does NOT trip the alarm or degrade /healthz."""
    report = run_scrape_drill(
        str(tmp_path), world=2, steps=6, kill_rank=None, storm=False,
        shed=1, served=9, shed_threshold=0.5)
    assert report["shed_total"] == 2.0
    assert abs(report["shed_ratio"] - 0.1) < 1e-6
    assert report["shed_alarm"] == 0.0
    health = report["healthz"]
    assert health["ok"] is True
    assert health["serve"]["shed_alarm"] is False


@pytest.mark.slow
def test_scrape_drill_aggregator_restart(tmp_path):
    """@slow: kill the aggregator mid-drill and respawn it — the
    cluster view must reconverge from store discovery alone (at
    world-1: the killed rank stays dead across the restart)."""
    report = run_scrape_drill(
        str(tmp_path), world=3, steps=8, kill_rank=1, storm=False,
        restart_aggregator=True)
    assert report["aggregator_restarted"] is True
    assert report["storm_alarm"] in (0.0, None)
    assert report["rcs"][1] == -9


@pytest.mark.slow
def test_scrape_drill_survives_master_respawn(tmp_path):
    """@slow: SIGKILL the WAL-backed store master mid-drill — the
    respawned master replays every published endpoint (generation
    bumped), and the aggregator's discovery rides the failover."""
    report = run_scrape_drill(
        str(tmp_path), world=3, steps=8, kill_rank=None, storm=True,
        respawn_master=True)
    assert report["master_respawned"] is True
    assert report["store_generation"] >= 2
    assert report["rcs"] == [0, 0, 0]
    assert report["storm_alarm"] == 1.0
