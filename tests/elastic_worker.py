"""Trainer subprocess for the elastic kill-and-relaunch integration test.

Joins elastic membership over the shared TCPStore, resumes from the
latest committed checkpoint via CheckpointManager (skipping any
uncommitted/corrupt debris a SIGKILL left behind), trains a toy model
for TOTAL_STEPS eager SGD steps (rank 0 commits every step atomically),
then exits 0. Registers the SIGTERM preemption hook so a graceful stop
also snapshots.

Env: ELASTIC_STORE_PORT, ELASTIC_HOST (logical host id), ELASTIC_CKPT
(CheckpointManager root), ELASTIC_TOTAL_STEPS, ELASTIC_STEP_SECS,
ELASTIC_LOG (progress file the test asserts on).
"""
import json
import os
import sys
import time

import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu import core  # noqa: E402
from paddle_tpu.distributed.checkpoint_manager import (  # noqa: E402
    CheckpointManager)
from paddle_tpu.distributed.fleet.elastic import (  # noqa: E402
    ElasticManager, on_preemption)


def log(entry):
    with open(os.environ["ELASTIC_LOG"], "a") as f:
        f.write(json.dumps(entry) + "\n")


def main():
    port = int(os.environ["ELASTIC_STORE_PORT"])
    host = os.environ["ELASTIC_HOST"]
    path = os.environ["ELASTIC_CKPT"]
    total = int(os.environ.get("ELASTIC_TOTAL_STEPS", "40"))
    dt = float(os.environ.get("ELASTIC_STEP_SECS", "0.05"))

    store = core.TCPStore("127.0.0.1", port)
    man = ElasticManager(store, host, np="1:2", heartbeat_interval=0.2,
                         lease_ttl=1.0)
    man.register()
    _, hosts, rank = man.match()

    pt.seed(0)
    dist.init_mesh({"dp": 1})
    model = pt.nn.Linear(8, 8)
    opt = pt.optimizer.SGD(learning_rate=0.05,
                           parameters=model.parameters())
    from paddle_tpu.distributed.train_step import build_train_step

    def loss_fn(out, y):
        return ((out - y) ** 2).mean()

    step_fn, state = build_train_step(model, loss_fn, opt, donate=False)
    state = dict(state)
    state["train_step"] = jnp.int32(0)

    mgr = CheckpointManager(path, keep_last_n=2)
    state, _ = mgr.restore_latest(template=state)
    start = int(state["train_step"])
    log({"event": "start", "host": host, "rank": rank,
         "resumed_from": start, "hosts": hosts, "pid": os.getpid()})

    on_preemption(lambda: (
        mgr.save(int(state["train_step"]), state, block=True),
        log({"event": "preempt_save", "host": host,
             "step": int(state["train_step"])})))

    rng = np.random.RandomState(0)
    x = rng.randn(8, 8).astype(np.float32)
    y = rng.randn(8, 8).astype(np.float32)
    loss = None
    for i in range(start, total):
        loss, new_state = step_fn(
            {k: state[k] for k in ("params", "buffers", "opt")}, x, y)
        state.update(new_state)
        state["train_step"] = jnp.int32(i + 1)
        if rank == 0:
            mgr.save(i + 1, state)
        time.sleep(dt)
    log({"event": "done", "host": host, "final_step": total,
         "final_loss": float(loss) if loss is not None else None})
    # NOTE: no man.exit() — the node's membership belongs to its
    # supervisor; a finishing trainer must not deregister the host
    return 0


if __name__ == "__main__":
    sys.exit(main())
