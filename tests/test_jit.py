"""to_static / jit compile path (ref model: test/dygraph_to_static/)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import to_tensor
from paddle_tpu.jit import to_static, InputSpec


class SmallNet(pt.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = pt.nn.Linear(4, 16)
        self.fc2 = pt.nn.Linear(16, 2)

    def forward(self, x):
        h = pt.nn.functional.relu(self.fc1(x))
        return self.fc2(h)


def test_to_static_matches_eager():
    pt.seed(1)
    net = SmallNet()
    x = to_tensor(np.random.rand(3, 4).astype(np.float32))
    eager_out = net(x).numpy()
    snet = to_static(net)
    static_out = snet(x).numpy()
    np.testing.assert_allclose(eager_out, static_out, rtol=1e-5, atol=1e-6)


def test_to_static_function():
    @to_static
    def f(a, b):
        return a * 2 + b

    out = f(to_tensor([1.0, 2.0]), to_tensor([10.0, 20.0]))
    np.testing.assert_allclose(out.numpy(), [12.0, 24.0])


def test_to_static_backward():
    pt.seed(2)
    net = to_static(SmallNet())
    x = to_tensor(np.random.rand(8, 4).astype(np.float32))
    y = to_tensor(np.random.randint(0, 2, 8))
    loss = pt.nn.CrossEntropyLoss()(net(x), y)
    loss.backward()
    grads_static = [p.grad.numpy().copy() for p in net.parameters()]

    # same weights, eager path
    net.forward.rollback()
    for p in net.parameters():
        p.clear_grad()
    loss2 = pt.nn.CrossEntropyLoss()(net(x), y)
    loss2.backward()
    grads_eager = [p.grad.numpy() for p in net.parameters()]
    for gs, ge in zip(grads_static, grads_eager):
        np.testing.assert_allclose(gs, ge, rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_to_static_training_loop_converges():
    pt.seed(3)
    np.random.seed(3)
    X = np.random.randn(64, 4).astype(np.float32)
    Y = (X.sum(1) > 0).astype(np.int64)
    net = to_static(SmallNet())
    opt = pt.optimizer.Adam(learning_rate=0.05, parameters=net.parameters())
    losses = []
    for _ in range(30):
        loss = pt.nn.CrossEntropyLoss()(net(to_tensor(X)), to_tensor(Y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5


def test_to_static_batchnorm_buffers_update():
    class BNNet(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.bn = pt.nn.BatchNorm1D(4, data_format="NCL")

        def forward(self, x):
            return self.bn(x)

    pt.seed(4)
    net = BNNet()
    snet = to_static(net)
    x = to_tensor(np.random.rand(8, 4, 6).astype(np.float32) + 5.0)
    before = net.bn._mean.numpy().copy()
    snet(x)
    after = net.bn._mean.numpy()
    assert not np.allclose(before, after), "running mean must update"


def test_to_static_dropout_fresh_masks():
    class DropNet(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.drop = pt.nn.Dropout(0.5)

        def forward(self, x):
            return self.drop(x)

    net = to_static(DropNet())
    net.train()
    x = to_tensor(np.ones((4, 32), np.float32))
    a = net(x).numpy()
    b = net(x).numpy()
    assert not np.allclose(a, b), "dropout mask must differ across calls"
    net.eval()
    c = net(x).numpy()
    np.testing.assert_allclose(c, np.ones_like(c))


def test_control_flow_via_python():
    @to_static
    def f(x, flag):
        if flag:  # static python branch — becomes part of the jit key
            return x * 2
        return x * 3

    x = to_tensor([1.0])
    assert f(x, True).numpy()[0] == 2
    assert f(x, False).numpy()[0] == 3


def test_jit_save_load(tmp_path):
    pt.seed(5)
    net = SmallNet()
    x = np.random.rand(2, 4).astype(np.float32)
    expect = net(to_tensor(x)).numpy()
    path = str(tmp_path / "model")
    pt.jit.save(net, path, input_spec=[InputSpec([2, 4], "float32")])
    loaded = pt.jit.load(path)
    got = loaded(to_tensor(x)).numpy()
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


def test_paddle_save_load_roundtrip(tmp_path):
    net = SmallNet()
    opt = pt.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    path = str(tmp_path / "ckpt.pdparams")
    pt.save(net.state_dict(), path)
    loaded = pt.load(path)
    net2 = SmallNet()
    net2.set_state_dict(loaded)
    x = to_tensor(np.random.rand(2, 4).astype(np.float32))
    np.testing.assert_allclose(net(x).numpy(), net2(x).numpy(), rtol=1e-6)


def test_save_load_rejects_malicious_pickle(tmp_path):
    import pickle

    class Evil:
        def __reduce__(self):
            return (os.system, ("echo pwned",))

    path = str(tmp_path / "evil.pdparams")
    with open(path, "wb") as f:
        pickle.dump({"w": Evil()}, f)
    with pytest.raises(Exception):
        pt.load(path)


class TestDataLoader:
    def _dataset(self, n=20):
        class DS(pt.io.Dataset):
            def __getitem__(self, i):
                return (np.full((3,), i, np.float32),
                        np.asarray(i % 2, np.int64))

            def __len__(self):
                return n
        return DS()

    def test_basic_batching(self):
        dl = pt.io.DataLoader(self._dataset(), batch_size=4)
        batches = list(dl)
        assert len(batches) == 5
        xb, yb = batches[0]
        assert xb.shape == [4, 3]
        assert yb.shape == [4]

    def test_shuffle_and_drop_last(self):
        dl = pt.io.DataLoader(self._dataset(10), batch_size=3, shuffle=True,
                              drop_last=True)
        batches = list(dl)
        assert len(batches) == 3

    def test_multiprocess_workers(self):
        dl = pt.io.DataLoader(self._dataset(16), batch_size=4, num_workers=2)
        batches = list(dl)
        assert len(batches) == 4
        seen = sorted({int(v) for xb, _ in batches
                       for v in xb.numpy()[:, 0]})
        assert seen == list(range(16))

    def test_tensor_dataset_and_random_split(self):
        X = np.random.rand(10, 2).astype(np.float32)
        Y = np.arange(10)
        ds = pt.io.TensorDataset([X, Y])
        a, b = pt.io.random_split(ds, [7, 3])
        assert len(a) == 7 and len(b) == 3
        x0, y0 = ds[0]
        assert x0.shape == [2]

    def test_iterable_dataset(self):
        class Stream(pt.io.IterableDataset):
            def __iter__(self):
                for i in range(7):
                    yield np.full((2,), i, np.float32)
        dl = pt.io.DataLoader(Stream(), batch_size=3, drop_last=False)
        batches = list(dl)
        assert len(batches) == 3
        assert batches[-1].shape == [1, 2]


def test_to_static_function_closure_layer_trains():
    """to_static on a bare FUNCTION must thread closure-captured layers'
    params through the program — previously they traced as constants and
    backward() silently produced no grads (loss never moved)."""
    pt.seed(9)
    np.random.seed(9)
    net = pt.nn.Sequential(pt.nn.Linear(8, 16), pt.nn.GELU(),
                           pt.nn.Linear(16, 2))
    opt = pt.optimizer.SGD(learning_rate=0.3, parameters=net.parameters())
    X = np.random.randn(32, 8).astype(np.float32)
    Y = (X.sum(1) > 0).astype(np.int64)

    @pt.jit.to_static
    def step(xb, yb):
        return pt.nn.functional.cross_entropy(net(xb), yb)

    losses = []
    for _ in range(20):
        loss = step(to_tensor(X), to_tensor(Y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses[::19]


def test_to_static_function_closure_buffers_update():
    """Buffer mutations (BN running stats) inside a closure-captured layer
    must write back to the live layer after a compiled-function call."""
    pt.seed(4)
    bn = pt.nn.BatchNorm1D(4, data_format="NCL")
    bn.train()

    @pt.jit.to_static
    def fwd(x):
        return bn(x)

    before = bn._mean.numpy().copy()
    x = to_tensor(np.random.rand(8, 4, 6).astype(np.float32) + 5.0)
    fwd(x)
    assert not np.allclose(before, bn._mean.numpy())


_global_net = None


def test_to_static_function_global_layer_trains():
    """Layers referenced as module-level globals (not closure freevars)
    must also thread through the compiled program."""
    global _global_net
    pt.seed(12)
    np.random.seed(12)
    _global_net = pt.nn.Linear(6, 2)
    opt = pt.optimizer.SGD(learning_rate=0.5,
                           parameters=_global_net.parameters())
    X = np.random.randn(32, 6).astype(np.float32)
    Y = (X.sum(1) > 0).astype(np.int64)

    @pt.jit.to_static
    def step(xb, yb):
        return pt.nn.functional.cross_entropy(_global_net(xb), yb)

    losses = []
    for _ in range(15):
        loss = step(to_tensor(X), to_tensor(Y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[::14]


def test_to_static_function_per_layer_mode_retrace():
    """Flipping ONE captured layer's train/eval mode must retrace — an
    aggregate boolean cache key would silently keep the stale mode."""
    pt.seed(13)
    drop = pt.nn.Dropout(0.5)
    scalev = pt.nn.Linear(8, 8)
    drop.train()

    @pt.jit.to_static
    def fwd(x):
        return drop(scalev(x))

    x = to_tensor(np.ones((4, 8), np.float32))
    a = fwd(x).numpy()
    b = fwd(x).numpy()
    assert not np.allclose(a, b)  # dropout active
    drop.eval()  # only drop's mode changes
    c = fwd(x).numpy()
    d = fwd(x).numpy()
    np.testing.assert_allclose(c, d)  # deterministic now


def test_to_static_function_rebound_global_retraces():
    """Rebinding a captured global layer to a fresh instance must be
    picked up (no stale-object cache)."""
    global _global_net
    pt.seed(14)
    _global_net = pt.nn.Linear(4, 2)

    @pt.jit.to_static
    def fwd(x):
        return _global_net(x)

    x = to_tensor(np.ones((2, 4), np.float32))
    a = fwd(x).numpy()
    pt.seed(99)
    _global_net = pt.nn.Linear(4, 2)  # fresh weights
    b = fwd(x).numpy()
    assert not np.allclose(a, b), "rebound layer's weights must be used"


def test_to_static_attr_name_collision_not_captured():
    """An unrelated global layer whose NAME matches an attribute access
    must not be captured as traced params."""
    global _decoy
    _decoy = pt.nn.Linear(3, 3)

    class Holder:
        pass

    h = Holder()
    h._decoy = "just a string attribute"

    @pt.jit.to_static
    def fwd(x):
        _ = h._decoy  # attribute named like the global layer
        return x * 2.0

    from paddle_tpu.jit.api import _closure_layer_targets
    names = [p for p, _ in _closure_layer_targets(fwd._orig_fn)]
    assert all("_decoy" != n for n in names), names
    # 'h' itself IS a freevar but not a Layer, so nothing is captured
    out = fwd(to_tensor(np.ones(2, np.float32)))
    np.testing.assert_allclose(out.numpy(), [2.0, 2.0])
