"""Pipeline-parallel memory accounting (VERDICT r2 weak #3): the
compiled pipelined step's per-device XLA memory footprint must beat the
plain replicated baseline on resident state, and its activation working
set must stay bounded (remat discipline) — measured from XLA's own
memory analysis of the lowered program, the compile-time equivalent of
``device.memory_stats()``."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.train_step import build_train_step
from paddle_tpu.incubate.models import (GPTForCausalLM,
                                        GPTPretrainingCriterion, gpt_tiny)
from paddle_tpu.framework import random as _random
from paddle_tpu.distributed._jax_compat import shard_map as _shard_map, use_mesh as _use_mesh


@pytest.fixture(autouse=True)
def _reset():
    yield
    dist.set_mesh(None)
    dist.destroy_process_group()


def _mem(step, state, ids, labels):
    """Lower the train step AOT and read XLA's memory analysis."""
    key = jax.random.key(0)
    lr = jnp.float32(1e-3)
    x = jax.device_put(jnp.asarray(ids), step.data_sharding)
    y = jax.device_put(jnp.asarray(labels), step.data_sharding)
    with _use_mesh(step.mesh):
        compiled = step.jitted.lower(state, key, lr, x, y).compile()
    ma = compiled.memory_analysis()
    return (int(ma.argument_size_in_bytes), int(ma.temp_size_in_bytes))


@pytest.mark.slow
def test_pipelined_state_bytes_beat_replicated_baseline():
    pt.seed(0)
    cfg = gpt_tiny(tensor_parallel=False)
    cfg.num_layers = 4
    cfg.hidden_dropout_prob = 0.0
    cfg.attention_probs_dropout_prob = 0.0
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 1024, (8, 16)).astype(np.int32)
    labels = rng.randint(0, 1024, (8, 16)).astype(np.int32)

    # replicated baseline: dp only, every chip holds the full model+opt
    dist.init_mesh({"dp": 2})
    opt1 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step1, state1 = build_train_step(model, crit, opt1, donate=False)
    base_args, base_temp = _mem(step1, state1, ids, labels)

    # pipelined: same dp, blocks + their optimizer state sharded over pp
    dist.init_mesh({"dp": 2, "pp": 4})
    opt2 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step2, state2 = build_train_step(model, crit, opt2, donate=False)
    pp_args, pp_temp = _mem(step2, state2, ids, labels)

    # resident state (params + adam moments) shrinks: each chip stores
    # only its stage's blocks
    assert pp_args < base_args, (pp_args, base_args)
    # activation working set stays bounded (per-tick stage inputs via
    # remat, not the whole unrolled pipeline)
    assert pp_temp <= 3 * max(base_temp, 1), (pp_temp, base_temp)


@pytest.mark.slow
def test_zero_sharding_shrinks_argument_bytes():
    """ZeRO-1: optimizer-state partitioning must show up in the lowered
    program's per-device argument bytes."""
    from paddle_tpu.distributed.sharding import group_sharded_parallel

    def build(level):
        pt.seed(3)
        model = pt.nn.Sequential(pt.nn.Linear(256, 512), pt.nn.ReLU(),
                                 pt.nn.Linear(512, 256))
        opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
        if level:
            group_sharded_parallel(model, opt, level=level)
        return build_train_step(
            model, lambda o, y: ((o - y) ** 2).mean(), opt, donate=False)

    rng = np.random.RandomState(0)
    x = rng.randn(16, 256).astype(np.float32)
    y = rng.randn(16, 256).astype(np.float32)

    dist.init_mesh({"dp": 2, "sharding": 4})
    step1, state1 = build(None)
    base_args, _ = _mem(step1, state1, x, y)
    step2, state2 = build("os")
    os_args, _ = _mem(step2, state2, x, y)
    assert os_args < base_args, (os_args, base_args)
