"""tpu-lint: rule fixtures, suppressions, baseline round-trip, and the
self-clean gate that keeps paddle_tpu/ + exp/ free of new violations.

Each rule gets a positive fixture (must fire) and a negative fixture
(must stay silent) — the negative encodes the correct idiom the rule
pushes toward, so a rule that over-triggers fails here before it ever
annoys a developer.
"""
import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu.tools.lint import (
    default_baseline_path, default_rules, diff_against_baseline,
    lint_source, load_baseline, rule_catalog, run_paths, write_baseline,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GATE_PATHS = [os.path.join(ROOT, p)
              for p in ("paddle_tpu", "exp", "bench.py", "bench_eager.py")]


def rules_fired(src, path="pkg/mod.py"):
    return {v.rule for v in lint_source(textwrap.dedent(src), path=path)}


# -- rule fixtures -----------------------------------------------------------
# {rule: (path, positive source, negative source)}
FIXTURES = {
    "TPU001": (
        "pkg/mod.py",
        """
        import jax
        def run(xs):
            for x in xs:
                f = jax.jit(lambda a: a + 1)
                f(x)
        """,
        """
        import jax
        f = jax.jit(lambda a: a + 1)
        def run(xs):
            for x in xs:
                f(x)
        """,
    ),
    "TPU002": (
        "pkg/mod.py",
        """
        import jax
        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
        """,
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def f(x):
            if x.ndim > 0 and x is not None:
                return jnp.where(x > 0, x, -x)
            return x
        """,
    ),
    "TPU003": (
        "pkg/mod.py",
        """
        class Net:
            def forward(self, x):
                scale = float(x.mean().item())
                return x * scale
        """,
        """
        class Net:
            def forward(self, x):
                scale = x.mean()
                return x * scale
        """,
    ),
    "TPU004": (
        "pkg/mod.py",
        """
        import jax
        class Net:
            def build(self):
                @jax.jit
                def step(x):
                    self.cache = x * 2
                    return x + 1
                return step
        """,
        """
        import jax
        class Net:
            def build(self):
                @jax.jit
                def step(x):
                    return x * 2, x + 1
                return step
        """,
    ),
    "TPU005": (
        "pkg/mod.py",
        """
        import jax
        def build(f):
            return jax.jit(f, static_argnums=("mode",))
        """,
        """
        import jax
        def build(f):
            return jax.jit(f, static_argnums=(0, 1),
                           static_argnames=("mode",))
        """,
    ),
    "TPU006": (
        "pkg/mod.py",
        """
        import jax
        def outer(xs):
            history = []
            def body(carry, x):
                history.append(x)
                return carry + x, x
            return jax.lax.scan(body, 0, xs)
        """,
        """
        import jax
        def outer(xs):
            def body(carry, x):
                acc = []
                acc.append(x)
                return carry + x, x
            return jax.lax.scan(body, 0, xs)
        """,
    ),
    "TPU007": (
        "pkg/mod.py",
        """
        import jax
        def train_loop(step, batches, state):
            for b in batches:
                state, loss = step(state, b)
                print(jax.device_get(loss))
            return state
        """,
        """
        import jax
        def train_loop(step, batches, state):
            loss = None
            for b in batches:
                state, loss = step(state, b)
            print(jax.device_get(loss))
            return state
        """,
    ),
    "TPU008": (
        "pkg/distributed/mod.py",
        """
        def deregister(store, key):
            try:
                store.delete(key)
            except Exception:
                pass
        """,
        """
        import logging
        def deregister(store, key):
            try:
                store.delete(key)
            except Exception as e:
                logging.getLogger(__name__).warning("delete: %s", e)
        """,
    ),
    "TPU009": (
        "pkg/distributed/mod.py",
        """
        import time
        def barrier(store, key, world):
            store.add(key, 1)
            while store.add(key, 0) < world:
                time.sleep(0.01)
        """,
        """
        from ..utils.retry import wait_until
        def barrier(store, key, world, timeout):
            store.add(key, 1)
            wait_until(lambda: store.add(key, 0) >= world, timeout,
                       desc="barrier")
        """,
    ),
    "TPU010": (
        "paddle_tpu/hapi/mod.py",
        """
        def fit_hook(epoch, loss):
            print(f"epoch {epoch}: loss={loss}")
        """,
        """
        import sys
        from ..observability import get_logger
        def fit_hook(epoch, loss):
            get_logger(__name__).info("epoch %s: loss=%s", epoch, loss)
            print("progress", file=sys.stderr)
        """,
    ),
    "TPU011": (
        "pkg/mod.py",
        """
        import jax
        def train(step_fn, params, batch):
            f = jax.jit(step_fn, donate_argnums=(0,))
            new_params = f(params, batch)
            return params["w"], new_params
        """,
        """
        import jax
        def train(step_fn, params, batch):
            f = jax.jit(step_fn, donate_argnums=(0,))
            params = f(params, batch)
            return params["w"]
        """,
    ),
    "TPU012": (
        "pkg/mod.py",
        """
        from jax.experimental import pallas as pl
        def attention(q, k, v):
            return pl.pallas_call(_kernel, out_shape=q)(q, k, v)
        """,
        """
        def attention(q, k, v):
            from paddle_tpu.ops.pallas_ops import mha
            return mha(q, k, v, causal=True)
        """,
    ),
    "TPU013": (
        "pkg/mod.py",
        """
        from paddle_tpu.core import RecordEvent
        def step(model, x):
            with RecordEvent("forward"):
                loss = model(x)
                return loss.item()
        """,
        """
        from paddle_tpu.core import RecordEvent
        def step(model, x):
            with RecordEvent("forward"):
                loss = model(x)
            return loss.item()
        """,
    ),
    "TPU015": (
        "paddle_tpu/incubate/models/m.py",
        """
        from jax.sharding import PartitionSpec as P
        def seq_constraint(x):
            return P("dp", "sep")
        """,
        """
        def seq_constraint(x):
            from paddle_tpu.distributed.auto_parallel.spec_layout import (
                default_layout)
            return default_layout().batch_seq(x.ndim)
        """,
    ),
    "TPU016": (
        "paddle_tpu/incubate/models/m.py",
        """
        class Block:
            def forward(self, x, mask):
                return self.ln1(x + self.attention(x, mask))
        """,
        """
        class Block:
            def forward(self, x, mask):
                return self.ln1(x, residual=self.attention(x, mask))
        """,
    ),
    "TPU017": (
        "paddle_tpu/hapi/mod.py",
        """
        import math
        def train_loop(model, data):
            for x, y in data:
                loss = model.train_batch(x, y)
                if math.isnan(float(loss)):
                    raise RuntimeError("diverged")
        """,
        """
        from paddle_tpu.observability.numerics import get_monitor
        def train_loop(model, data):
            for x, y in data:
                model.train_batch(x, y)
            if get_monitor().anomaly_count("nonfinite"):
                raise RuntimeError("diverged")
        """,
    ),
    "TPU018": (
        "pkg/mod.py",
        """
        def train_loop(step, data):
            losses = []
            for x, y in data:
                loss = step(x, y)
                losses.append(loss)
            return losses
        """,
        """
        def train_loop(step, data):
            losses = []
            for i, (x, y) in enumerate(data):
                loss = step(x, y)
                if i % 100 == 0:
                    losses.append(float(loss))
            return losses
        """,
    ),
    "TPU019": (
        "paddle_tpu/serving/handlers.py",
        """
        import jax
        def handle_generate(engine, tokens):
            fn = jax.jit(engine.decode_fn)
            return fn(tokens)
        """,
        """
        def handle_generate(engine, tokens):
            exe = engine.decode_exe[engine.decode_bucket_for(len(tokens))]
            return exe(tokens)
        """,
    ),
    "TPU020": (
        "paddle_tpu/utils/mod.py",
        """
        import os
        CACHE_HOME = os.environ.get("PT_CACHE_HOME", "/tmp/cache")
        """,
        """
        import os
        def cache_home():
            return os.environ.get("PT_CACHE_HOME", "/tmp/cache")
        """,
    ),
    "TPU021": (
        "paddle_tpu/serving/mod.py",
        """
        def handle(stream, worker):
            out = stream.result()
            worker.join()
            return out
        """,
        """
        def handle(stream, worker):
            out = stream.result(timeout=120.0)
            worker.join(5.0)
            return out
        """,
    ),
    "TPU022": (
        "paddle_tpu/serving/mod.py",
        """
        import jax.numpy as jnp
        def pack(x):
            return x.astype(jnp.int8)
        """,
        """
        from paddle_tpu.ops.quant_kernels import quantize_kv
        def pack(x):
            q, scale = quantize_kv(x)
            return q, scale
        """,
    ),
    "TPU023": (
        "paddle_tpu/core/mod.py",
        """
        import signal
        def arm(cb):
            signal.signal(signal.SIGTERM, cb)
        """,
        """
        import signal
        def arm(cb, install=None):
            # library code surfaces the callback; the process OWNER
            # (preemption hook / launcher / drain installer) registers
            if install is not None:
                install(signal.SIGTERM, cb)
            return cb
        """,
    ),
    "TPU024": (
        "paddle_tpu/core/mod.py",
        """
        import time
        import jax
        @jax.jit
        def step(params, x):
            noise = time.time()
            return params * x + noise
        """,
        """
        import jax
        import jax.random as jrandom
        @jax.jit
        def step(params, x, key, step_idx):
            k = jrandom.fold_in(key, step_idx)
            noise = jrandom.normal(k, x.shape)
            return params * x + noise
        """,
    ),
    "TPU014": (
        "paddle_tpu/distributed/mod.py",
        """
        import jax.lax as lax
        def reduce_grads(grads):
            out = {}
            for name, g in grads.items():
                out[name] = lax.psum(g, "dp")
            return out
        """,
        """
        import jax.numpy as jnp
        import jax.lax as lax
        def reduce_grads(grads, plan):
            flat = jnp.concatenate([jnp.ravel(g) for g in grads.values()])
            return lax.psum(flat, "dp")
        """,
    ),
}


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_fires_on_positive(rule):
    path, pos, _ = FIXTURES[rule]
    assert rule in rules_fired(pos, path=path)


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_silent_on_negative(rule):
    path, _, neg = FIXTURES[rule]
    assert rule not in rules_fired(neg, path=path)


def test_catalog_has_at_least_eight_rules():
    cat = rule_catalog()
    assert len(cat) >= 8
    for rid, name, rationale in cat:
        assert rid.startswith("TPU") and len(rid) == 6
        assert name and rationale


# -- rule-specific edges -----------------------------------------------------

def test_tpu001_fires_per_call_in_forward():
    src = """
    import jax
    class Net:
        def forward(self, x):
            return jax.jit(lambda a: a + 1)(x)
    """
    assert "TPU001" in rules_fired(src)


def test_tpu001_silent_for_jit_in_for_iterable():
    # the iterable expression evaluates once, not per iteration
    src = """
    import jax
    def bench(x):
        out = []
        for name, fn in [("a", jax.jit(abs))]:
            out.append(fn(x))
        return out
    """
    assert "TPU001" not in rules_fired(src)


def test_tpu001_partial_jit_counts():
    src = """
    import functools, jax
    def run(xs):
        for x in xs:
            f = functools.partial(jax.jit, donate_argnums=(0,))(abs)
            f(x)
    """
    assert "TPU001" in rules_fired(src)


def test_tpu002_star_args_truthiness_is_static():
    src = """
    import jax
    @jax.jit
    def f(x, *labels):
        if labels:
            return x + labels[0]
        return x
    """
    assert "TPU002" not in rules_fired(src)


def test_tpu002_while_on_traced_value():
    src = """
    import jax
    @jax.jit
    def f(x):
        while x > 0:
            x = x - 1
        return x
    """
    assert "TPU002" in rules_fired(src)


def test_tpu003_kernel_path_without_forward_name():
    src = """
    import numpy as np
    def softmax(x, axis):
        host = np.asarray(x._data)
        return host
    """
    assert "TPU003" in rules_fired(src, path="paddle_tpu/ops/fake.py")
    # same code outside a kernel path and outside forward: silent
    assert "TPU003" not in rules_fired(src, path="pkg/utils.py")


def test_tpu003_chained_sync_reports_once():
    src = """
    class Net:
        def forward(self, x):
            return x.numpy().tolist()
    """
    vs = [v for v in lint_source(textwrap.dedent(src)) if v.rule == "TPU003"]
    assert len(vs) == 1


def test_tpu005_static_argnames_int_flagged():
    src = """
    import jax
    g = jax.jit(abs, static_argnames=(0,))
    """
    assert "TPU005" in rules_fired(src)


def test_tpu009_scoped_to_distributed_and_core_paths():
    src = """
    import time
    def poll(proc):
        while proc.poll() is None:
            time.sleep(0.2)
    """
    assert "TPU009" in rules_fired(src, path="pkg/distributed/launch.py")
    assert "TPU009" in rules_fired(src, path="paddle_tpu/core/store.py")
    # a data-loader pacing sleep outside coordination code is fine
    assert "TPU009" not in rules_fired(src, path="pkg/vision/loader.py")


def test_tpu009_sleep_outside_loop_is_silent():
    src = """
    import time
    def settle():
        time.sleep(0.1)
    """
    assert "TPU009" not in rules_fired(src, path="pkg/distributed/mod.py")


def test_tpu010_scoped_to_library_code_only():
    src = """
    def report(msg):
        print(msg)
    """
    assert "TPU010" in rules_fired(src, path="paddle_tpu/optimizer/lr.py")
    # CLI entry points, tools and tests own their stdout
    assert "TPU010" not in rules_fired(src, path="paddle_tpu/tools/lint/cli.py")
    assert "TPU010" not in rules_fired(src, path="paddle_tpu/tests/test_x.py")
    assert "TPU010" not in rules_fired(src, path="tests/test_x.py")
    assert "TPU010" not in rules_fired(src, path="bench.py")
    assert "TPU010" not in rules_fired(src, path="paddle_tpu/cli.py")


def test_tpu010_explicit_file_kwarg_is_silent():
    src = """
    import sys
    def report(msg, stream):
        print(msg, file=stream)
        print("fatal", file=sys.stderr)
    """
    assert "TPU010" not in rules_fired(src, path="paddle_tpu/hapi/model.py")


def test_tpu008_bare_except_flagged_only_in_distributed_paths():
    src = """
    def f(store):
        try:
            store.get("k")
        except:
            pass
    """
    assert "TPU008" in rules_fired(src, path="pkg/fleet/util.py")
    assert "TPU008" not in rules_fired(src, path="pkg/vision/util.py")


def test_tpu011_loop_carried_reuse_fires():
    # f(params) every iteration without rebinding: iteration 2 passes a
    # buffer iteration 1 already donated
    src = """
    import jax
    def train(step_fn, params, batches):
        f = jax.jit(step_fn, donate_argnums=(0,))
        for b in batches:
            out = f(params, b)
        return out
    """
    assert "TPU011" in rules_fired(src)


def test_tpu011_loop_rebind_is_silent():
    src = """
    import jax
    def train(step_fn, params, batches):
        f = jax.jit(step_fn, donate_argnums=(0,))
        for b in batches:
            params = f(params, b)
        return params
    """
    assert "TPU011" not in rules_fired(src)


def test_tpu011_non_donated_position_is_silent():
    # only position 0 is donated; `batch` stays readable
    src = """
    import jax
    def train(step_fn, params, batch):
        f = jax.jit(step_fn, donate_argnums=(0,))
        out = f(params, batch)
        return batch.shape, out
    """
    assert "TPU011" not in rules_fired(src)


def test_tpu011_direct_jit_call_fires():
    src = """
    import jax
    def train(step_fn, params, batch):
        out = jax.jit(step_fn, donate_argnums=0)(params, batch)
        return params["w"], out
    """
    assert "TPU011" in rules_fired(src)


def test_tpu011_plain_jit_without_donation_is_silent():
    src = """
    import jax
    def train(step_fn, params, batch):
        f = jax.jit(step_fn)
        out = f(params, batch)
        return params["w"], out
    """
    assert "TPU011" not in rules_fired(src)


PALLAS_SRC = """
from jax.experimental import pallas as pl
def kernel_entry(x):
    return pl.pallas_call(_body, out_shape=x)(x)
"""


def test_tpu012_inside_ops_is_silent():
    # the dispatch layer itself is where raw pallas_call belongs
    assert "TPU012" not in rules_fired(
        PALLAS_SRC, path="paddle_tpu/ops/pallas_ops.py")
    assert "TPU012" not in rules_fired(
        PALLAS_SRC, path="paddle_tpu/ops/fused_kernels.py")


def test_tpu012_fires_outside_ops():
    for path in ("paddle_tpu/nn/functional/common.py", "exp/bench_flash.py",
                 "bench.py"):
        assert "TPU012" in rules_fired(PALLAS_SRC, path=path)


def test_tpu012_alternate_spellings_fire():
    src = """
    from jax.experimental.pallas import pallas_call
    def f(x):
        return pallas_call(_body, out_shape=x)(x)
    """
    assert "TPU012" in rules_fired(src)
    src = """
    import jax
    def f(x):
        return jax.experimental.pallas.pallas_call(_body, out_shape=x)(x)
    """
    assert "TPU012" in rules_fired(src)


def test_tpu013_fires_in_tracer_phase_span():
    src = """
    import numpy as np
    def step(tr, model, x):
        with tr.phase("backward"):
            loss = model(x)
            host = np.asarray(loss._data)
        return host
    """
    assert "TPU013" in rules_fired(src)


def test_tpu013_fires_on_get_tracer_receiver():
    src = """
    from paddle_tpu.observability.trace import get_tracer
    def step(model, x):
        with get_tracer().phase("forward"):
            return model(x).numpy()
    """
    assert "TPU013" in rules_fired(src)


def test_tpu013_silent_on_deferred_def_inside_span():
    # a function DEFINED inside the span runs later — not a sync in the
    # timed window
    src = """
    from paddle_tpu.core import RecordEvent
    def build(model, x):
        with RecordEvent("build"):
            def hook(t):
                return t.item()
            return hook
    """
    assert "TPU013" not in rules_fired(src)


def test_tpu013_suppression_comment():
    src = """
    from paddle_tpu.core import RecordEvent
    def step(model, x):
        with RecordEvent("forward"):
            return model(x).item()  # tpu-lint: disable=TPU013
    """
    assert "TPU013" not in rules_fired(src)


def test_tpu014_fires_on_repo_all_reduce_wrapper():
    src = """
    import paddle_tpu.distributed as dist
    def sync_grads(model):
        for p in model.parameters():
            dist.all_reduce(p.grad)
    """
    assert "TPU014" in rules_fired(src, path="paddle_tpu/x.py")


def test_tpu014_silent_on_non_param_loop():
    src = """
    import jax.lax as lax
    def losses(batches):
        return [lax.pmean(b, "dp") for b in batches] + [
            lax.psum(b, "dp") for b in batches]
    def accumulate(batches):
        tot = 0
        for b in batches:
            tot = tot + lax.psum(b, "dp")
        return tot
    """
    assert "TPU014" not in rules_fired(src, path="paddle_tpu/x.py")


def test_tpu014_silent_outside_library_code():
    src = """
    import jax.lax as lax
    def check(grads):
        for g in grads.values():
            assert lax.psum(g, "dp") is not None
    """
    assert "TPU014" not in rules_fired(src, path="tests/test_x.py")
    assert "TPU014" not in rules_fired(src, path="paddle_tpu/tools/x.py")


def test_tpu014_nested_param_loop_reports_once_per_call():
    from paddle_tpu.tools.lint import lint_source
    import textwrap
    src = textwrap.dedent("""
    import jax.lax as lax
    def sync(groups):
        for group in groups.values():
            for name, g in group.grads.items():
                g = lax.psum(g, "dp")
    """)
    hits = [v for v in lint_source(src, path="paddle_tpu/x.py")
            if v.rule == "TPU014"]
    assert len(hits) == 1


def test_tpu014_silent_on_deferred_def_in_param_loop():
    src = """
    import jax.lax as lax
    def build_hooks(params):
        hooks = []
        for p in params:
            def hook(g):
                return lax.psum(g, "dp")
            hooks.append(hook)
        return hooks
    """
    assert "TPU014" not in rules_fired(src, path="paddle_tpu/x.py")


def test_tpu015_scoped_to_model_and_bench_paths():
    src = """
    from jax.sharding import PartitionSpec as P
    def spec():
        return P("dp")
    """
    assert "TPU015" in rules_fired(src, path="paddle_tpu/incubate/models/g.py")
    assert "TPU015" in rules_fired(src, path="paddle_tpu/vision/models/r.py")
    assert "TPU015" in rules_fired(src, path="bench.py")
    assert "TPU015" in rules_fired(src, path="bench_eager.py")
    # library / infra code is where PartitionSpec construction BELONGS
    assert "TPU015" not in rules_fired(
        src, path="paddle_tpu/distributed/train_step.py")
    assert "TPU015" not in rules_fired(
        src, path="paddle_tpu/distributed/auto_parallel/spec_layout.py")


def test_tpu015_alternate_spellings_fire():
    src = """
    import jax.sharding as shd
    from jax.sharding import PartitionSpec
    def specs():
        return [PartitionSpec("mp"), shd.PartitionSpec(None, "mp")]
    """
    fired = rules_fired(src, path="paddle_tpu/incubate/models/g.py")
    assert "TPU015" in fired


def test_tpu015_layout_helper_is_silent():
    src = """
    def spec(x):
        from paddle_tpu.distributed.auto_parallel.spec_layout import (
            default_layout)
        return default_layout().batch(x.ndim)
    """
    assert "TPU015" not in rules_fired(
        src, path="paddle_tpu/incubate/models/g.py")


def test_tpu016_functional_and_add_call_forms_fire():
    src = """
    import paddle_tpu.nn.functional as F
    def block(x, r, w, b):
        return F.layer_norm(x + r, 16, w, b)
    """
    assert "TPU016" in rules_fired(src, path="paddle_tpu/nn/mod.py")
    src2 = """
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    def block(x, r, w, b):
        return F.layer_norm(paddle.add(x, r), 16, w, b)
    """
    assert "TPU016" in rules_fired(src2, path="paddle_tpu/nn/mod.py")


def test_tpu016_scoped_to_nn_and_incubate_models():
    src = """
    def block(self, x, r):
        return self.ln1(x + r)
    """
    assert "TPU016" not in rules_fired(src, path="tests/test_x.py")
    assert "TPU016" not in rules_fired(src, path="paddle_tpu/ops/mod.py")
    assert "TPU016" not in rules_fired(src, path="bench.py")


def test_tpu017_all_three_spellings_fire():
    # sync method chained onto the device-side check
    src = """
    import jax.numpy as jnp
    def check(loss):
        return jnp.isnan(loss).item()
    """
    assert "TPU017" in rules_fired(src, path="paddle_tpu/hapi/m.py")
    # host cast wrapped around the device-side check
    src2 = """
    import jax.numpy as jnp
    def check(grads):
        return bool(jnp.any(~jnp.isfinite(grads)))
    """
    assert "TPU017" in rules_fired(src2, path="paddle_tpu/hapi/m.py")
    # host-side check fed by an explicit sync
    src3 = """
    import numpy as np
    def check(x):
        return np.isnan(x.numpy()).any()
    """
    assert "TPU017" in rules_fired(src3, path="paddle_tpu/hapi/m.py")


def test_tpu017_scoped_to_library_and_train_loops():
    src = """
    import math
    def train_steps(model, batches):
        for b in batches:
            if math.isnan(float(model.step(b))):
                break
    """
    # train-loop functions fire even outside the library tree...
    assert "TPU017" in rules_fired(src, path="myscript.py")
    # ...but an arbitrary user helper does not
    src2 = """
    import math
    def summarize(v):
        return math.isnan(float(v))
    """
    assert "TPU017" not in rules_fired(src2, path="myscript.py")
    assert "TPU017" in rules_fired(src2, path="paddle_tpu/hapi/m.py")


def test_tpu017_device_side_checks_are_silent():
    # in-graph nan handling never leaves the device: no sync, no report
    src = """
    import jax.numpy as jnp
    def sanitize(x):
        return jnp.where(jnp.isnan(x), 0.0, x)
    """
    assert "TPU017" not in rules_fired(src, path="paddle_tpu/ops/m.py")
    # math.isnan on a plain host scalar is not a device sync either
    src2 = """
    import math
    def valid(lr):
        return not math.isnan(lr)
    """
    assert "TPU017" not in rules_fired(src2, path="paddle_tpu/ops/m.py")


def test_tpu017_inner_sync_carries_the_report_once():
    # bool(math.isnan(float(x))): the inner spelling-3 call reports;
    # the wrapper must not double-book the same sync
    src = """
    import math
    def check(x):
        return bool(math.isnan(float(x)))
    """
    vs = [v for v in lint_source(textwrap.dedent(src),
                                 path="paddle_tpu/hapi/m.py")
          if v.rule == "TPU017"]
    assert len(vs) == 1


def test_tpu017_suppression_directive_respected():
    src = """
    import jax.numpy as jnp
    def audit(out):
        # tpu-lint: disable=TPU017
        return bool(jnp.all(jnp.isfinite(out)))
    """
    assert "TPU017" not in rules_fired(src, path="paddle_tpu/ops/m.py")


def test_tpu018_direct_call_and_jnp_results_fire():
    # appending the step call's result directly — no intermediate name
    src = """
    def run_steps(step, batches):
        out = []
        for b in batches:
            out.append(step(b))
        return out
    """
    assert "TPU018" in rules_fired(src, path="myscript.py")
    # a jnp op's result is a device array too
    src2 = """
    import jax.numpy as jnp
    def train(grads_seq):
        norms = []
        for g in grads_seq:
            norms.append(jnp.sqrt(jnp.sum(g * g)))
        return norms
    """
    assert "TPU018" in rules_fired(src2, path="myscript.py")
    # insert/extend accumulate the same way append does
    src3 = """
    def fit(step, data):
        history = []
        for x in data:
            logits = step(x)
            history.insert(0, logits)
    """
    assert "TPU018" in rules_fired(src3, path="myscript.py")


def test_tpu018_host_conversions_are_silent():
    # every host-detaching spelling the rule pushes toward
    for conv in ("float(loss)", "loss.item()", "loss.numpy()",
                 "loss.tolist()", "np.asarray(loss)",
                 "jax.device_get(loss)", "loss.numpy().tobytes()"):
        src = f"""
        import numpy as np
        import jax
        def train_loop(step, data):
            losses = []
            for x in data:
                loss = step(x)
                losses.append({conv})
            return losses
        """
        assert "TPU018" not in rules_fired(src, path="myscript.py"), conv


def test_tpu018_scoped_to_step_loops_only():
    # identical accumulation outside a train-named function: silent
    src = """
    def collect(step, data):
        losses = []
        for x in data:
            losses.append(step(x))
        return losses
    """
    assert "TPU018" not in rules_fired(src, path="myscript.py")
    # host-side bookkeeping in a train loop: silent (no device name)
    src2 = """
    import time
    def train_loop(run, data):
        step_times = []
        for x in data:
            t0 = time.perf_counter()
            run(x)
            step_times.append(time.perf_counter() - t0)
    """
    assert "TPU018" not in rules_fired(src2, path="myscript.py")


def test_tpu018_host_rebind_clears_the_name():
    # `loss = float(raw)` rebinds the device-ish NAME to a host value;
    # accumulating it afterwards is the correct cadence idiom
    src = """
    def train_loop(step, data):
        losses = []
        for x in data:
            loss = float(step(x))
            losses.append(loss)
        return losses
    """
    assert "TPU018" not in rules_fired(src, path="myscript.py")


def test_tpu018_deferred_bodies_and_nested_loops_report_once():
    # a callback def'd in the loop is deferred execution — silent
    src = """
    def train_loop(step, data, on_done):
        for x in data:
            def cb(loss):
                results.append(loss)
            on_done(cb)
    """
    assert "TPU018" not in rules_fired(src, path="myscript.py")
    # the inner loop's own event carries the report — exactly one
    src2 = """
    def train_epoch(step, loader):
        losses = []
        for epoch in range(3):
            for x in loader:
                losses.append(step(x))
    """
    vs = [v for v in lint_source(textwrap.dedent(src2),
                                 path="myscript.py")
          if v.rule == "TPU018"]
    assert len(vs) == 1


def test_tpu016_vector_norms_and_fused_entry_are_silent():
    # jnp.linalg.norm is a vector norm, not a layer norm
    src = """
    import jax.numpy as jnp
    def penalty(a, b):
        return jnp.linalg.norm(a + b)
    """
    assert "TPU016" not in rules_fired(src, path="paddle_tpu/nn/mod.py")
    src2 = """
    import paddle_tpu.nn.functional as F
    def block(x, r, w, b):
        return F.fused_add_layer_norm(x, r, 16, w, b)
    """
    assert "TPU016" not in rules_fired(src2, path="paddle_tpu/nn/mod.py")


def test_tpu019_lower_chain_fires_in_handler():
    # jit(f).lower(x).compile() mid-request: both the jit() and the
    # argumentful .lower() are request-path compiles
    src = """
    import jax
    def serve_request(engine, tokens):
        exe = jax.jit(engine.step).lower(tokens).compile()
        return exe(tokens)
    """
    vs = [v for v in lint_source(textwrap.dedent(src),
                                 path="paddle_tpu/serving/http.py")
          if v.rule == "TPU019"]
    assert len(vs) >= 1


def test_tpu019_str_lower_is_silent():
    # str.lower() takes no arguments — not an XLA lowering
    src = """
    def handle_request(payload):
        method = payload["method"].lower()
        return method
    """
    assert "TPU019" not in rules_fired(
        src, path="paddle_tpu/serving/http.py")


def test_tpu019_scoped_to_serving_paths():
    # identical jit-in-handler outside paddle_tpu/serving/: other
    # rules' business, not TPU019's
    src = """
    import jax
    def handle_generate(engine, tokens):
        return jax.jit(engine.step)(tokens)
    """
    assert "TPU019" not in rules_fired(src, path="paddle_tpu/hapi/m.py")
    assert "TPU019" not in rules_fired(src, path="tests/test_x.py")


def test_tpu019_build_phase_is_exempt():
    # the engine's AOT build/warmup surface is WHERE compiles belong
    src = """
    import jax
    class Engine:
        def _build_programs(self, buckets, structs):
            jit = jax.jit(self._step, donate_argnums=(1, 2))
            return {b: jit.lower(structs[b]).compile() for b in buckets}
        def _warmup(self):
            for exe in self._exes.values():
                exe(self._zeros)
    """
    assert "TPU019" not in rules_fired(
        src, path="paddle_tpu/serving/engine.py")


def test_tpu019_serving_tree_is_clean():
    # the shipped serving package must satisfy its own rule
    violations, errors = run_paths(
        [os.path.join(ROOT, "paddle_tpu", "serving")])
    assert errors == {}
    assert [v for v in violations if v.rule == "TPU019"] == []


def test_tpu020_all_read_forms_fire_at_module_scope():
    # os.getenv, os.environ.get and the subscript read all pin at import
    src = """
    import os
    A = os.getenv("PT_A")
    B = os.environ.get("PT_B", "0")
    C = os.environ["PT_C"]
    """
    vs = [v for v in lint_source(textwrap.dedent(src),
                                 path="paddle_tpu/x.py")
          if v.rule == "TPU020"]
    assert len(vs) == 3


def test_tpu020_class_body_is_import_time():
    src = """
    import os
    class Config:
        root = os.environ.get("PT_ROOT", "/tmp")
    """
    assert "TPU020" in rules_fired(src, path="paddle_tpu/x.py")


def test_tpu020_function_and_lambda_reads_are_lazy():
    # the rule pushes toward exactly these spellings — both defer the
    # read past import
    src = """
    import os
    def root():
        return os.environ.get("PT_ROOT", "/tmp")
    root_fn = lambda: os.getenv("PT_ROOT", "/tmp")
    """
    assert "TPU020" not in rules_fired(src, path="paddle_tpu/x.py")


def test_tpu020_exempt_outside_library_code():
    # tools/tests/CLI own their process env; scripts outside the
    # package are not library code
    src = """
    import os
    DEBUG = os.environ.get("PT_DEBUG", "")
    """
    for path in ("paddle_tpu/tools/lint/cli.py", "tests/conftest.py",
                 "paddle_tpu/cli.py", "bench.py"):
        assert "TPU020" not in rules_fired(src, path=path), path


def test_tpu020_package_has_no_import_time_env_reads():
    # satellite contract: zero baseline entries for TPU020, ever
    bl = load_baseline(default_baseline_path())
    assert not [k for k in bl if "::TPU020::" in k]
    violations, errors = run_paths(GATE_PATHS)
    assert errors == {}
    assert [v for v in violations if v.rule == "TPU020"] == []


def test_tpu021_every_blocking_name_fires():
    src = """
    def serve(stream, thread, lock, ev):
        stream.result()
        thread.join()
        lock.acquire()
        ev.wait()
    """
    for path in ("paddle_tpu/serving/x.py", "paddle_tpu/distributed/x.py",
                 "paddle_tpu/distributed/fleet/x.py"):
        vs = [v for v in lint_source(textwrap.dedent(src), path=path)
              if v.rule == "TPU021"]
        assert len(vs) == 4, path


def test_tpu021_bounded_and_nonblocking_forms_are_quiet():
    src = """
    def serve(stream, thread, lock, ev):
        stream.result(timeout=30)
        thread.join(5.0)
        lock.acquire(False)
        lock.acquire(blocking=False)
        ev.wait(0.05)
    """
    assert "TPU021" not in rules_fired(src, path="paddle_tpu/serving/x.py")


def test_tpu021_self_wrapper_deferral():
    # `self.wait()` where the same file defines a bounded wait(): the
    # wrapper body is the lint target, not every internal call site
    src = """
    class Handle:
        def wait(self):
            while not self._done.wait(60.0):
                pass
        def synchronize(self):
            self.wait()
    """
    assert "TPU021" not in rules_fired(src, path="paddle_tpu/distributed/x.py")
    # ...but an unbounded wait on anything else still fires
    src2 = """
    class Handle:
        def synchronize(self, other):
            other.wait()
    """
    assert "TPU021" in rules_fired(src2, path="paddle_tpu/distributed/x.py")


def test_tpu021_scoped_to_serving_and_distributed_paths():
    src = """
    def trainer(thread):
        thread.join()
    """
    for path in ("paddle_tpu/nn/x.py", "paddle_tpu/optimizer/x.py",
                 "tests/test_x.py"):
        assert "TPU021" not in rules_fired(src, path=path), path


def test_tpu021_request_paths_have_no_unbounded_blocking_calls():
    # satellite contract: self-clean at ZERO baseline entries — every
    # serving/distributed blocking call in-tree carries a bound
    bl = load_baseline(default_baseline_path())
    assert not [k for k in bl if "::TPU021::" in k]
    violations, errors = run_paths(GATE_PATHS)
    assert errors == {}
    assert [v for v in violations if v.rule == "TPU021"] == []


def test_tpu022_every_cast_spelling_fires():
    # attribute dtype, string dtype, dtype= kwarg, and the view form
    src = """
    import numpy as np
    import jax.numpy as jnp
    def f(x):
        a = x.astype(jnp.int8)
        b = x.astype("int8")
        c = x.astype(dtype=np.int8)
        d = x.view(jnp.int8)
        return a, b, c, d
    """
    vs = [v for v in lint_source(textwrap.dedent(src),
                                 path="paddle_tpu/serving/x.py")
          if v.rule == "TPU022"]
    assert len(vs) == 4


def test_tpu022_quant_layers_are_exempt():
    src = """
    import jax.numpy as jnp
    def quantize(x):
        return x.astype(jnp.int8)
    """
    for path in ("paddle_tpu/ops/quant_kernels.py",
                 "paddle_tpu/quantization/functional.py",
                 "tests/test_x.py", "bench.py"):
        assert "TPU022" not in rules_fired(src, path=path), path
    assert "TPU022" in rules_fired(src, path="paddle_tpu/serving/x.py")


def test_tpu022_wide_dtypes_and_uint8_images_are_silent():
    # non-quant dtypes cast freely; astype(uint8) is the image-pixel
    # idiom (vision transforms) — only view(uint8) reinterprets bytes
    src = """
    import numpy as np
    import jax.numpy as jnp
    def f(x):
        a = x.astype(jnp.bfloat16)
        b = x.astype(jnp.int32)
        c = (x * 255.0).astype(np.uint8)
        return a, b, c
    """
    assert "TPU022" not in rules_fired(src, path="paddle_tpu/vision/x.py")
    src2 = """
    import numpy as np
    def f(x):
        return x.view(np.uint8)
    """
    assert "TPU022" in rules_fired(src2, path="paddle_tpu/serving/x.py")


def test_tpu022_package_has_no_raw_quant_casts():
    # satellite contract: zero baseline entries for TPU022, ever — all
    # in-tree int8 casts live in ops/quant_kernels.py + quantization/
    bl = load_baseline(default_baseline_path())
    assert not [k for k in bl if "::TPU022::" in k]
    violations, errors = run_paths(GATE_PATHS)
    assert errors == {}
    assert [v for v in violations if v.rule == "TPU022"] == []


def test_tpu023_sanctioned_entrypoints_are_exempt():
    # process-global signal disposition belongs to the process owner —
    # the launch entrypoint, the serving frontend, the aggregator, the
    # preemption hook.  Everything else must accept a callback instead.
    src = """
    import signal
    def install(cb):
        signal.signal(signal.SIGTERM, cb)
    """
    for path in ("paddle_tpu/distributed/launch/main.py",
                 "paddle_tpu/serving/http.py",
                 "paddle_tpu/observability/aggregator.py",
                 "paddle_tpu/distributed/fleet/elastic/preemption.py",
                 "tests/test_x.py", "bench.py"):
        assert "TPU023" not in rules_fired(src, path=path), path
    for path in ("paddle_tpu/core/mod.py",
                 "paddle_tpu/distributed/supervisor.py",
                 "paddle_tpu/io/dataloader.py"):
        assert "TPU023" in rules_fired(src, path=path), path


def test_tpu023_package_has_zero_baseline_entries():
    # satellite contract: zero baseline entries for TPU023, ever —
    # library code takes shutdown callbacks, it never owns the handler
    bl = load_baseline(default_baseline_path())
    assert not [k for k in bl if "::TPU023::" in k]
    violations, errors = run_paths(GATE_PATHS)
    assert errors == {}
    assert [v for v in violations if v.rule == "TPU023"] == []


def test_tpu024_host_step_loop_flags_only_tensor_bound_nondeterminism():
    # host-side train loop: time.time() into a log line is fine;
    # time.time() into a tensor constructor / PRNG seed is a replica-
    # divergence hazard the SDC sentry would later finger as corruption
    src = """
    import time
    import jax.numpy as jnp
    def train_step(params, x, log):
        log.info("step at %s", time.time())
        noise = jnp.full(x.shape, time.time())
        return params + noise
    """
    assert "TPU024" in rules_fired(src, path="paddle_tpu/core/mod.py")
    src2 = """
    import time
    def train_step(params, x, log):
        log.info("step at %s", time.time())
        return params + x
    """
    assert "TPU024" not in rules_fired(src2, path="paddle_tpu/core/mod.py")


def test_tpu024_unseeded_prngkey_in_train_loop_fires():
    src = """
    import time
    import jax.random as jrandom
    def train(params, xs):
        for i, x in enumerate(xs):
            key = jrandom.PRNGKey(time.time_ns())
            params = params + jrandom.normal(key, x.shape)
        return params
    """
    assert "TPU024" in rules_fired(src, path="paddle_tpu/core/mod.py")
    # a constant-seeded key folded per step is the sanctioned idiom
    src2 = """
    import jax.random as jrandom
    def train(params, xs, seed):
        key = jrandom.PRNGKey(seed)
        for i, x in enumerate(xs):
            k = jrandom.fold_in(key, i)
            params = params + jrandom.normal(k, x.shape)
        return params
    """
    assert "TPU024" not in rules_fired(src2, path="paddle_tpu/core/mod.py")


def test_tpu024_module_prng_draws_in_trace_fire_seeded_apis_do_not():
    src = """
    import jax
    import numpy as np
    @jax.jit
    def step(x):
        return x + np.random.rand()
    """
    assert "TPU024" in rules_fired(src, path="paddle_tpu/core/mod.py")
    # explicit-generator construction and seeding are the discipline,
    # not the hazard — and perf_counter is host telemetry, never flagged
    src2 = """
    import time
    import numpy as np
    def train_step(rng, x):
        gen = np.random.default_rng(1234)
        np.random.seed(0)
        t0 = time.perf_counter()
        return x + gen.standard_normal(x.shape), t0
    """
    assert "TPU024" not in rules_fired(src2, path="paddle_tpu/core/mod.py")


def test_tpu024_outside_step_functions_and_library_stays_silent():
    # nondeterminism feeding tensors OUTSIDE step/train loops (dataset
    # shuffling setup, run-id minting) is not this rule's business,
    # and non-library paths (tests, bench) are exempt wholesale
    src = """
    import time
    import jax.numpy as jnp
    def make_run_banner(x):
        return jnp.full((1,), time.time())
    """
    assert "TPU024" not in rules_fired(src, path="paddle_tpu/core/mod.py")
    src2 = """
    import time
    import jax
    @jax.jit
    def step(x):
        return x + time.time()
    """
    for path in ("tests/test_x.py", "bench.py",
                 "paddle_tpu/tools/lint/rules.py"):
        assert "TPU024" not in rules_fired(src2, path=path), path


def test_tpu024_package_has_zero_baseline_entries():
    # satellite contract: zero baseline entries for TPU024, ever — the
    # captured step is deterministic by construction (the SDC consensus
    # fingerprints depend on it)
    bl = load_baseline(default_baseline_path())
    assert not [k for k in bl if "::TPU024::" in k]
    violations, errors = run_paths(GATE_PATHS)
    assert errors == {}
    assert [v for v in violations if v.rule == "TPU024"] == []


# -- suppressions ------------------------------------------------------------

SUPPRESSIBLE = """
class Net:
    def forward(self, x):
        return float(x.item())
"""


def test_suppression_same_line():
    src = SUPPRESSIBLE.replace(
        "return float(x.item())",
        "return float(x.item())  # tpu-lint: disable=TPU003")
    assert "TPU003" not in rules_fired(src)


def test_suppression_previous_line_comment():
    src = SUPPRESSIBLE.replace(
        "        return float(x.item())",
        "        # tpu-lint: disable=TPU003\n"
        "        return float(x.item())")
    assert "TPU003" not in rules_fired(src)


def test_suppression_all_and_multi_rule():
    src = SUPPRESSIBLE.replace(
        "return float(x.item())",
        "return float(x.item())  # tpu-lint: disable=all")
    assert rules_fired(src) == set()
    src2 = SUPPRESSIBLE.replace(
        "return float(x.item())",
        "return float(x.item())  # tpu-lint: disable=TPU001,TPU003")
    assert "TPU003" not in rules_fired(src2)


def test_suppression_wrong_rule_does_not_mask():
    src = SUPPRESSIBLE.replace(
        "return float(x.item())",
        "return float(x.item())  # tpu-lint: disable=TPU001")
    assert "TPU003" in rules_fired(src)


def test_suppression_on_later_line_of_multiline_statement():
    # the violation reports at the statement's FIRST line; the closing
    # paren is often the only line with room for the directive — it
    # must suppress across the statement's whole physical span
    src = """
    class Net:
        def forward(self, x):
            return float(
                x.item()
            )  # tpu-lint: disable=TPU003
    """
    assert "TPU003" not in rules_fired(src)
    # middle line of the span works too
    src2 = """
    class Net:
        def forward(self, x):
            return float(
                x.item()  # tpu-lint: disable=TPU003
            )
    """
    assert "TPU003" not in rules_fired(src2)


def test_suppression_inside_block_does_not_mask_header():
    # a directive deep inside a compound statement's BODY must not
    # bleed onto the header's own violations
    src = """
    import time
    def barrier(store, key, world):
        while store.add(key, 0) < world:
            time.sleep(0.01)
            x = 1  # tpu-lint: disable=TPU009
    """
    assert "TPU009" in rules_fired(src, path="pkg/distributed/mod.py")


# -- baseline ----------------------------------------------------------------

def _violating_file(tmp_path, name="mod.py"):
    p = tmp_path / "distributed" / name
    p.parent.mkdir(exist_ok=True)
    p.write_text(textwrap.dedent("""
        def f(store):
            try:
                store.get("k")
            except Exception:
                pass
    """))
    return str(p)


def test_baseline_round_trip(tmp_path):
    f = _violating_file(tmp_path)
    vs, errors = run_paths([f])
    assert not errors and len(vs) == 1

    bl_path = str(tmp_path / "baseline.txt")
    assert write_baseline(bl_path, vs) == 1

    # identical tree against its own baseline: nothing new, nothing stale
    vs2, _ = run_paths([f])
    new, old, stale = diff_against_baseline(vs2, load_baseline(bl_path))
    assert new == [] and len(old) == 1 and stale == []


def test_baseline_catches_new_violation(tmp_path):
    f = _violating_file(tmp_path)
    vs, _ = run_paths([f])
    bl_path = str(tmp_path / "baseline.txt")
    write_baseline(bl_path, vs)

    # add a second, distinct violation: only IT shows up as new
    with open(f, "a") as fh:
        fh.write(textwrap.dedent("""
            def g(store):
                try:
                    store.set("k", "v")
                except:
                    pass
        """))
    vs2, _ = run_paths([f])
    new, old, stale = diff_against_baseline(vs2, load_baseline(bl_path))
    assert len(new) == 1 and len(old) == 1 and stale == []
    assert "bare" in new[0].message


def test_baseline_reports_stale_entries(tmp_path):
    f = _violating_file(tmp_path)
    vs, _ = run_paths([f])
    bl_path = str(tmp_path / "baseline.txt")
    write_baseline(bl_path, vs)

    os.remove(f)
    new, old, stale = diff_against_baseline([], load_baseline(bl_path))
    assert new == [] and old == [] and len(stale) == 1


def test_baseline_keys_are_line_number_free(tmp_path):
    # editing ABOVE a grandfathered violation must not invalidate it
    f = _violating_file(tmp_path)
    vs, _ = run_paths([f])
    bl_path = str(tmp_path / "baseline.txt")
    write_baseline(bl_path, vs)

    with open(f) as fh:
        src = fh.read()
    with open(f, "w") as fh:
        fh.write("import os  # new first line\n" + src)
    vs2, _ = run_paths([f])
    new, old, stale = diff_against_baseline(vs2, load_baseline(bl_path))
    assert new == [] and len(old) == 1 and stale == []


def test_select_unknown_rule_raises():
    with pytest.raises(KeyError):
        default_rules(["TPU999"])


# -- the self-clean gate -----------------------------------------------------

def test_package_is_self_clean():
    """paddle_tpu/ + exp/ + bench drivers carry zero non-baseline
    violations — new hazards fail tier-1 from this commit forward."""
    violations, errors = run_paths(GATE_PATHS)
    assert errors == {}, errors
    new, _, stale = diff_against_baseline(
        violations, load_baseline(default_baseline_path()))
    assert new == [], "new tpu-lint violations:\n" + "\n".join(
        str(v) for v in new)
    assert stale == [], ("baseline entries no longer needed — prune "
                         "them (python -m paddle_tpu.tools.lint "
                         "--write-baseline paddle_tpu exp bench.py "
                         "bench_eager.py):\n" + "\n".join(stale))


def test_baseline_is_pinned_at_or_below_74():
    """Regression pin for the grandfathered-debt burn-down: PR 16 fixed
    six host-sync sites (masked_select/masked_scatter/where/nonzero/
    initializer-Assign/creation-assign), shrinking the baseline 80→74.
    New entries must come with a fix elsewhere, never a net grow."""
    n = sum(load_baseline(default_baseline_path()).values())
    assert n <= 74, (f"lint baseline grew to {n} entries (pin: 74) — "
                     f"fix the new violation instead of baselining it")


def test_cli_gate_exits_zero():
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.tools.lint",
         "paddle_tpu", "exp", "bench.py", "bench_eager.py"],
        cwd=ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=180)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 new violations" in out.stdout


def test_cli_list_rules():
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.tools.lint", "--list-rules"],
        cwd=ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=180)
    assert out.returncode == 0
    for rid in FIXTURES:
        assert rid in out.stdout
