"""Launcher CLI tests (ref harness: test/legacy_test/
test_parallel_dygraph_dataparallel.py TestMultipleGpus — launches a
script under the launcher and checks rank env + exit codes)."""
import os
import subprocess
import sys

import pytest

SCRIPT = """
import os, sys
rank = int(os.environ["PADDLE_TRAINER_ID"])
world = int(os.environ["PADDLE_TRAINERS_NUM"])
assert os.environ["PADDLE_CURRENT_ENDPOINT"] in \
    os.environ["PADDLE_TRAINER_ENDPOINTS"].split(",")
print(f"rank={rank} world={world}")
if len(sys.argv) > 1 and sys.argv[1] == "--fail" and rank == 1:
    sys.exit(3)
"""


def _run(tmp_path, extra_args, script_args=()):
    script = tmp_path / "worker.py"
    script.write_text(SCRIPT)
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--log_dir", str(tmp_path / "log"), *extra_args, str(script),
         *script_args],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.slow
def test_launch_two_procs(tmp_path):
    r = _run(tmp_path, ["--nproc_per_node", "2"])
    assert r.returncode == 0, r.stderr
    logs = sorted(os.listdir(tmp_path / "log"))
    assert logs == ["workerlog.0", "workerlog.1"]
    body = (tmp_path / "log" / "workerlog.1").read_text()
    assert "rank=1 world=2" in body


@pytest.mark.slow
def test_launch_propagates_failure(tmp_path):
    r = _run(tmp_path, ["--nproc_per_node", "2"], ("--fail",))
    assert r.returncode == 3
    assert "exited with code 3" in r.stderr


def test_spawn_single_proc_env():
    from paddle_tpu.distributed import spawn

    captured = {}

    def f():
        captured["rank"] = os.environ["PADDLE_TRAINER_ID"]

    spawn(f, nprocs=1)
    assert captured["rank"] == "0"
