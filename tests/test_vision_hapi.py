"""Vision models + hapi Model.fit e2e (ref model: test/book end-to-end
model tests; config[0] ResNet path in miniature)."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # model-zoo tier: run with -m slow

import paddle_tpu as pt
from paddle_tpu.vision.datasets import FakeData


class TestModels:
    def test_resnet18_forward_backward(self):
        pt.seed(0)
        net = pt.vision.models.resnet18(num_classes=10)
        x = pt.to_tensor(np.random.rand(2, 3, 32, 32).astype(np.float32))
        out = net(x)
        assert out.shape == [2, 10]
        loss = out.sum()
        loss.backward()
        assert net.conv1.weight.grad is not None

    def test_resnet50_shapes(self):
        net = pt.vision.models.resnet50(num_classes=10)
        net.eval()
        x = pt.to_tensor(np.random.rand(1, 3, 64, 64).astype(np.float32))
        assert net(x).shape == [1, 10]

    def test_lenet(self):
        net = pt.vision.models.LeNet()
        x = pt.to_tensor(np.random.rand(2, 1, 28, 28).astype(np.float32))
        assert net(x).shape == [2, 10]

    def test_mobilenet_v2(self):
        net = pt.vision.models.mobilenet_v2(num_classes=5)
        net.eval()
        x = pt.to_tensor(np.random.rand(1, 3, 32, 32).astype(np.float32))
        assert net(x).shape == [1, 5]

    def test_transforms(self):
        from paddle_tpu.vision import transforms as T
        img = (np.random.rand(40, 48, 3) * 255).astype(np.uint8)
        pipeline = T.Compose([
            T.Resize(36), T.RandomCrop(32), T.RandomHorizontalFlip(),
            T.ToTensor(), T.Normalize([0.5] * 3, [0.5] * 3)])
        out = pipeline(img)
        assert out.shape == [3, 32, 32]
        assert float(out.numpy().max()) <= 1.0 + 1e-6

    def test_vision_box_ops(self):
        b1 = pt.to_tensor([[0., 0., 2., 2.]])
        b2 = pt.to_tensor([[1., 1., 3., 3.], [0., 0., 2., 2.]])
        iou = pt.vision.ops.box_iou(b1, b2)
        np.testing.assert_allclose(iou.numpy(), [[1. / 7, 1.0]], rtol=1e-5)
        keep = pt.vision.ops.nms(b2, 0.5, scores=pt.to_tensor([0.9, 0.8]))
        assert keep.numpy().tolist() == [0, 1]


class TestHapi:
    def _model(self):
        pt.seed(42)
        net = pt.nn.Sequential(
            pt.nn.Flatten(), pt.nn.Linear(3 * 8 * 8, 32), pt.nn.ReLU(),
            pt.nn.Linear(32, 4))
        model = pt.Model(net)
        model.prepare(
            optimizer=pt.optimizer.Adam(learning_rate=0.01,
                                        parameters=net.parameters()),
            loss=pt.nn.CrossEntropyLoss(),
            metrics=pt.metric.Accuracy())
        return model

    def test_fit_improves(self, capsys):
        model = self._model()
        data = FakeData(size=64, image_shape=(3, 8, 8), num_classes=4)
        before = model.evaluate(data, batch_size=32, verbose=0)
        model.fit(data, epochs=3, batch_size=32, verbose=0)
        after = model.evaluate(data, batch_size=32, verbose=0)
        assert after["loss"] < before["loss"]
        assert after["acc"] > before["acc"]

    def test_predict(self):
        model = self._model()
        data = FakeData(size=16, image_shape=(3, 8, 8), num_classes=4)
        outs = model.predict(data, batch_size=8, stack_outputs=True)
        assert outs[0].shape == (16, 4)

    def test_save_load(self, tmp_path):
        model = self._model()
        data = FakeData(size=32, image_shape=(3, 8, 8), num_classes=4)
        model.fit(data, epochs=1, batch_size=16, verbose=0)
        path = str(tmp_path / "ckpt")
        model.save(path)
        model2 = self._model()
        model2.load(path)
        x = pt.to_tensor(np.random.rand(2, 3, 8, 8).astype(np.float32))
        np.testing.assert_allclose(model.network(x).numpy(),
                                   model2.network(x).numpy(), rtol=1e-6)

    def test_early_stopping(self):
        model = self._model()
        data = FakeData(size=32, image_shape=(3, 8, 8), num_classes=4)
        es = pt.callbacks.EarlyStopping(monitor="loss", patience=0,
                                        mode="min")
        model.fit(data, epochs=5, batch_size=16, verbose=0, callbacks=[es])
        # with patience 0 the model may stop early; just assert no crash
        assert es.best is not None

    def test_summary(self, capsys):
        net = pt.nn.Sequential(pt.nn.Linear(4, 8), pt.nn.ReLU(),
                               pt.nn.Linear(8, 2))
        info = pt.summary(net, (1, 4))
        assert info["total_params"] == 4 * 8 + 8 + 8 * 2 + 2

    def test_metric_accuracy(self):
        m = pt.metric.Accuracy()
        pred = pt.to_tensor([[0.9, 0.1], [0.2, 0.8]])
        label = pt.to_tensor([0, 0])
        corr = m.compute(pred, label)
        m.update(corr)
        assert abs(m.accumulate() - 0.5) < 1e-6


def test_flowers_voc_synthetic():
    """Flowers / VOC2012 dataset surface (ref vision/datasets/{flowers,
    voc2012}.py): offline synthetic splits feed classification and
    segmentation pipelines."""
    f = pt.vision.datasets.Flowers(synthetic=True, n_samples=8)
    img, lbl = f[3]
    assert img.shape == (3, 64, 64) and 0 <= int(lbl) < 102
    v = pt.vision.datasets.VOC2012(synthetic=True, n_samples=4)
    img, mask = v[0]
    assert mask.shape == (64, 64) and mask.dtype == np.int64
    assert 0 < mask.max() < v.NUM_CLASSES
    # train/eval splits differ
    v2 = pt.vision.datasets.VOC2012(synthetic=True, mode="val", n_samples=4)
    assert not np.array_equal(v[0][1], v2[0][1])
    with pytest.raises(FileNotFoundError):
        pt.vision.datasets.Flowers()
    with pytest.raises(FileNotFoundError):
        pt.vision.datasets.VOC2012()


def test_flowers_real_archive(tmp_path):
    """Real-archive Flowers path with REFERENCE semantics: train/test
    split arrays exchanged (train = tstid), 1-based labels of shape
    (1,), setid file order preserved, extract-once loading."""
    import io, tarfile
    from PIL import Image
    import scipy.io as sio
    tgz = str(tmp_path / "102flowers.tgz")
    with tarfile.open(tgz, "w:gz") as tf:
        for i in range(1, 5):
            arr = np.full((8, 8, 3), i * 40, np.uint8)
            buf = io.BytesIO()
            Image.fromarray(arr).save(buf, format="JPEG")
            data = buf.getvalue()
            info = tarfile.TarInfo(f"jpg/image_{i:05d}.jpg")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    lab = str(tmp_path / "imagelabels.mat")
    sio.savemat(lab, {"labels": np.array([[5, 6, 5, 6]])})
    sid = str(tmp_path / "setid.mat")
    sio.savemat(sid, {"trnid": np.array([[4]]),
                      "valid": np.array([[1]]),
                      "tstid": np.array([[3, 2]])})  # non-ascending order
    ds = pt.vision.datasets.Flowers(data_file=tgz, label_file=lab,
                                    setid_file=sid, mode="train")
    # train reads tstid (the reference's deliberate swap), file order kept
    assert len(ds) == 2
    img, label = ds[0]
    assert img.shape == (8, 8, 3)
    assert label.shape == (1,) and int(label[0]) == 5  # raw 1-based
    test = pt.vision.datasets.Flowers(data_file=tgz, label_file=lab,
                                      setid_file=sid, mode="test")
    assert len(test) == 1 and int(test[0][1][0]) == 6  # trnid id 4
    # pil backend returns a PIL image; cv2 returns BGR ndarray
    pil_ds = pt.vision.datasets.Flowers(data_file=tgz, label_file=lab,
                                        setid_file=sid, backend="pil")
    assert hasattr(pil_ds[0][0], "resize")
    cv_ds = pt.vision.datasets.Flowers(data_file=tgz, label_file=lab,
                                       setid_file=sid, backend="cv2")
    assert isinstance(cv_ds[0][0], np.ndarray)
    assert cv_ds[0][0].shape[-1] == 3
    with pytest.raises(ValueError):
        pt.vision.datasets.Flowers(synthetic=True, backend="cv")
    with pytest.raises(ValueError):
        pt.vision.datasets.Flowers(synthetic=True, mode="generate")
