"""PipelineLayer / PipelineParallel: compiled SPMD 1F1B vs eager oracle.

Ref strategy: test/collective/fleet/test_parallel_dygraph_pipeline_parallel.py
(numeric parity between pipelined and non-pipelined runs).
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.tensor import Tensor
from paddle_tpu.distributed.fleet.meta_parallel import (
    PipelineLayer, PipelineParallel, LayerDesc)


@pytest.fixture(autouse=True)
def _reset():
    yield
    dist.set_mesh(None)
    dist.destroy_process_group()


class Block(pt.nn.Layer):
    def __init__(self, h=32):
        super().__init__()
        self.fc = pt.nn.Linear(h, h)

    def forward(self, x):
        return pt.nn.functional.tanh(self.fc(x)) + x


def _loss(out, y):
    return pt.nn.functional.cross_entropy(out, y)


def _make(n_blocks=4, h=32):
    return PipelineLayer(
        layers=[LayerDesc(pt.nn.Linear, 16, h)] +
               [LayerDesc(Block, h) for _ in range(n_blocks)] +
               [LayerDesc(pt.nn.Linear, h, 10)],
        num_stages=2, loss_fn=_loss)


def test_segmentation_and_homogeneous_run():
    pt.seed(0)
    dist.init_mesh({"dp": 8})
    pl = _make()
    assert pl.num_stages == 2
    run = pl._homogeneous_run()
    assert run == (1, 5)
    prefixes, block = pl.pipeline_blocks()
    assert len(prefixes) == 4 and isinstance(block, Block)


def test_forward_oracle_runs():
    pt.seed(0)
    dist.init_mesh({"dp": 8})
    pl = _make()
    x = Tensor(np.random.RandomState(0).randn(4, 16).astype(np.float32))
    out = pl(x)
    assert out.shape == [4, 10]


@pytest.mark.slow
def test_train_batch_sequential_vs_compiled_parity():
    """pp2 compiled train_batch == no-pp eager accumulation, 3 steps."""
    rng = np.random.RandomState(1)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randint(0, 10, 8).astype(np.int32)

    # eager sequential (no pp axis in mesh)
    dist.init_mesh({"dp": 8})
    pt.seed(0)
    pl1 = _make()
    pp1 = PipelineParallel(pl1)
    pp1.accumulate_steps = 4
    opt1 = pt.optimizer.SGD(learning_rate=0.1, parameters=pl1.parameters())
    ref = [float(pp1.train_batch((Tensor(x), Tensor(y)), opt1))
           for _ in range(3)]

    # compiled SPMD pipeline (pp mesh axis)
    dist.init_mesh({"dp": 4, "pp": 2})
    pt.seed(0)
    pl2 = _make()
    pp2 = PipelineParallel(pl2)
    pp2.accumulate_steps = 4
    opt2 = pt.optimizer.SGD(learning_rate=0.1, parameters=pl2.parameters())
    got = [float(pp2.train_batch((Tensor(x), Tensor(y)), opt2))
           for _ in range(3)]
    assert getattr(pp2, "_pp_step", None) is not None, \
        "compiled pipeline path was not taken"
    np.testing.assert_allclose(ref, got, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_state_dict_sync_after_compiled_steps():
    dist.init_mesh({"dp": 4, "pp": 2})
    pt.seed(0)
    pl = _make()
    pp = PipelineParallel(pl)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=pl.parameters())
    rng = np.random.RandomState(2)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randint(0, 10, 8).astype(np.int32)
    before = {k: np.asarray(v._data).copy()
              for k, v in pp.state_dict().items()}
    pp.train_batch((Tensor(x), Tensor(y)), opt)
    after = pp.state_dict()
    changed = sum(
        not np.allclose(before[k], np.asarray(after[k]._data))
        for k in before)
    assert changed > 0, "state_dict did not reflect compiled updates"


def test_lr_scheduler_threaded_into_compiled_step():
    """LR is a runtime arg of the compiled step (not baked at trace time):
    a StepDecay schedule must change the update magnitude mid-training."""
    from paddle_tpu.distributed.train_step import build_train_step

    dist.init_mesh({"dp": 8})
    pt.seed(0)
    model = pt.nn.Linear(8, 8)
    sched = pt.optimizer.lr.StepDecay(learning_rate=1.0, step_size=1,
                                      gamma=0.0)  # lr: 1.0 then 0.0
    opt = pt.optimizer.SGD(learning_rate=sched,
                           parameters=model.parameters())

    def loss_fn(out, y):
        return ((out - y) ** 2).mean()

    step, state = build_train_step(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    x = rng.randn(8, 8).astype(np.float32)
    y = rng.randn(8, 8).astype(np.float32)

    w0 = np.asarray(state["params"]["weight"]).copy()
    _, state = step(state, x, y)          # lr = 1.0
    w1 = np.asarray(state["params"]["weight"]).copy()
    assert not np.allclose(w0, w1)
    sched.step()                           # lr -> 0.0
    _, state = step(state, x, y)
    w2 = np.asarray(state["params"]["weight"]).copy()
    np.testing.assert_allclose(w1, w2)     # zero LR => no movement


@pytest.mark.slow
def test_scaler_through_compiled_pipeline_parity():
    """AMP scaler + pp2 must take the COMPILED path (ref runs 1F1B with
    its scaler, ``hybrid_parallel_gradscaler.py``) and match the eager
    sequential schedule's losses."""
    rng = np.random.RandomState(3)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randint(0, 10, 8).astype(np.int32)

    dist.init_mesh({"dp": 8})
    pt.seed(0)
    pl1 = _make()
    pp1 = PipelineParallel(pl1)
    pp1.accumulate_steps = 4
    opt1 = pt.optimizer.SGD(learning_rate=0.1, parameters=pl1.parameters())
    sc1 = pt.amp.GradScaler(init_loss_scaling=256.0)
    ref = [float(pp1.train_batch((Tensor(x), Tensor(y)), opt1, scaler=sc1))
           for _ in range(3)]

    dist.init_mesh({"dp": 4, "pp": 2})
    pt.seed(0)
    pl2 = _make()
    pp2 = PipelineParallel(pl2)
    pp2.accumulate_steps = 4
    opt2 = pt.optimizer.SGD(learning_rate=0.1, parameters=pl2.parameters())
    sc2 = pt.amp.GradScaler(init_loss_scaling=256.0)
    got = [float(pp2.train_batch((Tensor(x), Tensor(y)), opt2, scaler=sc2))
           for _ in range(3)]
    assert getattr(pp2, "_pp_step", None) is not None, \
        "scaler forced the sequential fallback (silent degrade)"
    assert "scaler" in pp2._pp_state
    np.testing.assert_allclose(ref, got, rtol=2e-4, atol=2e-4)
    assert float(sc2._scale) == 256.0  # finite grads: scale unchanged


def test_compiled_scaler_skips_update_on_overflow():
    """A non-finite batch must leave params untouched and shrink the
    scale; the next finite batch trains normally."""
    from paddle_tpu.distributed.train_step import build_train_step

    dist.init_mesh({"dp": 8})
    pt.seed(0)
    model = pt.nn.Linear(8, 8)
    opt = pt.optimizer.SGD(learning_rate=0.1,
                           parameters=model.parameters())
    scaler = pt.amp.GradScaler(init_loss_scaling=64.0)

    def loss_fn(out, y):
        return ((out - y) ** 2).mean()

    step, state = build_train_step(model, loss_fn, opt, scaler=scaler)
    rng = np.random.RandomState(0)
    x = rng.randn(8, 8).astype(np.float32)
    y = rng.randn(8, 8).astype(np.float32)
    bad_x = x.copy()
    bad_x[0, 0] = np.inf

    w0 = np.asarray(state["params"]["weight"]).copy()
    _, state = step(state, bad_x, y)
    w1 = np.asarray(state["params"]["weight"]).copy()
    np.testing.assert_allclose(w0, w1)        # overflow: update skipped
    assert bool(state["scaler"]["found_inf"])
    assert float(state["scaler"]["scale"]) == 32.0  # 64 * decr_ratio 0.5

    _, state = step(state, x, y)
    w2 = np.asarray(state["params"]["weight"]).copy()
    assert not np.allclose(w1, w2)            # finite: trained
    assert not bool(state["scaler"]["found_inf"])
