"""Optimizers + LR schedulers (ref model: test/legacy_test/test_adam_op.py
style numeric checks + scheduler unit tests)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import to_tensor


def _quadratic_problem():
    """min ||Wx - y||^2 over W."""
    pt.seed(0)
    np.random.seed(0)
    X = np.random.randn(64, 8).astype(np.float32)
    W_true = np.random.randn(8, 4).astype(np.float32)
    Y = X @ W_true
    model = pt.nn.Linear(8, 4)
    return model, X, Y


def _train(model, opt, X, Y, steps=60):
    losses = []
    for _ in range(steps):
        loss = pt.nn.functional.mse_loss(model(to_tensor(X)), to_tensor(Y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("opt_cls,kwargs", [
    (pt.optimizer.SGD, dict(learning_rate=0.1)),
    (pt.optimizer.Adam, dict(learning_rate=0.05)),
    (pt.optimizer.AdamW, dict(learning_rate=0.05, weight_decay=0.0)),
    # the remaining families converge in the slow tier — one compile
    # per optimizer is the cost, not the math
    pytest.param(pt.optimizer.Momentum,
                 dict(learning_rate=0.05, momentum=0.9),
                 marks=pytest.mark.slow),
    pytest.param(pt.optimizer.RMSProp,
                 dict(learning_rate=0.05, momentum=0.9),
                 marks=pytest.mark.slow),
    pytest.param(pt.optimizer.Adagrad, dict(learning_rate=0.3),
                 marks=pytest.mark.slow),
    pytest.param(pt.optimizer.Adamax, dict(learning_rate=0.05),
                 marks=pytest.mark.slow),
    pytest.param(pt.optimizer.Lamb,
                 dict(learning_rate=0.05, lamb_weight_decay=0.0),
                 marks=pytest.mark.slow),
    pytest.param(pt.optimizer.Adadelta, dict(learning_rate=1.0, rho=0.5),
                 marks=pytest.mark.slow),
])
def test_optimizer_converges(opt_cls, kwargs):
    model, X, Y = _quadratic_problem()
    opt = opt_cls(parameters=model.parameters(), **kwargs)
    # adadelta self-scales its step and needs a longer horizon
    steps = 300 if opt_cls is pt.optimizer.Adadelta else 60
    losses = _train(model, opt, X, Y, steps=steps)
    assert losses[-1] < losses[0] * 0.2, \
        f"{opt_cls.__name__}: {losses[0]} -> {losses[-1]}"


def test_adam_matches_reference_formula():
    """Single-step numeric check against hand-computed Adam update."""
    p0 = np.array([1.0, -2.0], np.float32)
    g0 = np.array([0.5, 0.25], np.float32)
    p = pt.Tensor(p0.copy(), stop_gradient=False)
    from paddle_tpu.tensor import Parameter
    param = Parameter(p0.copy())
    param.grad = pt.Tensor(g0)
    opt = pt.optimizer.Adam(learning_rate=0.1, parameters=[param])
    opt.step()
    lr, b1, b2, eps = 0.1, 0.9, 0.999, 1e-8
    m = (1 - b1) * g0
    v = (1 - b2) * g0 * g0
    m_hat = m / (1 - b1)
    v_hat = v / (1 - b2)
    expect = p0 - lr * m_hat / (np.sqrt(v_hat) + eps)
    np.testing.assert_allclose(param.numpy(), expect, rtol=1e-5)


def test_weight_decay_l2():
    param = __import__("paddle_tpu").tensor.Parameter(
        np.array([1.0], np.float32))
    param.grad = pt.Tensor(np.array([0.0], np.float32))
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=[param],
                           weight_decay=0.5)
    opt.step()
    # g_eff = 0 + 0.5*1.0 -> p = 1 - 0.1*0.5
    np.testing.assert_allclose(param.numpy(), [0.95], rtol=1e-6)


def test_adamw_decoupled_decay():
    from paddle_tpu.tensor import Parameter
    param = Parameter(np.array([1.0], np.float32))
    param.grad = pt.Tensor(np.array([0.0], np.float32))
    opt = pt.optimizer.AdamW(learning_rate=0.1, parameters=[param],
                             weight_decay=0.1)
    opt.step()
    # adam update with g=0 is 0; decoupled decay: p -= lr*wd*p
    np.testing.assert_allclose(param.numpy(), [1.0 - 0.1 * 0.1 * 1.0],
                               rtol=1e-5)


def test_grad_clip_global_norm():
    from paddle_tpu.tensor import Parameter
    p1 = Parameter(np.zeros(2, np.float32))
    p2 = Parameter(np.zeros(2, np.float32))
    p1.grad = pt.Tensor(np.array([3.0, 0.0], np.float32))
    p2.grad = pt.Tensor(np.array([0.0, 4.0], np.float32))
    clip = pt.nn.ClipGradByGlobalNorm(1.0)
    opt = pt.optimizer.SGD(learning_rate=1.0, parameters=[p1, p2],
                           grad_clip=clip)
    opt.step()
    # global norm 5 -> scale 1/5
    np.testing.assert_allclose(p1.numpy(), [-0.6, 0.0], rtol=1e-5)
    np.testing.assert_allclose(p2.numpy(), [0.0, -0.8], rtol=1e-5)


def test_master_weights_bf16():
    from paddle_tpu.tensor import Parameter
    param = Parameter(np.ones(4, np.float32))
    param._data = param._data.astype("bfloat16")
    param.grad = pt.Tensor(np.full(4, 1e-3, np.float32))
    opt = pt.optimizer.SGD(learning_rate=1e-3, parameters=[param])
    for _ in range(10):
        param.grad = pt.Tensor(np.full(4, 1e-3, np.float32))
        opt.step()
    # bf16 alone would lose the 1e-6 updates; master weight accumulates
    master = opt._master_weights[param.name]
    assert abs(float(master[0]) - (1 - 10 * 1e-6)) < 1e-6


def test_optimizer_state_roundtrip(tmp_path):
    model, X, Y = _quadratic_problem()
    opt = pt.optimizer.Adam(learning_rate=0.05,
                            parameters=model.parameters())
    _train(model, opt, X, Y, steps=3)
    sd = opt.state_dict()
    opt2 = pt.optimizer.Adam(learning_rate=0.05,
                             parameters=model.parameters())
    opt2.set_state_dict(sd)
    assert opt2._global_step == opt._global_step
    k = next(iter(opt._accumulators["moment1"]))
    np.testing.assert_allclose(
        np.asarray(opt2._accumulators["moment1"][k]),
        np.asarray(opt._accumulators["moment1"][k]))


class TestLRSchedulers:
    def test_step_decay(self):
        sched = pt.optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        lrs = []
        for _ in range(5):
            lrs.append(sched())
            sched.step()
        np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025])

    def test_cosine(self):
        sched = pt.optimizer.lr.CosineAnnealingDecay(1.0, T_max=10)
        assert abs(sched() - 1.0) < 1e-6
        for _ in range(10):
            sched.step()
        assert sched() < 1e-6

    def test_warmup(self):
        sched = pt.optimizer.lr.LinearWarmup(0.1, warmup_steps=5,
                                             start_lr=0.0, end_lr=0.1)
        vals = []
        for _ in range(7):
            vals.append(sched())
            sched.step()
        assert vals[0] == 0.0
        assert abs(vals[4] - 0.08) < 1e-6
        assert vals[6] == 0.1

    def test_reduce_on_plateau(self):
        sched = pt.optimizer.lr.ReduceOnPlateau(0.1, patience=1, factor=0.5)
        for v in [1.0, 1.0, 1.0, 1.0]:
            sched.step(v)
        assert sched() < 0.1

    def test_optimizer_with_scheduler(self):
        model, X, Y = _quadratic_problem()
        sched = pt.optimizer.lr.ExponentialDecay(0.1, gamma=0.9)
        opt = pt.optimizer.SGD(learning_rate=sched,
                               parameters=model.parameters())
        assert abs(opt.get_lr() - 0.1) < 1e-9
        sched.step()
        assert abs(opt.get_lr() - 0.09) < 1e-9

    def test_noam_piecewise_poly(self):
        noam = pt.optimizer.lr.NoamDecay(d_model=512, warmup_steps=10)
        assert noam() > 0
        pw = pt.optimizer.lr.PiecewiseDecay([2, 4], [1.0, 0.5, 0.1])
        vals = []
        for _ in range(5):
            vals.append(pw())
            pw.step()
        np.testing.assert_allclose(vals, [1.0, 1.0, 0.5, 0.5, 0.1])
        poly = pt.optimizer.lr.PolynomialDecay(1.0, decay_steps=10,
                                               end_lr=0.0, power=1.0)
        for _ in range(5):
            poly.step()
        assert abs(poly() - 0.5) < 0.11


class TestLBFGS:
    """ref: python/paddle/optimizer/lbfgs.py (closure-style step)."""

    def test_quadratic_converges_fast(self):
        pt.seed(0)
        # min ||Ax - b||^2 — LBFGS should crush this in a few steps
        rs = np.random.RandomState(0)
        A = pt.to_tensor(rs.randn(12, 6).astype(np.float32))
        b = pt.to_tensor(rs.randn(12).astype(np.float32))
        x = pt.to_tensor(np.zeros(6, np.float32), stop_gradient=False)
        opt = pt.optimizer.LBFGS(parameters=[x], max_iter=20,
                                 line_search_fn="strong_wolfe")

        def closure():
            loss = ((pt.matmul(A, x) - b) ** 2).sum()
            loss.backward()
            return loss

        final = opt.step(closure)
        x_star = np.linalg.lstsq(np.asarray(A.numpy(), np.float64),
                                 np.asarray(b.numpy(), np.float64),
                                 rcond=None)[0]
        np.testing.assert_allclose(x.numpy(), x_star, atol=1e-3, rtol=1e-3)

    @pytest.mark.slow
    def test_rosenbrock_descends(self):
        xy = pt.to_tensor(np.array([-1.2, 1.0], np.float32),
                          stop_gradient=False)
        opt = pt.optimizer.LBFGS(parameters=[xy], max_iter=30,
                                 line_search_fn="strong_wolfe")

        def closure():
            x, y = xy[0], xy[1]
            loss = (1 - x) ** 2 + 100 * (y - x ** 2) ** 2
            loss.backward()
            return loss

        f0 = float(closure().item())
        opt.clear_grad()
        for _ in range(3):
            f = float(opt.step(closure).item())  # step returns the Tensor
        assert f < f0 * 1e-3, (f0, f)

    def test_plain_step_without_line_search(self):
        w = pt.to_tensor(np.array([5.0], np.float32), stop_gradient=False)
        opt = pt.optimizer.LBFGS(parameters=[w], learning_rate=0.5,
                                 max_iter=10)

        def closure():
            loss = (w ** 2).sum()
            loss.backward()
            return loss

        loss = opt.step(closure)
        assert abs(float(w.numpy()[0])) < 1.0

    def test_rejects_unknown_line_search(self):
        w = pt.to_tensor(np.ones(1, np.float32), stop_gradient=False)
        with pytest.raises(ValueError):
            pt.optimizer.LBFGS(parameters=[w], line_search_fn="armijo")


def test_lbfgs_state_dict_roundtrip():
    """set_state_dict must neither mutate the caller's dict nor leak the
    'lbfgs' sub-dict into the base class's array conversion."""
    w = pt.to_tensor(np.array([3.0, -2.0], np.float32), stop_gradient=False)
    opt = pt.optimizer.LBFGS(parameters=[w], max_iter=5)

    def closure():
        loss = (w ** 2).sum()
        loss.backward()
        return loss

    opt.step(closure)
    sd = opt.state_dict()
    w2 = pt.to_tensor(np.zeros(2, np.float32), stop_gradient=False)
    opt2 = pt.optimizer.LBFGS(parameters=[w2], max_iter=5)
    opt2.set_state_dict(sd)
    assert "lbfgs" in sd  # caller's dict untouched
    opt3 = pt.optimizer.LBFGS(parameters=[w2], max_iter=5)
    opt3.set_state_dict(sd)  # second load still sees the history
    assert len(opt3._hist_s) == len(opt._hist_s)
