"""Store-failover units: WAL round-trips, torn tails, generation
fencing, ResilientStore reconnect/fencing/deadline semantics, the
TCPStore satellite fixes (error context, large-value resize, b""
1-tuple), the store_barrier transient-retry contract, and the
store telemetry/healthz block.

The real kill-the-master drills live in
tests/drills/test_store_failover_drills.py; everything here is
in-process and fast.
"""
from __future__ import annotations

import os
import re
import struct
import threading
import time

import pytest

from paddle_tpu.core import (GENERATION_KEY, DurableTCPStoreServer,
                             StoreWAL, TCPStore, native_available,
                             replay_wal)
from paddle_tpu.core import store_server as _ss
from paddle_tpu.distributed import resilient_store as _rs
from paddle_tpu.distributed.checkpoint import store_barrier
from paddle_tpu.distributed.resilient_store import (
    ResilientStore, StoreUnavailableError, read_endpoint_file,
    write_endpoint_file)

from fault_injection import corrupt_file, truncate_file

needs_native = pytest.mark.skipif(not native_available(),
                                  reason="native TCPStore client "
                                         "unavailable")


# -- WAL units ---------------------------------------------------------------

def test_wal_set_add_delete_roundtrip(tmp_path):
    wal = str(tmp_path / "store.wal")
    w = StoreWAL(wal)
    w.record_set("a", b"hello")
    w.record_set("empty", b"")
    w.record_add("cnt", 5)
    w.record_add("cnt", -2)
    w.record_set("gone", b"x")
    w.record_delete("gone")
    w.close()
    kv = replay_wal(wal)
    assert kv["a"] == b"hello"
    assert kv["empty"] == b""
    assert struct.unpack("<q", kv["cnt"])[0] == 3
    assert "gone" not in kv


def test_wal_replay_missing_file_is_empty(tmp_path):
    assert replay_wal(str(tmp_path / "nope.wal")) == {}


def test_wal_torn_tail_ignored(tmp_path):
    """A master SIGKILLed mid-append leaves a half line; replay must
    keep every intact record and drop only the torn tail."""
    wal = str(tmp_path / "store.wal")
    w = StoreWAL(wal)
    w.record_set("a", b"1")
    w.record_set("b", b"2")
    w.close()
    # tear the final record mid-line (no trailing newline survives)
    truncate_file(wal, keep=os.path.getsize(wal) - 5)
    kv = replay_wal(wal)
    assert kv["a"] == b"1"
    assert "b" not in kv


def test_wal_binary_values_roundtrip(tmp_path):
    """Arbitrary bytes (not utf-8) survive the JSON journal — base64."""
    wal = str(tmp_path / "store.wal")
    blob = bytes(range(256)) * 3
    w = StoreWAL(wal)
    w.record_set("blob", blob)
    w.close()
    assert replay_wal(wal)["blob"] == blob


def test_wal_counter_replay_matches_live_semantics(tmp_path):
    """ADD replay must agree bit-for-bit with the live 8-byte-LE
    counter: a replayed barrier count IS the barrier state."""
    wal = str(tmp_path / "store.wal")
    w = StoreWAL(wal)
    w.record_set("cnt", b"not-a-counter")  # overwritten by first add
    w.record_add("cnt", 7)
    w.close()
    kv = replay_wal(wal)
    live = {}
    _ss._counter_add(live, "cnt", 7)
    assert kv["cnt"] == live["cnt"]


# -- durable server vs native client ----------------------------------------

@needs_native
def test_durable_server_restart_restores_state(tmp_path):
    wal = str(tmp_path / "store.wal")
    m = TCPStore(is_master=True, wal_path=wal)
    assert m.generation == 1
    m.set("k", b"v")
    assert m.add("cnt", 4) == 4
    m.delete("k2-never")
    m.close()

    m2 = TCPStore(is_master=True, wal_path=wal)
    try:
        assert m2.generation == 2
        assert m2.get("k", wait=False) == b"v"
        assert m2.add("cnt", 0) == 4  # counter restored exactly
        assert m2.get(GENERATION_KEY, wait=False) == b"2"
    finally:
        m2.close()


@needs_native
@pytest.mark.parametrize("wal", [False, True])
def test_get_large_value_resize_and_empty_tuple_semantics(tmp_path, wal):
    """Satellite coverage: values beyond the 1 MiB first-shot buffer
    take the resize-retry path, and b'' is a real value (1-tuple
    internally, never confused with 'missing') — against BOTH the
    native server and the durable Python one."""
    kw = {"wal_path": str(tmp_path / "s.wal")} if wal else {}
    m = TCPStore(is_master=True, **kw)
    try:
        big = os.urandom((1 << 20) + 4097)
        m.set("big", big)
        assert m.get("big", wait=False) == big
        m.set("empty", b"")
        assert m.get("empty", wait=False) == b""
        assert m.get("missing", wait=False) is None
    finally:
        m.close()


@needs_native
def test_store_errors_name_endpoint_key_and_op(tmp_path):
    """Satellite: a dead master's errors must say WHAT failed WHERE —
    host:port, key and op — not a bare 'TCPStore set failed'."""
    m = TCPStore(is_master=True)
    host, port = m.host, m.port
    w = TCPStore(host, port, is_master=False, timeout=5)
    m.close()  # kill the master under the connected worker
    with pytest.raises(ConnectionError) as ei:
        w.set("some/key", b"v")
    msg = str(ei.value)
    assert "set" in msg and "some/key" in msg and f"{host}:{port}" in msg
    with pytest.raises(ConnectionError) as ei:
        w.add("cnt/key", 1)
    msg = str(ei.value)
    assert "add" in msg and "cnt/key" in msg and f"{host}:{port}" in msg
    w.close()


@needs_native
def test_durable_server_blocking_wait_op(tmp_path):
    """Protocol op 3 (server-side blocking WAIT) releases when the key
    appears — the native client's `wait` path must work unchanged
    against the Python server."""
    m = TCPStore(is_master=True, wal_path=str(tmp_path / "s.wal"))
    try:
        t = threading.Thread(target=lambda: (time.sleep(0.1),
                                             m.set("late", b"v")))
        t.start()
        got = m.get("late", wait=True, timeout=5.0)
        t.join()
        assert got == b"v"
    finally:
        m.close()


# -- endpoint file -----------------------------------------------------------

def test_endpoint_file_roundtrip_and_torn_reads(tmp_path):
    p = str(tmp_path / "ep")
    assert read_endpoint_file(p) is None  # absent
    write_endpoint_file(p, "10.0.0.7", 12345)
    assert read_endpoint_file(p) == ("10.0.0.7", 12345)
    with open(p, "w") as f:
        f.write("garbage-no-colon")
    assert read_endpoint_file(p) is None
    with open(p, "w") as f:
        f.write("host:notaport")
    assert read_endpoint_file(p) is None


def test_generation_key_constants_agree():
    """resilient_store deliberately does not import core; the two
    GENERATION_KEY constants must stay identical."""
    assert _rs.GENERATION_KEY == _ss.GENERATION_KEY


# -- ResilientStore (fake factory: no sockets) ------------------------------

class _FakeStore:
    """In-memory TCPStore double with scriptable failures."""

    def __init__(self, kv=None, generation=None, fail_ops=0):
        self.kv = dict(kv or {})
        if generation is not None:
            self.kv[GENERATION_KEY] = str(generation).encode()
        self.fail_ops = fail_ops  # raise on the next N mutating ops
        self.closed = False

    def _maybe_fail(self):
        if self.fail_ops > 0:
            self.fail_ops -= 1
            raise ConnectionError("fake: master gone")

    def get(self, key, wait=True, timeout=None):
        v = self.kv.get(key)
        return v

    def set(self, key, value):
        self._maybe_fail()
        self.kv[key] = value if isinstance(value, bytes) \
            else value.encode()

    def add(self, key, delta=1):
        self._maybe_fail()
        cur = int(self.kv.get(key, b"\0" * 8) and
                  struct.unpack("<q", self.kv.get(key, b"\0" * 8))[0])
        cur += delta
        self.kv[key] = struct.pack("<q", cur)
        return cur

    def delete(self, key):
        self.kv.pop(key, None)

    def num_keys(self):
        return len(self.kv)

    def close(self):
        self.closed = True


def test_resilient_store_retries_transparently():
    """A transient ConnectionError mid-op reconnects and retries —
    the caller never sees it."""
    backend = _FakeStore(generation=1, fail_ops=1)
    calls = []

    def factory(host, port, timeout):
        calls.append((host, port))
        return backend

    rs = ResilientStore("h", 1, deadline=5.0, store_factory=factory)
    rs.set("k", b"v")  # first set fails once, retried after reconnect
    assert backend.kv["k"] == b"v"
    assert len(calls) == 2  # initial connect + one reconnect
    assert rs.generation == 1


def test_resilient_store_deadline_raises_unavailable():
    def factory(host, port, timeout):
        raise ConnectionError("nobody home")

    rs = ResilientStore("deadhost", 99, deadline=0.3,
                        store_factory=factory)
    t0 = time.monotonic()
    with pytest.raises(StoreUnavailableError) as ei:
        rs.set("k", b"v")
    assert time.monotonic() - t0 < 5.0
    e = ei.value
    assert e.endpoint == "deadhost:99"
    assert e.op == "set" and e.key == "k"
    assert e.elapsed is not None and e.elapsed >= 0.3
    # structured fields also appear in the message
    msg = str(e)
    assert "deadhost:99" in msg and "set" in msg and "'k'" in msg
    # and it still IS a ConnectionError (legacy except clauses work)
    assert isinstance(e, ConnectionError)


def test_resilient_store_fences_amnesiac_master_immediately():
    """Once generation >= 1 was observed, a reconnect seeing a lower
    (or missing) generation must fail fast — no deadline burn."""
    stores = [_FakeStore(generation=3), _FakeStore()]  # amnesiac 2nd

    def factory(host, port, timeout):
        return stores.pop(0)

    rs = ResilientStore("h", 1, deadline=30.0, store_factory=factory)
    rs.set("k", b"v")
    assert rs.generation == 3
    rs.close()  # force reconnect; next store has NO generation key
    t0 = time.monotonic()
    with pytest.raises(StoreUnavailableError) as ei:
        rs.set("k2", b"v2")
    assert time.monotonic() - t0 < 5.0  # fence, not deadline
    assert "amnesiac" in str(ei.value)


def test_resilient_store_accepts_generation_bump():
    stores = [_FakeStore(generation=1), _FakeStore(generation=2)]

    def factory(host, port, timeout):
        return stores.pop(0)

    rs = ResilientStore("h", 1, deadline=5.0, store_factory=factory)
    rs.set("a", b"1")
    rs.close()
    rs.set("b", b"2")  # respawned master, gen 2: allowed
    assert rs.generation == 2


def test_resilient_store_plain_master_never_arms_fence():
    """Masters that never advertise a generation (native volatile
    server) stay fully compatible: the fence never arms."""
    stores = [_FakeStore(), _FakeStore()]

    def factory(host, port, timeout):
        return stores.pop(0)

    rs = ResilientStore("h", 1, deadline=5.0, store_factory=factory)
    rs.set("a", b"1")
    assert rs.generation is None
    rs.close()
    rs.set("b", b"2")  # reconnect to another gen-less master: fine


def test_resilient_store_get_wait_and_empty_value():
    backend = _FakeStore(generation=1)
    rs = ResilientStore("h", 1, deadline=5.0,
                        store_factory=lambda *a: backend)
    backend.kv["empty"] = b""
    assert rs.get("empty", wait=True, timeout=1.0) == b""  # 1-tuple
    assert rs.get("missing", wait=False) is None
    with pytest.raises(TimeoutError):
        rs.get("never", wait=True, timeout=0.2)


def test_resilient_store_endpoint_file_reresolution(tmp_path):
    """Each reconnect re-reads the endpoint file — a respawn on a new
    port is transparent."""
    ep = str(tmp_path / "ep")
    write_endpoint_file(ep, "hostA", 1111)
    seen = []
    backend = _FakeStore(generation=1)

    def factory(host, port, timeout):
        seen.append((host, port))
        return backend

    rs = ResilientStore(endpoint_file=ep, deadline=5.0,
                        store_factory=factory)
    rs.set("a", b"1")
    assert seen == [("hostA", 1111)]
    rs.close()
    write_endpoint_file(ep, "hostB", 2222)  # master moved
    rs.set("b", b"2")
    assert seen[-1] == ("hostB", 2222)


# -- store_barrier transient-retry contract ---------------------------------

class _FlakyBarrierStore(_FakeStore):
    """Fails every op while `down` is set — a master mid-respawn."""

    def __init__(self):
        super().__init__()
        self.down = False

    def _gate(self):
        if self.down:
            raise ConnectionError("master restarting")

    def get(self, key, wait=True, timeout=None):
        self._gate()
        return super().get(key, wait=wait, timeout=timeout)

    def set(self, key, value):
        self._gate()
        return super().set(key, value)

    def add(self, key, delta=1):
        self._gate()
        return super().add(key, delta)


def test_store_barrier_rides_transient_outage():
    """A ConnectionError while polling is retried within the deadline
    instead of failing the commit instantly (satellite)."""
    s = _FlakyBarrierStore()
    s.set("b/rank/1", b"1")  # peer already arrived
    s.add("b", 1)

    def _restore():
        time.sleep(0.3)
        s.down = False

    t = threading.Thread(target=_restore)
    s.down = True
    t.start()
    try:
        # arrival itself must also ride the outage
        store_barrier(s, "b", world=2, rank=0, timeout=10.0)
    finally:
        t.join()


def test_store_barrier_terminal_on_store_unavailable():
    """StoreUnavailableError from a ResilientStore that exhausted ITS
    deadline is terminal — the barrier must not burn its own timeout
    re-retrying a lost cause."""

    class _Gone:
        def set(self, key, value):
            raise StoreUnavailableError("master gone for good",
                                        endpoint="h:1", op="set",
                                        key=key, elapsed=9.9)

        def add(self, key, delta=1):
            raise StoreUnavailableError("master gone for good",
                                        endpoint="h:1", op="add",
                                        key=key, elapsed=9.9)

        def get(self, key, wait=True, timeout=None):
            return None

    t0 = time.monotonic()
    with pytest.raises(StoreUnavailableError):
        store_barrier(_Gone(), "b", world=2, rank=0, timeout=30.0)
    assert time.monotonic() - t0 < 5.0  # terminal, not 30s of retries


def test_store_barrier_double_arrival_cannot_release_early():
    """With per-rank sealing, a retried arrival that double-bumps the
    shared counter must NOT release the barrier while a rank is truly
    missing (the at-least-once `add` hazard)."""
    s = _FakeStore()
    # rank 0 arrived TWICE (retry after a lost reply): counter says 2
    s.set("b/rank/0", b"1")
    s.add("b", 1)
    s.add("b", 1)
    assert s.add("b", 0) == 2  # the counter alone would (wrongly) seal
    with pytest.raises(TimeoutError) as ei:
        store_barrier(s, "b", world=2, rank=0, timeout=0.4)
    assert "missing ranks [1]" in str(ei.value)
    assert "arrived: [0]" in str(ei.value)


# -- telemetry / healthz ----------------------------------------------------

def test_healthz_store_block_positive_evidence_only():
    from paddle_tpu.observability import telemetry as tel_mod
    tel_mod.reset()
    try:
        t = tel_mod.get_telemetry()
        t.enable()
        # no store activity at all: no block, healthy
        h = t.healthz()
        assert h["store"] is None and h["ok"] is True
        # successful ops: block present, healthy, generation surfaced
        t.record_store_op(generation=2)
        h = t.healthz()
        assert h["ok"] is True
        assert h["store"]["ok"] is True
        assert h["store"]["generation"] == 2
        assert h["store"]["last_ok_age_sec"] is not None
        # a declared unavailability AFTER the last success: unhealthy
        t.record_store_unavailable(7.5, op="set", endpoint="h:1")
        h = t.healthz()
        assert h["store"]["ok"] is False and h["ok"] is False
        # recovery: a later successful op clears it
        t.record_store_op(generation=3)
        h = t.healthz()
        assert h["store"]["ok"] is True and h["ok"] is True
    finally:
        tel_mod.reset()


def test_store_metrics_reconnects_and_unavailable_histogram():
    from paddle_tpu.observability import telemetry as tel_mod
    tel_mod.reset()
    try:
        t = tel_mod.get_telemetry()
        t.enable()
        t.record_store_reconnect("set")
        t.record_store_reconnect("set")
        t.record_store_reconnect("get")
        t.record_store_unavailable(3.0, op="get", endpoint="h:1")
        text = t.registry.prometheus_text()
        # const identity labels ride along -> match by label subset
        assert re.search(
            r'pt_store_reconnects_total\{[^}]*op="set"[^}]*\} 2\b', text)
        assert re.search(
            r'pt_store_reconnects_total\{[^}]*op="get"[^}]*\} 1\b', text)
        assert "pt_store_unavailable_seconds" in text
    finally:
        tel_mod.reset()


def test_resilient_store_emits_reconnect_metric():
    """The ResilientStore wiring feeds pt_store_reconnects_total."""
    from paddle_tpu.observability import telemetry as tel_mod
    tel_mod.reset()
    try:
        t = tel_mod.get_telemetry()
        t.enable()
        backend = _FakeStore(generation=1, fail_ops=1)
        rs = ResilientStore("h", 1, deadline=5.0,
                            store_factory=lambda *a: backend)
        rs.set("k", b"v")
        text = t.registry.prometheus_text()
        assert re.search(
            r'pt_store_reconnects_total\{[^}]*op="set"[^}]*\} 1\b', text)
        assert re.search(r"pt_store_generation(\{[^}]*\})? 1\b", text)
    finally:
        tel_mod.reset()


# -- hot-standby follower edges ----------------------------------------------
# (the live promote-under-fire drill is tests/drills/test_supervisor_drills.py;
#  these pin the StoreFollower tail/promote edges in-process)

def test_follower_tails_incrementally_and_buffers_torn_tail(tmp_path):
    """Mid-replication torn tail: the master is mid-write(2) — the
    follower must buffer the half line, apply NOTHING of it, and apply
    it exactly once when the rest of the bytes land."""
    wal = str(tmp_path / "store.wal")
    w = StoreWAL(wal)
    w.record_set("a", b"1")
    f = _ss.StoreFollower(wal)
    assert f.poll() == 1
    assert f.kv["a"] == b"1"
    # append a record, then tear its tail off the file — exactly the
    # bytes a follower sees racing the master's in-flight write(2)
    w.record_set("b", b"22222222")
    with open(wal, "rb") as fh:
        full = fh.read()
    truncate_file(wal, keep=len(full) - 6)
    assert f.poll() == 0        # half a line: buffered, not applied
    assert "b" not in f.kv
    assert f.broken is None     # a torn TAIL is not corruption
    # the rest of the write lands: restore the missing 6 bytes
    with open(wal, "ab") as fh:
        fh.write(full[-6:])
    assert f.poll() == 1        # the buffered half + the rest = one record
    assert f.kv["b"] == b"22222222"
    assert f.broken is None
    w.close()


def test_follower_behind_at_promote_catches_up_first(tmp_path):
    """Follower behind at promote: records appended after the last
    poll() must still be served by the promoted master — promote()
    does one final catch-up before seeding the server."""
    wal = str(tmp_path / "store.wal")
    w = StoreWAL(wal)
    w.record_set("early", b"1")
    f = _ss.StoreFollower(wal)
    assert f.poll() == 1
    # the master keeps writing; the follower never polls again
    w.record_set("late", b"2")
    w.record_add("cnt", 9)
    w.close()
    srv = f.promote()
    try:
        assert srv._kv["early"] == b"1"
        assert srv._kv["late"] == b"2"
        assert struct.unpack("<q", srv._kv["cnt"])[0] == 9
        assert srv.generation == 1  # no prior generation record → 1
    finally:
        srv.stop()


def test_promote_during_write_drops_unacked_tail(tmp_path):
    """Promote-during-write: the master died mid-append — the torn
    bytes were never acknowledged to any client, so the promoted
    master must drop them (from memory AND from the shared WAL file)
    and serve every complete record."""
    wal = str(tmp_path / "store.wal")
    srv0 = DurableTCPStoreServer(wal_path=wal, wal_fsync=False)
    srv0.stop()
    w = StoreWAL(wal)
    w.record_set("acked", b"yes")
    w.close()
    truncate_file(wal, keep=os.path.getsize(wal) - 4)  # mid-append death
    f = _ss.StoreFollower(wal)
    f.poll()
    assert f._buf  # the torn fragment is sitting in the buffer
    srv = f.promote()
    try:
        assert "acked" not in srv._kv  # torn record: never acked, gone
        assert srv.generation == 2     # bumped past the dead master's 1
        # the promoted master's append path truncated the torn bytes:
        # a full re-replay of the shared WAL sees no damage
        kv = replay_wal(wal)
        assert kv[GENERATION_KEY] == b"2"
    finally:
        srv.stop()


def test_follower_mid_file_corruption_refuses_promotion(tmp_path):
    """A hole in the MIDDLE of the journal (bit-rot, not a torn tail)
    must brick the follower: applying records past a hole would serve
    wrong state behind an intact generation fence."""
    wal = str(tmp_path / "store.wal")
    w = StoreWAL(wal)
    w.record_set("a", b"1")
    w.record_set("b", b"2")
    w.record_set("c", b"3")
    w.close()
    corrupt_file(wal, offset=os.path.getsize(wal) // 2)
    f = _ss.StoreFollower(wal)
    f.poll()
    assert f.broken is not None
    with pytest.raises(RuntimeError, match="cannot promote"):
        f.promote()
