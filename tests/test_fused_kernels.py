"""Fused Pallas kernels (layernorm, softmax-xent) + the search-based
autotuner: interpret-mode parity vs pure-jnp references, framework
dispatch (flag on → fused, ineligible → clean XLA fallback), cost-model
pruning, cache persistence with stale-key invalidation, and
cross-process reload via PT_AUTOTUNE_CACHE.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops import autotune as at
from paddle_tpu.ops import fused_kernels as fk

FWD_TOL = dict(rtol=1e-5, atol=1e-5)
GRAD_TOL = dict(rtol=1e-4, atol=1e-4)


@pytest.fixture(autouse=True)
def _clean_tuner():
    at.cache_clear()
    enabled = at.enabled()
    yield
    at.cache_clear()
    at.set_enabled(enabled)


def _rand(shape, seed=0, dtype=np.float32):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape)
                       .astype(dtype))


# ---------------------------------------------------------------------------
# fused layernorm parity
# ---------------------------------------------------------------------------
class TestFusedLayerNorm:

    # ragged rows/features that don't divide the (block_rows, 128) tile
    @pytest.mark.parametrize("rows,d", [(8, 128), (37, 193), (130, 96),
                                        (256, 640), (5, 515)])
    def test_forward_parity(self, rows, d):
        x = _rand((rows, d))
        w = _rand((d,), 1)
        b = _rand((d,), 2)
        out = fk.fused_layer_norm(x, w, b, interpret=True)
        ref = fk.layer_norm_reference(x, w, b)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   **FWD_TOL)

    def test_forward_no_affine_and_residual(self):
        x = _rand((33, 257))
        res = _rand((33, 257), 7)
        out = fk.fused_layer_norm(x, residual=res, interpret=True)
        ref = fk.layer_norm_reference(x, residual=res)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   **FWD_TOL)

    def test_grad_parity_full(self):
        x, res = _rand((37, 193), 0), _rand((37, 193), 3)
        w, b = _rand((193,), 1), _rand((193,), 2)

        def f(fn):
            return lambda x, w, b, r: jnp.sum(
                jnp.sin(fn(x, w, b, residual=r)))

        g1 = jax.grad(f(lambda *a, **k: fk.fused_layer_norm(
            *a, **k, interpret=True)), argnums=(0, 1, 2, 3))(x, w, b, res)
        g2 = jax.grad(f(fk.layer_norm_reference),
                      argnums=(0, 1, 2, 3))(x, w, b, res)
        for got, want in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       **GRAD_TOL)

    def test_grad_parity_no_affine(self):
        x = _rand((29, 130))
        g1 = jax.grad(lambda a: jnp.sum(jnp.cos(
            fk.fused_layer_norm(a, interpret=True))))(x)
        g2 = jax.grad(lambda a: jnp.sum(jnp.cos(
            fk.layer_norm_reference(a))))(x)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   **GRAD_TOL)

    def test_bf16_in_f32_accumulate(self):
        # bf16 inputs, f32 stats: the fused output must match the f32
        # reference computed from the SAME bf16 inputs to bf16 noise
        x = _rand((64, 256)).astype(jnp.bfloat16)
        w = _rand((256,), 1).astype(jnp.bfloat16)
        out = fk.fused_layer_norm(x, w, interpret=True)
        assert out.dtype == jnp.bfloat16
        ref = fk.layer_norm_reference(x, w)
        np.testing.assert_allclose(
            np.asarray(out.astype(jnp.float32)),
            np.asarray(ref.astype(jnp.float32)), rtol=3e-2, atol=3e-2)

    def test_explicit_block_config(self):
        x = _rand((100, 100))
        for br, par in ((8, True), (64, False), (1024, True)):
            out = fk.fused_layer_norm(x, block_rows=br, parallel=par,
                                      interpret=True)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(fk.layer_norm_reference(x)),
                **FWD_TOL)


# ---------------------------------------------------------------------------
# fused softmax cross-entropy parity
# ---------------------------------------------------------------------------
class TestFusedSoftmaxXent:

    @pytest.mark.parametrize("rows,V", [(8, 128), (29, 517), (64, 1024),
                                        (7, 90)])
    def test_forward_parity(self, rows, V):
        logits = _rand((rows, V))
        lab = jnp.asarray(np.random.RandomState(1).randint(
            0, V, rows).astype(np.int32))
        out = fk.fused_softmax_xent(logits, lab, interpret=True)
        ref = fk.softmax_xent_reference(logits, lab)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   **FWD_TOL)

    def test_ignore_index(self):
        logits = _rand((31, 200))
        lab = np.random.RandomState(1).randint(0, 200, 31).astype(np.int32)
        lab[[0, 7, 30]] = -100
        lab = jnp.asarray(lab)
        out = fk.fused_softmax_xent(logits, lab, interpret=True)
        ref = fk.softmax_xent_reference(logits, lab)
        assert float(out[0]) == 0.0 and float(out[7]) == 0.0
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   **FWD_TOL)

    def test_label_smoothing_fwd_and_grad(self):
        logits = _rand((29, 517))
        lab = np.random.RandomState(1).randint(0, 517, 29).astype(np.int32)
        lab[3] = -100
        lab = jnp.asarray(lab)
        out = fk.fused_softmax_xent(logits, lab, label_smoothing=0.1,
                                    interpret=True)
        ref = fk.softmax_xent_reference(logits, lab, label_smoothing=0.1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   **FWD_TOL)
        g1 = jax.grad(lambda l: jnp.sum(fk.fused_softmax_xent(
            l, lab, label_smoothing=0.1, interpret=True)))(logits)
        g2 = jax.grad(lambda l: jnp.sum(fk.softmax_xent_reference(
            l, lab, label_smoothing=0.1)))(logits)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   **GRAD_TOL)

    def test_grad_is_softmax_minus_onehot(self):
        # weighted per-row cotangents exercise the bwd kernel's gloss
        # broadcast, not just sum()
        logits = _rand((16, 384))
        lab = jnp.asarray(np.random.RandomState(2).randint(
            0, 384, 16).astype(np.int32))
        wrow = jnp.arange(16, dtype=jnp.float32)
        g1 = jax.grad(lambda l: jnp.sum(fk.fused_softmax_xent(
            l, lab, interpret=True) * wrow))(logits)
        g2 = jax.grad(lambda l: jnp.sum(fk.softmax_xent_reference(
            l, lab) * wrow))(logits)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   **GRAD_TOL)

    def test_bf16_logits_f32_loss(self):
        logits = _rand((24, 300)).astype(jnp.bfloat16)
        lab = jnp.asarray(np.random.RandomState(3).randint(
            0, 300, 24).astype(np.int32))
        out = fk.fused_softmax_xent(logits, lab, interpret=True)
        assert out.dtype == jnp.float32
        ref = fk.softmax_xent_reference(logits, lab)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-2, atol=1e-2)

    def test_multi_vocab_tiles(self):
        # force the online logsumexp across several vocab tiles
        logits = _rand((9, 1500))
        lab = jnp.asarray(np.random.RandomState(4).randint(
            0, 1500, 9).astype(np.int32))
        out = fk.fused_softmax_xent(logits, lab, block_v=256,
                                    block_rows=8, interpret=True)
        ref = fk.softmax_xent_reference(logits, lab)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   **FWD_TOL)


# ---------------------------------------------------------------------------
# framework dispatch (flag + canary gate, XLA fallback)
# ---------------------------------------------------------------------------
def _force_cpu_dispatch(monkeypatch):
    """Force the TPU-only gate open on CPU: the canary verdicts are
    pinned True and _on_tpu patched, so the fused path runs in interpret
    mode (the tests' stand-in for real hardware)."""
    from paddle_tpu.nn.functional import common
    monkeypatch.setitem(common._CANARY_CACHE, "fused_layer_norm", True)
    monkeypatch.setitem(common._CANARY_CACHE, "fused_softmax_xent", True)
    monkeypatch.setattr(common, "_on_tpu", lambda: True)


@pytest.fixture
def fresh_metrics():
    from paddle_tpu.observability.metrics import get_registry, \
        reset_registry
    from paddle_tpu.observability.telemetry import get_telemetry
    tel = get_telemetry()
    prev = tel.enabled
    tel.enabled = True  # counters gate on this; no watcher/server needed
    reset_registry()
    yield get_registry()
    reset_registry()
    tel.enabled = prev


class TestDispatch:

    def test_layer_norm_picks_up_fused(self, monkeypatch, fresh_metrics):
        import paddle_tpu as pt
        import paddle_tpu.nn.functional as F
        import paddle_tpu.framework.flags as flags
        _force_cpu_dispatch(monkeypatch)
        x_np = np.random.RandomState(0).randn(4, 16, 96).astype(np.float32)
        w_np = np.random.RandomState(1).randn(96).astype(np.float32)
        x = pt.to_tensor(x_np, stop_gradient=False)
        w = pt.to_tensor(w_np, stop_gradient=False)
        fused = F.layer_norm(x, 96, weight=w)
        fused.sum().backward()
        assert np.isfinite(x.grad.numpy()).all()
        c = fresh_metrics.counter("pt_pallas_calls_total",
                                  labelnames=("kernel", "path"))
        assert c.value(kernel="fused_layer_norm", path="pallas") >= 1

        flags.set_flags({"use_pallas_kernels": False})
        try:
            ref = F.layer_norm(pt.to_tensor(x_np), 96,
                               weight=pt.to_tensor(w_np))
        finally:
            flags.set_flags({"use_pallas_kernels": True})
        np.testing.assert_allclose(fused.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-5)
        assert c.value(kernel="fused_layer_norm", path="fallback") >= 1

    def test_cross_entropy_picks_up_fused(self, monkeypatch,
                                          fresh_metrics):
        import paddle_tpu as pt
        import paddle_tpu.nn.functional as F
        import paddle_tpu.framework.flags as flags
        _force_cpu_dispatch(monkeypatch)
        rng = np.random.RandomState(0)
        logits_np = rng.randn(8, 12, 257).astype(np.float32)
        lab_np = rng.randint(0, 257, size=(8, 12)).astype(np.int64)
        lab_np[0, :3] = -100
        logits = pt.to_tensor(logits_np, stop_gradient=False)
        fused = F.cross_entropy(logits, pt.to_tensor(lab_np),
                                ignore_index=-100, label_smoothing=0.1)
        fused.backward()
        g_fused = logits.grad.numpy()
        c = fresh_metrics.counter("pt_pallas_calls_total",
                                  labelnames=("kernel", "path"))
        assert c.value(kernel="fused_softmax_xent", path="pallas") >= 1

        flags.set_flags({"use_pallas_kernels": False})
        try:
            logits2 = pt.to_tensor(logits_np, stop_gradient=False)
            ref = F.cross_entropy(logits2, pt.to_tensor(lab_np),
                                  ignore_index=-100, label_smoothing=0.1)
            ref.backward()
        finally:
            flags.set_flags({"use_pallas_kernels": True})
        np.testing.assert_allclose(float(fused.numpy()),
                                   float(ref.numpy()), rtol=1e-5)
        np.testing.assert_allclose(g_fused, logits2.grad.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_softmax_with_cross_entropy_dispatches(self, monkeypatch,
                                                   fresh_metrics):
        import paddle_tpu as pt
        import paddle_tpu.nn.functional as F
        _force_cpu_dispatch(monkeypatch)
        rng = np.random.RandomState(0)
        logits = pt.to_tensor(rng.randn(4, 6, 130).astype(np.float32))
        lab = pt.to_tensor(rng.randint(0, 130, size=(4, 6, 1))
                           .astype(np.int64))
        out = F.softmax_with_cross_entropy(logits, lab)
        assert tuple(out.shape) == (4, 6, 1)
        c = fresh_metrics.counter("pt_pallas_calls_total",
                                  labelnames=("kernel", "path"))
        assert c.value(kernel="fused_softmax_xent", path="pallas") >= 1

    def test_ineligible_shapes_fall_back(self, monkeypatch,
                                         fresh_metrics):
        import paddle_tpu as pt
        import paddle_tpu.nn.functional as F
        _force_cpu_dispatch(monkeypatch)
        rng = np.random.RandomState(0)
        c = fresh_metrics.counter("pt_pallas_calls_total",
                                  labelnames=("kernel", "path"))
        # soft labels → XLA
        soft = rng.rand(8, 100).astype(np.float32)
        soft /= soft.sum(-1, keepdims=True)
        out = F.cross_entropy(pt.to_tensor(rng.randn(8, 100)
                                           .astype(np.float32)),
                              pt.to_tensor(soft), soft_label=True)
        assert np.isfinite(float(out.numpy()))
        # class axis not trailing → XLA
        out = F.cross_entropy(
            pt.to_tensor(rng.randn(8, 100, 4).astype(np.float32)),
            pt.to_tensor(rng.randint(0, 100, size=(8, 4))
                         .astype(np.int64)), axis=1)
        assert np.isfinite(float(out.numpy()))
        # per-class weights → XLA
        out = F.cross_entropy(
            pt.to_tensor(rng.randn(8, 100).astype(np.float32)),
            pt.to_tensor(rng.randint(0, 100, 8).astype(np.int64)),
            weight=pt.to_tensor(np.ones(100, np.float32)))
        assert np.isfinite(float(out.numpy()))
        assert c.value(kernel="fused_softmax_xent", path="fallback") >= 3
        assert c.value(kernel="fused_softmax_xent", path="pallas") == 0


# ---------------------------------------------------------------------------
# autotuner: search, pruning, persistence, cross-process reload
# ---------------------------------------------------------------------------
class TestAutotuneSearch:

    def test_layer_norm_search_times_three_plus_candidates(self):
        x = _rand((2048, 256))
        best, timings = fk.tune_layer_norm(x, interpret=True)
        assert best in timings and len(timings) >= 3
        assert at.summary()["fused_layer_norm"]["timed"] >= 3
        # the winner now drives default-config calls
        assert at.enabled()
        hit = at.cache_get("fused_layer_norm",
                           (2048, 256, "float32", True))
        assert hit == best

    def test_flash_search_times_three_plus_candidates(self):
        q = _rand((1, 1, 512, 16))
        from paddle_tpu.ops.pallas_ops import tune_mha
        best, timings = tune_mha(q, q, q, causal=True, interpret=True)
        assert best in timings and len(timings) >= 3
        assert at.summary()["flash_mha"]["timed"] >= 3

    def test_softmax_xent_search(self):
        logits = _rand((512, 1024))
        lab = jnp.zeros((512,), jnp.int32)
        best, timings = fk.tune_softmax_xent(logits, lab, interpret=True)
        assert best in timings and len(timings) >= 3
        assert at.cache_get(
            "fused_softmax_xent",
            (512, 1024, "float32", False, True)) == best

    def test_cache_hit_skips_search_and_counts(self, fresh_metrics):
        x = _rand((1024, 128))
        _, t1 = fk.tune_layer_norm(x, interpret=True)
        assert len(t1) >= 1
        best2, t2 = fk.tune_layer_norm(x, interpret=True)
        assert t2 == {}  # nothing re-timed: answered from cache
        hits = fresh_metrics.counter("pt_autotune_cache_hits_total",
                                     labelnames=("kernel",))
        assert hits.value(kernel="fused_layer_norm") >= 1

    def test_vmem_overflowing_candidate_never_timed(self):
        timed = []

        def run(cfg):
            timed.append(cfg)

        def cost(cfg):
            return {"flops": 1.0, "bytes": 1.0,
                    "vmem_bytes": 1e12 if cfg == (512, 512) else 1024,
                    "mxu_underfill": cfg == (4, 4)}

        best, timings = at.search(
            "probe_kernel", ("k",), run,
            [(128, 128), (512, 512), (4, 4), (256, 256)], cost=cost)
        assert (512, 512) not in timed      # vmem overflow pruned
        assert (4, 4) not in timed          # MXU underfill pruned
        assert set(timed) == {(128, 128), (256, 256)}
        assert best in {(128, 128), (256, 256)}

    def test_all_pruned_raises(self):
        with pytest.raises(RuntimeError, match="pruned every candidate"):
            at.search("probe_kernel", ("k2",), lambda cfg: None,
                      [(1, 1)], cost=lambda cfg: None)

    def test_roofline_ordering(self):
        # compute-bound vs bandwidth-bound: the max() of the two sides
        assert at.roofline_seconds(at.PEAK_FLOPS, 0.0) == pytest.approx(1.0)
        assert at.roofline_seconds(0.0, at.HBM_BW) == pytest.approx(1.0)

    def test_analytic_seed_from_cost_model(self):
        seed = at.analytic_seed(
            lambda a: jnp.sum(a * a), jnp.ones((128, 128), jnp.float32))
        # CPU backends may not expose cost analysis — None is a valid
        # answer; when present, both axes must be positive
        if seed is not None:
            assert seed["flops"] > 0 or seed["bytes"] > 0


class TestAutotunePersistence:

    def test_round_trip(self, tmp_path):
        at.cache_put("fused_layer_norm", (64, 256, "float32", True),
                     (256, 1))
        p = str(tmp_path / "tune.json")
        at.save_cache(p)
        at.cache_clear()
        assert at.cache_get("fused_layer_norm",
                            (64, 256, "float32", True)) is None
        at.load_cache(p)
        assert at.cache_get("fused_layer_norm",
                            (64, 256, "float32", True)) == (256, 1)

    def test_stale_jax_version_invalidated_on_load(self, tmp_path):
        at.cache_put("fused_layer_norm", (64, 256, "float32", True),
                     (256, 1))
        p = str(tmp_path / "tune.json")
        at.save_cache(p)
        with open(p) as f:
            raw = json.load(f)
        stale = {}
        for k, v in raw.items():
            kernel, schema, kind, _ver, key = json.loads(k)
            stale[json.dumps([kernel, schema, kind, "0.0.1", key])] = v
        with open(p, "w") as f:
            json.dump(stale, f)
        at.cache_clear()
        at.load_cache(p)  # must not crash, must drop the stale entry
        assert at.cache_get("fused_layer_norm",
                            (64, 256, "float32", True)) is None

    def test_stale_device_kind_and_schema_invalidated(self, tmp_path):
        at.cache_put("flash_mha", (64, 64, 16, "float32", True, True),
                     (64, 64))
        p = str(tmp_path / "tune.json")
        at.save_cache(p)
        with open(p) as f:
            raw = json.load(f)
        mutated = {}
        for k, v in raw.items():
            kernel, schema, _kind, ver, key = json.loads(k)
            mutated[json.dumps([kernel, schema, "TPU v9", ver, key])] = v
            mutated[json.dumps([kernel, schema + 1, "cpu", ver, key])] = v
        mutated["not json structured"] = [1, 2]
        with open(p, "w") as f:
            json.dump(mutated, f)
        at.cache_clear()
        at.load_cache(p)
        assert at.cache_get("flash_mha",
                            (64, 64, 16, "float32", True, True)) is None

    def test_second_process_reloads_without_searching(self, tmp_path):
        """The acceptance drill: process A searches and persists via
        PT_AUTOTUNE_CACHE; process B with the same env var answers the
        same tune request from cache — zero candidates timed, the hit
        counter incremented."""
        cache = str(tmp_path / "shared_tune.json")
        child = (
            "import os, json, jax.numpy as jnp\n"
            "from paddle_tpu.ops import autotune as at\n"
            "from paddle_tpu.ops import fused_kernels as fk\n"
            "from paddle_tpu.observability.metrics import get_registry\n"
            "x = jnp.zeros((1024, 128), jnp.float32)\n"
            "best, timings = fk.tune_layer_norm(x, interpret=True)\n"
            "reg = get_registry()\n"
            "hits = reg.counter('pt_autotune_cache_hits_total',"
            " labelnames=('kernel',))\n"
            "misses = reg.counter('pt_autotune_cache_misses_total',"
            " labelnames=('kernel',))\n"
            "print(json.dumps({'best': list(best),"
            " 'timed': len(timings),"
            " 'hits': hits.value(kernel='fused_layer_norm'),"
            " 'misses': misses.value(kernel='fused_layer_norm')}))\n"
        )
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PT_AUTOTUNE_CACHE": cache, "PT_TELEMETRY": "1"}

        def run_child():
            out = subprocess.run([sys.executable, "-c", child], env=env,
                                 capture_output=True, text=True,
                                 timeout=240)
            assert out.returncode == 0, out.stderr[-2000:]
            return json.loads(out.stdout.strip().splitlines()[-1])

        a = run_child()
        assert a["timed"] >= 1 and a["misses"] == 1 and a["hits"] == 0
        assert os.path.exists(cache)
        b = run_child()
        assert b["timed"] == 0      # reloaded, nothing re-searched
        assert b["hits"] == 1 and b["misses"] == 0
        assert b["best"] == a["best"]
