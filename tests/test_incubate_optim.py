"""Incubate optimizers + ASP structured sparsity (ref:
``python/paddle/incubate/optimizer/lookahead.py``, ``modelaverage.py``,
``distributed_fused_lamb.py``, ``python/paddle/incubate/asp/``)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.incubate import LookAhead, ModelAverage, DistributedFusedLamb
from paddle_tpu.incubate import asp


@pytest.fixture(autouse=True)
def _clean_asp():
    yield
    asp.reset_excluded_layers()
    asp._masks.clear()


def _problem(seed=0):
    pt.seed(seed)
    net = pt.nn.Linear(8, 8)
    rng = np.random.RandomState(0)
    x = rng.randn(32, 8).astype(np.float32)
    y = rng.randn(32, 8).astype(np.float32)

    def step(opt):
        loss = ((net(pt.to_tensor(x)) - pt.to_tensor(y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return float(loss)

    return net, step


class TestLookAhead:
    def test_slow_weights_sync_every_k(self):
        net, step = _problem()
        inner = pt.optimizer.SGD(learning_rate=0.1,
                                 parameters=net.parameters())
        opt = LookAhead(inner, alpha=0.5, k=3)
        losses = [step(opt) for _ in range(12)]
        assert losses[-1] < losses[0]
        # slow weights exist for every param after a sync point
        assert set(opt._slow) == {p.name for p in net.parameters()}

    def test_state_dict_roundtrip(self):
        net, step = _problem()
        inner = pt.optimizer.SGD(learning_rate=0.1,
                                 parameters=net.parameters())
        opt = LookAhead(inner, alpha=0.5, k=2)
        for _ in range(4):
            step(opt)
        sd = opt.state_dict()
        assert sd["lookahead_steps"] == 4

        net2, _ = _problem(seed=1)
        opt2 = LookAhead(pt.optimizer.SGD(learning_rate=0.1,
                                          parameters=net2.parameters()),
                         alpha=0.5, k=2)
        opt2.set_state_dict(sd)
        assert opt2._steps == 4 and opt2._slow


class TestModelAverage:
    def test_apply_restore(self):
        net, step = _problem()
        inner = pt.optimizer.SGD(learning_rate=0.1,
                                 parameters=net.parameters())
        avg = ModelAverage(parameters=net.parameters())
        for _ in range(5):
            step(inner)
            avg.step()
        current = np.asarray(net.weight._data).copy()
        avg.apply()
        averaged = np.asarray(net.weight._data)
        assert not np.allclose(current, averaged)
        avg.restore()
        np.testing.assert_array_equal(np.asarray(net.weight._data), current)


class TestDistributedFusedLamb:
    def test_trains_and_defaults_to_zero2(self):
        net, step = _problem()
        opt = DistributedFusedLamb(learning_rate=0.01,
                                   parameters=net.parameters(),
                                   clip_after_allreduce=True)
        assert opt._group_sharded_level == "os_g"
        losses = [step(opt) for _ in range(10)]
        assert losses[-1] < losses[0]


class TestASP:
    def test_mask_is_2_of_4(self):
        w = np.random.RandomState(0).randn(8, 8).astype(np.float32)
        mask = asp.create_mask(w)
        assert mask.shape == w.shape
        assert asp.check_sparsity(w * mask)
        assert abs(asp.calculate_density(w * mask) - 0.5) < 1e-6
        # kept entries are the 2 largest |w| of each group of 4
        g = (np.abs(w).reshape(8, 2, 4)).argsort(-1)[..., 2:]
        kept = np.zeros((8, 2, 4))
        np.put_along_axis(kept, g, 1.0, -1)
        np.testing.assert_array_equal(mask.reshape(8, 2, 4), kept)

    def test_prune_model_and_sparsity_guarantee(self):
        net, step = _problem()
        masks = asp.prune_model(net)
        assert masks and asp.check_sparsity(net.weight)
        opt = asp.decorate(pt.optimizer.AdamW(
            learning_rate=0.01, parameters=net.parameters()))
        losses = [step(opt) for _ in range(5)]
        assert losses[-1] < losses[0]
        # sparsity survived five dense-gradient updates
        assert asp.check_sparsity(net.weight)
        assert abs(asp.calculate_density(net.weight) - 0.5) < 1e-6

    def test_excluded_layers(self):
        net, _ = _problem()
        asp.set_excluded_layers([""])  # the root Linear itself
        masks = asp.prune_model(net)
        assert not masks


def test_lookahead_first_sync_pulls_back():
    """Slow weights snapshot the INITIAL params: the first sync moves the
    fast weights alpha of the way back toward the start."""
    net, step = _problem()
    w0 = np.asarray(net.weight._data).copy()
    inner = pt.optimizer.SGD(learning_rate=0.1,
                             parameters=net.parameters())
    opt = LookAhead(inner, alpha=0.5, k=2)
    step(opt)                       # step 1: fast only
    w_fast = np.asarray(net.weight._data).copy()
    step(opt)                       # step 2: sync point
    w_sync = np.asarray(net.weight._data)
    # closer to w0 than a pure fast trajectory would be
    assert np.linalg.norm(w_sync - w0) < np.linalg.norm(w_fast - w0) + 1e-9


def test_model_average_windowing():
    """Only the most recent <= 2*max_average_window steps contribute."""
    p = pt.to_tensor(np.zeros((2,), np.float32))
    p.name = "p"
    avg = ModelAverage(average_window_rate=1.0, parameters=[p],
                       min_average_window=3, max_average_window=3)
    # 9 steps with values 1..9: window keeps blocks {4,5,6} + {7,8,9}
    for v in range(1, 10):
        p._data = pt.to_tensor(np.full((2,), float(v), np.float32))._data
        avg.step()
    avg.apply()
    got = float(np.asarray(p._data)[0])
    assert abs(got - np.mean([4, 5, 6, 7, 8, 9])) < 1e-6, got
    avg.restore()
    assert float(np.asarray(p._data)[0]) == 9.0


def test_deform_conv2d_layer_registers_params():
    import paddle_tpu.vision.ops as V
    pt.seed(0)
    layer = V.DeformConv2D(2, 3, 3)
    names = {n for n, _ in layer.named_parameters()}
    assert names == {"weight", "bias"}
    assert "weight" in layer.state_dict()
    # framework RNG drives init: two layers differ
    layer2 = V.DeformConv2D(2, 3, 3)
    assert not np.allclose(np.asarray(layer.weight._data),
                           np.asarray(layer2.weight._data))


class TestFusedIncubateOps:
    """fused_matmul_bias / fused_ec_moe / fused_gate_attention (ref:
    ``incubate/nn/functional/``) vs plain numpy/einsum oracles."""

    def test_fused_matmul_bias(self):
        rs = np.random.RandomState(0)
        x = rs.randn(3, 4).astype(np.float32)
        w = rs.randn(5, 4).astype(np.float32)
        b = rs.randn(5).astype(np.float32)
        from paddle_tpu.incubate.nn.functional import fused_matmul_bias
        out = fused_matmul_bias(pt.to_tensor(x), pt.to_tensor(w),
                                pt.to_tensor(b), transpose_y=True)
        np.testing.assert_allclose(out.numpy(), x @ w.T + b, rtol=1e-5,
                                   atol=1e-5)

    def test_fused_ec_moe_matches_loop(self):
        from paddle_tpu.incubate.nn.functional import fused_ec_moe
        rs = np.random.RandomState(1)
        B, S, D, F_, E = 2, 3, 4, 8, 3
        x = rs.randn(B, S, D).astype(np.float32)
        gate = rs.randn(B, S, E).astype(np.float32)
        w0 = rs.randn(E, D, F_).astype(np.float32)
        b0 = rs.randn(E, 1, F_).astype(np.float32)
        w1 = rs.randn(E, F_, D).astype(np.float32)
        b1 = rs.randn(E, 1, D).astype(np.float32)
        out = fused_ec_moe(pt.to_tensor(x), pt.to_tensor(gate),
                           pt.to_tensor(w0), pt.to_tensor(b0),
                           pt.to_tensor(w1), pt.to_tensor(b1), "relu")
        # oracle: explicit loop over experts
        p = np.exp(gate - gate.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        want = np.zeros((B, S, D), np.float64)
        for e in range(E):
            h = np.maximum(x @ w0[e] + b0[e][0], 0)
            y = h @ w1[e] + b1[e][0]
            want += p[..., e:e + 1] * y
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-4, atol=1e-4)
        with pytest.raises(ValueError):
            fused_ec_moe(pt.to_tensor(x), pt.to_tensor(gate),
                         pt.to_tensor(w0), pt.to_tensor(b0),
                         pt.to_tensor(w1), pt.to_tensor(b1), "swish")

    def test_fused_gate_attention_merge_qkv(self):
        from paddle_tpu.incubate.nn.functional import fused_gate_attention
        rs = np.random.RandomState(2)
        B, M, R, D, H, Dh = 2, 3, 4, 8, 2, 4
        q = rs.randn(B, M, R, D).astype(np.float32)
        qkv_w = rs.randn(3, H, Dh, D).astype(np.float32)
        gw = rs.randn(D, H, Dh).astype(np.float32)
        gb = rs.randn(H, Dh).astype(np.float32)
        ow = rs.randn(H, Dh, D).astype(np.float32)
        ob = rs.randn(D).astype(np.float32)
        out = fused_gate_attention(
            pt.to_tensor(q), qkv_weight=pt.to_tensor(qkv_w),
            gate_linear_weight=pt.to_tensor(gw),
            gate_linear_bias=pt.to_tensor(gb),
            out_linear_weight=pt.to_tensor(ow),
            out_linear_bias=pt.to_tensor(ob))
        # oracle: the reference pseudo-code verbatim in numpy/einsum
        qq = np.einsum("nbqa,hca->nbqhc", q, qkv_w[0])
        kk = np.einsum("nbka,hca->nbkhc", q, qkv_w[1])
        vv = np.einsum("nbka,hca->nbkhc", q, qkv_w[2])
        c = Dh ** (-0.5)
        logits = np.einsum("nbqhc,nbkhc->nbhqk", qq * c, kk)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        w = e / e.sum(-1, keepdims=True)
        avg = np.einsum("nbhqk,nbkhc->nbqhc", w, vv)
        gate = 1 / (1 + np.exp(-(np.einsum("nbqc,chv->nbqhv", q, gw) + gb)))
        want = np.einsum("nbqhc,hco->nbqo", avg * gate, ow) + ob
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-4, atol=1e-4)
        assert tuple(out.shape) == (B, M, R, D)

    @pytest.mark.slow
    def test_fused_gate_attention_separate_kv_grads(self):
        from paddle_tpu.incubate.nn.functional import fused_gate_attention
        rs = np.random.RandomState(3)
        B, M, R, D, H, Dh = 1, 2, 3, 4, 2, 2
        q = pt.to_tensor(rs.randn(B, M, R, D).astype(np.float32),
                         stop_gradient=False)
        k = pt.to_tensor(rs.randn(B, M, R, D).astype(np.float32))
        mk = lambda *s: pt.to_tensor(rs.randn(*s).astype(np.float32))
        out = fused_gate_attention(
            q, key=k, query_weight=mk(D, H, Dh), key_weight=mk(D, H, Dh),
            value_weight=mk(D, H, Dh), out_linear_weight=mk(H, Dh, D),
            has_gating=False, merge_qkv=False)
        out.sum().backward()
        assert q.grad is not None and np.isfinite(q.grad.numpy()).all()
