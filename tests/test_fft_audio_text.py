"""fft / signal / audio / text / BERT tests.

Oracles: numpy.fft for transforms, librosa-documented closed forms for mel
(slaney), scipy-documented windows, brute-force search for viterbi.
"""
from __future__ import annotations

import itertools
import math

import numpy as np
import pytest

import paddle_tpu as pt


class TestFFT:
    def test_fft_family_matches_numpy(self):
        x = np.random.RandomState(0).randn(2, 32).astype(np.float32)
        t = pt.to_tensor(x)
        np.testing.assert_allclose(pt.fft.fft(t).numpy(), np.fft.fft(x),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(pt.fft.rfft(t).numpy(), np.fft.rfft(x),
                                   rtol=1e-4, atol=1e-4)
        X = pt.fft.fft(t)
        np.testing.assert_allclose(pt.fft.ifft(X).numpy().real, x,
                                   atol=1e-5)
        np.testing.assert_allclose(
            pt.fft.fftshift(t).numpy(), np.fft.fftshift(x), rtol=1e-6)
        np.testing.assert_allclose(pt.fft.fftfreq(8, 0.5).numpy(),
                                   np.fft.fftfreq(8, 0.5), rtol=1e-6)

    def test_fft2_fftn(self):
        x = np.random.RandomState(1).randn(4, 8, 8).astype(np.float32)
        t = pt.to_tensor(x)
        np.testing.assert_allclose(pt.fft.fft2(t).numpy(), np.fft.fft2(x),
                                   rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(
            pt.fft.fftn(t, axes=(1, 2)).numpy(),
            np.fft.fftn(x, axes=(1, 2)), rtol=1e-4, atol=1e-3)

    def test_grad_through_fft(self):
        x = pt.to_tensor(np.random.RandomState(2).randn(16)
                         .astype(np.float32), stop_gradient=False)
        y = pt.fft.rfft(x).abs().sum()
        y.backward()
        assert x.grad is not None and x.grad.numpy().shape == (16,)


class TestSignal:
    @pytest.mark.slow
    def test_stft_istft_round_trip(self):
        sig = np.sin(np.linspace(0, 50, 400)).astype(np.float32)[None]
        win = pt.audio.get_window("hann", 128)
        spec = pt.signal.stft(pt.to_tensor(sig), 128, 32, window=win)
        assert spec.numpy().shape == (1, 65, 13)
        rec = pt.signal.istft(spec, 128, 32, window=win,
                              length=400).numpy()
        np.testing.assert_allclose(rec[0, 64:320], sig[0, 64:320],
                                   atol=1e-4)

    def test_frame_overlap_add_inverse(self):
        sig = np.arange(64, dtype=np.float32)[None]
        fr = pt.signal.frame(pt.to_tensor(sig), 16, 16)  # non-overlapping
        assert fr.numpy().shape == (1, 16, 4)
        back = pt.signal.overlap_add(fr, 16).numpy()
        np.testing.assert_allclose(back[0], sig[0])


class TestAudio:
    def test_mel_scale_round_trip(self):
        F = pt.audio.functional
        for htk in (False, True):
            hz = np.array([100.0, 440.0, 4000.0], np.float32)
            mel = F.hz_to_mel(pt.to_tensor(hz), htk=htk)
            back = F.mel_to_hz(mel, htk=htk).numpy()
            np.testing.assert_allclose(back, hz, rtol=1e-4)
        assert abs(F.hz_to_mel(1000.0, htk=True) - 999.98) < 0.1

    def test_fbank_matrix_shape_and_partition(self):
        F = pt.audio.functional
        fb = F.compute_fbank_matrix(16000, 512, n_mels=40).numpy()
        assert fb.shape == (40, 257)
        assert (fb >= 0).all()
        # each filter has some support
        assert (fb.sum(axis=1) > 0).all()

    def test_power_to_db(self):
        F = pt.audio.functional
        x = np.array([1.0, 10.0, 100.0], np.float32)
        db = F.power_to_db(pt.to_tensor(x), top_db=None).numpy()
        np.testing.assert_allclose(db, [0.0, 10.0, 20.0], atol=1e-5)

    def test_windows_match_scipy_formulas(self):
        w = pt.audio.get_window("hamming", 16, fftbins=False).numpy()
        n = np.arange(16)
        want = 0.54 - 0.46 * np.cos(2 * math.pi * n / 15)
        np.testing.assert_allclose(w, want, atol=1e-6)
        for name in ("hann", "blackman", "nuttall", "triang", "cosine",
                     "bohman", "tukey"):
            w = pt.audio.get_window(name, 32).numpy()
            assert w.shape == (32,) and w.max() <= 1.0 + 1e-6

    @pytest.mark.slow
    def test_feature_layers(self):
        sig = np.sin(2 * math.pi * 440 *
                     np.linspace(0, 1, 8000)).astype(np.float32)[None]
        t = pt.to_tensor(sig)
        spec = pt.audio.features.Spectrogram(n_fft=256)(t)
        assert spec.numpy().shape[1] == 129
        mel = pt.audio.features.MelSpectrogram(sr=8000, n_fft=256,
                                               n_mels=32)(t)
        assert mel.numpy().shape[1] == 32
        logmel = pt.audio.features.LogMelSpectrogram(sr=8000, n_fft=256,
                                                     n_mels=32)(t)
        assert np.isfinite(logmel.numpy()).all()
        mfcc = pt.audio.features.MFCC(sr=8000, n_mfcc=13, n_fft=256,
                                      n_mels=32)(t)
        assert mfcc.numpy().shape[1] == 13


class TestViterbi:
    def _brute_force(self, pot, trans, include_tags):
        N = pot.shape[-1]
        best, best_path = -np.inf, None
        for path in itertools.product(range(N), repeat=pot.shape[0]):
            s = pot[0, path[0]]
            if include_tags:
                s += trans[N, path[0]]
            for t in range(1, len(path)):
                s += trans[path[t - 1], path[t]] + pot[t, path[t]]
            if include_tags:
                s += trans[path[-1], N + 1]
            if s > best:
                best, best_path = s, path
        return best, list(best_path)

    @pytest.mark.parametrize("include_tags", [False, True])
    def test_matches_brute_force(self, include_tags):
        rng = np.random.RandomState(0)
        N, T, B = 3, 4, 2
        pot = rng.randn(B, T, N).astype(np.float32)
        tdim = N + 2 if include_tags else N
        trans = rng.randn(tdim, tdim).astype(np.float32)
        scores, paths = pt.text.viterbi_decode(
            pt.to_tensor(pot), pt.to_tensor(trans),
            include_bos_eos_tag=include_tags)
        for b in range(B):
            want_s, want_p = self._brute_force(pot[b], trans, include_tags)
            np.testing.assert_allclose(float(scores.numpy()[b]), want_s,
                                       rtol=1e-5)
            assert list(paths.numpy()[b]) == want_p

    def test_lengths_masking(self):
        rng = np.random.RandomState(1)
        pot = rng.randn(1, 5, 3).astype(np.float32)
        trans = rng.randn(3, 3).astype(np.float32)
        s_full, p_full = pt.text.viterbi_decode(
            pt.to_tensor(pot[:, :3]), pt.to_tensor(trans),
            include_bos_eos_tag=False)
        s_mask, p_mask = pt.text.viterbi_decode(
            pt.to_tensor(pot), pt.to_tensor(trans),
            lengths=pt.to_tensor(np.array([3])),
            include_bos_eos_tag=False)
        np.testing.assert_allclose(s_full.numpy(), s_mask.numpy(),
                                   rtol=1e-5)
        assert list(p_full.numpy()[0]) == list(p_mask.numpy()[0][:3])


class TestTextDatasets:
    def test_synthetic_schemas(self):
        imdb = pt.text.Imdb(synthetic=True, n_samples=8)
        doc, label = imdb[0]
        assert doc.dtype == np.int64 and label in (0, 1)
        ng = pt.text.Imikolov(synthetic=True, n_samples=8)
        ctx, nxt = ng[0]
        assert len(ctx) == 4
        uci = pt.text.UCIHousing(synthetic=True, n_samples=8)
        f, y = uci[0]
        assert f.shape == (13,) and y.shape == (1,)
        srl = pt.text.Conll05st(synthetic=True, n_samples=4)
        words, pred, labels = srl[0]
        assert words.shape == pred.shape == labels.shape
        ml = pt.text.Movielens(synthetic=True, n_samples=4)
        assert len(ml[0]) == 8

    def test_requires_source(self):
        with pytest.raises(FileNotFoundError):
            pt.text.Imdb()


class TestBert:
    @pytest.mark.slow
    def test_forward_and_finetune(self):
        from paddle_tpu.incubate.models import (bert_tiny,
                                                BertForSequenceClassification)
        pt.seed(0)
        cfg = bert_tiny()
        model = BertForSequenceClassification(cfg, num_classes=2)
        ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 16))
        mask = np.ones((4, 16), np.int64)
        mask[:, 12:] = 0
        opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
        Y = np.random.RandomState(1).randint(0, 2, 4)
        losses = []
        for _ in range(6):
            logits = model(pt.to_tensor(ids),
                           attention_mask=pt.to_tensor(mask))
            loss = pt.nn.CrossEntropyLoss()(logits, pt.to_tensor(Y))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

    @pytest.mark.slow
    def test_padding_mask_matters(self):
        from paddle_tpu.incubate.models import bert_tiny, BertModel
        pt.seed(1)
        model = BertModel(bert_tiny())
        model.eval()
        ids = np.random.RandomState(0).randint(0, 1024, (2, 8))
        mask = np.ones((2, 8), np.int64)
        seq1, _ = model(pt.to_tensor(ids),
                        attention_mask=pt.to_tensor(mask))
        ids2 = ids.copy()
        ids2[:, 6:] = 7  # change padded-out tokens
        mask2 = mask.copy()
        mask2[:, 6:] = 0
        seq2, _ = model(pt.to_tensor(ids2),
                        attention_mask=pt.to_tensor(mask2))
        seq3, _ = model(pt.to_tensor(ids),
                        attention_mask=pt.to_tensor(mask2))
        # with mask, content of masked positions must not affect others
        np.testing.assert_allclose(seq2.numpy()[:, :6], seq3.numpy()[:, :6],
                                   atol=1e-5)

    @pytest.mark.slow
    def test_pretraining_heads(self):
        from paddle_tpu.incubate.models import (bert_tiny,
                                                BertForPretraining,
                                                BertPretrainingCriterion)
        pt.seed(2)
        cfg = bert_tiny()
        model = BertForPretraining(cfg)
        ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 8))
        mlm, nsp = model(pt.to_tensor(ids))
        assert mlm.shape == [2, 8, cfg.vocab_size] and nsp.shape == [2, 2]
        loss = BertPretrainingCriterion()(
            mlm, nsp, pt.to_tensor(ids),
            pt.to_tensor(np.zeros(2, np.int64)))
        assert float(loss.numpy()) > 0


class TestWMT:
    def test_wmt14_synthetic_schema(self):
        ds = pt.text.WMT14(synthetic=True, n_samples=8, dict_size=100)
        assert len(ds) == 8
        s, t, tn = ds[0]
        # trg starts with <s>=0; trg_next ends with <e>=1; shifted pair
        assert t[0] == 0 and tn[-1] == 1
        np.testing.assert_array_equal(t[1:], tn[:-1])
        assert s.dtype == np.int64

    def test_wmt16_subclass(self):
        ds = pt.text.WMT16(synthetic=True, n_samples=4, src_dict_size=50,
                           trg_dict_size=60, lang="de")
        assert len(ds) == 4 and ds.lang == "de"
        with pytest.raises(FileNotFoundError):
            pt.text.WMT14()  # no file, no synthetic -> loud error
