"""Round-4 API long tail: multiplex, attribute predicates, LazyGuard,
printoptions, hermitian FFTs (ref: ``python/paddle/__init__.py __all__``,
``python/paddle/fft.py:1123``)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.fft as ptf
from paddle_tpu import Tensor

scipy_fft = pytest.importorskip("scipy.fft")


def test_multiplex_rows():
    ins = [pt.to_tensor(np.full((4, 3), i, "float32")) for i in range(3)]
    idx = pt.to_tensor(np.array([[2], [0], [1], [0]], "int32"))
    out = pt.multiplex(ins, idx).numpy()
    np.testing.assert_allclose(out[:, 0], [2, 0, 1, 0])


def test_multiplex_grad():
    a = Tensor(np.ones((2, 3), np.float32), stop_gradient=False)
    b = Tensor(np.ones((2, 3), np.float32) * 2, stop_gradient=False)
    idx = pt.to_tensor(np.array([[0], [1]], "int32"))
    out = pt.multiplex([a, b], idx)
    pt.sum(out).backward()
    # row 0 comes from a, row 1 from b
    np.testing.assert_allclose(np.asarray(a.grad._data),
                               [[1, 1, 1], [0, 0, 0]])
    np.testing.assert_allclose(np.asarray(b.grad._data),
                               [[0, 0, 0], [1, 1, 1]])


def test_shape_and_predicates():
    x = pt.to_tensor(np.zeros((2, 3), "float32"))
    np.testing.assert_array_equal(pt.shape(x).numpy(), [2, 3])
    assert pt.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]
    assert pt.is_floating_point(x)
    assert not pt.is_integer(x)
    assert pt.is_integer(pt.to_tensor(np.array([1], "int32")))
    assert pt.is_complex(pt.to_tensor(np.array([1 + 2j], "complex64")))
    bf = pt.to_tensor(np.zeros(2, "float32")).astype("bfloat16")
    assert pt.is_floating_point(bf)


def test_check_shape():
    pt.check_shape([2, 3])
    with pytest.raises(ValueError):
        pt.check_shape([2, -3])
    with pytest.raises(TypeError):
        pt.check_shape([2, 3.5])


def test_create_parameter():
    p = pt.create_parameter([3, 4], "float32")
    assert type(p).__name__ == "Parameter" and p.shape == [3, 4]
    assert float(np.abs(p.numpy()).sum()) > 0  # xavier, not zeros


def test_lazy_guard_defers_init():
    import paddle_tpu.nn as nn
    with pt.LazyGuard():
        fc = nn.Linear(8, 8)
    # under the guard: host numpy placeholder, no device array
    assert isinstance(fc.weight._data, np.ndarray)
    assert float(np.abs(fc.weight.numpy()).sum()) == 0.0
    fc.weight.initialize()
    assert not isinstance(fc.weight._data, np.ndarray)
    assert float(np.abs(fc.weight.numpy()).sum()) > 0
    # bias initializer is zeros either way; initialize() is a no-op after
    fc.weight.initialize()


def test_lazy_guard_standalone_create_parameter():
    with pt.LazyGuard():
        p = pt.create_parameter([4, 4], "float32")
    assert isinstance(p._data, np.ndarray)
    p.initialize()
    assert float(np.abs(p.numpy()).sum()) > 0


def test_trapezoid_x_dx_conflict():
    y = pt.to_tensor(np.ones((3,), "float32"))
    with pytest.raises(ValueError):
        pt.trapezoid(y, x=y, dx=1.0)
    with pytest.raises(ValueError):
        pt.cumulative_trapezoid(y, x=y, dx=1.0)


def test_multiplex_oob_index():
    ins = [pt.to_tensor(np.ones((2, 3), "float32"))] * 2
    with pytest.raises(ValueError):
        pt.multiplex(ins, pt.to_tensor(np.array([[5], [0]], "int32")))


def test_hfftn_s_defaults_axes():
    rng = np.random.RandomState(2)
    a = (rng.rand(2, 3, 5) + 1j * rng.rand(2, 3, 5)).astype("complex64")
    np.testing.assert_allclose(
        ptf.hfftn(pt.to_tensor(a), s=[4, 6]).numpy(),
        scipy_fft.hfftn(a, s=[4, 6]), atol=1e-3, rtol=1e-3)


def test_sci_mode_printoptions():
    pt.set_printoptions(precision=3, sci_mode=True)
    s = repr(pt.to_tensor(np.array([1.5], "float32")))
    assert "e+00" in s
    pt.set_printoptions(sci_mode=False)
    s2 = repr(pt.to_tensor(np.array([1.5], "float32")))
    assert "e+00" not in s2
    # Parameter honors the same options
    p = pt.create_parameter([2], "float32")
    pt.set_printoptions(sci_mode=True)
    assert "e" in repr(p)
    pt.set_printoptions(sci_mode=False, precision=8)


def test_set_printoptions_scoped():
    pt.set_printoptions(precision=2)
    s = repr(pt.to_tensor(np.array([1.23456789], "float32")))
    assert "1.23" in s and "1.2345" not in s
    # numpy's own global state must be untouched
    assert np.get_printoptions()["precision"] == 8
    pt.set_printoptions(precision=8)


def test_cuda_parity_shims():
    assert pt.get_cuda_rng_state() == []
    pt.set_cuda_rng_state([])
    with pytest.raises(ValueError):
        pt.set_cuda_rng_state([1])
    pt.disable_signal_handler()
    assert pt.CUDAPinnedPlace() is not None


@pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
def test_hfft2_ihfft2_vs_scipy(norm):
    rng = np.random.RandomState(0)
    a = (rng.rand(3, 5) + 1j * rng.rand(3, 5)).astype("complex64")
    np.testing.assert_allclose(
        ptf.hfft2(pt.to_tensor(a), norm=norm).numpy(),
        scipy_fft.hfft2(a, norm=norm), atol=1e-3, rtol=1e-3)
    r = rng.rand(4, 8).astype("float32")
    np.testing.assert_allclose(
        ptf.ihfft2(pt.to_tensor(r), norm=norm).numpy(),
        scipy_fft.ihfft2(r, norm=norm), atol=1e-5, rtol=1e-4)


def test_hfftn_ihfftn_with_s():
    rng = np.random.RandomState(1)
    a = (rng.rand(3, 5) + 1j * rng.rand(3, 5)).astype("complex64")
    np.testing.assert_allclose(
        ptf.hfftn(pt.to_tensor(a), s=[4, 6]).numpy(),
        scipy_fft.hfftn(a, s=[4, 6]), atol=1e-3, rtol=1e-3)
    r = rng.rand(4, 8).astype("float32")
    np.testing.assert_allclose(
        ptf.ihfftn(pt.to_tensor(r), s=[3, 6]).numpy(),
        scipy_fft.ihfftn(r, s=[3, 6]), atol=1e-5, rtol=1e-4)
    with pytest.raises(ValueError):
        ptf.hfftn(pt.to_tensor(a), s=[4], axes=(0, 1))


class TestInplaceIndexOps:
    """index_add_/index_put_ (ref manipulation.py:4502,4633) + the
    rebind-inplace grad semantics they ride on."""

    def test_index_add__values_and_grads(self):
        x = pt.to_tensor(np.zeros((4, 3), np.float32))
        x.stop_gradient = False
        v = pt.to_tensor(np.ones((2, 3), np.float32))
        v.stop_gradient = False
        y = x * 2.0
        out = pt.index_add_(y, pt.to_tensor(np.array([0, 2], np.int64)),
                            0, v)
        assert out is y
        want = np.zeros((4, 3), np.float32)
        want[[0, 2]] = 1.0
        np.testing.assert_allclose(y.numpy(), want)
        y.sum().backward()
        # chain through the overwritten intermediate must survive
        np.testing.assert_allclose(x.grad.numpy(),
                                   np.full((4, 3), 2.0, np.float32))
        np.testing.assert_allclose(v.grad.numpy(),
                                   np.ones((2, 3), np.float32))

    def test_index_put__set_and_accumulate(self):
        z = pt.to_tensor(np.zeros((3, 3), np.float32))
        idx = [pt.to_tensor(np.array([0, 1]))]
        val = pt.to_tensor(np.ones((2, 3), np.float32))
        pt.index_put_(z, idx, val)
        assert float(z.numpy().sum()) == 6.0
        pt.index_put_(z, idx, val, accumulate=True)
        assert float(z.numpy().sum()) == 12.0
        # tensor-method form
        z2 = pt.to_tensor(np.zeros((4,), np.float32))
        z2.index_put_([pt.to_tensor(np.array([3]))],
                      pt.to_tensor(np.array([5.0], np.float32)))
        assert float(z2.numpy()[3]) == 5.0

    def test_leaf_with_grad_raises(self):
        x = pt.to_tensor(np.ones((2, 2), np.float32))
        x.stop_gradient = False
        with pytest.raises(RuntimeError, match="[Ll]eaf"):
            pt.index_add_(x, pt.to_tensor(np.array([0])), 0,
                          pt.to_tensor(np.ones((1, 2), np.float32)))
        with pt.no_grad():  # init-style writes stay allowed
            pt.index_add_(x, pt.to_tensor(np.array([0])), 0,
                          pt.to_tensor(np.ones((1, 2), np.float32)))


def test_sparse_pca_lowrank_matches_dense_svd():
    """sparse.pca_lowrank (ref sparse/unary.py:956): randomized PCA over
    BCOO matmuls; singular values must match the centered dense SVD."""
    rs = np.random.RandomState(0)
    d = rs.randn(30, 12).astype(np.float32)
    d[rs.rand(30, 12) > 0.4] = 0.0
    nz = np.nonzero(d)
    sx = pt.sparse.sparse_coo_tensor(np.stack(nz), d[nz], shape=[30, 12])
    U, S, V = pt.sparse.pca_lowrank(sx, q=5)
    assert tuple(U.shape) == (30, 5) and tuple(V.shape) == (12, 5)
    c = d - d.mean(0, keepdims=True)
    s_ref = np.linalg.svd(c, compute_uv=False)[:5]
    np.testing.assert_allclose(np.asarray(S._data), s_ref, rtol=0.05)
    with pytest.raises(ValueError):
        pt.sparse.pca_lowrank(sx, q=999)
    with pytest.raises(TypeError):
        pt.sparse.pca_lowrank(pt.to_tensor(d))


def test_distributed_parallel_mode_and_is_available():
    import paddle_tpu.distributed as dist
    assert dist.ParallelMode.DATA_PARALLEL == 0
    assert dist.ParallelMode.TENSOR_PARALLEL == 1
    assert dist.ParallelMode.PIPELINE_PARALLEL == 2
    assert dist.ParallelMode.SHARDING_PARALLEL == 3
    assert dist.is_available() is True


def test_inplace_duplicate_occurrence_keeps_full_grad():
    # y.add_(y): both occurrences of y in the node's inputs must
    # share one proxy or half the gradient silently vanishes
    x = pt.to_tensor(np.ones(2, np.float32))
    x.stop_gradient = False
    y = x * 1.0
    pt.add_(y, y)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_inplace_stop_gradient_buffer_write_flows_to_values():
    # KV-cache pattern: write grad-carrying values into a stop-gradient
    # buffer; the node must not consume its own output after the rebind
    # (backward would deadlock silently)
    z = pt.to_tensor(np.zeros((3, 3), np.float32))
    v = pt.to_tensor(np.ones((2, 3), np.float32))
    v.stop_gradient = False
    pt.index_add_(z, pt.to_tensor(np.array([0, 2])), 0, v)
    z.sum().backward()
    np.testing.assert_allclose(v.grad.numpy(), np.ones((2, 3)))
