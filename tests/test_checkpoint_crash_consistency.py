"""Crash-consistent checkpointing: the fault-injection proof.

The contract under test (distributed/checkpoint.py + checkpoint_manager.py):
a process killed at ANY instant of a save leaves the previous committed
checkpoint loadable bit-for-bit, and post-commit corruption (bit-rot,
truncation) is detected and skipped — never silently loaded.  Faults are
injected deterministically via tests/fault_injection.py, which patches the
two functions every durable byte funnels through.
"""
import json
import os
import random

import numpy as np
import pytest
import jax.numpy as jnp

from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed.checkpoint import (
    CheckpointCorruptError, is_committed, load_sharded, save_sharded,
    store_barrier, verify_checkpoint,
)
from paddle_tpu.distributed.checkpoint_manager import (
    CheckpointManager, latest_checkpoint,
)
from paddle_tpu.utils.retry import backoff_delays, retry_call, wait_until

from fault_injection import (
    FaultInjector, KilledSave, corrupt_file, data_files, truncate_file,
)


def _state(v):
    """Small deterministic pytree; distinct per version ``v``."""
    return {"w": jnp.arange(32, dtype=jnp.float32).reshape(8, 4) + v,
            "nested": {"b": jnp.full((6,), float(v), dtype=jnp.float32)}}


def _assert_state_equal(a, b):
    fa = sorted(ckpt._flat_items(a))
    fb = sorted(ckpt._flat_items(b))
    assert [p for p, _ in fa] == [p for p, _ in fb]
    for (_, x), (_, y) in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _count_writes(tmp_path, state):
    """Durable file writes of one single-host save of ``state``."""
    with FaultInjector(fail_after=10 ** 6) as fi:
        save_sharded(state, str(tmp_path / "_probe"))
    return fi.writes


# -- retry primitives (deterministic: injected rng/sleep/clock) --------------

class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def sleep(self, d):
        assert d >= 0
        self.t += d


def test_backoff_delays_shape_and_cap():
    ds = list(backoff_delays(base=0.1, factor=2.0, max_delay=0.5,
                             jitter=0.0, max_tries=5))
    assert ds == [0.1, 0.2, 0.4, 0.5, 0.5]


def test_backoff_delays_jitter_band():
    rng = random.Random(0)
    ds = list(backoff_delays(base=1.0, factor=1.0, max_delay=1.0,
                             jitter=0.25, max_tries=100, rng=rng))
    assert all(0.75 <= d <= 1.25 for d in ds)
    assert len(set(ds)) > 1  # actually jittered


def test_backoff_delays_respects_deadline():
    clk = _FakeClock()
    ds = backoff_delays(base=1.0, factor=1.0, max_delay=1.0, jitter=0.0,
                        deadline=2.5, clock=clk)
    out = []
    for d in ds:
        out.append(d)
        clk.sleep(d)
    # 1.0 + 1.0 + clipped 0.5 == deadline; never sleeps past it
    assert out == [1.0, 1.0, 0.5]
    assert clk.t == 2.5


def test_backoff_delays_rejects_bad_policy():
    with pytest.raises(ValueError):
        next(backoff_delays(base=-1))
    with pytest.raises(ValueError):
        next(backoff_delays(factor=0.5))
    with pytest.raises(ValueError):
        next(backoff_delays(jitter=2.0))


def test_retry_call_retries_then_succeeds():
    clk = _FakeClock()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("store not up yet")
        return "ok"

    seen = []
    out = retry_call(flaky, retry_on=(ConnectionError,), deadline=60,
                     base=0.05, jitter=0.0, sleep=clk.sleep, clock=clk,
                     on_retry=lambda a, e, d: seen.append((a, d)))
    assert out == "ok" and calls["n"] == 3
    assert seen == [(1, 0.05), (2, 0.1)]


def test_retry_call_exhausted_reraises_last():
    clk = _FakeClock()

    def always():
        raise TimeoutError("still down")

    with pytest.raises(TimeoutError, match="still down"):
        retry_call(always, retry_on=(TimeoutError,), max_tries=3,
                   jitter=0.0, sleep=clk.sleep, clock=clk)


def test_retry_call_unlisted_exception_propagates_immediately():
    calls = {"n": 0}

    def boom():
        calls["n"] += 1
        raise ValueError("not retryable")

    with pytest.raises(ValueError):
        retry_call(boom, retry_on=(ConnectionError,), max_tries=10,
                   sleep=lambda d: None)
    assert calls["n"] == 1


def test_wait_until_returns_first_truthy_value():
    clk = _FakeClock()
    vals = iter([None, 0, "", (1, 2)])
    out = wait_until(lambda: next(vals), timeout=60, jitter=0.0,
                     sleep=clk.sleep, clock=clk)
    assert out == (1, 2)


def test_wait_until_timeout_names_the_wait():
    clk = _FakeClock()
    with pytest.raises(TimeoutError, match="peer rendezvous"):
        wait_until(lambda: False, timeout=1.0, jitter=0.0,
                   desc="peer rendezvous", sleep=clk.sleep, clock=clk)
    assert clk.t <= 1.0  # never slept past the deadline


# -- atomic commit: kill at every write boundary -----------------------------

def test_kill_after_any_write_falls_back_to_previous_commit(tmp_path):
    """The tentpole proof: interrupt a save after the Nth durable write,
    for EVERY N, and the previous committed checkpoint must restore
    bit-for-bit with latest_step() reporting it."""
    v1, v2 = _state(1), _state(2)
    total = _count_writes(tmp_path, v1)
    assert total >= 4  # 2 shards + index + COMMIT marker

    for n in range(total):
        root = str(tmp_path / f"root_{n}")
        mgr = CheckpointManager(root, keep_last_n=3)
        mgr.save(1, v1)
        assert is_committed(mgr.step_dir(1))

        with pytest.raises(KilledSave):
            with FaultInjector(fail_after=n):
                mgr.save(2, v2)

        assert mgr.latest_step() == 1
        restored, step = mgr.restore_latest(template=v1)
        assert step == 1
        _assert_state_equal(restored, v1)
        # and the recovery path still saves cleanly afterwards
        mgr.save(2, v2)
        restored2, step2 = mgr.restore_latest(template=v1)
        assert step2 == 2
        _assert_state_equal(restored2, v2)


def test_kill_before_rename_leaves_no_new_step(tmp_path):
    """Crash in the narrowest window — staging complete, rename pending:
    the new step dir must not exist and the old one must win."""
    root = str(tmp_path / "root")
    mgr = CheckpointManager(root)
    mgr.save(1, _state(1))
    with pytest.raises(KilledSave):
        with FaultInjector(fail_after=None, fail_before_rename=True):
            mgr.save(2, _state(2))
    assert not os.path.isdir(mgr.step_dir(2))
    assert mgr.latest_step() == 1
    # staged debris is swept once a newer save commits
    assert any(".tmp." in n for n in os.listdir(root))
    mgr.save(3, _state(3))
    assert not any(".tmp." in n for n in os.listdir(root))


def test_torn_write_is_never_loadable(tmp_path):
    """A torn write (partial payload of the killing write lands) must
    leave the staged dir uncommitted — the COMMIT marker is written
    last, so the tear can only hit data/index before any marker."""
    root = str(tmp_path / "root")
    mgr = CheckpointManager(root)
    mgr.save(1, _state(1))
    with pytest.raises(KilledSave):
        with FaultInjector(fail_after=1, partial_bytes=7):
            mgr.save(2, _state(2))
    assert mgr.latest_step() == 1
    restored, step = mgr.restore_latest(template=_state(0))
    assert step == 1
    _assert_state_equal(restored, _state(1))


def test_overwrite_same_step_is_atomic(tmp_path):
    """Re-saving an existing step (preemption re-save) swaps the old
    commit out atomically; a kill mid-overwrite keeps the OLD content."""
    root = str(tmp_path / "root")
    mgr = CheckpointManager(root)
    mgr.save(1, _state(1))
    with pytest.raises(KilledSave):
        with FaultInjector(fail_after=2):
            mgr.save(1, _state(9))
    restored, step = mgr.restore_latest(template=_state(0))
    assert step == 1
    _assert_state_equal(restored, _state(1))


# -- integrity: post-commit corruption ---------------------------------------

def test_corrupted_shard_detected_named_and_skipped(tmp_path):
    root = str(tmp_path / "root")
    mgr = CheckpointManager(root)
    mgr.save(1, _state(1))
    mgr.save(2, _state(2))
    d2 = mgr.step_dir(2)
    victim = data_files(d2)[0]
    corrupt_file(os.path.join(d2, victim))

    # direct load: raises, naming the offending file
    with pytest.raises(CheckpointCorruptError, match="CRC"):
        load_sharded(d2, template=_state(0))
    with pytest.raises(CheckpointCorruptError,
                       match=victim.replace("\\", "/").split("/")[-1]):
        verify_checkpoint(d2, integrity="full")

    # size-level scan can't see bit-rot (size unchanged)...
    assert mgr.latest_step() == 2
    # ...but restore_latest full-verifies, falls back, and remembers
    restored, step = mgr.restore_latest(template=_state(0))
    assert step == 1
    _assert_state_equal(restored, _state(1))
    assert mgr.latest_step() == 1  # reports the fallback step


def test_truncated_shard_detected_by_cheap_scan(tmp_path):
    root = str(tmp_path / "root")
    mgr = CheckpointManager(root)
    mgr.save(1, _state(1))
    mgr.save(2, _state(2))
    d2 = mgr.step_dir(2)
    truncate_file(os.path.join(d2, data_files(d2)[0]))
    # size mismatch: even the size-level manifest scan rejects step 2
    assert mgr.latest_step() == 1
    restored, step = mgr.restore_latest(template=_state(0))
    assert step == 1
    _assert_state_equal(restored, _state(1))


def test_missing_shard_and_stray_file_detected(tmp_path):
    p = str(tmp_path / "ck")
    save_sharded(_state(1), p)
    files = data_files(p)
    os.remove(os.path.join(p, files[0]))
    with pytest.raises(CheckpointCorruptError, match="missing"):
        verify_checkpoint(p, integrity="size")


def test_unreadable_commit_marker_is_corrupt_not_crash(tmp_path):
    p = str(tmp_path / "ck")
    save_sharded(_state(1), p)
    marker = os.path.join(p, "COMMIT.0")
    with open(marker, "w") as f:
        f.write("{not json")
    assert not is_committed(p)
    with pytest.raises(CheckpointCorruptError):
        verify_checkpoint(p)


def test_uncommitted_dir_is_invisible_to_loads(tmp_path):
    p = str(tmp_path / "ck")
    save_sharded(_state(1), p)
    os.remove(os.path.join(p, "COMMIT.0"))
    assert not is_committed(p)
    with pytest.raises(CheckpointCorruptError, match="COMMIT"):
        load_sharded(p, template=_state(0))


def test_legacy_unverified_load_still_works(tmp_path):
    """integrity="off" skips manifest checks but still requires commit."""
    p = str(tmp_path / "ck")
    save_sharded(_state(3), p)
    out = load_sharded(p, template=_state(0), integrity="off")
    _assert_state_equal(out, _state(3))


# -- multi-host commit markers ----------------------------------------------

def test_multihost_commit_requires_all_markers(tmp_path):
    p = str(tmp_path / "ck")
    v = _state(4)
    save_sharded(v, p, process_index=0, world_size=2)
    # half-committed: proc 1's marker missing -> not loadable
    assert os.path.exists(os.path.join(p, "COMMIT.0"))
    assert not is_committed(p)
    with pytest.raises(CheckpointCorruptError, match="1"):
        verify_checkpoint(p, integrity="size")

    save_sharded(v, p, process_index=1, world_size=2)
    assert is_committed(p)
    verify_checkpoint(p, integrity="full")
    marker = json.load(open(os.path.join(p, "COMMIT.1")))
    assert marker["world"] == 2 and marker["proc"] == 1


def test_store_barrier_blocks_until_world_arrives():
    class _Store:
        def __init__(self):
            self.counts = {}

        def add(self, key, n):
            self.counts[key] = self.counts.get(key, 0) + n
            return self.counts[key]

    s = _Store()
    # world of 1: own arrival satisfies the barrier immediately
    store_barrier(s, "ckpt/x/commit", 1)
    # simulate the peer having arrived first: count reaches 2 instantly
    s.add("ckpt/y/commit", 1)
    store_barrier(s, "ckpt/y/commit", 2)
    assert s.counts["ckpt/y/commit"] == 2

    with pytest.raises(TimeoutError):
        store_barrier(_Store(), "ckpt/z/commit", 2, timeout=0.2)


# -- CheckpointManager: rotation, GC, async ----------------------------------

def test_gc_keeps_last_n_only(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "root"), keep_last_n=2)
    for i in range(1, 5):
        mgr.save(i, _state(i))
    assert mgr.all_steps() == [3, 4]
    assert mgr.valid_steps() == [3, 4]


def test_gc_never_deletes_only_valid_checkpoint(tmp_path):
    root = str(tmp_path / "root")
    mgr = CheckpointManager(root, keep_last_n=1)
    mgr.save(1, _state(1))
    for n in (0, 1, 2):
        with pytest.raises(KilledSave):
            with FaultInjector(fail_after=n):
                mgr.save(2, _state(2))
        assert mgr.latest_step() == 1  # sole survivor untouched
    mgr.save(3, _state(3))
    assert mgr.all_steps() == [3]  # rotation resumes once a commit lands


def test_gc_sweeps_old_uncommitted_debris_not_newer(tmp_path):
    root = str(tmp_path / "root")
    mgr = CheckpointManager(root, keep_last_n=2)
    mgr.save(1, _state(1))
    # fake crash debris OLDER than the newest valid step...
    os.makedirs(os.path.join(root, "step_00000000"))
    # ...and an uncommitted dir NEWER (a concurrent in-flight save)
    os.makedirs(os.path.join(root, "step_00000099"))
    mgr.save(2, _state(2))
    names = set(os.listdir(root))
    assert "step_00000000" not in names   # swept
    assert "step_00000099" in names       # left alone
    assert mgr.latest_step() == 2


def test_keep_last_n_validation(tmp_path):
    with pytest.raises(ValueError):
        CheckpointManager(str(tmp_path / "r"), keep_last_n=0)


def test_restore_latest_on_empty_root_is_fresh_start(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "root"))
    tpl = _state(0)
    state, step = mgr.restore_latest(template=tpl)
    assert step is None and state is tpl


def test_async_save_round_trip_and_ordering(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "root"), async_save=True,
                            keep_last_n=2)
    for i in range(1, 4):
        mgr.save(i, _state(i))
    mgr.close()
    assert mgr.all_steps() == [2, 3]
    restored, step = mgr.restore_latest(template=_state(0))
    assert step == 3
    _assert_state_equal(restored, _state(3))


def test_async_save_error_surfaces_on_next_call(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "root"), async_save=True)
    mgr.save(1, _state(1))
    mgr.wait()
    with FaultInjector(fail_after=0):
        mgr.save(2, _state(2))    # queues; writer dies in background
        with pytest.raises(KilledSave):
            mgr.wait()            # ...and the failure surfaces here
    # manager remains usable; step 1 still the latest valid
    assert mgr.latest_step() == 1
    mgr.save(3, _state(3))
    mgr.close()
    assert mgr.latest_step() == 3


def test_save_block_forces_synchronous_commit(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "root"), async_save=True)
    mgr.save(1, _state(1), block=True)
    # committed before returning — no wait() needed
    assert is_committed(mgr.step_dir(1))


def test_latest_checkpoint_helper(tmp_path):
    root = str(tmp_path / "root")
    assert latest_checkpoint(root) is None       # doesn't exist
    mgr = CheckpointManager(root)
    assert latest_checkpoint(root) is None       # no steps yet
    mgr.save(7, _state(7))
    assert latest_checkpoint(root) == mgr.step_dir(7)
    # a plain (non-manager) sharded dir: None, caller keeps its path
    p = str(tmp_path / "plain")
    save_sharded(_state(1), p)
    assert latest_checkpoint(p) is None


def test_hapi_model_load_resolves_manager_root(tmp_path):
    import paddle_tpu as pt
    net = pt.nn.Sequential(pt.nn.Linear(4, 8), pt.nn.ReLU(),
                           pt.nn.Linear(8, 2))
    m = pt.Model(net)
    root = str(tmp_path / "root")
    mgr = CheckpointManager(root)
    params = {k: t._data for k, t in net.state_dict().items()}
    mgr.save(1, {"params": params})
    w_before = np.asarray(net[0].weight._data).copy()
    # corrupt a NEWER step: load must resolve to the older valid one
    mgr.save(2, {"params": {k: v + 123.0 for k, v in params.items()}})
    d2 = mgr.step_dir(2)
    truncate_file(os.path.join(d2, data_files(d2)[0]))
    net[0].weight._data = net[0].weight._data + 1.0
    m.load(root)
    np.testing.assert_array_equal(np.asarray(net[0].weight._data),
                                  w_before)


def test_engine_restore_latest(tmp_path):
    import paddle_tpu as pt
    from paddle_tpu.distributed.auto_parallel.engine import Engine

    def _build():
        pt.seed(0)
        net = pt.nn.Sequential(pt.nn.Linear(4, 8), pt.nn.ReLU(),
                               pt.nn.Linear(8, 2))
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
        return Engine(net, pt.nn.CrossEntropyLoss(), opt)

    eng = _build()
    eng.prepare(mode="train")
    root = str(tmp_path / "root")
    mgr = CheckpointManager(root)
    assert _build().restore_latest(root) is None   # empty -> fresh start
    mgr.save(5, eng._state)
    eng2 = _build()
    assert eng2.restore_latest(root) == 5
    _assert_state_equal(eng2._state, eng._state)
