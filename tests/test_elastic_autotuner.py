"""Elastic manager + auto-tuner tests (ref: test/collective/fleet/
test_elastic_manager.py, test/auto_tuner/)."""
from __future__ import annotations

import sys
import time

import numpy as np
import pytest

import paddle_tpu.core as core
from paddle_tpu.distributed.auto_tuner import (AutoTuner, HistoryRecorder,
                                               prune_by_rules)
from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                  ElasticStatus,
                                                  LauncherInterface)


class TestAutoTuner:
    CFG = {
        "candidates": {
            "dp_degree": [1, 2, 4, 8],
            "mp_degree": [1, 2, 4],
            "pp_degree": [1, 2],
            "micro_batch_size": [1, 2, 4],
            "sharding_degree": [1],
            "sharding_stage": [None],
            "use_recompute": [False, True],
            "recompute_granularity": [None],
        },
        "num_chips": 8,
        "global_batch_size": 16,
    }

    def test_grid_yields_only_valid_mesh_shapes(self):
        tuner = AutoTuner(self.CFG)
        seen = []
        while (cfg := tuner.search_once()) is not None:
            seen.append(cfg)
        assert seen, "search space empty"
        for cfg in seen:
            assert cfg["dp_degree"] * cfg["mp_degree"] * cfg["pp_degree"] \
                == 8
            per = 16 // cfg["dp_degree"]
            assert per % cfg["micro_batch_size"] == 0

    def test_best_selection_and_oom_prune(self):
        tuner = AutoTuner(self.CFG)
        # simulate: bigger mbs oom, smaller ok
        n = 0
        while (cfg := tuner.search_once()) is not None and n < 12:
            n += 1
            if cfg["micro_batch_size"] >= 4:
                tuner.add_cfg(**cfg, throughput=None, status="oom")
            else:
                tuner.add_cfg(**cfg,
                              throughput=100 * cfg["micro_batch_size"],
                              status="ok")
        best, err = tuner.get_best()
        assert not err
        assert best["status"] == "ok"
        assert best["throughput"] == max(
            c.get("throughput") or 0 for c in tuner.recorder.history)

    def test_oom_history_prunes_larger(self):
        cfg = {"num_chips": None}
        history = [{"micro_batch_size": 2, "mp_degree": 1, "status": "oom",
                    "use_recompute": False}]
        assert prune_by_rules(cfg, {"micro_batch_size": 4, "mp_degree": 1},
                              history)
        assert not prune_by_rules(cfg, {"micro_batch_size": 1,
                                        "mp_degree": 1}, history)

    def test_recorder_store_load(self, tmp_path):
        r = HistoryRecorder()
        r.add_cfg(dp_degree=2, throughput=10.5, status="ok")
        r.add_cfg(dp_degree=4, throughput=20.0, status="ok")
        path = str(tmp_path / "hist.csv")
        r.store_history(path)
        r2 = HistoryRecorder()
        rows, err = r2.load_history(path)
        assert not err and len(rows) == 2
        best, _ = r2.get_best()
        assert best["throughput"] == 20.0


@pytest.mark.skipif(not core.native_available(),
                    reason="needs native TCPStore")
class TestElastic:
    def _mgr(self, store, host, np="1:3", ttl=0.6):
        return ElasticManager(store, host, np=np,
                              heartbeat_interval=0.1, lease_ttl=ttl)

    def test_register_and_match(self):
        master = core.TCPStore(is_master=True)
        try:
            m1 = self._mgr(master, "host-a")
            m1.register()
            c2 = core.TCPStore("127.0.0.1", master.port)
            m2 = self._mgr(c2, "host-b")
            m2.register()
            ok, hosts, rank = m1.match()
            assert ok and hosts == ["host-a", "host-b"]
            assert rank == 0 and m2.match()[2] == 1
            m1.exit()
            m2.exit()
            c2.close()
        finally:
            master.close()

    def test_dead_node_detected_and_rematch(self):
        master = core.TCPStore(is_master=True)
        try:
            m1 = self._mgr(master, "host-a", ttl=0.5)
            m1.register()
            c2 = core.TCPStore("127.0.0.1", master.port)
            m2 = self._mgr(c2, "host-b", ttl=0.5)
            m2.register()
            assert len(m1.alive_nodes()) == 2
            # host-b dies (heartbeat stops)
            m2._stop.set()
            time.sleep(1.2)
            hosts, rank = m1.wait_for_np(timeout=5.0)
            assert hosts == ["host-a"] and rank == 0
            m1.exit()
            c2.close()
        finally:
            master.close()

    def test_watch_detects_join(self):
        master = core.TCPStore(is_master=True)
        try:
            m1 = self._mgr(master, "host-a")
            m1.register()
            assert m1.watch(timeout=0.3) == ElasticStatus.COMPLETED
            c2 = core.TCPStore("127.0.0.1", master.port)
            m2 = self._mgr(c2, "host-b")
            m2.register()
            status = m1.watch(timeout=3.0)
            assert status == ElasticStatus.RESTART
            m1.exit()
            m2.exit()
            c2.close()
        finally:
            master.close()

    def test_launcher_interface(self):
        li = LauncherInterface([sys.executable, "-c",
                                "import time; time.sleep(30)"])
        li.launch()
        assert li.watch() is None
        li.stop(timeout=5.0)
        assert li.watch() is not None

    def test_hold_below_np_min(self):
        master = core.TCPStore(is_master=True)
        try:
            m1 = self._mgr(master, "host-a", np="2:3")
            m1.register()
            ok, hosts, _ = m1.match()
            assert not ok and hosts == ["host-a"]
            with pytest.raises(TimeoutError):
                m1.wait_for_np(timeout=0.5)
            m1.exit()
        finally:
            master.close()
