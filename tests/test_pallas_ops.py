"""Flash-attention Pallas kernel tests (interpret mode on CPU).

Mirrors the reference's flash-attn tests
(test/legacy_test/test_flash_attention.py): kernel output vs a plain
softmax-attention oracle, forward and gradients, causal and non-causal,
unaligned sequence lengths and head dims.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas_ops import mha, mha_reference


def _rand(shape, seed):
    return jnp.asarray(
        np.random.RandomState(seed).standard_normal(shape).astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize(
    "b,h,sq,skv,d",
    [
        (2, 2, 128, 128, 64),
        (1, 3, 256, 256, 128),
        (2, 1, 100, 100, 32),     # unaligned S and D → padding path
        (1, 2, 128, 256, 64),     # cross attention, kv longer
    ],
)
def test_flash_forward_matches_reference(causal, b, h, sq, skv, d):
    if causal and sq != skv:
        # causal cross-attn aligns at the end; still defined
        pass
    q, k, v = (_rand((b, h, s, d), i) for i, s in
               enumerate([sq, skv, skv]))
    out = mha(q, k, v, causal=causal, interpret=True, block_q=128,
              block_k=128)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)


@pytest.mark.slow
@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    b, h, s, d = 1, 2, 128, 64
    q, k, v = (_rand((b, h, s, d), 10 + i) for i in range(3))

    def loss_kernel(q, k, v):
        o = mha(q, k, v, causal=causal, interpret=True)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = mha_reference(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-2, rtol=2e-2)


def test_flash_grads_unaligned():
    b, h, s, d = 1, 1, 72, 48
    q, k, v = (_rand((b, h, s, d), 20 + i) for i in range(3))

    def loss_kernel(q, k, v):
        return jnp.sum(mha(q, k, v, causal=True, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-2, rtol=2e-2)


def test_flash_bf16():
    b, h, s, d = 1, 2, 128, 64
    q, k, v = (_rand((b, h, s, d), 30 + i).astype(jnp.bfloat16)
               for i in range(3))
    out = mha(q, k, v, causal=True, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = mha_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                        v.astype(jnp.float32), causal=True)
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref), atol=3e-2, rtol=3e-2)


@pytest.mark.slow
def test_framework_entry_tensor_layout():
    """flash_attention takes paddle (B, S, H, D) Tensors and autodiffs
    through the framework tape."""
    import paddle_tpu as pt
    from paddle_tpu.ops.pallas_ops import flash_attention

    np.random.seed(0)
    q = pt.to_tensor(np.random.randn(2, 64, 2, 32).astype(np.float32),
                     stop_gradient=False)
    k = pt.to_tensor(np.random.randn(2, 64, 2, 32).astype(np.float32),
                     stop_gradient=False)
    v = pt.to_tensor(np.random.randn(2, 64, 2, 32).astype(np.float32),
                     stop_gradient=False)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    assert tuple(out.shape) == (2, 64, 2, 32)
    out.sum().backward()
    assert q.grad is not None and np.isfinite(q.grad.numpy()).all()

    ref = mha_reference(
        jnp.swapaxes(q._data, 1, 2), jnp.swapaxes(k._data, 1, 2),
        jnp.swapaxes(v._data, 1, 2), causal=True)
    np.testing.assert_allclose(
        np.asarray(jnp.swapaxes(out._data, 1, 2)), np.asarray(ref),
        atol=2e-3, rtol=2e-3)


class TestKernelAutotune:
    """Kernel-config autotune (ref: paddle/phi/kernels/autotune/): warmup
    timing picks a block config, the cache feeds later (traced) calls."""

    @pytest.mark.slow
    def test_tune_mha_populates_cache_and_outputs_match(self):
        import jax
        from paddle_tpu.ops import autotune as at
        from paddle_tpu.ops.pallas_ops import mha, tune_mha, mha_reference
        at.cache_clear()
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(1, 2, 64, 16).astype(np.float32))
        k = jnp.asarray(rng.randn(1, 2, 64, 16).astype(np.float32))
        v = jnp.asarray(rng.randn(1, 2, 64, 16).astype(np.float32))
        best, timings = tune_mha(q, k, v, causal=True, interpret=True,
                                 candidates=((128, 128), (64, 64)))
        assert best in timings and len(timings) >= 1
        # the cached choice drives default-config calls now
        key_hit = at.cache_get(
            "flash_mha", (64, 64, 16, "float32", True, True))
        assert key_hit == best
        out = mha(q, k, v, causal=True, interpret=True)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)

    def test_cache_roundtrip_and_set_config(self, tmp_path):
        from paddle_tpu.ops import autotune as at
        from paddle_tpu.incubate import autotune as iat
        at.cache_clear()
        at.cache_put("flash_mha", (128, 128, 64, "bfloat16", False, False),
                     (256, 128))
        p = str(tmp_path / "tune.json")
        iat.save_cache(p)
        at.cache_clear()
        assert at.cache_get(
            "flash_mha", (128, 128, 64, "bfloat16", False, False)) is None
        iat.load_cache(p)
        assert at.cache_get(
            "flash_mha",
            (128, 128, 64, "bfloat16", False, False)) == (256, 128)
        iat.set_config({"kernel": {"enable": True}})
        assert at.enabled()
        iat.set_config({"kernel": {"enable": False}})
        assert not at.enabled()
