"""Flash-attention Pallas kernel tests (interpret mode on CPU).

Mirrors the reference's flash-attn tests
(test/legacy_test/test_flash_attention.py): kernel output vs a plain
softmax-attention oracle, forward and gradients, causal and non-causal,
unaligned sequence lengths and head dims.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas_ops import mha, mha_reference


def _rand(shape, seed):
    return jnp.asarray(
        np.random.RandomState(seed).standard_normal(shape).astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize(
    "b,h,sq,skv,d",
    [
        (2, 2, 128, 128, 64),
        (1, 3, 256, 256, 128),
        (2, 1, 100, 100, 32),     # unaligned S and D → padding path
        (1, 2, 128, 256, 64),     # cross attention, kv longer
    ],
)
def test_flash_forward_matches_reference(causal, b, h, sq, skv, d):
    if causal and sq != skv:
        # causal cross-attn aligns at the end; still defined
        pass
    q, k, v = (_rand((b, h, s, d), i) for i, s in
               enumerate([sq, skv, skv]))
    out = mha(q, k, v, causal=causal, interpret=True, block_q=128,
              block_k=128)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)


@pytest.mark.slow
@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    b, h, s, d = 1, 2, 128, 64
    q, k, v = (_rand((b, h, s, d), 10 + i) for i in range(3))

    def loss_kernel(q, k, v):
        o = mha(q, k, v, causal=causal, interpret=True)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = mha_reference(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-2, rtol=2e-2)


@pytest.mark.slow
def test_flash_grads_unaligned():
    b, h, s, d = 1, 1, 72, 48
    q, k, v = (_rand((b, h, s, d), 20 + i) for i in range(3))

    def loss_kernel(q, k, v):
        return jnp.sum(mha(q, k, v, causal=True, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-2, rtol=2e-2)


def test_flash_bf16():
    b, h, s, d = 1, 2, 128, 64
    q, k, v = (_rand((b, h, s, d), 30 + i).astype(jnp.bfloat16)
               for i in range(3))
    out = mha(q, k, v, causal=True, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = mha_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                        v.astype(jnp.float32), causal=True)
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref), atol=3e-2, rtol=3e-2)


@pytest.mark.slow
def test_framework_entry_tensor_layout():
    """flash_attention takes paddle (B, S, H, D) Tensors and autodiffs
    through the framework tape."""
    import paddle_tpu as pt
    from paddle_tpu.ops.pallas_ops import flash_attention

    np.random.seed(0)
    q = pt.to_tensor(np.random.randn(2, 64, 2, 32).astype(np.float32),
                     stop_gradient=False)
    k = pt.to_tensor(np.random.randn(2, 64, 2, 32).astype(np.float32),
                     stop_gradient=False)
    v = pt.to_tensor(np.random.randn(2, 64, 2, 32).astype(np.float32),
                     stop_gradient=False)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    assert tuple(out.shape) == (2, 64, 2, 32)
    out.sum().backward()
    assert q.grad is not None and np.isfinite(q.grad.numpy()).all()

    ref = mha_reference(
        jnp.swapaxes(q._data, 1, 2), jnp.swapaxes(k._data, 1, 2),
        jnp.swapaxes(v._data, 1, 2), causal=True)
    np.testing.assert_allclose(
        np.asarray(jnp.swapaxes(out._data, 1, 2)), np.asarray(ref),
        atol=2e-3, rtol=2e-3)


class TestKernelAutotune:
    """Kernel-config autotune (ref: paddle/phi/kernels/autotune/): warmup
    timing picks a block config, the cache feeds later (traced) calls."""

    @pytest.mark.slow
    def test_tune_mha_populates_cache_and_outputs_match(self):
        import jax
        from paddle_tpu.ops import autotune as at
        from paddle_tpu.ops.pallas_ops import mha, tune_mha, mha_reference
        at.cache_clear()
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(1, 2, 64, 16).astype(np.float32))
        k = jnp.asarray(rng.randn(1, 2, 64, 16).astype(np.float32))
        v = jnp.asarray(rng.randn(1, 2, 64, 16).astype(np.float32))
        best, timings = tune_mha(q, k, v, causal=True, interpret=True,
                                 candidates=((128, 128), (64, 64)))
        assert best in timings and len(timings) >= 1
        # the cached choice drives default-config calls now
        key_hit = at.cache_get(
            "flash_mha", (64, 64, 16, "float32", True, True))
        assert key_hit == best
        out = mha(q, k, v, causal=True, interpret=True)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)

    def test_cache_roundtrip_and_set_config(self, tmp_path):
        from paddle_tpu.ops import autotune as at
        from paddle_tpu.incubate import autotune as iat
        at.cache_clear()
        at.cache_put("flash_mha", (128, 128, 64, "bfloat16", False, False),
                     (256, 128))
        p = str(tmp_path / "tune.json")
        iat.save_cache(p)
        at.cache_clear()
        assert at.cache_get(
            "flash_mha", (128, 128, 64, "bfloat16", False, False)) is None
        iat.load_cache(p)
        assert at.cache_get(
            "flash_mha",
            (128, 128, 64, "bfloat16", False, False)) == (256, 128)
        iat.set_config({"kernel": {"enable": True}})
        assert at.enabled()
        iat.set_config({"kernel": {"enable": False}})
        assert not at.enabled()


class TestFlashDropout:
    """In-kernel attention dropout (ref flash_attn dropout path,
    ``paddle/phi/kernels/gpu/flash_attn_kernel.cu``): the counter-based
    mask is deterministic given (seed, coords), so an exact oracle can
    rebuild it outside the kernel via _tile_keep_mask."""

    PD = 0.3

    def _setup(self, b=1, h=2, s=128, d=64):
        q, k, v = (_rand((b, h, s, d), i) for i in range(3))
        seed = jnp.asarray(1.2345, jnp.float32)
        return q, k, v, seed

    def _oracle(self, q, k, v, seed, pd):
        from paddle_tpu.ops.pallas_ops import _tile_keep_mask
        b, h, s, d = q.shape
        bh = b * h
        qq, kk, vv = (x.reshape(bh, s, d) for x in (q, k, v))
        p = jax.nn.softmax(
            jnp.einsum("bqd,bkd->bqk", qq, kk) / np.sqrt(d), axis=-1)
        s32 = jax.lax.bitcast_convert_type(seed, jnp.int32)
        M = jnp.stack([
            jnp.concatenate([
                jnp.concatenate([
                    _tile_keep_mask(s32, jnp.int32(bi), jnp.int32(qi),
                                    jnp.int32(ki), 128, 128, pd)
                    for ki in range(s // 128)], axis=1)
                for qi in range(s // 128)], axis=0)
            for bi in range(bh)])
        pt = jnp.where(M, p / (1 - pd), 0.0)
        return jnp.einsum("bqk,bkd->bqd", pt, vv).reshape(b, h, s, d)

    def test_forward_matches_mask_oracle(self):
        q, k, v, seed = self._setup()
        out = mha(q, k, v, dropout_p=self.PD, seed=seed, interpret=True,
                  block_q=128, block_k=128)
        ref = self._oracle(q, k, v, seed, self.PD)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.slow
    def test_grads_match_mask_oracle(self):
        q, k, v, seed = self._setup()
        g = jax.grad(lambda *a: (mha(*a[:3], dropout_p=self.PD, seed=a[3],
                                     interpret=True, block_q=128,
                                     block_k=128) ** 2).sum(),
                     argnums=(0, 1, 2))(q, k, v, seed)
        gr = jax.grad(lambda *a: (self._oracle(*a, self.PD) ** 2).sum(),
                      argnums=(0, 1, 2))(q, k, v, seed)
        for a, b_ in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=3e-4, rtol=3e-4)

    def test_keep_fraction_and_seed_sensitivity(self):
        from paddle_tpu.ops.pallas_ops import _tile_keep_mask
        s32 = jnp.int32(12345)
        m = _tile_keep_mask(s32, jnp.int32(0), jnp.int32(0), jnp.int32(0),
                            128, 128, self.PD)
        assert abs(float(m.mean()) - (1 - self.PD)) < 0.02
        m2 = _tile_keep_mask(jnp.int32(54321), jnp.int32(0), jnp.int32(0),
                             jnp.int32(0), 128, 128, self.PD)
        assert bool((m != m2).any())
        # different tiles get different masks
        m3 = _tile_keep_mask(s32, jnp.int32(0), jnp.int32(1), jnp.int32(0),
                             128, 128, self.PD)
        assert bool((m != m3).any())

    @pytest.mark.slow
    def test_dropout_changes_with_seed_and_zero_is_exact(self):
        q, k, v, _ = self._setup()
        o1 = mha(q, k, v, dropout_p=self.PD,
                 seed=jnp.asarray(1.0, jnp.float32), interpret=True)
        o2 = mha(q, k, v, dropout_p=self.PD,
                 seed=jnp.asarray(2.0, jnp.float32), interpret=True)
        assert float(jnp.abs(o1 - o2).max()) > 1e-4
        o0 = mha(q, k, v, dropout_p=0.0, interpret=True)
        np.testing.assert_allclose(np.asarray(o0),
                                   np.asarray(mha_reference(q, k, v)),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.slow
    def test_framework_entry_dropout_trains(self):
        """flash_attention with dropout through the tape: grads flow and
        two eager calls draw different masks (generator advances)."""
        import paddle_tpu as pt
        from paddle_tpu.ops.pallas_ops import flash_attention
        pt.seed(11)
        x = np.random.RandomState(0).randn(1, 128, 2, 64).astype(np.float32)
        q = pt.to_tensor(x, stop_gradient=False)
        o1 = flash_attention(q, pt.to_tensor(x), pt.to_tensor(x),
                             causal=True, dropout_p=0.4, interpret=True)
        o1.sum().backward()
        assert q.grad is not None and np.isfinite(q.grad.numpy()).all()
        o2 = flash_attention(pt.to_tensor(x), pt.to_tensor(x),
                             pt.to_tensor(x), causal=True, dropout_p=0.4,
                             interpret=True)
        assert float(np.abs(o1.numpy() - o2.numpy()).max()) > 1e-5


class TestVarlen:
    """Per-row kv-length masking (ref flash_attn_unpadded,
    ``python/paddle/nn/functional/flash_attention.py:272``)."""

    def _ref_padded(self, q, k, v, lens, causal=False):
        b, h, s, d = q.shape
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
        kcol = jnp.arange(s)[None, None, None, :]
        mask = kcol < jnp.asarray(lens)[:, None, None, None]
        if causal:
            qrow = jnp.arange(s)[None, None, :, None]
            mask = mask & (kcol <= qrow)
        logits = jnp.where(mask, logits, -jnp.inf)
        p = jax.nn.softmax(logits, axis=-1)
        p = jnp.where(jnp.isnan(p), 0.0, p)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    @pytest.mark.slow
    def test_forward_matches_masked_reference(self):
        q, k, v = (_rand((3, 2, 128, 64), i) for i in range(3))
        lens = np.array([128, 70, 1], np.int32)
        for causal in (False, True):
            out = mha(q, k, v, seq_lens=lens, causal=causal, interpret=True)
            ref = self._ref_padded(q, k, v, lens, causal)
            # only rows < len are meaningful
            for bi, L in enumerate(lens):
                np.testing.assert_allclose(
                    np.asarray(out)[bi, :, :L], np.asarray(ref)[bi, :, :L],
                    atol=2e-5, rtol=2e-5)

    @pytest.mark.slow
    def test_grads_match_masked_reference(self):
        q, k, v = (_rand((2, 2, 128, 64), i) for i in range(3))
        lens = np.array([100, 40], np.int32)

        def valid_loss(out):
            # padded query rows excluded, as a caller's loss mask would
            m = (jnp.arange(128)[None, :] < jnp.asarray(lens)[:, None])
            return ((out * m[:, None, :, None]) ** 2).sum()

        g = jax.grad(lambda *a: valid_loss(
            mha(*a, seq_lens=lens, interpret=True)), argnums=(0, 1, 2))(
                q, k, v)
        gr = jax.grad(lambda *a: valid_loss(
            self._ref_padded(*a, lens)), argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=3e-4, rtol=3e-4)

    @pytest.mark.slow
    def test_unpadded_api_packed_layout(self):
        import paddle_tpu as pt
        from paddle_tpu.nn.functional import flash_attn_unpadded
        rs = np.random.RandomState(3)
        lens = [60, 128, 13]
        cu = np.cumsum([0] + lens).astype(np.int32)
        total, h, d = int(cu[-1]), 2, 64
        qkv = [rs.randn(total, h, d).astype(np.float32) for _ in range(3)]
        out, _ = flash_attn_unpadded(
            pt.to_tensor(qkv[0]), pt.to_tensor(qkv[1]), pt.to_tensor(qkv[2]),
            pt.to_tensor(cu), pt.to_tensor(cu), 128, 128,
            scale=1.0 / np.sqrt(d))
        assert tuple(out.shape) == (total, h, d)
        # each packed sequence must equal standalone attention on itself
        for i in range(len(lens)):
            s0, s1 = int(cu[i]), int(cu[i + 1])
            qi = jnp.asarray(qkv[0][s0:s1])[None].swapaxes(1, 2)
            ki = jnp.asarray(qkv[1][s0:s1])[None].swapaxes(1, 2)
            vi = jnp.asarray(qkv[2][s0:s1])[None].swapaxes(1, 2)
            ref = mha_reference(qi, ki, vi)[0].swapaxes(0, 1)
            np.testing.assert_allclose(out.numpy()[s0:s1], np.asarray(ref),
                                       atol=2e-3, rtol=2e-3)

    def test_flash_attention_api(self):
        import paddle_tpu as pt
        from paddle_tpu.nn.functional.flash_attention import flash_attention
        x = np.random.RandomState(0).randn(1, 128, 2, 64).astype(np.float32)
        t = pt.to_tensor(x)
        out, sm = flash_attention(t, t, t, causal=True)
        assert sm is None and tuple(out.shape) == (1, 128, 2, 64)
        out2, sm2 = flash_attention(t, t, t, causal=True,
                                    return_softmax=True)
        assert sm2 is not None
        np.testing.assert_allclose(out.numpy(), out2.numpy(), atol=2e-3,
                                   rtol=2e-3)


def test_sdpa_flash_min_seq_gate(monkeypatch):
    """SDPA must keep short sequences on the XLA path (flash's padding +
    grid overhead loses below flash_min_seq: v5e BERT s=128 measured
    808 vs 750 seq/s) and route long ones to the kernel."""
    import paddle_tpu as pt
    import paddle_tpu.nn.functional.common as C

    calls = []
    monkeypatch.setattr(C, "_on_tpu", lambda: True)
    monkeypatch.setattr(C, "_flash_usable", lambda: True)

    import paddle_tpu.ops.pallas_ops as po
    real_fa = po.flash_attention

    def spy_fa(q, k, v, **kw):
        calls.append(tuple(q.shape))
        kw["interpret"] = True  # no real TPU in CI
        return real_fa(q, k, v, **kw)

    monkeypatch.setattr(po, "flash_attention", spy_fa)
    x_short = pt.to_tensor(np.ones((1, 128, 2, 64), np.float32))
    x_long = pt.to_tensor(np.ones((1, 512, 2, 64), np.float32))
    C.scaled_dot_product_attention(x_short, x_short, x_short)
    assert calls == []  # 128 < flash_min_seq -> XLA path
    C.scaled_dot_product_attention(x_long, x_long, x_long)
    assert calls == [(1, 512, 2, 64)]


class TestPackedVarlen:
    """True ragged varlen kernel (mha_packed): cross lengths, causal
    bottom-right alignment, tape grads, validation (ref
    ``python/paddle/nn/functional/flash_attention.py:272``)."""

    @staticmethod
    def _oracle(q, k, v, cu_q, cu_k, causal):
        d = q.shape[-1]
        out = np.zeros_like(q)
        for i in range(len(cu_q) - 1):
            qs, qe = cu_q[i], cu_q[i + 1]
            ks, ke = cu_k[i], cu_k[i + 1]
            qq = q[qs:qe].transpose(1, 0, 2)
            kk = k[ks:ke].transpose(1, 0, 2)
            vv = v[ks:ke].transpose(1, 0, 2)
            s = np.einsum("hqd,hkd->hqk", qq, kk) / np.sqrt(d)
            lq, lk = qe - qs, ke - ks
            if causal:
                mask = (np.arange(lk)[None, :]
                        <= np.arange(lq)[:, None] + (lk - lq))
                s = np.where(mask, s, -np.inf)
            with np.errstate(invalid="ignore"):
                p = np.exp(s - s.max(-1, keepdims=True))
                p = np.nan_to_num(p, nan=0.0)
                den = p.sum(-1, keepdims=True)
                p = np.where(den > 0, p / np.where(den > 0, den, 1.0), 0.0)
            out[qs:qe] = np.einsum("hqk,hkd->hqd", p, vv).transpose(1, 0, 2)
        return out

    @pytest.mark.slow
    def test_self_and_cross_all_modes(self):
        from paddle_tpu.ops.pallas_ops import mha_packed
        rs = np.random.RandomState(0)
        H, D = 2, 64
        cu = np.cumsum([0, 64, 200, 37]).astype(np.int32)
        cuk = np.cumsum([0, 80, 150, 100]).astype(np.int32)
        q = rs.randn(int(cu[-1]), H, D).astype(np.float32)
        k = rs.randn(int(cuk[-1]), H, D).astype(np.float32)
        v = rs.randn(int(cuk[-1]), H, D).astype(np.float32)
        for cu_k_used, kk, vv in ((cu, q, q), (cuk, k, v)):
            for causal in (False, True):
                got = np.asarray(mha_packed(
                    jnp.asarray(q), jnp.asarray(kk), jnp.asarray(vv),
                    jnp.asarray(cu), jnp.asarray(cu_k_used),
                    causal=causal, block_q=128, block_k=128,
                    interpret=True))
                want = self._oracle(q, kk, vv, cu, cu_k_used, causal)
                np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)

    @pytest.mark.slow
    def test_grads_vs_dense(self):
        from paddle_tpu.ops.pallas_ops import mha_packed
        rs = np.random.RandomState(1)
        H, D = 2, 64
        cu = np.cumsum([0, 50, 90]).astype(np.int32)
        q = jnp.asarray(rs.randn(int(cu[-1]), H, D).astype(np.float32))

        def loss(q, k, v):
            o = mha_packed(q, k, v, jnp.asarray(cu), jnp.asarray(cu),
                           causal=True, block_q=64, block_k=64,
                           interpret=True)
            return (o.astype(jnp.float32) ** 2).sum()

        g = jax.grad(loss, argnums=(0, 1, 2))(q, q, q)

        def dense(q, k, v):
            outs = []
            for i in range(len(cu) - 1):
                s0, s1 = int(cu[i]), int(cu[i + 1])
                qq = jnp.swapaxes(q[s0:s1], 0, 1)
                kk = jnp.swapaxes(k[s0:s1], 0, 1)
                vv = jnp.swapaxes(v[s0:s1], 0, 1)
                s = jnp.einsum("hqd,hkd->hqk", qq, kk) / np.sqrt(D)
                L = s1 - s0
                mask = jnp.tril(jnp.ones((L, L), bool))
                p = jax.nn.softmax(jnp.where(mask, s, -1e30), axis=-1)
                outs.append(jnp.swapaxes(
                    jnp.einsum("hqk,hkd->hqd", p, vv), 0, 1))
            return (jnp.concatenate(outs) ** 2).sum()

        gw = jax.grad(dense, argnums=(0, 1, 2))(q, q, q)
        for a, b_ in zip(g, gw):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=3e-4, rtol=3e-4)

    @pytest.mark.slow
    def test_unpadded_api_cross_lengths_and_validation(self):
        # small shapes on purpose: this is the FAST-tier guard for the
        # packed path; the full-size parity lives in the slow tier
        import paddle_tpu as pt
        from paddle_tpu.nn.functional import flash_attn_unpadded
        rs = np.random.RandomState(5)
        H, D = 1, 32
        cu = np.cumsum([0, 12, 20]).astype(np.int32)
        cuk = np.cumsum([0, 16, 10]).astype(np.int32)
        q = rs.randn(int(cu[-1]), H, D).astype(np.float32)
        k = rs.randn(int(cuk[-1]), H, D).astype(np.float32)
        v = rs.randn(int(cuk[-1]), H, D).astype(np.float32)
        out, _ = flash_attn_unpadded(
            pt.to_tensor(q), pt.to_tensor(k), pt.to_tensor(v),
            pt.to_tensor(cu), pt.to_tensor(cuk), 20, 16,
            scale=1.0 / np.sqrt(D))
        want = self._oracle(q, k, v, cu, cuk, False)
        np.testing.assert_allclose(out.numpy(), want, atol=2e-3, rtol=2e-3)
        # malformed cu raises eagerly (no NaN poison)
        bad = np.array([0, 25, 10], np.int32)
        with pytest.raises(ValueError):
            flash_attn_unpadded(
                pt.to_tensor(q), pt.to_tensor(k), pt.to_tensor(v),
                pt.to_tensor(bad), pt.to_tensor(cuk), 20, 16,
                scale=1.0 / np.sqrt(D))

    @pytest.mark.slow
    def test_unpadded_grad_through_tape(self):
        import paddle_tpu as pt
        from paddle_tpu.nn.functional import flash_attn_unpadded
        from paddle_tpu import Tensor
        rs = np.random.RandomState(6)
        cu = np.cumsum([0, 30, 50]).astype(np.int32)
        q = Tensor(rs.randn(int(cu[-1]), 2, 64).astype(np.float32),
                   stop_gradient=False)
        out, _ = flash_attn_unpadded(q, q, q, pt.to_tensor(cu),
                                     pt.to_tensor(cu), 50, 50, scale=0.125,
                                     causal=True)
        pt.sum(out * out).backward()
        assert q.grad is not None
        assert np.isfinite(np.asarray(q.grad._data)).all()


def test_packed_varlen_minimal_fast():
    """FAST-tier guard for the packed kernel itself: one tiny single-
    sequence forward (every capability keeps at least one fast test;
    the richer guard + parity suites are slow-tier)."""
    from paddle_tpu.ops.pallas_ops import mha_packed
    rs = np.random.RandomState(9)
    q = jnp.asarray(rs.randn(8, 1, 8).astype(np.float32))
    cu = jnp.asarray(np.array([0, 8], np.int32))
    got = np.asarray(mha_packed(q, q, q, cu, cu, causal=True, block_q=8,
                                block_k=8, interpret=True))[:, 0]
    qq = np.asarray(q)[:, 0]
    lg = qq @ qq.T / np.sqrt(8)
    lg = np.where(np.tril(np.ones_like(lg, dtype=bool)), lg, -1e30)
    pr = np.exp(lg - lg.max(-1, keepdims=True))
    pr /= pr.sum(-1, keepdims=True)
    np.testing.assert_allclose(got, pr @ qq, atol=2e-5)


@pytest.mark.slow
def test_packed_varlen_fast_guard():
    """Minimal fast-tier guard for the packed path: ONE tiny kernel call
    (single cross pair) + the eager cu validation. Full parity suites
    are slow-tier."""
    import paddle_tpu as pt
    from paddle_tpu.ops.pallas_ops import mha_packed
    rs = np.random.RandomState(7)
    cu = np.array([0, 10], np.int32)
    cuk = np.array([0, 14], np.int32)
    q = jnp.asarray(rs.randn(10, 1, 16).astype(np.float32))
    k = jnp.asarray(rs.randn(14, 1, 16).astype(np.float32))
    v = jnp.asarray(rs.randn(14, 1, 16).astype(np.float32))
    got = np.asarray(mha_packed(q, k, v, jnp.asarray(cu), jnp.asarray(cuk),
                                causal=False, block_q=16, block_k=16,
                                interpret=True))
    s = np.einsum("qhd,khd->hqk", np.asarray(q), np.asarray(k)) / 4.0
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.einsum("hqk,khd->qhd", p, np.asarray(v))
    np.testing.assert_allclose(got, want, atol=2e-5)
    from paddle_tpu.nn.functional.flash_attention import _validate_cu
    with pytest.raises(ValueError):
        _validate_cu(np.array([0, 20, 10], np.int32), 14, "cu_seqlens_k")


class TestPackedFallback:
    """The padded-XLA fallback behind ``_packed_usable`` must match the
    packed kernel exactly — it is what a jitted train step silently
    drops to when the kernel cannot lower on real TPU."""

    def _force_fallback(self, monkeypatch):
        from paddle_tpu.nn.functional import flash_attention as fa_mod
        from paddle_tpu.ops import pallas_ops
        from paddle_tpu.nn.functional import common
        monkeypatch.setattr(pallas_ops, "_interpret_default", lambda: False)
        monkeypatch.setattr(common, "_on_tpu", lambda: False)
        # the canary verdict must not leak between forced/unforced runs
        monkeypatch.setattr(common, "_CANARY_CACHE", {})
        del fa_mod  # gate lives in common's shared cache now

    def _run(self, causal):
        import paddle_tpu as pt
        from paddle_tpu.nn.functional import flash_attn_unpadded
        rs = np.random.RandomState(11)
        H, D = 2, 32
        cu = np.cumsum([0, 12, 20, 7]).astype(np.int32)
        cuk = np.cumsum([0, 16, 10, 7]).astype(np.int32)
        q = rs.randn(int(cu[-1]), H, D).astype(np.float32)
        k = rs.randn(int(cuk[-1]), H, D).astype(np.float32)
        v = rs.randn(int(cuk[-1]), H, D).astype(np.float32)
        out, _ = flash_attn_unpadded(
            pt.to_tensor(q), pt.to_tensor(k), pt.to_tensor(v),
            pt.to_tensor(cu), pt.to_tensor(cuk), 20, 16,
            scale=1.0 / np.sqrt(D), causal=causal)
        return out.numpy()

    @pytest.mark.slow
    @pytest.mark.parametrize("causal", [False, True])
    def test_fallback_matches_kernel(self, monkeypatch, causal):
        want = self._run(causal)           # kernel (interpret) path
        self._force_fallback(monkeypatch)
        got = self._run(causal)            # padded-XLA fallback path
        np.testing.assert_allclose(got, want, atol=2e-3, rtol=2e-3)

    @pytest.mark.slow
    def test_fallback_grads_finite(self, monkeypatch):
        self._force_fallback(monkeypatch)
        import paddle_tpu as pt
        from paddle_tpu.nn.functional import flash_attn_unpadded
        from paddle_tpu import Tensor
        rs = np.random.RandomState(12)
        cu = np.cumsum([0, 9, 15]).astype(np.int32)
        q = Tensor(rs.randn(int(cu[-1]), 1, 16).astype(np.float32),
                   stop_gradient=False)
        out, _ = flash_attn_unpadded(q, q, q, pt.to_tensor(cu),
                                     pt.to_tensor(cu), 15, 15, scale=0.25,
                                     causal=True)
        pt.sum(out * out).backward()
        assert q.grad is not None
        assert np.isfinite(np.asarray(q.grad._data)).all()

    @pytest.mark.slow
    def test_fallback_dropout_scales(self, monkeypatch):
        self._force_fallback(monkeypatch)
        import paddle_tpu as pt
        from paddle_tpu.nn.functional import flash_attn_unpadded
        rs = np.random.RandomState(13)
        cu = np.cumsum([0, 64]).astype(np.int32)
        q = rs.randn(64, 1, 16).astype(np.float32)
        out, _ = flash_attn_unpadded(
            pt.to_tensor(q), pt.to_tensor(q), pt.to_tensor(q),
            pt.to_tensor(cu), pt.to_tensor(cu), 64, 64, scale=0.25,
            dropout=0.5, training=True)
        a = out.numpy()
        assert np.isfinite(a).all()
        # dropout must actually do something (some outputs differ from
        # the deterministic run)
        det, _ = flash_attn_unpadded(
            pt.to_tensor(q), pt.to_tensor(q), pt.to_tensor(q),
            pt.to_tensor(cu), pt.to_tensor(cu), 64, 64, scale=0.25,
            dropout=0.0)
        assert np.abs(a - det.numpy()).max() > 1e-4


def test_fallback_matches_oracle_fast(monkeypatch):
    """FAST-tier guard for the padded-XLA fallback: tiny shapes, no
    kernel (interpret-mode pallas is what makes the parity suite slow
    — that cross-check lives in the slow tier)."""
    import paddle_tpu as pt
    from paddle_tpu.nn.functional import flash_attn_unpadded
    TestPackedFallback()._force_fallback(monkeypatch)
    rs = np.random.RandomState(21)
    H, D = 1, 16
    cu = np.cumsum([0, 6, 10]).astype(np.int32)
    q = rs.randn(int(cu[-1]), H, D).astype(np.float32)
    out, _ = flash_attn_unpadded(
        pt.to_tensor(q), pt.to_tensor(q), pt.to_tensor(q),
        pt.to_tensor(cu), pt.to_tensor(cu), 10, 10, scale=1.0 / np.sqrt(D),
        causal=True)
    outs = []
    for b in range(2):
        s_, e_ = int(cu[b]), int(cu[b + 1])
        qq = q[s_:e_, 0]
        lg = qq @ qq.T / np.sqrt(D)
        lg = np.where(np.tril(np.ones_like(lg, dtype=bool)), lg, -1e30)
        p_ = np.exp(lg - lg.max(-1, keepdims=True))
        p_ /= p_.sum(-1, keepdims=True)
        outs.append((p_ @ qq)[:, None, :])
    np.testing.assert_allclose(out.numpy(), np.concatenate(outs),
                               atol=2e-3, rtol=2e-3)


def test_unpadded_rejects_understated_max_seqlen():
    """max_seqlen is load-bearing on the fallback path — understating it
    must raise eagerly on BOTH paths, not silently truncate."""
    import paddle_tpu as pt
    from paddle_tpu.nn.functional import flash_attn_unpadded
    rs = np.random.RandomState(3)
    cu = np.cumsum([0, 10, 30]).astype(np.int32)
    q = rs.randn(int(cu[-1]), 1, 16).astype(np.float32)
    with pytest.raises(ValueError, match="longest sequence"):
        flash_attn_unpadded(
            pt.to_tensor(q), pt.to_tensor(q), pt.to_tensor(q),
            pt.to_tensor(cu), pt.to_tensor(cu), 16, 30, scale=0.25)
