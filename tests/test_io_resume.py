"""Mid-epoch data-pipeline resume: DataLoader/sampler ``state_dict``
round-trips, the bit-identical loss-trajectory pin, the
CheckpointManager ``data_state`` ride-along, and the SIGKILLed-worker
diagnostic (never a hang).

The contract under test: interrupt a shuffled multi-epoch run
anywhere, persist ``DataLoader.state_dict()`` beside the params,
rebuild the pipeline from scratch, ``load_state_dict()``, and the
remaining batches — hence the loss trajectory — are bit-identical to
an uninterrupted oracle: no replayed and no skipped samples.
"""
from __future__ import annotations

import multiprocessing
import os
import signal
import time

import numpy as np
import pytest

from paddle_tpu.io import DataLoader
from paddle_tpu.io.dataset import Dataset
from paddle_tpu.io.sampler import (BatchSampler, DistributedBatchSampler,
                                   RandomSampler)


class _Arange(Dataset):
    def __init__(self, n=24, dim=3):
        self.n, self.dim = n, dim

    def __getitem__(self, i):
        return np.full((self.dim,), float(i), np.float64)

    def __len__(self):
        return self.n


def _sampler(n=24, batch_size=2):
    return DistributedBatchSampler(_Arange(n), batch_size=batch_size,
                                   num_replicas=1, rank=0, shuffle=True)


def _loader(n=24, batch_size=2):
    ds = _Arange(n)
    return DataLoader(ds, batch_sampler=_sampler(n, batch_size))


def _train(loader, w, total_batches):
    """Deterministic numpy 'training': returns the per-batch loss
    trajectory; mutates ``w`` in place.  Pure fp64 arithmetic, so two
    runs over the same batch sequence are bit-identical."""
    losses = []
    while len(losses) < total_batches:
        for batch in loader:
            x = np.asarray(batch._data, np.float64)
            g = x.mean(axis=0)
            losses.append(float(np.dot(w, g)))
            w -= 0.01 * g
            if len(losses) >= total_batches:
                break
    return losses


# -- the pinned acceptance test ----------------------------------------------

def test_resumed_loss_trajectory_is_bit_identical_to_oracle():
    """Tier-1 pin: interrupt a shuffled 3-epoch run mid-epoch-1, resume
    through a FRESH DataLoader from state_dict() — every remaining loss
    is bit-identical (exact float equality) to the uninterrupted
    oracle's."""
    epochs, per_epoch = 3, len(_sampler())
    total = epochs * per_epoch

    oracle_w = np.zeros(3, np.float64)
    oracle = _train(_loader(), oracle_w, total)
    assert len(set(oracle)) > 1  # the trajectory actually moves

    # interrupted run: stop mid-epoch-1 (an awkward, non-boundary spot)
    stop = per_epoch + 3
    w = np.zeros(3, np.float64)
    first_leg = _train(_loader_with_capture := _loader(), w, stop)
    state = _loader_with_capture.state_dict()
    assert state["delivered"] == 3  # 3 batches into epoch 1
    assert state["sampler"] == {"epoch": 1, "cursor": 3}

    resumed = _loader()  # brand-new pipeline, as after a real restart
    resumed.load_state_dict(state)
    second_leg = _train(resumed, w, total - stop)

    assert first_leg + second_leg == oracle  # bit-identical, all 36
    np.testing.assert_array_equal(w, oracle_w)


def test_resume_at_exact_epoch_boundary_rolls_over():
    per_epoch = len(_sampler())
    loader = _loader()
    w = np.zeros(3, np.float64)
    _train(loader, w, per_epoch)  # exactly one full epoch
    state = loader.state_dict()
    assert state["sampler"]["cursor"] == per_epoch

    oracle = _train(_loader(), np.zeros(3, np.float64), 2 * per_epoch)
    resumed = _loader()
    resumed.load_state_dict(state)
    # the rollover must start epoch 1 at cursor 0 — not replay epoch 0
    # and not skip epoch 1
    assert _train(resumed, w, per_epoch) == oracle[per_epoch:]


def test_skipped_batches_fetch_no_data():
    fetched = []

    class Spy(_Arange):
        def __getitem__(self, i):
            fetched.append(i)
            return super().__getitem__(i)

    sampler = _sampler()
    loader = DataLoader(Spy(), batch_sampler=sampler)
    loader.load_state_dict(
        {"delivered": 4, "sampler": {"epoch": 0, "cursor": 4}})
    batches = list(loader)
    assert len(batches) == len(sampler) - 4
    # index-level skip: the 8 samples of the 4 skipped batches were
    # never touched
    assert len(fetched) == 2 * len(batches)


# -- sampler state round-trips ------------------------------------------------

def test_batch_sampler_state_roundtrip():
    bs = BatchSampler(_Arange(10), batch_size=2)
    it = iter(bs)
    first = [next(it), next(it)]
    assert first == [[0, 1], [2, 3]]
    assert bs.state_dict() == {"cursor": 2}
    bs2 = BatchSampler(_Arange(10), batch_size=2)
    bs2.load_state_dict(bs.state_dict())
    assert list(bs2) == [[4, 5], [6, 7], [8, 9]]
    # a full-epoch cursor wraps to a fresh epoch
    bs3 = BatchSampler(_Arange(10), batch_size=2)
    bs3.load_state_dict({"cursor": 5})
    assert list(bs3) == [[0, 1], [2, 3], [4, 5], [6, 7], [8, 9]]


def test_distributed_batch_sampler_permutation_is_epoch_pure():
    a, b = _sampler(), _sampler()
    assert list(a) == list(b)  # same epoch → same permutation
    b.set_epoch(5)
    epoch5 = list(b)
    assert epoch5 != list(a)   # different epoch → different permutation
    b.set_epoch(5)
    assert list(b) == epoch5   # and it is a pure function of the epoch


def test_random_sampler_honors_generator():
    order1 = list(RandomSampler(_Arange(16),
                                generator=np.random.RandomState(7)))
    order2 = list(RandomSampler(_Arange(16),
                                generator=np.random.RandomState(7)))
    assert order1 == order2
    assert sorted(order1) == list(range(16))
    order3 = list(RandomSampler(_Arange(16),
                                generator=np.random.default_rng(7)))
    assert sorted(order3) == list(range(16))


# -- CheckpointManager data_state ride-along ----------------------------------

@pytest.mark.parametrize("async_save", [False, True])
def test_checkpoint_manager_persists_data_state(tmp_path, async_save):
    from paddle_tpu.distributed.checkpoint_manager import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), process_index=0, world_size=1,
                            async_save=async_save)
    loader = _loader()
    w = np.zeros(3, np.float64)
    _train(loader, w, 5)
    mgr.save(5, {"w": w}, data_state=loader.state_dict(),
             block=async_save)
    mgr.wait()

    mgr2 = CheckpointManager(str(tmp_path), process_index=0, world_size=1)
    ds = mgr2.load_data_state()
    assert ds == loader.state_dict()
    resumed = _loader()
    resumed.load_state_dict(ds)
    total = 3 * len(_sampler())
    oracle = _train(_loader(), np.zeros(3, np.float64), total)
    assert _train(resumed, w, total - 5) == oracle[5:]


def test_checkpoint_without_data_state_loads_none(tmp_path):
    from paddle_tpu.distributed.checkpoint_manager import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), process_index=0, world_size=1)
    mgr.save(1, {"w": np.arange(4.0)})
    assert mgr.load_data_state() is None
    assert mgr.load_data_state(step=99) is None


# -- dead multiprocess worker: named diagnostic, never a hang -----------------

@pytest.mark.skipif(os.name != "posix", reason="SIGKILLs a real worker")
def test_sigkilled_worker_raises_naming_worker_and_batch():
    """Regression pin for the dead-worker path: SIGKILL one worker
    mid-epoch → the iterator raises within its timeout naming the
    worker id, its pid, and the last batch index dispatched to it —
    it must never hang."""

    class Slow(_Arange):  # locally defined → unpicklable → fork ctx
        def __getitem__(self, i):
            time.sleep(0.05)
            return super().__getitem__(i)

    before = set(multiprocessing.active_children())
    loader = DataLoader(Slow(64), batch_size=2, num_workers=2,
                        use_shared_memory=False, timeout=60)
    it = iter(loader)
    next(it)
    workers = [p for p in multiprocessing.active_children()
               if p not in before]
    assert len(workers) == 2
    victim = workers[0]
    os.kill(victim.pid, signal.SIGKILL)
    t0 = time.monotonic()
    with pytest.raises(RuntimeError) as ei:
        for _ in it:
            pass
    elapsed = time.monotonic() - t0
    msg = str(ei.value)
    assert "exited unexpectedly" in msg
    assert f"pid {victim.pid}" in msg
    assert "last dispatched batch index" in msg
    assert elapsed < 30, f"dead-worker detection took {elapsed:.0f}s"
