"""Per-op OpTest corpus, part 2: losses, norms, pools, convs, misc
(ref: ``test/legacy_test/eager_op_test.py:377`` + per-op tolerance
tables ``test/white_list/op_accuracy_white_list.py``). Same declarative
scheme as test_op_suite.py; rows here cover the nn.functional callables
that part 1 does not."""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu import Tensor
from op_test import check_output, check_grad


def _sp(*shape, seed=0, pos=False, lo=-2.0, hi=2.0):
    rng = np.random.RandomState(seed)
    a = rng.uniform(lo, hi, shape).astype(np.float32)
    if pos:
        a = np.abs(a) + 0.5
    return a


def _softmax_np(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def _sig(x):
    return 1 / (1 + np.exp(-x))


# fixed auxiliary data (labels etc.) closed over so check_grad only
# perturbs the float inputs
_LBL = np.random.RandomState(7).randint(0, 5, (4,))
_LBL_T = pt.to_tensor(_LBL)
_BIN = np.random.RandomState(8).uniform(0.1, 0.9, (4, 5)).astype(np.float32)
_BIN01 = (np.random.RandomState(9).rand(4, 5) > 0.5).astype(np.float32)
_PM1 = np.where(np.random.RandomState(10).rand(4) > 0.5, 1., -1.).astype(
    np.float32)

_W1D = _sp(3, 2, 3, seed=21)          # conv1d weight (Cout, Cin, K)
_W3D = _sp(2, 2, 2, 2, 2, seed=22)    # conv3d weight
_W2T = _sp(2, 3, 3, 3, seed=23)       # conv2d_transpose weight (Cin, Cout, K, K)
_W1T = _sp(2, 3, 3, seed=24)
_W3T = _sp(2, 2, 2, 2, 2, seed=25)
_EMB = _sp(10, 4, seed=26)
_BILIN_W = _sp(3, 4, 5, seed=27)      # bilinear (out, in1, in2)


def _conv1d_np(x, w):
    b, ci, L = x.shape
    co, _, k = w.shape
    out = np.zeros((b, co, L - k + 1), np.float64)
    for i in range(L - k + 1):
        out[:, :, i] = np.einsum("bck,ock->bo", x[:, :, i:i + k], w)
    return out


def _conv3d_np(x, w):
    b, ci, D, H, W = x.shape
    co, _, kd, kh, kw = w.shape
    out = np.zeros((b, co, D - kd + 1, H - kh + 1, W - kw + 1), np.float64)
    for z in range(out.shape[2]):
        for i in range(out.shape[3]):
            for j in range(out.shape[4]):
                patch = x[:, :, z:z + kd, i:i + kh, j:j + kw]
                out[:, :, z, i, j] = np.einsum("bcdhw,ocdhw->bo", patch, w)
    return out


def _convt_np(x, w, dims):
    """Transposed conv via scatter-accumulate, stride 1, no padding.
    w layout (Cin, Cout, *K)."""
    b, ci = x.shape[:2]
    co = w.shape[1]
    insp = x.shape[2:]
    ksp = w.shape[2:]
    outsp = tuple(i + k - 1 for i, k in zip(insp, ksp))
    out = np.zeros((b, co) + outsp, np.float64)
    for idx in np.ndindex(*insp):
        val = x[(slice(None), slice(None)) + idx]  # (b, ci)
        contrib = np.einsum("bc,co...->bo...", val, w)
        sl = tuple(slice(i, i + k) for i, k in zip(idx, ksp))
        out[(slice(None), slice(None)) + sl] += contrib
    return out


def _avgpool_np(x, k, nd):
    sp = x.shape[2:]
    osp = tuple(s // k for s in sp)
    out = np.zeros(x.shape[:2] + osp, np.float64)
    for idx in np.ndindex(*osp):
        sl = tuple(slice(i * k, i * k + k) for i in idx)
        out[(...,) + idx] = x[(...,) + sl].mean(
            axis=tuple(range(2, 2 + nd)))
    return out


def _maxpool_np(x, k, nd):
    sp = x.shape[2:]
    osp = tuple(s // k for s in sp)
    out = np.zeros(x.shape[:2] + osp, np.float64)
    for idx in np.ndindex(*osp):
        sl = tuple(slice(i * k, i * k + k) for i in idx)
        out[(...,) + idx] = x[(...,) + sl].max(
            axis=tuple(range(2, 2 + nd)))
    return out


def _group_norm_np(x, g, eps=1e-5):
    b, c = x.shape[:2]
    xs = x.reshape(b, g, -1)
    m = xs.mean(-1, keepdims=True)
    v = xs.var(-1, keepdims=True)
    return ((xs - m) / np.sqrt(v + eps)).reshape(x.shape)


def _lrn_np(x, size, alpha=1e-4, beta=0.75, k=1.0):
    sq = x ** 2
    c = x.shape[1]
    div = np.zeros_like(x)
    half = size // 2
    for i in range(c):
        lo, hi = max(0, i - half), min(c, i - half + size)
        div[:, i] = sq[:, lo:hi].sum(axis=1)
    return x / (k + alpha * div / size) ** beta


OPS = [
    # -- activation variants ------------------------------------------------
    ("swish", F.swish, lambda x: x * _sig(x), [_sp(3, 4)], {}),
    ("thresholded_relu", F.thresholded_relu,
     lambda x: np.where(x > 1.0, x, 0), [_sp(3, 4)], {"grad": False}),
    ("stanh", F.stanh,
     lambda x: 1.7159 * np.tanh(0.67 * x), [_sp(3, 4)], {}),
    ("prelu", lambda x: F.prelu(x, pt.to_tensor(np.float32([0.25]))),
     lambda x: np.where(x > 0, x, 0.25 * x), [_sp(3, 4)], {"grad": False}),
    ("glu", F.glu,
     lambda x: x[:, :2] * _sig(x[:, 2:]), [_sp(3, 4)], {}),
    ("maxout", lambda x: F.maxout(x, groups=2),
     lambda x: x.reshape(2, 2, 2, 3, 3).max(2).reshape(2, 2, 3, 3),
     [_sp(2, 4, 3, 3)], {"grad": False}),
    ("relu_", lambda x: F.relu_(x.clone()),
     lambda x: np.maximum(x, 0), [_sp(3, 4)], {"grad": False}),
    ("tanh_", lambda x: F.tanh_(x.clone()), np.tanh, [_sp(3, 4)],
     {"grad": False}),
    ("elu_", lambda x: F.elu_(x.clone()),
     lambda x: np.where(x > 0, x, np.exp(np.minimum(x, 0)) - 1),
     [_sp(3, 4)], {"grad": False}),
    ("softmax_", lambda x: F.softmax_(x.clone()), _softmax_np,
     [_sp(3, 5)], {"grad": False}),
    # -- losses -------------------------------------------------------------
    ("cross_entropy", lambda x: F.cross_entropy(x, _LBL_T),
     lambda x: -np.log(_softmax_np(x))[np.arange(4), _LBL].mean(),
     [_sp(4, 5)], {}),
    ("softmax_with_cross_entropy",
     lambda x: F.softmax_with_cross_entropy(x, pt.to_tensor(_LBL[:, None])),
     lambda x: -np.log(_softmax_np(x))[np.arange(4), _LBL][:, None],
     [_sp(4, 5)], {}),
    ("binary_cross_entropy",
     lambda x: F.binary_cross_entropy(x, pt.to_tensor(_BIN01)),
     lambda x: -(_BIN01 * np.log(x) + (1 - _BIN01) * np.log(1 - x)).mean(),
     [_BIN], {}),
    ("binary_cross_entropy_with_logits",
     lambda x: F.binary_cross_entropy_with_logits(x, pt.to_tensor(_BIN01)),
     lambda x: (np.maximum(x, 0) - x * _BIN01 + np.log1p(
         np.exp(-np.abs(x)))).mean(),
     [_sp(4, 5)], {}),
    ("nll_loss", lambda x: F.nll_loss(x, _LBL_T),
     lambda x: -x[np.arange(4), _LBL].mean(), [_sp(4, 5)], {}),
    ("smooth_l1_loss",
     lambda x: F.smooth_l1_loss(x, pt.to_tensor(_BIN)),
     lambda x: np.where(np.abs(x - _BIN) < 1.0,
                        0.5 * (x - _BIN) ** 2,
                        np.abs(x - _BIN) - 0.5).mean(),
     [_sp(4, 5)], {}),
    ("square_error_cost",
     lambda x: F.square_error_cost(x, pt.to_tensor(_BIN)),
     lambda x: (x - _BIN) ** 2, [_sp(4, 5)], {}),
    ("log_loss",
     lambda x: F.log_loss(x, pt.to_tensor(_BIN01[:, :1])),
     lambda x: -(_BIN01[:, :1] * np.log(x + 1e-4)
                 + (1 - _BIN01[:, :1]) * np.log(1 - x + 1e-4)),
     [_sp(4, 1, lo=0.2, hi=0.8)], {}),
    ("soft_margin_loss",
     lambda x: F.soft_margin_loss(x, pt.to_tensor(np.tile(_PM1, (5, 1)).T)),
     lambda x: np.log1p(np.exp(-np.tile(_PM1, (5, 1)).T * x)).mean(),
     [_sp(4, 5)], {}),
    ("multi_label_soft_margin_loss",
     lambda x: F.multi_label_soft_margin_loss(x, pt.to_tensor(_BIN01)),
     lambda x: -(_BIN01 * np.log(_sig(x)) + (1 - _BIN01) * np.log(
         _sig(-x))).mean(axis=-1).mean(),
     [_sp(4, 5)], {}),
    ("sigmoid_focal_loss",
     lambda x: F.sigmoid_focal_loss(x, pt.to_tensor(_BIN01),
                                    reduction="mean"),
     lambda x: np.mean(
         (0.25 * _BIN01 + 0.75 * (1 - _BIN01))
         * ((1 - (_sig(x) * _BIN01 + (1 - _sig(x)) * (1 - _BIN01))) ** 2.0)
         * (np.maximum(x, 0) - x * _BIN01 + np.log1p(np.exp(-np.abs(x))))),
     [_sp(4, 5)], {"grad_atol": 2e-2}),
    ("dice_loss",
     lambda x: F.dice_loss(x, pt.to_tensor(_LBL[:, None].astype(np.int64))),
     None, [_softmax_np(_sp(4, 5))], {"ref_self": True}),
    ("margin_ranking_loss",
     lambda x, y: F.margin_ranking_loss(x, y, pt.to_tensor(_PM1)),
     lambda x, y: np.maximum(-_PM1 * (x - y), 0).mean(),
     [_sp(4), _sp(4, seed=1)], {"grad": False}),
    ("hinge_embedding_loss",
     lambda x: F.hinge_embedding_loss(x, pt.to_tensor(_PM1)),
     lambda x: np.where(_PM1 > 0, x, np.maximum(0, 1.0 - x)).mean(),
     [_sp(4)], {"grad": False}),
    ("cosine_embedding_loss",
     lambda x, y: F.cosine_embedding_loss(x, y, pt.to_tensor(_PM1)),
     lambda x, y: np.where(
         _PM1 > 0,
         1 - (x * y).sum(-1) / (np.linalg.norm(x, axis=-1)
                                * np.linalg.norm(y, axis=-1)),
         np.maximum(0, (x * y).sum(-1) / (np.linalg.norm(x, axis=-1)
                                          * np.linalg.norm(y, axis=-1)))
     ).mean(),
     [_sp(4, 3), _sp(4, 3, seed=1)], {}),
    ("triplet_margin_loss",
     lambda a, p, n: F.triplet_margin_loss(a, p, n),
     lambda a, p, n: np.maximum(
         np.linalg.norm(a - p, axis=-1) - np.linalg.norm(a - n, axis=-1)
         + 1.0, 0).mean(),
     [_sp(4, 3), _sp(4, 3, seed=1), _sp(4, 3, seed=2)], {}),
    ("triplet_margin_with_distance_loss",
     lambda a, p, n: F.triplet_margin_with_distance_loss(a, p, n),
     lambda a, p, n: np.maximum(
         np.linalg.norm(a - p, axis=-1) - np.linalg.norm(a - n, axis=-1)
         + 1.0, 0).mean(),
     [_sp(4, 3), _sp(4, 3, seed=1), _sp(4, 3, seed=2)], {}),
    ("poisson_nll_loss",
     lambda x: F.poisson_nll_loss(x, pt.to_tensor(np.abs(_BIN))),
     lambda x: (np.exp(x) - np.abs(_BIN) * x).mean(),
     [_sp(4, 5)], {}),
    ("gaussian_nll_loss",
     lambda x: F.gaussian_nll_loss(x, pt.to_tensor(_BIN),
                                   pt.to_tensor(np.abs(_BIN) + 0.5)),
     lambda x: (0.5 * (np.log(np.abs(_BIN) + 0.5)
                       + (x - _BIN) ** 2 / (np.abs(_BIN) + 0.5))).mean(),
     [_sp(4, 5)], {}),
    ("npair_loss",
     lambda a, p: F.npair_loss(a, p, pt.to_tensor(_LBL)),
     None, [_sp(4, 3), _sp(4, 3, seed=1)], {"ref_self": True}),
    # -- norms --------------------------------------------------------------
    ("normalize", F.normalize,
     lambda x: x / np.maximum(np.linalg.norm(x, axis=1, keepdims=True),
                              1e-12),
     [_sp(3, 4)], {}),
    ("rms_norm", F.rms_norm,
     lambda x: x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6),
     [_sp(3, 4)], {}),
    ("group_norm", lambda x: F.group_norm(x, num_groups=2),
     lambda x: _group_norm_np(x, 2), [_sp(2, 4, 3)], {"grad_atol": 2e-2}),
    ("instance_norm", lambda x: F.instance_norm(x),
     lambda x: (x - x.mean((2, 3), keepdims=True))
     / np.sqrt(x.var((2, 3), keepdims=True) + 1e-5),
     [_sp(2, 3, 4, 4)], {"grad_atol": 2e-2}),
    ("batch_norm_eval",
     lambda x: F.batch_norm(
         x, pt.to_tensor(np.zeros(3, np.float32)),
         pt.to_tensor(np.ones(3, np.float32)), training=False),
     lambda x: x / np.sqrt(1 + 1e-5),
     [_sp(2, 3, 4)], {}),
    ("local_response_norm",
     lambda x: F.local_response_norm(x, size=3),
     lambda x: _lrn_np(x, 3), [_sp(2, 5, 4)], {"grad_atol": 2e-2}),
    # -- pools --------------------------------------------------------------
    ("avg_pool1d", lambda x: F.avg_pool1d(x, kernel_size=2, stride=2),
     lambda x: _avgpool_np(x, 2, 1), [_sp(2, 3, 8)], {}),
    ("avg_pool3d", lambda x: F.avg_pool3d(x, kernel_size=2, stride=2),
     lambda x: _avgpool_np(x, 2, 3), [_sp(1, 2, 4, 4, 4)], {}),
    ("max_pool1d", lambda x: F.max_pool1d(x, kernel_size=2, stride=2),
     lambda x: _maxpool_np(x, 2, 1), [_sp(2, 3, 8)], {"grad": False}),
    ("max_pool3d", lambda x: F.max_pool3d(x, kernel_size=2, stride=2),
     lambda x: _maxpool_np(x, 2, 3), [_sp(1, 2, 4, 4, 4)],
     {"grad": False}),
    ("adaptive_avg_pool1d", lambda x: F.adaptive_avg_pool1d(x, 2),
     lambda x: _avgpool_np(x, 4, 1), [_sp(2, 3, 8)], {}),
    ("adaptive_avg_pool2d", lambda x: F.adaptive_avg_pool2d(x, 2),
     lambda x: _avgpool_np(x, 2, 2), [_sp(2, 3, 4, 4)], {}),
    ("adaptive_avg_pool3d", lambda x: F.adaptive_avg_pool3d(x, 2),
     lambda x: _avgpool_np(x, 2, 3), [_sp(1, 2, 4, 4, 4)], {}),
    ("adaptive_max_pool1d", lambda x: F.adaptive_max_pool1d(x, 2),
     lambda x: _maxpool_np(x, 4, 1), [_sp(2, 3, 8)], {"grad": False}),
    ("adaptive_max_pool2d", lambda x: F.adaptive_max_pool2d(x, 2),
     lambda x: _maxpool_np(x, 2, 2), [_sp(2, 3, 4, 4)], {"grad": False}),
    ("adaptive_max_pool3d", lambda x: F.adaptive_max_pool3d(x, 2),
     lambda x: _maxpool_np(x, 2, 3), [_sp(1, 2, 4, 4, 4)],
     {"grad": False}),
    ("lp_pool1d", lambda x: F.lp_pool1d(x, 2, kernel_size=2, stride=2),
     lambda x: np.sqrt((_avgpool_np(x ** 2, 2, 1)) * 2),
     [_sp(2, 3, 8, pos=True)], {}),
    ("lp_pool2d", lambda x: F.lp_pool2d(x, 2, kernel_size=2, stride=2),
     lambda x: np.sqrt((_avgpool_np(x ** 2, 2, 2)) * 4),
     [_sp(2, 3, 4, 4, pos=True)], {}),
    # -- convs --------------------------------------------------------------
    ("conv1d", lambda x: F.conv1d(x, pt.to_tensor(_W1D).astype(x.dtype)),
     lambda x: _conv1d_np(x, _W1D), [_sp(2, 2, 6)],
     {"bf16_atol": 5e-2, "bf16_rtol": 5e-2}),
    ("conv3d", lambda x: F.conv3d(x, pt.to_tensor(_W3D).astype(x.dtype)),
     lambda x: _conv3d_np(x, _W3D), [_sp(1, 2, 4, 4, 4)],
     {"bf16_atol": 5e-2, "bf16_rtol": 5e-2}),
    ("conv1d_transpose",
     lambda x: F.conv1d_transpose(x, pt.to_tensor(_W1T).astype(x.dtype)),
     lambda x: _convt_np(x, _W1T, 1), [_sp(2, 2, 5)],
     {"bf16_atol": 5e-2, "bf16_rtol": 5e-2}),
    ("conv2d_transpose",
     lambda x: F.conv2d_transpose(x, pt.to_tensor(_W2T).astype(x.dtype)),
     lambda x: _convt_np(x, _W2T, 2), [_sp(1, 2, 4, 4)],
     {"bf16_atol": 8e-2, "bf16_rtol": 8e-2}),
    ("conv3d_transpose",
     lambda x: F.conv3d_transpose(x, pt.to_tensor(_W3T).astype(x.dtype)),
     lambda x: _convt_np(x, _W3T, 3), [_sp(1, 2, 3, 3, 3)],
     {"bf16_atol": 8e-2, "bf16_rtol": 8e-2}),
    # -- misc ---------------------------------------------------------------
    ("linear",
     lambda x: F.linear(x, pt.to_tensor(_sp(4, 3, seed=30))),
     lambda x: x @ _sp(4, 3, seed=30), [_sp(2, 4)],
     {"bf16_atol": 5e-2, "bf16_rtol": 5e-2}),
    ("embedding",
     lambda: F.embedding(pt.to_tensor(_LBL), pt.to_tensor(_EMB)),
     lambda: _EMB[_LBL], [], {"grad": False, "no_inputs": True}),
    ("one_hot",
     lambda: F.one_hot(pt.to_tensor(_LBL), num_classes=5),
     lambda: np.eye(5, dtype=np.float32)[_LBL], [],
     {"grad": False, "no_inputs": True}),
    ("label_smooth",
     lambda x: F.label_smooth(x, epsilon=0.1),
     lambda x: x * 0.9 + 0.1 / x.shape[-1], [_BIN], {}),
    ("bilinear",
     lambda x, y: F.bilinear(x, y, pt.to_tensor(_BILIN_W)),
     lambda x, y: np.einsum("bi,oij,bj->bo", x, _BILIN_W, y),
     [_sp(2, 4), _sp(2, 5, seed=1)],
     {"bf16_atol": 5e-2, "bf16_rtol": 5e-2}),
    ("pixel_unshuffle",
     lambda x: F.pixel_unshuffle(x, 2),
     lambda x: x.reshape(1, 2, 2, 2, 2, 2).transpose(
         0, 1, 3, 5, 2, 4).reshape(1, 8, 2, 2),
     [_sp(1, 2, 4, 4)], {}),
    ("channel_shuffle",
     lambda x: F.channel_shuffle(x, 2),
     lambda x: x.reshape(1, 2, 2, 3, 3).transpose(0, 2, 1, 3, 4).reshape(
         1, 4, 3, 3),
     [_sp(1, 4, 3, 3)], {}),
    ("pad_constant",
     lambda x: F.pad(x, [1, 1], value=0.0),
     lambda x: np.pad(x, ((0, 0), (0, 0), (1, 1))), [_sp(1, 2, 4)], {}),
    ("zeropad2d",
     lambda x: F.zeropad2d(x, [1, 0, 1, 0]),
     lambda x: np.pad(x, ((0, 0), (0, 0), (1, 0), (1, 0))),
     [_sp(1, 2, 3, 3)], {}),
    ("temporal_shift",
     lambda x: F.temporal_shift(x, seg_num=2, shift_ratio=0.25),
     None, [_sp(4, 4, 3, 3)], {"ref_self": True}),
    ("sequence_mask",
     lambda: F.sequence_mask(pt.to_tensor(np.array([1, 3, 2])), maxlen=4),
     lambda: (np.arange(4)[None, :] < np.array([[1], [3], [2]])),
     [], {"grad": False, "no_inputs": True}),
    ("interpolate_nearest",
     lambda x: F.interpolate(x, scale_factor=2, mode="nearest"),
     lambda x: x.repeat(2, axis=2).repeat(2, axis=3), [_sp(1, 2, 3, 3)],
     {}),
    ("upsample_nearest",
     lambda x: F.upsample(x, scale_factor=2, mode="nearest"),
     lambda x: x.repeat(2, axis=2).repeat(2, axis=3), [_sp(1, 2, 3, 3)],
     {}),
]

_IDS = [row[0] for row in OPS]


def _maybe_self_ref(op, ref, inputs, opts):
    """rows with ref_self: compare eager vs jitted only (the op IS its
    own reference; covered for behavior in dedicated suites)."""
    if opts.get("ref_self"):
        def ref2(*a):
            out = op(*[Tensor(np.asarray(x)) for x in a]) if a else op()
            out = out[0] if isinstance(out, (list, tuple)) else out
            return np.asarray(out._data)
        return ref2
    return ref


@pytest.mark.parametrize("name,op,ref,inputs,opts", OPS, ids=_IDS)
def test_output_float32(name, op, ref, inputs, opts):
    ref = _maybe_self_ref(op, ref, inputs, opts)
    if opts.get("no_inputs"):
        got = op()
        got = got[0] if isinstance(got, (list, tuple)) else got
        np.testing.assert_allclose(np.asarray(got._data), ref(),
                                   atol=1e-5, rtol=1e-5)
        return
    check_output(op, ref, inputs,
                 atol=opts.get("atol", 1e-5), rtol=opts.get("rtol", 1e-5))


@pytest.mark.parametrize(
    "name,op,ref,inputs,opts",
    [r for r in OPS if not r[4].get("no_inputs")
     and not r[4].get("ref_self")],
    ids=[r[0] for r in OPS if not r[4].get("no_inputs")
         and not r[4].get("ref_self")])
def test_output_bfloat16(name, op, ref, inputs, opts):
    tensors = [Tensor(jnp.asarray(a).astype(jnp.bfloat16)) for a in inputs]
    out = op(*tensors)
    out = out[0] if isinstance(out, (list, tuple)) else out
    got = np.asarray(out._data.astype(jnp.float32), dtype=np.float64)
    want = np.asarray(ref(*[np.asarray(a) for a in inputs]),
                      dtype=np.float64)
    np.testing.assert_allclose(
        got, want, atol=opts.get("bf16_atol", 3e-2),
        rtol=opts.get("bf16_rtol", 3e-2), err_msg=f"bf16 {name}")


# FD-grad rows whose central-difference loops dominate the fast tier;
# their OUTPUT checks stay fast, the grad leg runs in the slow tier
_SLOW_GRAD = {"adaptive_avg_pool3d", "adaptive_avg_pool2d",
              "temporal_shift", "group_norm", "local_response_norm",
              "npair_loss", "lp_pool2d", "conv3d_transpose",
              "instance_norm", "lp_pool1d"}
_GRAD_ROWS = [r for r in OPS if r[4].get("grad", True)
              and not r[4].get("no_inputs")]


@pytest.mark.parametrize(
    "name,op,ref,inputs,opts",
    [r for r in _GRAD_ROWS if r[0] not in _SLOW_GRAD],
    ids=[r[0] for r in _GRAD_ROWS if r[0] not in _SLOW_GRAD])
def test_grad_float32(name, op, ref, inputs, opts):
    check_grad(op, inputs, atol=opts.get("grad_atol", 5e-3),
               rtol=opts.get("grad_rtol", opts.get("grad_atol", 5e-3)))


@pytest.mark.slow
@pytest.mark.parametrize(
    "name,op,ref,inputs,opts",
    [r for r in _GRAD_ROWS if r[0] in _SLOW_GRAD],
    ids=[r[0] for r in _GRAD_ROWS if r[0] in _SLOW_GRAD])
def test_grad_float32_slow(name, op, ref, inputs, opts):
    check_grad(op, inputs, atol=opts.get("grad_atol", 5e-3),
               rtol=opts.get("grad_rtol", opts.get("grad_atol", 5e-3)))
