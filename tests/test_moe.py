"""MoE tests (ref: test/collective/collective_global_scatter.py and the
moe_layer unit tests): gating math against hand-computed routing, MoE
forward/backward, ample-capacity top-1 equivalence with dense expert
selection, and GSPMD sharding of the expert dimension."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.incubate.distributed.models.moe import (
    ExpertMlp, GShardGate, MoELayer, SwitchGate)
from paddle_tpu.incubate.distributed.models.moe.functional import (
    combine, dispatch, top1_gating, top2_gating)


def test_top1_gating_routes_to_argmax():
    logits = jnp.asarray([[2.0, 0.0, -1.0],
                          [0.0, 3.0, 0.0],
                          [0.1, 0.2, 5.0],
                          [4.0, 0.0, 0.0]])
    comb, disp, aux, gates, mask = top1_gating(logits, capacity=2)
    idx = np.argmax(np.asarray(logits), axis=-1)
    for t in range(4):
        assert np.asarray(disp)[t, idx[t]].any()
        np.testing.assert_allclose(
            np.asarray(comb)[t].sum(),
            np.asarray(jax.nn.softmax(logits[t]))[idx[t]], rtol=1e-6)
    assert float(aux) > 0


def test_top1_capacity_drops_overflow():
    # all four tokens pick expert 0; capacity 2 → two dropped
    logits = jnp.tile(jnp.asarray([[5.0, 0.0]]), (4, 1))
    comb, disp, aux, _, _ = top1_gating(logits, capacity=2)
    kept = np.asarray(disp).sum()
    assert kept == 2


def test_top2_combines_two_experts():
    logits = jnp.asarray([[2.0, 1.9, -5.0, -5.0]])
    comb, disp, aux = top2_gating(logits, capacity=2)
    d = np.asarray(disp)[0]
    assert d[0].any() and d[1].any() and not d[2].any()
    np.testing.assert_allclose(np.asarray(comb)[0].sum(), 1.0, rtol=1e-5)


def test_dispatch_combine_roundtrip_identity_experts():
    t, e, c, d = 8, 4, 4, 16
    x = jnp.asarray(np.random.RandomState(0).randn(t, d).astype(np.float32))
    logits = jnp.asarray(
        np.random.RandomState(1).randn(t, e).astype(np.float32))
    comb, disp, _ = top2_gating(logits, capacity=c)
    xe = dispatch(x, disp)
    y = combine(xe, comb)
    # identity experts + normalized top-2 weights → y ≈ x for kept tokens
    kept = np.asarray(disp).any(axis=(1, 2))
    np.testing.assert_allclose(np.asarray(y)[kept], np.asarray(x)[kept],
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("gate_type", [
    pytest.param("gshard", marks=pytest.mark.slow), "switch"])
@pytest.mark.slow
def test_moe_layer_forward_backward(gate_type):
    pt.seed(0)
    layer = MoELayer(d_model=16,
                     experts=ExpertMlp(4, 16, 32),
                     gate={"type": gate_type,
                           "top_k": 1 if gate_type == "switch" else 2})
    x = pt.to_tensor(
        np.random.RandomState(2).randn(2, 8, 16).astype(np.float32),
        stop_gradient=False)
    y = layer(x)
    assert tuple(y.shape) == (2, 8, 16)
    assert layer.l_aux is not None and float(layer.l_aux.numpy()) > 0
    loss = y.mean() + 0.01 * layer.l_aux
    loss.backward()
    for n, p in layer.named_parameters():
        assert p.grad is not None, n
    g = layer.experts.w1.grad.numpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


@pytest.mark.slow
def test_moe_layer_list_experts_matches_stacked():
    """Generic LayerList experts path produces the same result as the
    stacked ExpertMlp when weights are copied across."""
    pt.seed(0)
    stacked = ExpertMlp(2, 8, 16)
    layer_s = MoELayer(d_model=8, experts=stacked,
                       gate={"type": "switch", "top_k": 1})

    class OneExpert(pt.nn.Layer):
        def __init__(self, w1, b1, w2, b2):
            super().__init__()
            self.fc1 = pt.nn.Linear(8, 16)
            self.fc2 = pt.nn.Linear(16, 8)
            self.fc1.weight.set_value(w1)
            self.fc1.bias.set_value(b1.reshape(-1))
            self.fc2.weight.set_value(w2)
            self.fc2.bias.set_value(b2.reshape(-1))

        def forward(self, x):
            return self.fc2(pt.nn.functional.gelu(self.fc1(x)))

    w = {k: v.numpy() for k, v in stacked.state_dict().items()}
    experts = [OneExpert(w["w1"][i], w["b1"][i], w["w2"][i], w["b2"][i])
               for i in range(2)]
    layer_l = MoELayer(d_model=8, experts=experts,
                       gate={"type": "switch", "top_k": 1})
    layer_l.gate.set_state_dict(layer_s.gate.state_dict())

    x = pt.to_tensor(
        np.random.RandomState(3).randn(4, 8).astype(np.float32))
    ys = layer_s(x).numpy()
    yl = layer_l(x).numpy()
    np.testing.assert_allclose(ys, yl, rtol=2e-3, atol=2e-4)


@pytest.mark.slow
def test_moe_expert_axis_gspmd_shardable():
    """The dispatch einsum compiles under a mesh with the expert dim
    sharded (the global_scatter equivalent is XLA's all_to_all)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    t, e, c, d = 16, 8, 4, 32
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("ep",))
    x = jnp.asarray(np.random.RandomState(0).randn(t, d).astype(np.float32))
    logits = jnp.asarray(
        np.random.RandomState(1).randn(t, e).astype(np.float32))

    @jax.jit
    def moe_dispatch(x, logits):
        comb, disp, aux = top2_gating(logits, capacity=c)
        xe = dispatch(x, disp)
        xe = jax.lax.with_sharding_constraint(
            xe, NamedSharding(mesh, P("ep", None, None)))
        return xe

    xe = moe_dispatch(x, logits)
    assert xe.shape == (e, c, d)
    ref = dispatch(x, top2_gating(logits, capacity=c)[1])
    np.testing.assert_allclose(np.asarray(xe), np.asarray(ref), rtol=1e-5)
