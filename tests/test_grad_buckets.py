"""Bucketed data-parallel gradient reduction (distributed/grad_buckets.py).

Partitioner units (size targets, reverse order, dtype purity, giant
params), the custom_vjp reduction marker's backward semantics, train-step
bit-parity bucketed vs unbucketed on the 8-device CPU mesh, eligibility
gating, the 1F1B overlap schedule's parity, and the telemetry contract:
``pt_collective_bytes`` must record the FUSED payload (one sample per
bucket, not one per parameter) plus ``pt_grad_buckets_total`` /
``pt_grad_bucket_bytes``.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
import paddle_tpu.observability as obs
from paddle_tpu.distributed._jax_compat import shard_map
from paddle_tpu.distributed.grad_buckets import (
    apply_bucketed_reduction, bucket_reduce_marker, default_bucket_bytes,
    partition_buckets)
from paddle_tpu.distributed.train_step import (
    _bucket_plan_for, build_train_step)


@pytest.fixture(autouse=True)
def _reset():
    yield
    dist.set_mesh(None)
    dist.destroy_process_group()
    obs.reset()


def _params(*specs):
    """{name: np array} in declaration order; specs = (name, shape, dtype)."""
    out = {}
    for name, shape, dtype in specs:
        out[name] = np.zeros(shape, dtype)
    return out


# -- partitioner -------------------------------------------------------------

def test_size_target_closes_buckets():
    # six 4000-byte params, 10 KB target -> greedy pairs of two
    params = _params(*[(f"p{i}", (1000,), np.float32) for i in range(6)])
    plan = partition_buckets(params, 10_000)
    assert plan.n_buckets == 3
    assert all(b.nbytes == 8000 for b in plan.buckets)
    # partition covers every parameter exactly once
    names = [n for b in plan.buckets for n in b.names]
    assert sorted(names) == sorted(params)
    assert sum(b.numel for b in plan.buckets) == 6000


def test_reverse_registration_order():
    params = _params(("first", (10,), np.float32),
                     ("mid", (10,), np.float32),
                     ("last", (10,), np.float32))
    plan = partition_buckets(params, 1 << 30)
    # backward produces grads last-layer-first: bucket 0 leads with the
    # LAST registered parameter
    assert plan.n_buckets == 1
    assert plan.buckets[0].names == ["last", "mid", "first"]
    # explicit order overrides
    plan2 = partition_buckets(params, 1 << 30,
                              order=["mid", "first", "last"])
    assert plan2.buckets[0].names == ["mid", "first", "last"]


def test_dtype_change_closes_bucket():
    params = _params(("a", (8,), np.float32),
                     ("b", (8,), np.float32),
                     ("c", (8,), np.float16),
                     ("d", (8,), np.float16),
                     ("e", (8,), np.float32))
    plan = partition_buckets(params, 1 << 30)
    # reverse order: e | d,c | b,a — dtype-homogeneous, never cast
    assert [b.names for b in plan.buckets] == [["e"], ["d", "c"],
                                               ["b", "a"]]
    for b in plan.buckets:
        assert all(params[n].dtype == b.dtype for n in b.names)


def test_giant_param_gets_own_bucket():
    params = _params(("small1", (10,), np.float32),
                     ("giant", (100_000,), np.float32),
                     ("small2", (10,), np.float32))
    plan = partition_buckets(params, 1000)
    # reverse order: small2 | giant (alone, over target) | small1
    assert [b.names for b in plan.buckets] == [["small2"], ["giant"],
                                               ["small1"]]
    assert plan.buckets[1].nbytes == 400_000  # may exceed the target


def test_non_positive_target_raises():
    with pytest.raises(ValueError):
        partition_buckets(_params(("p", (4,), np.float32)), 0)


def test_default_bucket_bytes_precedence(monkeypatch):
    monkeypatch.delenv("PT_GRAD_BUCKET_MB", raising=False)
    assert default_bucket_bytes() == 32 * 1024 * 1024
    assert default_bucket_bytes(4) == 4 * 1024 * 1024
    monkeypatch.setenv("PT_GRAD_BUCKET_MB", "2")
    assert default_bucket_bytes(4) == 2 * 1024 * 1024  # env wins


# -- reduction marker --------------------------------------------------------

def test_marker_forward_identity_and_reconstruction():
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(3, 5).astype(np.float32)),
              "b": jnp.asarray(rng.randn(5).astype(np.float32)),
              "v": jnp.asarray(rng.randn(2, 2, 2).astype(np.float32))}
    plan = partition_buckets(params, 1 << 30)
    out = apply_bucketed_reduction(params, plan)
    assert set(out) == set(params)
    for k in params:
        assert out[k].shape == params[k].shape
        np.testing.assert_array_equal(np.asarray(out[k]),
                                      np.asarray(params[k]))


def test_marker_backward_is_one_pmean_over_dp():
    mesh = dist.init_mesh({"dp": 8})

    def body(x):
        def loss(v):
            v = bucket_reduce_marker(v, "dp")
            rank = jax.lax.axis_index("dp").astype(jnp.float32)
            return (v * rank).sum()
        return jax.grad(loss)(x)

    g = jax.jit(shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(),
                          axis_names={"dp"},
                          check_vma=False))(jnp.ones(4))
    # local grad on rank r is r; pmean over 8 ranks = mean(0..7) = 3.5
    np.testing.assert_allclose(np.asarray(g), 3.5, rtol=1e-6)


# -- train-step integration --------------------------------------------------

def _mlp():
    pt.seed(7)
    return nn.Sequential(nn.Linear(64, 128), nn.ReLU(),
                         nn.Linear(128, 128), nn.ReLU(),
                         nn.Linear(128, 8))


def _loss_fn(out, y):
    return pt.nn.functional.cross_entropy(out, y)


def _batch():
    rng = np.random.RandomState(0)
    return (rng.rand(16, 64).astype(np.float32),
            rng.randint(0, 8, (16,)).astype(np.int64))


def _train_mlp(grad_bucket_mb, steps=3):
    mesh = dist.init_mesh({"dp": 8})
    model = _mlp()
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    step, state = build_train_step(model, _loss_fn, opt, mesh=mesh,
                                   grad_bucket_mb=grad_bucket_mb)
    x, y = _batch()
    losses = []
    for _ in range(steps):
        loss, state = step(state, x, y)
        losses.append(float(loss))
    return losses


def test_bucketed_step_bit_parity_on_dp8():
    # tiny target forces MANY buckets; 0 disables bucketing entirely
    bucketed = _train_mlp(0.05)
    plain = _train_mlp(0)
    np.testing.assert_allclose(bucketed, plain, rtol=0, atol=1e-6)


def test_bucket_eligibility_gating(monkeypatch):
    params = {"w": np.zeros((8, 8), np.float32)}
    mesh = dist.init_mesh({"dp": 8})
    assert _bucket_plan_for(params, mesh, None, None) is not None
    # explicit off
    assert _bucket_plan_for(params, mesh, None, 0) is None
    # ZeRO owns its own reduce-scatter layout
    assert _bucket_plan_for(params, mesh, object(), None) is None
    # kill-switch env
    monkeypatch.setenv("PT_GRAD_BUCKETS", "0")
    assert _bucket_plan_for(params, mesh, None, None) is None
    monkeypatch.delenv("PT_GRAD_BUCKETS")
    # non-dp axes: GSPMD owns the gradient reduction
    mesh_mp = dist.init_mesh({"dp": 4, "mp": 2})
    assert _bucket_plan_for(params, mesh_mp, None, None) is None
    # dp=1: nothing to reduce
    mesh1 = dist.init_mesh({"dp": 1},
                           devices=np.array(jax.devices()[:1]))
    assert _bucket_plan_for(params, mesh1, None, None) is None


def test_bucket_metrics_record_fused_payload():
    tel = obs.get_telemetry().enable()
    mesh = dist.init_mesh({"dp": 8})
    model = _mlp()
    params = {k: p._data for k, p in model.named_parameters()}
    plan = _bucket_plan_for(params, mesh, None, 0.05)
    assert plan is not None and plan.n_buckets > 1
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    pre = obs.get_registry().snapshot()
    step, state = build_train_step(model, _loss_fn, opt, mesh=mesh,
                                   grad_bucket_mb=0.05)
    x, y = _batch()
    loss, state = step(state, x, y)
    jax.block_until_ready(loss)
    snap = obs.get_registry().snapshot()
    # one pt_grad_buckets_total sample per bucket, sized by flat payload
    # (labeled by reduction kind: pure-dp plans are all_reduce buckets)
    prev = pre["pt_grad_buckets_total"]["series"].get("kind=all_reduce", 0)
    assert (snap["pt_grad_buckets_total"]["series"]["kind=all_reduce"]
            - prev == plan.n_buckets)
    hist = snap["pt_grad_bucket_bytes"]["series"][""]
    assert hist["sum"] >= sum(b.nbytes for b in plan.buckets)
    # collective byte accounting is the FUSED payload: trace-time
    # all_reduce bytes equal the summed flat bucket sizes, not one
    # sample per original parameter
    coll = snap["pt_collective_bytes"]["series"]["op=all_reduce"]
    assert coll["count"] == plan.n_buckets
    assert coll["sum"] == sum(b.nbytes for b in plan.buckets)
    assert tel.enabled


# -- 1F1B overlap schedule ---------------------------------------------------

class _Block(pt.nn.Layer):
    def __init__(self, h=32):
        super().__init__()
        self.fc = pt.nn.Linear(h, h)

    def forward(self, x):
        return pt.nn.functional.tanh(self.fc(x)) + x


def _pipeline_losses(overlap, steps=3):
    from paddle_tpu.distributed.fleet.meta_parallel import (
        LayerDesc, PipelineLayer)
    dist.init_mesh({"dp": 4, "pp": 2})
    pt.seed(0)
    pl = PipelineLayer(
        layers=[LayerDesc(pt.nn.Linear, 16, 32)] +
               [LayerDesc(_Block, 32) for _ in range(4)] +
               [LayerDesc(pt.nn.Linear, 32, 10)],
        num_stages=2, loss_fn=_loss_fn)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=pl.parameters())
    step, state = build_train_step(pl, _loss_fn, opt,
                                   pipeline_microbatches=4,
                                   pipeline_overlap=overlap)
    rng = np.random.RandomState(1)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randint(0, 10, 8).astype(np.int32)
    losses = []
    for _ in range(steps):
        loss, state = step(state, x, y)
        losses.append(float(loss))
    return losses


def test_pipeline_overlap_schedule_bit_parity():
    # the double-buffered hop changes WHEN transport happens, not math
    on = _pipeline_losses(True)
    off = _pipeline_losses(False)
    np.testing.assert_allclose(on, off, rtol=0, atol=1e-6)
