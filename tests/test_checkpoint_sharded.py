"""Sharded checkpoint + resharding-on-load.

Ref oracle: auto_parallel dist_saver/converter semantics — a checkpoint
written on one mesh must restore onto a different mesh and continue
training with identical numerics
(python/paddle/distributed/auto_parallel/static/dist_saver.py,
converter.py, fleet/utils/pp_parallel_adaptor.py).
"""
import numpy as np
import pytest
import jax

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed.train_step import build_train_step
from paddle_tpu.incubate.models import (GPTForCausalLM,
                                        GPTPretrainingCriterion, gpt_tiny)
from paddle_tpu.distributed.fleet.meta_parallel.sharding_parallel import \
    annotate_fsdp_specs


@pytest.fixture(autouse=True)
def _reset():
    yield
    dist.set_mesh(None)
    dist.destroy_process_group()


def _cfg():
    cfg = gpt_tiny(tensor_parallel=True)
    cfg.hidden_dropout_prob = 0.0
    cfg.attention_probs_dropout_prob = 0.0
    return cfg


@pytest.mark.slow
def test_save_load_roundtrip_same_mesh(tmp_path):
    pt.seed(0)
    model = GPTForCausalLM(_cfg())
    crit = GPTPretrainingCriterion()
    dist.init_mesh({"dp": 2, "mp": 2, "sharding": 2})
    annotate_fsdp_specs(model, min_size=16)
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    step, state = build_train_step(model, crit, opt, donate=False)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 1024, (4, 16)).astype(np.int32)
    lab = rng.randint(0, 1024, (4, 16)).astype(np.int32)
    _, state = step(state, ids, lab)

    ckpt.save_state(state, str(tmp_path / "ck"))
    restored = ckpt.load_state(str(tmp_path / "ck"), state)
    for (p1, a1), (p2, a2) in zip(
            sorted(ckpt._flat_items(state)), sorted(ckpt._flat_items(restored))):
        assert p1 == p2
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))


@pytest.mark.slow
def test_reshard_on_load_different_mesh(tmp_path):
    """Save on (dp2, mp2, sharding2); load on (dp4, mp2); resumed loss
    must match continuing on the original mesh bit-for-bit-ish."""
    pt.seed(0)
    model = GPTForCausalLM(_cfg())
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 1024, (8, 16)).astype(np.int32)
    lab = rng.randint(0, 1024, (8, 16)).astype(np.int32)

    dist.init_mesh({"dp": 2, "mp": 2, "sharding": 2})
    annotate_fsdp_specs(model, min_size=16)
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    step, state = build_train_step(model, crit, opt, donate=False)
    _, state = step(state, ids, lab)
    ckpt.save_state(state, str(tmp_path / "ck"))
    # original-mesh continuation (the oracle)
    loss_cont, _ = step(state, ids, lab)

    # new mesh: dp4 x mp2, no sharding axis
    dist.init_mesh({"dp": 4, "mp": 2})
    opt2 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step2, state2 = build_train_step(model, crit, opt2, donate=False)
    restored = ckpt.load_state(str(tmp_path / "ck"), state2)
    # restored arrays carry the NEW mesh placements
    some = restored["params"]["gpt.final_ln.weight"]
    msh = some.sharding.mesh.shape
    assert msh["dp"] == 4 and msh["mp"] == 2
    loss_resumed, _ = step2(restored, ids, lab)
    np.testing.assert_allclose(float(loss_cont), float(loss_resumed),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_reshard_pipeline_stacked_state(tmp_path):
    """pp-stacked train state written on (dp2, pp2) restores onto
    (dp1, pp4) — stage re-partitioning on load (pp_parallel_adaptor)."""
    pt.seed(0)
    cfg = _cfg()
    cfg.num_layers = 4
    cfg.tensor_parallel = False
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(2)
    ids = rng.randint(0, 1024, (8, 16)).astype(np.int32)
    lab = rng.randint(0, 1024, (8, 16)).astype(np.int32)

    dist.init_mesh({"dp": 4, "pp": 2})
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    step, state = build_train_step(model, crit, opt, donate=False)
    _, state = step(state, ids, lab)
    ckpt.save_state(state, str(tmp_path / "ck"))
    loss_cont, _ = step(state, ids, lab)

    dist.init_mesh({"dp": 2, "pp": 4})
    opt2 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step2, state2 = build_train_step(model, crit, opt2, donate=False)
    restored = ckpt.load_state(str(tmp_path / "ck"), state2)
    loss_resumed, _ = step2(restored, ids, lab)
    np.testing.assert_allclose(float(loss_cont), float(loss_resumed),
                               rtol=1e-5, atol=1e-5)


def test_load_without_template_uses_saved_specs(tmp_path):
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = dist.init_mesh({"dp": 4, "mp": 2})
    x = jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                       NamedSharding(mesh, P("dp", "mp")))
    ckpt.save_sharded({"x": x, "nested": {"y": x + 1}}, str(tmp_path / "c"))
    # load onto a smaller mesh: dp axis no longer divides? 8 % 2 == 0 fine
    mesh2 = dist.init_mesh({"dp": 2, "mp": 2},
                           devices=np.array(jax.devices()[:4]).reshape(4))
    out = ckpt.load_sharded(str(tmp_path / "c"), mesh=mesh2)
    np.testing.assert_array_equal(np.asarray(out["x"]), np.asarray(x))
    np.testing.assert_array_equal(np.asarray(out["nested"]["y"]),
                                  np.asarray(x) + 1)


def test_hapi_sharded_save_load(tmp_path):
    pt.seed(0)
    dist.init_mesh({"dp": 8})
    net = pt.nn.Sequential(pt.nn.Linear(8, 16), pt.nn.ReLU(),
                           pt.nn.Linear(16, 4))
    m = pt.Model(net)
    m.save(str(tmp_path / "hapi_ck"), sharded=True)
    w_before = np.asarray(net[0].weight._data).copy()
    # perturb, then load back
    net[0].weight._data = net[0].weight._data + 1.0
    m.load(str(tmp_path / "hapi_ck"))
    np.testing.assert_array_equal(np.asarray(net[0].weight._data), w_before)


@pytest.mark.slow
def test_fleet_sharded_facade(tmp_path):
    from paddle_tpu.distributed.fleet import fleet as fleet_obj
    pt.seed(0)
    dist.init_mesh({"dp": 4, "mp": 2})
    model = GPTForCausalLM(_cfg())
    crit = GPTPretrainingCriterion()
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    step, state = build_train_step(model, crit, opt, donate=False)
    fleet_obj.save_sharded(state, str(tmp_path / "fck"))
    restored = fleet_obj.load_sharded(str(tmp_path / "fck"), state)
    k = "gpt.final_ln.weight"
    np.testing.assert_array_equal(np.asarray(state["params"][k]),
                                  np.asarray(restored["params"][k]))


@pytest.mark.slow
def test_pp_stacked_to_unstacked_translation(tmp_path):
    """pp-stacked checkpoint loads onto a NON-pp mesh (unstack) and a
    plain checkpoint loads onto a pp mesh (stack) — both directions of
    the pp_parallel_adaptor re-partitioning."""
    cfg = _cfg()
    pt.seed(0)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(3)
    ids = rng.randint(0, 1024, (8, 16)).astype(np.int32)
    lab = rng.randint(0, 1024, (8, 16)).astype(np.int32)

    dist.init_mesh({"dp": 2, "mp": 2, "pp": 2})
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    step, state = build_train_step(model, crit, opt, donate=False)
    l0, state = step(state, ids, lab)
    ckpt.save_state(state, str(tmp_path / "pp_ck"))

    # stacked -> per-block
    dist.init_mesh({"dp": 4, "mp": 2})
    opt2 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step2, state2 = build_train_step(model, crit, opt2, donate=False)
    state2 = ckpt.load_state(str(tmp_path / "pp_ck"), state2)
    l1, state2 = step2(state2, ids, lab)
    assert float(l1) < float(l0)
    ckpt.save_state(state2, str(tmp_path / "flat_ck"))

    # per-block -> stacked
    dist.init_mesh({"dp": 2, "mp": 2, "pp": 2})
    opt3 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step3, state3 = build_train_step(model, crit, opt3, donate=False)
    state3 = ckpt.load_state(str(tmp_path / "flat_ck"), state3)
    l2, state3 = step3(state3, ids, lab)
    assert float(l2) < float(l1)


@pytest.mark.slow
def test_hapi_sharded_save_preserves_optimizer(tmp_path):
    pt.seed(0)
    dist.init_mesh({"dp": 8})
    net = pt.nn.Sequential(pt.nn.Linear(8, 16), pt.nn.ReLU(),
                           pt.nn.Linear(16, 4))
    opt = pt.optimizer.Adam(learning_rate=0.01,
                            parameters=net.parameters())
    m = pt.Model(net)
    m.prepare(optimizer=opt, loss=pt.nn.CrossEntropyLoss())
    x = np.random.RandomState(0).randn(16, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 4, 16).astype(np.int64)
    m.train_batch([x], [y])
    m.train_batch([x], [y])
    assert int(m._opt_state["step"]) == 2
    m.save(str(tmp_path / "ck2"), sharded=True)

    pt.seed(0)
    net2 = pt.nn.Sequential(pt.nn.Linear(8, 16), pt.nn.ReLU(),
                            pt.nn.Linear(16, 4))
    opt2 = pt.optimizer.Adam(learning_rate=0.01,
                             parameters=net2.parameters())
    m2 = pt.Model(net2)
    m2.prepare(optimizer=opt2, loss=pt.nn.CrossEntropyLoss())
    m2.load(str(tmp_path / "ck2"))
    assert int(m2._opt_state["step"]) == 2
    moments = m2._opt_state["slots"].get("moment1", {})
    assert moments and all(
        np.abs(np.asarray(v)).sum() > 0 for v in moments.values())
    # resumed training continues without error
    m2.train_batch([x], [y])
    assert int(m2._opt_state["step"]) == 3


@pytest.mark.slow
def test_pipeline_train_batch_ragged_batch_falls_back():
    from paddle_tpu.distributed.fleet.meta_parallel import (
        PipelineLayer, PipelineParallel, LayerDesc)

    class Blk(pt.nn.Layer):
        def __init__(self, h=16):
            super().__init__()
            self.fc = pt.nn.Linear(h, h)

        def forward(self, x):
            return pt.nn.functional.relu(self.fc(x)) + x

    dist.init_mesh({"dp": 4, "pp": 2})
    pt.seed(0)
    pl = PipelineLayer(
        layers=[LayerDesc(pt.nn.Linear, 8, 16)] +
               [LayerDesc(Blk) for _ in range(2)] +
               [LayerDesc(pt.nn.Linear, 16, 4)],
        num_stages=2,
        loss_fn=lambda o, y: pt.nn.functional.cross_entropy(o, y))
    pp = PipelineParallel(pl)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=pl.parameters())
    from paddle_tpu.tensor import Tensor
    # batch of 7 is not divisible by 2 microbatches: sequential fallback
    x = np.random.RandomState(0).randn(7, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 4, 7).astype(np.int32)
    loss = pp.train_batch((Tensor(x), Tensor(y)), opt)
    assert np.isfinite(float(loss))
    assert pp._pp_step is None  # compiled path not taken


@pytest.mark.slow
def test_reshard_flat_to_interleaved_pp_layout(tmp_path):
    """A checkpoint written with the flat pp layout restores into an
    INTERLEAVED (virtual-stage [v, pp*Lv, ...]) template and vice versa —
    both are row-major views of the natural block order
    (checkpoint._LeadLayoutReader)."""
    pt.seed(0)
    cfg = _cfg()
    cfg.num_layers = 4
    cfg.tensor_parallel = False
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(3)
    ids = rng.randint(0, 1024, (8, 16)).astype(np.int32)
    lab = rng.randint(0, 1024, (8, 16)).astype(np.int32)

    # write with flat pp2 layout
    dist.init_mesh({"dp": 4, "pp": 2})
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    step, state = build_train_step(model, crit, opt, donate=False)
    _, state = step(state, ids, lab)
    ckpt.save_state(state, str(tmp_path / "flat"))
    loss_cont, _ = step(state, ids, lab)

    # restore into interleaved pp2 x v2 template
    opt2 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step2, state2 = build_train_step(model, crit, opt2, donate=False,
                                     pipeline_virtual_stages=2)
    restored = ckpt.load_state(str(tmp_path / "flat"), state2)
    for k, a in restored["params"].items():
        if k.startswith("__ppstack__."):
            assert a.shape[0] == 2  # interleaved leading layout
    loss_resumed, _ = step2(restored, ids, lab)
    np.testing.assert_allclose(float(loss_cont), float(loss_resumed),
                               rtol=1e-5, atol=1e-5)

    # and the reverse: interleaved checkpoint -> flat template
    ckpt.save_state(restored, str(tmp_path / "ileave"))
    opt3 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step3, state3 = build_train_step(model, crit, opt3, donate=False)
    restored3 = ckpt.load_state(str(tmp_path / "ileave"), state3)
    loss3, _ = step3(restored3, ids, lab)
    np.testing.assert_allclose(float(loss_cont), float(loss3),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_interleaved_checkpoint_to_unstacked_template(tmp_path):
    """An interleaved ([v, pp*Lv, ...]) pipelined checkpoint restores into
    a NON-pipelined (per-block param names) template — the _RowReader
    direction must view the saved leaf flat first."""
    pt.seed(0)
    cfg = _cfg()
    cfg.num_layers = 4
    cfg.tensor_parallel = False
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(5)
    ids = rng.randint(0, 1024, (8, 16)).astype(np.int32)
    lab = rng.randint(0, 1024, (8, 16)).astype(np.int32)

    dist.init_mesh({"dp": 4, "pp": 2})
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    step, state = build_train_step(model, crit, opt, donate=False,
                                   pipeline_virtual_stages=2)
    _, state = step(state, ids, lab)
    ckpt.save_state(state, str(tmp_path / "il"))
    loss_cont, _ = step(state, ids, lab)

    dist.init_mesh({"dp": 1})
    opt2 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step2, state2 = build_train_step(model, crit, opt2, donate=False)
    assert not any(k.startswith("__ppstack__.") for k in state2["params"])
    restored = ckpt.load_state(str(tmp_path / "il"), state2)
    loss1, _ = step2(restored, ids, lab)
    np.testing.assert_allclose(float(loss_cont), float(loss1),
                               rtol=1e-5, atol=1e-5)
