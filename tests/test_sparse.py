"""paddle.sparse tests (ref: test/legacy_test/test_sparse_*_op.py family).

Oracle: dense numpy reference for every op (the sparse OpTest pattern)."""
from __future__ import annotations

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import sparse as S


@pytest.fixture
def coo():
    idx = np.array([[0, 0, 1, 2], [0, 2, 1, 0]])
    vals = np.array([1., 2., 3., 4.], np.float32)
    dense = np.zeros((3, 3), np.float32)
    dense[tuple(idx)] = vals
    return S.sparse_coo_tensor(idx, vals, shape=[3, 3]), dense


class TestCreation:
    def test_coo_round_trip(self, coo):
        x, dense = coo
        np.testing.assert_allclose(x.to_dense().numpy(), dense)
        assert x.nnz == 4 and x.shape == [3, 3]
        # indices come back in paddle layout [sparse_dim, nnz]
        assert x.indices().numpy().shape == (2, 4)

    def test_csr_round_trip(self):
        crows = np.array([0, 2, 3, 4])
        cols = np.array([0, 2, 1, 0])
        vals = np.array([1., 2., 3., 4.], np.float32)
        x = S.sparse_csr_tensor(crows, cols, vals, [3, 3])
        dense = np.zeros((3, 3), np.float32)
        dense[0, 0], dense[0, 2], dense[1, 1], dense[2, 0] = 1, 2, 3, 4
        np.testing.assert_allclose(x.to_dense().numpy(), dense)

    @pytest.mark.slow
    def test_coo_csr_conversion(self, coo):
        x, dense = coo
        csr = x.to_sparse_csr()
        np.testing.assert_allclose(csr.to_dense().numpy(), dense)
        back = csr.to_sparse_coo()
        np.testing.assert_allclose(back.to_dense().numpy(), dense)

    def test_coalesce(self):
        idx = np.array([[0, 0], [1, 1]])  # duplicate entry
        x = S.sparse_coo_tensor(idx, np.array([2., 5.], np.float32),
                                shape=[2, 2])
        c = S.coalesce(x)
        assert c.nnz <= 2
        np.testing.assert_allclose(c.to_dense().numpy()[0, 1], 7.0)


class TestUnary:
    @pytest.mark.parametrize("op,ref", [
        ("sin", np.sin), ("tanh", np.tanh), ("sqrt", np.sqrt),
        ("square", np.square), ("log1p", np.log1p), ("abs", np.abs),
        ("neg", np.negative), ("expm1", np.expm1),
    ])
    def test_value_ops(self, coo, op, ref):
        x, dense = coo
        out = getattr(S, op)(x).to_dense().numpy()
        want = np.where(dense != 0, ref(dense.astype(np.float64)), 0)
        np.testing.assert_allclose(out, want.astype(np.float32), rtol=1e-5)

    def test_pow_cast(self, coo):
        x, dense = coo
        np.testing.assert_allclose(S.pow(x, 3).to_dense().numpy(),
                                   dense ** 3, rtol=1e-5)
        c = S.cast(x, value_dtype="float16")
        assert str(c.dtype) == "float16"


class TestBinary:
    @pytest.mark.slow
    def test_add_subtract_union_pattern(self, coo):
        x, dense = coo
        other = np.zeros((3, 3), np.float32)
        other[0, 0], other[2, 2] = 10, 20
        y = S.sparse_coo_tensor(np.array([[0, 2], [0, 2]]),
                                np.array([10., 20.], np.float32), [3, 3])
        np.testing.assert_allclose(S.add(x, y).to_dense().numpy(),
                                   dense + other)
        np.testing.assert_allclose(S.subtract(x, y).to_dense().numpy(),
                                   dense - other)

    def test_multiply(self, coo):
        x, dense = coo
        np.testing.assert_allclose(S.multiply(x, 2.5).to_dense().numpy(),
                                   dense * 2.5)
        d = np.random.RandomState(0).randn(3, 3).astype(np.float32)
        np.testing.assert_allclose(S.multiply(x, d).to_dense().numpy(),
                                   dense * d, rtol=1e-6)

    def test_matmul_mv(self, coo):
        x, dense = coo
        d = np.random.RandomState(0).randn(3, 5).astype(np.float32)
        np.testing.assert_allclose(S.matmul(x, d).numpy(), dense @ d,
                                   rtol=1e-5)
        v = np.random.RandomState(1).randn(3).astype(np.float32)
        np.testing.assert_allclose(S.mv(x, v).numpy(), dense @ v, rtol=1e-5)

    def test_masked_matmul(self, coo):
        x, dense = coo
        a = np.random.RandomState(1).randn(3, 4).astype(np.float32)
        b = np.random.RandomState(2).randn(4, 3).astype(np.float32)
        out = S.masked_matmul(a, b, x).to_dense().numpy()
        np.testing.assert_allclose(out, np.where(dense != 0, a @ b, 0),
                                   rtol=1e-5)

    def test_addmm(self, coo):
        x, dense = coo
        inp = np.random.RandomState(3).randn(3, 5).astype(np.float32)
        d = np.random.RandomState(4).randn(3, 5).astype(np.float32)
        out = S.addmm(inp, x, d, beta=0.5, alpha=2.0).numpy()
        np.testing.assert_allclose(out, 0.5 * inp + 2.0 * (dense @ d),
                                   rtol=1e-5)


class TestManipulation:
    @pytest.mark.slow
    def test_transpose_reshape_slice_sum(self, coo):
        x, dense = coo
        np.testing.assert_allclose(S.transpose(x, [1, 0]).to_dense().numpy(),
                                   dense.T)
        np.testing.assert_allclose(S.reshape(x, [9]).to_dense().numpy(),
                                   dense.reshape(9))
        np.testing.assert_allclose(
            S.slice(x, [0], [0], [2]).to_dense().numpy(), dense[:2])
        np.testing.assert_allclose(float(S.sum(x).numpy()), dense.sum())
        np.testing.assert_allclose(S.sum(x, axis=0).numpy(), dense.sum(0))


class TestNN:
    def test_relu_family(self, coo):
        x, dense = coo
        neg = S.neg(x)
        np.testing.assert_allclose(
            S.nn.functional.relu(neg).to_dense().numpy(),
            np.maximum(-dense, 0))
        np.testing.assert_allclose(
            S.nn.functional.leaky_relu(neg, 0.1).to_dense().numpy(),
            np.where(-dense >= 0, -dense, -0.1 * dense), rtol=1e-6)

    def test_csr_softmax_rows(self):
        crows = np.array([0, 2, 3])
        cols = np.array([0, 2, 1])
        vals = np.array([1., 2., 5.], np.float32)
        x = S.sparse_csr_tensor(crows, cols, vals, [2, 3])
        sm = S.nn.functional.softmax(x).to_dense().numpy()
        row0 = np.exp([1., 2.]) / np.exp([1., 2.]).sum()
        np.testing.assert_allclose(sm[0, [0, 2]], row0, rtol=1e-5)
        np.testing.assert_allclose(sm[1, 1], 1.0, rtol=1e-6)
