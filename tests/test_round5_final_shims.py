"""Final round-5 closures: fused functional transformer forms,
functional BFGS/L-BFGS minimizers, PassManager, recompute_sequential,
device.cuda/xpu surface, fleet fs utils."""
import numpy as np
import pytest

import paddle_tpu as pt


def _t(a):
    return pt.to_tensor(np.asarray(a))


class TestFusedFunctional:
    def test_ffn_matches_oracle(self):
        import paddle_tpu.incubate.nn.functional as FF
        rs = np.random.RandomState(0)
        x = _t(rs.randn(2, 3, 8).astype(np.float32))
        w1 = _t(rs.randn(8, 16).astype(np.float32))
        w2 = _t(rs.randn(16, 8).astype(np.float32))
        g = _t(np.ones(8, np.float32))
        b = _t(np.zeros(8, np.float32))
        out = FF.fused_feedforward(x, w1, w2, ln2_scale=g, ln2_bias=b,
                                   dropout1_rate=0.0, dropout2_rate=0.0,
                                   training=False)
        xn = x.numpy()
        h = xn + np.maximum(xn @ w1.numpy(), 0) @ w2.numpy()
        mu = h.mean(-1, keepdims=True)
        var = h.var(-1, keepdims=True)
        np.testing.assert_allclose(out.numpy(),
                                   (h - mu) / np.sqrt(var + 1e-5),
                                   atol=2e-4, rtol=2e-4)

    def test_mha_matches_sdpa_oracle(self):
        import paddle_tpu.incubate.nn.functional as FF
        rs = np.random.RandomState(1)
        B, S, H, nh = 2, 4, 8, 2
        hd = H // nh
        x = _t(rs.randn(B, S, H).astype(np.float32))
        qkv_w = _t(rs.randn(3, nh, hd, H).astype(np.float32))
        lin_w = _t(rs.randn(H, H).astype(np.float32))
        g = _t(np.ones(H, np.float32))
        lb = _t(np.zeros(H, np.float32))
        out = FF.fused_multi_head_attention(
            x, qkv_w, lin_w, ln_scale=g, ln_bias=lb, dropout_rate=0.0,
            attn_dropout_rate=0.0, training=False)
        # oracle
        xn = x.numpy()
        qkv = np.einsum("bsh,tndh->btnsd", xn, qkv_w.numpy())
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        lg = np.einsum("bnqd,bnkd->bnqk", q, k) / np.sqrt(hd)
        p = np.exp(lg - lg.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ctx = np.einsum("bnqk,bnkd->bnqd", p, v)
        ctx = np.moveaxis(ctx, 1, 2).reshape(B, S, H)
        h = xn + ctx @ lin_w.numpy()
        mu = h.mean(-1, keepdims=True)
        var = h.var(-1, keepdims=True)
        np.testing.assert_allclose(out.numpy(),
                                   (h - mu) / np.sqrt(var + 1e-5),
                                   atol=3e-4, rtol=3e-4)

    def test_multi_transformer_stacks_and_caches_raise(self):
        import paddle_tpu.incubate.nn.functional as FF
        rs = np.random.RandomState(2)
        x = _t(rs.randn(1, 3, 8).astype(np.float32))
        qkv_w = _t(rs.randn(3, 2, 4, 8).astype(np.float32))
        lin_w = _t(rs.randn(8, 8).astype(np.float32))
        w1 = _t(rs.randn(8, 16).astype(np.float32))
        w2 = _t(rs.randn(16, 8).astype(np.float32))
        out = FF.fused_multi_transformer(
            x, [None] * 2, [None] * 2, [qkv_w] * 2, None, [lin_w] * 2,
            None, [None] * 2, [None] * 2, [w1] * 2, None, [w2] * 2, None)
        assert tuple(out.shape) == (1, 3, 8)
        with pytest.raises(NotImplementedError):
            FF.fused_multi_transformer(
                x, [None], [None], [qkv_w], None, [lin_w], None, [None],
                [None], [w1], None, [w2], None, cache_kvs=[1])


class TestFunctionalMinimizers:
    def _rosen(self, v):
        a, b = v[0], v[1]
        return (1 - a) ** 2 + 100.0 * (b - a * a) ** 2

    def test_bfgs_rosenbrock(self):
        from paddle_tpu.incubate.optimizer.functional import minimize_bfgs
        ok, n, pos, val, grad, H = minimize_bfgs(
            self._rosen, _t(np.array([-1.2, 1.0], np.float32)),
            max_iters=200)
        assert ok and n > 0
        np.testing.assert_allclose(pos.numpy(), [1.0, 1.0], atol=1e-2)
        assert float(val.numpy()) < 1e-4
        assert tuple(H.shape) == (2, 2)

    def test_lbfgs_rosenbrock(self):
        from paddle_tpu.incubate.optimizer.functional import \
            minimize_lbfgs
        ok, n, pos, val, grad = minimize_lbfgs(
            self._rosen, _t(np.array([-1.2, 1.0], np.float32)),
            max_iters=300)
        assert ok
        np.testing.assert_allclose(pos.numpy(), [1.0, 1.0], atol=1e-2)


def test_pass_manager_orders_and_applies():
    from paddle_tpu.distributed.passes import (PassBase, PassManager,
                                               PassType, new_pass,
                                               register_pass)

    @register_pass("test_pm_fusion")
    class Fus(PassBase):
        def _check_self(self):
            return True

        def _check_conflict(self, other):
            return True

        def _type(self):
            return PassType.FUSION_OPT

        def _apply_single_impl(self, main, startup, ctx):
            ctx.set_attr("order", ctx.get_attr("order", []) + ["fusion"])

    @register_pass("test_pm_calc")
    class Calc(PassBase):
        def _check_self(self):
            return True

        def _check_conflict(self, other):
            return True

        def _type(self):
            return PassType.CALC_OPT

        def _apply_single_impl(self, main, startup, ctx):
            ctx.set_attr("order", ctx.get_attr("order", []) + ["calc"])

    # fusion listed FIRST must still run LAST (auto conflict solve)
    pm = PassManager([new_pass("test_pm_fusion"), new_pass("test_pm_calc")])
    assert pm.names == ["test_pm_calc", "test_pm_fusion"]

    class FakeProg:
        version = 0

        def __init__(self):
            self.nodes = []

    ctx = pm.apply([FakeProg()], [FakeProg()])
    assert ctx.get_attr("order") == ["calc", "fusion"]


def test_recompute_sequential_matches_plain():
    from paddle_tpu.distributed.fleet import recompute_sequential
    pt.seed(0)
    net = pt.nn.Sequential(pt.nn.Linear(8, 8), pt.nn.Tanh(),
                           pt.nn.Linear(8, 4), pt.nn.Tanh())
    x = _t(np.random.RandomState(0).randn(2, 8).astype(np.float32))
    x.stop_gradient = False
    ref = net(x)
    got = recompute_sequential({"segments": 2}, net, x)
    np.testing.assert_allclose(got.numpy(), ref.numpy(), atol=1e-6)
    got.sum().backward()
    assert x.grad is not None and np.isfinite(x.grad.numpy()).all()


def test_device_cuda_xpu_surface():
    d = pt.device
    assert d.cuda.get_device_name()
    assert d.cuda.get_device_capability() == (0, 0)
    assert d.cuda.memory_reserved() >= 0
    assert d.cuda.max_memory_reserved() >= 0
    s = d.cuda.current_stream()
    assert s.query()
    d.xpu.synchronize()
    props = d.cuda.get_device_properties()
    assert hasattr(props, "total_memory")


def test_fleet_fs_utils(tmp_path):
    from paddle_tpu.distributed.fleet.utils import (DistributedInfer,
                                                    HDFSClient, LocalFS)
    fs = LocalFS()
    p = str(tmp_path / "d")
    fs.mkdirs(p)
    assert fs.is_exist(p)
    dirs, files = fs.ls_dir(str(tmp_path))
    assert "d" in dirs
    fs.delete(p)
    assert not fs.is_exist(p)
    with pytest.raises(RuntimeError, match="hadoop"):
        HDFSClient("/nonexistent/hadoop_home")
    di = DistributedInfer()
    assert di.get_dist_infer_program() is not None


def test_fused_downscale_in_infer_and_validation():
    import paddle_tpu.incubate.nn.functional as FF
    rs = np.random.RandomState(3)
    x = _t(rs.randn(1, 2, 4).astype(np.float32))
    w1 = _t(rs.randn(4, 8).astype(np.float32))
    w2 = _t(np.zeros((8, 4), np.float32))
    # downscale_in_infer must scale the (zero) branch consistently —
    # compare against mode-default inference with p=0 (same math here)
    out = FF.fused_feedforward(x, w1, w2, dropout1_rate=0.5,
                               dropout2_rate=0.5, training=False,
                               mode="downscale_in_infer",
                               pre_layer_norm=True)
    assert np.isfinite(out.numpy()).all()
    qkv_w2d = _t(rs.randn(4, 12).astype(np.float32))
    lin_w = _t(rs.randn(4, 4).astype(np.float32))
    with pytest.raises(ValueError, match="num_heads"):
        FF.fused_multi_head_attention(x, qkv_w2d, lin_w,
                                      transpose_qkv_wb=True)
    with pytest.raises(NotImplementedError, match="trans_qkvw"):
        FF.fused_multi_transformer(
            x, [None], [None], [qkv_w2d], None, [lin_w], None, [None],
            [None], [w1], None, [w2], None, trans_qkvw=False)


def test_multi_transformer_post_ln_uses_scales():
    import paddle_tpu.incubate.nn.functional as FF
    rs = np.random.RandomState(4)
    x = _t(rs.randn(1, 3, 8).astype(np.float32))
    qkv_w = _t(rs.randn(3, 2, 4, 8).astype(np.float32) * 0.2)
    lin_w = _t(rs.randn(8, 8).astype(np.float32) * 0.2)
    w1 = _t(rs.randn(8, 16).astype(np.float32) * 0.2)
    w2 = _t(rs.randn(16, 8).astype(np.float32) * 0.2)
    g = _t(np.full(8, 3.0, np.float32))
    b = _t(np.zeros(8, np.float32))
    out_scaled = FF.fused_multi_transformer(
        x, [g], [b], [qkv_w], None, [lin_w], None, [g], [b], [w1], None,
        [w2], None, pre_layer_norm=False)
    # the stack ENDS in the ffn post-LN: with scale=3, bias=0 the final
    # activations are 3 * normalized -> per-position std == 3 (a scale
    # that silently fails to apply leaves std == 1, the old bug)
    std = out_scaled.numpy().std(-1)
    np.testing.assert_allclose(std, 3.0, rtol=2e-2)
    assert abs(out_scaled.numpy().mean(-1)).max() < 1e-3


def test_recompute_sequential_multiarg_first_layer():
    from paddle_tpu.distributed.fleet import recompute_sequential

    class TwoIn(pt.nn.Layer):
        def forward(self, a, b):
            return a + b

    class Sq(pt.nn.Layer):
        def forward(self, x):
            return x * x

    seq = pt.nn.Sequential(TwoIn(), Sq())
    a = _t(np.full((2,), 2.0, np.float32))
    b = _t(np.full((2,), 3.0, np.float32))
    out = recompute_sequential({"segments": 2}, seq, a, b)
    np.testing.assert_allclose(out.numpy(), [25.0, 25.0])
