"""Quantization tests (ref: test/quantization/ test_quant_aware /
test_ptq)."""
from __future__ import annotations

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import quantization as Q


def _model():
    pt.seed(3)
    return pt.nn.Sequential(
        pt.nn.Linear(8, 16), pt.nn.ReLU(), pt.nn.Linear(16, 4))


class TestFakeQuant:
    def test_quant_dequant_levels(self):
        x = np.linspace(-1, 1, 101).astype(np.float32)
        out = Q.quant_dequant(pt.to_tensor(x), scale=1.0,
                              bit_length=8).numpy()
        # 8-bit symmetric: values land on k/127 grid
        np.testing.assert_allclose(out * 127, np.round(out * 127),
                                   atol=1e-4)
        assert np.abs(out - x).max() <= 1 / 127 + 1e-6

    def test_straight_through_gradient(self):
        x = pt.to_tensor(np.array([0.3, -0.7], np.float32),
                         stop_gradient=False)
        y = Q.quant_dequant(x, scale=1.0, bit_length=8)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones(2))

    def test_per_channel(self):
        x = np.stack([np.full(4, 0.5), np.full(4, 5.0)]).astype(np.float32)
        out = Q.quant_dequant(pt.to_tensor(x),
                              scale=np.array([0.5, 5.0], np.float32),
                              bit_length=8, channel_axis=0).numpy()
        np.testing.assert_allclose(out, x, rtol=1e-2)


class TestObservers:
    def test_absmax(self):
        obs = Q.AbsmaxObserver()
        obs.observe(pt.to_tensor(np.array([1.0, -3.0], np.float32)))
        obs.observe(pt.to_tensor(np.array([2.0], np.float32)))
        assert obs.scales() == 3.0

    def test_moving_average(self):
        obs = Q.MovingAverageAbsmaxObserver(moving_rate=0.5)
        obs.observe(pt.to_tensor(np.array([4.0], np.float32)))
        obs.observe(pt.to_tensor(np.array([2.0], np.float32)))
        assert obs.scales() == pytest.approx(3.0)

    def test_per_channel_absmax(self):
        obs = Q.PerChannelAbsmaxObserver(quant_axis_=0)
        obs.observe(pt.to_tensor(np.array([[1., -2.], [3., 0.5]],
                                          np.float32)))
        np.testing.assert_allclose(obs.scales(), [2.0, 3.0])

    def test_hist_percentile(self):
        obs = Q.HistObserver(percentile=0.5)
        obs.observe(pt.to_tensor(np.linspace(0, 10, 1001,
                                             dtype=np.float32)))
        assert 4.0 < obs.scales() < 6.0  # median magnitude ≈ 5


class TestQAT:
    @pytest.mark.slow
    def test_quantize_wraps_and_trains(self):
        model = _model()
        cfg = Q.QuantConfig(
            activation=Q.FakeQuanterWithAbsMaxObserver(),
            weight=Q.FakeQuanterWithAbsMaxObserver())
        qat = Q.QAT(cfg)
        qmodel = qat.quantize(model, inplace=True)
        kinds = [type(l).__name__ for l in qmodel.sublayers()]
        assert kinds.count("QuantedLinear") == 2
        # trains end-to-end with STE gradients
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=qmodel.parameters())
        X = np.random.RandomState(0).randn(32, 8).astype(np.float32)
        Y = np.random.RandomState(1).randint(0, 4, 32)
        losses = []
        for _ in range(15):
            loss = pt.nn.CrossEntropyLoss()(qmodel(pt.to_tensor(X)),
                                            pt.to_tensor(Y))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

    def test_convert_folds_scales(self):
        model = _model()
        cfg = Q.QuantConfig(
            activation=Q.FakeQuanterWithAbsMaxObserver(),
            weight=Q.FakeQuanterWithAbsMaxObserver())
        qat = Q.QAT(cfg)
        qmodel = qat.quantize(model, inplace=True)
        X = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        qmodel(pt.to_tensor(X))  # calibrate
        deployed = qat.convert(qmodel, inplace=True)
        kinds = [type(l).__name__ for l in deployed.sublayers()]
        assert "QuantedLinear" not in kinds
        lin = [l for l in deployed.sublayers()
               if type(l).__name__ == "Linear"][0]
        assert hasattr(lin, "quant_scale")
        # folded weights lie on the int8 grid for their scale
        w = lin.weight.numpy()
        s = np.abs(w).max()
        grid = np.round(w / s * 127)
        np.testing.assert_allclose(w, grid * s / 127, atol=1e-6)


class TestConfigTargeting:
    def test_layer_config_survives_deepcopy(self):
        model = _model()
        cfg = Q.QuantConfig()
        cfg.add_layer_config(model[0],
                             activation=Q.FakeQuanterWithAbsMaxObserver(),
                             weight=Q.FakeQuanterWithAbsMaxObserver())
        qmodel = Q.QAT(cfg).quantize(model)  # inplace=False → deepcopy
        kinds = [type(l).__name__ for l in qmodel.sublayers()]
        assert kinds.count("QuantedLinear") == 1
        # original untouched
        assert all(type(l).__name__ != "QuantedLinear"
                   for l in model.sublayers())

    def test_type_config(self):
        model = _model()
        cfg = Q.QuantConfig()
        cfg.add_type_config(pt.nn.Linear,
                            weight=Q.FakeQuanterWithAbsMaxObserver())
        qmodel = Q.QAT(cfg).quantize(model, inplace=True)
        kinds = [type(l).__name__ for l in qmodel.sublayers()]
        assert kinds.count("QuantedLinear") == 2

    def test_hist_observer_range_growth(self):
        obs = Q.HistObserver(percentile=0.99)
        # batch of small values, then one big outlier batch
        obs.observe(pt.to_tensor(np.full(1000, 0.99, np.float32)))
        obs.observe(pt.to_tensor(np.array([10.0], np.float32)))
        # 99th percentile of {1000×0.99, 1×10.0} must stay near 1, not 10
        assert obs.scales() < 2.0


class TestPTQ:
    def test_nested_layers_observed(self):
        pt.seed(0)
        model = pt.nn.Sequential(
            pt.nn.Sequential(pt.nn.Linear(4, 8), pt.nn.ReLU()),
            pt.nn.Linear(8, 2))
        cfg = Q.QuantConfig(activation=Q.AbsmaxObserver(),
                            weight=Q.AbsmaxObserver())
        qmodel = Q.PTQ(cfg).quantize(model, inplace=True)
        kinds = [type(l).__name__ for l in qmodel.sublayers()]
        assert kinds.count("_ObservedLayer") == 2  # both Linears, not the
        # container
        qmodel(pt.to_tensor(np.ones((2, 4), np.float32)))
        deployed = Q.PTQ(cfg).convert(qmodel, inplace=True)
        linears = [l for l in deployed.sublayers()
                   if type(l).__name__ == "Linear"]
        assert all(hasattr(l, "quant_scale") for l in linears)

    def test_calibrate_and_convert(self):
        model = _model()
        cfg = Q.QuantConfig(activation=Q.AbsmaxObserver(),
                            weight=Q.AbsmaxObserver())
        ptq = Q.PTQ(cfg)
        qmodel = ptq.quantize(model, inplace=True)
        rng = np.random.RandomState(0)
        ref_out = None
        for _ in range(4):
            X = rng.randn(16, 8).astype(np.float32)
            out = qmodel(pt.to_tensor(X))
        deployed = ptq.convert(qmodel, inplace=True)
        # deployed model output stays close to float model
        X = rng.randn(16, 8).astype(np.float32)
        got = deployed(pt.to_tensor(X)).numpy()
        want = _model()(pt.to_tensor(X)).numpy()  # same seed -> same init
        np.testing.assert_allclose(got, want, atol=0.15)
