"""Quantization tests (ref: test/quantization/ test_quant_aware /
test_ptq)."""
from __future__ import annotations

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import quantization as Q


def _model():
    pt.seed(3)
    return pt.nn.Sequential(
        pt.nn.Linear(8, 16), pt.nn.ReLU(), pt.nn.Linear(16, 4))


class TestFakeQuant:
    def test_quant_dequant_levels(self):
        x = np.linspace(-1, 1, 101).astype(np.float32)
        out = Q.quant_dequant(pt.to_tensor(x), scale=1.0,
                              bit_length=8).numpy()
        # 8-bit symmetric: values land on k/127 grid
        np.testing.assert_allclose(out * 127, np.round(out * 127),
                                   atol=1e-4)
        assert np.abs(out - x).max() <= 1 / 127 + 1e-6

    def test_straight_through_gradient(self):
        x = pt.to_tensor(np.array([0.3, -0.7], np.float32),
                         stop_gradient=False)
        y = Q.quant_dequant(x, scale=1.0, bit_length=8)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones(2))

    def test_per_channel(self):
        x = np.stack([np.full(4, 0.5), np.full(4, 5.0)]).astype(np.float32)
        out = Q.quant_dequant(pt.to_tensor(x),
                              scale=np.array([0.5, 5.0], np.float32),
                              bit_length=8, channel_axis=0).numpy()
        np.testing.assert_allclose(out, x, rtol=1e-2)


class TestObservers:
    def test_absmax(self):
        obs = Q.AbsmaxObserver()
        obs.observe(pt.to_tensor(np.array([1.0, -3.0], np.float32)))
        obs.observe(pt.to_tensor(np.array([2.0], np.float32)))
        assert obs.scales() == 3.0

    def test_moving_average(self):
        obs = Q.MovingAverageAbsmaxObserver(moving_rate=0.5)
        obs.observe(pt.to_tensor(np.array([4.0], np.float32)))
        obs.observe(pt.to_tensor(np.array([2.0], np.float32)))
        assert obs.scales() == pytest.approx(3.0)

    def test_per_channel_absmax(self):
        obs = Q.PerChannelAbsmaxObserver(quant_axis_=0)
        obs.observe(pt.to_tensor(np.array([[1., -2.], [3., 0.5]],
                                          np.float32)))
        np.testing.assert_allclose(obs.scales(), [2.0, 3.0])

    def test_hist_percentile(self):
        obs = Q.HistObserver(percentile=0.5)
        obs.observe(pt.to_tensor(np.linspace(0, 10, 1001,
                                             dtype=np.float32)))
        assert 4.0 < obs.scales() < 6.0  # median magnitude ≈ 5


class TestQAT:
    @pytest.mark.slow
    def test_quantize_wraps_and_trains(self):
        model = _model()
        cfg = Q.QuantConfig(
            activation=Q.FakeQuanterWithAbsMaxObserver(),
            weight=Q.FakeQuanterWithAbsMaxObserver())
        qat = Q.QAT(cfg)
        qmodel = qat.quantize(model, inplace=True)
        kinds = [type(l).__name__ for l in qmodel.sublayers()]
        assert kinds.count("QuantedLinear") == 2
        # trains end-to-end with STE gradients
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=qmodel.parameters())
        X = np.random.RandomState(0).randn(32, 8).astype(np.float32)
        Y = np.random.RandomState(1).randint(0, 4, 32)
        losses = []
        for _ in range(15):
            loss = pt.nn.CrossEntropyLoss()(qmodel(pt.to_tensor(X)),
                                            pt.to_tensor(Y))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

    def test_convert_folds_scales(self):
        model = _model()
        cfg = Q.QuantConfig(
            activation=Q.FakeQuanterWithAbsMaxObserver(),
            weight=Q.FakeQuanterWithAbsMaxObserver())
        qat = Q.QAT(cfg)
        qmodel = qat.quantize(model, inplace=True)
        X = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        qmodel(pt.to_tensor(X))  # calibrate
        deployed = qat.convert(qmodel, inplace=True)
        kinds = [type(l).__name__ for l in deployed.sublayers()]
        assert "QuantedLinear" not in kinds
        lin = [l for l in deployed.sublayers()
               if type(l).__name__ == "Linear"][0]
        assert hasattr(lin, "quant_scale")
        # folded weights lie on the int8 grid for their scale
        w = lin.weight.numpy()
        s = np.abs(w).max()
        grid = np.round(w / s * 127)
        np.testing.assert_allclose(w, grid * s / 127, atol=1e-6)


class TestConfigTargeting:
    def test_layer_config_survives_deepcopy(self):
        model = _model()
        cfg = Q.QuantConfig()
        cfg.add_layer_config(model[0],
                             activation=Q.FakeQuanterWithAbsMaxObserver(),
                             weight=Q.FakeQuanterWithAbsMaxObserver())
        qmodel = Q.QAT(cfg).quantize(model)  # inplace=False → deepcopy
        kinds = [type(l).__name__ for l in qmodel.sublayers()]
        assert kinds.count("QuantedLinear") == 1
        # original untouched
        assert all(type(l).__name__ != "QuantedLinear"
                   for l in model.sublayers())

    def test_type_config(self):
        model = _model()
        cfg = Q.QuantConfig()
        cfg.add_type_config(pt.nn.Linear,
                            weight=Q.FakeQuanterWithAbsMaxObserver())
        qmodel = Q.QAT(cfg).quantize(model, inplace=True)
        kinds = [type(l).__name__ for l in qmodel.sublayers()]
        assert kinds.count("QuantedLinear") == 2

    def test_hist_observer_range_growth(self):
        obs = Q.HistObserver(percentile=0.99)
        # batch of small values, then one big outlier batch
        obs.observe(pt.to_tensor(np.full(1000, 0.99, np.float32)))
        obs.observe(pt.to_tensor(np.array([10.0], np.float32)))
        # 99th percentile of {1000×0.99, 1×10.0} must stay near 1, not 10
        assert obs.scales() < 2.0


class TestPTQ:
    def test_nested_layers_observed(self):
        pt.seed(0)
        model = pt.nn.Sequential(
            pt.nn.Sequential(pt.nn.Linear(4, 8), pt.nn.ReLU()),
            pt.nn.Linear(8, 2))
        cfg = Q.QuantConfig(activation=Q.AbsmaxObserver(),
                            weight=Q.AbsmaxObserver())
        qmodel = Q.PTQ(cfg).quantize(model, inplace=True)
        kinds = [type(l).__name__ for l in qmodel.sublayers()]
        assert kinds.count("_ObservedLayer") == 2  # both Linears, not the
        # container
        qmodel(pt.to_tensor(np.ones((2, 4), np.float32)))
        deployed = Q.PTQ(cfg).convert(qmodel, inplace=True)
        linears = [l for l in deployed.sublayers()
                   if type(l).__name__ == "Linear"]
        assert all(hasattr(l, "quant_scale") for l in linears)

    def test_calibrate_and_convert(self):
        model = _model()
        cfg = Q.QuantConfig(activation=Q.AbsmaxObserver(),
                            weight=Q.AbsmaxObserver())
        ptq = Q.PTQ(cfg)
        qmodel = ptq.quantize(model, inplace=True)
        rng = np.random.RandomState(0)
        ref_out = None
        for _ in range(4):
            X = rng.randn(16, 8).astype(np.float32)
            out = qmodel(pt.to_tensor(X))
        deployed = ptq.convert(qmodel, inplace=True)
        # deployed model output stays close to float model
        X = rng.randn(16, 8).astype(np.float32)
        got = deployed(pt.to_tensor(X)).numpy()
        want = _model()(pt.to_tensor(X)).numpy()  # same seed -> same init
        np.testing.assert_allclose(got, want, atol=0.15)


class TestObserverRoundTrip:
    """Observer-driven fake-quant round-trips: scale SHAPES (per-tensor
    scalar vs per-channel vector), the symmetric zero-point-free
    contract, bf16 inputs, and zero-input degeneracy."""

    def test_scale_shapes_per_tensor_vs_per_channel(self):
        x = np.random.RandomState(0).randn(4, 6).astype(np.float32)
        per_t = Q.AbsmaxObserver()
        per_t.observe(pt.to_tensor(x))
        assert np.ndim(per_t.scales()) == 0          # one scalar scale
        assert per_t.quant_axis() is None
        per_c = Q.PerChannelAbsmaxObserver(quant_axis_=1)
        per_c.observe(pt.to_tensor(x))
        s = np.asarray(per_c.scales())
        assert s.shape == (6,)                       # one scale per channel
        assert per_c.quant_axis() == 1
        np.testing.assert_allclose(s, np.abs(x).max(axis=0))

    def test_per_channel_roundtrip_beats_per_tensor(self):
        # channel magnitudes spanning 100x: the global absmax scale
        # wipes out the small channel, per-channel scales keep it
        rng = np.random.RandomState(1)
        x = (rng.randn(64, 3) * np.array([0.05, 1.0, 5.0])) \
            .astype(np.float32)
        per_c = Q.PerChannelAbsmaxObserver(quant_axis_=1)
        per_c.observe(pt.to_tensor(x))
        s = np.asarray(per_c.scales(), np.float32)
        out_c = Q.quant_dequant(pt.to_tensor(x), scale=s,
                                channel_axis=1).numpy()
        # round-to-nearest on each channel's k*s/127 grid: error <= s/254
        assert np.all(np.abs(out_c - x) <= s / 254 + 1e-7)
        per_t = Q.AbsmaxObserver()
        per_t.observe(pt.to_tensor(x))
        out_t = Q.quant_dequant(pt.to_tensor(x),
                                scale=float(per_t.scales())).numpy()
        small = np.abs(x[:, 0])
        assert np.abs(out_c[:, 0] - x[:, 0]).max() \
            < np.abs(out_t[:, 0] - x[:, 0]).max()
        assert small.max() > 0  # the comparison above was non-vacuous

    def test_symmetric_scheme_has_no_zero_point(self):
        # symmetric int8: zero maps to exactly zero and the grid is odd
        # (q(-x) == -q(x)) — there is no zero-point offset to carry
        x = np.array([0.0, 0.37, -0.37, 0.99, -0.99], np.float32)
        out = np.asarray(Q.quant_dequant(x, scale=1.0, bit_length=8))
        assert out[0] == 0.0
        np.testing.assert_allclose(out[1::2], -out[2::2])

    def test_bf16_inputs(self):
        x = np.random.RandomState(2).randn(8, 16).astype(np.float32)
        t = pt.to_tensor(x).astype("bfloat16")
        obs = Q.AbsmaxObserver()
        obs.observe(t)
        # bf16 rounds the input, so the scale matches within bf16 eps
        assert obs.scales() == pytest.approx(np.abs(x).max(), rel=0.01)
        per_c = Q.PerChannelAbsmaxObserver(quant_axis_=1)
        per_c.observe(t)
        assert np.asarray(per_c.scales()).shape == (16,)
        out = Q.quant_dequant(t, scale=float(obs.scales()), bit_length=8)
        assert "bfloat16" in str(out.dtype)          # dtype preserved
        step = float(obs.scales()) / 127
        np.testing.assert_allclose(
            np.asarray(out.numpy(), np.float32), x,
            atol=step / 2 + 0.01 * np.abs(x).max())  # grid + bf16 rounding

    def test_zero_input_degenerate(self):
        obs = Q.AbsmaxObserver()
        assert obs.scales() == pytest.approx(1e-9)   # never-observed floor
        obs.observe(pt.to_tensor(np.zeros(4, np.float32)))
        assert obs.scales() == 0.0
        out = np.asarray(Q.quant_dequant(np.zeros(4, np.float32),
                                         scale=obs.scales()))
        assert np.isfinite(out).all() and not out.any()


class TestQuantKernels:
    """ops/quant_kernels: the serve-side int8 pack/unpack + w8a16
    matmul (every raw quant-dtype cast in the tree lives there)."""

    def _wx(self):
        rng = np.random.RandomState(0)
        x = rng.randn(5, 16).astype(np.float32)
        w = (rng.randn(16, 8) * np.linspace(0.1, 4.0, 8)) \
            .astype(np.float32)
        return x, w

    def test_quantize_weight_shapes_dtypes_grid(self):
        from paddle_tpu.ops import quant_kernels as qk
        x, w = self._wx()
        q, s = qk.quantize_weight(w, axis=1)
        assert str(q.dtype) == "int8" and q.shape == w.shape
        assert s.shape == (8,) and str(s.dtype) == "float32"
        assert np.abs(np.asarray(q, np.int32)).max() <= 127
        deq = np.asarray(qk.dequantize_weight(q, s, axis=1))
        # round-to-nearest on each column's grid: error <= scale/2
        assert np.all(np.abs(deq - w) <= np.asarray(s)[None, :] / 2 + 1e-7)

    def test_quantize_weight_zero_channel(self):
        from paddle_tpu.ops import quant_kernels as qk
        w = np.zeros((4, 3), np.float32)
        w[:, 1] = [1.0, -2.0, 0.5, 0.0]
        q, s = qk.quantize_weight(w, axis=1)
        assert np.isfinite(np.asarray(s)).all()
        deq = np.asarray(qk.dequantize_weight(q, s, axis=1))
        assert not deq[:, 0].any() and not deq[:, 2].any()

    def test_quantize_kv_row_independent_and_roundtrip(self):
        from paddle_tpu.ops import quant_kernels as qk
        kv = np.random.RandomState(3).randn(6, 2, 16).astype(np.float32)
        qb, sb = qk.quantize_kv(kv)
        assert qb.shape == kv.shape and sb.shape == (6, 2)
        # a row's stored bytes must not depend on its batch neighbours
        # (the continuous-batching bit-identity contract at int8)
        q1, s1 = qk.quantize_kv(kv[3])
        assert np.array_equal(np.asarray(qb)[3], np.asarray(q1))
        assert np.array_equal(np.asarray(sb)[3], np.asarray(s1))
        deq = np.asarray(qk.dequantize_kv(qb, sb))
        assert np.all(np.abs(deq - kv) <= np.asarray(sb)[..., None] / 2
                      + 1e-7)

    def test_w8a16_matmul_reference_numerics(self):
        from paddle_tpu.ops import quant_kernels as qk
        x, w = self._wx()
        q, s = qk.quantize_weight(w, axis=1)
        got = np.asarray(qk.w8a16_matmul_reference(x, q, s))
        # (x @ q) * s is x @ dequant(q, s) up to f32 reassociation
        deq = np.asarray(qk.dequantize_weight(q, s, axis=1))
        np.testing.assert_allclose(got, x @ deq, atol=1e-4)
        # and within the analytic quant bound of the fp32 matmul
        bound = np.abs(x) @ np.ones_like(w) * (np.asarray(s) / 2)
        assert np.all(np.abs(got - x @ w) <= bound + 1e-5)

    def test_w8a16_pallas_interpret_bit_identical_to_mirror(self):
        import jax.numpy as jnp
        from paddle_tpu.ops import quant_kernels as qk
        x, w = self._wx()          # m=5, n=8: both block pads exercised
        q, s = qk.quantize_weight(w, axis=1)
        out_p = np.asarray(qk.w8a16_matmul(jnp.asarray(x), q, s,
                                           use_pallas=True,
                                           interpret=True))
        out_r = np.asarray(qk.w8a16_matmul_reference(jnp.asarray(x), q, s))
        assert np.array_equal(out_p, out_r)

    def test_w8a16_bf16_activations(self):
        import jax.numpy as jnp
        from paddle_tpu.ops import quant_kernels as qk
        x, w = self._wx()
        q, s = qk.quantize_weight(w, axis=1)
        ref = np.asarray(qk.w8a16_matmul_reference(x, q, s))
        out = qk.w8a16_matmul_reference(jnp.asarray(x, jnp.bfloat16), q, s)
        assert str(out.dtype) == "bfloat16"          # "a16" half honoured
        rel = np.abs(np.asarray(out, np.float32) - ref).max() \
            / np.abs(ref).max()
        assert rel < 0.02                            # bf16 rounding only

    def test_kernel_schema_has_quant_entries(self):
        from paddle_tpu.ops.autotune import KERNEL_SCHEMA
        assert "w8a16_matmul" in KERNEL_SCHEMA
        assert "paged_attention_int8" in KERNEL_SCHEMA

    def test_paged_attention_int8_matches_fp32_within_quant_tol(self):
        from paddle_tpu.ops import quant_kernels as qk
        from paddle_tpu.ops.paged_attention import (
            paged_attention_reference, paged_attention_int8,
            paged_attention_int8_reference)
        rng = np.random.RandomState(4)
        P, ps, H, D = 5, 4, 2, 8
        kp = rng.randn(P, ps, H, D).astype(np.float32)
        vp = rng.randn(P, ps, H, D).astype(np.float32)
        kq, ks = qk.quantize_kv(kp)
        vq, vs = qk.quantize_kv(vp)
        qact = rng.randn(2, H, D).astype(np.float32)
        ptab = np.array([[0, 2], [3, 1]], np.int32)
        ln = np.array([3, 7], np.int32)
        o32 = np.asarray(paged_attention_reference(qact, kp, vp, ptab, ln))
        o8 = np.asarray(paged_attention_int8_reference(
            qact, kq, vq, ks, vs, ptab, ln))
        np.testing.assert_allclose(o8, o32, atol=0.05)
        # the CPU dispatcher must be the reference bit-for-bit — the
        # serve path's numerics definition off-TPU
        o8d = np.asarray(paged_attention_int8(qact, kq, vq, ks, vs,
                                              ptab, ln))
        assert np.array_equal(o8d, o8)
