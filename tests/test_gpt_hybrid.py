"""GPT flagship + hybrid-parallel train step on the virtual 8-device mesh.

The oracle mirrors the reference's hybrid tests
(``test/collective/fleet/hybrid_parallel_mp_model.py``): the sharded
compiled step must match the replicated single-device computation.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.tensor import Tensor
from paddle_tpu.incubate.models import (GPTConfig, GPTForCausalLM,
                                        GPTPretrainingCriterion, gpt_tiny)
from paddle_tpu.distributed.train_step import build_train_step


@pytest.fixture(autouse=True)
def _reset():
    yield
    dist.set_mesh(None)
    dist.destroy_process_group()


def _tiny(tp=True, **kw):
    cfg = gpt_tiny(tensor_parallel=tp, **kw)
    cfg.hidden_dropout_prob = 0.0
    cfg.attention_probs_dropout_prob = 0.0
    return cfg


@pytest.mark.slow
def test_gpt_forward_shapes():
    dist.init_mesh({"dp": 8})
    pt.seed(0)
    model = GPTForCausalLM(_tiny())
    ids = Tensor(np.random.RandomState(0).randint(0, 1024, (2, 16))
                 .astype(np.int32))
    logits = model(ids)
    assert logits.shape == [2, 16, 1024]


@pytest.mark.slow
def test_gpt_loss_backward_eager():
    dist.init_mesh({"dp": 8})
    pt.seed(0)
    model = GPTForCausalLM(_tiny(tp=False))
    crit = GPTPretrainingCriterion()
    ids = Tensor(np.random.RandomState(1).randint(0, 1024, (2, 16))
                 .astype(np.int32))
    labels = Tensor(np.random.RandomState(2).randint(0, 1024, (2, 16))
                    .astype(np.int32))
    loss = crit(model(ids), labels)
    assert loss.size == 1
    loss.backward()
    some_param = model.gpt.embeddings.word_embeddings.weight
    assert some_param.grad is not None
    assert np.isfinite(float(loss))


@pytest.mark.slow
def test_gpt_hybrid_train_step_matches_single_device():
    """dp2 × mp2 × sharding2 compiled step == single-device step."""
    pt.seed(0)
    cfg = _tiny(tp=True)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()

    rng = np.random.RandomState(3)
    ids = rng.randint(0, 1024, (4, 16)).astype(np.int32)
    labels = rng.randint(0, 1024, (4, 16)).astype(np.int32)

    def loss_fn(logits, lab):
        return crit(logits, lab)

    # single-device (dp-only mesh degenerates to replication)
    dist.init_mesh({"dp": 1})
    opt1 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step1, state1 = build_train_step(model, loss_fn, opt1)
    loss_ref, state1 = step1(state1, ids, labels)

    # hybrid mesh — SAME initial params (re-extracted from the layer,
    # which still holds the original arrays)
    from paddle_tpu.distributed.fleet.meta_parallel.sharding_parallel \
        import annotate_fsdp_specs
    dist.init_mesh({"dp": 2, "mp": 2, "sharding": 2})
    annotate_fsdp_specs(model, min_size=16)
    opt2 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step2, state2 = build_train_step(model, loss_fn, opt2)
    loss_hyb, state2 = step2(state2, ids, labels)

    np.testing.assert_allclose(float(loss_ref), float(loss_hyb),
                               rtol=2e-4, atol=2e-4)
    # updated params must match too (same math, different partitioning)
    k = "gpt.final_ln.weight"
    np.testing.assert_allclose(
        np.asarray(state1["params"][k]), np.asarray(state2["params"][k]),
        rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_gpt_recompute_matches_plain():
    pt.seed(0)
    dist.init_mesh({"dp": 1})
    cfg = _tiny(tp=False)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    ids = np.random.RandomState(5).randint(0, 1024, (2, 16)).astype(np.int32)
    labels = np.random.RandomState(6).randint(0, 1024, (2, 16)) \
        .astype(np.int32)

    def loss_fn(logits, lab):
        return crit(logits, lab)

    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    step, state = build_train_step(model, loss_fn, opt)
    loss_plain, _ = step(state, ids, labels)

    model.gpt.use_recompute = True
    opt2 = pt.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    step2, state2 = build_train_step(model, loss_fn, opt2)
    loss_rc, _ = step2(state2, ids, labels)
    np.testing.assert_allclose(float(loss_plain), float(loss_rc),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_gpt_rope_variant_runs():
    dist.init_mesh({"dp": 1})
    pt.seed(0)
    cfg = _tiny(tp=False, use_rope=True)
    model = GPTForCausalLM(cfg)
    ids = Tensor(np.random.RandomState(7).randint(0, 1024, (2, 8))
                 .astype(np.int32))
    logits = model(ids)
    assert logits.shape == [2, 8, 1024]


@pytest.mark.slow
def test_gpt_pipeline_pp2_matches_single_device():
    """dp2 × mp2 × pp2 compiled 1F1B == single-device step, 3 steps.

    Ref oracle: hybrid_parallel numeric parity
    (test/collective/fleet/hybrid_parallel_mp_model.py) applied to the
    pipeline schedule (pipeline_parallel.py:372 forward_backward_pipeline).
    """
    pt.seed(0)
    cfg = _tiny(tp=True)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(11)
    ids = rng.randint(0, 1024, (8, 16)).astype(np.int32)
    labels = rng.randint(0, 1024, (8, 16)).astype(np.int32)

    def loss_fn(logits, lab):
        return crit(logits, lab)

    dist.init_mesh({"dp": 1})
    opt1 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step1, state1 = build_train_step(model, loss_fn, opt1)
    ref = []
    for _ in range(3):
        loss, state1 = step1(state1, ids, labels)
        ref.append(float(loss))

    dist.init_mesh({"dp": 2, "mp": 2, "pp": 2})
    opt2 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step2, state2 = build_train_step(model, loss_fn, opt2)
    # stacked state layout: block params live in __ppstack__ leaves
    assert any(k.startswith("__ppstack__.") for k in state2["params"])
    got = []
    for _ in range(3):
        loss, state2 = step2(state2, ids, labels)
        got.append(float(loss))
    np.testing.assert_allclose(ref, got, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_gpt_pipeline_pp4_microbatches():
    """pp4 with 4 blocks (L=1) and M=8 microbatches matches pp=1."""
    pt.seed(0)
    cfg = _tiny(tp=False)
    cfg.num_layers = 4
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(13)
    ids = rng.randint(0, 1024, (8, 16)).astype(np.int32)
    labels = rng.randint(0, 1024, (8, 16)).astype(np.int32)

    def loss_fn(logits, lab):
        return crit(logits, lab)

    dist.init_mesh({"dp": 1})
    opt1 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step1, state1 = build_train_step(model, loss_fn, opt1)
    loss_ref, _ = step1(state1, ids, labels)

    dist.init_mesh({"dp": 2, "pp": 4})
    opt2 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step2, state2 = build_train_step(model, loss_fn, opt2,
                                     pipeline_microbatches=8)
    loss_pp, _ = step2(state2, ids, labels)
    np.testing.assert_allclose(float(loss_ref), float(loss_pp),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_pipeline_spmd_stage_sharding():
    """Stacked block params are physically sharded over pp (the memory
    win ZeRO-style asserted on sharding specs, VERDICT weak #4)."""
    pt.seed(0)
    cfg = _tiny(tp=False)
    cfg.num_layers = 4
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    dist.init_mesh({"dp": 2, "pp": 4})
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())

    def loss_fn(logits, lab):
        return crit(logits, lab)

    step, state = build_train_step(model, loss_fn, opt)
    stacked = {k: v for k, v in state["params"].items()
               if k.startswith("__ppstack__.")}
    assert stacked
    for k, v in stacked.items():
        spec = v.sharding.spec
        assert spec[0] == "pp", (k, spec)
        # optimizer slots inherit the stacked sharding
        for s in state["opt"]["slots"]:
            assert state["opt"]["slots"][s][k].sharding.spec[0] == "pp"


@pytest.mark.slow
def test_gpt_pipeline_with_attention_mask_extras():
    """Per-sample attention masks are micro-batched through the pipeline
    (each stage indexes the mask at its own micro-batch offset)."""
    pt.seed(0)
    cfg = _tiny(tp=False)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(17)
    B, S = 8, 16
    ids = rng.randint(0, 1024, (B, S)).astype(np.int32)
    labels = rng.randint(0, 1024, (B, S)).astype(np.int32)
    # causal mask with per-sample random padding: additive -inf style
    causal = np.tril(np.ones((S, S), np.float32))
    keep = (rng.rand(B, S) > 0.2).astype(np.float32)
    mask = causal[None, None] * keep[:, None, None, :]
    mask_add = np.where(mask > 0, 0.0, -1e9).astype(np.float32)

    def loss_fn(logits, lab):
        return crit(logits, lab)

    import functools

    dist.init_mesh({"dp": 1})
    # drive forward with the mask via functools.partial through
    # build_train_step's single-input contract
    model._orig_forward = functools.partial(
        model.forward, attention_mask=Tensor(np.asarray(mask_add)))
    opt1 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step1, state1 = build_train_step(model, loss_fn, opt1)
    loss_ref, _ = step1(state1, ids, labels)

    dist.init_mesh({"dp": 2, "pp": 2})
    opt2 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step2, state2 = build_train_step(model, loss_fn, opt2,
                                     pipeline_microbatches=4)
    loss_pp, _ = step2(state2, ids, labels)
    del model._orig_forward
    np.testing.assert_allclose(float(loss_ref), float(loss_pp),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_gpt_interleaved_pipeline_pp2_v2_matches_single_device():
    """Interleaved virtual stages (ref pipeline_parallel.py:807): pp2 with
    v=2 (4 blocks -> 4 virtual stages of 1 block, chip s owns vstages
    {s, s+2}) matches the single-device step over 3 steps."""
    pt.seed(0)
    cfg = _tiny(tp=False)
    cfg.num_layers = 4
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(17)
    ids = rng.randint(0, 1024, (8, 16)).astype(np.int32)
    labels = rng.randint(0, 1024, (8, 16)).astype(np.int32)

    def loss_fn(logits, lab):
        return crit(logits, lab)

    dist.init_mesh({"dp": 1})
    opt1 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step1, state1 = build_train_step(model, loss_fn, opt1)
    ref = []
    for _ in range(3):
        loss, state1 = step1(state1, ids, labels)
        ref.append(float(loss))

    dist.init_mesh({"dp": 4, "pp": 2})
    opt2 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step2, state2 = build_train_step(model, loss_fn, opt2,
                                     pipeline_microbatches=4,
                                     pipeline_virtual_stages=2)
    # interleaved layout: [v, pp*Lv, ...] sharded P(None, 'pp', ...)
    stacked = {k: a for k, a in state2["params"].items()
               if k.startswith("__ppstack__.")}
    assert stacked
    for k, a in stacked.items():
        assert a.shape[0] == 2, (k, a.shape)
        spec = a.sharding.spec
        assert spec[0] is None and spec[1] == "pp", (k, spec)
        # each chip stores 1/pp of the stacked blocks (the memory win
        # survives interleaving)
        assert a.addressable_shards[0].data.size == a.size // 2
    got = []
    for _ in range(3):
        loss, state2 = step2(state2, ids, labels)
        got.append(float(loss))
    np.testing.assert_allclose(ref, got, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_gpt_interleaved_pipeline_pp4_v2():
    """pp4 × v=2 over 8 blocks (Lv=1), M=8 microbatches == pp1 oracle."""
    pt.seed(0)
    cfg = _tiny(tp=False)
    cfg.num_layers = 8
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(19)
    ids = rng.randint(0, 1024, (8, 16)).astype(np.int32)
    labels = rng.randint(0, 1024, (8, 16)).astype(np.int32)

    def loss_fn(logits, lab):
        return crit(logits, lab)

    dist.init_mesh({"dp": 1})
    opt1 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step1, state1 = build_train_step(model, loss_fn, opt1)
    loss_ref, _ = step1(state1, ids, labels)

    dist.init_mesh({"dp": 2, "pp": 4})
    opt2 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
    step2, state2 = build_train_step(model, loss_fn, opt2,
                                     pipeline_microbatches=8,
                                     pipeline_virtual_stages=2)
    loss_pp, _ = step2(state2, ids, labels)
    np.testing.assert_allclose(float(loss_ref), float(loss_pp),
                               rtol=2e-4, atol=2e-4)
