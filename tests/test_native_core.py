"""Native runtime core tests (paddle_tpu.core over libptcore.so).

Mirrors the reference's C++ runtime unit tests (test/cpp/phi, the
custom-device capi_test) at the ctypes boundary: tracer spans, flag table,
host buffer pool semantics, workqueue drain, and TCPStore set/get/wait/add
across processes.
"""
from __future__ import annotations

import json
import multiprocessing
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu.core as core

pytestmark = pytest.mark.skipif(
    not core.native_available(), reason="no C++ toolchain for native core")


class TestTracer:
    def setup_method(self):
        core.tracer_clear()
        core.tracer_enable()

    def teardown_method(self):
        core.tracer_disable()
        core.tracer_clear()

    def test_spans_nested(self):
        with core.RecordEvent("outer"):
            with core.RecordEvent("inner"):
                time.sleep(0.002)
        names = {e[0] for e in core.tracer_events()}
        assert {"outer", "inner"} <= names
        # inner nested within outer: shorter duration
        ev = {e[0]: e for e in core.tracer_events()}
        assert ev["inner"][2] <= ev["outer"][2]

    def test_disabled_push_pop_balanced(self):
        core.tracer_disable()
        with core.RecordEvent("ghost"):
            pass
        core.tracer_enable()
        with core.RecordEvent("real"):
            pass
        names = [e[0] for e in core.tracer_events()]
        assert "ghost" not in names and "real" in names

    def test_chrome_dump(self, tmp_path):
        with core.RecordEvent("step"):
            time.sleep(0.001)
        out = tmp_path / "trace.json"
        core.tracer_dump(str(out))
        j = json.loads(out.read_text())
        assert any(e["name"] == "step" and e["ph"] == "X"
                   for e in j["traceEvents"])

    def test_decorator(self):
        @core.RecordEvent("fn_span")
        def f(x):
            return x + 1
        assert f(1) == 2
        assert "fn_span" in [e[0] for e in core.tracer_events()]

    def test_multithreaded(self):
        def work(i):
            with core.RecordEvent(f"t{i}"):
                time.sleep(0.001)
        ts = [threading.Thread(target=work, args=(i,)) for i in range(8)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        names = {e[0] for e in core.tracer_events()}
        assert {f"t{i}" for i in range(8)} <= names


class TestFlags:
    def test_native_mirror(self):
        import paddle_tpu as pt
        pt.set_flags({"check_nan_inf": True})
        lib = core._load()
        import ctypes
        buf = ctypes.create_string_buffer(64)
        n = lib.pt_flag_get(b"check_nan_inf", buf, 64)
        assert n > 0 and buf.value == b"True"
        pt.set_flags({"check_nan_inf": False})


class TestHostPool:
    def test_reuse_and_stats(self):
        pool = core.HostBufferPool()
        mv1, tok1 = pool.take(4096)
        mv1[:8] = b"01234567"
        assert np.frombuffer(mv1, np.uint8)[:8].tobytes() == b"01234567"
        s1 = core.host_memory_stats()
        assert s1["allocated"] >= 4096 and s1["reserved"] >= s1["allocated"]
        pool.give(tok1)
        s2 = core.host_memory_stats()
        assert s2["allocated"] == s1["allocated"] - 4096
        # freed block is reused (best-fit) without growing reserved
        mv2, tok2 = pool.take(4096)
        assert core.host_memory_stats()["reserved"] == s2["reserved"]
        pool.give(tok2)

    def test_many_sizes(self):
        pool = core.HostBufferPool()
        toks = []
        for sz in [1, 63, 64, 65, 1 << 10, 1 << 16, (1 << 20) + 3]:
            mv, tok = pool.take(sz)
            assert len(mv) == sz
            mv[-1:] = b"\x07"
            toks.append(tok)
        for t in toks:
            pool.give(t)
        released = pool.release_free()
        assert released >= 0  # chunks fully coalesced can be released


class TestWorkQueue:
    def test_drain(self):
        wq = core.WorkQueue(4)
        hits = []
        lock = threading.Lock()
        for i in range(200):
            def job(i=i):
                with lock:
                    hits.append(i)
            wq.submit(job)
        wq.wait()
        assert sorted(hits) == list(range(200))
        assert wq.pending() == 0
        wq.shutdown()

    def test_job_error_does_not_kill_pool(self, capsys):
        wq = core.WorkQueue(2)
        done = []
        wq.submit(lambda: 1 / 0)
        wq.submit(lambda: done.append(1))
        wq.wait()
        assert done == [1]
        wq.shutdown()


def _store_worker(port, rank, q):
    import paddle_tpu.core as core
    c = core.TCPStore("127.0.0.1", port)
    c.set(f"rank{rank}", str(rank))
    n = c.add("barrier", 1)
    # blocking get: master sets "go" only after all ranks arrive
    q.put((rank, c.get("go"), n))
    c.close()


class TestTCPStore:
    def test_set_get_add(self):
        s = core.TCPStore(is_master=True)
        s.set("k", b"v1")
        assert s.get("k") == b"v1"
        assert s.get("missing", wait=False) is None
        assert s.add("ctr", 3) == 3
        assert s.add("ctr", -1) == 2
        s.delete("k")
        assert s.get("k", wait=False) is None
        s.close()

    @pytest.mark.slow
    def test_multiprocess_rendezvous(self):
        ctx = multiprocessing.get_context("spawn")
        s = core.TCPStore(is_master=True)
        q = ctx.Queue()
        ps = [ctx.Process(target=_store_worker, args=(s.port, r, q))
              for r in range(3)]
        [p.start() for p in ps]
        # wait until all 3 hit the barrier, then release them
        while True:
            got = s.get("barrier", wait=False)
            if got is not None and int.from_bytes(got, "little",
                                                  signed=True) == 3:
                break
            time.sleep(0.01)
        s.set("go", b"now")
        results = [q.get(timeout=30) for _ in range(3)]
        [p.join(timeout=30) for p in ps]
        assert {r[0] for r in results} == {0, 1, 2}
        assert all(r[1] == b"now" for r in results)
        assert {r[2] for r in results} == {1, 2, 3}
        for r in range(3):
            assert s.get(f"rank{r}") == str(r).encode()
        s.close()


class TestShmSegment:
    """Shared-memory batch transport (native shm.cc; ref
    mmap_allocator.cc)."""

    def test_create_attach_roundtrip(self):
        import os
        from paddle_tpu.core import ShmSegment, shm_available
        if not shm_available():
            pytest.skip("native core unavailable")
        name = f"/pt_test_{os.getpid()}"
        seg = ShmSegment.create(name, 64)
        seg.buffer()[:5] = b"hello"
        seg.close()
        seg2 = ShmSegment.attach(name, 64)
        assert bytes(seg2.buffer()[:5]) == b"hello"
        seg2.close()
        seg2.unlink()
        with pytest.raises(OSError):
            ShmSegment.attach(name, 64)  # unlinked

    def test_dataloader_pack_unpack(self):
        import os
        from paddle_tpu.core import shm_available
        if not shm_available():
            pytest.skip("native core unavailable")
        from paddle_tpu.io.dataloader import _shm_pack, _shm_unpack
        rng = np.random.RandomState(0)
        batch = (rng.randn(8, 3).astype(np.float32),
                 {"y": rng.randint(0, 5, (8,)).astype(np.int64),
                  "tag": "keep-me"})
        payload = _shm_pack(batch, f"/pt_test_dl_{os.getpid()}")
        assert payload is not None
        out = _shm_unpack(payload)
        np.testing.assert_array_equal(out[0], batch[0])
        np.testing.assert_array_equal(out[1]["y"], batch[1]["y"])
        assert out[1]["tag"] == "keep-me"

    def test_dataloader_shared_memory_e2e(self):
        from paddle_tpu.io import DataLoader
        from paddle_tpu.io.dataset import Dataset

        class DS(Dataset):
            def __getitem__(self, i):
                return (np.full((4, 4), i, np.float32),
                        np.int64(i))

            def __len__(self):
                return 16

        dl = DataLoader(DS(), batch_size=4, num_workers=2, shuffle=False,
                        use_shared_memory=True)
        seen = []
        for x, y in dl:
            assert x.shape == [4, 4, 4]
            seen.extend(np.asarray(y._data).tolist())
        assert sorted(seen) == list(range(16))
