"""Auto-parallel Engine: strategy-driven fit/evaluate/predict e2e (ref:
``python/paddle/distributed/auto_parallel/static/engine.py:55,854``).

Acceptance test per SURVEY §2: BERT finetune through Engine.fit on the
8-device virtual CPU mesh, with strategy toggles (AMP, ZeRO sharding)
actually changing the built step."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.auto_parallel import Engine, to_static
from paddle_tpu.distributed.fleet.base.distributed_strategy import (
    DistributedStrategy)
from paddle_tpu.io import Dataset


@pytest.fixture(autouse=True)
def _reset():
    yield
    dist.set_mesh(None)
    dist.destroy_process_group()


class _SST2Toy(Dataset):
    """Tiny SST-2-shaped dataset: (input_ids, label)."""

    def __init__(self, n=32, seq=16, vocab=1024, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randint(0, vocab, (n, seq)).astype(np.int32)
        self.y = (self.x.sum(-1) % 2).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _bert():
    from paddle_tpu.incubate.models import (bert_tiny,
                                            BertForSequenceClassification)
    pt.seed(11)
    cfg = bert_tiny()
    cfg.hidden_dropout_prob = 0.0
    cfg.attention_probs_dropout_prob = 0.0
    return BertForSequenceClassification(cfg, num_classes=2)


def _loss(out, y):
    return pt.nn.functional.cross_entropy(out, y)


@pytest.mark.slow
def test_engine_fit_bert_loss_decreases():
    dist.init_mesh({"dp": 4, "mp": 2})
    model = _bert()
    opt = pt.optimizer.AdamW(learning_rate=5e-3,
                             parameters=model.parameters())
    eng = Engine(model, loss=_loss, optimizer=opt)
    hist = eng.fit(_SST2Toy(), batch_size=8, epochs=4, verbose=0)
    assert len(hist["loss"]) == 4
    assert hist["loss"][-1] < hist["loss"][0], hist["loss"]


@pytest.mark.slow
def test_engine_evaluate_and_predict():
    dist.init_mesh({"dp": 8})
    model = _bert()
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    eng = Engine(model, loss=_loss, optimizer=opt,
                 metrics=pt.metric.Accuracy())
    eng.fit(_SST2Toy(), batch_size=8, epochs=1, verbose=0)
    out = eng.evaluate(_SST2Toy(), batch_size=8, verbose=0)
    assert "loss" in out and np.isfinite(out["loss"])
    assert "acc" in out and 0.0 <= out["acc"] <= 1.0
    preds = eng.predict(_SST2Toy(n=8), batch_size=8, verbose=0)
    assert preds[0].shape == (8, 2)


@pytest.mark.slow
def test_engine_strategy_amp_and_sharding():
    """strategy.amp builds a compiled scaler; strategy.sharding partitions
    the optimizer state over the sharding axis."""
    dist.init_mesh({"dp": 2, "sharding": 4})
    model = _bert()
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    s = DistributedStrategy()
    s.amp = True
    s.amp_configs = {"use_bf16": True}
    s.sharding = True
    s.sharding_configs = {"stage": 2}
    eng = Engine(model, loss=_loss, optimizer=opt, strategy=s)
    hist = eng.fit(_SST2Toy(), batch_size=8, epochs=1, verbose=0)
    assert np.isfinite(hist["loss"][0])
    assert "scaler" in eng._state                     # compiled AMP scaler
    assert opt._group_sharded_level == "os_g"         # stage 2 applied
    m1 = eng._state["opt"]["slots"]["moment1"]
    sharded = [k for k, v in m1.items()
               if "sharding" in str(v.sharding.spec)]
    assert sharded, "no optimizer-state leaf was ZeRO-partitioned"
    # bf16 O2: params cast, master weights exist
    assert eng._state["opt"]["master"], "O2 master weights missing"


@pytest.mark.slow
def test_engine_save_load_roundtrip(tmp_path):
    dist.init_mesh({"dp": 8})
    model = _bert()
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    eng = Engine(model, loss=_loss, optimizer=opt)
    eng.fit(_SST2Toy(), batch_size=8, epochs=1, verbose=0)
    path = str(tmp_path / "ckpt")
    eng.save(path)
    w = np.asarray(eng._state["params"]["classifier.weight"])

    model2 = _bert()
    opt2 = pt.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model2.parameters())
    eng2 = Engine(model2, loss=_loss, optimizer=opt2)
    eng2.load(path)
    w2 = np.asarray(eng2._state["params"]["classifier.weight"])
    np.testing.assert_allclose(w, w2)


@pytest.mark.slow
def test_to_static_returns_engine():
    dist.init_mesh({"dp": 8})
    model = _bert()
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    eng = to_static(model, loss=_loss, optimizer=opt)
    assert isinstance(eng, Engine)


@pytest.mark.slow
def test_engine_fp16_o1_strategy_casts_matmuls():
    """amp with use_bf16=False (fp16 O1) must actually change compute
    dtype inside the compiled step, not silently run fp32."""
    dist.init_mesh({"dp": 8})
    model = _bert()
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    s = DistributedStrategy()
    s.amp = True
    s.amp_configs = {"use_bf16": False}
    eng = Engine(model, loss=_loss, optimizer=opt, strategy=s)
    hist = eng.fit(_SST2Toy(), batch_size=8, epochs=1, verbose=0)
    assert np.isfinite(hist["loss"][0])
    assert "scaler" in eng._state
    # O1: params remain fp32 (no O2 decorate)
    assert str(eng._state["params"]["classifier.weight"].dtype) == "float32"


@pytest.mark.slow
def test_engine_without_optimizer_raises_clearly():
    dist.init_mesh({"dp": 8})
    model = _bert()
    eng = Engine(model, loss=_loss)
    with pytest.raises(ValueError, match="optimizer"):
        eng.fit(_SST2Toy(), batch_size=8, epochs=1, verbose=0)
