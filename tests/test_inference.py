"""Inference predictor tests (ref: test/inference API tests /
test_analysis_predictor)."""
from __future__ import annotations

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import inference as infer
from paddle_tpu.jit.api import InputSpec


def _save_jit_artifact(tmp_path):
    pt.seed(0)
    net = pt.nn.Sequential(pt.nn.Linear(8, 16), pt.nn.ReLU(),
                           pt.nn.Linear(16, 3))
    prefix = str(tmp_path / "model")
    pt.jit.save(net, prefix, input_spec=[InputSpec([4, 8], "float32")])
    return net, prefix


class TestPredictorJitArtifact:
    def test_handles_round_trip(self, tmp_path):
        net, prefix = _save_jit_artifact(tmp_path)
        cfg = infer.Config(prefix)
        pred = infer.create_predictor(cfg)
        names = pred.get_input_names()
        assert len(names) == 1
        X = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        h = pred.get_input_handle(names[0])
        h.copy_from_cpu(X)
        assert h.shape() == [4, 8]
        pred.run()
        out_name = pred.get_output_names()[0]
        out = pred.get_output_handle(out_name).copy_to_cpu()
        net.eval()
        want = net(pt.to_tensor(X)).numpy()
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)

    def test_convenience_run(self, tmp_path):
        net, prefix = _save_jit_artifact(tmp_path)
        pred = infer.create_predictor(infer.Config(prefix))
        X = np.random.RandomState(1).randn(4, 8).astype(np.float32)
        outs = pred.run([X])
        assert outs[0].shape == (4, 3)

    def test_predictor_pool(self, tmp_path):
        _, prefix = _save_jit_artifact(tmp_path)
        pool = infer.PredictorPool(infer.Config(prefix), size=2)
        X = np.ones((4, 8), np.float32)
        o1 = pool.retrive(0).run([X])[0]
        o2 = pool.retrieve(1).run([X])[0]
        np.testing.assert_allclose(o1, o2)

    def test_missing_artifact(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            infer.create_predictor(infer.Config(str(tmp_path / "nope")))


class TestPredictorStaticArtifact:
    def test_static_artifact(self, tmp_path):
        pt.enable_static()
        try:
            from paddle_tpu import static
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [2, 5], "float32")
                y = pt.nn.Linear(5, 3)(x)
            exe = static.Executor()
            exe.run(startup)
            X = np.random.RandomState(0).randn(2, 5).astype(np.float32)
            want, = exe.run(main, feed={"x": X}, fetch_list=[y])
            prefix = str(tmp_path / "sm")
            static.save_inference_model(prefix, [x], [y], exe)
        finally:
            pt.disable_static()
        pred = infer.create_predictor(infer.Config(prefix))
        assert pred.get_input_names() == ["x"]
        got = pred.run([X])[0]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestPredictorServedModel:
    """A serving-engine model dir routes through the AOT engine: the
    Predictor surface is unchanged, but run() is a full generate loop
    over the zero-compile serve graphs."""

    @pytest.fixture(scope="class")
    def served_dir(self, tmp_path_factory):
        from paddle_tpu.serving import (
            ModelSpec, ServeConfig, init_params, save_served_model)
        spec = ModelSpec(vocab_size=64, hidden=32, layers=1, heads=2,
                         max_seq_len=64)
        cfg = ServeConfig(decode_buckets=(2,), prefill_buckets=(16,),
                          kv_pages=16, page_size=4,
                          max_new_tokens=4)
        root = str(tmp_path_factory.mktemp("served") / "model")
        save_served_model(root, spec, init_params(spec, seed=0),
                          config=cfg, step=1)
        return root

    def test_served_dir_round_trip(self, served_dir):
        pred = infer.create_predictor(infer.Config(served_dir))
        assert pred.get_input_names() == ["tokens"]
        h = pred.get_input_handle("tokens")
        h.copy_from_cpu(np.array([5, 9, 2], np.int32))
        pred.run()
        out = pred.get_output_handle(
            pred.get_output_names()[0]).copy_to_cpu()
        assert out.dtype == np.int32 and out.shape == (4,)
        # same tokens as the engine's own generate path
        eng = pred._engine
        assert out.tolist() == eng.generate([[5, 9, 2]],
                                            max_new_tokens=4)[0]
        assert eng.unexpected_compiles == 0
        eng.close()

    def test_non_served_prefix_unaffected(self, tmp_path):
        # the routing probe must not misfire on ordinary jit artifacts
        _, prefix = _save_jit_artifact(tmp_path)
        pred = infer.create_predictor(infer.Config(prefix))
        assert pred._engine is None
        assert pred.get_input_names() != ["tokens"]


def test_precision_type_docstring_names_fluid():
    # the reference path is paddle/fluid/ — regression-pin the typo fix
    assert "paddle/fluid/" in infer.PrecisionType.__doc__
    assert "fidle" not in infer.PrecisionType.__doc__


@pytest.mark.slow
class TestFullModelRoundTrip:
    """VERDICT weak #7: full exported model artifacts must round-trip
    through the Predictor and match EAGER outputs at tolerance (the
    reference's analysis-predictor accuracy tests)."""

    def test_resnet18_export_matches_eager(self, tmp_path):
        pt.seed(3)
        net = pt.vision.models.resnet18(num_classes=10)
        net.eval()
        prefix = str(tmp_path / "resnet18")
        pt.jit.save(net, prefix,
                    input_spec=[InputSpec([2, 3, 32, 32], "float32")])
        X = np.random.RandomState(0).rand(2, 3, 32, 32).astype(np.float32)
        want = net(pt.to_tensor(X)).numpy()

        pred = infer.create_predictor(infer.Config(prefix))
        out = pred.run([X])[0]
        assert out.shape == (2, 10)
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-4)

    def test_bert_export_matches_eager(self, tmp_path):
        from paddle_tpu.incubate.models import (
            bert_tiny, BertForSequenceClassification)
        pt.seed(4)
        cfg = bert_tiny()
        cfg.hidden_dropout_prob = 0.0
        cfg.attention_probs_dropout_prob = 0.0
        net = BertForSequenceClassification(cfg, num_classes=2)
        net.eval()
        prefix = str(tmp_path / "bert")
        pt.jit.save(net, prefix,
                    input_spec=[InputSpec([2, 16], "int32")])
        ids = np.random.RandomState(1).randint(
            0, 1024, (2, 16)).astype(np.int32)
        want = net(pt.to_tensor(ids)).numpy()

        pred = infer.create_predictor(infer.Config(prefix))
        out = pred.run([ids])[0]
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-4)
