"""affine_grid / grid_sample (ref: python/paddle/nn/functional/vision.py
-> phi grid_sample kernels). Oracles: identity-transform passthrough,
integer-shift equivalence, manual bilinear math."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu.tensor import Tensor


def _x(N=1, C=2, H=5, W=5, seed=0):
    return np.random.RandomState(seed).randn(N, C, H, W).astype(np.float32)


def _identity_grid(N, H, W):
    theta = np.tile(np.array([[1, 0, 0], [0, 1, 0]], np.float32), (N, 1, 1))
    return F.affine_grid(Tensor(theta), [N, 1, H, W], align_corners=True)


def test_identity_affine_grid_samples_input_exactly():
    x = _x()
    grid = _identity_grid(1, 5, 5)
    out = F.grid_sample(Tensor(x), grid, align_corners=True)
    np.testing.assert_allclose(np.asarray(out._data), x, rtol=1e-5,
                               atol=1e-5)


def test_affine_grid_shape_and_range():
    g = np.asarray(_identity_grid(2, 4, 6)._data)
    assert g.shape == (2, 4, 6, 2)
    assert g.min() == -1.0 and g.max() == 1.0


def test_translation_shifts_pixels():
    x = _x(H=4, W=4)
    # shift one pixel right in normalized units (align_corners=True)
    theta = np.array([[[1, 0, 2.0 / 3.0], [0, 1, 0]]], np.float32)
    grid = F.affine_grid(Tensor(theta), [1, 1, 4, 4], align_corners=True)
    out = np.asarray(F.grid_sample(Tensor(x), grid,
                                   align_corners=True)._data)
    np.testing.assert_allclose(out[..., :3], x[..., 1:], rtol=1e-4,
                               atol=1e-5)
    # zeros padding beyond the right edge
    np.testing.assert_allclose(out[..., 3], 0.0, atol=1e-6)


def test_border_and_reflection_padding():
    x = _x(H=4, W=4)
    theta = np.array([[[1, 0, 1.0], [0, 1, 0]]], np.float32)  # big shift
    grid = F.affine_grid(Tensor(theta), [1, 1, 4, 4], align_corners=True)
    border = np.asarray(F.grid_sample(Tensor(x), grid,
                                      padding_mode="border",
                                      align_corners=True)._data)
    np.testing.assert_allclose(border[..., -1], x[..., -1], rtol=1e-5)
    refl = np.asarray(F.grid_sample(Tensor(x), grid,
                                    padding_mode="reflection",
                                    align_corners=True)._data)
    assert np.all(np.isfinite(refl))


def test_nearest_mode_matches_rounding():
    x = _x(H=3, W=3)
    grid = _identity_grid(1, 3, 3)
    out = np.asarray(F.grid_sample(Tensor(x), grid, mode="nearest",
                                   align_corners=True)._data)
    np.testing.assert_allclose(out, x, rtol=1e-6)


def test_manual_bilinear_point():
    x = np.zeros((1, 1, 2, 2), np.float32)
    x[0, 0] = [[1.0, 2.0], [3.0, 4.0]]
    # sample the exact center: average of all four
    grid = np.zeros((1, 1, 1, 2), np.float32)
    out = F.grid_sample(Tensor(x), Tensor(grid), align_corners=True)
    assert abs(float(out._data.reshape(())) - 2.5) < 1e-6


def test_gradients_flow_through_sampler():
    x = Tensor(_x())
    x.stop_gradient = False
    theta = Tensor(np.array([[[1, 0, 0.1], [0, 1, -0.1]]], np.float32))
    theta.stop_gradient = False
    grid = F.affine_grid(theta, [1, 1, 5, 5], align_corners=True)
    out = F.grid_sample(x, grid, align_corners=True)
    out.sum().backward()
    assert np.abs(np.asarray(x.grad._data)).sum() > 0
    assert np.abs(np.asarray(theta.grad._data)).sum() > 0
