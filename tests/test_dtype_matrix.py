"""bf16 + f32 dtype matrix over pooling/conv/norm functionals.

Regression shield for the round-1 bench crash: `max_pool2d` on bfloat16 fell
into `jnp.iinfo` because numpy's `dtype.kind` is 'V' for bfloat16
(pooling.py). Every functional that the AMP-O2 CNN fast path touches must
run under BOTH float32 and bfloat16 (ref test pattern:
`test/legacy_test/eager_op_test.py` dtype sweeps + `test/amp/`).
"""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu.tensor import Tensor

DTYPES = ["float32", "bfloat16"]


def _x(shape, dtype, seed=0):
    rng = np.random.RandomState(seed)
    return Tensor(jnp.asarray(rng.randn(*shape).astype(np.float32)).astype(dtype))


@pytest.mark.parametrize("dtype", DTYPES)
class TestPoolingDtypes:
    def test_max_pool2d(self, dtype):
        out = F.max_pool2d(_x((2, 3, 8, 8), dtype), 2)
        assert out.dtype == getattr(pt, dtype) and out.shape == [2, 3, 4, 4]

    def test_max_pool2d_mask(self, dtype):
        out, mask = F.max_pool2d(_x((2, 3, 8, 8), dtype), 2, return_mask=True)
        assert mask.shape == [2, 3, 4, 4]

    def test_max_pool1d(self, dtype):
        assert F.max_pool1d(_x((2, 3, 8), dtype), 2).shape == [2, 3, 4]

    def test_max_pool3d(self, dtype):
        assert F.max_pool3d(_x((1, 2, 4, 4, 4), dtype), 2).shape == \
            [1, 2, 2, 2, 2]

    def test_avg_pool2d(self, dtype):
        out = F.avg_pool2d(_x((2, 3, 8, 8), dtype), 2)
        assert out.shape == [2, 3, 4, 4]

    def test_avg_pool2d_padded(self, dtype):
        out = F.avg_pool2d(_x((2, 3, 8, 8), dtype), 3, stride=2, padding=1)
        assert out.shape == [2, 3, 4, 4]

    def test_max_pool2d_ceil(self, dtype):
        out = F.max_pool2d(_x((2, 3, 7, 7), dtype), 2, ceil_mode=True)
        assert out.shape == [2, 3, 4, 4]

    def test_adaptive_avg_pool2d(self, dtype):
        assert F.adaptive_avg_pool2d(_x((2, 3, 8, 8), dtype), 1).shape == \
            [2, 3, 1, 1]

    def test_adaptive_max_pool2d(self, dtype):
        assert F.adaptive_max_pool2d(_x((2, 3, 9, 9), dtype), 3).shape == \
            [2, 3, 3, 3]

    def test_lp_pool2d(self, dtype):
        assert F.lp_pool2d(_x((2, 3, 8, 8), dtype), 2.0, 2).shape == \
            [2, 3, 4, 4]


@pytest.mark.parametrize("dtype", DTYPES)
class TestConvNormDtypes:
    def test_conv2d(self, dtype):
        w = _x((4, 3, 3, 3), dtype, 1)
        out = F.conv2d(_x((2, 3, 8, 8), dtype), w, padding=1)
        assert out.shape == [2, 4, 8, 8]

    def test_conv2d_stride(self, dtype):
        w = _x((4, 3, 3, 3), dtype, 1)
        assert F.conv2d(_x((2, 3, 8, 8), dtype), w, stride=2,
                        padding=1).shape == [2, 4, 4, 4]

    def test_conv1d(self, dtype):
        w = _x((4, 3, 3), dtype, 1)
        assert F.conv1d(_x((2, 3, 8), dtype), w, padding=1).shape == [2, 4, 8]

    def test_conv2d_transpose(self, dtype):
        w = _x((3, 4, 2, 2), dtype, 1)
        out = F.conv2d_transpose(_x((2, 3, 4, 4), dtype), w, stride=2)
        assert out.shape == [2, 4, 8, 8]

    def test_batch_norm(self, dtype):
        x = _x((4, 3, 8, 8), dtype)
        rm = Tensor(jnp.zeros((3,), jnp.float32))
        rv = Tensor(jnp.ones((3,), jnp.float32))
        w = Tensor(jnp.ones((3,), jnp.float32))
        b = Tensor(jnp.zeros((3,), jnp.float32))
        out = F.batch_norm(x, rm, rv, w, b, training=True)
        assert out.shape == [4, 3, 8, 8]

    def test_layer_norm(self, dtype):
        x = _x((4, 8), dtype)
        w = Tensor(jnp.ones((8,), jnp.float32))
        b = Tensor(jnp.zeros((8,), jnp.float32))
        assert F.layer_norm(x, [8], w, b).shape == [4, 8]

    def test_relu_softmax_gelu(self, dtype):
        x = _x((4, 8), dtype)
        for fn in (F.relu, F.gelu, lambda t: F.softmax(t, axis=-1),
                   F.sigmoid, F.silu):
            assert fn(x).shape == [4, 8]

    def test_linear(self, dtype):
        w = _x((8, 4), dtype, 1)
        assert F.linear(_x((2, 8), dtype), w).shape == [2, 4]

    def test_cross_entropy_bf16_logits(self, dtype):
        logits = _x((4, 10), dtype)
        lab = Tensor(jnp.asarray([1, 2, 3, 4], jnp.int32))
        loss = F.cross_entropy(logits, lab)
        assert np.isfinite(np.asarray(loss._data, np.float32))

    def test_dropout(self, dtype):
        assert F.dropout(_x((4, 8), dtype), 0.5, training=True).shape == [4, 8]


class TestAmpO2BenchPath:
    """The exact bench.py fast path on a tiny net — compile + one step."""

    @pytest.mark.slow
    def test_resnet_amp_o2_train_step(self):
        import jax
        from paddle_tpu.jit.api import functional_call

        pt.seed(0)
        net = pt.vision.models.resnet18(num_classes=10)
        pt.amp.decorate(net, level="O2", dtype="bfloat16")
        opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=net.parameters(),
                                    multi_precision=True)
        params = {k: p._data for k, p in net.named_parameters()}
        buffers = {k: b._data for k, b in net.named_buffers()}
        opt_state = opt.init_state_tree(params)
        fwd = getattr(net, "_orig_forward", net.forward)

        def train_step(params, buffers, opt_state, x, y):
            def loss_of(p):
                out, nb = functional_call(net, p, buffers, (Tensor(x),),
                                          training=True, forward_fn=fwd)
                logits = out._data.astype(jnp.float32)
                logp = jax.nn.log_softmax(logits)
                return -jnp.take_along_axis(logp, y[:, None],
                                            axis=1).mean(), nb

            (loss, nb), grads = jax.value_and_grad(loss_of, has_aux=True)(
                params)
            np_, no_ = opt.apply_gradients_tree(params, grads, opt_state)
            return loss, np_, nb, no_

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.rand(4, 3, 32, 32).astype(np.float32)).astype(
            jnp.bfloat16)
        y = jnp.asarray(rng.randint(0, 10, 4).astype(np.int32))
        loss, params, buffers, opt_state = jax.jit(train_step)(
            params, buffers, opt_state, x, y)
        assert np.isfinite(float(loss))
