"""AMP debugging tooling (ref: python/paddle/amp/debugging.py,
accuracy_compare.py): operator dtype stats, nan/inf localization by op
name, per-layer fp32-vs-bf16 accuracy compare."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.amp.debugging import (
    DebugMode, TensorCheckerConfig, enable_tensor_checker,
    disable_tensor_checker, collect_operator_stats, compare_accuracy)


def test_collect_operator_stats_counts_dtypes(capsys):
    x = pt.to_tensor(np.random.rand(8, 8).astype(np.float32))
    xb = x.astype("bfloat16")
    with collect_operator_stats():
        _ = x + x          # fp32
        _ = pt.matmul(xb, xb)  # bf16
    out = capsys.readouterr().out
    assert "Op Name" in out and "BF16 Calls" in out
    assert "matmul" in out


def test_tensor_checker_localizes_first_bad_op():
    x = pt.to_tensor(np.array([1.0, 0.0], np.float32))
    enable_tensor_checker(TensorCheckerConfig(
        enable=True, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT))
    try:
        with pytest.raises(FloatingPointError, match="divide|log"):
            y = x / pt.to_tensor(np.array([1.0, 0.0], np.float32))
            _ = pt.log(pt.to_tensor(np.array([-1.0], np.float32)))
    finally:
        findings = disable_tensor_checker()
    assert findings and findings[0]["num_nan_inf"] >= 1


def test_tensor_checker_log_mode_collects_all(capsys):
    enable_tensor_checker(TensorCheckerConfig(
        enable=True, debug_mode=DebugMode.CHECK_NAN_INF))
    try:
        _ = pt.log(pt.to_tensor(np.array([-1.0], np.float32)))
        _ = pt.to_tensor(np.array([1.0], np.float32)) / \
            pt.to_tensor(np.array([0.0], np.float32))
    finally:
        findings = disable_tensor_checker()
    assert len(findings) >= 2
    assert {f["op"] for f in findings} >= {"log"}


@pytest.mark.slow
def test_compare_accuracy_reports_per_layer_divergence():
    pt.seed(0)
    net = pt.nn.Sequential(
        pt.nn.Linear(32, 64), pt.nn.ReLU(), pt.nn.Linear(64, 8))
    x = np.random.RandomState(0).randn(4, 32).astype(np.float32) * 100
    rows = compare_accuracy(net, pt.to_tensor(x), dtype="bfloat16",
                            atol=1e-3, rtol=1e-3, print_report=False)
    assert rows, "no layers captured"
    names = [r["layer"] for r in rows]
    assert any("0" in n for n in names)
    # bf16 matmul on large-magnitude inputs must show a nonzero diff
    assert max(r["max_abs_diff"] for r in rows) > 0
    assert any(r["exceeds"] for r in rows)
