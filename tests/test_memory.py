"""Device-memory observability unit tests: the guarded allocator read,
compile-time footprints + pre-flight fit check, live-buffer census
attribution, watermark timeline (gauges + Chrome counter track), the
OOM postmortem payload, env enablement, and the capture integration
(one compile with the monitor on, footprint harvested, postmortem
naming a parameter path).

Everything follows the telemetry contract: zero cost disabled, never
sync the device, never initialize a jax backend just to read allocator
stats, never raise into the run.  The multi-process half (flight dump
through a real OOM'd worker, fleet skew through the aggregator) lives
in ``tests/drills/test_oom_drills.py``.
"""
from __future__ import annotations

import gc
import json
import logging
import os

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.observability as obs
from paddle_tpu.observability import memory as memory_mod
from paddle_tpu.observability.memory import (
    KINDS, MemoryMonitor, current_memory_monitor, device_memory_stat,
    device_memory_stats, get_memory_monitor, is_oom_error,
    oom_postmortem, program_memory_analysis, reset_memory_monitor,
)
from paddle_tpu.observability.metrics import get_registry
from paddle_tpu.observability.trace import get_tracer


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    # env must never leak enablement into (or out of) a test
    for var in ("PT_TELEMETRY", "PT_TELEMETRY_DIR", "PT_METRICS_PORT",
                "PT_PROCESS_INDEX", "PT_RUN_ID", "PT_TRACE",
                "PT_TRACE_DIR", "PT_FLIGHT_RECORDER", "PT_MEMORY",
                "PT_MEMORY_TOPK"):
        monkeypatch.delenv(var, raising=False)
    obs.reset()
    yield
    obs.reset()


def _prom_value(name, **labels):
    """One sample value out of the process registry's exposition."""
    from paddle_tpu.observability.aggregator import parse_prometheus_text
    fams = parse_prometheus_text(get_registry().prometheus_text())
    fam = fams.get(name)
    if fam is None:
        return None
    for sname, slabels, value in fam["samples"]:
        if sname == name and all(slabels.get(k) == v
                                 for k, v in labels.items()):
            return value
    return None


# -- the one guarded allocator read -----------------------------------------

def test_device_memory_stats_cpu_backend_has_no_allocator():
    # cpu devices report no allocator stats: summed dict is empty, the
    # per-device list is empty — and nothing raised
    assert device_memory_stats() == {}
    assert device_memory_stats(per_device=True) == []
    assert device_memory_stat("bytes_in_use") == 0
    assert device_memory_stat("bytes_limit", device_index=7) == 0


def test_device_memory_stats_survives_backend_errors(monkeypatch):
    import jax
    monkeypatch.setattr(jax, "local_devices",
                        lambda: (_ for _ in ()).throw(RuntimeError("x")))
    assert device_memory_stats() == {}
    assert device_memory_stats(per_device=True) == []


def test_cuda_parity_shims_route_through_guarded_read():
    # paddle's cuda.* memory API returns plain ints (0 on cpu), never
    # raises, never initializes anything
    cuda = pt.device.cuda
    assert cuda.memory_allocated() == 0
    assert cuda.max_memory_allocated() == 0
    assert cuda.memory_reserved() == 0
    assert cuda.max_memory_reserved() == 0


def test_telemetry_device_memory_delegates_to_guarded_read():
    tel = obs.get_telemetry()
    assert tel.device_memory() == device_memory_stats()


# -- compile-time footprint -------------------------------------------------

def test_program_memory_analysis_harvests_real_jitted_fn():
    import jax
    import jax.numpy as jnp
    f = jax.jit(lambda a: a @ a)
    x = jnp.ones((32, 32), jnp.float32)
    mem = program_memory_analysis(f, x)
    assert mem is not None
    assert set(mem) == set(KINDS) | {"alias"}
    assert all(isinstance(v, int) and v >= 0 for v in mem.values())
    assert mem["output"] >= 32 * 32 * 4  # one f32 result buffer
    assert MemoryMonitor.required_bytes(mem) >= 32 * 32 * 4


def test_program_memory_analysis_never_raises():
    assert program_memory_analysis(object()) is None
    assert program_memory_analysis(None) is None


def test_required_bytes_credits_donation_aliasing():
    mem = {"argument": 100, "output": 50, "temp": 25,
           "generated_code": 25, "alias": 60}
    assert MemoryMonitor.required_bytes(mem) == 140
    mem["alias"] = 10_000  # aliasing can never go negative
    assert MemoryMonitor.required_bytes(mem) == 0
    assert MemoryMonitor.required_bytes({}) == 0


def test_record_program_memory_exports_gauges_and_fit_verdict():
    mm = MemoryMonitor()
    mm.enable()
    mm.record_program_memory("trainstep", {
        "argument": 1000, "output": 200, "temp": 300,
        "generated_code": 50, "alias": 200})
    snap = mm.snapshot()
    assert snap["programs"]["trainstep"]["argument"] == 1000
    # no bytes_limit on cpu -> fit verdict is unknown, not a failure
    assert snap["fit"]["trainstep"]["fits"] is None
    assert snap["fit"]["trainstep"]["required_bytes"] == 1350
    assert snap["fit_ok"] is None
    for kind, want in (("argument", 1000.0), ("output", 200.0),
                       ("temp", 300.0), ("generated_code", 50.0)):
        assert _prom_value("pt_program_memory_bytes",
                           program="trainstep", kind=kind) == want


def test_fit_check_warns_once_naming_program_and_shortfall(
        monkeypatch, caplog):
    mm = MemoryMonitor()
    mm.enable()
    monkeypatch.setattr(memory_mod, "device_memory_stats",
                        lambda per_device=False: {"bytes_limit": 1000})
    with caplog.at_level(logging.WARNING,
                         logger="paddle_tpu.observability.memory"):
        mm.record_program_memory("big", {"argument": 1200,
                                         "output": 300})
        mm.record_program_memory("big", {"argument": 1200,
                                         "output": 300})
    warns = [r for r in caplog.records if "fit check" in r.getMessage()]
    assert len(warns) == 1  # warn ONCE per program, not per compile
    msg = warns[0].getMessage()
    assert "'big'" in msg and "1500" in msg and "500" in msg
    snap = mm.snapshot()
    assert snap["fit"]["big"] == {
        "fits": False, "required_bytes": 1500, "limit_bytes": 1000,
        "shortfall_bytes": 500}
    assert snap["fit_ok"] is False
    # a second program that fits does not flip the aggregate back
    mm.record_program_memory("small", {"argument": 10})
    assert mm.snapshot()["fit_ok"] is False


def test_fit_ok_true_when_every_program_fits(monkeypatch):
    mm = MemoryMonitor()
    monkeypatch.setattr(memory_mod, "device_memory_stats",
                        lambda per_device=False: {"bytes_limit": 10**9})
    mm.record_program_memory("a", {"argument": 100})
    mm.record_program_memory("b", {"output": 200})
    assert mm.snapshot()["fit_ok"] is True


# -- live-buffer census -----------------------------------------------------

def test_census_attributes_bytes_to_registered_provider_names():
    import jax.numpy as jnp
    arr = jnp.ones((128, 64), jnp.float32)  # 32 KiB
    mm = MemoryMonitor(topk=5)
    mm.register_provider(lambda: {"param::model::w": arr})
    census = mm.live_buffer_census()
    assert census["by_category"]["param"] == arr.nbytes
    assert census["count"] >= 1
    assert census["total_bytes"] >= arr.nbytes
    mine = [r for r in census["top"] if r["name"] == "param::model::w"]
    assert mine and mine[0]["bytes"] == arr.nbytes
    assert mine[0]["shape"] == [128, 64]
    assert mine[0]["dtype"] == "float32"
    assert len(census["top"]) <= 5


def test_census_extra_named_and_unattributed_bucket():
    import jax.numpy as jnp
    a = jnp.zeros((16, 16), jnp.float32)
    b = jnp.zeros((8, 8), jnp.float32)  # nobody claims b
    mm = MemoryMonitor()
    census = mm.live_buffer_census(extra_named={"opt0::velocity::w": a})
    assert census["by_category"]["opt0"] == a.nbytes
    assert census["by_category"].get("unattributed", 0) >= b.nbytes
    del b


def test_census_provider_held_weakly_never_keeps_step_alive():
    import jax.numpy as jnp

    class Step:
        def __init__(self):
            self.arr = jnp.ones((4, 4), jnp.float32)

        def named(self):
            return {"param::m::w": self.arr}

    mm = MemoryMonitor()
    step = Step()
    mm.register_provider(step.named)
    assert "param" in mm.live_buffer_census()["by_category"]
    del step
    gc.collect()
    census = mm.live_buffer_census()  # dead provider dropped silently
    assert "param" not in census["by_category"]
    assert mm._providers == []


def test_census_without_jax_arrays_is_empty_shape():
    mm = MemoryMonitor()
    census = mm.live_buffer_census(extra_named=None, topk=3)
    assert set(census) == {"total_bytes", "count", "by_category", "top"}


# -- watermark timeline -----------------------------------------------------

def test_observe_sample_books_history_gauges_and_counter_track():
    tr = get_tracer().enable(process_index=2)
    mm = MemoryMonitor()
    mm.enable()
    mm.observe_sample({"bytes_in_use": 100, "peak_bytes_in_use": 250,
                       "bytes_reserved": 160}, t_ns=1_000)
    mm.observe_sample({"bytes_in_use": 120, "peak_bytes_in_use": 250},
                      t_ns=2_000)
    marks = mm.watermarks()
    assert [m["t_ns"] for m in marks] == [1_000, 2_000]
    assert marks[0] == {"t_ns": 1_000, "bytes_in_use": 100,
                        "peak_bytes_in_use": 250,
                        "fragmentation_bytes": 60}
    assert marks[1]["fragmentation_bytes"] == 0  # no reserved stat
    # gauges carry the LAST sample
    assert _prom_value("pt_memory_watermark_bytes",
                       stat="bytes_in_use") == 120.0
    assert _prom_value("pt_memory_watermark_bytes",
                       stat="peak_bytes_in_use") == 250.0
    assert _prom_value("pt_memory_watermark_bytes",
                       stat="fragmentation") == 0.0
    # and each sample became one Chrome counter event on this rank
    cs = [c for c in tr.counters() if c[0] == "device_memory"]
    assert len(cs) == 2
    assert cs[0][1] == 1_000
    assert cs[0][2] == {"bytes_in_use": 100.0,
                        "peak_bytes_in_use": 250.0,
                        "fragmentation": 60.0}
    snap = mm.snapshot()
    assert snap["samples"] == 2
    assert snap["bytes_in_use"] == 120
    assert snap["fragmentation_bytes"] == 0


def test_on_step_respects_sampling_cadence(monkeypatch):
    mm = MemoryMonitor()
    mm.enable(sample_every=4)
    reads = []
    monkeypatch.setattr(
        memory_mod, "device_memory_stats",
        lambda per_device=False: reads.append(1) or
        {"bytes_in_use": 7, "peak_bytes_in_use": 7})
    for step in range(12):
        mm.on_step(step)
    assert len(reads) == 3  # steps 4, 8, 12
    assert len(mm.watermarks()) == 3
    mm.disable()
    mm.on_step(99)
    assert len(reads) == 3  # disabled hook is a no-op


def test_sample_watermark_noop_without_allocator_stats():
    mm = MemoryMonitor()
    mm.enable()
    mm.sample_watermark()  # cpu: no stats, no sample, no raise
    assert mm.watermarks() == []


# -- OOM intercept + postmortem ---------------------------------------------

def test_is_oom_error_needles():
    assert is_oom_error(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "1073741824 bytes."))
    assert is_oom_error("Resource exhausted: hbm")
    assert is_oom_error(MemoryError("allocation OOM"))
    assert is_oom_error("requested shape exceeds the memory capacity")
    assert not is_oom_error(ValueError("shape mismatch (4, 8)"))
    assert not is_oom_error("INVALID_ARGUMENT: dtype")
    assert not is_oom_error(None)


def test_record_oom_books_flight_dump_with_memory_payload(tmp_path):
    import jax.numpy as jnp
    tr = get_tracer().enable(flight_dir=str(tmp_path),
                             process_index=0, run_id="unit")
    big = jnp.zeros((1024, 1024), jnp.float32)  # 4 MiB dominates
    mm = get_memory_monitor()
    mm.enable()
    mm.record_program_memory("prog", {"argument": 64, "output": 64})
    mm.observe_sample({"bytes_in_use": 5, "peak_bytes_in_use": 9},
                      t_ns=1)
    exc = RuntimeError("RESOURCE_EXHAUSTED: Out of memory.")
    # the module-level entry point the intercepts call
    doc = oom_postmortem(program="prog", exc=exc,
                         extra_named={"param::model::w": big})
    assert doc["program"] == "prog"
    assert doc["top_buffer"] == "param::model::w"
    assert "RESOURCE_EXHAUSTED" in doc["error"]
    snap = mm.snapshot()
    assert snap["oom_events"] == 1
    assert snap["last_oom"] == {"program": "prog",
                                "top_buffer": "param::model::w",
                                "error": doc["error"]}
    assert _prom_value("pt_oom_events_total") == 1.0
    with open(tr.flight_path) as f:
        flight = json.load(f)
    assert flight["reason"] == "oom:prog:param::model::w"
    mem = flight["extra"]["memory"]
    assert mem["top_buffer"] == "param::model::w"
    assert mem["census"]["by_category"]["param"] == big.nbytes
    assert mem["programs"]["prog"]["argument"] == 64
    assert mem["fit"]["prog"]["required_bytes"] == 128
    assert mem["watermarks"] == [{"t_ns": 1, "bytes_in_use": 5,
                                  "peak_bytes_in_use": 9,
                                  "fragmentation_bytes": 0}]


def test_record_oom_runs_even_while_disabled():
    mm = MemoryMonitor()  # never enabled: OOM is terminal, book anyway
    doc = mm.record_oom(program="p", exc=RuntimeError("oom"))
    assert doc is not None and mm.snapshot()["oom_events"] == 1


# -- env enablement + singleton ---------------------------------------------

def test_env_enablement_and_reset(monkeypatch):
    assert current_memory_monitor() is None  # read-only accessor
    mm = get_memory_monitor()
    assert mm.enabled is False  # no env -> created disabled
    assert current_memory_monitor() is mm
    monkeypatch.setenv("PT_MEMORY", "1")
    monkeypatch.setenv("PT_MEMORY_TOPK", "5")
    reset_memory_monitor()
    mm2 = get_memory_monitor()
    assert mm2 is not mm
    assert mm2.enabled is True and mm2.topk == 5


def test_telemetry_snapshot_carries_memory_block():
    mm = get_memory_monitor()
    mm.enable()
    mm.record_program_memory("s", {"argument": 1})
    snap = obs.get_telemetry().snapshot()["memory"]
    assert snap["enabled"] is True
    assert snap["programs"] == 1
    assert snap["fit_ok"] is None  # cpu: no limit to check against
    assert "oom_events" in snap or "fragmentation_bytes" in snap


# -- capture integration ----------------------------------------------------

def _captured_mlp(width=256):
    np.random.seed(0)
    pt.seed(0)
    model = nn.Sequential(nn.Linear(64, width), nn.ReLU(),
                          nn.Linear(width, 1))
    opt = pt.optimizer.SGD(learning_rate=0.05,
                           parameters=model.parameters())
    mse = nn.MSELoss()

    @pt.jit.capture_step
    def step(x, y):
        loss = mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = pt.to_tensor(np.random.randn(8, 64).astype(np.float32))
    y = pt.to_tensor(np.random.randn(8, 1).astype(np.float32))
    return step, x, y


def test_capture_harvests_footprint_with_one_compile():
    mm = get_memory_monitor()
    mm.enable()
    step, x, y = _captured_mlp()
    for _ in range(3):
        step(x, y)
    # the monitored step still compiles exactly once
    assert step.stats["compiles"] == 1
    assert step.stats["fallback"] is None
    entry = next(iter(step._cache.values()))
    # footprint harvested from the same cache-shared AOT compile
    assert entry.memory is not None
    assert entry.memory["output"] > 0
    snap = mm.snapshot()
    assert "captured_step(step)" in snap["programs"]
    assert snap["programs"]["captured_step(step)"] == entry.memory
    # the capture registered itself as a census attribution source:
    # parameter paths resolve (64*256*4 first-weight bytes present)
    census = mm.live_buffer_census()
    assert census["by_category"].get("param", 0) >= 64 * 256 * 4
    named = step._memory_named()
    assert "param::model::0.weight" in named
    assert "buffer::" not in "".join(n for n in named
                                     if not n.startswith(("param::",
                                                          "opt")))


def test_bench_eager_memory_contract_one_compile_under_one_percent():
    """The tentpole acceptance bar, enforced in tier-1 through the
    bench's own contract block: monitoring adds no compile, changes no
    math, books the footprint, and costs <1% per step with watermark
    sampling on every step."""
    import bench_eager
    res = bench_eager._memory_contract(pt)
    if not res["ok"]:
        # the timing leg can lose one round to machine noise; the
        # compile/bitwise legs are deterministic, so one retry only
        # ever re-runs the clock
        res = bench_eager._memory_contract(pt)
    assert res["compiles_off"] == 1 and res["compiles_on"] == 1
    assert res["footprint_harvested"] is True
    assert res["loss_bitwise_identical"] is True
    assert res["census_param_bytes"] >= 256 * 256 * 4
    assert res["oom_events"] == 0
    assert res["overhead_ratio"] < 1.01
    assert res["ok"] is True


def test_capture_replay_intercepts_oom_and_names_parameter_path():
    mm = get_memory_monitor()
    mm.enable()
    step, x, y = _captured_mlp(width=512)  # 64*512*4 = 128 KiB weight
    for _ in range(2):
        step(x, y)
    entry = next(iter(step._cache.values()))

    def _exhausted(*a, **k):
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory while trying to "
            "allocate 1073741824 bytes.")

    entry.jitted = _exhausted
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        step(x, y)
    snap = mm.snapshot()
    assert snap["oom_events"] == 1
    assert snap["last_oom"]["program"] == "captured_step(step)"
    assert snap["last_oom"]["top_buffer"].startswith("param::")
    # a non-OOM failure must NOT book a postmortem
    def _other(*a, **k):
        raise ValueError("shape mismatch")

    entry.jitted = _other
    with pytest.raises(ValueError):
        step(x, y)
    assert mm.snapshot()["oom_events"] == 1
