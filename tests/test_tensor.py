"""Core Tensor semantics (ref model: test/legacy_test tensor tests)."""
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import Tensor, to_tensor


class TestCreation:
    def test_to_tensor(self):
        t = to_tensor([[1.0, 2.0], [3.0, 4.0]])
        assert t.shape == [2, 2]
        assert t.dtype == paddle_tpu.float32
        np.testing.assert_array_equal(t.numpy(), [[1, 2], [3, 4]])

    def test_dtype_coercion(self):
        assert to_tensor([1, 2]).dtype.is_integer
        assert to_tensor([1.0], dtype="float32").dtype == "float32"
        assert to_tensor(np.zeros(3, np.float64)).dtype == paddle_tpu.float32
        t = to_tensor([1], dtype="bfloat16")
        assert t.dtype == paddle_tpu.bfloat16

    def test_factories(self):
        assert paddle_tpu.zeros([2, 3]).shape == [2, 3]
        assert paddle_tpu.ones([4]).numpy().sum() == 4
        assert paddle_tpu.full([2], 7).numpy().tolist() == [7, 7]
        assert paddle_tpu.arange(5).numpy().tolist() == [0, 1, 2, 3, 4]
        assert paddle_tpu.eye(3).numpy().trace() == 3
        assert paddle_tpu.linspace(0, 1, 5).shape == [5]
        x = paddle_tpu.rand([3, 3])
        assert paddle_tpu.zeros_like(x).shape == [3, 3]

    def test_random_reproducible(self):
        paddle_tpu.seed(42)
        a = paddle_tpu.rand([4]).numpy()
        paddle_tpu.seed(42)
        b = paddle_tpu.rand([4]).numpy()
        np.testing.assert_array_equal(a, b)

    def test_randint(self):
        t = paddle_tpu.randint(0, 10, [100])
        assert t.numpy().min() >= 0 and t.numpy().max() < 10


class TestArithmetic:
    def test_binary_ops(self):
        x = to_tensor([1.0, 2.0, 3.0])
        y = to_tensor([4.0, 5.0, 6.0])
        np.testing.assert_allclose((x + y).numpy(), [5, 7, 9])
        np.testing.assert_allclose((x - y).numpy(), [-3, -3, -3])
        np.testing.assert_allclose((x * y).numpy(), [4, 10, 18])
        np.testing.assert_allclose((y / x).numpy(), [4, 2.5, 2])
        np.testing.assert_allclose((x ** 2).numpy(), [1, 4, 9])

    def test_scalar_broadcast(self):
        x = to_tensor([1.0, 2.0])
        np.testing.assert_allclose((x + 1).numpy(), [2, 3])
        np.testing.assert_allclose((2 * x).numpy(), [2, 4])
        np.testing.assert_allclose((1 - x).numpy(), [0, -1])

    def test_matmul(self):
        a = to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        b = to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
        c = a @ b
        np.testing.assert_allclose(c.numpy(), a.numpy() @ b.numpy())
        ct = paddle_tpu.matmul(b, a, transpose_x=True, transpose_y=True)
        np.testing.assert_allclose(ct.numpy(), (a.numpy() @ b.numpy()).T)

    def test_comparison(self):
        x = to_tensor([1.0, 2.0, 3.0])
        assert (x > 1.5).numpy().tolist() == [False, True, True]
        assert (x == 2.0).numpy().tolist() == [False, True, False]

    def test_reductions(self):
        x = to_tensor([[1.0, 2.0], [3.0, 4.0]])
        assert x.sum().item() == 10
        assert x.mean().item() == 2.5
        assert x.max().item() == 4
        np.testing.assert_allclose(x.sum(axis=0).numpy(), [4, 6])
        np.testing.assert_allclose(x.sum(axis=1, keepdim=True).numpy(),
                                   [[3], [7]])

    def test_inplace(self):
        x = to_tensor([1.0, 2.0])
        x.add_(1.0)
        np.testing.assert_allclose(x.numpy(), [2, 3])


class TestManipulation:
    def test_reshape_transpose(self):
        x = to_tensor(np.arange(12, dtype=np.float32))
        y = x.reshape([3, 4])
        assert y.shape == [3, 4]
        z = y.transpose([1, 0])
        assert z.shape == [4, 3]
        assert y.T.shape == [4, 3]

    def test_concat_split_stack(self):
        a = paddle_tpu.ones([2, 3])
        b = paddle_tpu.zeros([2, 3])
        c = paddle_tpu.concat([a, b], axis=0)
        assert c.shape == [4, 3]
        s = paddle_tpu.stack([a, b])
        assert s.shape == [2, 2, 3]
        parts = paddle_tpu.split(c, 2, axis=0)
        assert len(parts) == 2 and parts[0].shape == [2, 3]
        np.testing.assert_array_equal(parts[0].numpy(), a.numpy())

    def test_indexing(self):
        x = to_tensor(np.arange(24, dtype=np.float32).reshape(4, 6))
        assert x[0].shape == [6]
        assert x[1, 2].item() == 8
        assert x[:, :3].shape == [4, 3]
        assert x[::2].shape == [2, 6]
        idx = to_tensor([0, 2])
        assert x[idx].shape == [2, 6]

    def test_bool_mask_indexing(self):
        x = to_tensor([1.0, -2.0, 3.0, -4.0])
        got = x[x < 0]
        np.testing.assert_allclose(got.numpy(), [-2, -4])

    def test_setitem(self):
        x = paddle_tpu.zeros([3, 3])
        x[1] = 5.0
        assert x.numpy()[1].tolist() == [5, 5, 5]
        x[0, 0] = 1.0
        assert x[0, 0].item() == 1

    def test_gather_scatter(self):
        x = to_tensor(np.arange(12, dtype=np.float32).reshape(4, 3))
        g = paddle_tpu.gather(x, to_tensor([0, 2]))
        np.testing.assert_allclose(g.numpy(), x.numpy()[[0, 2]])
        upd = paddle_tpu.scatter(x, to_tensor([1]), to_tensor([[9., 9., 9.]]))
        assert upd.numpy()[1].tolist() == [9, 9, 9]

    def test_where_topk_sort(self):
        x = to_tensor([3.0, 1.0, 2.0])
        v, i = paddle_tpu.topk(x, 2)
        assert v.numpy().tolist() == [3, 2]
        assert i.numpy().tolist() == [0, 2]
        assert paddle_tpu.sort(x).numpy().tolist() == [1, 2, 3]
        assert paddle_tpu.argsort(x).numpy().tolist() == [1, 2, 0]
        w = paddle_tpu.where(x > 1.5, x, paddle_tpu.zeros_like(x))
        assert w.numpy().tolist() == [3, 0, 2]

    def test_pad_tile_flip(self):
        x = to_tensor([[1.0, 2.0]])
        assert paddle_tpu.tile(x, [2, 2]).shape == [2, 4]
        assert paddle_tpu.flip(x, axis=1).numpy().tolist() == [[2, 1]]
        # full-length pad spec pads dims first->last (paddle semantics)
        p = paddle_tpu.pad(x, [1, 1, 0, 0])
        assert p.shape == [3, 2]


class TestAPI:
    def test_item_and_conversions(self):
        t = to_tensor(3.5)
        assert t.item() == 3.5
        assert float(t) == 3.5
        assert to_tensor([[1, 2]]).tolist() == [[1, 2]]

    def test_astype_cast(self):
        x = to_tensor([1.9, 2.1])
        y = x.astype("int32")
        assert y.dtype == paddle_tpu.int32
        assert y.numpy().tolist() == [1, 2]

    def test_clone_detach(self):
        x = to_tensor([1.0], stop_gradient=False)
        d = x.detach()
        assert d.stop_gradient
        c = x.clone()
        assert not c.stop_gradient

    def test_numel_repr(self):
        x = paddle_tpu.ones([2, 5])
        assert x.size == 10
        assert "Tensor" in repr(x)
        assert x.element_size() == 4

    def test_linalg(self):
        a = np.array([[4.0, 1.0], [1.0, 3.0]], np.float32)
        t = to_tensor(a)
        np.testing.assert_allclose(paddle_tpu.linalg.inv(t).numpy(),
                                   np.linalg.inv(a), atol=1e-5)
        np.testing.assert_allclose(paddle_tpu.linalg.det(t).item(),
                                   np.linalg.det(a), rtol=1e-5)
        np.testing.assert_allclose(paddle_tpu.linalg.norm(t).item(),
                                   np.linalg.norm(a), rtol=1e-5)

    def test_einsum(self):
        a = np.random.rand(2, 3).astype(np.float32)
        b = np.random.rand(3, 4).astype(np.float32)
        out = paddle_tpu.einsum("ij,jk->ik", to_tensor(a), to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)

    def test_unique_nonzero(self):
        x = to_tensor([1, 2, 2, 3, 1])
        u = paddle_tpu.unique(x)
        assert u.numpy().tolist() == [1, 2, 3]
        nz = paddle_tpu.nonzero(to_tensor([0, 1, 0, 2]))
        assert nz.numpy().ravel().tolist() == [1, 3]
