"""jit.capture_step: trace-and-cache contract tests.

Covers the eager-fast-path acceptance surface: signature-cache hit/miss
semantics (no retrace on stable shapes, exactly one on a dtype flip),
numerical parity of captured vs eager training, donation safety for
caller-held arrays, graceful eager fallback on capture-unsafe code, and
the PT_CAPTURE=0 kill switch.
"""
import logging

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.observability import get_telemetry


def _mlp(seed=0):
    np.random.seed(seed)
    pt.seed(seed)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                parameters=model.parameters())
    return model, opt


def _batch(n=4, seed=1):
    rng = np.random.RandomState(seed)
    return (pt.to_tensor(rng.randn(n, 8).astype(np.float32)),
            pt.to_tensor(rng.randn(n, 1).astype(np.float32)))


def _train_step(model, opt):
    mse = nn.MSELoss()

    @pt.jit.capture_step
    def step(x, y):
        loss = mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    return step


def test_same_shapes_single_compile_sentinel_quiet():
    model, opt = _mlp()
    step = _train_step(model, opt)
    x, y = _batch()
    tel = get_telemetry()
    hits_before = tel.snapshot()["capture"]["hits"]
    for _ in range(10):
        step(x, y)
    assert step.stats["compiles"] == 1
    assert step.stats["hits"] == 9
    assert step.stats["misses"] == 1
    assert step.stats["fallback"] is None
    snap = tel.snapshot()
    assert snap["capture"]["hits"] - hits_before >= 9
    # the one compile must not read as churn to the recompile sentinel
    assert not [s for s in snap["recompile_storms"] if "captured_step" in s]


def test_dtype_change_exactly_one_retrace():
    @pt.jit.capture_step
    def f(a, b):
        return a * b + b

    xf = pt.to_tensor(np.ones((4, 4), np.float32))
    for _ in range(3):
        f(xf, xf)
    assert step_stats(f) == (1, 2, 1)
    xi = pt.to_tensor(np.ones((4, 4), np.int32))
    f(xi, xi)
    assert step_stats(f) == (2, 2, 2)  # one new trace, nothing dropped
    f(xf, xf)  # the float entry is still cached
    assert step_stats(f) == (2, 3, 2)


def step_stats(f):
    return (f.stats["misses"], f.stats["hits"], f.stats["compiles"])


def test_captured_matches_eager_10_steps():
    model, opt = _mlp()
    step = _train_step(model, opt)
    x, y = _batch()
    captured = [float(np.asarray(step(x, y)._data)) for _ in range(10)]

    model2, opt2 = _mlp()  # same seeds -> identical init
    mse = nn.MSELoss()
    x2 = pt.to_tensor(np.asarray(x._data))
    y2 = pt.to_tensor(np.asarray(y._data))
    eager = []
    for _ in range(10):
        loss = mse(model2(x2), y2)
        loss.backward()
        opt2.step()
        opt2.clear_grad()
        eager.append(float(np.asarray(loss._data)))

    # NOT bit-exact by design: the captured step is ONE fused XLA
    # program while eager runs per-op executables, and XLA reassociates
    # float math differently across fusion boundaries (~1 ULP at step
    # 0, observed <=1.2e-7 over 10 steps). The tolerance asserts the
    # trajectories are the same computation, not the same rounding.
    assert captured == pytest.approx(eager, abs=1e-5)
    for (n1, p1), (_, p2) in zip(model.named_parameters(),
                                 model2.named_parameters()):
        np.testing.assert_allclose(np.asarray(p1._data),
                                   np.asarray(p2._data), atol=1e-5,
                                   err_msg=n1)
    assert captured[-1] < captured[0]  # it actually trained


def test_replay_is_bit_deterministic():
    @pt.jit.capture_step
    def f(a, b):
        return a * b + b

    a = pt.to_tensor(np.random.RandomState(3).randn(8, 8)
                     .astype(np.float32))
    out1 = np.asarray(f(a, a)._data)
    out2 = np.asarray(f(a, a)._data)
    assert (out1 == out2).all()


def test_donation_safety_caller_arrays_survive():
    model, opt = _mlp()
    # caller-held references taken BEFORE capture: the capture layer
    # device-copies into private buffers, so donation must never
    # invalidate these
    held = {n: p._data for n, p in model.named_parameters()}
    before = {n: np.asarray(a).copy() for n, a in held.items()}
    step = _train_step(model, opt)
    x, y = _batch()
    for _ in range(5):
        step(x, y)
    for n, a in held.items():
        np.testing.assert_array_equal(np.asarray(a), before[n],
                                      err_msg=n)  # still readable + intact
    # while the live parameters did move
    moved = any(not np.array_equal(np.asarray(p._data), before[n])
                for n, p in model.named_parameters())
    assert moved


def test_capture_unsafe_falls_back_with_diagnostic(caplog):
    model, opt = _mlp()
    mse = nn.MSELoss()

    @pt.jit.capture_step
    def step(x, y):
        loss = mse(model(x), y)
        if float(np.asarray(loss._data)) > 1e9:  # host sync: unsafe
            return loss
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x, y = _batch()
    with caplog.at_level(logging.WARNING, logger="paddle_tpu"):
        losses = [float(np.asarray(step(x, y)._data)) for _ in range(5)]
    assert step.fallback_reason == "capture_unsafe"
    assert step.stats["fallback"] == "capture_unsafe"
    assert step.stats["compiles"] == 0
    diags = [r.getMessage() for r in caplog.records
             if r.name.startswith("paddle_tpu")]
    assert any("falling back to eager" in m for m in diags)
    # the one-shot diagnostic names the offending user line
    assert any("test_capture.py" in m for m in diags)
    assert losses[-1] < losses[0]  # eager fallback still trains


def test_pt_capture_env_disables(monkeypatch):
    monkeypatch.setenv("PT_CAPTURE", "0")
    model, opt = _mlp()
    step = _train_step(model, opt)
    x, y = _batch()
    losses = [float(np.asarray(step(x, y)._data)) for _ in range(4)]
    assert step.stats["compiles"] == 0
    assert step.stats["hits"] == 0 and step.stats["misses"] == 0
    assert losses[-1] < losses[0]


def test_lr_change_does_not_retrace():
    model, opt = _mlp()
    step = _train_step(model, opt)
    x, y = _batch()
    for _ in range(3):
        step(x, y)
    opt.set_lr(0.01)  # lr rides in as a weak-f32 runtime arg
    for _ in range(3):
        step(x, y)
    assert step.stats["compiles"] == 1
    assert step.stats["hits"] == 5


def test_shape_change_compiles_second_entry():
    model, opt = _mlp()
    step = _train_step(model, opt)
    x, y = _batch(n=4)
    x8, y8 = _batch(n=8, seed=2)
    step(x, y)
    step(x8, y8)
    step(x, y)
    step(x8, y8)
    assert step.stats["compiles"] == 2
    assert step.stats["misses"] == 2
    assert step.stats["hits"] == 2
