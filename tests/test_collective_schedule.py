"""Mesh-aware collective-schedule planning (distributed/collective_schedule).

The planner is pure metadata: given mesh axis sizes and a ZeRO level it
composes the per-bucket gradient reduction from per-axis stages ordered
fast-link-first (reduce_scatter over in-node ICI, all_reduce across DCN
on the 1/n payload, all_gather back).  These are trace-time decisions —
unit-testable without devices.
"""
import pytest

from paddle_tpu.distributed.collective_schedule import (
    CollectiveSchedule, Stage, dcn_axes, plan_grad_reduction,
    schedule_enabled)


# -- plans -------------------------------------------------------------------

def test_pure_dp_plan_is_single_all_reduce():
    s = plan_grad_reduction({"dp": 8}, zero=None)
    assert s.stages == (Stage("all_reduce", "dp", 8),)
    assert not s.scatters and s.kind == "all_reduce"
    assert s.shard_axis is None and s.shard_size == 1


def test_zero_sharded_plan_is_hierarchical():
    s = plan_grad_reduction({"dp": 2, "sharding": 4}, zero="os")
    assert [st.op for st in s.stages] == \
        ["reduce_scatter", "all_reduce", "all_gather"]
    assert s.scatters and s.kind == "reduce_scatter"
    assert s.shard_axis == "sharding" and s.shard_size == 4
    assert s.describe() == ("reduce_scatter(sharding:4) -> "
                            "all_reduce(dp:2) -> all_gather(sharding:4)")


def test_zero_sharding_only_plan_skips_dp_stage():
    s = plan_grad_reduction({"dp": 1, "sharding": 8}, zero="os_g")
    assert [st.op for st in s.stages] == ["reduce_scatter", "all_gather"]
    assert s.shard_size == 8


def test_nothing_to_plan_returns_none():
    # single device
    assert plan_grad_reduction({"dp": 1}, zero=None) is None
    # ZeRO without a sharding axis: the pre-existing GSPMD/zero_spec
    # path owns the reduction — planning must NOT claim it
    assert plan_grad_reduction({"dp": 8}, zero="os") is None
    assert plan_grad_reduction({"dp": 8, "sharding": 1}, zero="os_g") is None
    # sharded mesh without ZeRO: GSPMD owns layout
    assert plan_grad_reduction({"dp": 2, "sharding": 4}, zero=None) is None


# -- kill switches -----------------------------------------------------------

def test_env_kill_switch(monkeypatch):
    monkeypatch.delenv("PT_COLLECTIVE_SCHEDULE", raising=False)
    assert schedule_enabled()
    for off in ("0", "false", "False"):
        monkeypatch.setenv("PT_COLLECTIVE_SCHEDULE", off)
        assert not schedule_enabled()
        assert plan_grad_reduction({"dp": 2, "sharding": 4}, "os") is None
    monkeypatch.setenv("PT_COLLECTIVE_SCHEDULE", "1")
    assert schedule_enabled()


def test_strategy_flag_forces_off_but_env_wins(monkeypatch):
    monkeypatch.delenv("PT_COLLECTIVE_SCHEDULE", raising=False)
    assert not schedule_enabled(False)
    assert plan_grad_reduction({"dp": 2, "sharding": 4}, "os",
                               enabled=False) is None
    # flag=None means "no opinion", not off
    assert schedule_enabled(None)
    # the env kill switch wins over an explicit strategy opt-in
    monkeypatch.setenv("PT_COLLECTIVE_SCHEDULE", "0")
    assert not schedule_enabled(True)


# -- topology ----------------------------------------------------------------

def test_dcn_axes_default_and_override(monkeypatch):
    monkeypatch.delenv("PT_DCN_AXES", raising=False)
    assert dcn_axes() == ("dp", "pp")
    monkeypatch.setenv("PT_DCN_AXES", "dp")
    assert dcn_axes() == ("dp",)
    monkeypatch.setenv("PT_DCN_AXES", " dp , sharding ")
    assert dcn_axes() == ("dp", "sharding")


def test_describe_noop():
    assert CollectiveSchedule().describe() == "noop"
