"""PS-era distributed surface (ref: ``distributed/fleet/dataset/``,
``distributed/entry_attr.py``, ``distributed/io.py``,
``parallel_with_gloo.py``): MultiSlot dataset streaming/shuffle, entry
attr configs, persistables round trip, gloo single-rank lifecycle."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist


def _write_multislot(path, rows):
    # each row: ([dense floats], [sparse int ids])
    with open(path, "w") as f:
        for dense, ids in rows:
            f.write(f"{len(dense)} " + " ".join(map(str, dense)) + " "
                    + f"{len(ids)} " + " ".join(map(str, ids)) + "\n")


class _Var:
    def __init__(self, name, dtype, shape=None):
        self.name, self.dtype, self.shape = name, dtype, shape


@pytest.fixture
def slot_files(tmp_path):
    rows1 = [([0.5, 1.5], [7, 8, 9]), ([2.5, 3.5], [1]),
             ([4.5, 5.5], [2, 3])]
    rows2 = [([6.5, 7.5], [4, 5]), ([8.5, 9.5], [6])]
    p1, p2 = str(tmp_path / "a.txt"), str(tmp_path / "b.txt")
    _write_multislot(p1, rows1)
    _write_multislot(p2, rows2)
    return [p1, p2]


def _make(cls, files, batch_size=2):
    ds = cls()
    ds.init(batch_size=batch_size, thread_num=1,
            use_var=[_Var("dense", "float32", [-1, 2]),
                     _Var("ids", "int64")],   # ids: no static size -> ragged
            pipe_command="cat")
    ds.set_filelist(files)
    return ds


class TestQueueDataset:
    def test_streams_batches_through_pipe(self, slot_files):
        ds = _make(dist.QueueDataset, slot_files)
        batches = list(ds)
        assert len(batches) == 3  # 5 records, batch 2 -> 2+2+1
        b0 = batches[0]
        np.testing.assert_allclose(b0["dense"],
                                   [[0.5, 1.5], [2.5, 3.5]])
        assert b0["dense"].dtype == np.float32
        # undeclared-size slot is ALWAYS a list, even when a batch's
        # lengths coincide (type must not flip between batches)
        assert [a.tolist() for a in b0["ids"]] == [[7, 8, 9], [1]]
        assert isinstance(batches[1]["ids"], list)  # lens 2,2 — still list
        assert batches[2]["dense"].shape == (1, 2)

    def test_pipe_command_is_real(self, slot_files):
        ds = _make(dist.QueueDataset, slot_files[:1])
        # a pipe that keeps only the first record
        ds.pipe_command = "head -n 1"
        assert sum(len(b["dense"]) for b in ds) == 1

    def test_parse_error_is_loud(self, tmp_path):
        bad = str(tmp_path / "bad.txt")
        with open(bad, "w") as f:
            f.write("2 1.0\n")  # declares 2 values, has 1
        ds = _make(dist.QueueDataset, [bad])
        ds.use_var = [_Var("dense", "float32")]
        with pytest.raises(ValueError, match="MultiSlot"):
            list(ds)

    def test_declared_static_size_enforced(self, tmp_path):
        p = str(tmp_path / "mixed.txt")
        with open(p, "w") as f:
            f.write("2 1.0 2.0\n3 1.0 2.0 3.0\n")
        ds = _make(dist.QueueDataset, [p])
        ds.use_var = [_Var("dense", "float32", [-1, 2])]
        with pytest.raises(ValueError, match="MultiSlot"):
            list(ds)


class TestInMemoryDataset:
    def test_load_shuffle_release(self, slot_files):
        ds = _make(dist.InMemoryDataset, slot_files)
        with pytest.raises(RuntimeError, match="load_into_memory"):
            list(ds)
        ds.load_into_memory()
        assert ds.get_memory_data_size() == 5
        assert ds.get_shuffle_data_size() == 5
        dense_before = [b["dense"].sum() for b in ds]
        ds.local_shuffle()
        total_after = sum(b["dense"].sum() for b in ds)
        np.testing.assert_allclose(total_after, sum(dense_before))
        ds.global_shuffle()
        assert ds.get_memory_data_size() == 5
        ds.release_memory()
        assert ds.get_memory_data_size() == 0
        ds._init_distributed_settings(parse_ins_id=True)
        ds.update_settings(batch_size=4)
        assert ds.batch_size == 4


def test_entry_attrs_match_reference_attr_strings():
    assert dist.ProbabilityEntry(0.1)._to_attr() == "probability_entry:0.1"
    assert dist.CountFilterEntry(10)._to_attr() == "count_filter_entry:10"
    assert dist.ShowClickEntry("show", "click")._to_attr() == \
        "show_click_entry:show:click"
    with pytest.raises(ValueError):
        dist.ProbabilityEntry(1.5)
    with pytest.raises(ValueError):
        dist.CountFilterEntry(-1)
    with pytest.raises(ValueError):
        dist.ShowClickEntry("show", 3)


def test_io_persistables_round_trip(tmp_path):
    import paddle_tpu.static as static
    pt.seed(0)
    pt.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [2, 4], "float32")
            w = pt.create_parameter([4, 3], "float32")
            y = pt.matmul(x, w)
        exe = static.Executor()
        exe.run(startup)
        feed = {"x": np.ones((2, 4), np.float32)}
        before = np.asarray(exe.run(main, feed=feed, fetch_list=[y])[0])
        dist.io.save_persistables(exe, str(tmp_path), main)
        assert dist.io.is_persistable(w)
        assert not dist.io.is_persistable(x)
        # clobber then restore
        from paddle_tpu.static.executor import global_scope
        import jax.numpy as jnp
        scope = global_scope()
        for k in list(main.scope_tensors):
            v = scope.find_var(k)
            base = v if v is not None else main.scope_tensors[k]._data
            scope.set(k, jnp.zeros_like(base))
        mid = np.asarray(exe.run(main, feed=feed, fetch_list=[y])[0])
        assert abs(mid).max() == 0.0
        dist.io.load_persistables(exe, str(tmp_path), main)
        after = np.asarray(exe.run(main, feed=feed, fetch_list=[y])[0])
        np.testing.assert_allclose(after, before)
    finally:
        pt.disable_static()


def test_gloo_single_rank_lifecycle():
    dist.gloo_init_parallel_env(0, 1, "127.0.0.1:0")
    dist.gloo_barrier()  # no-op at world 1, must not hang
    dist.gloo_release()
