"""paddle.geometric parity tests.

Expected values follow the reference docstrings
(``python/paddle/geometric/message_passing/send_recv.py:36``,
``geometric/reindex.py:25``, ``geometric/math.py:23``).
"""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestSegment:
    def _data(self):
        return paddle.to_tensor(
            np.array([[1., 2., 3.], [3., 2., 1.], [4., 5., 6.]], np.float32))

    def test_sum(self):
        out = paddle.geometric.segment_sum(
            self._data(), paddle.to_tensor(np.array([0, 0, 1], np.int32)))
        np.testing.assert_allclose(out.numpy(), [[4., 4., 4.], [4., 5., 6.]])

    def test_mean(self):
        out = paddle.geometric.segment_mean(
            self._data(), paddle.to_tensor(np.array([0, 0, 1], np.int32)))
        np.testing.assert_allclose(out.numpy(), [[2., 2., 2.], [4., 5., 6.]])

    def test_min_max(self):
        ids = paddle.to_tensor(np.array([0, 0, 1], np.int32))
        mn = paddle.geometric.segment_min(self._data(), ids)
        mx = paddle.geometric.segment_max(self._data(), ids)
        np.testing.assert_allclose(mn.numpy(), [[1., 2., 1.], [4., 5., 6.]])
        np.testing.assert_allclose(mx.numpy(), [[3., 2., 3.], [4., 5., 6.]])

    def test_empty_segment_zero_filled(self):
        # segment 1 never appears: row must be 0, not +/-inf
        ids = paddle.to_tensor(np.array([0, 0, 2], np.int32))
        for fn in (paddle.geometric.segment_min, paddle.geometric.segment_max,
                   paddle.geometric.segment_sum, paddle.geometric.segment_mean):
            out = fn(self._data(), ids)
            assert out.shape[0] == 3
            np.testing.assert_allclose(out.numpy()[1], [0., 0., 0.])

    def test_grad(self):
        x = self._data()
        x.stop_gradient = False
        ids = paddle.to_tensor(np.array([0, 0, 1], np.int32))
        out = paddle.geometric.segment_mean(x, ids)
        out.sum().backward()
        np.testing.assert_allclose(
            x.grad.numpy(), [[.5] * 3, [.5] * 3, [1.] * 3])


class TestSendRecv:
    def setup_method(self, _):
        self.x = paddle.to_tensor(
            np.array([[0., 2., 3.], [1., 4., 5.], [2., 6., 7.]], np.float32))
        self.src = paddle.to_tensor(np.array([0, 1, 2, 0], np.int32))
        self.dst = paddle.to_tensor(np.array([1, 2, 1, 0], np.int32))

    def test_sum(self):
        out = paddle.geometric.send_u_recv(self.x, self.src, self.dst,
                                           reduce_op="sum")
        np.testing.assert_allclose(
            out.numpy(), [[0., 2., 3.], [2., 8., 10.], [1., 4., 5.]])

    def test_mean_out_size(self):
        out = paddle.geometric.send_u_recv(self.x, self.src, self.dst,
                                           reduce_op="mean", out_size=4)
        assert out.shape[0] == 4
        np.testing.assert_allclose(out.numpy()[1], [1., 4., 5.])
        np.testing.assert_allclose(out.numpy()[3], [0., 0., 0.])

    @pytest.mark.slow
    def test_max_grad(self):
        self.x.stop_gradient = False
        out = paddle.geometric.send_u_recv(self.x, self.src, self.dst,
                                           reduce_op="max")
        out.sum().backward()
        assert self.x.grad is not None

    def test_send_ue_recv(self):
        e = paddle.to_tensor(np.array([1., 2., 3., 4.], np.float32))
        out = paddle.geometric.send_ue_recv(self.x, e, self.src, self.dst,
                                            message_op="add", reduce_op="sum")
        # messages: x[0]+1 -> 1, x[1]+2 -> 2, x[2]+3 -> 1, x[0]+4 -> 0
        np.testing.assert_allclose(
            out.numpy(), [[4., 6., 7.], [6., 12., 14.], [3., 6., 7.]])

    def test_send_uv(self):
        y = paddle.to_tensor(
            np.array([[0., 1., 2.], [2., 3., 4.], [4., 5., 6.]], np.float32))
        out = paddle.geometric.send_uv(self.x, y, self.src, self.dst,
                                       message_op="mul")
        np.testing.assert_allclose(out.numpy()[0],
                                   self.x.numpy()[0] * y.numpy()[1])
        assert out.shape == [4, 3]

    def test_bad_ops_raise(self):
        with pytest.raises(ValueError):
            paddle.geometric.send_u_recv(self.x, self.src, self.dst,
                                         reduce_op="prod")
        with pytest.raises(ValueError):
            paddle.geometric.send_ue_recv(
                self.x, self.x, self.src, self.dst, message_op="pow")


class TestReindex:
    def test_reindex_graph(self):
        x = paddle.to_tensor(np.array([0, 1, 2], np.int64))
        neighbors = paddle.to_tensor(np.array([8, 9, 0, 4, 7, 6, 7], np.int64))
        count = paddle.to_tensor(np.array([2, 3, 2], np.int32))
        src, dst, nodes = paddle.geometric.reindex_graph(x, neighbors, count)
        np.testing.assert_array_equal(src.numpy(), [3, 4, 0, 5, 6, 7, 6])
        np.testing.assert_array_equal(dst.numpy(), [0, 0, 1, 1, 1, 2, 2])
        np.testing.assert_array_equal(nodes.numpy(), [0, 1, 2, 8, 9, 4, 7, 6])

    def test_reindex_heter_graph(self):
        x = paddle.to_tensor(np.array([0, 1, 2], np.int64))
        na = np.array([8, 9, 0, 4, 7, 6, 7], np.int64)
        nb = np.array([0, 2, 3, 5, 1], np.int64)
        ca, cb = np.array([2, 3, 2], np.int32), np.array([1, 3, 1], np.int32)
        src, dst, nodes = paddle.geometric.reindex_heter_graph(
            x, [na, nb], [ca, cb])
        np.testing.assert_array_equal(
            src.numpy(), [3, 4, 0, 5, 6, 7, 6, 0, 2, 8, 9, 1])
        np.testing.assert_array_equal(
            dst.numpy(), [0, 0, 1, 1, 1, 2, 2, 0, 1, 1, 1, 2])
        np.testing.assert_array_equal(
            nodes.numpy(), [0, 1, 2, 8, 9, 4, 7, 6, 3, 5])


class TestSampling:
    def _csc(self):
        # 3 nodes; node0 <- {1,2}, node1 <- {0,1,2,0}, node2 <- {2}
        row = np.array([1, 2, 0, 1, 2, 0, 2], np.int64)
        colptr = np.array([0, 2, 6, 7], np.int64)
        return row, colptr

    def test_full_neighborhood(self):
        row, colptr = self._csc()
        n, c = paddle.geometric.sample_neighbors(
            paddle.to_tensor(row), paddle.to_tensor(colptr),
            paddle.to_tensor(np.array([0, 2], np.int64)), sample_size=-1)
        np.testing.assert_array_equal(c.numpy(), [2, 1])
        np.testing.assert_array_equal(n.numpy(), [1, 2, 2])

    def test_subsample_and_eids(self):
        row, colptr = self._csc()
        eids = np.arange(7, dtype=np.int64) * 10
        n, c, e = paddle.geometric.sample_neighbors(
            paddle.to_tensor(row), paddle.to_tensor(colptr),
            paddle.to_tensor(np.array([1], np.int64)), sample_size=2,
            eids=paddle.to_tensor(eids), return_eids=True)
        assert c.numpy()[0] == 2 and len(n.numpy()) == 2
        # sampled eids must point back at the sampled rows
        for ei, ni in zip(e.numpy(), n.numpy()):
            assert row[ei // 10] == ni

    def test_weighted_prefers_heavy_edges(self):
        row, colptr = self._csc()
        # node1 has 4 in-edges; weight edge idx 3 (row value 1) overwhelmingly
        w = np.array([1, 1, 1e-6, 1e6, 1e-6, 1e-6, 1], np.float64)
        hits = 0
        for s in range(20):
            paddle.seed(1000 + s)
            n, c = paddle.geometric.weighted_sample_neighbors(
                paddle.to_tensor(row), paddle.to_tensor(colptr),
                paddle.to_tensor(w),
                paddle.to_tensor(np.array([1], np.int64)), sample_size=1)
            hits += int(n.numpy()[0] == 1)
        assert hits >= 18

    def test_successive_calls_draw_fresh_samples(self):
        # regression: _rng must advance the generator counter, not
        # rebuild from the fixed seed (else every mini-batch sees the
        # identical neighborhood)
        row, colptr = self._csc()
        paddle.seed(3)
        draws = set()
        for _ in range(6):
            n, _ = paddle.geometric.sample_neighbors(
                paddle.to_tensor(row), paddle.to_tensor(colptr),
                paddle.to_tensor(np.array([1], np.int64)), sample_size=2)
            draws.add(tuple(n.numpy().tolist()))
        assert len(draws) > 1

    def test_deterministic_under_seed(self):
        row, colptr = self._csc()
        outs = []
        for _ in range(2):
            paddle.seed(7)
            n, _ = paddle.geometric.sample_neighbors(
                paddle.to_tensor(row), paddle.to_tensor(colptr),
                paddle.to_tensor(np.array([1], np.int64)), sample_size=2)
            outs.append(n.numpy())
        np.testing.assert_array_equal(outs[0], outs[1])


class TestIncubateLegacyAliases:
    """The incubate-era spellings (ref: ``python/paddle/incubate/
    operators/``) stay available after graduation to geometric."""

    def test_graph_send_recv_matches_send_u_recv(self):
        x = paddle.to_tensor(np.array([[0, 2, 3], [1, 4, 5], [2, 6, 7]],
                                  np.float32))
        src = paddle.to_tensor(np.array([0, 1, 2, 0]))
        dst = paddle.to_tensor(np.array([1, 2, 1, 0]))
        got = paddle.incubate.graph_send_recv(x, src, dst, pool_type="sum")
        want = paddle.geometric.send_u_recv(x, src, dst, reduce_op="sum")
        np.testing.assert_allclose(got.numpy(), want.numpy())

    def test_khop_sampler_docstring_graph(self):
        row = paddle.to_tensor(np.array([3, 7, 0, 9, 1, 4, 2, 9, 3, 9, 1, 9,
                                     7], np.int64))
        colptr = paddle.to_tensor(np.array([0, 2, 4, 5, 6, 7, 9, 11, 11, 13,
                                        13], np.int64))
        nodes = paddle.to_tensor(np.array([0, 8, 1, 2], np.int64))
        es, ed, si, rn = paddle.incubate.graph_khop_sampler(
            row, colptr, nodes, [2, 2])
        es, ed, si, rn = (t.numpy() for t in (es, ed, si, rn))
        # seeds come first in the sample index and reindex to themselves
        assert si[:4].tolist() == [0, 8, 1, 2]
        assert rn.tolist() == [0, 1, 2, 3]
        # every edge endpoint is a valid reindexed node id
        assert es.max() < len(si) and ed.max() < len(si)
        # edges decode back to real graph edges: dst's original id must
        # list src's original id among its CSC column
        rown, cols = np.asarray(row.numpy()), np.asarray(colptr.numpy())
        for s, d in zip(es, ed):
            src_orig, dst_orig = si[s], si[d]
            nbrs = rown[cols[dst_orig]:cols[dst_orig + 1]]
            assert src_orig in nbrs

    def test_softmax_mask_fuse_and_upper_triangle(self):
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(2, 3, 5, 5).astype(np.float32))
        mask = paddle.to_tensor(
            np.where(rs.rand(2, 1, 5, 5) > 0.5, 0.0, -1e9)
            .astype(np.float32))
        p = paddle.incubate.softmax_mask_fuse(x, mask).numpy()
        np.testing.assert_allclose(p.sum(-1), np.ones_like(p.sum(-1)),
                                   atol=1e-5)
        assert p[np.broadcast_to(mask.numpy() < -1e8, p.shape)].max() \
            < 1e-6
        pu = paddle.incubate.softmax_mask_fuse_upper_triangle(x).numpy()
        assert np.abs(np.triu(pu, 1)).max() == 0.0
        np.testing.assert_allclose(pu.sum(-1), np.ones_like(pu.sum(-1)),
                                   atol=1e-5)

    def test_identity_loss_reductions(self):
        x = paddle.to_tensor(np.array([[1.0], [2.0], [3.0]], np.float32))
        assert float(paddle.incubate.identity_loss(x, "sum").numpy()) == 6.0
        assert float(paddle.incubate.identity_loss(x, 1).numpy()) == 2.0
        np.testing.assert_allclose(
            paddle.incubate.identity_loss(x, "none").numpy(), x.numpy())
        with pytest.raises(Exception, match="Unsupported"):
            paddle.incubate.identity_loss(x, "bogus")


def test_khop_eids_align_with_edges_and_empty_hops():
    row = paddle.to_tensor(np.array([3, 7, 0, 9, 1, 4, 2, 9, 3, 9, 1, 9,
                                     7], np.int64))
    colptr = paddle.to_tensor(np.array([0, 2, 4, 5, 6, 7, 9, 11, 11, 13,
                                        13], np.int64))
    nodes = paddle.to_tensor(np.array([0, 8, 1, 2], np.int64))
    eids = paddle.to_tensor(np.arange(13, dtype=np.int64))
    es, ed, si, rn, ee = paddle.incubate.graph_khop_sampler(
        row, colptr, nodes, [2, 2], sorted_eids=eids, return_eids=True)
    es, ed, si, ee = (t.numpy() for t in (es, ed, si, ee))
    rown = np.array([3, 7, 0, 9, 1, 4, 2, 9, 3, 9, 1, 9, 7])
    cols = np.array([0, 2, 4, 5, 6, 7, 9, 11, 11, 13, 13])
    assert len(ee) == len(es)
    for s, d, e in zip(es, ed, ee):
        # eid e must be a CSC position inside dst's column whose row
        # entry is exactly src's original id
        dst_orig, src_orig = si[d], si[s]
        assert cols[dst_orig] <= e < cols[dst_orig + 1]
        assert rown[e] == src_orig
    # empty sample_sizes: seeds-only degenerate result, no crash
    es0, ed0, si0, rn0 = paddle.incubate.graph_khop_sampler(
        row, colptr, nodes, [])
    assert len(es0.numpy()) == 0 and si0.numpy().tolist() == [0, 8, 1, 2]
    assert rn0.numpy().tolist() == [0, 1, 2, 3]
