"""paddle.reader decorators + paddle.audio wav IO backends (ref:
``python/paddle/reader/decorator.py``,
``python/paddle/audio/backends/wave_backend.py``)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _r(n=6):
    def reader():
        yield from range(n)
    return reader


class TestReaderDecorators:
    def test_cache_replays(self):
        calls = []

        def reader():
            calls.append(1)
            yield from range(3)

        c = paddle.reader.cache(reader)
        assert list(c()) == [0, 1, 2]
        assert list(c()) == [0, 1, 2]
        assert len(calls) == 1  # source consumed exactly once

    def test_map_readers(self):
        out = list(paddle.reader.map_readers(
            lambda a, b: a + b, _r(), _r())())
        assert out == [0, 2, 4, 6, 8, 10]

    def test_shuffle_is_permutation(self):
        np.random.seed(0)
        out = list(paddle.reader.shuffle(_r(20), buf_size=8)())
        assert sorted(out) == list(range(20))

    def test_chain_and_firstn(self):
        out = list(paddle.reader.chain(_r(2), _r(3))())
        assert out == [0, 1, 0, 1, 2]
        assert list(paddle.reader.firstn(_r(10), 4)()) == [0, 1, 2, 3]

    def test_compose_alignment(self):
        out = list(paddle.reader.compose(_r(3), _r(3))())
        assert out == [(0, 0), (1, 1), (2, 2)]
        with pytest.raises(ValueError):
            list(paddle.reader.compose(_r(2), _r(3))())

    def test_buffered(self):
        assert list(paddle.reader.buffered(_r(5), size=2)()) == list(range(5))

    @pytest.mark.parametrize("order", [False, True])
    def test_xmap_readers(self, order):
        out = list(paddle.reader.xmap_readers(
            lambda x: x * 10, _r(12), process_num=3, buffer_size=4,
            order=order)())
        if order:
            assert out == [i * 10 for i in range(12)]
        else:
            assert sorted(out) == [i * 10 for i in range(12)]


class TestAudioIO:
    def test_save_info_load_roundtrip(self, tmp_path):
        sr = 16000
        t = np.linspace(0, 1, sr // 10, dtype=np.float32)
        wav = np.stack([np.sin(2 * np.pi * 440 * t) * 0.5,
                        np.cos(2 * np.pi * 220 * t) * 0.25])
        p = str(tmp_path / "t.wav")
        paddle.audio.save(p, paddle.to_tensor(wav), sr)

        got_info = paddle.audio.info(p)
        assert got_info.sample_rate == sr
        assert got_info.num_channels == 2
        assert got_info.bits_per_sample == 16
        assert got_info.num_frames == wav.shape[1]

        back, sr2 = paddle.audio.load(p)
        assert sr2 == sr and tuple(back.shape) == wav.shape
        np.testing.assert_allclose(back.numpy(), wav, atol=1e-3)

    def test_load_raw_and_offsets(self, tmp_path):
        sr = 8000
        wav = (np.arange(100, dtype=np.float32) / 200.0)[None]
        p = str(tmp_path / "o.wav")
        paddle.audio.save(p, wav, sr)
        raw, _ = paddle.audio.load(p, normalize=False)
        assert raw.numpy().dtype == np.int16
        seg, _ = paddle.audio.load(p, frame_offset=10, num_frames=20)
        assert tuple(seg.shape) == (1, 20)
        np.testing.assert_allclose(seg.numpy(), wav[:, 10:30], atol=1e-3)

    def test_backend_registry(self):
        assert paddle.audio.backends.list_available_backends() == \
            ["wave_backend"]
        with pytest.raises(NotImplementedError):
            paddle.audio.backends.set_backend("soundfile")


class TestErrorPropagation:
    def test_xmap_mapper_error_propagates(self):
        def src():
            yield from [1, 0, 2]

        with pytest.raises(ZeroDivisionError):
            list(paddle.reader.xmap_readers(
                lambda x: 1 / x, src, process_num=2, buffer_size=4)())

    def test_xmap_reader_error_propagates(self):
        def src():
            yield 1
            raise IOError("source died")

        with pytest.raises(IOError):
            list(paddle.reader.xmap_readers(
                lambda x: x, src, process_num=2, buffer_size=4)())

    def test_buffered_error_propagates(self):
        def src():
            yield 1
            raise IOError("truncated")

        with pytest.raises(IOError):
            list(paddle.reader.buffered(src, size=2)())

    def test_audio_file_object_handling(self, tmp_path):
        sr = 8000
        wav = np.zeros(80, np.float32)
        p = tmp_path / "f.wav"
        with open(p, "wb") as f:
            paddle.audio.save(f, wav, sr)
        with open(p, "rb") as f:
            got = paddle.audio.info(f)
            assert got.num_frames == 80
            f.seek(0)
            back, _ = paddle.audio.load(f)  # handle still open
            assert tuple(back.shape) == (1, 80)

    def test_audio_mono_channels_last_save(self, tmp_path):
        p = str(tmp_path / "m.wav")
        paddle.audio.save(p, np.zeros(100, np.float32), 8000,
                          channels_first=False)
        assert paddle.audio.info(p).num_channels == 1
        assert paddle.audio.info(p).num_frames == 100


class TestIncubateMultiprocessing:
    def test_tensor_pickles_through_forking_pickler(self):
        """The registered reduction must round-trip a Tensor through
        ForkingPickler bytes (shm or raw fallback), same process."""
        import io as _io
        from multiprocessing.reduction import ForkingPickler
        import pickle
        import paddle_tpu.incubate.multiprocessing  # registers reductions

        import paddle_tpu.incubate.multiprocessing as pmp
        pmp.set_sharing_strategy("file_system")  # opt in to shm transport
        t = paddle.to_tensor(np.arange(256 * 256, dtype=np.float32)
                             .reshape(256, 256))  # >=64K: shm path
        try:
            self._roundtrip(t, pmp)
        finally:
            pmp.set_sharing_strategy("bytes")

    def _roundtrip(self, t, pmp):
        import io as _io
        from multiprocessing.reduction import ForkingPickler
        import pickle
        buf = _io.BytesIO()
        ForkingPickler(buf, pickle.HIGHEST_PROTOCOL).dump(t)
        back = pickle.loads(buf.getvalue())
        np.testing.assert_array_equal(back.numpy(), t.numpy())
        # pickles must be re-loadable (segment survives multiple loads)
        back2 = pickle.loads(buf.getvalue())
        np.testing.assert_array_equal(back2.numpy(), t.numpy())
        assert pmp.get_sharing_strategy() == "file_system"
        with pytest.raises(ValueError):
            pmp.set_sharing_strategy("cuda_ipc")

    def test_parameter_roundtrip_preserves_subclass(self):
        import io as _io
        from multiprocessing.reduction import ForkingPickler
        import pickle
        from paddle_tpu.tensor import Parameter
        import paddle_tpu.incubate.multiprocessing  # registers reductions

        p = Parameter(np.ones((64, 64), np.float32), name="w0")
        buf = _io.BytesIO()
        ForkingPickler(buf, pickle.HIGHEST_PROTOCOL).dump(p)
        back = pickle.loads(buf.getvalue())
        assert isinstance(back, Parameter)
        assert back.name == "w0" and not back.stop_gradient
        np.testing.assert_array_equal(back.numpy(), p.numpy())

    def test_version_and_sysconfig(self):
        import os
        assert paddle.version.full_version == paddle.__version__
        paddle.version.show()
        assert paddle.version.cuda() == "False"
        inc = paddle.sysconfig.get_include()
        assert os.path.exists(os.path.join(inc, "common.h"))
