"""OpTest harness — the workhorse test pattern, re-designed for TPU.

Reference: ``test/legacy_test/eager_op_test.py:377 OpTest`` runs each op
through dygraph AND static paths on every device and checks outputs against
a numpy reference, and analytic grads against numeric finite differences
(`check_grad :2330`).

TPU equivalent implemented here:
 - eager path   = tape-recorded op on Tensors
 - static path  = the same op traced under `jax.jit` (shape-specialised)
 - grad check   = eager tape backward vs numeric central differences
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu
from paddle_tpu import Tensor


def check_output(op_fn, np_ref, inputs, atol=1e-5, rtol=1e-5, kwargs=None):
    """Run op eagerly and jitted; compare both against numpy reference."""
    kwargs = kwargs or {}
    tensors = [Tensor(np.asarray(a)) for a in inputs]

    # eager
    eager_out = op_fn(*tensors, **kwargs)

    # jitted ("static") path: same python fn traced through jax
    @jax.jit
    def traced(*datas):
        ts = [Tensor(d) for d in datas]
        out = op_fn(*ts, **kwargs)
        return jax.tree_util.tree_map(
            lambda t: t._data if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    static_out = traced(*[t._data for t in tensors])

    ref_out = np_ref(*[np.asarray(a) for a in inputs], **kwargs)

    def _cmp(a, b, tag):
        a = np.asarray(a._data if isinstance(a, Tensor) else a, dtype=np.float64) \
            if _is_float(a) else np.asarray(a._data if isinstance(a, Tensor) else a)
        b = np.asarray(b)
        np.testing.assert_allclose(a, b, atol=atol, rtol=rtol,
                                   err_msg=f"{tag} mismatch")

    flat_e = _flat(eager_out)
    flat_s = _flat(static_out)
    flat_r = _flat(ref_out)
    assert len(flat_e) == len(flat_r), "output arity mismatch"
    for e, s, r in zip(flat_e, flat_s, flat_r):
        _cmp(e, r, "eager-vs-numpy")
        _cmp(s, r, "static-vs-numpy")
    return eager_out


def _flat(x):
    if isinstance(x, (list, tuple)):
        out = []
        for v in x:
            out.extend(_flat(v))
        return out
    return [x]


def _is_float(x):
    arr = x._data if isinstance(x, Tensor) else x
    d = np.dtype(jnp.asarray(arr).dtype) if not hasattr(arr, "dtype") else np.dtype(arr.dtype)
    return d.kind == "f" or d == jnp.bfloat16


def check_grad(op_fn, inputs, kwargs=None, atol=5e-3, rtol=5e-3, eps=1e-3,
               output_index=None):
    """Analytic (tape) grad vs numeric central differences, like
    OpTest.check_grad. Inputs must be float64-representable."""
    kwargs = kwargs or {}
    arrays = [np.asarray(a, dtype=np.float32) for a in inputs]

    def scalar_loss(*arrs):
        ts = [Tensor(a) for a in arrs]
        out = op_fn(*ts, **kwargs)
        if output_index is not None:
            out = _flat(out)[output_index]
        return float(np.sum(np.asarray(out._data, dtype=np.float64)))

    # analytic via tape
    tensors = [Tensor(a, stop_gradient=False) for a in arrays]
    out = op_fn(*tensors, **kwargs)
    if output_index is not None:
        out = _flat(out)[output_index]
    loss = paddle_tpu.sum(out.astype("float32"))
    loss.backward()
    analytic = [np.asarray(t.grad._data) if t.grad is not None
                else np.zeros_like(a) for t, a in zip(tensors, arrays)]

    # numeric
    for gi, (a, g) in enumerate(zip(arrays, analytic)):
        num = np.zeros_like(a, dtype=np.float64)
        flat = a.ravel()
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            up = scalar_loss(*arrays)
            flat[i] = orig - eps
            dn = scalar_loss(*arrays)
            flat[i] = orig
            num.ravel()[i] = (up - dn) / (2 * eps)
        np.testing.assert_allclose(
            np.asarray(g, dtype=np.float64), num, atol=atol, rtol=rtol,
            err_msg=f"gradient mismatch for input {gi}")
