"""Reduce-scatter gradient bucketing on dp×sharding ZeRO meshes
(distributed/grad_buckets.py + the collective-schedule planner wired
through train_step._bucket_plan_for).

Covers: plan eligibility and both kill switches (``PT_GRAD_BUCKETS``,
``PT_COLLECTIVE_SCHEDULE``), the rank-major packing invariant (scatter
rows ARE the ``zero_spec`` windows), the scheduled marker's backward
semantics under shard_map, train-step parity on the 8-device CPU mesh,
and the reduce_scatter telemetry contract.

Parity is asserted two ways, deliberately:

- **bit parity (0.0)** between fused buckets and one-bucket-per-param
  (``grad_bucket_mb=2e-6`` → 2-byte target): same program structure,
  exactly what fusion replaces.
- **atol ≤ 1.4e-6** against the unbucketed GSPMD step: XLA's
  partitioner is free to re-associate the loss/grad reductions over the
  sharding devices, so the GSPMD baseline's own step-1 loss shifts by
  1 ulp on identical params — exact equality with it is not a property
  any explicit-collective implementation can promise.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
import paddle_tpu.observability as obs
from paddle_tpu.distributed._jax_compat import shard_map
from paddle_tpu.distributed.collective_schedule import plan_grad_reduction
from paddle_tpu.distributed.grad_buckets import (
    _from_rank_major, _to_rank_major, bucket_reduce_marker,
    partition_buckets)
from paddle_tpu.distributed.sharding import group_sharded_parallel
from paddle_tpu.distributed.train_step import (
    _bucket_plan_for, build_train_step, zero_spec)


@pytest.fixture(autouse=True)
def _reset():
    yield
    dist.set_mesh(None)
    dist.destroy_process_group()
    obs.reset()


# -- plan eligibility --------------------------------------------------------

def test_rs_plan_shape_and_gating(monkeypatch):
    params = {"w": np.zeros((64, 64), np.float32)}
    mesh = dist.init_mesh({"dp": 2, "sharding": 4})
    plan = _bucket_plan_for(params, mesh, "os", None)
    assert plan is not None and plan.schedule is not None
    assert plan.schedule.describe() == (
        "reduce_scatter(sharding:4) -> all_reduce(dp:2) -> "
        "all_gather(sharding:4)")
    assert plan.mapped_axes == ("dp", "sharding")
    assert all(b.kind == "reduce_scatter" for b in plan.buckets)
    # strategy-level off (sharding_configs.comm_overlap = False)
    assert _bucket_plan_for(params, mesh, "os", None,
                            collective_schedule=False) is None
    # env kill switches
    monkeypatch.setenv("PT_COLLECTIVE_SCHEDULE", "0")
    assert _bucket_plan_for(params, mesh, "os", None) is None
    monkeypatch.delenv("PT_COLLECTIVE_SCHEDULE")
    monkeypatch.setenv("PT_GRAD_BUCKETS", "0")
    assert _bucket_plan_for(params, mesh, "os", None) is None
    monkeypatch.delenv("PT_GRAD_BUCKETS")
    # ZeRO without a sharding axis: prior behavior (no bucketing)
    mesh_dp = dist.init_mesh({"dp": 8})
    assert _bucket_plan_for(params, mesh_dp, "os", None) is None
    # mp in play: GSPMD owns the gradient reduction
    mesh_mp = dist.init_mesh({"dp": 2, "sharding": 2, "mp": 2})
    assert _bucket_plan_for(params, mesh_mp, "os", None) is None


def test_unscatterable_params_ride_all_reduce_buckets():
    # 7x9 has no dim divisible by 4 -> zero_spec leaves it replicated,
    # so its grad reduces as a plain dp pmean; kinds never share buckets
    params = {"odd": np.zeros((7, 9), np.float32),
              "w": np.zeros((64, 64), np.float32)}
    mesh = dist.init_mesh({"dp": 2, "sharding": 4})
    plan = _bucket_plan_for(params, mesh, "os", None)
    kinds = {n: b.kind for b in plan.buckets for n in b.names}
    assert kinds == {"odd": "all_reduce", "w": "reduce_scatter"}
    assert plan.n_buckets == 2


def test_scatter_dims_match_zero_spec_windows():
    mesh = dist.init_mesh({"dp": 2, "sharding": 4})
    params = {"w1": np.zeros((64, 128), np.float32),   # largest dim 1
              "w2": np.zeros((128, 64), np.float32),   # largest dim 0
              "b": np.zeros((128,), np.float32)}       # rank-1, dim 0
    plan = _bucket_plan_for(params, mesh, "os", None)
    dims = {n: d for b in plan.buckets
            for n, d in zip(b.names, b.dims)}
    assert dims == {"w1": 1, "w2": 0, "b": 0}
    # the dim IS where zero_spec put the sharding axis
    assert zero_spec(P(), (64, 128), mesh) == P(None, "sharding")
    assert zero_spec(P(), (128, 64), mesh) == P("sharding", None)
    assert zero_spec(P(), (128,), mesh) == P("sharding")


# -- rank-major packing ------------------------------------------------------

def test_rank_major_rows_are_shard_windows():
    arr = np.arange(8 * 6, dtype=np.float32).reshape(8, 6)
    rm = np.asarray(_to_rank_major(jnp.asarray(arr), 0, 4))
    assert rm.shape == (4, 12)
    for r in range(4):
        np.testing.assert_array_equal(rm[r], arr[2 * r:2 * r + 2].ravel())
    rm1 = np.asarray(_to_rank_major(jnp.asarray(arr), 1, 2))
    for r in range(2):
        np.testing.assert_array_equal(rm1[r], arr[:, 3 * r:3 * r + 3].ravel())
    # inverse round-trips
    np.testing.assert_array_equal(
        np.asarray(_from_rank_major(jnp.asarray(rm), (8, 6), 0, 4)), arr)
    np.testing.assert_array_equal(
        np.asarray(_from_rank_major(jnp.asarray(rm1), (8, 6), 1, 2)), arr)


# -- scheduled marker semantics ----------------------------------------------

def test_schedule_marker_backward_is_dp_mean():
    # grads are replica-identical along sharding (the batch is dp-sharded
    # only); the full rs -> ar -> ag pipeline must therefore equal one
    # pmean over dp — scatter picks rank 0's copy, gather reassembles
    mesh = dist.init_mesh({"dp": 4, "sharding": 2})
    sched = plan_grad_reduction({"dp": 4, "sharding": 2}, "os")

    def body(x):
        def loss(v):
            v = bucket_reduce_marker(v, schedule=sched)
            rank = jax.lax.axis_index("dp").astype(jnp.float32)
            return (v * rank).sum()
        return jax.grad(loss)(x)

    g = jax.jit(shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(),
                          axis_names={"dp", "sharding"},
                          check_vma=False))(jnp.ones(8))
    # local grad on dp rank r is r; pmean over 4 ranks = mean(0..3) = 1.5
    np.testing.assert_allclose(np.asarray(g), 1.5, rtol=1e-6)


# -- train-step parity on the dp×sharding mesh -------------------------------

def _mlp():
    pt.seed(7)
    return nn.Sequential(nn.Linear(64, 128), nn.ReLU(),
                         nn.Linear(128, 128), nn.ReLU(),
                         nn.Linear(128, 8))


def _loss_fn(out, y):
    return pt.nn.functional.cross_entropy(out, y)


def _batch():
    rng = np.random.RandomState(0)
    return (rng.rand(16, 64).astype(np.float32),
            rng.randint(0, 8, (16,)).astype(np.int64))


_CACHE = {}


def _train(level, grad_bucket_mb, steps=4):
    key = (level, grad_bucket_mb, steps)
    if key in _CACHE:
        return _CACHE[key]
    mesh = dist.init_mesh({"dp": 2, "sharding": 4})
    model = _mlp()
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    model, opt, _ = group_sharded_parallel(model, opt, level=level)
    step, state = build_train_step(model, _loss_fn, opt, mesh=mesh,
                                   grad_bucket_mb=grad_bucket_mb)
    x, y = _batch()
    losses = []
    for _ in range(steps):
        loss, state = step(state, x, y)
        losses.append(float(loss))
    params = {k: np.asarray(v) for k, v in state["params"].items()}
    _CACHE[key] = (losses, params)
    return _CACHE[key]


@pytest.mark.parametrize("level", [
    "os", pytest.param("os_g", marks=pytest.mark.slow)])
def test_fused_vs_per_param_bit_parity(level):
    # 2e-6 MB ~= a 2-byte target: every parameter gets its own bucket.
    # Fusing buckets must not change a single bit over 4 steps.
    fused_l, fused_p = _train(level, 0.05)
    per_l, per_p = _train(level, 2e-6)
    assert fused_l == per_l, (fused_l, per_l)
    for k in fused_p:
        np.testing.assert_array_equal(fused_p[k], per_p[k], err_msg=k)


def test_bucketed_vs_gspmd_unbucketed_parity():
    fused_l, fused_p = _train("os", 0.05)
    base_l, base_p = _train("os", 0)  # mb=0 disables bucketing entirely
    np.testing.assert_allclose(fused_l, base_l, rtol=0, atol=1.4e-6)
    for k in fused_p:
        np.testing.assert_allclose(fused_p[k], base_p[k], rtol=0,
                                   atol=1e-6, err_msg=k)


# -- telemetry ---------------------------------------------------------------

def test_reduce_scatter_metrics_record_fused_payload():
    obs.get_telemetry().enable()
    mesh = dist.init_mesh({"dp": 2, "sharding": 4})
    model = _mlp()
    params = {k: p._data for k, p in model.named_parameters()}
    expected = _bucket_plan_for(params, mesh, "os", 0.05)
    rs = [b for b in expected.buckets if b.kind == "reduce_scatter"]
    assert expected.n_buckets > 1 and rs
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    model2, opt, _ = group_sharded_parallel(model, opt, level="os")
    pre = obs.get_registry().snapshot()
    step, state = build_train_step(model2, _loss_fn, opt, mesh=mesh,
                                   grad_bucket_mb=0.05)
    x, y = _batch()
    loss, state = step(state, x, y)
    jax.block_until_ready(loss)
    snap = obs.get_registry().snapshot()

    def series(s, name, key, field=None):
        v = s.get(name, {}).get("series", {}).get(key, 0)
        return v[field] if field and v else (v or 0)

    # one pt_grad_buckets_total{kind=reduce_scatter} per rs bucket
    assert (series(snap, "pt_grad_buckets_total", "kind=reduce_scatter")
            - series(pre, "pt_grad_buckets_total", "kind=reduce_scatter")
            == len(rs))
    # pt_collective_bytes{op=reduce_scatter}: ONE sample per bucket,
    # payload = the fused flat bytes (not one sample per parameter)
    pre_c = pre.get("pt_collective_bytes", {}).get("series", {}).get(
        "op=reduce_scatter", {"count": 0, "sum": 0})
    cur = snap["pt_collective_bytes"]["series"]["op=reduce_scatter"]
    assert cur["count"] - pre_c["count"] == len(rs)
    assert cur["sum"] - pre_c["sum"] == sum(b.nbytes for b in rs)
