"""paddle.utils / paddle.hub / paddle.batch / paddle.cost_model /
paddle.onnx surface tests (ref: ``python/paddle/utils``, ``hapi/hub.py``,
``batch.py``, ``cost_model/cost_model.py``, ``onnx/export.py``)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import utils


class TestUniqueName:
    def test_generate_counts(self):
        a = utils.unique_name.generate("fc")
        b = utils.unique_name.generate("fc")
        assert a != b and a.startswith("fc_")

    def test_guard_isolates(self):
        with utils.unique_name.guard():
            a = utils.unique_name.generate("x")
        with utils.unique_name.guard():
            b = utils.unique_name.generate("x")
        assert a == b  # fresh namespace each guard

    def test_guard_prefix(self):
        with utils.unique_name.guard("pre_"):
            assert utils.unique_name.generate("y").startswith("pre_y_")


class TestDlpack:
    def test_roundtrip(self):
        x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
        cap = utils.dlpack.to_dlpack(x)
        y = utils.dlpack.from_dlpack(cap)
        np.testing.assert_array_equal(x.numpy(), y.numpy())

    def test_from_numpy_protocol(self):
        # numpy >= 1.23 arrays speak __dlpack__
        arr = np.arange(6, dtype=np.float32)
        y = utils.dlpack.from_dlpack(arr)
        np.testing.assert_array_equal(y.numpy(), arr)

    @pytest.mark.slow
    def test_torch_interop(self):
        torch = pytest.importorskip("torch")
        t = torch.arange(8, dtype=torch.float32)
        y = utils.dlpack.from_dlpack(t)
        np.testing.assert_array_equal(y.numpy(), t.numpy())


class TestStructure:
    def test_flatten_pack(self):
        nest = {"a": [1, 2, (3,)], "b": 4}
        flat = utils.flatten(nest)
        assert flat == [1, 2, 3, 4]
        again = utils.pack_sequence_as(nest, [x * 10 for x in flat])
        assert again == {"a": [10, 20, (30,)], "b": 40}

    def test_map_structure(self):
        out = utils.map_structure(lambda a, b: a + b, [1, [2]], [10, [20]])
        assert out == [11, [22]]

    def test_assert_same_structure(self):
        utils.assert_same_structure([1, (2, 3)], [9, (8, 7)])
        with pytest.raises(ValueError):
            utils.assert_same_structure([1, 2], [1, [2]])

    def test_convert_to_list(self):
        assert utils.convert_to_list(3, 2, "stride") == [3, 3]
        assert utils.convert_to_list((1, 2), 2, "stride") == [1, 2]
        with pytest.raises(ValueError):
            utils.convert_to_list((1, 2, 3), 2, "stride")


class TestDeprecatedAndVersion:
    def test_deprecated_warns(self):
        @utils.deprecated(since="2.0", update_to="paddle.new_api", level=1)
        def old():
            """doc."""
            return 1

        with pytest.warns(DeprecationWarning):
            assert old() == 1
        assert "deprecated" in old.__doc__

    def test_deprecated_raises_at_level2(self):
        @utils.deprecated(level=2)
        def gone():
            return 1

        with pytest.raises(RuntimeError):
            gone()

    def test_require_version(self):
        assert utils.require_version("0.0.1")
        with pytest.raises(Exception):
            utils.require_version("999.0.0")

    def test_try_import(self):
        assert utils.try_import("json") is not None
        with pytest.raises(ImportError):
            utils.try_import("definitely_not_a_module_xyz")


class TestDownload:
    def test_local_path_passthrough(self, tmp_path):
        p = tmp_path / "w.bin"
        p.write_bytes(b"abc")
        assert utils.download.get_path_from_url(str(p)) == str(p)

    def test_cache_hit_and_md5(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_WEIGHT_PATH", str(tmp_path))
        (tmp_path / "model.bin").write_bytes(b"weights")
        got = utils.download.get_path_from_url(
            "https://example.com/model.bin")
        assert got == str(tmp_path / "model.bin")
        import hashlib
        good = hashlib.md5(b"weights").hexdigest()
        assert utils.download.get_path_from_url(
            "https://example.com/model.bin", md5sum=good) == got
        with pytest.raises(IOError):
            utils.download.get_path_from_url(
                "https://example.com/model.bin", md5sum="0" * 32)

    def test_cache_miss_raises_no_egress(self):
        with pytest.raises(RuntimeError, match="without network"):
            utils.download.get_path_from_url("https://example.com/nope.bin")


class TestRunCheck:
    @pytest.mark.slow
    def test_run_check(self, capsys):
        utils.run_check()
        out = capsys.readouterr().out
        assert "installed successfully" in out


class TestCppExtension:
    def test_jit_load_and_call(self, tmp_path):
        src = tmp_path / "addmul.cc"
        src.write_text("""
        extern "C" {
        double addmul(double a, double b) { return a * b + a; }
        }
        """)
        lib = utils.cpp_extension.load("addmul", [str(src)],
                                       build_directory=str(tmp_path))
        import ctypes
        lib.addmul.restype = ctypes.c_double
        lib.addmul.argtypes = [ctypes.c_double, ctypes.c_double]
        assert lib.addmul(3.0, 4.0) == 15.0

    def test_cpp_extension_object(self):
        ext = utils.cpp_extension.CppExtension(["a.cc"])
        assert "-std=c++17" in ext.extra_compile_args


class TestHubBatch:
    def _make_repo(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "dependencies = ['numpy']\n"
            "def small_model(scale=1):\n"
            "    '''Tiny model entrypoint.'''\n"
            "    return {'scale': scale}\n")
        return str(tmp_path)

    def test_hub_local(self, tmp_path):
        repo = self._make_repo(tmp_path)
        assert "small_model" in paddle.hub.list(repo, source="local")
        assert "Tiny model" in paddle.hub.help(repo, "small_model",
                                               source="local")
        assert paddle.hub.load(repo, "small_model", source="local",
                               scale=3) == {"scale": 3}

    def test_hub_remote_gated(self):
        with pytest.raises(RuntimeError, match="network"):
            paddle.hub.load("owner/repo", "m", source="github")

    def test_batch(self):
        def reader():
            yield from range(5)

        out = [b for b in paddle.batch(reader, batch_size=2)()]
        assert out == [[0, 1], [2, 3], [4]]
        out = [b for b in paddle.batch(reader, 2, drop_last=True)()]
        assert out == [[0, 1], [2, 3]]


class TestCostModel:
    def test_analytic_cost(self):
        import jax.numpy as jnp
        cm = paddle.cost_model.CostModel()
        cost = cm.analytic_cost(lambda x: x @ x, np.eye(64, dtype=np.float32))
        assert cost["flops"] >= 2 * 64**3 * 0.9

    def test_static_table(self):
        cm = paddle.cost_model.CostModel()
        data = cm.static_cost_data()
        assert any(r["op"] == "matmul" for r in data)
        t = cm.get_static_op_time("matmul")
        assert t["op_time"] > 0
        tb = cm.get_static_op_time("conv2d", forward=False)
        assert tb["op_time"] > 0

    def test_profile_measure(self):
        cm = paddle.cost_model.CostModel()
        startup, main = cm.build_program()
        stats = cm.profile_measure(startup, main)
        assert isinstance(stats, dict)


class TestOnnxExport:
    def test_export_writes_stablehlo(self, tmp_path):
        net = paddle.nn.Linear(4, 2)
        spec = [paddle.static.InputSpec(shape=[3, 4], dtype="float32")]
        out = paddle.onnx.export(net, str(tmp_path / "m.onnx"),
                                 input_spec=spec)
        loaded = paddle.jit.load(out)
        x = paddle.to_tensor(np.ones((3, 4), np.float32))
        np.testing.assert_allclose(loaded(x).numpy(), net(x).numpy(),
                                   rtol=1e-5)

    def test_strict_onnx_raises(self, tmp_path):
        net = paddle.nn.Linear(4, 2)
        with pytest.raises((ImportError, NotImplementedError)):
            paddle.onnx.export(net, str(tmp_path / "m.onnx"),
                               input_spec=[paddle.static.InputSpec(
                                   shape=[3, 4], dtype="float32")],
                               format="onnx")
