"""Parameter-server replacement: vocab-sharded embedding + row-sparse
updates (ref: ``paddle/fluid/distributed/ps/`` sparse tables — see the
descope rationale in ``paddle_tpu/distributed/ps/__init__.py``)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.ps import (ShardedEmbedding, row_sparse_apply,
                                       RowSparseAdagrad)
from paddle_tpu.distributed.train_step import build_train_step
from paddle_tpu.tensor import Tensor


@pytest.fixture(autouse=True)
def _reset():
    yield
    dist.set_mesh(None)
    dist.destroy_process_group()


VOCAB, DIM = 4096, 16


class _Net(pt.nn.Layer):
    def __init__(self, emb_cls=ShardedEmbedding, **kw):
        super().__init__()
        pt.seed(5)
        self.emb = emb_cls(VOCAB, DIM, **kw)
        self.head = pt.nn.Linear(DIM, 4)

    def forward(self, ids):
        return self.head(self.emb(ids).mean(1))


def _loss(out, y):
    return pt.nn.functional.cross_entropy(out, y)


class TestShardedEmbedding:
    def test_table_sharded_over_data_axes(self):
        dist.init_mesh({"dp": 2, "sharding": 2, "mp": 2})
        net = _Net()
        w = net.emb.weight
        assert net.emb._shard_axes == ("dp", "sharding", "mp")
        assert w._spec[0] == ("dp", "sharding", "mp")
        # per-device rows shrink 1/8 — the PS "table shard" memory win
        assert w._data.addressable_shards[0].data.shape[0] == VOCAB // 8

    @pytest.mark.slow
    def test_train_step_parity_with_dense_embedding(self):
        rng = np.random.RandomState(0)
        ids = rng.randint(0, VOCAB, (16, 8)).astype(np.int32)
        y = rng.randint(0, 4, (16,)).astype(np.int64)

        dist.init_mesh({"dp": 1})
        net_ref = _Net(emb_cls=pt.nn.Embedding)
        opt_ref = pt.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=net_ref.parameters())
        step_ref, st_ref = build_train_step(net_ref, _loss, opt_ref)
        ref = []
        for _ in range(3):
            l, st_ref = step_ref(st_ref, ids, y)
            ref.append(float(l))

        dist.init_mesh({"dp": 2, "sharding": 2, "mp": 2})
        net = _Net()
        opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=net.parameters())
        step, st = build_train_step(net, _loss, opt)
        got = []
        for _ in range(3):
            l, st = step(st, ids, y)
            got.append(float(l))
        np.testing.assert_allclose(ref, got, rtol=2e-5, atol=2e-5)

    def test_optimizer_state_shards_with_table(self):
        """ZeRO on top: moments of the table shard like the table."""
        dist.init_mesh({"dp": 2, "sharding": 4})
        net = _Net()
        opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=net.parameters())
        _, st = build_train_step(net, _loss, opt)
        m1 = st["opt"]["slots"]["moment1"]["emb.weight"]
        assert "sharding" in str(m1.sharding.spec)


class TestRowSparse:
    def test_row_sparse_apply_matches_dense_scatter(self):
        rng = np.random.RandomState(1)
        w = jnp.asarray(rng.randn(64, 8).astype(np.float32))
        ids = jnp.asarray(np.array([3, 7, 3, 9, 7, 3], np.int32))
        g = jnp.asarray(rng.randn(6, 8).astype(np.float32))

        new_w, uniq = row_sparse_apply(
            w, ids, g, lambda rows, grads: rows - 0.1 * grads)

        dense = np.zeros((64, 8), np.float32)
        for i, r in zip(np.asarray(ids), np.asarray(g)):
            dense[i] += r
        expect = np.asarray(w) - 0.1 * dense
        np.testing.assert_allclose(np.asarray(new_w), expect, rtol=1e-6)

    def test_row_sparse_adagrad_touches_only_seen_rows(self):
        rng = np.random.RandomState(2)
        table = Tensor(rng.randn(128, 8).astype(np.float32))
        before = np.asarray(table._data).copy()
        opt = RowSparseAdagrad(table, learning_rate=0.1)
        ids = np.array([[5, 9, 5], [40, 9, 5]], np.int32)
        g = rng.randn(2, 3, 8).astype(np.float32)
        opt.step_rows(ids, g)
        after = np.asarray(table._data)
        touched = {5, 9, 40}
        for r in range(128):
            if r in touched:
                assert not np.allclose(before[r], after[r]), r
            else:
                np.testing.assert_array_equal(before[r], after[r])
        # second step keeps shrinking effective lr via the accumulator
        acc = np.asarray(opt._acc)
        assert all(acc[r] > 0 for r in touched)
        assert acc[0] == 0
