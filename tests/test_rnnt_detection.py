"""RNNT loss (numpy lattice-DP oracle) + detection ops
(generate_proposals / distribute_fpn_proposals / yolo_box).
Ref oracles: warprnnt transducer recursion (Graves 2012 eq. 16-18);
``python/paddle/vision/ops.py`` semantics."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.tensor import Tensor
import paddle_tpu.vision.ops as V


def _np_rnnt(acts, labels, T, U, blank=0):
    """Graves transducer -log p(y|x), single sample, numpy DP."""
    a = acts - np.max(acts, -1, keepdims=True)
    lp = a - np.log(np.exp(a).sum(-1, keepdims=True))
    alpha = np.full((T, U + 1), -np.inf)
    alpha[0, 0] = 0.0
    for t in range(T):
        for u in range(U + 1):
            if t == 0 and u == 0:
                continue
            terms = []
            if t > 0:
                terms.append(alpha[t - 1, u] + lp[t - 1, u, blank])
            if u > 0:
                terms.append(alpha[t, u - 1] + lp[t, u - 1, labels[u - 1]])
            alpha[t, u] = np.logaddexp.reduce(terms)
    return -(alpha[T - 1, U] + lp[T - 1, U, blank])


class TestRNNTLoss:
    def test_matches_numpy_dp(self):
        rng = np.random.RandomState(0)
        B, T, U, V_ = 3, 6, 4, 8
        acts = rng.randn(B, T, U + 1, V_).astype(np.float32)
        labels = rng.randint(1, V_, (B, U)).astype(np.int32)
        ilen = np.array([6, 5, 3], np.int32)
        ulen = np.array([4, 2, 3], np.int32)

        loss = pt.nn.functional.rnnt_loss(
            Tensor(acts), Tensor(labels), Tensor(ilen), Tensor(ulen),
            blank=0, fastemit_lambda=0.0, reduction="none")
        got = np.asarray(loss._data)
        want = np.array([
            _np_rnnt(acts[b, :ilen[b], :ulen[b] + 1], labels[b, :ulen[b]],
                     int(ilen[b]), int(ulen[b]))
            for b in range(B)])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    @pytest.mark.slow
    def test_gradients_flow_and_match_fd(self):
        rng = np.random.RandomState(1)
        T, U, V_ = 4, 2, 5
        acts = rng.randn(1, T, U + 1, V_).astype(np.float32)
        labels = np.array([[2, 3]], np.int32)
        ilen = np.array([T], np.int32)
        ulen = np.array([U], np.int32)

        def loss_of(a):
            t = Tensor(a)
            t.stop_gradient = False
            out = pt.nn.functional.rnnt_loss(
                t, Tensor(labels), Tensor(ilen), Tensor(ulen),
                fastemit_lambda=0.0, reduction="mean")
            return out

        x = Tensor(acts)
        x.stop_gradient = False
        out = pt.nn.functional.rnnt_loss(
            x, Tensor(labels), Tensor(ilen), Tensor(ulen),
            fastemit_lambda=0.0, reduction="mean")
        out.backward()
        g = np.asarray(x.grad._data)
        assert np.abs(g).sum() > 0
        # finite-difference check on a few coordinates
        eps = 1e-3
        for (t, u, v) in [(0, 0, 0), (2, 1, 3), (3, 2, 0)]:
            ap = acts.copy()
            ap[0, t, u, v] += eps
            am = acts.copy()
            am[0, t, u, v] -= eps
            fd = (float(loss_of(ap)._data) - float(loss_of(am)._data)) / (
                2 * eps)
            np.testing.assert_allclose(g[0, t, u, v], fd, rtol=5e-2,
                                       atol=5e-3)

    @pytest.mark.slow
    def test_fastemit_increases_emit_weight(self):
        rng = np.random.RandomState(2)
        acts = rng.randn(1, 4, 3, 6).astype(np.float32)
        labels = np.array([[1, 2]], np.int32)
        il, ul = np.array([4], np.int32), np.array([2], np.int32)
        l0 = float(pt.nn.functional.rnnt_loss(
            Tensor(acts), Tensor(labels), Tensor(il), Tensor(ul),
            fastemit_lambda=0.0)._data)
        l1 = float(pt.nn.functional.rnnt_loss(
            Tensor(acts), Tensor(labels), Tensor(il), Tensor(ul),
            fastemit_lambda=0.1)._data)
        assert l1 < l0  # emit paths up-weighted => higher ll, lower loss


class TestGenerateProposals:
    def _inputs(self):
        rng = np.random.RandomState(3)
        N, A, H, W = 1, 3, 4, 4
        scores = rng.rand(N, A, H, W).astype(np.float32)
        deltas = (rng.randn(N, 4 * A, H, W) * 0.1).astype(np.float32)
        img = np.array([[64.0, 64.0]], np.float32)
        ys, xs = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
        base = np.stack([xs * 16, ys * 16, xs * 16 + 15, ys * 16 + 15],
                        axis=-1).astype(np.float32)
        anchors = np.broadcast_to(base[:, :, None, :], (H, W, A, 4)).copy()
        var = np.ones((H, W, A, 4), np.float32)
        return scores, deltas, img, anchors, var

    def test_shapes_and_ordering(self):
        scores, deltas, img, anchors, var = self._inputs()
        rois, probs, num = V.generate_proposals(
            Tensor(scores), Tensor(deltas), Tensor(img), Tensor(anchors),
            Tensor(var), pre_nms_top_n=30, post_nms_top_n=10,
            nms_thresh=0.7, min_size=1.0, return_rois_num=True)
        r = np.asarray(rois._data)
        p = np.asarray(probs._data).ravel()
        assert r.shape[1] == 4 and r.shape[0] == int(num._data[0])
        assert r.shape[0] <= 10
        assert np.all(np.diff(p) <= 1e-6)          # score-sorted
        assert np.all(r[:, 0] >= 0) and np.all(r[:, 2] <= 64)
        assert np.all(r[:, 2] >= r[:, 0]) and np.all(r[:, 3] >= r[:, 1])

    def test_nms_suppresses_overlaps(self):
        scores, deltas, img, anchors, var = self._inputs()
        rois, _ = V.generate_proposals(
            Tensor(scores), Tensor(deltas), Tensor(img), Tensor(anchors),
            Tensor(var), pre_nms_top_n=48, post_nms_top_n=48,
            nms_thresh=0.3, min_size=1.0)
        r = np.asarray(rois._data)
        ious = np.asarray(V.box_iou(Tensor(r), Tensor(r))._data).copy()
        np.fill_diagonal(ious, 0.0)
        assert ious.max() <= 0.3 + 1e-5


class TestDistributeFpn:
    def test_routing_and_restore(self):
        rois = np.array([
            [0, 0, 16, 16],      # small -> low level
            [0, 0, 224, 224],    # refer_scale -> refer_level
            [0, 0, 500, 500],    # large -> high level
            [0, 0, 20, 20],
        ], np.float32)
        multi, restore, nums = V.distribute_fpn_proposals(
            Tensor(rois), min_level=2, max_level=5, refer_level=4,
            refer_scale=224, rois_num=Tensor(np.array([4], np.int32)))
        sizes = [int(np.asarray(m._data).shape[0]) for m in multi]
        assert sum(sizes) == 4
        assert sizes[-1] >= 1          # the 500-box went to the top level
        # restore index is a permutation that rebuilds the input order
        cat = np.concatenate([np.asarray(m._data) for m in multi
                              if np.asarray(m._data).size])
        ri = np.asarray(restore._data).ravel()
        np.testing.assert_allclose(cat[ri], rois)


class TestYoloBox:
    def test_decode_shapes_and_ranges(self):
        rng = np.random.RandomState(4)
        N, an, cls, H, W = 2, 3, 5, 4, 4
        x = rng.randn(N, an * (5 + cls), H, W).astype(np.float32)
        img = np.array([[128, 128], [96, 160]], np.int32)
        boxes, scores = V.yolo_box(
            Tensor(x), Tensor(img), anchors=[10, 13, 16, 30, 33, 23],
            class_num=cls, conf_thresh=0.0, downsample_ratio=32)
        b = np.asarray(boxes._data)
        s = np.asarray(scores._data)
        assert b.shape == (N, an * H * W, 4)
        assert s.shape == (N, an * H * W, cls)
        assert np.all(s >= 0) and np.all(s <= 1)
        assert np.all(b[0, :, 2] <= 127.0 + 1e-5)  # clipped to image
        assert np.all(b[:, :, 0] >= 0)

    @pytest.mark.slow
    def test_conf_thresh_zeroes_low_confidence(self):
        rng = np.random.RandomState(5)
        x = rng.randn(1, 2 * 7, 2, 2).astype(np.float32) * 0.01  # conf~0.5
        img = np.array([[64, 64]], np.int32)
        boxes, scores = V.yolo_box(
            Tensor(x), Tensor(img), anchors=[10, 13, 16, 30], class_num=2,
            conf_thresh=0.9, downsample_ratio=32)
        assert float(jnp.abs(boxes._data).sum()) == 0.0
        assert float(jnp.abs(scores._data).sum()) == 0.0

    def test_iou_aware_rescoring(self):
        rng = np.random.RandomState(6)
        an, cls, H, W = 2, 3, 2, 2
        x = rng.randn(1, an + an * (5 + cls), H, W).astype(np.float32)
        img = np.array([[64, 64]], np.int32)
        b1, s1 = V.yolo_box(Tensor(x), Tensor(img),
                            anchors=[10, 13, 16, 30], class_num=cls,
                            conf_thresh=0.0, downsample_ratio=32,
                            iou_aware=True, iou_aware_factor=0.5)
        # factor 0 must reduce to plain decoding of the non-iou part
        b0, s0 = V.yolo_box(Tensor(x[:, an:]), Tensor(img),
                            anchors=[10, 13, 16, 30], class_num=cls,
                            conf_thresh=0.0, downsample_ratio=32)
        np.testing.assert_allclose(np.asarray(b1._data),
                                   np.asarray(b0._data), rtol=1e-5)
        assert not np.allclose(np.asarray(s1._data), np.asarray(s0._data))


class TestDeformConv2d:
    @pytest.mark.slow
    def test_zero_offsets_match_plain_conv(self):
        """With zero offsets (and no mask) deformable conv IS standard
        convolution — oracle: F.conv2d."""
        rng = np.random.RandomState(7)
        x = rng.randn(2, 4, 8, 8).astype(np.float32)
        w = rng.randn(6, 4, 3, 3).astype(np.float32)
        off = np.zeros((2, 2 * 9, 6, 6), np.float32)
        got = V.deform_conv2d(Tensor(x), Tensor(off), Tensor(w))
        want = pt.nn.functional.conv2d(Tensor(x), Tensor(w))
        np.testing.assert_allclose(np.asarray(got._data),
                                   np.asarray(want._data),
                                   rtol=1e-4, atol=1e-4)

    def test_zero_offset_matches_plain_conv_fast(self):
        # FAST-tier guard: zero offsets reduce deform_conv2d to a plain
        # convolution (capability keeps one fast test; the sampling-
        # shift and modulation suites are slow-tier)
        rs = np.random.RandomState(3)
        x = rs.randn(1, 2, 6, 6).astype(np.float32)
        w = rs.randn(3, 2, 3, 3).astype(np.float32)
        off = np.zeros((1, 18, 6, 6), np.float32)
        got = np.asarray(V.deform_conv2d(
            Tensor(x), Tensor(off), Tensor(w), padding=1)._data)
        import jax
        ref = jax.lax.conv_general_dilated(
            x, w, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        np.testing.assert_allclose(got, np.asarray(ref), atol=2e-4,
                                   rtol=2e-4)

    @pytest.mark.slow
    def test_integer_offset_shifts_sampling(self):
        """An integer (dy, dx) = (0, 1) offset on every tap equals
        convolving the input shifted left by one pixel."""
        rng = np.random.RandomState(8)
        x = rng.randn(1, 2, 8, 8).astype(np.float32)
        w = rng.randn(3, 2, 3, 3).astype(np.float32)
        off = np.zeros((1, 2 * 9, 6, 6), np.float32)
        off[:, 1::2] = 1.0  # dx = +1 on every tap
        got = V.deform_conv2d(Tensor(x), Tensor(off), Tensor(w))
        x_shift = np.zeros_like(x)
        x_shift[..., :-1] = x[..., 1:]
        want = pt.nn.functional.conv2d(Tensor(x_shift), Tensor(w))
        # interior columns identical (border columns touch zero padding)
        np.testing.assert_allclose(
            np.asarray(got._data)[..., :-1],
            np.asarray(want._data)[..., :-1], rtol=1e-4, atol=1e-4)

    @pytest.mark.slow
    def test_modulated_mask_and_grads(self):
        rng = np.random.RandomState(9)
        x = Tensor(rng.randn(1, 2, 6, 6).astype(np.float32))
        x.stop_gradient = False
        w = Tensor(rng.randn(2, 2, 3, 3).astype(np.float32))
        w.stop_gradient = False
        off = Tensor((rng.randn(1, 18, 4, 4) * 0.5).astype(np.float32))
        off.stop_gradient = False
        mask = Tensor(np.full((1, 9, 4, 4), 0.5, np.float32))
        out = V.deform_conv2d(x, off, w, mask=mask)
        out.sum().backward()
        assert x.grad is not None and np.abs(
            np.asarray(x.grad._data)).sum() > 0
        assert w.grad is not None and off.grad is not None
        # mask=0.5 halves the output vs mask=None
        out2 = V.deform_conv2d(x, off, w)
        np.testing.assert_allclose(np.asarray(out._data) * 2,
                                   np.asarray(out2._data),
                                   rtol=1e-4, atol=1e-4)


class TestPSRoIPool:
    def test_position_sensitive_channel_selection(self):
        """Oracle: explicit numpy loop over bins/channels."""
        rng = np.random.RandomState(10)
        ph = pw = 2
        Co = 3
        x = rng.randn(1, Co * ph * pw, 8, 8).astype(np.float32)
        rois = np.array([[0.0, 0.0, 8.0, 8.0],
                         [2.0, 2.0, 6.0, 6.0]], np.float32)
        out = V.psroi_pool(Tensor(x), Tensor(rois),
                           Tensor(np.array([2], np.int32)), 2)
        got = np.asarray(out._data)
        assert got.shape == (2, Co, 2, 2)

        def oracle(box):
            o = np.zeros((Co, ph, pw), np.float32)
            x0, y0, x1, y1 = box
            rh, rw = max(y1 - y0, .1) / ph, max(x1 - x0, .1) / pw
            for c in range(Co):
                for i in range(ph):
                    for j in range(pw):
                        ys = int(np.floor(y0 + i * rh))
                        ye = int(np.ceil(y0 + (i + 1) * rh))
                        xs = int(np.floor(x0 + j * rw))
                        xe = int(np.ceil(x0 + (j + 1) * rw))
                        ch = c * ph * pw + i * pw + j
                        o[c, i, j] = x[0, ch, ys:ye, xs:xe].mean()
            return o

        for r in range(2):
            np.testing.assert_allclose(got[r], oracle(rois[r]),
                                       rtol=1e-5, atol=1e-5)

    @pytest.mark.slow
    def test_gradients_flow(self):
        rng = np.random.RandomState(11)
        x = Tensor(rng.randn(1, 8, 6, 6).astype(np.float32))
        x.stop_gradient = False
        rois = Tensor(np.array([[0.0, 0.0, 6.0, 6.0]], np.float32))
        out = V.psroi_pool(x, rois, Tensor(np.array([1], np.int32)), 2)
        out.sum().backward()
        assert np.abs(np.asarray(x.grad._data)).sum() > 0


class TestYoloLoss:
    def _setup(self, seed=12):
        rng = np.random.RandomState(seed)
        N, an, cls, H, W = 2, 3, 4, 4, 4
        x = (rng.randn(N, an * (5 + cls), H, W) * 0.1).astype(np.float32)
        gt_box = np.zeros((N, 3, 4), np.float32)
        gt_box[0, 0] = [0.3, 0.4, 0.25, 0.3]   # one real box
        gt_box[1, 0] = [0.6, 0.6, 0.4, 0.5]
        gt_label = np.zeros((N, 3), np.int64)
        gt_label[0, 0] = 2
        gt_label[1, 0] = 1
        kw = dict(anchors=[10, 13, 16, 30, 33, 23],
                  anchor_mask=[0, 1, 2], class_num=cls,
                  ignore_thresh=0.7, downsample_ratio=8,
                  use_label_smooth=False)
        return x, gt_box, gt_label, kw

    @pytest.mark.slow
    def test_shape_and_finite(self):
        x, gtb, gtl, kw = self._setup()
        loss = V.yolo_loss(Tensor(x), Tensor(gtb), Tensor(gtl), **kw)
        got = np.asarray(loss._data)
        assert got.shape == (2,)
        assert np.all(np.isfinite(got)) and np.all(got > 0)

    @pytest.mark.slow
    def test_trains_head_to_lower_loss(self):
        x, gtb, gtl, kw = self._setup()
        import paddle_tpu as ptm
        t = Tensor(x)
        t.stop_gradient = False
        opt = ptm.optimizer.Adam(learning_rate=0.05, parameters=[t])
        first = None
        for _ in range(30):
            loss = V.yolo_loss(t, Tensor(gtb), Tensor(gtl), **kw).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
            if first is None:
                first = float(loss)
        assert float(loss) < 0.5 * first, (first, float(loss))

    @pytest.mark.slow
    def test_padding_boxes_are_ignored(self):
        x, gtb, gtl, kw = self._setup()
        l1 = np.asarray(V.yolo_loss(Tensor(x), Tensor(gtb), Tensor(gtl),
                                    **kw)._data)
        # extra padding rows (w=0) must not change the loss
        gtb2 = np.concatenate([gtb, np.zeros((2, 5, 4), np.float32)], 1)
        gtl2 = np.concatenate([gtl, np.zeros((2, 5), np.int64)], 1)
        l2 = np.asarray(V.yolo_loss(Tensor(x), Tensor(gtb2), Tensor(gtl2),
                                    **kw)._data)
        np.testing.assert_allclose(l1, l2, rtol=1e-5)

    @pytest.mark.slow
    def test_gt_score_weights_positive_terms_linearly(self):
        """Mixup semantics per the reference kernel: gt_score WEIGHTS the
        positive-sample terms (obj target stays 1), so the loss is linear
        in the score: l(0.5) == (l(0) + l(1)) / 2."""
        x, gtb, gtl, kw = self._setup()

        def loss_with(s):
            sc = np.zeros((2, 3), np.float32)
            sc[0, 0] = sc[1, 0] = s
            return np.asarray(V.yolo_loss(
                Tensor(x), Tensor(gtb), Tensor(gtl),
                gt_score=Tensor(sc), **kw)._data)

        # linear on the positive range (score > 1e-5)
        l25, l50, l75 = loss_with(0.25), loss_with(0.5), loss_with(0.75)
        assert not np.allclose(l25, l75)
        np.testing.assert_allclose(l50, (l25 + l75) / 2, rtol=1e-5)
        # ref CalcObjnessLoss endpoint: score==0 flips the responsible
        # cell to a NEGATIVE sample, adding SCE(conf, 0) loss beyond the
        # linear extrapolation
        l0 = loss_with(0.0)
        extrap = 2 * l25 - l50
        assert np.all(l0 > extrap - 1e-6)
        assert np.any(l0 > extrap + 1e-6)

    @pytest.mark.slow
    def test_two_gts_in_same_cell_both_contribute(self):
        """Reference accumulates per-gt losses — a duplicate (cell,
        anchor) assignment must not silently drop one box."""
        x, gtb, gtl, kw = self._setup()
        gtb2 = gtb.copy()
        gtb2[0, 1] = gtb2[0, 0]          # same center/shape => same cell
        gtl2 = gtl.copy()
        gtl2[0, 1] = 3                   # different class
        l_one = np.asarray(V.yolo_loss(Tensor(x), Tensor(gtb),
                                       Tensor(gtl), **kw)._data)
        l_two = np.asarray(V.yolo_loss(Tensor(x), Tensor(gtb2),
                                       Tensor(gtl2), **kw)._data)
        assert l_two[0] > l_one[0]       # second gt's loc+cls terms added

    @pytest.mark.slow
    def test_degenerate_height_box_is_padding(self):
        x, gtb, gtl, kw = self._setup()
        gtb2 = gtb.copy()
        gtb2[0, 1] = [0.5, 0.5, 0.3, 0.0]   # w>0, h==0: invalid per ref
        l1 = np.asarray(V.yolo_loss(Tensor(x), Tensor(gtb),
                                    Tensor(gtl), **kw)._data)
        l2 = np.asarray(V.yolo_loss(Tensor(x), Tensor(gtb2),
                                    Tensor(gtl), **kw)._data)
        np.testing.assert_allclose(l1, l2, rtol=1e-5)

    def test_label_smoothing_formula(self):
        """Default use_label_smooth=True applies the reference delta =
        min(1/class_num, 1/40) two-sided smoothing (changes the loss)."""
        x, gtb, gtl, kw = self._setup()
        kw.pop("use_label_smooth")
        l_smooth = np.asarray(V.yolo_loss(
            Tensor(x), Tensor(gtb), Tensor(gtl),
            use_label_smooth=True, **kw)._data)
        l_hard = np.asarray(V.yolo_loss(
            Tensor(x), Tensor(gtb), Tensor(gtl),
            use_label_smooth=False, **kw)._data)
        assert np.all(np.isfinite(l_smooth))
        assert not np.allclose(l_smooth, l_hard)


class TestVisionOpsExtra:
    """read_file/decode_jpeg/prior_box/matrix_nms/ConvNormActivation
    (ref ``python/paddle/vision/ops.py``)."""

    def test_read_decode_jpeg_roundtrip(self, tmp_path):
        from PIL import Image
        import paddle_tpu as ptm
        # smooth gradient: random noise is JPEG-hostile at any quality
        yy, xx = np.mgrid[0:16, 0:20].astype(np.float32)
        arr = np.stack([yy * 15, xx * 12, (yy + xx) * 7],
                       -1).astype(np.uint8)
        p = str(tmp_path / "t.jpg")
        Image.fromarray(arr).save(p, quality=95)
        raw = ptm.vision.ops.read_file(p)
        assert raw.numpy().dtype == np.uint8 and raw.numpy().ndim == 1
        img = ptm.vision.ops.decode_jpeg(raw, mode="rgb")
        assert tuple(img.shape) == (3, 16, 20)
        # jpeg is lossy; mean error must still be small
        assert np.abs(img.numpy().transpose(1, 2, 0).astype(np.int32)
                      - arr.astype(np.int32)).mean() < 16
        g = ptm.vision.ops.decode_jpeg(raw, mode="gray")
        assert tuple(g.shape) == (1, 16, 20)

    def test_prior_box_geometry(self):
        import paddle_tpu as ptm
        feat = ptm.to_tensor(np.zeros((1, 8, 4, 4), np.float32))
        img = ptm.to_tensor(np.zeros((1, 3, 32, 32), np.float32))
        boxes, variances = ptm.vision.ops.prior_box(
            feat, img, min_sizes=[8.0], max_sizes=[16.0],
            aspect_ratios=[2.0], flip=True, clip=True)
        # priors: ar 1 + big + ar 2 + ar 1/2 = 4
        assert tuple(boxes.shape) == (4, 4, 4, 4)
        b = boxes.numpy()
        assert (b >= 0).all() and (b <= 1).all()
        # center of cell (0,0) is at offset*step = 4px -> 0.125
        cx = (b[0, 0, 0, 0] + b[0, 0, 0, 2]) / 2
        np.testing.assert_allclose(cx, 0.125, atol=1e-6)
        np.testing.assert_allclose(variances.numpy()[0, 0, 0],
                                   [0.1, 0.1, 0.2, 0.2])

    def test_matrix_nms_suppresses_overlaps(self):
        import paddle_tpu as ptm
        # two heavily-overlapping boxes + one separate box, one class
        boxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                           [20, 20, 30, 30]]], np.float32)
        scores = np.array([[[0.0, 0.0, 0.0],      # class 0 = background
                            [0.9, 0.8, 0.7]]], np.float32)
        out, rois_num = ptm.vision.ops.matrix_nms(
            ptm.to_tensor(boxes), ptm.to_tensor(scores),
            score_threshold=0.1, post_threshold=0.5, nms_top_k=10,
            keep_top_k=10)
        o = out.numpy()
        assert rois_num.numpy()[0] == o.shape[0]
        # top box and the separate box survive; the duplicate is decayed
        kept_scores = sorted(o[:, 1], reverse=True)
        assert kept_scores[0] > 0.89
        assert all(o[:, 0] == 1)  # class label 1
        dup = [r for r in o if abs(r[2] - 0.5) < 0.2 and r[1] > 0.5]
        assert not dup, dup  # decayed duplicate must drop below 0.5
        assert o.shape[0] == 2, o

    def test_matrix_nms_return_index_and_gaussian(self):
        import paddle_tpu as ptm
        boxes = np.random.RandomState(0).rand(2, 5, 4).astype(np.float32)
        boxes[..., 2:] += boxes[..., :2] + 0.1
        scores = np.random.RandomState(1).rand(2, 2, 5).astype(np.float32)
        out, idx, rn = ptm.vision.ops.matrix_nms(
            ptm.to_tensor(boxes), ptm.to_tensor(scores),
            score_threshold=0.0, post_threshold=0.0, nms_top_k=-1,
            keep_top_k=3, use_gaussian=True, return_index=True)
        assert rn.numpy().sum() == out.numpy().shape[0]
        assert idx.numpy().shape == (out.numpy().shape[0], 1)
        assert (rn.numpy() <= 3).all()

    def test_conv_norm_activation_block(self):
        import paddle_tpu as ptm
        blk = ptm.vision.ops.ConvNormActivation(3, 8, kernel_size=3)
        x = ptm.to_tensor(np.random.RandomState(0)
                          .rand(2, 3, 8, 8).astype(np.float32))
        out = blk(x)
        assert tuple(out.shape) == (2, 8, 8, 8)
        assert float(out.numpy().min()) >= 0  # ReLU tail

    def test_conv_norm_activation_none_omits_layers(self):
        import paddle_tpu as ptm
        blk = ptm.vision.ops.ConvNormActivation(3, 8, norm_layer=None,
                                                activation_layer=None)
        names = [type(l).__name__ for l in blk]
        assert names == ["Conv2D"], names
        # norm-free conv keeps its bias (reference default)
        assert blk[0].bias is not None

    def test_image_backend_and_load(self, tmp_path):
        import paddle_tpu as ptm
        from PIL import Image
        p = str(tmp_path / "img.png")
        arr = np.arange(48, dtype=np.uint8).reshape(4, 4, 3)
        Image.fromarray(arr).save(p)
        assert ptm.vision.get_image_backend() == "pil"
        img = ptm.vision.image_load(p)
        assert hasattr(img, "resize")  # PIL object
        arr2 = ptm.vision.image_load(p, backend="cv2")
        assert isinstance(arr2, np.ndarray)
        np.testing.assert_array_equal(arr2[..., ::-1], arr)  # BGR vs RGB
        ptm.vision.set_image_backend("cv2")
        try:
            assert isinstance(ptm.vision.image_load(p), np.ndarray)
        finally:
            ptm.vision.set_image_backend("pil")
        with pytest.raises(ValueError):
            ptm.vision.set_image_backend("turbojpeg")
