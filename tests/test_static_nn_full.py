"""static.nn completion (ref: ``python/paddle/static/nn/``): layer
wrappers, data_norm/row_conv/nce/py_func, the LoD sequence op family
over the side-registry lod convention, and StaticRNN unrolling."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.static as static

S = static.nn


def _t(a):
    return pt.to_tensor(np.asarray(a))


class TestLayerWrappers:
    @pytest.mark.slow
    def test_norm_wrappers_match_layers(self):
        pt.seed(0)
        x = _t(np.random.RandomState(0).randn(2, 4, 6, 6)
               .astype(np.float32))
        assert tuple(S.group_norm(x, groups=2).shape) == (2, 4, 6, 6)
        assert tuple(S.instance_norm(x).shape) == (2, 4, 6, 6)
        ln = S.layer_norm(x, begin_norm_axis=2)
        m = ln.numpy().reshape(2, 4, -1).mean(-1)
        assert abs(m).max() < 1e-4  # normalized over dims [2:]
        assert tuple(S.prelu(x, "channel").shape) == (2, 4, 6, 6)
        w = _t(np.random.RandomState(1).randn(5, 8).astype(np.float32))
        sn = S.spectral_norm(w)
        # spectral norm scales the largest singular value to ~1
        assert np.linalg.svd(sn.numpy(), compute_uv=False)[0] < 1.5

    def test_conv3d_and_transpose(self):
        pt.seed(0)
        x = _t(np.random.RandomState(0).randn(1, 2, 4, 4, 4)
               .astype(np.float32))
        assert tuple(S.conv3d(x, 3, 3, padding=1).shape) == (1, 3, 4, 4, 4)
        assert tuple(S.conv3d_transpose(x, 3, 2, stride=2).shape) == \
            (1, 3, 8, 8, 8)

    def test_bilinear_fast(self):
        pt.seed(0)
        a = _t(np.random.RandomState(0).randn(3, 4).astype(np.float32))
        b = _t(np.random.RandomState(1).randn(3, 5).astype(np.float32))
        assert tuple(S.bilinear_tensor_product(a, b, 6).shape) == (3, 6)

    def test_row_conv_fast(self):
        pt.seed(0)
        seq = _t(np.random.RandomState(1).randn(1, 4, 2)
                 .astype(np.float32))
        rc = S.row_conv(seq, future_context_size=1)
        assert tuple(rc.shape) == (1, 4, 2)

    @pytest.mark.slow
    def test_bilinear_and_deform(self):
        pt.seed(0)
        a = _t(np.random.RandomState(0).randn(3, 4).astype(np.float32))
        b = _t(np.random.RandomState(1).randn(3, 5).astype(np.float32))
        assert tuple(S.bilinear_tensor_product(a, b, 6).shape) == (3, 6)
        x = _t(np.random.RandomState(2).randn(1, 2, 5, 5)
               .astype(np.float32))
        off = _t(np.zeros((1, 18, 5, 5), np.float32))
        mask = _t(np.ones((1, 9, 5, 5), np.float32))
        out = S.deform_conv2d(x, off, mask, 4, 3, padding=1)
        assert tuple(out.shape) == (1, 4, 5, 5)

    @pytest.mark.slow
    def test_data_norm_row_conv_nce(self):
        pt.seed(0)
        x = _t(np.random.RandomState(0).randn(8, 4).astype(np.float32))
        dn = S.data_norm(x)
        assert tuple(dn.shape) == (8, 4)  # stats-normalized, not NaN
        assert np.isfinite(dn.numpy()).all()
        seq = _t(np.random.RandomState(1).randn(2, 6, 3)
                 .astype(np.float32))
        rc = S.row_conv(seq, future_context_size=2)
        assert tuple(rc.shape) == (2, 6, 3)
        emb = _t(np.random.RandomState(2).randn(4, 8).astype(np.float32))
        lab = _t(np.array([1, 3, 0, 2], np.int64))
        loss = S.nce(emb, lab, num_total_classes=10, num_neg_samples=3)
        assert tuple(loss.shape) == (4, 1)
        assert (loss.numpy() > 0).all()

    def test_py_func_eager_and_traced(self):
        import jax

        def np_fn(a):
            return (a * 2 + 1).astype(np.float32)

        x = _t(np.ones((2, 3), np.float32))
        out = S.py_func(np_fn, x, out=x)
        np.testing.assert_allclose(out.numpy(), 3.0)

        def traced(arr):
            from paddle_tpu.tensor import Tensor
            return S.py_func(np_fn, Tensor(arr), out=Tensor(arr))._data

        got = jax.jit(traced)(np.ones((2, 3), np.float32))
        np.testing.assert_allclose(np.asarray(got), 3.0)

    def test_sparse_embedding(self):
        ids = _t(np.array([[1], [3]], np.int64))
        out = S.sparse_embedding(ids, [10, 6])
        assert tuple(out.shape) == (2, 1, 6)


class TestSequenceOps:
    def _lod_x(self, lens=(2, 3, 1), d=4, seed=0):
        total = sum(lens)
        x = _t(np.random.RandomState(seed).randn(total, d)
               .astype(np.float32))
        return S.set_lod(x, lens)

    def test_pool_variants_and_steps(self):
        x = self._lod_x()
        xn = x.numpy()
        np.testing.assert_allclose(
            S.sequence_pool(x, "sum").numpy(),
            np.stack([xn[0:2].sum(0), xn[2:5].sum(0), xn[5:6].sum(0)]),
            rtol=1e-5)
        np.testing.assert_allclose(
            S.sequence_pool(x, "average").numpy()[1], xn[2:5].mean(0),
            rtol=1e-5)
        np.testing.assert_allclose(
            S.sequence_pool(x, "max").numpy()[0], xn[0:2].max(0),
            rtol=1e-5)
        np.testing.assert_allclose(S.sequence_first_step(x).numpy(),
                                   xn[[0, 2, 5]], rtol=1e-6)
        np.testing.assert_allclose(S.sequence_last_step(x).numpy(),
                                   xn[[1, 4, 5]], rtol=1e-6)

    def test_softmax_and_reverse(self):
        x = self._lod_x(d=1)
        p = S.sequence_softmax(x).numpy().ravel()
        assert abs(p[0:2].sum() - 1) < 1e-5
        assert abs(p[2:5].sum() - 1) < 1e-5
        r = S.sequence_reverse(x).numpy().ravel()
        xn = x.numpy().ravel()
        np.testing.assert_allclose(r[:2], xn[1::-1], rtol=1e-6)
        np.testing.assert_allclose(r[2:5], xn[4:1:-1], rtol=1e-6)

    def test_pad_unpad_round_trip(self):
        x = self._lod_x()
        out, length = S.sequence_pad(x, _t(np.float32(0.0)))
        assert tuple(out.shape) == (3, 3, 4)
        assert length.numpy().tolist() == [2, 3, 1]
        assert np.abs(out.numpy()[0, 2]).max() == 0.0  # padded slot
        back = S.sequence_unpad(out, length)
        np.testing.assert_allclose(back.numpy(), x.numpy(), rtol=1e-6)
        assert S.get_lod(back).tolist() == [2, 3, 1]

    def test_expand_and_expand_as(self):
        x = _t(np.array([[1.0], [2.0], [3.0]], np.float32))
        S.set_lod(x, [1, 2])
        y = _t(np.zeros((5, 1), np.float32))
        S.set_lod(y, [2, 3])
        ex = S.sequence_expand(x, y)
        np.testing.assert_allclose(ex.numpy().ravel(),
                                   [1, 1, 2, 3, 2, 3, 2, 3])
        x2 = _t(np.array([[7.0], [9.0]], np.float32))
        ea = S.sequence_expand_as(x2, y)
        np.testing.assert_allclose(ea.numpy().ravel(),
                                   [7, 7, 9, 9, 9])

    def test_concat_slice_reshape_enumerate_scatter(self):
        a = _t(np.arange(6, dtype=np.float32).reshape(3, 2))
        S.set_lod(a, [2, 1])
        b = _t(np.arange(10, 16, dtype=np.float32).reshape(3, 2))
        S.set_lod(b, [1, 2])
        c = S.sequence_concat([a, b])
        np.testing.assert_allclose(
            c.numpy(),
            np.vstack([a.numpy()[:2], b.numpy()[:1],
                       a.numpy()[2:], b.numpy()[1:]]))
        assert S.get_lod(c).tolist() == [3, 3]
        sl = S.sequence_slice(c, _t(np.array([0, 1])),
                              _t(np.array([2, 1])))
        assert sl.numpy().shape == (3, 2)
        rs = S.sequence_reshape(a, new_dim=1)
        assert S.get_lod(rs).tolist() == [4, 2]
        ids = _t(np.array([[3], [1], [2], [0]], np.int64))
        S.set_lod(ids, [2, 2])
        en = S.sequence_enumerate(ids, win_size=2, pad_value=-1)
        np.testing.assert_allclose(en.numpy(),
                                   [[3, 1], [1, -1], [2, 0], [0, -1]])
        base = _t(np.zeros((2, 5), np.float32))
        upd = _t(np.ones((4, 1), np.float32).ravel())
        sc = S.sequence_scatter(base, ids, upd)
        want = np.zeros((2, 5), np.float32)
        want[0, 3] = want[0, 1] = want[1, 2] = want[1, 0] = 1.0
        np.testing.assert_allclose(sc.numpy(), want)

    def test_sequence_conv_window_oracle(self):
        pt.seed(0)
        x = self._lod_x(lens=(3, 2), d=2, seed=3)
        out = S.sequence_conv(x, num_filters=3, filter_size=3,
                              bias_attr=False)
        assert tuple(out.shape) == (5, 3)
        assert S.get_lod(out).tolist() == [3, 2]
        # boundary rows must not see the neighbouring sequence: row 3
        # (first of seq 2) uses window [pad, x3, x4] only
        assert np.isfinite(out.numpy()).all()

    def test_lod_validation(self):
        x = _t(np.zeros((4, 2), np.float32))
        with pytest.raises(ValueError, match="lod lengths"):
            S.set_lod(x, [1, 1])


def test_static_rnn_unroll_matches_manual_loop():
    pt.seed(0)
    T, B, D, H = 4, 2, 3, 5
    x = _t(np.random.RandomState(0).randn(T, B, D).astype(np.float32))
    W = _t(np.random.RandomState(1).randn(D + H, H).astype(np.float32))
    rnn = S.StaticRNN()
    with rnn.step():
        word = rnn.step_input(x)
        prev = rnn.memory(shape=[H], batch_ref=word, ref_batch_dim_idx=0)
        hidden = pt.tanh(pt.matmul(pt.concat([word, prev], axis=1), W))
        rnn.update_memory(prev, hidden)
        rnn.step_output(hidden)
    out = rnn()
    h = np.zeros((B, H), np.float32)
    outs = []
    for t in range(T):
        h = np.tanh(np.concatenate([x.numpy()[t], h], axis=1) @ W.numpy())
        outs.append(h)
    np.testing.assert_allclose(out.numpy(), np.stack(outs), atol=1e-5)


def test_static_rnn_grads_flow_to_weights():
    pt.seed(0)
    x = _t(np.random.RandomState(0).randn(3, 2, 4).astype(np.float32))
    W = _t(np.random.RandomState(1).randn(4 + 4, 4).astype(np.float32))
    W.stop_gradient = False
    rnn = S.StaticRNN()
    with rnn.step():
        word = rnn.step_input(x)
        prev = rnn.memory(shape=[4], batch_ref=word, ref_batch_dim_idx=0)
        h = pt.tanh(pt.matmul(pt.concat([word, prev], axis=1), W))
        rnn.update_memory(prev, h)
        rnn.step_output(h)
    rnn().sum().backward()
    assert W.grad is not None
    g = W.grad.numpy()
    assert np.isfinite(g).all() and np.abs(g).max() > 0


def test_prelu_element_mode_and_group_norm_nhwc():
    pt.seed(0)
    x = _t(np.random.RandomState(0).randn(2, 3, 4, 5).astype(np.float32))
    out = S.prelu(x, "element")
    xn = x.numpy()
    np.testing.assert_allclose(out.numpy(),
                               np.where(xn > 0, xn, 0.25 * xn), rtol=1e-5)
    xh = _t(np.random.RandomState(1).randn(2, 6, 6, 4)
            .astype(np.float32))
    gn = S.group_norm(xh, groups=2, data_layout="NHWC")
    # per-sample, per-group statistics over the CHANNEL-LAST layout
    g = gn.numpy().reshape(2, -1, 2, 2)  # (B, HW, groups, C/groups)
    assert abs(g.mean(axis=(1, 3))).max() < 1e-3


def test_data_norm_counters_accumulate():
    pt.seed(0)
    x = _t(np.ones((10, 3), np.float32) * 2.0)
    from paddle_tpu.static import nn_static as _m
    # counters are created inside; run twice and confirm the stats move
    out1 = S.data_norm(x)
    assert np.isfinite(out1.numpy()).all()


def test_static_rnn_read_only_memory():
    pt.seed(0)
    x = _t(np.random.RandomState(0).randn(3, 2, 4).astype(np.float32))
    bias = _t(np.random.RandomState(1).randn(2, 4).astype(np.float32))
    rnn = S.StaticRNN()
    with rnn.step():
        w = rnn.step_input(x)
        ro = rnn.memory(init=bias)  # never updated: constant context
        rnn.step_output(w + ro)
    out = rnn()
    want = x.numpy() + bias.numpy()[None]
    np.testing.assert_allclose(out.numpy(), want, rtol=1e-5)
