"""Round-4 nn gaps: margin_cross_entropy (incl. mp-sharded), hsigmoid,
multi-margin, pairwise distance, max-unpool, Softmax2D/Unflatten, beam
search decode (ref: ``python/paddle/nn/functional/loss.py:2033``,
``python/paddle/nn/decode.py:153,994``)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import Tensor
from paddle_tpu.distributed._jax_compat import shard_map as _shard_map, use_mesh as _use_mesh

RNG = np.random.RandomState(0)


def _cosine_logits(n, c, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(n, 3)
    X /= np.linalg.norm(X, axis=1, keepdims=True)
    W = r.randn(3, c)
    W /= np.linalg.norm(W, axis=0, keepdims=True)
    return (X @ W).astype("float32")


def _mce_ref(logits, label, m1=1.0, m2=0.5, m3=0.0, s=64.0):
    mod = logits.copy().astype(np.float64)
    for i in range(len(label)):
        c = np.clip(logits[i, label[i]], -1 + 1e-7, 1 - 1e-7)
        mod[i, label[i]] = np.cos(m1 * np.arccos(c) + m2) - m3
    mod *= s
    sm = np.exp(mod - mod.max(1, keepdims=True))
    sm /= sm.sum(1, keepdims=True)
    return -np.log(sm[np.arange(len(label)), label]), sm


def test_margin_cross_entropy_single():
    logits = _cosine_logits(4, 6)
    label = np.array([2, 0, 5, 3], "int64")
    loss, sm = F.margin_cross_entropy(
        pt.to_tensor(logits), pt.to_tensor(label), return_softmax=True,
        reduction=None)
    ref_loss, ref_sm = _mce_ref(logits, label)
    np.testing.assert_allclose(loss.numpy().ravel(), ref_loss, atol=1e-4)
    np.testing.assert_allclose(sm.numpy(), ref_sm, atol=1e-4)
    # reductions
    lm = F.margin_cross_entropy(pt.to_tensor(logits), pt.to_tensor(label),
                                reduction="mean")
    np.testing.assert_allclose(float(lm.numpy()), ref_loss.mean(), rtol=1e-4)


def test_margin_cross_entropy_mp_sharded():
    """Class-sharded margin CE over an mp mesh must match the gathered
    single-device result (the reference's model-parallel mode)."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU mesh")
    n_mp = 4
    C = 8  # 2 classes per rank
    logits = _cosine_logits(6, C, seed=1)
    label = np.array([0, 3, 7, 5, 2, 6], "int64")
    ref_loss, _ = _mce_ref(logits, label)
    mesh = Mesh(np.array(jax.devices()[:n_mp]).reshape(n_mp), ("mp",))

    def f(lg, y):
        out = F.margin_cross_entropy(Tensor(lg), Tensor(y), reduction=None)
        return out._data

    sharded = jax.jit(_shard_map(
        f, mesh=mesh, in_specs=(P(None, "mp"), P()), out_specs=P()))
    got = np.asarray(sharded(jnp.asarray(logits), jnp.asarray(label)))
    np.testing.assert_allclose(got.ravel(), ref_loss, atol=1e-4)


def test_hsigmoid_loss_default_tree():
    D, K = 5, 8
    x = RNG.randn(3, D).astype("float32")
    w = RNG.randn(K - 1, D).astype("float32")
    b = RNG.randn(K - 1, 1).astype("float32")
    y = np.array([0, 3, 7], "int64")
    out = F.hsigmoid_loss(pt.to_tensor(x), pt.to_tensor(y), K,
                          pt.to_tensor(w), bias=pt.to_tensor(b))

    def ref(xi, yi):
        c = yi + K
        tot = 0.0
        for bit in range(int(np.floor(np.log2(c)))):
            idx = (c >> (bit + 1)) - 1
            t = float((c >> bit) & 1)
            pre = w[idx] @ xi + b[idx, 0]
            tot += max(pre, 0) - pre * t + np.log1p(np.exp(-abs(pre)))
        return tot

    want = [ref(x[i], int(y[i])) for i in range(3)]
    np.testing.assert_allclose(out.numpy().ravel(), want, atol=1e-4)


def test_hsigmoid_custom_path():
    D = 4
    x = RNG.randn(2, D).astype("float32")
    w = RNG.randn(5, D).astype("float32")
    table = np.array([[0, 2, 4], [1, 3, -1]], "int64")
    code = np.array([[1, 0, 1], [0, 1, 0]], "int64")
    out = F.hsigmoid_loss(pt.to_tensor(x), pt.to_tensor(
        np.array([0, 1], "int64")), 6, pt.to_tensor(w),
        path_table=pt.to_tensor(table), path_code=pt.to_tensor(code))
    want = []
    for i in range(2):
        tot = 0.0
        for jj in range(3):
            if table[i, jj] < 0:
                continue
            pre = w[table[i, jj]] @ x[i]
            t = float(code[i, jj])
            tot += max(pre, 0) - pre * t + np.log1p(np.exp(-abs(pre)))
        want.append(tot)
    np.testing.assert_allclose(out.numpy().ravel(), want, atol=1e-4)
    with pytest.raises(ValueError):
        F.hsigmoid_loss(pt.to_tensor(x), pt.to_tensor(
            np.array([0, 1], "int64")), 6, pt.to_tensor(w),
            path_table=pt.to_tensor(table))


@pytest.mark.slow
def test_hsigmoid_layer_trains():
    layer = nn.HSigmoidLoss(6, 10)
    x = Tensor(RNG.randn(4, 6).astype("float32"), stop_gradient=False)
    loss = pt.sum(layer(x, pt.to_tensor(np.array([1, 5, 9, 0], "int64"))))
    loss.backward()
    assert x.grad is not None
    assert np.isfinite(np.asarray(layer.weight.grad._data)).all()


def test_multi_margin_loss():
    x = RNG.randn(4, 5).astype("float32")
    y = RNG.randint(0, 5, 4).astype("int64")
    w = np.abs(RNG.randn(5)).astype("float32")
    got = F.multi_margin_loss(pt.to_tensor(x), pt.to_tensor(y), p=2,
                              margin=0.7, weight=pt.to_tensor(w),
                              reduction="none")
    want = []
    for i in range(4):
        acc = 0.0
        for j in range(5):
            if j != y[i]:
                acc += w[y[i]] * max(0.0, 0.7 - x[i, y[i]] + x[i, j]) ** 2
        want.append(acc / 5)
    np.testing.assert_allclose(got.numpy(), want, rtol=1e-4)
    assert nn.MultiMarginLoss()(pt.to_tensor(x),
                                pt.to_tensor(y)).shape == []


def test_pairwise_distance():
    x = RNG.randn(4, 6).astype("float32")
    y = RNG.randn(4, 6).astype("float32")
    got = F.pairwise_distance(pt.to_tensor(x), pt.to_tensor(y))
    want = np.linalg.norm(x - y + 1e-6, axis=-1)
    np.testing.assert_allclose(got.numpy(), want, rtol=1e-5)
    got = nn.PairwiseDistance(p=np.inf, keepdim=True)(
        pt.to_tensor(x), pt.to_tensor(y))
    np.testing.assert_allclose(
        got.numpy(), np.abs(x - y + 1e-6).max(-1, keepdims=True), rtol=1e-5)


@pytest.mark.parametrize("nd", [1, 2, 3])
def test_max_unpool_roundtrip(nd):
    shape = {1: (2, 3, 8), 2: (2, 3, 8, 8), 3: (1, 2, 4, 4, 4)}[nd]
    x = RNG.randn(*shape).astype("float32")
    pool = [F.max_pool1d, F.max_pool2d, F.max_pool3d][nd - 1]
    unpool = [F.max_unpool1d, F.max_unpool2d, F.max_unpool3d][nd - 1]
    pooled, idx = pool(pt.to_tensor(x), 2, return_mask=True)
    un = unpool(pooled, idx, 2)
    assert un.shape == list(shape)
    # every pooled max value lands back at its argmax position
    uv = un.numpy()
    pv = pooled.numpy()
    np.testing.assert_allclose(np.sort(uv[uv != 0]), np.sort(pv.ravel()),
                               rtol=1e-6)
    layer = [nn.MaxUnPool1D, nn.MaxUnPool2D, nn.MaxUnPool3D][nd - 1](2)
    np.testing.assert_allclose(layer(pooled, idx).numpy(), uv)


@pytest.mark.slow
def test_max_unpool_grad():
    x = Tensor(RNG.randn(1, 2, 4, 4).astype("float32"),
               stop_gradient=False)
    pooled, idx = F.max_pool2d(x, 2, return_mask=True)
    out = F.max_unpool2d(pooled, idx, 2)
    pt.sum(out * out).backward()
    assert np.isfinite(np.asarray(x.grad._data)).all()


def test_softmax2d_unflatten():
    x = RNG.randn(2, 3, 4, 5).astype("float32")
    out = nn.Softmax2D()(pt.to_tensor(x))
    np.testing.assert_allclose(out.numpy().sum(1),
                               np.ones((2, 4, 5)), rtol=1e-5)
    with pytest.raises(ValueError):
        nn.Softmax2D()(pt.to_tensor(np.zeros((2, 3), "float32")))
    u = nn.Unflatten(1, [2, 2])(pt.to_tensor(RNG.randn(3, 4).astype("f")))
    assert u.shape == [3, 2, 2]


def test_rnnt_loss_layer():
    logits = RNG.randn(2, 4, 3, 5).astype("float32")
    labels = np.array([[1, 2], [1, 1]], "int32")
    layer = nn.RNNTLoss(blank=0, fastemit_lambda=0.0)
    out = layer(pt.to_tensor(logits), pt.to_tensor(labels),
                pt.to_tensor(np.array([4, 4], "int32")),
                pt.to_tensor(np.array([2, 2], "int32")))
    want = F.rnnt_loss(pt.to_tensor(logits), pt.to_tensor(labels),
                       pt.to_tensor(np.array([4, 4], "int32")),
                       pt.to_tensor(np.array([2, 2], "int32")),
                       blank=0, fastemit_lambda=0.0)
    np.testing.assert_allclose(out.numpy(), want.numpy())


def test_gather_tree_docs_example():
    ids = pt.to_tensor(np.array(
        [[[2, 2], [6, 1]], [[3, 9], [6, 1]], [[0, 1], [9, 0]]], "int64"))
    parents = pt.to_tensor(np.array(
        [[[0, 0], [1, 1]], [[1, 0], [1, 0]], [[0, 0], [0, 1]]], "int64"))
    out = F.gather_tree(ids, parents)
    want = [[[2, 2], [1, 6]], [[3, 3], [6, 1]], [[0, 1], [9, 0]]]
    assert out.numpy().tolist() == want
    with pytest.raises(ValueError):
        F.gather_tree(pt.to_tensor(np.zeros((2, 2), "int64")), parents)


def test_beam_search_decode_end_token_wins():
    """A rigged cell that always prefers the end token must finish every
    beam immediately and early-exit the decode loop."""
    V, H = 7, 4

    class RiggedCell(nn.RNNCellBase):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(H, V)

        def forward(self, inputs, states=None):
            logits = np.full((inputs.shape[0], V), -5.0, np.float32)
            logits[:, 1] = 5.0  # end token
            return Tensor(jnp.asarray(logits)), states

    dec = nn.BeamSearchDecoder(RiggedCell(), start_token=0, end_token=1,
                               beam_size=2,
                               embedding_fn=nn.Embedding(V, H))
    h0 = pt.to_tensor(np.zeros((3, H), "float32"))
    outs, states, lens = nn.dynamic_decode(dec, inits=h0, max_step_num=10,
                                           return_length=True)
    # beam 0 ends at step 1; beam 1 keeps one non-end candidate for one
    # more step (correct beam-search bookkeeping) — loop exits at T=2,
    # far before max_step_num
    assert outs.shape[1] == 2
    arr = np.asarray(outs._data)
    assert (arr[:, 0, 0] == 1).all()          # top beam: end immediately
    assert np.asarray(states.finished).all()  # every beam finished
    assert (np.asarray(lens._data)[:, 0] == 1).all()


@pytest.mark.slow
def test_beam_search_decode_greedy_path():
    """Deterministic cell: token probabilities depend on the previous
    token so the top beam must follow the argmax chain."""
    V, H = 6, 5
    table = np.random.RandomState(42).randn(V, V).astype("float32") * 3

    class ChainCell(nn.RNNCellBase):
        def forward(self, inputs, states=None):
            # inputs: embedded previous token — we smuggle the raw id in
            # states instead (states = last token ids [N, 1])
            prev = states
            logits = jnp.asarray(table)[prev._data[:, 0]]
            return Tensor(logits), Tensor(prev._data)

    class IdEmb(nn.Layer):
        def forward(self, ids):
            return ids

    dec = nn.BeamSearchDecoder(ChainCell(), start_token=2, end_token=V - 1,
                               beam_size=3, embedding_fn=IdEmb())
    # states carry the previous ids; initialize with start token
    h0 = pt.to_tensor(np.full((2, 1), 2, "int32"))

    # patch: ChainCell ignores inputs; drive states with chosen tokens
    class ChainCell2(ChainCell):
        def forward(self, inputs, states=None):
            ids = inputs._data.reshape(-1)
            logits = jnp.asarray(table)[ids]
            return Tensor(logits), states

    dec = nn.BeamSearchDecoder(ChainCell2(), start_token=2,
                               end_token=V - 1, beam_size=3,
                               embedding_fn=IdEmb())
    outs, _ = nn.dynamic_decode(dec, inits=h0, max_step_num=4)
    got_first = np.asarray(outs._data)[0, :, 0]  # batch 0, top beam
    # manual greedy chain from token 2 (greedy == top beam for step 1)
    assert got_first[0] == int(np.argmax(table[2]))


@pytest.mark.slow
def test_margin_ce_layerwise_grad():
    logits = Tensor(_cosine_logits(4, 6), stop_gradient=False)
    label = pt.to_tensor(np.array([2, 0, 5, 3], "int64"))
    loss = F.margin_cross_entropy(logits, label)
    loss.backward()
    assert np.isfinite(np.asarray(logits.grad._data)).all()


def test_sparse_attention_vs_dense():
    """CSR-masked attention must equal dense attention with the same
    boolean mask (incl. an all-empty row and a padding/attn mask)."""
    B, H, S, D = 2, 2, 8, 4
    q = RNG.randn(B, H, S, D).astype("float32")
    k = RNG.randn(B, H, S, D).astype("float32")
    v = RNG.randn(B, H, S, D).astype("float32")
    # random CSR pattern per (b, h); row 3 of (0,0) left empty
    offsets = np.zeros((B, H, S + 1), "int32")
    columns = np.zeros((B, H, S * S), "int32")
    dense = np.zeros((B, H, S, S), bool)
    for b in range(B):
        for h in range(H):
            ptr = 0
            for r in range(S):
                if (b, h, r) == (0, 0, 3):
                    nnz = 0
                else:
                    nnz = RNG.randint(1, S)
                cs = np.sort(RNG.choice(S, nnz, replace=False))
                columns[b, h, ptr:ptr + nnz] = cs
                dense[b, h, r, cs] = True
                ptr += nnz
                offsets[b, h, r + 1] = ptr
    got = F.sparse_attention(
        pt.to_tensor(q), pt.to_tensor(k), pt.to_tensor(v),
        pt.to_tensor(offsets), pt.to_tensor(columns)).numpy()
    # dense reference
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    s = np.where(dense, s, -1e9)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    p = np.where(dense, p, 0.0)
    p = p / np.maximum(p.sum(-1, keepdims=True), 1e-9)
    want = np.einsum("bhqk,bhkd->bhqd", p, v)
    # empty row: output ~0 in our impl (all probs masked)
    np.testing.assert_allclose(got[0, 0, 3], 0.0, atol=1e-5)
    mask_rows = dense.any(-1)
    np.testing.assert_allclose(got[mask_rows], want[mask_rows], atol=1e-4)


def test_sparse_attention_masks_zero_means_masked():
    B, H, S, D = 1, 1, 4, 4
    q = RNG.randn(B, H, S, D).astype("float32")
    # full CSR pattern
    offsets = np.arange(0, (S + 1) * S, S, dtype="int32").reshape(1, 1, -1)
    columns = np.tile(np.arange(S, dtype="int32"), S).reshape(1, 1, -1)
    kpm = np.array([[1, 1, 0, 1]], "float32")  # key 2 padded out
    got = F.sparse_attention(
        pt.to_tensor(q), pt.to_tensor(q), pt.to_tensor(q),
        pt.to_tensor(offsets), pt.to_tensor(columns),
        key_padding_mask=pt.to_tensor(kpm)).numpy()
    # attn_mask with 0 at (q=1, k=3) must match kpm-style masking there
    am = np.ones((S, S), "float32")
    am[1, 3] = 0.0
    got2 = F.sparse_attention(
        pt.to_tensor(q), pt.to_tensor(q), pt.to_tensor(q),
        pt.to_tensor(offsets), pt.to_tensor(columns),
        attn_mask=pt.to_tensor(am)).numpy()
    assert np.isfinite(got).all() and np.isfinite(got2).all()
    # key 2 contributes nothing under kpm: recompute densely without it
    s = np.einsum("bhqd,bhkd->bhqk", q, q) / np.sqrt(D)
    s[..., 2] = -1e9
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", p, q)
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_dynamic_decode_custom_decoder():
    """A minimal custom Decoder (plain tuples, no namedtuple/lengths)
    must work through dynamic_decode."""
    class CountDecoder(nn.decode.Decoder):
        def initialize(self, inits):
            import jax.numpy as jnp2
            n = inits
            return (jnp2.zeros((n,), "int32"),
                    jnp2.zeros((n,), "int32"),
                    jnp2.zeros((n,), bool))

        def step(self, time, inputs, states, **kw):
            import jax.numpy as jnp2
            nxt = states + 1
            out = inputs + nxt
            fin = nxt >= 3
            return out, nxt, out, fin

        def finalize(self, outputs, final_states, seq_lens):
            return outputs, final_states

    outs, final, lens = nn.dynamic_decode(CountDecoder(), inits=4,
                                          max_step_num=10,
                                          return_length=True)
    arr = np.asarray(outs)
    assert arr.shape == (4, 3)  # batch-major [N, T]
    assert (np.asarray(lens._data) == 3).all()


def test_fastpath_fields_cover_slots():
    """The hot-path inlined constructors (autograd.record's Node fill,
    op_utils._fast_tensor) must keep setting every slot their classes
    declare — guards the duplicated field lists against silent desync."""
    from paddle_tpu.autograd import Node
    a = Tensor(np.ones(2, "float32"), stop_gradient=False)
    out = a * 2.0  # goes through _fast_tensor + inlined Node fill
    lazy_ok = {"name"}  # generated on first access via __getattr__
    for slot in Tensor.__slots__:
        if slot in ("__weakref__",) or slot in lazy_ok:
            continue
        assert hasattr(out, slot), f"_fast_tensor missed slot {slot}"
    node = out._node
    for slot in Node.__slots__:
        if slot == "__weakref__":
            continue
        assert hasattr(node, slot), f"record() missed Node slot {slot}"


def test_dynamic_decode_impute_finished():
    class TwoStep(nn.decode.Decoder):
        def initialize(self, inits):
            return (jnp.zeros((3,), "float32"), jnp.zeros((3,), "int32"),
                    jnp.zeros((3,), bool))

        def step(self, time, inputs, states, **kw):
            nxt = states + 1
            out = jnp.ones((3,), "float32")
            fin = nxt >= jnp.asarray([1, 2, 3])
            return out, nxt, out, fin

        def finalize(self, outputs, final_states, seq_lens):
            return outputs, final_states

    outs, _, lens = nn.dynamic_decode(TwoStep(), inits=None, max_step_num=5,
                                      impute_finished=True,
                                      return_length=True)
    arr = np.asarray(outs)  # [3, 3] batch-major
    # row 0 finished at t=1 -> steps 2,3 imputed to 0
    np.testing.assert_allclose(arr[0], [1, 0, 0])
    np.testing.assert_allclose(arr[1], [1, 1, 0])
    np.testing.assert_allclose(arr[2], [1, 1, 1])
    np.testing.assert_array_equal(np.asarray(lens._data), [1, 2, 3])
