"""Low-precision serving: the bf16/int8 ladders of the AOT engine.

Pins the contracts the low-precision subsystem ships on:

 - byte-budget page scaling (``kv_page_budget``): int8 buys >= 1.9x
   usable pages at the same HBM spend (the acceptance floor);
 - zero request-path compiles at EVERY precision — the per-precision
   bucket ladders are AOT-built like the fp32 one;
 - continuous-batching join/leave bit-identity at bf16 AND int8 (the
   fp32 contract survives the drop: per-row dynamic KV scales);
 - quantized served-model dirs: save -> load round-trips bit-identical
   to an engine that quantized the same fp32 weights inline;
 - PT_SERVE_PRECISION env plumbing and its validation;
 - the quality contract: max logit divergence of the int8 serve path
   vs the fp32 oracle stays inside the pinned tolerance.
"""
from __future__ import annotations

import numpy as np
import pytest

from paddle_tpu.serving import quant as sq
from paddle_tpu.serving.engine import ServeConfig, ServingEngine, load_engine
from paddle_tpu.serving.kv_cache import kv_page_budget
from paddle_tpu.serving.model import ModelSpec, init_params

SPEC = ModelSpec(vocab_size=64, hidden=32, layers=2, heads=2,
                 max_seq_len=64)
CFG = ServeConfig(decode_buckets=(4,), prefill_buckets=(16,),
                  kv_pages=32, page_size=4, max_inflight=16,
                  max_new_tokens=8)

# the int8 quality bar: max |logit gap| vs the fp32 oracle across the
# calibration prompts (measured ~2.5e-3 at this spec; an order of
# magnitude of slack, still far below anything that flips an argmax
# on this vocab)
DIVERGENCE_TOL = 0.05


def _params():
    return init_params(SPEC, seed=0)


def _prompts(n=7):
    rng = np.random.RandomState(2)
    return [rng.randint(1, SPEC.vocab_size,
                        size=rng.randint(2, 12)).tolist()
            for _ in range(n)]


@pytest.fixture(scope="module")
def int8_engine():
    eng = ServingEngine(SPEC, _params(), CFG.replace(precision="int8"))
    yield eng
    eng.close()


@pytest.fixture(scope="module")
def bf16_engine():
    eng = ServingEngine(SPEC, _params(), CFG.replace(precision="bf16"))
    yield eng
    eng.close()


# -- byte-budget page scaling ------------------------------------------------
class TestPageBudget:
    def test_fp32_budget_is_identity(self):
        assert kv_page_budget(32, "fp32", 16) == 32

    def test_bf16_doubles_usable_pages(self):
        # 31 usable fp32 pages at 64 B/row -> 62 usable at 32 B/row
        assert kv_page_budget(32, "bf16", 16) == 63

    def test_int8_clears_the_headroom_floor(self):
        # int8 at D=16 costs D + 4 B/row (values + the f32 scale riding
        # in the shadow scale pages): 1 + int(31 * 64 / 20) = 100
        pages = kv_page_budget(32, "int8", 16)
        assert pages == 100
        assert (pages - 1) / 31 >= 1.9      # the acceptance floor

    def test_unknown_precision_raises(self):
        with pytest.raises(ValueError):
            kv_page_budget(32, "fp8", 16)


# -- the bf16 / int8 ladders -------------------------------------------------
class TestInt8Engine:
    def test_pool_is_int8_with_scale_shadows(self, int8_engine):
        snap = int8_engine.pool.snapshot()
        assert snap["dtype"] == "int8"
        assert snap["scale_pages"] is True
        want = kv_page_budget(CFG.kv_pages, "int8", SPEC.head_dim)
        assert snap["usable_pages"] == want - 1   # minus the null page

    def test_healthz_reports_precision(self, int8_engine):
        health = int8_engine.healthz()
        assert health["precision"] == "int8"
        assert health["unexpected_compiles"] == 0

    def test_join_leave_bit_identity_and_zero_compiles(self, int8_engine):
        # the fp32 continuous-batching contract, unchanged at int8:
        # per-(token, head) KV scales are a pure per-row function, so a
        # sequence's bytes never depend on its batch neighbours
        prompts = _prompts()
        solo = [int8_engine.generate([p], max_new_tokens=8)[0]
                for p in prompts]
        batched = int8_engine.generate(prompts, max_new_tokens=8)
        assert batched == solo
        assert int8_engine.unexpected_compiles == 0

    def test_generate_is_deterministic(self, int8_engine):
        prompts = _prompts(3)
        first = int8_engine.generate(prompts, max_new_tokens=8)
        second = int8_engine.generate(prompts, max_new_tokens=8)
        assert first == second


class TestBf16Engine:
    def test_pool_is_bf16(self, bf16_engine):
        snap = bf16_engine.pool.snapshot()
        assert snap["dtype"] == "bfloat16"
        assert snap["scale_pages"] is False
        want = kv_page_budget(CFG.kv_pages, "bf16", SPEC.head_dim)
        assert snap["usable_pages"] == want - 1

    def test_join_leave_bit_identity_and_zero_compiles(self, bf16_engine):
        prompts = _prompts()
        solo = [bf16_engine.generate([p], max_new_tokens=8)[0]
                for p in prompts]
        batched = bf16_engine.generate(prompts, max_new_tokens=8)
        assert batched == solo
        assert bf16_engine.unexpected_compiles == 0


# -- quantized served-model dirs ---------------------------------------------
class TestQuantizedDir:
    def test_save_load_bit_identical_to_inline(self, tmp_path,
                                               int8_engine):
        path = sq.save_quantized_model(str(tmp_path / "m"), SPEC,
                                       _params(), config=CFG)
        eng = load_engine(path)
        try:
            assert eng.config.precision == "int8"
            prompts = _prompts(4)
            got = eng.generate(prompts, max_new_tokens=8)
            want = int8_engine.generate(prompts, max_new_tokens=8)
            # a dir saved from fp32 weights serves bit-for-bit like an
            # engine that quantized the same weights inline
            assert got == want
            assert eng.unexpected_compiles == 0
        finally:
            eng.close()

    def test_template_matches_quantized_tree(self):
        tmpl = sq.quantized_template(SPEC)
        qp = sq.quantize_params(_params(), SPEC)
        assert set(tmpl) == set(qp)
        for name in tmpl:
            assert tmpl[name].shape == qp[name].shape, name
            assert tmpl[name].dtype == qp[name].dtype, name

    def test_quantize_params_idempotent_and_detectable(self):
        p = _params()
        assert not sq.is_quantized_params(p)
        qp = sq.quantize_params(p, SPEC)
        assert sq.is_quantized_params(qp)
        again = sq.quantize_params(qp, SPEC)
        assert set(again) == set(qp)      # second pass is a no-op


# -- env plumbing ------------------------------------------------------------
class TestEnvPrecision:
    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("PT_SERVE_PRECISION", "int8")
        assert ServeConfig.from_env().precision == "int8"

    def test_explicit_override_beats_env(self, monkeypatch):
        monkeypatch.setenv("PT_SERVE_PRECISION", "int8")
        assert ServeConfig.from_env(
            precision="bf16").precision == "bf16"

    def test_bad_precision_rejected(self, monkeypatch):
        # from_env passes the raw string through; the gate is
        # normalized(), which every engine build runs before compiling
        monkeypatch.setenv("PT_SERVE_PRECISION", "fp8")
        cfg = ServeConfig.from_env()
        assert cfg.precision == "fp8"
        with pytest.raises(ValueError, match="precision"):
            cfg.normalized(SPEC)


# -- calibration + the quality contract --------------------------------------
class TestQuality:
    def test_calibrate_records_positive_scales(self):
        cal = sq.calibrate(SPEC, _params(),
                           sq.default_calibration_prompts(SPEC),
                           page_size=CFG.page_size)
        assert cal["samples"] > 0
        assert cal["act_scales"]
        for site, scale in cal["act_scales"].items():
            assert np.isfinite(scale) and scale > 0, site

    def test_logit_divergence_within_pinned_tolerance(self):
        div = sq.logit_divergence(SPEC, _params(),
                                  page_size=CFG.page_size)
        assert 0.0 <= div < DIVERGENCE_TOL

    def test_eager_quant_tooling_is_sanctioned_next_to_live_engine(
            self, int8_engine):
        # calibration/quality replays compile eagerly; run beside a LIVE
        # armed engine they must ride the sanctioned build phase instead
        # of booking request-path compiles on it
        before = int8_engine.unexpected_compiles
        sq.calibrate(SPEC, _params(),
                     sq.default_calibration_prompts(SPEC, n=1),
                     page_size=CFG.page_size)
        sq.logit_divergence(SPEC, _params(), prompts=[[3, 5, 7]],
                            page_size=CFG.page_size)
        assert int8_engine.unexpected_compiles == before
        assert int8_engine.healthz()["ok"]
