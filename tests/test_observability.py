"""Observability subsystem tests: metrics registry, exposition format,
event sink, HTTP endpoint, recompile sentinel, and the hapi/checkpoint
integration path."""
from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu.observability as obs
from paddle_tpu.observability import (
    Counter, EventSink, Gauge, Histogram, MetricsRegistry, MetricsServer,
    RecompileSentinel, get_registry, get_telemetry, log_buckets,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    # env must never leak enablement into (or out of) a test
    for var in ("PT_TELEMETRY", "PT_TELEMETRY_DIR", "PT_METRICS_PORT",
                "PT_RECOMPILE_THRESHOLD", "PT_PROCESS_INDEX",
                "PT_RUN_ID", "PADDLE_TRAINER_ID"):
        monkeypatch.delenv(var, raising=False)
    obs.reset()
    yield
    obs.reset()


# -- import hygiene ----------------------------------------------------------

def test_import_is_side_effect_free(tmp_path):
    """Tier-1 guard: importing the package must not start threads, touch
    the filesystem, or initialize a jax backend."""
    script = (
        "import threading, sys, os\n"
        "import paddle_tpu.observability\n"
        "assert threading.active_count() == 1, threading.enumerate()\n"
        "xb = sys.modules.get('jax._src.xla_bridge')\n"
        "assert xb is None or not xb._backends, 'jax backend initialized'\n"
        "assert os.listdir('.') == [], os.listdir('.')\n"
        "print('CLEAN')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("PT_TELEMETRY", None)
    out = subprocess.run([sys.executable, "-c", script], cwd=str(tmp_path),
                         env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "CLEAN" in out.stdout


# -- metrics registry --------------------------------------------------------

def test_counter_labels_and_conflicts():
    reg = MetricsRegistry()
    c = reg.counter("req_total", "requests", labelnames=("code",))
    c.inc(code="200")
    c.inc(2, code="500")
    assert c.value(code="200") == 1
    assert c.value(code="500") == 2
    # idempotent getter returns the same child-bearing metric
    assert reg.counter("req_total", labelnames=("code",)) is c
    with pytest.raises(ValueError):
        c.inc(-1, code="200")
    with pytest.raises(ValueError):
        reg.gauge("req_total")            # kind conflict
    with pytest.raises(ValueError):
        reg.counter("req_total", labelnames=("method",))  # label conflict


def test_gauge_set_inc_dec():
    reg = MetricsRegistry()
    g = reg.gauge("inflight", "in flight")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.value() == 4


def test_histogram_percentile_and_buckets():
    bks = log_buckets(1e-3, 10.0, 3)
    assert bks == sorted(bks) and bks[0] <= 1e-3 and bks[-1] >= 10.0
    reg = MetricsRegistry()
    h = reg.histogram("lat", "latency", buckets=[0.1, 1.0, 10.0])
    for v in [0.05] * 50 + [0.5] * 40 + [5.0] * 10:
        h.observe(v)
    p50 = h.percentile(0.50)
    p95 = h.percentile(0.95)
    assert p50 <= 0.1          # half the mass sits in the first bucket
    assert 1.0 < p95 <= 10.0   # rank 95 lands past the 90 below le=1.0
    assert h.percentile(0.999) <= 10.0


def test_registry_concurrent_increments():
    reg = MetricsRegistry()
    c = reg.counter("hits", "hits", labelnames=("t",))

    def work(tid):
        for _ in range(2000):
            c.inc(t=str(tid % 2))

    threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value(t="0") + c.value(t="1") == 8000


SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? (-?[0-9.e+-]+|\+Inf|NaN)$")


def _validate_prometheus(text):
    """Minimal exposition-format 0.0.4 checker."""
    typed = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert kind in ("counter", "gauge", "histogram"), line
            typed[name] = kind
        elif line.startswith("# HELP "):
            assert len(line.split(" ", 3)) == 4, line
        else:
            assert SAMPLE_RE.match(line), f"bad sample line: {line!r}"
    return typed


def test_prometheus_text_is_valid_exposition():
    reg = MetricsRegistry()
    reg.counter("a_total", "with \\ and \n escapes", ("x",)).inc(x='q"v')
    reg.gauge("b", "gauge").set(3.5)
    h = reg.histogram("c_seconds", "hist", buckets=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(50)
    text = reg.prometheus_text()
    typed = _validate_prometheus(text)
    assert typed == {"a_total": "counter", "b": "gauge",
                     "c_seconds": "histogram"}
    # histogram contract: cumulative buckets, +Inf bucket == _count
    counts = [int(float(m.group(1))) for m in re.finditer(
        r'c_seconds_bucket\{le="[^"]+"\} ([0-9.]+)', text)]
    assert counts == sorted(counts), "buckets must be cumulative"
    inf = re.search(r'c_seconds_bucket\{le="\+Inf"\} ([0-9.]+)', text)
    cnt = re.search(r"c_seconds_count ([0-9.]+)", text)
    assert inf and cnt and float(inf.group(1)) == float(cnt.group(1)) == 3


def test_snapshot_json_round_trips():
    reg = MetricsRegistry()
    reg.counter("n_total", "n", ("k",)).inc(5, k="a")
    snap = json.loads(reg.snapshot_json())
    assert snap["n_total"]["kind"] == "counter"
    assert snap["n_total"]["series"]['k=a'] == 5


# -- event sink --------------------------------------------------------------

def test_event_sink_writes_and_rotates(tmp_path):
    sink = EventSink(str(tmp_path), max_bytes=256)
    for i in range(30):
        sink.emit("step", idx=i, pad="x" * 32)
    sink.close()
    main, rotated = sink.path, sink.path + ".1"
    assert os.path.exists(main) and os.path.exists(rotated)
    for line in open(main):
        rec = json.loads(line)
        assert rec["event"] == "step" and "ts" in rec and "pid" in rec
        # ISO-8601 UTC timestamp
        assert re.match(r"\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d+", rec["ts"])
    assert sink.dropped == 0


def test_event_sink_never_raises_on_io_error(tmp_path):
    sink = EventSink(str(tmp_path))
    sink.emit("warm")            # opens the file
    sink._fh.close()             # force the next write to fail
    sink.emit("after-close")     # must not raise
    assert sink.dropped >= 1


# -- HTTP endpoint -----------------------------------------------------------

def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as r:
            return r.status, r.headers.get("Content-Type"), r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Content-Type"), e.read().decode()


def test_metrics_server_serves_and_stops():
    reg = MetricsRegistry()
    reg.counter("pings_total", "pings").inc(7)
    health = {"ok": True, "steps": 1}
    srv = MetricsServer(reg, health_cb=lambda: health, port=0)
    srv.start()
    try:
        code, ctype, body = _get(srv.port, "/metrics")
        assert code == 200 and ctype.startswith("text/plain")
        assert "version=0.0.4" in ctype
        assert "pings_total 7" in body
        _validate_prometheus(body)

        code, ctype, body = _get(srv.port, "/healthz")
        assert code == 200 and json.loads(body)["steps"] == 1

        health["ok"] = False
        code, _, _ = _get(srv.port, "/healthz")
        assert code == 503

        code, _, _ = _get(srv.port, "/nope")
        assert code == 404
    finally:
        srv.stop()
    with pytest.raises(Exception):
        _get(srv.port, "/metrics")


# -- recompile sentinel ------------------------------------------------------

def test_sentinel_requires_distinct_signatures():
    s = RecompileSentinel(threshold=3)
    for _ in range(10):                      # same signature: cache thrash
        assert s.observe("f", "(f32[2])") is None   # is not churn
    assert not s.tripped()
    trip = None
    for i in range(4):
        trip = s.observe("g", f"(f32[{i}])") or trip
    assert trip and trip["callable"] == "g"
    assert trip["compiles"] >= 3 and trip["distinct_signatures"] >= 3
    assert set(s.tripped()) == {"g"}
    # reported once, not every compile after the trip
    assert s.observe("g", "(f32[99])") is None


def test_sentinel_trips_on_real_shape_churn():
    """Acceptance: a jitted loop fed changing shapes trips the sentinel
    and names the offending callable; a stable-shape loop does not."""
    import jax
    import jax.numpy as jnp

    tel = get_telemetry().enable(compile_watch=True)

    @jax.jit
    def stable_fn(a):
        return (a * 2.0).sum()

    for _ in range(8):
        stable_fn(jnp.ones((4,), jnp.float32)).block_until_ready()
    assert "stable_fn" not in tel.sentinel.tripped()
    assert tel.sentinel.compile_counts().get("stable_fn", 0) <= 1

    @jax.jit
    def churn_fn(a):
        return (a * 2.0).sum()

    for n in range(2, 9):                    # 7 distinct shapes
        churn_fn(jnp.ones((n,), jnp.float32)).block_until_ready()
    assert "churn_fn" in tel.sentinel.tripped()
    counts = tel.sentinel.compile_counts()
    assert counts["churn_fn"] >= 5
    snap = tel.snapshot()
    assert "churn_fn" in snap["recompile_storms"]
    assert snap["compiles"] >= counts["churn_fn"]


def test_compile_watcher_restores_jax_config():
    import jax
    prev = jax.config.jax_log_compiles
    tel = get_telemetry().enable(compile_watch=True)
    assert jax.config.jax_log_compiles is True
    tel.disable()
    assert jax.config.jax_log_compiles == prev


# -- telemetry hub -----------------------------------------------------------

def test_disabled_hub_is_inert(tmp_path):
    tel = get_telemetry()
    assert not tel.enabled
    assert tel.step_start() is None
    tel.step_end(None)
    tel.data_wait(0.1)
    tel.collective_op("all_reduce", 1024)
    tel.record_checkpoint_save(0.1, step=1)
    tel.heartbeat()
    assert tel.snapshot()["enabled"] is False
    assert tel.snapshot()["steps"] == 0
    assert get_registry().snapshot() == {}
    assert os.listdir(str(tmp_path)) == []


def test_step_timing_and_percentiles():
    tel = get_telemetry().enable(compile_watch=False)
    for ms in (1, 2, 3, 4, 100):
        tel.observe_step(ms / 1e3, mode="train", batch_size=32)
    snap = tel.snapshot()
    assert snap["steps"] == 5
    assert 1 <= snap["step_ms_p50"] <= 4
    assert snap["step_ms_p95"] >= 4
    text = tel.registry.prometheus_text()
    # const identity labels ride along -> match by label subset
    assert re.search(r'pt_steps_total\{[^}]*mode="train"[^}]*\} 5\b',
                     text)
    assert "pt_step_time_seconds_bucket" in text


def test_healthz_lease_expiry():
    tel = get_telemetry().enable(compile_watch=False)
    tel.heartbeat(ok=True, lease_ttl=30.0)
    hz = tel.healthz()
    assert hz["ok"] is True and hz["elastic"]["lease_ok"] is True

    tel.heartbeat(ok=True, lease_ttl=0.01)
    time.sleep(0.05)
    hz = tel.healthz()
    assert hz["ok"] is False
    assert hz["elastic"]["lease_ok"] is False
    assert hz["elastic"]["last_heartbeat_age_sec"] > 0.01


def test_healthz_without_elastic_is_healthy():
    tel = get_telemetry().enable(compile_watch=False)
    hz = tel.healthz()
    assert hz["ok"] is True and hz["elastic"] is None


def test_env_auto_enable(monkeypatch, tmp_path):
    monkeypatch.setenv("PT_TELEMETRY", "1")
    monkeypatch.setenv("PT_TELEMETRY_DIR", str(tmp_path))
    tel = get_telemetry()   # first call after reset: consults the env
    assert tel.enabled and tel.sink is not None
    assert tel.sink.path.startswith(str(tmp_path))


def test_checkpoint_counters():
    tel = get_telemetry().enable(compile_watch=False)
    tel.record_checkpoint_save(0.5, step=10, mode="sync", ok=True)
    tel.record_checkpoint_save(0.1, step=11, mode="async", ok=False)
    tel.record_checkpoint_restore(0.2, step=10, ok=True)
    tel.record_checkpoint_gc(3)
    text = tel.registry.prometheus_text()

    def sample(name, labels, value):
        return re.search(rf'{name}\{{[^}}]*{labels}[^}}]*\}} {value}\b',
                         text)

    assert sample("pt_checkpoint_ops_total", 'op="save",status="ok"', 1)
    assert sample("pt_checkpoint_ops_total",
                  'op="save",status="async_error"', 1)
    assert sample("pt_checkpoint_ops_total", 'op="restore",status="ok"',
                  1)
    assert sample("pt_checkpoint_gc_deleted_total", "", 3)
    assert tel.healthz()["last_checkpoint_step"] == 10


def test_collective_time_histogram_eager():
    """Satellite: an eagerly dispatched collective records one
    pt_collective_time_seconds{op=...} observation, timed at the host
    boundary."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.tensor import Tensor

    tel = get_telemetry().enable(compile_watch=False)
    import jax
    n = jax.device_count()  # rank-major eager layout
    out = dist.all_reduce(Tensor(np.ones((n, 4), np.float32)))
    assert out is not None
    text = tel.registry.prometheus_text()
    assert re.search(r'pt_collective_time_seconds_count'
                     r'\{[^}]*op="all_reduce"[^}]*\} 1\b', text)
    assert re.search(r'pt_collective_time_seconds_sum'
                     r'\{[^}]*op="all_reduce"[^}]*\} [0-9.]', text)


def test_collective_time_is_tracer_safe():
    """The timing wrapper must record NOTHING while tracing — a traced
    perf_counter would time tracing, not execution, and a host
    callback inside jit would be a TPU008-class hazard."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.distributed import collective as coll

    tel = get_telemetry().enable(compile_watch=False)

    @coll._timed("probe")
    def inner(a):
        return a * 2.0

    @jax.jit
    def traced(a):
        return inner(a)

    traced(jnp.ones((4,), jnp.float32)).block_until_ready()
    text = tel.registry.prometheus_text()
    assert 'op="probe"' not in text  # traced call: not timed

    inner(jnp.ones((4,), jnp.float32))  # eager call: timed
    text = tel.registry.prometheus_text()
    assert re.search(r'pt_collective_time_seconds_count'
                     r'\{[^}]*op="probe"[^}]*\} 1\b', text)


def test_collective_time_disabled_hub_records_nothing():
    import paddle_tpu.distributed as dist
    from paddle_tpu.tensor import Tensor

    import jax
    n = jax.device_count()
    dist.all_reduce(Tensor(np.ones((n, 4), np.float32)))
    assert get_registry().snapshot() == {}


def test_lint_clean_over_observability_package():
    """Tier-1 guard: the new package holds itself to the linter it ships
    next to — zero violations, no baseline allowance."""
    from paddle_tpu.tools.lint import run_paths
    pkg = os.path.join(REPO, "paddle_tpu", "observability")
    violations, errors = run_paths([pkg])
    assert not errors, errors
    assert violations == [], [f"{v.path}:{v.line} {v.rule}"
                              for v in violations]


# -- integration -------------------------------------------------------------

def test_fit_and_checkpoint_end_to_end(tmp_path):
    """Short hapi fit with telemetry on: JSONL stream, /metrics scrape
    with step-time histogram + compile counter + checkpoint-save
    duration, /healthz carrying the last checkpoint step."""
    import paddle_tpu as pt
    from paddle_tpu.distributed.checkpoint_manager import CheckpointManager
    from paddle_tpu.vision.datasets import FakeData

    tel = get_telemetry().enable(jsonl_dir=str(tmp_path / "ev"), http_port=0,
                                 compile_watch=True)

    net = pt.nn.Sequential(pt.nn.Flatten(), pt.nn.Linear(3 * 8 * 8, 4))
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.SGD(
                      learning_rate=0.01, parameters=net.parameters()),
                  loss=pt.nn.CrossEntropyLoss())
    data = FakeData(size=64, image_shape=(3, 8, 8), num_classes=4)
    model.fit(data, epochs=1, batch_size=16, verbose=0)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), durable=False)
    mgr.save(3, {"w": np.ones((4, 4), np.float32)})

    code, _, text = _get(tel.server.port, "/metrics")
    assert code == 200
    _validate_prometheus(text)
    assert "pt_step_time_seconds_bucket" in text
    assert "pt_compiles_total" in text
    assert re.search(r"pt_checkpoint_save_seconds_count(\{[^}]*\})? 1\b",
                     text)
    assert "pt_data_wait_seconds" in text

    code, _, body = _get(tel.server.port, "/healthz")
    hz = json.loads(body)
    assert code == 200 and hz["ok"] is True
    assert hz["steps"] >= 4
    assert hz["last_checkpoint_step"] == 3

    events = [json.loads(l) for l in open(tel.sink.path)]
    kinds = {e["event"] for e in events}
    assert "step" in kinds and "checkpoint_save" in kinds
    steps = [e for e in events if e["event"] == "step"]
    assert all(e["duration_sec"] > 0 for e in steps)

    snap = tel.snapshot()
    assert snap["steps"] >= 4 and snap["compiles"] >= 1


def test_fit_with_telemetry_disabled_emits_nothing(tmp_path):
    import paddle_tpu as pt
    from paddle_tpu.vision.datasets import FakeData

    net = pt.nn.Sequential(pt.nn.Flatten(), pt.nn.Linear(3 * 8 * 8, 4))
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.SGD(
                      learning_rate=0.01, parameters=net.parameters()),
                  loss=pt.nn.CrossEntropyLoss())
    model.fit(FakeData(size=32, image_shape=(3, 8, 8), num_classes=4),
              epochs=1, batch_size=16, verbose=0)

    assert get_telemetry().snapshot()["steps"] == 0
    assert get_registry().snapshot() == {}
    assert os.listdir(str(tmp_path)) == []
