"""Distributed core tests: collectives (eager rank-major + SPMD modes),
topology, fleet init, TP layers vs dense reference, recompute.

Mirrors the reference's collective test strategy
(``test/collective/collective_allreduce_api.py`` family checks results
against numpy; ``hybrid_parallel_mp_model.py`` checks TP == replicated) on
the virtual 8-device CPU mesh (conftest).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.tensor import Tensor
from paddle_tpu.distributed._jax_compat import shard_map as _shard_map, use_mesh as _use_mesh


N = 8  # virtual device count (conftest)


@pytest.fixture(autouse=True)
def _reset_dist_state():
    yield
    dist.set_mesh(None)
    dist.destroy_process_group()


# ---------------------------------------------------------------------------
# eager collectives (rank-major layout)
# ---------------------------------------------------------------------------

def test_all_reduce_sum_eager():
    x = np.arange(N * 3, dtype=np.float32).reshape(N, 3)
    out = dist.all_reduce(Tensor(x.copy()))
    expect = np.tile(x.sum(0, keepdims=True), (N, 1))
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-6)


def test_all_reduce_max_min_eager():
    x = np.random.RandomState(0).rand(N, 4).astype(np.float32)
    out = dist.all_reduce(Tensor(x.copy()), op=dist.ReduceOp.MAX)
    np.testing.assert_allclose(out.numpy(),
                               np.tile(x.max(0), (N, 1)), rtol=1e-6)
    out = dist.all_reduce(Tensor(x.copy()), op=dist.ReduceOp.MIN)
    np.testing.assert_allclose(out.numpy(),
                               np.tile(x.min(0), (N, 1)), rtol=1e-6)


def test_all_gather_eager():
    x = np.random.RandomState(1).rand(N, 2).astype(np.float32)
    got = dist.all_gather(Tensor(x.copy()))
    np.testing.assert_allclose(got.numpy(), x, rtol=1e-6)
    lst = []
    dist.all_gather(lst, Tensor(x.copy()))
    assert len(lst) == N
    for i in range(N):
        np.testing.assert_allclose(lst[i].numpy(), x[i], rtol=1e-6)


def test_broadcast_eager():
    x = np.random.RandomState(2).rand(N, 5).astype(np.float32)
    out = dist.broadcast(Tensor(x.copy()), src=3)
    np.testing.assert_allclose(out.numpy(), np.tile(x[3], (N, 1)), rtol=1e-6)


def test_reduce_eager():
    x = np.random.RandomState(3).rand(N, 2).astype(np.float32)
    out = dist.reduce(Tensor(x.copy()), dst=2)
    expect = x.copy()
    expect[2] = x.sum(0)
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-5)


def test_scatter_eager():
    parts = [np.full((2,), i, np.float32) for i in range(N)]
    out = dist.scatter(Tensor(np.zeros((N, 2), np.float32)),
                       [Tensor(p) for p in parts], src=0)
    np.testing.assert_allclose(out.numpy(), np.stack(parts), rtol=1e-6)


def test_alltoall_eager():
    x = np.arange(N * N * 2, dtype=np.float32).reshape(N, N, 2)
    out = dist.alltoall(Tensor(x.copy()))
    np.testing.assert_allclose(out.numpy(), x.transpose(1, 0, 2), rtol=1e-6)


def test_alltoall_single_eager():
    x = np.arange(N * N * 2, dtype=np.float32).reshape(N, N * 2)
    out = dist.alltoall_single(Tensor(x.copy()))
    expect = x.reshape(N, N, 2).transpose(1, 0, 2).reshape(N, N * 2)
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-6)


def test_reduce_scatter_eager():
    x = np.random.RandomState(4).rand(N, N * 2).astype(np.float32)
    out = dist.reduce_scatter(Tensor(x.copy()))
    # rank i owns chunk i of the sum
    summed = x.reshape(N, N, 2).sum(0)
    np.testing.assert_allclose(out.numpy(), summed.reshape(N, 2)[:, None, :]
                               .reshape(N, 2), rtol=1e-5)


def test_barrier_and_env():
    dist.barrier()
    assert dist.get_rank() == 0
    assert dist.get_world_size() >= 1


def test_send_recv_eager_mailbox():
    t = Tensor(np.ones((3,), np.float32) * 7)
    dist.send(t, dst=0)
    out = dist.recv(Tensor(np.zeros((3,), np.float32)), src=0)
    np.testing.assert_allclose(out.numpy(), 7 * np.ones(3), rtol=0)


# ---------------------------------------------------------------------------
# SPMD-mode collectives inside shard_map
# ---------------------------------------------------------------------------

def test_all_reduce_spmd_inside_shard_map():
    mesh = dist.init_mesh({"dp": N})
    g = dist.new_group(list(range(N)), axis_name="dp")
    x = np.arange(N * 2, dtype=np.float32).reshape(N, 2)

    def body(xs):
        t = dist.all_reduce(Tensor(xs), group=g)
        return t._data

    out = _shard_map(body, mesh=mesh, in_specs=P("dp"),
                        out_specs=P("dp"))(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out),
                               np.tile(x.sum(0, keepdims=True), (N, 1)),
                               rtol=1e-6)


def test_reduce_scatter_spmd():
    mesh = dist.init_mesh({"dp": N})
    g = dist.new_group(list(range(N)), axis_name="dp")
    x = np.random.RandomState(5).rand(N * N * 2).astype(np.float32)

    def body(xs):
        return dist.reduce_scatter(Tensor(xs), group=g)._data

    out = _shard_map(body, mesh=mesh, in_specs=P("dp"),
                        out_specs=P("dp"), check_vma=False)(jnp.asarray(x))
    # per-rank input chunk [N*2]; psum_scatter: rank i gets the sum over
    # ranks of subchunk i
    expect = x.reshape(N, N, -1).sum(0).reshape(-1)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5)


# ---------------------------------------------------------------------------
# topology + fleet
# ---------------------------------------------------------------------------

def test_communicate_topology_rank_math():
    topo = dist.CommunicateTopology(
        ("data", "pipe", "sharding", "sep", "model"), (2, 2, 1, 1, 2))
    assert topo.world_size() == 8
    assert topo.get_rank(data=1, pipe=0, sharding=0, sep=0, model=1) == 5
    assert topo.get_coord(5) == (1, 0, 0, 0, 1)
    comm = topo.get_comm_list("model")
    assert [0, 1] in comm and [6, 7] in comm
    assert topo.get_axis_list("data", 0) == [0, 1, 2, 3]


def test_fleet_init_hybrid():
    import paddle_tpu.distributed.fleet as fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "pp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_pipe_parallel_world_size() == 2
    assert hcg.nranks == 8
    mesh = dist.get_mesh()
    assert mesh.shape["dp"] == 2 and mesh.shape["mp"] == 2 \
        and mesh.shape["pp"] == 2
    # rank 0 groups
    assert hcg.get_model_parallel_group().nranks == 2
    assert hcg.get_data_parallel_group().nranks == 2


def test_distributed_strategy_validation():
    s = dist.fleet.DistributedStrategy()
    with pytest.raises(ValueError):
        s.amp_configs = {"bogus_key": 1}
    s.amp_configs = {"init_loss_scaling": 1024.0}
    assert s.amp_configs["init_loss_scaling"] == 1024.0


# ---------------------------------------------------------------------------
# TP layers: manual SPMD mode == dense reference
# ---------------------------------------------------------------------------

def _mp_mesh(n=4):
    return dist.init_mesh({"mp": n})


def test_column_parallel_linear_manual_vs_dense():
    from paddle_tpu.distributed.fleet.meta_parallel import \
        ColumnParallelLinear
    mesh = _mp_mesh(4)
    layer = ColumnParallelLinear(16, 32, gather_output=True)
    x = np.random.RandomState(0).rand(4, 16).astype(np.float32)
    w = np.asarray(layer.weight._data)
    b = np.asarray(layer.bias._data)
    dense = x @ w + b

    def body(xs, ws, bs):
        from paddle_tpu.jit.api import functional_call
        out, _ = functional_call(layer, {"weight": ws, "bias": bs}, {},
                                 (Tensor(xs),))
        return out._data

    out = _shard_map(body, mesh=mesh,
                        in_specs=(P(), P(None, "mp"), P("mp")),
                        out_specs=P(), check_vma=False)(
        jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(out), dense, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_row_parallel_linear_manual_vs_dense():
    from paddle_tpu.distributed.fleet.meta_parallel import RowParallelLinear
    mesh = _mp_mesh(4)
    layer = RowParallelLinear(16, 12, input_is_parallel=False)
    x = np.random.RandomState(1).rand(4, 16).astype(np.float32)
    w = np.asarray(layer.weight._data)
    b = np.asarray(layer.bias._data)
    dense = x @ w + b

    def body(xs, ws, bs):
        from paddle_tpu.jit.api import functional_call
        out, _ = functional_call(layer, {"weight": ws, "bias": bs}, {},
                                 (Tensor(xs),))
        return out._data

    out = _shard_map(body, mesh=mesh,
                        in_specs=(P(), P("mp", None), P()),
                        out_specs=P(), check_vma=False)(
        jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(out), dense, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_vocab_parallel_embedding_manual_vs_dense():
    from paddle_tpu.distributed.fleet.meta_parallel import \
        VocabParallelEmbedding
    mesh = _mp_mesh(4)
    layer = VocabParallelEmbedding(32, 8)
    idx = np.random.RandomState(2).randint(0, 32, (5, 3)).astype(np.int32)
    w = np.asarray(layer.weight._data)
    dense = w[idx]

    def body(ids, ws):
        from paddle_tpu.jit.api import functional_call
        out, _ = functional_call(layer, {"weight": ws}, {}, (Tensor(ids),))
        return out._data

    out = _shard_map(body, mesh=mesh, in_specs=(P(), P("mp", None)),
                        out_specs=P(), check_vma=False)(
        jnp.asarray(idx), jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(out), dense, rtol=1e-6)


@pytest.mark.slow
def test_parallel_cross_entropy_manual_vs_dense():
    from paddle_tpu.distributed.fleet.meta_parallel import \
        ParallelCrossEntropy
    mesh = _mp_mesh(4)
    ce = ParallelCrossEntropy()
    B, V = 6, 16
    logits = np.random.RandomState(3).rand(B, V).astype(np.float32) * 4
    y = np.random.RandomState(4).randint(0, V, (B,)).astype(np.int32)
    # dense reference
    m = logits.max(-1, keepdims=True)
    lse = np.log(np.exp(logits - m).sum(-1)) + m[:, 0]
    dense = lse - logits[np.arange(B), y]

    def body(lg, yy):
        return ce(Tensor(lg), Tensor(yy))._data

    out = _shard_map(body, mesh=mesh, in_specs=(P(None, "mp"), P()),
                        out_specs=P(), check_vma=False)(
        jnp.asarray(logits), jnp.asarray(y))
    np.testing.assert_allclose(np.asarray(out)[:, 0], dense, rtol=1e-5,
                               atol=1e-5)


def test_column_parallel_gspmd_jit_matches_dense():
    """GSPMD mode: full logical weights + specs under plain jit."""
    from paddle_tpu.distributed.fleet.meta_parallel import (
        ColumnParallelLinear, RowParallelLinear)
    mesh = _mp_mesh(4)
    col = ColumnParallelLinear(8, 16, gather_output=False)
    row = RowParallelLinear(16, 8, input_is_parallel=True)
    x = np.random.RandomState(5).rand(4, 8).astype(np.float32)
    dense = (x @ np.asarray(col.weight._data) +
             np.asarray(col.bias._data)) @ np.asarray(row.weight._data) \
        + np.asarray(row.bias._data)

    from paddle_tpu.jit.api import functional_call

    def fwd(params, xs):
        h, _ = functional_call(col, {"weight": params["cw"],
                                     "bias": params["cb"]}, {},
                               (Tensor(xs),))
        out, _ = functional_call(row, {"weight": params["rw"],
                                       "bias": params["rb"]}, {}, (h,))
        return out._data

    params = {"cw": col.weight._data, "cb": col.bias._data,
              "rw": row.weight._data, "rb": row.bias._data}
    shardings = {"cw": NamedSharding(mesh, P(None, "mp")),
                 "cb": NamedSharding(mesh, P("mp")),
                 "rw": NamedSharding(mesh, P("mp", None)),
                 "rb": NamedSharding(mesh, P())}
    params = jax.device_put(params, shardings)
    with _use_mesh(mesh):
        out = jax.jit(fwd)(params, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), dense, rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# recompute, DataParallel, sharding api, auto_parallel api
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_recompute_grad_matches_plain():
    from paddle_tpu.distributed.fleet.utils import recompute
    from paddle_tpu import autograd
    net = pt.nn.Sequential(pt.nn.Linear(8, 8), pt.nn.ReLU(),
                           pt.nn.Linear(8, 4))
    x = np.random.RandomState(6).rand(3, 8).astype(np.float32)

    from paddle_tpu.jit.api import functional_call
    params = {k: p._data for k, p in net.named_parameters()}

    def loss_plain(p, xs):
        out, _ = functional_call(net, p, {}, (Tensor(xs),))
        return jnp.sum(out._data ** 2)

    def loss_rc(p, xs):
        def inner(xs_t):
            out, _ = functional_call(net, p, {}, (xs_t,))
            return out
        out = recompute(inner, Tensor(xs))
        return jnp.sum(out._data ** 2)

    g1 = jax.grad(loss_plain)(params, jnp.asarray(x))
    g2 = jax.grad(loss_rc)(params, jnp.asarray(x))
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                   rtol=1e-5, atol=1e-6)


def test_recompute_policy_matches_plain():
    """policy= selects a jax.checkpoint_policies saveable set without
    changing the math (ref recompute granularity core_attn/full)."""
    from paddle_tpu.distributed.fleet.utils import recompute
    from paddle_tpu.jit.api import functional_call
    net = pt.nn.Sequential(pt.nn.Linear(8, 8), pt.nn.GELU(),
                           pt.nn.Linear(8, 4))
    x = np.random.RandomState(6).rand(3, 8).astype(np.float32)
    params = {k: p._data for k, p in net.named_parameters()}

    def loss(p, xs, policy):
        def inner(xs_t):
            out, _ = functional_call(net, p, {}, (xs_t,))
            return out
        if policy == "plain":
            return jnp.sum(inner(Tensor(xs))._data ** 2)
        out = recompute(inner, Tensor(xs), policy=policy)
        return jnp.sum(out._data ** 2)

    ref = jax.grad(loss)(params, jnp.asarray(x), "plain")
    for policy in ("dots", "dots_with_no_batch_dims", None):
        g = jax.grad(loss)(params, jnp.asarray(x), policy)
        for k in ref:
            np.testing.assert_allclose(np.asarray(ref[k]), np.asarray(g[k]),
                                       rtol=1e-5, atol=1e-6)
    import pytest as _pytest
    with _pytest.raises(AttributeError):
        jax.grad(loss)(params, jnp.asarray(x), "not_a_policy")


def test_data_parallel_wrapper_shards_and_trains():
    dist.init_mesh({"dp": N})
    net = pt.nn.Linear(4, 2)
    dp = dist.DataParallel(net)
    x = Tensor(np.random.RandomState(7).rand(16, 4).astype(np.float32))
    out = dp(x)
    assert out.shape == [16, 2]
    loss = (out * out).sum()
    loss.backward()
    assert net.weight.grad is not None


def test_process_mesh_shard_tensor():
    pm = dist.ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])
    assert pm.shape == [2, 4]
    t = dist.shard_tensor(np.random.rand(8, 4).astype(np.float32), pm,
                          [dist.Shard(0), dist.Replicate()])
    assert tuple(t._spec) == ("x", None)
    t2 = dist.reshard(t, pm, [dist.Replicate(), dist.Replicate()])
    np.testing.assert_allclose(t.numpy(), t2.numpy(), rtol=0)


def test_group_sharded_parallel_annotates():
    dist.init_mesh({"sharding": 8})
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    net = pt.nn.Linear(64, 64)
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=net.parameters())
    m, o, s = group_sharded_parallel(net, opt, level="p_g_os")
    assert net.weight._spec is not None
    assert "sharding" in tuple(net.weight._spec)


def test_fleet_strategy_toggles_are_applied():
    """VERDICT weak #6: amp/recompute/sharding strategy toggles must
    change behavior through the fleet facade, not sit inert."""
    import paddle_tpu.distributed.fleet as fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "sharding_degree": 2}
    strategy.amp = True
    strategy.amp_configs = {"use_bf16": True}
    strategy.sharding = True
    strategy.sharding_configs = {"stage": 2}
    fleet.init(is_collective=True, strategy=strategy)

    pt.seed(0)
    model = pt.nn.Linear(16, 16)
    model = fleet.distributed_model(model)
    # amp O2: params cast to bf16 by the facade
    p = next(iter(model.parameters()))
    assert str(p.dtype) in ("paddle_tpu.bfloat16", "bfloat16") or \
        "bfloat16" in str(p._data.dtype)

    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    opt = fleet.distributed_optimizer(opt)
    # sharding stage 2 -> ZeRO level on the inner optimizer
    assert getattr(opt._inner_opt, "_group_sharded_level", None) == "os_g"


def test_stream_namespace_collectives():
    """paddle.distributed.communication.stream variants (ref
    ``distributed/communication/stream/``): same ops, stream knobs
    accepted — XLA's one logical stream subsumes use_calc_stream."""
    import paddle_tpu as paddle
    assert paddle.distributed.stream is dist.communication.stream
    x = np.arange(N * 3, dtype=np.float32).reshape(N, 3)
    out = dist.stream.all_reduce(Tensor(x.copy()), use_calc_stream=True)
    np.testing.assert_allclose(
        out.numpy(), np.tile(x.sum(0, keepdims=True), (N, 1)), rtol=1e-6)
    out2 = dist.stream.broadcast(Tensor(x.copy()), src=1)
    np.testing.assert_allclose(out2.numpy(), np.tile(x[1:2], (N, 1)),
                               rtol=1e-6)


def test_gather_eager_and_stream_guard():
    x = np.random.RandomState(6).rand(N, 3).astype(np.float32)
    out = []
    dist.gather(Tensor(x.copy()), out, dst=0)
    assert len(out) == N
    np.testing.assert_allclose(out[2].numpy(), x[2], rtol=1e-6)
    with pytest.raises(RuntimeError, match="use_calc_stream"):
        dist.stream.all_reduce(Tensor(x.copy()), sync_op=False,
                               use_calc_stream=True)
