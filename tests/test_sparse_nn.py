"""sparse.nn: conv/pool/norm/activation over BCOO vs dense masked oracles
(ref: ``python/paddle/sparse/nn/layer/conv.py:239,509``)."""
import numpy as np
import pytest

import jax.numpy as jnp
from jax import lax

import paddle_tpu as pt
import paddle_tpu.sparse as sp
import paddle_tpu.sparse.nn as snn
from paddle_tpu import Tensor

RNG = np.random.RandomState(0)


def _rand_coo(nnz=20, shape=(2, 6, 6, 6, 3)):
    nd = len(shape) - 2
    idx = np.stack([RNG.randint(0, shape[0], nnz)] +
                   [RNG.randint(0, shape[1 + a], nnz) for a in range(nd)])
    idx = np.unique(idx.T, axis=0).T
    vals = RNG.randn(idx.shape[1], shape[-1]).astype("float32")
    x = sp.sparse_coo_tensor(pt.to_tensor(idx), pt.to_tensor(vals),
                             shape=list(shape))
    return x, idx, vals


def _dense(idx, vals, shape):
    d = np.zeros(shape, "float32")
    d[tuple(idx)] = vals
    return d


def _conv_oracle(dense, w, b, stride, pad, nd):
    dn = lax.conv_dimension_numbers(
        (1,) * (nd + 2), (1,) * (nd + 2),
        ("NDHWC" if nd == 3 else "NHWC", "DHWIO" if nd == 3 else "HWIO",
         "NDHWC" if nd == 3 else "NHWC"))
    out = lax.conv_general_dilated(
        jnp.asarray(dense), jnp.asarray(w), (stride,) * nd,
        [(pad, pad)] * nd, dimension_numbers=dn)
    return np.asarray(out) + (b if b is not None else 0)


def test_subm_conv3d_matches_masked_dense():
    x, idx, vals = _rand_coo()
    conv = snn.SubmConv3D(3, 4, 3, padding=1)
    out = conv(x)
    assert out.nnz == x.nnz and out.shape == [2, 6, 6, 6, 4]
    oracle = _conv_oracle(_dense(idx, vals, (2, 6, 6, 6, 3)),
                          np.asarray(conv.weight._data),
                          np.asarray(conv.bias._data), 1, 1, 3)
    got = out.to_dense().numpy()
    np.testing.assert_allclose(got[tuple(idx)], oracle[tuple(idx)],
                               atol=1e-4)
    # submanifold rule: zero everywhere else, even where the oracle isn't
    mask = np.zeros((2, 6, 6, 6), bool)
    mask[tuple(idx)] = True
    assert np.allclose(got[~mask], 0)


@pytest.mark.slow
def test_conv3d_pattern_and_values():
    x, idx, vals = _rand_coo()
    conv = snn.Conv3D(3, 4, 3, stride=2, padding=1)
    out = conv(x)
    assert out.shape == [2, 3, 3, 3, 4]
    oracle = _conv_oracle(_dense(idx, vals, (2, 6, 6, 6, 3)),
                          np.asarray(conv.weight._data),
                          np.asarray(conv.bias._data), 2, 1, 3)
    oi = np.asarray(out._bcoo.indices)
    np.testing.assert_allclose(out.to_dense().numpy()[tuple(oi.T)],
                               oracle[tuple(oi.T)], atol=1e-4)
    # rulebook completeness: every site whose window touches an active
    # input must be in the pattern
    active = set(map(tuple, oi))
    for (b, d, h, w) in map(tuple, idx.T[:, :4]):
        od, oh, ow = (d + 1) // 2, (h + 1) // 2, (w + 1) // 2
        if od < 3 and oh < 3 and ow < 3:
            assert (b, od, oh, ow) in active


def test_subm_conv2d():
    x, idx, vals = _rand_coo(15, (2, 8, 8, 3))
    conv = snn.SubmConv2D(3, 5, 3, padding=1)
    out = conv(x)
    oracle = _conv_oracle(_dense(idx, vals, (2, 8, 8, 3)),
                          np.asarray(conv.weight._data),
                          np.asarray(conv.bias._data), 1, 1, 2)
    got = out.to_dense().numpy()
    np.testing.assert_allclose(got[tuple(idx)], oracle[tuple(idx)],
                               atol=1e-4)


@pytest.mark.slow
def test_sparse_conv_grad_fd():
    """FD check on one weight element through subm conv + relu."""
    x, idx, vals = _rand_coo(8, (1, 4, 4, 4, 2))
    conv = snn.SubmConv3D(2, 2, 3, padding=1)

    def loss_val():
        out = snn.functional.relu(conv(x))
        return float(pt.sum(out.values() * out.values()).numpy())

    out = snn.functional.relu(conv(x))
    loss = pt.sum(out.values() * out.values())
    loss.backward()
    g = np.asarray(conv.weight.grad._data)

    w = conv.weight
    eps = 1e-2
    base = np.asarray(w._data).copy()
    for pos in [(0, 0, 0, 0, 0), (1, 2, 1, 1, 1)]:
        pert = base.copy()
        pert[pos] += eps
        w._data = jnp.asarray(pert)
        up = loss_val()
        pert[pos] -= 2 * eps
        w._data = jnp.asarray(pert)
        dn = loss_val()
        w._data = jnp.asarray(base)
        fd = (up - dn) / (2 * eps)
        np.testing.assert_allclose(g[pos], fd, rtol=5e-2, atol=5e-2)


def test_sparse_batch_norm_stats():
    x, idx, vals = _rand_coo()
    bn = snn.BatchNorm(3)
    bn.train()
    out = bn(x)
    ov = out.values().numpy()
    # normalized over active values only
    np.testing.assert_allclose(ov.mean(0), 0, atol=1e-4)
    np.testing.assert_allclose(ov.var(0), 1, atol=1e-3)
    # eval mode uses running stats
    bn.eval()
    out2 = bn(x).values().numpy()
    assert not np.allclose(out2.mean(0), 0, atol=1e-6)


def test_sparse_activations_and_pool():
    x, idx, vals = _rand_coo()
    r = snn.ReLU()(x).values().numpy()
    np.testing.assert_allclose(r, np.maximum(vals, 0), atol=1e-6)
    l = snn.LeakyReLU(0.1)(x).values().numpy()
    np.testing.assert_allclose(l, np.where(vals > 0, vals, 0.1 * vals),
                               atol=1e-6)
    r6 = snn.functional.relu6(x).values().numpy()
    np.testing.assert_allclose(r6, np.clip(vals, 0, 6), atol=1e-6)
    mp = snn.MaxPool3D(2)(x)
    dense = _dense(idx, vals, (2, 6, 6, 6, 3))
    # dense max pool oracle at the active output sites; empty windows in
    # the sparse realization hold -inf -> only compare active sites
    oracle = np.asarray(lax.reduce_window(
        jnp.asarray(np.where(dense == 0, -np.inf, dense)), -jnp.inf,
        lax.max, (1, 2, 2, 2, 1), (1, 2, 2, 2, 1), "VALID"))
    oi = np.asarray(mp._bcoo.indices)
    got = mp.values().numpy()
    want = oracle[tuple(oi.T)]
    # windows whose max is an explicit active value
    np.testing.assert_allclose(got[np.isfinite(want)],
                               want[np.isfinite(want)], atol=1e-5)


@pytest.mark.slow
def test_sparse_softmax_csr():
    m = RNG.rand(5, 6)
    m[m < 0.5] = 0
    csr = sp.sparse_coo_tensor(
        pt.to_tensor(np.stack(np.nonzero(m))),
        pt.to_tensor(m[m != 0].astype("float32")),
        shape=[5, 6]).to_sparse_csr()
    out = snn.Softmax()(csr).to_dense().numpy()
    rows = (m != 0)
    for r in range(5):
        if rows[r].any():
            e = np.exp(m[r][rows[r]] - m[r][rows[r]].max())
            want = e / e.sum()
            np.testing.assert_allclose(out[r][rows[r]], want, atol=1e-5)
    with pytest.raises(ValueError):
        snn.functional.softmax(csr, axis=0)


def test_sparse_attention_wrapper():
    B, H, S, D = 1, 2, 4, 4
    q = pt.to_tensor(RNG.randn(B, H, S, D).astype("float32"))
    # full mask pattern as a batched CSR [B*H, S, S] (ref layout)
    crows = np.tile(np.arange(0, (S + 1) * S, S, dtype="int32"),
                    (B * H, 1))
    cols = np.tile(np.tile(np.arange(S, dtype="int32"), S), (B * H, 1))
    vals = np.ones((B * H, S * S), "float32")
    mask = sp.sparse_csr_tensor(crows, cols, vals, [B * H, S, S])
    out = snn.functional.attention(q, q, q, mask)
    # equals dense softmax attention with full pattern
    qn = q.numpy()
    s = np.einsum("bhqd,bhkd->bhqk", qn, qn) / np.sqrt(D)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", p, qn)
    np.testing.assert_allclose(out.numpy(), want, atol=1e-4)


def test_sync_batchnorm_convert():
    class Net(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.bn = snn.BatchNorm(3)

    net = Net()
    out = snn.SyncBatchNorm.convert_sync_batchnorm(net)
    assert isinstance(out.bn, snn.SyncBatchNorm)


@pytest.mark.slow
def test_softmax_coo_keeps_tape():
    """conv -> relu -> COO softmax -> backward must reach the conv
    weights (the severed-tape regression)."""
    x, idx, vals = _rand_coo(10, (1, 4, 4, 4, 2))
    conv = snn.SubmConv3D(2, 3, 3, padding=1)
    out = snn.functional.softmax(snn.functional.relu(conv(x)))
    assert isinstance(out, sp.SparseCooTensor)
    loss = pt.sum(out.values() * out.values())
    loss.backward()
    assert conv.weight.grad is not None
    assert np.isfinite(np.asarray(conv.weight.grad._data)).all()
    # channel softmax: each active site's channel vector sums to 1
    ov = out.values().numpy()
    np.testing.assert_allclose(ov.sum(-1), 1.0, atol=1e-5)
    # fully sparse COO: softmax over the last sparse dim, tape-linked
    vals1 = Tensor(RNG.randn(4).astype("float32"), stop_gradient=False)
    idx1 = pt.to_tensor(np.array([[0, 0, 1, 1], [0, 1, 0, 2]], "int64"))
    m = sp.sparse_coo_tensor(idx1, vals1, shape=[2, 3],
                             stop_gradient=False)
    sm = snn.functional.softmax(m)
    d1 = sm.to_dense().numpy()
    np.testing.assert_allclose(d1[0, :2].sum(), 1.0, atol=1e-5)
    pt.sum(sm.values()).backward()
    assert vals1.grad is not None


def test_sparse_coo_tensor_stop_gradient_contract():
    vals = Tensor(RNG.randn(3, 2).astype("float32"), stop_gradient=False)
    idx = pt.to_tensor(np.array([[0, 1, 2], [0, 1, 0]], "int64"))
    # default stop_gradient=True -> detached values
    t = sp.sparse_coo_tensor(idx, vals, shape=[3, 3, 2])
    assert t.values().stop_gradient
    # explicit stop_gradient=False keeps the link
    t2 = sp.sparse_coo_tensor(idx, vals, shape=[3, 3, 2],
                              stop_gradient=False)
    assert t2.values() is vals


@pytest.mark.slow
def test_sparse_pool_ceil_mode():
    x, idx, vals = _rand_coo(12, (1, 5, 5, 5, 2))
    out_floor = snn.MaxPool3D(2, stride=2)(x)
    out_ceil = snn.MaxPool3D(2, stride=2, ceil_mode=True)(x)
    assert out_floor.shape[1:4] == [2, 2, 2]
    assert out_ceil.shape[1:4] == [3, 3, 3]
    with pytest.raises(NotImplementedError):
        snn.MaxPool3D(2, return_mask=True)
