"""Test config: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's testing trick of a fake device backend
(`paddle/phi/backends/custom/fake_cpu_device.h`, custom_cpu plugin tests):
multi-chip sharding logic is validated without TPU hardware by forcing the
XLA CPU backend to expose 8 devices. MUST run before jax initializes.
"""
import os

# FORCE cpu: the environment bakes JAX_PLATFORMS=axon (TPU tunnel) and a
# sitecustomize registers that backend in every interpreter; unit tests must
# never ride the tunnel (single-client, slow, bf16 default matmul).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_COMPILATION_CACHE", "false")

import numpy as np  # noqa: E402
import pytest  # noqa: E402
import jax  # noqa: E402

# The environment's sitecustomize registers the TPU-tunnel backend and then
# sets jax_platforms="axon,cpu" via config (which overrides the env var!).
# Re-override to cpu-only BEFORE any backend initializes.
jax.config.update("jax_platforms", "cpu")

# numeric tests compare against float64 numpy: pin matmuls to true fp32
# (the default 'bf16 passes' precision is the perf configuration, not the
# numerics-test configuration)
jax.config.update("jax_default_matmul_precision", "float32")


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu
    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield
