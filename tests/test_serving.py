"""AOT serving engine: paged KV-cache, zero-compile request path,
continuous batching, weight swap, and the HTTP front end.

The load-bearing guarantees under test:

 - the page-pool allocator never double-books, never leaks, and refuses
   admission rather than OOM-ing mid-decode;
 - after engine warmup the request path performs ZERO XLA compiles
   (the sentinel that trips /healthz in production must stay at 0 for
   every in-ladder shape here);
 - a sequence decoded inside a continuous batch — with neighbours
   joining and leaving — produces BIT-IDENTICAL tokens to the same
   sequence decoded alone (row-independent decode math).
"""
import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.ops.paged_attention import (
    paged_attention, paged_attention_reference)
from paddle_tpu.serving import (
    EngineSaturated, KVPoolExhausted, ModelSpec, NULL_PAGE, PagePool,
    ServeConfig, ServingEngine, init_params, is_served_model_dir,
    load_engine, save_served_model)

SPEC = ModelSpec(vocab_size=64, hidden=32, layers=2, heads=2,
                 max_seq_len=64)
# one small bucket per family keeps the AOT build fast; decode bucket 4
# still exercises padding rows and join/leave churn
CFG = ServeConfig(decode_buckets=(4,), prefill_buckets=(16,),
                  kv_pages=32, page_size=4, max_inflight=16,
                  max_new_tokens=8)


@pytest.fixture(scope="module")
def engine():
    eng = ServingEngine(SPEC, init_params(SPEC, seed=0), CFG)
    yield eng
    eng.close()


# -- page pool ---------------------------------------------------------------

def _pool(pages=8, page_size=4):
    return PagePool(layers=1, pages=pages, page_size=page_size,
                    heads=1, head_dim=4)


def test_pool_alloc_free_reuse():
    pool = _pool(pages=8)
    a = pool.alloc(3)
    assert len(a) == 3 and NULL_PAGE not in a
    assert len(set(a)) == 3
    pool.free(a)
    b = pool.alloc(3)
    # LIFO free list: freed pages are reused before untouched ones
    assert set(b) == set(a)
    pool.free(b)
    pool.check_consistency()
    assert pool.stats["allocs"] == 6 and pool.stats["frees"] == 6


def test_pool_exhaustion_and_double_free():
    pool = _pool(pages=4)  # 3 usable (page 0 reserved as null)
    a = pool.alloc(3)
    with pytest.raises(KVPoolExhausted):
        pool.alloc(1)
    assert pool.stats["alloc_failures"] == 1
    with pytest.raises(ValueError):
        pool.free([NULL_PAGE])
    pool.free(a)
    with pytest.raises(ValueError):
        pool.free([a[0]])  # double free
    pool.check_consistency()


def test_pool_reservation_admission_control():
    pool = _pool(pages=8)  # 7 usable
    assert pool.can_admit(7) and not pool.can_admit(8)
    pool.reserve(5)
    assert pool.headroom() == 2
    assert not pool.can_admit(3)
    with pytest.raises(KVPoolExhausted):
        pool.reserve(3)
    assert pool.stats["reserve_refusals"] == 1
    # reserved allocs draw down the promise, not fresh headroom
    got = pool.alloc(2, reserved=True)
    assert pool.headroom() == 2
    pool.free(got)
    pool.release_reservation(3)
    assert pool.headroom() == 7
    pool.check_consistency()


def test_pool_fragmentation_interleaved_lifetimes():
    # interleaved alloc/free of different sizes must never lose a page
    pool = _pool(pages=16)
    rng = np.random.RandomState(0)
    live = []
    for _ in range(200):
        if live and (rng.rand() < 0.5 or pool.headroom() < 4):
            pool.free(live.pop(rng.randint(len(live))))
        else:
            live.append(pool.alloc(int(rng.randint(1, 4))))
        pool.check_consistency()
    for pages in live:
        pool.free(pages)
    assert pool.headroom() == pool.usable_pages
    assert pool.stats["high_watermark"] <= pool.usable_pages


def test_pool_pages_needed_and_padded_table():
    pool = _pool(page_size=4)
    assert pool.pages_needed(0) == 1
    assert pool.pages_needed(4) == 1
    assert pool.pages_needed(5) == 2
    t = pool.null_padded_table([3, 5], 4)
    assert t.tolist() == [3, 5, NULL_PAGE, NULL_PAGE]
    assert t.dtype == np.int32


# -- paged attention ---------------------------------------------------------

def test_paged_attention_matches_reference():
    rng = np.random.RandomState(1)
    b, h, d, ps, maxp = 3, 2, 8, 4, 5
    pages = 1 + b * maxp
    q = jnp.asarray(rng.randn(b, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(pages, ps, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(pages, ps, h, d), jnp.float32)
    tables = jnp.asarray(
        rng.permutation(np.arange(1, pages))[:b * maxp].reshape(b, maxp))
    lengths = jnp.asarray([1, 7, 20], jnp.int32)
    ref = paged_attention_reference(q, k, v, tables, lengths)
    out = paged_attention(q, k, v, tables, lengths,
                          use_pallas=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


# -- engine: zero-compile request path ---------------------------------------

def test_engine_zero_compiles_after_warmup(engine):
    assert engine.unexpected_compiles == 0
    outs = engine.generate([[1, 2, 3], [4, 5, 6, 7, 8]],
                           max_new_tokens=6)
    assert len(outs) == 2 and all(len(o) == 6 for o in outs)
    # every in-ladder shape was AOT-compiled at load: still zero
    assert engine.unexpected_compiles == 0
    assert engine.healthz()["ok"]


def test_engine_out_of_ladder_shapes_refused(engine):
    with pytest.raises(ValueError):
        engine.prefill_bucket_for(CFG.prefill_buckets[-1] + 1)
    with pytest.raises(ValueError):
        engine.scheduler.submit(list(range(1, 40)))  # > prefill bucket
    with pytest.raises(ValueError):
        engine.scheduler.submit([])
    with pytest.raises(ValueError):
        engine.scheduler.submit([SPEC.vocab_size + 5])


def test_engine_kv_pages_returned_after_retire(engine):
    before = engine.pool.snapshot()
    engine.generate([[7, 8, 9]], max_new_tokens=4)
    after = engine.pool.snapshot()
    assert after["used_pages"] == before["used_pages"]
    assert after["reserved_pages"] == before["reserved_pages"]
    engine.pool.check_consistency()


# -- continuous batching: bit-identity ---------------------------------------

def test_continuous_batching_bit_identical_to_solo(engine):
    rng = np.random.RandomState(2)
    prompts = [rng.randint(1, SPEC.vocab_size,
                           size=rng.randint(2, 12)).tolist()
               for _ in range(7)]
    # solo: one request at a time — each decode step is a batch of one
    # sequence padded into the bucket
    solo = [engine.generate([p], max_new_tokens=8)[0] for p in prompts]
    # batched: all seven compete for a 4-wide decode bucket, so every
    # sequence sees neighbours join and leave mid-generation
    batched = engine.generate(prompts, max_new_tokens=8)
    assert batched == solo
    assert engine.unexpected_compiles == 0


def test_saturation_refusal(engine):
    sched = engine.scheduler
    streams = []
    try:
        with pytest.raises(EngineSaturated):
            for _ in range(CFG.max_inflight + 1):
                streams.append(sched.submit([1, 2], max_new_tokens=1))
    finally:
        sched.drain()
    for st in streams:
        st.result(timeout=30)


def test_kv_headroom_blocks_admission():
    # pool sized so the second request cannot reserve its worst case
    cfg = CFG.replace(kv_pages=8, max_new_tokens=8)  # 7 usable pages
    eng = ServingEngine(SPEC, init_params(SPEC, seed=0), cfg)
    try:
        # worst case per request: ceil((6+8)/4) = 4 pages → only one fits
        s1 = eng.scheduler.submit([1, 2, 3, 4, 5, 6], max_new_tokens=8)
        s2 = eng.scheduler.submit([1, 2, 3, 4, 5, 6], max_new_tokens=8)
        eng.scheduler.step()
        snap = eng.scheduler.snapshot()
        assert snap["active_sequences"] == 1
        assert snap["queue_depth"] == 1
        assert snap["refused_kv"] >= 1
        eng.scheduler.drain()
        # head-of-line request ran after the first retired its pages
        assert s1.result(timeout=30) == s2.result(timeout=30)
        assert eng.pool.snapshot()["used_pages"] == 0
    finally:
        eng.close()


# -- weight swap -------------------------------------------------------------

def test_install_weights_zero_downtime(engine):
    prompt = [3, 1, 4, 1, 5]
    base = engine.generate([prompt], max_new_tokens=6)[0]
    old_step = engine.weights_step
    try:
        # all-zero weights make every logit equal → greedy decode is
        # deterministically token 0, observable proof the swap landed
        zeros = {k: np.zeros_like(np.asarray(v))
                 for k, v in init_params(SPEC, seed=0).items()}
        engine.install_weights(zeros, step=9)
        assert engine.weights_step == 9
        assert engine.generate([prompt], max_new_tokens=6)[0] == [0] * 6
        assert engine.unexpected_compiles == 0  # swap never recompiles
    finally:
        engine.install_weights(init_params(SPEC, seed=0), step=old_step)
    assert engine.generate([prompt], max_new_tokens=6)[0] == base


def test_install_weights_rejects_mismatched_tree(engine):
    bad = dict(init_params(SPEC, seed=0))
    first = next(iter(bad))
    bad[first] = np.zeros((3, 3), np.float32)
    with pytest.raises(ValueError):
        engine.install_weights(bad)


# -- served model dir --------------------------------------------------------

def test_save_load_roundtrip(tmp_path, engine):
    root = str(tmp_path / "served")
    save_served_model(root, SPEC, init_params(SPEC, seed=0),
                      config=CFG, step=3)
    assert is_served_model_dir(root)
    assert not is_served_model_dir(str(tmp_path))
    eng2 = load_engine(root)
    try:
        assert eng2.weights_step == 3
        assert eng2.config.decode_buckets == CFG.decode_buckets
        prompt = [2, 7, 1]
        assert (eng2.generate([prompt], max_new_tokens=5)[0]
                == engine.generate([prompt], max_new_tokens=5)[0])
        assert eng2.unexpected_compiles == 0
    finally:
        eng2.close()


def test_load_engine_missing_checkpoint(tmp_path):
    root = str(tmp_path / "empty")
    os.makedirs(root)
    with open(os.path.join(root, "serve_config.json"), "w") as f:
        json.dump({"model": SPEC.to_dict(), "serve": CFG.to_dict()}, f)
    with pytest.raises(FileNotFoundError):
        load_engine(root)


def test_serve_config_env_roundtrip(monkeypatch):
    monkeypatch.setenv("PT_SERVE_BUCKETS", "2,8")
    monkeypatch.setenv("PT_SERVE_KV_PAGES", "64")
    monkeypatch.setenv("PT_SERVE_MAX_INFLIGHT", "5")
    cfg = ServeConfig.from_env()
    assert cfg.decode_buckets == (2, 8)
    assert cfg.kv_pages == 64 and cfg.max_inflight == 5
    assert ServeConfig.from_dict(cfg.to_dict()) == cfg


def test_serve_config_normalized_clamps_ladder():
    cfg = ServeConfig(decode_buckets=(1, 2, 3),
                      prefill_buckets=(16, 4096)).normalized(SPEC)
    # decode bucket 1 is clamped to 2 (batch-1 gemv reduction order
    # differs → would break the bit-identity contract)
    assert min(cfg.decode_buckets) >= 2
    assert all(b <= SPEC.max_seq_len for b in cfg.prefill_buckets)


# -- HTTP front end ----------------------------------------------------------

def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.status, json.loads(r.read())


def test_http_end_to_end():
    from paddle_tpu.serving.http import ServeHTTPServer
    eng = ServingEngine(SPEC, init_params(SPEC, seed=0), CFG)
    srv = ServeHTTPServer(eng, port=0).start()
    base = f"http://{srv.host}:{srv.port}"
    try:
        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            health = json.loads(r.read())
        assert r.status == 200 and health["ok"]

        status, out = _post(base + "/v1/generate",
                            {"tokens": [1, 2, 3], "max_new_tokens": 4})
        assert status == 200
        assert len(out["tokens"]) == 4
        assert out["latency_ms"] >= 0
        # parity with the in-process path
        assert out["tokens"] == eng.generate([[1, 2, 3]],
                                             max_new_tokens=4)[0]

        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            assert r.status == 200

        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base + "/v1/generate", {"tokens": "nope"})
        assert ei.value.code == 400
        assert eng.unexpected_compiles == 0
    finally:
        srv.stop()
        eng.close()


def test_http_saturation_returns_429():
    from paddle_tpu.serving.http import ServeHTTPServer
    cfg = CFG.replace(max_inflight=1)
    eng = ServingEngine(SPEC, init_params(SPEC, seed=0), cfg)
    # stall the scheduler loop so the first request stays in flight
    eng.scheduler.start()
    srv = ServeHTTPServer(eng, port=0).start()
    base = f"http://{srv.host}:{srv.port}"
    hold = threading.Event()
    orig_step = eng.scheduler.step

    def slow_step():
        hold.wait(5.0)
        return orig_step()

    eng.scheduler.step = slow_step
    try:
        t = threading.Thread(
            target=lambda: _post(base + "/v1/generate",
                                 {"tokens": [1, 2], "max_new_tokens": 2}))
        t.start()
        # wait until the in-flight slot is taken
        deadline = 50
        while eng.scheduler.snapshot()["submitted"] == 0 and deadline:
            deadline -= 1
            threading.Event().wait(0.05)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base + "/v1/generate",
                  {"tokens": [3, 4], "max_new_tokens": 2})
        assert ei.value.code == 429
        hold.set()
        t.join(timeout=30)
    finally:
        hold.set()
        eng.scheduler.step = orig_step
        srv.stop()
        eng.close()
