"""cost_model -> auto_tuner wiring (VERDICT r04 item 6; ref:
``auto_parallel/static/cluster.py`` + ``cost/`` estimator feeding the
tuner): predicted-OOM pruning, best-predicted-first ordering, and the
headline property — the guided tuner reaches a same-or-better config in
fewer measured trials than blind grid search on a recorded scenario."""
import numpy as np
import pytest

from paddle_tpu.cost_model import (predict, predict_memory_bytes,
                                   predict_step_time)
from paddle_tpu.distributed.auto_parallel import Cluster
from paddle_tpu.distributed.auto_tuner import AutoTuner

# GPT-1.3B-class: big enough that some VALID 8-chip tilings genuinely
# exceed 16G HBM (dp=8 no-remat), so OOM pruning has real work to do
MODEL = dict(n_params=1.3e9, num_layers=24, hidden_size=2048, seq_len=1024)
CLUSTER = Cluster(num_chips=8, device_kind="TPU v5e", peak_flops=197e12,
                  hbm_bytes=16 << 30, ici_bandwidth=400e9)
CANDIDATES = {
    "dp_degree": [1, 2, 4, 8],
    "mp_degree": [1, 2, 4],
    "pp_degree": [1, 2],
    "sharding_degree": [1, 2],
    "micro_batch_size": [2, 4, 8, 32],
    "use_recompute": [False, True],
}
GBS = 64


def _tuner_cfg(with_model):
    cfg = {"candidates": dict(CANDIDATES), "num_chips": 8,
           "global_batch_size": GBS}
    if with_model:
        cfg["model"] = MODEL
        cfg["cluster"] = CLUSTER
    return cfg


def _ground_truth(cfg):
    """The 'real hardware': same physics family as the predictor but a
    DIFFERENT cluster (slower interconnect, lower efficiency) plus a
    deterministic per-config wobble — the tuner must win via ordering,
    not via the oracle being identical."""
    real = Cluster(num_chips=8, device_kind="TPU v5e",
                   peak_flops=197e12 * 0.8, hbm_bytes=15 << 30,
                   ici_bandwidth=250e9)
    mem = predict_memory_bytes(MODEL, cfg, real)
    if mem > real.hbm_bytes * 0.9:
        return None, "oom"
    t = predict_step_time(MODEL, cfg, real, global_batch_size=GBS)
    # crc32, not hash(): builtin string hashing is randomized per
    # process, which would make the ground truth flake across CI runs
    import zlib
    digest = zlib.crc32(repr(sorted(
        (k, v) for k, v in cfg.items() if k in CANDIDATES)).encode())
    wobble = 1.0 + 0.06 * ((digest % 100) / 100.0 - 0.5)
    tput = GBS * MODEL["seq_len"] / (t * wobble)
    return tput, "ok"


def _run_search(with_model, stop_within=None, best_tput=None):
    """Run the tuner loop; return (trials_to_near_best, best_found)."""
    tuner = AutoTuner(_tuner_cfg(with_model))
    trials, first_hit = 0, None
    while (cfg := tuner.search_once()) is not None:
        tput, status = _ground_truth(cfg)
        trials += 1
        tuner.add_cfg(**cfg, throughput=tput, status=status)
        if (first_hit is None and tput is not None and best_tput
                and tput >= stop_within * best_tput):
            first_hit = trials
    best, err = tuner.get_best()
    assert not err
    return first_hit, best, trials


def _global_best():
    tuner = AutoTuner(_tuner_cfg(False))
    best = 0.0
    while (cfg := tuner.search_once()) is not None:
        tput, status = _ground_truth(cfg)
        tuner.add_cfg(**cfg, throughput=tput, status=status)
        if status == "ok":
            best = max(best, tput)
    return best


def test_predicted_oom_configs_never_trialed():
    tuner = AutoTuner(_tuner_cfg(True))
    assert tuner.pruned_by_cost > 0
    seen = []
    while (cfg := tuner.search_once()) is not None:
        seen.append(cfg)
    for cfg in seen:
        assert cfg["predicted_memory_bytes"] <= CLUSTER.hbm_bytes * 0.92
        assert "predicted_step_time" in cfg  # predicted-vs-measured rows


def test_guided_order_is_best_predicted_first():
    tuner = AutoTuner(_tuner_cfg(True))
    times = []
    while (cfg := tuner.search_once()) is not None:
        times.append(cfg["predicted_step_time"])
    assert times == sorted(times) and len(times) > 5


def test_guided_tuner_converges_in_fewer_trials():
    best = _global_best()
    hit_guided, best_guided, n_guided = _run_search(
        True, stop_within=0.97, best_tput=best)
    hit_blind, best_blind, n_blind = _run_search(
        False, stop_within=0.97, best_tput=best)
    assert hit_guided is not None
    # the cost model must put a near-best config within the first few
    # trials; blind grid order takes (much) longer
    assert hit_guided < hit_blind, (hit_guided, hit_blind)
    assert hit_guided <= 5, hit_guided
    # and the chosen config is same-or-better
    assert best_guided["throughput"] >= best_blind["throughput"] * 0.97
    # the guided search also visits a smaller space (OOM pruned)
    assert n_guided < n_blind


def test_cluster_auto_detect_and_engine_estimate():
    import jax
    jax.config.update("jax_platforms", "cpu")
    c = Cluster.auto_detect()
    assert c.num_chips >= 1 and c.peak_flops > 0
    import paddle_tpu as pt
    from paddle_tpu.distributed.auto_parallel import Engine
    eng = Engine(pt.nn.Linear(4, 4))
    t, m, fits = eng.estimate_cost(MODEL, {"dp_degree": 1,
                                           "micro_batch_size": 1})
    assert t > 0 and m > 0 and isinstance(fits, (bool, np.bool_))
