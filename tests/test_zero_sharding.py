"""ZeRO stage-1/2 optimizer-state sharding (ref:
``python/paddle/distributed/fleet/meta_parallel/sharding/
group_sharded_stage2.py``, ``group_sharded_optimizer_stage2.py``,
``dygraph_optimizer/dygraph_sharding_optimizer.py:29``).

Asserts the real memory win: with level "os"/"os_g" the optimizer
slot/master trees are partitioned over the `sharding` mesh axis — each
device stores ~1/N of the state bytes — and training losses match the
unsharded baseline exactly (same math, different placement)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.sharding import group_sharded_parallel
from paddle_tpu.distributed.train_step import build_train_step, zero_spec
from jax.sharding import PartitionSpec as P


def _mlp():
    pt.seed(7)
    return nn.Sequential(
        nn.Linear(64, 256), nn.ReLU(),
        nn.Linear(256, 256), nn.ReLU(),
        nn.Linear(256, 8))


def _loss_fn(out, y):
    return pt.nn.functional.cross_entropy(out, y)


def _batch():
    rng = np.random.RandomState(0)
    x = rng.rand(16, 64).astype(np.float32)
    y = rng.randint(0, 8, (16,)).astype(np.int64)
    return x, y


def _max_local_bytes(arr):
    """Largest per-device shard of a placed jax array, in bytes."""
    return max(s.data.nbytes for s in arr.addressable_shards)


def _opt_bytes_per_device(state):
    total = 0
    for sv in state["opt"]["slots"].values():
        total += sum(_max_local_bytes(v) for v in sv.values())
    total += sum(_max_local_bytes(v)
                 for v in state["opt"]["master"].values())
    return total


def _train(level, steps=3):
    mesh = dist.init_mesh({"dp": 2, "sharding": 4})
    model = _mlp()
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    if level is not None:
        model, opt, _ = group_sharded_parallel(model, opt, level=level)
    step, state = build_train_step(model, _loss_fn, opt, mesh=mesh)
    x, y = _batch()
    losses = []
    for _ in range(steps):
        loss, state = step(state, x, y)
        losses.append(float(loss))
    return losses, state


class TestZeroSpec:
    def test_inserts_sharding_axis_on_largest_divisible_dim(self):
        mesh = dist.init_mesh({"dp": 2, "sharding": 4})
        assert zero_spec(P(), (256, 64), mesh) == P("sharding", None)
        # dim0 indivisible by 4 -> falls to dim1
        assert zero_spec(P(), (66, 256), mesh) == P(None, "sharding")

    def test_respects_existing_axes(self):
        mesh = dist.init_mesh({"dp": 2, "sharding": 4})
        # param already fsdp-sharded: state inherits, no double insert
        assert zero_spec(P("sharding", None), (256, 64), mesh) == \
            P("sharding", None)
        # mp-sharded dim is occupied; sharding goes to the free dim
        assert zero_spec(P("mp", None), (256, 64), mesh) == \
            P("mp", "sharding")

    def test_indivisible_leaf_stays_replicated(self):
        mesh = dist.init_mesh({"dp": 2, "sharding": 4})
        assert zero_spec(P(), (7, 9), mesh) == P()

    def test_rank1_bias_leaves(self):
        mesh = dist.init_mesh({"dp": 2, "sharding": 4})
        # a divisible bias shards over its only dim
        assert zero_spec(P(), (256,), mesh) == P("sharding")
        # an indivisible one stays replicated
        assert zero_spec(P(), (6,), mesh) == P()
        # already sharded: inherited unchanged, no double insert
        assert zero_spec(P("sharding"), (256,), mesh) == P("sharding")


class TestZeroStage12:
    def test_os_state_is_partitioned(self):
        _, state = _train("os", steps=1)
        m1 = state["opt"]["slots"]["moment1"]
        # every shardable leaf carries the sharding axis
        w = m1["0.weight"]
        assert "sharding" in jax.tree.leaves(
            [w.sharding.spec])[0:] or "sharding" in str(w.sharding.spec)
        shard = w.addressable_shards[0].data
        assert shard.size == w.size // 4

    def test_os_memory_shrinks_vs_baseline(self):
        _, base_state = _train(None, steps=1)
        _, os_state = _train("os", steps=1)
        base = _opt_bytes_per_device(base_state)
        shard = _opt_bytes_per_device(os_state)
        # biases (size 256/8) shard too where divisible; demand >=3x
        assert shard * 3 <= base, (shard, base)

    @pytest.mark.parametrize("level", [
    pytest.param("os", marks=pytest.mark.slow), "os_g"])
    @pytest.mark.slow
    def test_loss_parity_with_baseline(self, level):
        ref, _ = _train(None)
        got, _ = _train(level)
        assert np.allclose(ref, got, atol=1e-5), (ref, got)

    @pytest.mark.slow
    def test_os_g_grad_constraint_compiles(self):
        # stage 2 runs and keeps state sharded across steps (donated
        # buffers must not silently re-replicate)
        _, state = _train("os_g", steps=2)
        w = state["opt"]["slots"]["moment2"]["2.weight"]
        assert w.addressable_shards[0].data.size == w.size // 4


class TestDygraphShardingOptimizer:
    def test_partition_and_level(self):
        from paddle_tpu.distributed.fleet.meta_optimizers import (
            DygraphShardingOptimizer)
        model = _mlp()
        inner = pt.optimizer.AdamW(learning_rate=1e-3,
                                   parameters=model.parameters())
        opt = DygraphShardingOptimizer(optimizer=inner)
        assert inner._group_sharded_level == "os"
        # greedy partition covers every parameter exactly once
        allp = [p for ps in opt._rank2params.values() for p in ps]
        assert len(allp) == len(list(model.parameters()))
