"""Elastic resume (world-size M→N resharding), staged multi-host
commit, barrier diagnostics, the staging janitor, and their metrics.

In-process, fast (tier-1) counterpart to the real-SIGKILL drills in
tests/drills/: the same protocol surfaces exercised through threads,
fabricated directories and a real TCPStore — no subprocesses."""
from __future__ import annotations

import json
import os
import re
import threading
import time

import numpy as np
import pytest

import paddle_tpu.observability as obs
from paddle_tpu.core import TCPStore
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed.checkpoint import (
    CheckpointCorruptError, HostLocalShard, ReshardError, read_leaf,
    store_barrier, sweep_staging, verify_checkpoint)
from paddle_tpu.distributed.checkpoint_manager import CheckpointManager

ROWS, COLS = 12, 4


def _global_state(seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(ROWS, COLS).astype(np.float32),
            rng.randn(COLS).astype(np.float32))


def _save_world(path, world, w, bias):
    """Store-less multi-host save: each rank writes its row window of
    ``w`` plus the replicated ``bias`` (overlapping full windows)."""
    for rank in range(world):
        lo, hi = rank * ROWS // world, (rank + 1) * ROWS // world
        state = {
            "w": HostLocalShard(w[lo:hi], window=[[lo, hi], [0, COLS]],
                                global_shape=(ROWS, COLS)),
            "bias": HostLocalShard(bias),
        }
        ckpt.save_sharded(state, path, process_index=rank,
                          world_size=world, durable=False)


# -- HostLocalShard contract -------------------------------------------------

def test_hostlocalshard_validates_window():
    with pytest.raises(ValueError, match="window rank"):
        HostLocalShard(np.zeros((2, 3)), window=[[0, 2]],
                       global_shape=(4, 3))
    with pytest.raises(ValueError, match="out of bounds"):
        HostLocalShard(np.zeros((2, 3)), window=[[3, 5], [0, 3]],
                       global_shape=(4, 3))
    with pytest.raises(ValueError, match="does not fill"):
        HostLocalShard(np.zeros((2, 3)), window=[[0, 3], [0, 3]],
                       global_shape=(4, 3))


# -- M -> N resharding -------------------------------------------------------

@pytest.mark.parametrize("m,n", [(2, 1), (1, 2), (3, 2), (2, 3)])
def test_reshard_roundtrip_across_world_sizes(tmp_path, m, n):
    """A checkpoint written by M processes hands an N-process fleet its
    exact rows back — the coverage-window stitching on per-shard
    manifests, no jax involved."""
    w, bias = _global_state()
    path = str(tmp_path / "step")
    _save_world(path, m, w, bias)
    verify_checkpoint(path, integrity="full")
    for rank in range(n):
        lo, hi = rank * ROWS // n, (rank + 1) * ROWS // n
        got = read_leaf(path, "w", window=[[lo, hi], [0, COLS]])
        assert got.tobytes() == w[lo:hi].tobytes()
    assert read_leaf(path, "bias").tobytes() == bias.tobytes()


def test_load_sharded_elastic_full_tree(tmp_path):
    w, bias = _global_state()
    path = str(tmp_path / "step")
    _save_world(path, 2, w, bias)
    out = ckpt.load_sharded(path, elastic=True)
    assert np.asarray(out["w"]).tobytes() == w.tobytes()
    assert np.asarray(out["bias"]).tobytes() == bias.tobytes()


def test_overlapping_windows_any_one_covers(tmp_path):
    """Replicated leaves are saved by every rank with full overlapping
    windows; elastic resume must be able to stitch from any survivor."""
    w, bias = _global_state()
    path = str(tmp_path / "step")
    _save_world(path, 3, w, bias)
    # lose ranks 1 and 2: bias still fully covered by rank 0's window
    os.remove(os.path.join(path, "COMMIT.1"))
    os.remove(os.path.join(path, "COMMIT.2"))
    got = read_leaf(path, "bias", elastic=True)
    assert got.tobytes() == bias.tobytes()


def test_gapped_windows_raise_reshard_error(tmp_path):
    """A window set with a hole must raise — never silently zero-fill —
    and the error names the committed ranks."""
    w, bias = _global_state()
    path = str(tmp_path / "step")
    _save_world(path, 3, w, bias)
    os.remove(os.path.join(path, "COMMIT.1"))  # rows [4, 8) now gone
    with pytest.raises(ReshardError, match=r"committed ranks \[0, 2\]"):
        read_leaf(path, "w", elastic=True)
    with pytest.raises(ReshardError):
        ckpt.load_sharded(path, elastic=True)
    # ReshardError subclasses CheckpointCorruptError so resume-latest
    # fallback machinery treats the step as unusable, not fatal
    assert issubclass(ReshardError, CheckpointCorruptError)


def test_world_size_mismatch_error_is_actionable(tmp_path):
    """Strict load of a partial marker set must name the committed
    ranks, the expected set, and point at the elastic reshard path."""
    w, bias = _global_state()
    path = str(tmp_path / "step")
    _save_world(path, 2, w, bias)
    os.remove(os.path.join(path, "COMMIT.1"))
    with pytest.raises(CheckpointCorruptError) as ei:
        ckpt.load_sharded(path)
    msg = str(ei.value)
    assert "ranks [0]" in msg
    assert "expects ranks [0, 1]" in msg
    assert "missing ranks [1]" in msg
    assert "elastic=True" in msg


def test_elastic_never_reads_uncommitted_rank_data(tmp_path):
    """An uncommitted rank's shard files may be torn — elastic stitching
    must ignore them even when they are present on disk."""
    w, bias = _global_state()
    path = str(tmp_path / "step")
    _save_world(path, 2, w, bias)
    os.remove(os.path.join(path, "COMMIT.1"))
    # corrupt rank 1's (now uncommitted) shard file; a correct elastic
    # reader never opens it, so only ReshardError may surface
    for f in os.listdir(os.path.join(path, "data", "w")):
        if f.startswith("1_"):
            with open(os.path.join(path, "data", "w", f), "wb") as fh:
                fh.write(b"garbage")
    with pytest.raises(ReshardError):
        read_leaf(path, "w", elastic=True)


# -- staged multi-host commit over a real store ------------------------------

def test_staged_commit_two_ranks_threads(tmp_path):
    """Both ranks stage into ONE shared tmp dir, barrier, rank 0
    promotes atomically: the final dir is fully committed and no
    staging debris survives a successful save."""
    w, bias = _global_state()
    root = str(tmp_path / "run")
    master = TCPStore("127.0.0.1", 0, is_master=True)
    errs = []

    def one_rank(rank):
        try:
            store = TCPStore("127.0.0.1", master.port, is_master=False)
            mgr = CheckpointManager(root, keep_last_n=None, store=store,
                                    world_size=2, process_index=rank,
                                    durable=False, run_id="t-reshard",
                                    barrier_timeout=30.0)
            lo, hi = rank * ROWS // 2, (rank + 1) * ROWS // 2
            state = {"w": HostLocalShard(
                w[lo:hi], window=[[lo, hi], [0, COLS]],
                global_shape=(ROWS, COLS))}
            mgr.save(7, state)
        except BaseException as e:  # pragma: no cover - failure path
            errs.append((rank, e))

    ts = [threading.Thread(target=one_rank, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    master.close()
    assert not errs, errs
    step = os.path.join(root, "step_00000007")
    verify_checkpoint(step, integrity="full")
    assert read_leaf(step, "w").tobytes() == w.tobytes()
    assert not [n for n in os.listdir(root) if ".tmp." in n]
    # markers record the staging nonce (the promote-safety signal)
    mk = json.load(open(os.path.join(step, "COMMIT.0")))
    assert mk.get("nonce")


def test_store_barrier_timeout_names_missing_ranks():
    master = TCPStore("127.0.0.1", 0, is_master=True)
    try:
        with pytest.raises(TimeoutError) as ei:
            store_barrier(master, "b/x", world=3, rank=0, timeout=0.4)
        msg = str(ei.value)
        assert "missing ranks [1, 2]" in msg
        assert "arrived: [0]" in msg
    finally:
        master.close()


def test_store_barrier_without_rank_keeps_count_only_diag():
    """rank=None is the legacy contract: stores that only implement
    ``add`` (no per-rank keys) must still barrier."""

    class _AddOnly:
        def __init__(self):
            self.n = 0

        def add(self, key, amount):
            self.n += amount
            return self.n

    s = _AddOnly()
    s.n = 1  # one peer already arrived
    store_barrier(s, "k", world=2, timeout=5.0)


# -- janitor -----------------------------------------------------------------

def test_sweep_staging_age_gate_and_newest_spared(tmp_path):
    root = str(tmp_path)
    w, bias = _global_state()
    _save_world(os.path.join(root, "step_00000001"), 1, w, bias)
    old = time.time() - 7200
    for name, aged in [("step_00000002.tmp.aaaa", True),
                       ("step_00000002.old.bbbb", True),
                       ("step_00000003.tmp.cccc", False)]:
        d = os.path.join(root, name, "data")
        os.makedirs(d)
        if aged:
            os.utime(os.path.join(root, name), (old, old))
    # an aged directory that is NOT checkpoint-shaped must survive
    os.makedirs(os.path.join(root, "notes"))
    os.utime(os.path.join(root, "notes"), (old, old))
    n = sweep_staging(root, max_age=3600.0)
    assert n == 2
    left = sorted(os.listdir(root))
    assert "step_00000003.tmp.cccc" in left          # newest spared
    assert "step_00000001" in left                   # committed spared
    assert "notes" in left                           # not ours to touch
    assert "step_00000002.tmp.aaaa" not in left
    assert "step_00000002.old.bbbb" not in left


def test_sweep_staging_removes_aged_partial_marker_dirs(tmp_path):
    """Store-less in-place saves that died mid-fleet leave a partial
    marker set in the FINAL dir; aged ones are debris."""
    root = str(tmp_path)
    w, bias = _global_state()
    path = os.path.join(root, "step_00000004")
    lo, hi = 0, ROWS // 2
    ckpt.save_sharded(
        {"w": HostLocalShard(w[lo:hi], window=[[lo, hi], [0, COLS]],
                             global_shape=(ROWS, COLS))},
        path, process_index=0, world_size=2, durable=False)
    assert not ckpt.is_committed(path)
    old = time.time() - 7200
    os.utime(path, (old, old))
    assert sweep_staging(root, max_age=3600.0) == 1
    assert not os.path.exists(path)
    # a FRESH partial dir (possibly a fleet mid-save) is left alone
    ckpt.save_sharded(
        {"w": HostLocalShard(w[lo:hi], window=[[lo, hi], [0, COLS]],
                             global_shape=(ROWS, COLS))},
        path, process_index=0, world_size=2, durable=False)
    assert sweep_staging(root, max_age=3600.0) == 0
    assert os.path.exists(path)


def test_sweep_staging_missing_root_is_noop(tmp_path):
    assert sweep_staging(str(tmp_path / "nope")) == 0


# -- CheckpointManager elastic wiring ---------------------------------------

def test_manager_elastic_restore_and_fallback(tmp_path):
    root = str(tmp_path / "run")
    os.makedirs(root)
    w1, b1 = _global_state(1)
    w2, b2 = _global_state(2)
    _save_world(os.path.join(root, "step_00000001"), 1, w1, b1)
    _save_world(os.path.join(root, "step_00000002"), 2, w2, b2)
    # step 2 loses rank 1: a genuine hole in "w"
    os.remove(os.path.join(root, "step_00000002", "COMMIT.1"))
    mgr = CheckpointManager(root, keep_last_n=None, elastic=True,
                            orphan_age=None)
    assert mgr.valid_steps() == [1]  # holey step 2 is not a resume point
    state, step = mgr.restore_latest()
    assert step == 1
    assert np.asarray(state["w"]).tobytes() == w1.tobytes()
    # strict manager agrees step 2 is unusable
    strict = CheckpointManager(root, keep_last_n=None, orphan_age=None)
    assert strict.valid_steps() == [1]


def test_manager_init_runs_janitor(tmp_path):
    root = str(tmp_path / "run")
    os.makedirs(os.path.join(root, "step_00000001.tmp.aaaa", "data"))
    os.makedirs(os.path.join(root, "step_00000002.tmp.bbbb", "data"))
    old = time.time() - 7200
    os.utime(os.path.join(root, "step_00000001.tmp.aaaa"), (old, old))
    CheckpointManager(root, orphan_age=3600.0)
    assert not os.path.exists(
        os.path.join(root, "step_00000001.tmp.aaaa"))
    assert os.path.exists(os.path.join(root, "step_00000002.tmp.bbbb"))


# -- observability ----------------------------------------------------------

@pytest.fixture
def _tel():
    obs.reset()
    tel = obs.get_telemetry().enable(compile_watch=False)
    yield tel
    obs.reset()


def test_barrier_and_sweep_metrics(tmp_path, _tel):
    master = TCPStore("127.0.0.1", 0, is_master=True)
    try:
        store_barrier(master, "m/ok", world=1, rank=0, timeout=5.0)
        with pytest.raises(TimeoutError):
            store_barrier(master, "m/t", world=2, rank=0, timeout=0.2)
    finally:
        master.close()
    root = str(tmp_path)
    os.makedirs(os.path.join(root, "a.tmp.1111", "data"))
    os.makedirs(os.path.join(root, "b.tmp.2222", "data"))
    old = time.time() - 7200
    for n in ("a.tmp.1111", "b.tmp.2222"):
        os.utime(os.path.join(root, n), (old, old))
    assert sweep_staging(root, max_age=3600.0) == 1
    text = _tel.registry.prometheus_text()
    # const identity labels ride along -> match by label subset
    assert re.search(r'pt_checkpoint_barrier_wait_seconds_count'
                     r'\{[^}]*status="ok"[^}]*\} 1\b', text)
    assert re.search(r'pt_checkpoint_barrier_wait_seconds_count'
                     r'\{[^}]*status="timeout"[^}]*\}', text)
    assert re.search(r'pt_checkpoint_staging_orphans_swept_total'
                     r'(\{[^}]*\})? 1\b', text)
