"""Systematic per-op OpTest corpus (ref: ``test/legacy_test/
eager_op_test.py:377`` + the per-op tolerance tables in
``test/white_list/op_accuracy_white_list.py``).

One declarative table drives three checks per op:
 - float32 output vs numpy reference (eager AND jitted paths),
 - bfloat16 output vs the float32 numpy reference at the op's bf16
   tolerance (the TPU-first accuracy contract),
 - float32 analytic-vs-finite-difference gradient (where differentiable).
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu import Tensor
from op_test import check_output, check_grad


def _sp(*shape, seed=0, pos=False, lo=-2.0, hi=2.0):
    rng = np.random.RandomState(seed)
    a = rng.uniform(lo, hi, shape).astype(np.float32)
    if pos:
        a = np.abs(a) + 0.5
    return a


def _softmax_np(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def _erf_np(x):
    from math import erf
    return np.vectorize(erf)(x).astype(np.float64)


# (name, op_fn, np_ref, inputs, {opts})
# opts: grad=False to skip FD check; bf16_atol/bf16_rtol overrides;
#       atol/rtol f32 overrides; grad_atol for noisy pullbacks.
OPS = [
    # -- activations --------------------------------------------------------
    ("relu", F.relu, lambda x: np.maximum(x, 0), [_sp(3, 4)], {}),
    ("relu6", F.relu6, lambda x: np.clip(x, 0, 6), [_sp(3, 4, hi=8)], {}),
    ("sigmoid", F.sigmoid, lambda x: 1 / (1 + np.exp(-x)), [_sp(3, 4)], {}),
    ("tanh", F.tanh, np.tanh, [_sp(3, 4)], {}),
    ("silu", F.silu, lambda x: x / (1 + np.exp(-x)), [_sp(3, 4)], {}),
    ("softplus", F.softplus, lambda x: np.log1p(np.exp(x)), [_sp(3, 4)],
     {}),
    ("softsign", F.softsign, lambda x: x / (1 + np.abs(x)), [_sp(3, 4)],
     {}),
    ("gelu", F.gelu,
     lambda x: 0.5 * x * (1 + _erf_np(x / np.sqrt(2))), [_sp(3, 4)], {}),
    ("elu", F.elu,
     lambda x: np.where(x > 0, x, np.exp(np.minimum(x, 0)) - 1),
     [_sp(3, 4)], {}),
    ("leaky_relu", F.leaky_relu,
     lambda x: np.where(x > 0, x, 0.01 * x), [_sp(3, 4)], {}),
    ("hardtanh", F.hardtanh, lambda x: np.clip(x, -1, 1), [_sp(3, 4)],
     {"grad": False}),  # FD unstable at the clip kinks
    ("hardsigmoid", F.hardsigmoid,
     lambda x: np.clip(x / 6 + 0.5, 0, 1), [_sp(3, 4, hi=8, lo=-8)],
     {"grad": False}),
    ("hardswish", F.hardswish,
     lambda x: x * np.clip(x + 3, 0, 6) / 6, [_sp(3, 4, hi=5, lo=-5)],
     {"grad": False}),
    ("mish", F.mish,
     lambda x: x * np.tanh(np.log1p(np.exp(x))), [_sp(3, 4)], {}),
    ("log_sigmoid", F.log_sigmoid,
     lambda x: -np.log1p(np.exp(-x)), [_sp(3, 4)], {}),
    ("tanhshrink", F.tanhshrink, lambda x: x - np.tanh(x), [_sp(3, 4)],
     {"grad_atol": 2e-2}),
    ("hardshrink", F.hardshrink,
     lambda x: np.where(np.abs(x) > 0.5, x, 0), [_sp(3, 4)],
     {"grad": False}),
    ("softshrink", F.softshrink,
     lambda x: np.where(x > 0.5, x - 0.5, np.where(x < -0.5, x + 0.5, 0)),
     [_sp(3, 4)], {"grad": False}),
    ("selu", F.selu,
     lambda x: 1.0507009873554805 * np.where(
         x > 0, x, 1.6732632423543772 * (np.exp(np.minimum(x, 0)) - 1)),
     [_sp(3, 4)], {}),
    ("celu", F.celu,
     lambda x: np.maximum(x, 0) + np.minimum(
         0, np.exp(np.minimum(x, 0)) - 1), [_sp(3, 4)], {}),
    ("softmax", F.softmax, _softmax_np, [_sp(3, 5)], {}),
    ("log_softmax", F.log_softmax,
     lambda x: np.log(_softmax_np(x)), [_sp(3, 5)], {}),
    # -- elementwise math ---------------------------------------------------
    ("add", pt.add, np.add, [_sp(3, 4), _sp(3, 4, seed=1)], {}),
    ("subtract", pt.subtract, np.subtract,
     [_sp(3, 4), _sp(3, 4, seed=1)], {}),
    ("multiply", pt.multiply, np.multiply,
     [_sp(3, 4), _sp(3, 4, seed=1)], {}),
    ("divide", pt.divide, np.divide,
     [_sp(3, 4), _sp(3, 4, seed=1, pos=True)], {}),
    ("pow", lambda x: pt.pow(x, 3.0), lambda x: x ** 3, [_sp(3, 4)], {}),
    ("exp", pt.exp, np.exp, [_sp(3, 4)], {}),
    ("log", pt.log, np.log, [_sp(3, 4, pos=True)], {}),
    ("log2", pt.log2, np.log2, [_sp(3, 4, pos=True)], {}),
    ("log1p", pt.log1p, np.log1p, [_sp(3, 4, pos=True)], {}),
    ("sqrt", pt.sqrt, np.sqrt, [_sp(3, 4, pos=True)], {}),
    ("rsqrt", pt.rsqrt, lambda x: 1 / np.sqrt(x), [_sp(3, 4, pos=True)],
     {}),
    ("abs", pt.abs, np.abs, [_sp(3, 4)], {"grad": False}),
    ("sin", pt.sin, np.sin, [_sp(3, 4)], {}),
    ("cos", pt.cos, np.cos, [_sp(3, 4)], {}),
    ("tan", pt.tan, np.tan, [_sp(3, 4, hi=1.2, lo=-1.2)], {}),
    ("asin", pt.asin, np.arcsin, [_sp(3, 4, hi=0.9, lo=-0.9)], {}),
    ("acos", pt.acos, np.arccos, [_sp(3, 4, hi=0.9, lo=-0.9)], {}),
    ("atan", pt.atan, np.arctan, [_sp(3, 4)], {}),
    ("sinh", pt.sinh, np.sinh, [_sp(3, 4)], {}),
    ("cosh", pt.cosh, np.cosh, [_sp(3, 4)], {}),
    ("expm1", pt.expm1, np.expm1, [_sp(3, 4)], {}),
    ("floor", pt.floor, np.floor, [_sp(3, 4)], {"grad": False}),
    ("ceil", pt.ceil, np.ceil, [_sp(3, 4)], {"grad": False}),
    ("round", pt.round, np.round, [_sp(3, 4)], {"grad": False}),
    ("sign", pt.sign, np.sign, [_sp(3, 4)], {"grad": False}),
    ("clip", lambda x: pt.clip(x, -1.0, 1.0),
     lambda x: np.clip(x, -1, 1), [_sp(3, 4)], {"grad": False}),
    ("maximum", pt.maximum, np.maximum,
     [_sp(3, 4), _sp(3, 4, seed=1)], {"grad": False}),
    ("minimum", pt.minimum, np.minimum,
     [_sp(3, 4), _sp(3, 4, seed=1)], {"grad": False}),
    ("reciprocal", pt.reciprocal, lambda x: 1 / x,
     [_sp(3, 4, pos=True)], {}),
    ("square", pt.square, np.square, [_sp(3, 4)], {}),
    ("logit", pt.logit, lambda x: np.log(x / (1 - x)),
     [_sp(3, 4, hi=0.9, lo=0.1)], {}),
    # -- reductions ---------------------------------------------------------
    ("sum", pt.sum, np.sum, [_sp(3, 4)], {}),
    ("mean", pt.mean, np.mean, [_sp(3, 4)], {}),
    ("max", pt.max, np.max, [_sp(3, 4)], {"grad": False}),
    ("min", pt.min, np.min, [_sp(3, 4)], {"grad": False}),
    ("prod", pt.prod, np.prod, [_sp(2, 3)], {"grad_atol": 2e-2}),
    ("logsumexp", pt.logsumexp,
     lambda x: np.log(np.exp(x).sum()), [_sp(3, 4)], {}),
    ("var", pt.var, lambda x: np.var(x, ddof=1), [_sp(3, 4)], {}),
    ("std", pt.std, lambda x: np.std(x, ddof=1), [_sp(3, 4)], {}),
    ("sum_axis", lambda x: pt.sum(x, axis=1),
     lambda x: np.sum(x, axis=1), [_sp(3, 4)], {}),
    ("cumsum", lambda x: pt.cumsum(x, axis=1),
     lambda x: np.cumsum(x, axis=1), [_sp(3, 4)], {}),
    # -- linalg / matmul ----------------------------------------------------
    ("matmul", pt.matmul, np.matmul, [_sp(3, 4), _sp(4, 5, seed=1)],
     {"bf16_atol": 5e-2, "bf16_rtol": 5e-2}),
    ("bmm", pt.bmm, np.matmul, [_sp(2, 3, 4), _sp(2, 4, 5, seed=1)],
     {"bf16_atol": 5e-2, "bf16_rtol": 5e-2}),
    ("t_2d", pt.t, np.transpose, [_sp(3, 4)], {}),
    # -- shape ops ----------------------------------------------------------
    ("reshape", lambda x: pt.reshape(x, [4, 3]),
     lambda x: np.reshape(x, (4, 3)), [_sp(3, 4)], {}),
    ("transpose", lambda x: pt.transpose(x, [1, 0]),
     lambda x: np.transpose(x), [_sp(3, 4)], {}),
    ("squeeze", lambda x: pt.squeeze(x, axis=1),
     lambda x: np.squeeze(x, 1), [_sp(3, 1, 4)], {}),
    ("unsqueeze", lambda x: pt.unsqueeze(x, axis=0),
     lambda x: x[None], [_sp(3, 4)], {}),
    ("flip", lambda x: pt.flip(x, axis=[1]),
     lambda x: x[:, ::-1].copy(), [_sp(3, 4)], {}),
    ("roll", lambda x: pt.roll(x, 1, axis=1),
     lambda x: np.roll(x, 1, 1), [_sp(3, 4)], {}),
    ("tile", lambda x: pt.tile(x, [2, 1]),
     lambda x: np.tile(x, (2, 1)), [_sp(3, 4)], {}),
    ("concat2", lambda a, b: pt.concat([a, b], axis=1),
     lambda a, b: np.concatenate([a, b], 1),
     [_sp(3, 4), _sp(3, 2, seed=1)], {}),
    ("stack2", lambda a, b: pt.stack([a, b], axis=0),
     lambda a, b: np.stack([a, b], 0),
     [_sp(3, 4), _sp(3, 4, seed=1)], {}),
    # paddle semantics: len(pad)==2*ndim pads FIRST dim to last
    # ([d0_l, d0_r, d1_l, d1_r]), unlike torch's last-dim-first
    ("pad2d", lambda x: F.pad(x, [1, 1, 2, 0]),
     lambda x: np.pad(x, ((1, 1), (2, 0))), [_sp(3, 4)], {}),
    ("where", lambda c, a, b: pt.where(c > 0, a, b),
     lambda c, a, b: np.where(c > 0, a, b),
     [_sp(3, 4, seed=2), _sp(3, 4), _sp(3, 4, seed=1)], {"grad": False}),
    # -- losses -------------------------------------------------------------
    ("mse_loss", F.mse_loss,
     lambda x, y: np.mean((x - y) ** 2),
     [_sp(3, 4), _sp(3, 4, seed=1)], {}),
    ("l1_loss", F.l1_loss,
     lambda x, y: np.mean(np.abs(x - y)),
     [_sp(3, 4), _sp(3, 4, seed=1)], {"grad": False}),
    ("smooth_l1", F.smooth_l1_loss,
     lambda x, y: np.mean(np.where(np.abs(x - y) < 1.0,
                                   0.5 * (x - y) ** 2,
                                   np.abs(x - y) - 0.5)),
     [_sp(3, 4), _sp(3, 4, seed=1)], {}),
    ("bce_with_logits", F.binary_cross_entropy_with_logits,
     lambda x, y: np.mean(np.maximum(x, 0) - x * y + np.log1p(
         np.exp(-np.abs(x)))),
     [_sp(3, 4), (_sp(3, 4, seed=1) > 0).astype(np.float32)], {}),
    ("kl_div", lambda a, b: F.kl_div(a, b, reduction="mean"),
     lambda a, b: np.mean(b * (np.log(b) - a)),
     [np.log(_sp(3, 4, pos=True) / 4), _sp(3, 4, seed=1, pos=True) / 4],
     {"grad": False}),
    ("cosine_similarity", F.cosine_similarity,
     lambda a, b: (a * b).sum(-1) / (
         np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1)),
     [_sp(3, 4), _sp(3, 4, seed=1)], {}),
]


def _conv2d_np(x, w):
    N, C, H, W = x.shape
    O, _, kh, kw = w.shape
    out = np.zeros((N, O, H - kh + 1, W - kw + 1), np.float64)
    for i in range(H - kh + 1):
        for j in range(W - kw + 1):
            patch = x[:, :, i:i + kh, j:j + kw]
            out[:, :, i, j] = np.tensordot(patch, w, ([1, 2, 3], [1, 2, 3]))
    return out


def _pool2_np(x, red):
    N, C, H, W = x.shape
    return red(x.reshape(N, C, H // 2, 2, W // 2, 2), (3, 5))


OPS += [
    # -- conv / pool / norm / resize ---------------------------------------
    ("conv2d", F.conv2d, _conv2d_np,
     [_sp(1, 2, 5, 5), _sp(3, 2, 3, 3, seed=1)],
     {"bf16_atol": 5e-2, "bf16_rtol": 5e-2, "atol": 1e-4, "rtol": 1e-4}),
    ("linear_wb", F.linear,
     lambda x, w, b: x @ w + b,
     [_sp(3, 4), _sp(4, 5, seed=1), _sp(5, seed=2)],
     {"bf16_atol": 5e-2, "bf16_rtol": 5e-2}),
    ("max_pool2d", lambda x: F.max_pool2d(x, 2),
     lambda x: _pool2_np(x, np.max), [_sp(1, 2, 4, 4)], {"grad": False}),
    ("avg_pool2d", lambda x: F.avg_pool2d(x, 2),
     lambda x: _pool2_np(x, np.mean), [_sp(1, 2, 4, 4)], {}),
    ("adaptive_avg_pool2d_1", lambda x: F.adaptive_avg_pool2d(x, 1),
     lambda x: x.mean((2, 3), keepdims=True), [_sp(1, 2, 4, 4)], {}),
    ("layer_norm", lambda x: F.layer_norm(x, 4),
     lambda x: (x - x.mean(-1, keepdims=True)) / np.sqrt(
         x.var(-1, keepdims=True) + 1e-5),
     [_sp(3, 4)], {"grad_atol": 2e-2}),
    ("normalize_l2", F.normalize,
     lambda x: x / np.maximum(
         np.linalg.norm(x, axis=-1, keepdims=True), 1e-12),
     [_sp(3, 4)], {}),
    ("interp_nearest",
     lambda x: F.interpolate(x, scale_factor=2, mode="nearest"),
     lambda x: x.repeat(2, axis=2).repeat(2, axis=3),
     [_sp(1, 2, 3, 3)], {}),
    ("pixel_shuffle", lambda x: F.pixel_shuffle(x, 2),
     lambda x: x.reshape(1, 1, 2, 2, 3, 3).transpose(
         0, 1, 4, 2, 5, 3).reshape(1, 1, 6, 6),
     [_sp(1, 4, 3, 3)], {}),
    ("unfold3", lambda x: pt.unsqueeze(F.unfold(x, 3), 0).squeeze(0),
     lambda x: np.stack(
         [x[0, :, i:i + 3, j:j + 3].reshape(-1)
          for i in range(2) for j in range(2)], -1)[None],
     [_sp(1, 2, 4, 4)], {"grad": False}),
    # -- round-4 long tail --------------------------------------------------
    ("addmm", lambda i, a, b: pt.addmm(i, a, b, beta=0.5, alpha=2.0),
     lambda i, a, b: 0.5 * i + 2.0 * (a @ b),
     [_sp(2, 5), _sp(2, 3), _sp(3, 5, seed=1)],
     {"bf16_atol": 5e-2, "bf16_rtol": 5e-2}),
    ("diff", pt.diff, lambda x: np.diff(x), [_sp(3, 5)], {}),
    ("diff_n2_ax0", lambda x: pt.diff(x, n=2, axis=0),
     lambda x: np.diff(x, n=2, axis=0), [_sp(4, 3)], {}),
    ("trapezoid", pt.trapezoid,
     lambda y: np.trapz(y, axis=-1), [_sp(3, 5)], {}),
    ("trapezoid_x", pt.trapezoid,
     lambda y, x: np.trapz(y, x=np.sort(x), axis=-1),
     [_sp(3, 5), np.sort(_sp(5, seed=3))], {"grad": False}),
    ("cumulative_trapezoid", pt.cumulative_trapezoid,
     lambda y: np.stack([np.cumsum((y[..., :-1] + y[..., 1:]) * 0.5,
                                   axis=-1)])[0],
     [_sp(3, 5)], {}),
    ("vander", lambda x: pt.vander(x, n=4),
     lambda x: np.vander(x, N=4), [_sp(5, lo=0.5, hi=2.0)],
     {"grad": False, "atol": 1e-4, "rtol": 1e-4, "bf16_atol": 2e-1,
      "bf16_rtol": 2e-1}),
    ("cdist", pt.linalg.cdist,
     lambda a, b: np.sqrt(((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)),
     [_sp(4, 3), _sp(5, 3, seed=1)],
     {"atol": 1e-4, "rtol": 1e-4, "grad_atol": 2e-2,
      "bf16_atol": 1e-1, "bf16_rtol": 1e-1}),
    ("cdist_p1", lambda a, b: pt.linalg.cdist(a, b, p=1.0),
     lambda a, b: np.abs(a[:, None, :] - b[None, :, :]).sum(-1),
     [_sp(4, 3), _sp(5, 3, seed=1)], {"grad": False}),
    ("reverse", lambda x: pt.reverse(x, [0]),
     lambda x: x[::-1], [_sp(3, 4)], {}),
]

_IDS = [row[0] for row in OPS]


@pytest.mark.parametrize("name,op,ref,inputs,opts", OPS, ids=_IDS)
def test_output_float32(name, op, ref, inputs, opts):
    check_output(op, ref, inputs,
                 atol=opts.get("atol", 1e-5), rtol=opts.get("rtol", 1e-5))


@pytest.mark.parametrize("name,op,ref,inputs,opts", OPS, ids=_IDS)
def test_output_bfloat16(name, op, ref, inputs, opts):
    """bf16 inputs vs the float32 numpy oracle at the op's bf16
    tolerance (default 2e-2 — one bf16 ulp at unit scale)."""
    tensors = [Tensor(jnp.asarray(a).astype(jnp.bfloat16)) for a in inputs]
    out = op(*tensors)
    out = out[0] if isinstance(out, (list, tuple)) else out
    got = np.asarray(out._data.astype(jnp.float32), dtype=np.float64)
    want = np.asarray(ref(*[np.asarray(a) for a in inputs]),
                      dtype=np.float64)
    np.testing.assert_allclose(
        got, want, atol=opts.get("bf16_atol", 2e-2),
        rtol=opts.get("bf16_rtol", 2e-2), err_msg=f"bf16 {name}")


@pytest.mark.parametrize(
    "name,op,ref,inputs,opts",
    [row for row in OPS if row[4].get("grad", True)],
    ids=[row[0] for row in OPS if row[4].get("grad", True)])
def test_grad_float32(name, op, ref, inputs, opts):
    check_grad(op, inputs, atol=opts.get("grad_atol", 5e-3),
               rtol=opts.get("grad_rtol", opts.get("grad_atol", 5e-3)))
