"""Higher-order eager autograd: create_graph=True (ref:
``paddle/fluid/prim/`` double-grad, ``incubate/autograd/primapi.py:220``).
Oracles are analytic derivatives."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import autograd


def _t(a):
    t = pt.to_tensor(np.asarray(a, np.float32))
    t.stop_gradient = False
    return t


def test_second_derivative_cubic():
    x = _t([1.0, 2.0, -3.0])
    y = (x ** 3).sum()
    (g,) = autograd.grad(y, x, create_graph=True)
    np.testing.assert_allclose(np.asarray(g._data),
                               3 * np.array([1., 4., 9.]), rtol=1e-6)
    (g2,) = autograd.grad(g.sum(), x)
    np.testing.assert_allclose(np.asarray(g2._data),
                               6 * np.array([1., 2., -3.]), rtol=1e-6)


def test_third_derivative():
    x = _t([2.0])
    y = x ** 4
    (g1,) = autograd.grad(y, x, create_graph=True)      # 4x^3 = 32
    (g2,) = autograd.grad(g1, x, create_graph=True)     # 12x^2 = 48
    (g3,) = autograd.grad(g2, x)                        # 24x = 48
    assert abs(float(g1) - 32) < 1e-4
    assert abs(float(g2) - 48) < 1e-4
    assert abs(float(g3) - 48) < 1e-4


def test_mixed_partial():
    x = _t([3.0])
    ybar = _t([5.0])
    f = (x ** 2) * ybar                                  # x^2 y
    (gx,) = autograd.grad(f, x, create_graph=True)       # 2xy = 30
    (gxy,) = autograd.grad(gx, ybar)                     # d(2xy)/dy = 2x
    assert abs(float(gx) - 30) < 1e-4
    assert abs(float(gxy) - 6) < 1e-4


def test_backward_through_taped_grad():
    """Gradient-penalty pattern: backward() through a create_graph grad
    accumulates d/dx of |df/dx|^2 into x.grad = 2 f'(x) f''(x)."""
    x = _t([2.0])
    y = (x ** 3).sum()                                   # f' = 3x^2=12, f''=6x=12
    (g,) = autograd.grad(y, x, create_graph=True)
    penalty = (g ** 2).sum()
    penalty.backward()
    np.testing.assert_allclose(float(x.grad), 2 * 12 * 12, rtol=1e-5)


def test_second_derivative_through_nn_ops():
    """tanh has well-known f'' = -2 tanh (1 - tanh^2)."""
    x = _t([0.5, -0.7])
    y = pt.nn.functional.tanh(x).sum()
    (g,) = autograd.grad(y, x, create_graph=True)
    (g2,) = autograd.grad(g.sum(), x)
    th = np.tanh([0.5, -0.7])
    np.testing.assert_allclose(np.asarray(g._data), 1 - th ** 2, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g2._data),
                               -2 * th * (1 - th ** 2), rtol=1e-4)


def test_first_order_paths_unchanged():
    """create_graph=False remains the plain fast path (grads constant)."""
    x = _t([1.5])
    y = (x ** 2).sum()
    (g,) = autograd.grad(y, x)
    assert g.stop_gradient
    assert g._node is None


class TestJacobianHessian:
    """paddle.autograd.jacobian / hessian (ref autograd/autograd.py:450,
    :542): lazy row-cached objects over the tape."""

    def test_jacobian_vector(self):
        x = pt.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        x.stop_gradient = False
        y = x * x  # dy_i/dx_j = diag(2x)
        J = pt.autograd.jacobian(y, x)
        got = np.asarray(J[:])
        np.testing.assert_allclose(got, np.diag([2.0, 4.0, 6.0]),
                                   atol=1e-6)
        assert J.shape == [3, 3]

    def test_jacobian_batched(self):
        rs = np.random.RandomState(0)
        A = rs.randn(4, 2).astype(np.float32)
        x = pt.to_tensor(rs.randn(3, 4).astype(np.float32))
        x.stop_gradient = False
        y = pt.matmul(x, pt.to_tensor(A))          # [3, 2]
        J = pt.autograd.jacobian(y, x, batch_axis=0)
        got = np.asarray(J[:])                      # [3, 2, 4]
        want = np.broadcast_to(A.T, (3, 2, 4))
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_jacobian_tuple_inputs(self):
        a = pt.to_tensor(np.array([1.0, 2.0], np.float32))
        b = pt.to_tensor(np.array([3.0], np.float32))
        a.stop_gradient = b.stop_gradient = False
        y = pt.concat([a * 2.0, b * 5.0])
        Ja, Jb = pt.autograd.jacobian(y, (a, b))
        np.testing.assert_allclose(np.asarray(Ja[:]),
                                   [[2, 0], [0, 2], [0, 0]], atol=1e-6)
        np.testing.assert_allclose(np.asarray(Jb[:]),
                                   [[0], [0], [5]], atol=1e-6)

    def test_hessian_quadratic(self):
        # f(x) = x^T A x  =>  H = A + A^T
        A = np.array([[2.0, 1.0], [0.0, 3.0]], np.float32)
        x = pt.to_tensor(np.array([1.5, -0.5], np.float32))
        x.stop_gradient = False
        y = pt.sum(x * pt.matmul(pt.to_tensor(A), x))
        H = pt.autograd.hessian(y, x)
        np.testing.assert_allclose(np.asarray(H[:]), A + A.T, atol=1e-5)

    def test_hessian_rejects_vector_ys(self):
        x = pt.to_tensor(np.ones(3, np.float32))
        x.stop_gradient = False
        with pytest.raises(ValueError, match="scalar"):
            pt.autograd.hessian(x * x, x)


class TestSavedTensorsHooks:
    def test_pack_unpack_round_trip_and_call_counts(self):
        packed, unpacked = [], []

        def pack(t):
            packed.append(tuple(t.shape))
            return np.asarray(t._data)  # "offload to host"

        def unpack(p):
            unpacked.append(p.shape)
            return pt.to_tensor(p)

        x = pt.to_tensor(np.full((2, 2), 3.0, np.float32))
        x.stop_gradient = False
        with pt.autograd.saved_tensors_hooks(pack, unpack):
            y = x * x
        (y.sum()).backward()
        assert packed and unpacked  # both hooks actually ran
        np.testing.assert_allclose(x.grad.numpy(),
                                   np.full((2, 2), 6.0), atol=1e-6)

    def test_pylayer_saved_tensor_routes_through_hooks(self):
        seen = []

        class Sq(pt.autograd.PyLayer):
            @staticmethod
            def forward(ctx, a):
                ctx.save_for_backward(a)
                return a * a

            @staticmethod
            def backward(ctx, g):
                (a,) = ctx.saved_tensor
                return g * a * 2.0

        def pack(t):
            seen.append("pack")
            return t

        def unpack(p):
            seen.append("unpack")
            return p

        x = pt.to_tensor(np.array([2.0], np.float32))
        x.stop_gradient = False
        with pt.autograd.saved_tensors_hooks(pack, unpack):
            y = Sq.apply(x)
        y.backward()
        assert "pack" in seen and "unpack" in seen
        np.testing.assert_allclose(x.grad.numpy(), [4.0], atol=1e-6)


def test_jacobian_lazy_rows_and_hooks_with_create_graph():
    # laziness: indexing one row must evaluate exactly one row
    x = pt.to_tensor(np.arange(1.0, 6.0, dtype=np.float32))
    x.stop_gradient = False
    y = x * x
    J = pt.autograd.jacobian(y, x)
    _ = np.asarray(J[2]._data if hasattr(J[2], "_data") else J[2])
    assert len(J._rows) == 1
    _ = J[1:3]
    assert len(J._rows) == 2  # row 2 cached, row 1 new
    # hooks + create_graph (hessian) must unpack packed datas
    calls = []

    def pack(t):
        calls.append("p")
        return np.asarray(t._data)

    def unpack(p):
        calls.append("u")
        return pt.to_tensor(p)

    x2 = pt.to_tensor(np.array([1.0, 2.0], np.float32))
    x2.stop_gradient = False
    with pt.autograd.saved_tensors_hooks(pack, unpack):
        y2 = pt.sum(x2 * x2 * x2)
    H = np.asarray(pt.autograd.hessian(y2, x2)[:])
    np.testing.assert_allclose(H, np.diag([6.0, 12.0]), atol=1e-5)
    assert "p" in calls and "u" in calls
