"""Higher-order eager autograd: create_graph=True (ref:
``paddle/fluid/prim/`` double-grad, ``incubate/autograd/primapi.py:220``).
Oracles are analytic derivatives."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import autograd


def _t(a):
    t = pt.to_tensor(np.asarray(a, np.float32))
    t.stop_gradient = False
    return t


def test_second_derivative_cubic():
    x = _t([1.0, 2.0, -3.0])
    y = (x ** 3).sum()
    (g,) = autograd.grad(y, x, create_graph=True)
    np.testing.assert_allclose(np.asarray(g._data),
                               3 * np.array([1., 4., 9.]), rtol=1e-6)
    (g2,) = autograd.grad(g.sum(), x)
    np.testing.assert_allclose(np.asarray(g2._data),
                               6 * np.array([1., 2., -3.]), rtol=1e-6)


def test_third_derivative():
    x = _t([2.0])
    y = x ** 4
    (g1,) = autograd.grad(y, x, create_graph=True)      # 4x^3 = 32
    (g2,) = autograd.grad(g1, x, create_graph=True)     # 12x^2 = 48
    (g3,) = autograd.grad(g2, x)                        # 24x = 48
    assert abs(float(g1) - 32) < 1e-4
    assert abs(float(g2) - 48) < 1e-4
    assert abs(float(g3) - 48) < 1e-4


def test_mixed_partial():
    x = _t([3.0])
    ybar = _t([5.0])
    f = (x ** 2) * ybar                                  # x^2 y
    (gx,) = autograd.grad(f, x, create_graph=True)       # 2xy = 30
    (gxy,) = autograd.grad(gx, ybar)                     # d(2xy)/dy = 2x
    assert abs(float(gx) - 30) < 1e-4
    assert abs(float(gxy) - 6) < 1e-4


def test_backward_through_taped_grad():
    """Gradient-penalty pattern: backward() through a create_graph grad
    accumulates d/dx of |df/dx|^2 into x.grad = 2 f'(x) f''(x)."""
    x = _t([2.0])
    y = (x ** 3).sum()                                   # f' = 3x^2=12, f''=6x=12
    (g,) = autograd.grad(y, x, create_graph=True)
    penalty = (g ** 2).sum()
    penalty.backward()
    np.testing.assert_allclose(float(x.grad), 2 * 12 * 12, rtol=1e-5)


def test_second_derivative_through_nn_ops():
    """tanh has well-known f'' = -2 tanh (1 - tanh^2)."""
    x = _t([0.5, -0.7])
    y = pt.nn.functional.tanh(x).sum()
    (g,) = autograd.grad(y, x, create_graph=True)
    (g2,) = autograd.grad(g.sum(), x)
    th = np.tanh([0.5, -0.7])
    np.testing.assert_allclose(np.asarray(g._data), 1 - th ** 2, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g2._data),
                               -2 * th * (1 - th ** 2), rtol=1e-4)


def test_first_order_paths_unchanged():
    """create_graph=False remains the plain fast path (grads constant)."""
    x = _t([1.5])
    y = (x ** 2).sum()
    (g,) = autograd.grad(y, x)
    assert g.stop_gradient
    assert g._node is None
