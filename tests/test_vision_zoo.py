"""Vision model zoo completion tests (ref: test/legacy_test/
test_vision_models.py pattern: construct each family, forward a small
batch, check logits shape)."""
from __future__ import annotations

import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # model-zoo tier: run with -m slow

import paddle_tpu as pt

M = pt.vision.models


def _x(size=64, batch=1):
    return pt.to_tensor(np.random.RandomState(0)
                        .randn(batch, 3, size, size).astype(np.float32))


CASES = [
    ("densenet121", lambda: M.densenet121(num_classes=7), 64),
    ("squeezenet1_1", lambda: M.squeezenet1_1(num_classes=7), 64),
    ("mobilenet_v1", lambda: M.mobilenet_v1(num_classes=7), 64),
    ("mobilenet_v3_small", lambda: M.mobilenet_v3_small(num_classes=7), 64),
    ("shufflenet_v2_x0_25", lambda: M.shufflenet_v2_x0_25(num_classes=7),
     64),
    ("inception_v3", lambda: M.inception_v3(num_classes=7), 96),
]


class TestZooForward:
    @pytest.mark.parametrize("name,ctor,size", CASES,
                             ids=[c[0] for c in CASES])
    def test_forward_shape(self, name, ctor, size):
        pt.seed(0)
        m = ctor()
        m.eval()
        out = m(_x(size))
        assert out.shape == [1, 7]
        assert np.isfinite(out.numpy()).all()

    def test_googlenet_aux_heads(self):
        pt.seed(0)
        g = M.googlenet(num_classes=7)
        g.eval()
        out, aux1, aux2 = g(_x(96))
        assert out.shape == aux1.shape == aux2.shape == [1, 7]

    def test_mobilenet_v3_trains(self):
        pt.seed(0)
        m = M.mobilenet_v3_small(num_classes=4, scale=0.35)
        opt = pt.optimizer.SGD(learning_rate=0.05,
                               parameters=m.parameters())
        X = _x(32, batch=4)
        Y = pt.to_tensor(np.array([0, 1, 2, 3]))
        losses = []
        for _ in range(4):
            loss = pt.nn.CrossEntropyLoss()(m(X), Y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]


class TestDatasetFolder:
    def _make_tree(self, root):
        for cls in ("cat", "dog"):
            d = os.path.join(root, cls)
            os.makedirs(d)
            for i in range(3):
                np.save(os.path.join(d, f"{i}.npy"),
                        np.full((8, 8, 3), ord(cls[0]), np.uint8))

    def test_dataset_folder(self, tmp_path):
        self._make_tree(str(tmp_path))
        ds = pt.vision.datasets.DatasetFolder(str(tmp_path))
        assert ds.classes == ["cat", "dog"]
        assert len(ds) == 6
        img, label = ds[0]
        assert img.shape == (8, 8, 3) and label == 0
        img, label = ds[5]
        assert label == 1

    def test_image_folder(self, tmp_path):
        self._make_tree(str(tmp_path))
        ds = pt.vision.datasets.ImageFolder(str(tmp_path))
        assert len(ds) == 6
        (img,) = ds[0]
        assert img.shape == (8, 8, 3)

    def test_transform_applied(self, tmp_path):
        self._make_tree(str(tmp_path))
        T = pt.vision.transforms
        ds = pt.vision.datasets.DatasetFolder(
            str(tmp_path), transform=T.Compose([T.ToTensor()]))
        img, _ = ds[0]
        assert list(img.shape) == [3, 8, 8]

    def test_empty_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            pt.vision.datasets.DatasetFolder(str(tmp_path))
