"""Eager autograd tape (ref model: eager backward tests in
test/legacy_test; engine re-design documented in paddle_tpu/autograd.py)."""
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import Tensor, to_tensor


class TestBackward:
    def test_simple_chain(self):
        x = to_tensor([2.0, 3.0], stop_gradient=False)
        y = x * x + 1.0
        loss = y.sum()
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])

    def test_two_branches(self):
        x = to_tensor([1.0, 2.0], stop_gradient=False)
        a = x * 2.0
        b = x * 3.0
        loss = (a + b).sum()
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])

    def test_matmul_grad(self):
        a = to_tensor(np.ones((2, 3), np.float32), stop_gradient=False)
        b = to_tensor(np.ones((3, 4), np.float32), stop_gradient=False)
        loss = paddle_tpu.matmul(a, b).sum()
        loss.backward()
        np.testing.assert_allclose(a.grad.numpy(), 4 * np.ones((2, 3)))
        np.testing.assert_allclose(b.grad.numpy(), 2 * np.ones((3, 4)))

    def test_grad_accumulation(self):
        x = to_tensor([1.0], stop_gradient=False)
        (x * 2).backward()
        (x * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0])
        x.clear_grad()
        assert x.grad is None

    def test_stop_gradient_cuts(self):
        x = to_tensor([1.0], stop_gradient=False)
        y = to_tensor([2.0], stop_gradient=True)
        loss = (x * y).sum()
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])
        assert y.grad is None

    def test_detach_cuts(self):
        x = to_tensor([3.0], stop_gradient=False)
        y = (x * 2).detach()
        z = y * x
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])

    def test_no_grad_context(self):
        x = to_tensor([1.0], stop_gradient=False)
        with paddle_tpu.no_grad():
            y = x * 5
        assert y.stop_gradient
        assert y._node is None

    def test_nonscalar_backward_raises(self):
        x = to_tensor([1.0, 2.0], stop_gradient=False)
        with pytest.raises(RuntimeError):
            (x * 2).backward()

    def test_backward_with_grad_tensor(self):
        x = to_tensor([1.0, 2.0], stop_gradient=False)
        y = x * 3
        y.backward(to_tensor([1.0, 10.0]))
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 30.0])

    def test_double_backward_raises_without_retain(self):
        x = to_tensor([1.0], stop_gradient=False)
        y = (x * x).sum()
        y.backward(retain_graph=True)
        y.backward(retain_graph=False)  # second ok because retained first
        x.clear_grad()
        z = (x * x).sum()
        z.backward()
        with pytest.raises(RuntimeError):
            z.backward()

    def test_multi_output_op_grad(self):
        x = to_tensor([3.0, 1.0, 2.0], stop_gradient=False)
        v, i = paddle_tpu.topk(x, 2)
        v.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 0.0, 1.0])

    def test_broadcast_grad(self):
        x = to_tensor(np.ones((2, 3), np.float32), stop_gradient=False)
        b = to_tensor(np.ones((3,), np.float32), stop_gradient=False)
        (x + b).sum().backward()
        np.testing.assert_allclose(b.grad.numpy(), [2.0, 2.0, 2.0])

    def test_deep_chain(self):
        x = to_tensor([1.0], stop_gradient=False)
        y = x
        for _ in range(50):
            y = y * 1.1
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.1 ** 50], rtol=1e-4)

    def test_paddle_grad_api(self):
        x = to_tensor([2.0], stop_gradient=False)
        y = x * x
        (gx,) = paddle_tpu.grad(y, x)
        np.testing.assert_allclose(gx.numpy(), [4.0])
        assert x.grad is None  # .grad untouched

    def test_register_hook(self):
        x = to_tensor([1.0], stop_gradient=False)
        seen = []
        h = x.register_hook(lambda g: seen.append(g.numpy()) or g * 2)
        (x * 3).sum().backward()
        assert len(seen) == 1
        np.testing.assert_allclose(x.grad.numpy(), [6.0])
        h.remove()

    def test_int_op_no_grad_path(self):
        x = to_tensor([1.0, 5.0, 3.0], stop_gradient=False)
        am = paddle_tpu.argmax(x)
        assert am.item() == 1  # int output, no crash in tape


class TestPyLayer:
    def test_custom_forward_backward(self):
        class Double(paddle_tpu.autograd.PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * 2

            @staticmethod
            def backward(ctx, g):
                return g * 2

        x = to_tensor([1.0, 2.0], stop_gradient=False)
        y = Double.apply(x)
        np.testing.assert_allclose(y.numpy(), [2.0, 4.0])
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


class TestOpTestHarness:
    def test_check_output_and_grad(self):
        from op_test import check_output, check_grad
        check_output(paddle_tpu.tanh, np.tanh, [np.random.rand(3, 4)])
        check_grad(paddle_tpu.tanh, [np.random.rand(2, 3)])

    def test_binary_grad(self):
        from op_test import check_grad
        a = np.random.rand(2, 2) + 0.5
        b = np.random.rand(2, 2) + 0.5
        check_grad(paddle_tpu.multiply, [a, b])
        check_grad(paddle_tpu.divide, [a, b])


class TestIncubateAutograd:
    """ref: python/paddle/incubate/autograd functional.py jvp/vjp/Jacobian."""

    def test_jvp_vjp(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.incubate.autograd import jvp, vjp

        def f(x):
            return (x ** 2).sum()

        x = pt.to_tensor(np.array([1., 2., 3.], np.float32))
        out, tangent = jvp(f, [x], [pt.to_tensor(np.ones(3, np.float32))])
        np.testing.assert_allclose(float(out.numpy()), 14.0)
        np.testing.assert_allclose(float(tangent.numpy()), 12.0)  # sum(2x)
        out, grads = vjp(f, [x])
        np.testing.assert_allclose(grads[0].numpy(), [2., 4., 6.])

    def test_jacobian_hessian(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.incubate.autograd import Jacobian, Hessian

        def f(x):
            return x ** 3

        x = pt.to_tensor(np.array([1., 2.], np.float32))
        J = Jacobian(f, [x])
        np.testing.assert_allclose(np.asarray(J[0].numpy()),
                                   np.diag([3., 12.]), rtol=1e-5)

        def g(x):
            return (x ** 3).sum()

        H = Hessian(g, [x])
        h = np.asarray(H.value[0][0].numpy())
        np.testing.assert_allclose(h, np.diag([6., 12.]), rtol=1e-5)
