"""Cluster observability units: exposition parsing, cross-rank merge
semantics, skew/percentile math, the in-process aggregator end-to-end
(real MetricsServers scraped over HTTP), the telemetry-JSONL merge CLI,
store-key convention pins, and metric-series identity labels.

The multi-PROCESS acceptance drills live in
tests/drills/test_scrape_drills.py; everything here is in-process and
fast."""
from __future__ import annotations

import io
import json
import os
import re
import sys
import time

import pytest

import paddle_tpu.observability as obs
from paddle_tpu.observability import (
    ClusterAggregator, EventSink, MergeConflict, MetricsRegistry,
    MetricsServer, cluster_snapshot, get_registry, get_telemetry,
    merge_scrapes, parse_prometheus_text, render_exposition,
)
from paddle_tpu.observability.aggregator import (
    bucket_percentile, endpoint_key, world_key,
)
from paddle_tpu.observability import merge as merge_cli


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    for var in ("PT_TELEMETRY", "PT_TELEMETRY_DIR", "PT_METRICS_PORT",
                "PT_RECOMPILE_THRESHOLD", "PT_PROCESS_INDEX",
                "PT_RUN_ID", "PADDLE_TRAINER_ID"):
        monkeypatch.delenv(var, raising=False)
    obs.reset()
    yield
    obs.reset()


def _registry_text(rank, run_id="r1", steps=5, step_ms=10.0):
    """One rank's realistic exposition text: identity const labels,
    a counter, a histogram, and a per-rank gauge."""
    reg = MetricsRegistry()
    reg.set_const_labels(process_index=rank, run_id=run_id)
    reg.counter("pt_steps_total", "steps", ("mode",)).inc(
        steps, mode="train")
    h = reg.histogram("pt_step_time_seconds", "step time", ("mode",),
                      buckets=[0.005, 0.05, 0.5])
    for _ in range(steps):
        h.observe(step_ms / 1e3, mode="train")
    reg.gauge("pt_throughput_samples_per_second", "tput",
              ("mode",)).set(100.0 / (rank + 1), mode="train")
    return reg.prometheus_text()


# -- exposition parsing ------------------------------------------------------

def test_parse_round_trips_registry_output():
    text = _registry_text(0)
    fams = parse_prometheus_text(text)
    assert fams["pt_steps_total"]["kind"] == "counter"
    assert fams["pt_step_time_seconds"]["kind"] == "histogram"
    # histogram children folded into the base family
    assert "pt_step_time_seconds_bucket" not in fams
    names = {s[0] for s in fams["pt_step_time_seconds"]["samples"]}
    assert names == {"pt_step_time_seconds_bucket",
                     "pt_step_time_seconds_sum",
                     "pt_step_time_seconds_count"}
    (sname, labels, value), = fams["pt_steps_total"]["samples"]
    assert labels == {"mode": "train", "process_index": "0",
                      "run_id": "r1"}
    assert value == 5.0


def test_parse_label_escapes_and_inf():
    text = ('# TYPE weird gauge\n'
            'weird{msg="a\\"b\\\\c\\nd",le="+Inf"} 3\n')
    fams = parse_prometheus_text(text)
    (_, labels, value), = fams["weird"]["samples"]
    assert labels["msg"] == 'a"b\\c\nd'
    assert value == 3.0


def test_parse_rejects_malformed_lines():
    with pytest.raises(ValueError):
        parse_prometheus_text("this is not exposition format\n")
    with pytest.raises(ValueError):
        parse_prometheus_text("ok_metric not-a-number\n")


# -- merge semantics ---------------------------------------------------------

def test_merge_sums_counters_dropping_process_index():
    scrapes = {r: parse_prometheus_text(_registry_text(r, steps=5))
               for r in range(3)}
    merged, conflicts = merge_scrapes(scrapes)
    assert conflicts == []
    series = merged["pt_steps_total"]["series"]
    key = (("mode", "train"), ("run_id", "r1"))
    assert series == {key: 15.0}  # summed, process_index dropped


def test_merge_sums_histogram_buckets():
    scrapes = {r: parse_prometheus_text(
        _registry_text(r, steps=4, step_ms=10.0)) for r in range(2)}
    merged, _ = merge_scrapes(scrapes)
    (h,) = merged["pt_step_time_seconds"]["series"].values()
    assert h["count"] == 8.0
    assert h["buckets"][float("inf")] == 8.0
    assert h["buckets"][0.05] == 8.0   # every 10ms sample <= 50ms
    assert h["buckets"][0.005] == 0.0
    assert h["sum"] == pytest.approx(8 * 0.010)


def test_merge_rejects_mismatched_bucket_layouts():
    def one(buckets):
        reg = MetricsRegistry()
        reg.histogram("h_seconds", "h", buckets=buckets).observe(0.1)
        return parse_prometheus_text(reg.prometheus_text())

    with pytest.raises(MergeConflict):
        merge_scrapes({0: one([0.1, 1.0]), 1: one([0.2, 1.0])})
    # on_conflict="skip": the whole family is dropped, not half-merged
    merged, conflicts = merge_scrapes(
        {0: one([0.1, 1.0]), 1: one([0.2, 1.0])}, on_conflict="skip")
    assert "h_seconds" not in merged
    assert len(conflicts) == 1 and "bucket layouts" in conflicts[0]


def test_merge_keeps_gauges_per_rank_and_rejects_collisions():
    scrapes = {r: parse_prometheus_text(_registry_text(r))
               for r in range(2)}
    merged, _ = merge_scrapes(scrapes)
    series = merged["pt_throughput_samples_per_second"]["series"]
    assert len(series) == 2  # one labeled series per rank
    by_rank = {dict(k)["process_index"]: v for k, v in series.items()}
    assert by_rank == {"0": 100.0, "1": 50.0}

    # identical label sets from two scrapes would last-write-win:
    # that is a conflict, not a merge
    same = parse_prometheus_text("# TYPE g gauge\ng 1\n")
    same2 = parse_prometheus_text("# TYPE g gauge\ng 2\n")
    with pytest.raises(MergeConflict):
        merge_scrapes({0: same, 1: same2})


def test_merge_rejects_kind_mismatch():
    a = parse_prometheus_text("# TYPE m counter\nm 1\n")
    b = parse_prometheus_text("# TYPE m gauge\nm 1\n")
    with pytest.raises(MergeConflict):
        merge_scrapes({0: a, 1: b})
    merged, conflicts = merge_scrapes({0: a, 1: b}, on_conflict="skip")
    assert "m" not in merged and len(conflicts) == 1


def test_merged_output_is_valid_exposition():
    """The aggregated view must itself satisfy the exposition-format
    validator (round-trip through the parser proves it)."""
    scrapes = {r: parse_prometheus_text(_registry_text(r))
               for r in range(3)}
    merged, _ = merge_scrapes(scrapes)
    text = render_exposition(merged)
    again = parse_prometheus_text(text)  # would raise on bad lines
    assert set(again) == set(merged)
    # cumulative-bucket contract survives the merge
    counts = [float(m.group(1)) for m in re.finditer(
        r'pt_step_time_seconds_bucket\{[^}]*\} ([0-9.]+)', text)]
    assert counts == sorted(counts)


def test_bucket_percentile_matches_histogram_percentile():
    reg = MetricsRegistry()
    h = reg.histogram("lat", "latency", buckets=[0.1, 1.0, 10.0])
    for v in [0.05] * 50 + [0.5] * 40 + [5.0] * 10:
        h.observe(v)
    fams = parse_prometheus_text(reg.prometheus_text())
    buckets, count = {}, 0.0
    for sname, labels, value in fams["lat"]["samples"]:
        if sname.endswith("_bucket"):
            le = float("inf") if labels["le"] == "+Inf" \
                else float(labels["le"])
            buckets[le] = value
        elif sname.endswith("_count"):
            count = value
    for q in (0.5, 0.9, 0.95, 0.999):
        assert bucket_percentile(buckets, count, q) == \
            pytest.approx(h.percentile(q))
    assert bucket_percentile({}, 0, 0.5) is None


# -- in-process aggregator ---------------------------------------------------

def _serve_rank(rank, run_id="agg", steps=6, step_ms=10.0, storms=0):
    reg = MetricsRegistry()
    reg.set_const_labels(process_index=rank, run_id=run_id)
    reg.counter("pt_steps_total", "steps", ("mode",)).inc(
        steps, mode="train")
    h = reg.histogram("pt_step_time_seconds", "t", ("mode",),
                      buckets=[0.005, 0.02, 0.05, 0.5])
    for _ in range(steps):
        h.observe(step_ms / 1e3, mode="train")
    if storms:
        reg.counter("pt_recompile_storms_total", "storms").inc(storms)
    srv = MetricsServer(reg, port=0).start()
    return srv


def test_aggregator_end_to_end_skew_storm_and_staleness():
    """Two REAL MetricsServers scraped over HTTP: merged counters,
    nonzero skew, straggler ratio, the cross-rank storm alarm (503
    semantics via healthz ok=False), then one server stops and must be
    marked stale — within bounded time, never hanging."""
    s0 = _serve_rank(0, step_ms=10.0, storms=1)
    s1 = _serve_rank(1, step_ms=30.0, storms=1)
    agg = ClusterAggregator(
        endpoints={0: f"127.0.0.1:{s0.port}",
                   1: f"127.0.0.1:{s1.port}"},
        stale_after=0.5, scrape_timeout=2.0, storm_threshold=2)
    try:
        t0 = time.monotonic()
        agg.scrape_once()
        assert time.monotonic() - t0 < 5.0
        text = agg.prometheus_text()
        fams = parse_prometheus_text(text)  # valid exposition

        def val(name, **labels):
            for f in fams.values():
                for sname, lbls, v in f["samples"]:
                    if sname == name and all(
                            lbls.get(k) == x
                            for k, x in labels.items()):
                        return v
            return None

        assert val("pt_cluster_ranks_up") == 2.0
        assert val("pt_steps_total", mode="train") == 12.0
        skew = val("pt_step_time_skew_seconds", mode="train")
        assert skew == pytest.approx(0.020, rel=0.2)
        assert val("pt_step_time_straggler_ratio", mode="train") > 1.0
        assert val("pt_cluster_recompile_storms_total") == 2.0
        assert val("pt_cluster_recompile_storm_alarm") == 1.0
        assert val("pt_rank_up", process_index="1") == 1.0
        # per-rank quantiles are first-class labeled series
        assert val("pt_rank_step_time_seconds", process_index="1",
                   quantile="p95") is not None
        health = agg.healthz()
        assert health["ok"] is False  # alarm up -> healthz 503
        assert health["storm_alarm"] is True
        assert health["ranks_up"] == 2
        assert health["step_time_skew_seconds"]["train"] > 0

        # rank 1 goes silent: bounded scrape, marked stale, dropped
        # from merges but still visible as pt_rank_up 0
        s1.stop()
        time.sleep(0.6)  # > stale_after
        t0 = time.monotonic()
        agg.scrape_once()
        assert time.monotonic() - t0 < 5.0
        fams = parse_prometheus_text(agg.prometheus_text())
        assert val("pt_cluster_ranks_up") == 1.0
        assert val("pt_rank_up", process_index="1") == 0.0
        assert val("pt_steps_total", mode="train") == 6.0
        health = agg.healthz()
        assert health["stale_ranks"] == [1]
        assert health["ranks"]["1"]["up"] is False
        assert health["scrape_errors_total"] >= 1
    finally:
        agg.stop()
        s0.stop()
        s1.stop()


def test_aggregator_healthz_503_through_metrics_server():
    """The aggregator's own serving contract: /healthz returns 503
    while the storm alarm is up (MetricsServer keys off ok=False)."""
    import urllib.error
    import urllib.request

    s0 = _serve_rank(0, storms=3)
    agg = ClusterAggregator(endpoints={0: f"127.0.0.1:{s0.port}"},
                            storm_threshold=1)
    agg.scrape_once()
    srv = MetricsServer(metrics_cb=agg.prometheus_text,
                        health_cb=agg.healthz, port=0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5)
        assert ei.value.code == 503
        body = json.loads(ei.value.read().decode())
        assert body["storm_alarm"] is True
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as r:
            text = r.read().decode()
        assert "pt_cluster_recompile_storm_alarm 1" in text
    finally:
        srv.stop()
        agg.stop()
        s0.stop()


def test_cluster_snapshot_local_mode_shape():
    tel = get_telemetry().enable(compile_watch=False, process_index=4,
                                 run_id="snaprun")
    tel.observe_step(0.01, mode="train", batch_size=8)
    snap = cluster_snapshot()
    assert snap["source"] == "local"
    assert snap["run_id"] == "snaprun"
    assert snap["ranks_up"] == 1
    assert snap["ranks"]["4"]["steps"] == 1
    assert snap["ranks"]["4"]["step_time"]["train"]["count"] == 1


def _serve_replica(rank, latencies, queue_depth=0, compiles=0,
                   run_id="srv"):
    """One serving replica's exposition: the request-latency histogram,
    queue-depth gauge, and unexpected-compile counter the engine books."""
    reg = MetricsRegistry()
    reg.set_const_labels(process_index=rank, run_id=run_id)
    h = reg.histogram("pt_serve_request_latency_seconds",
                      "End-to-end request latency",
                      buckets=[0.01, 0.05, 0.25, 1.0, 5.0])
    for v in latencies:
        h.observe(v)
    reg.gauge("pt_serve_queue_depth", "queue").set(queue_depth)
    if compiles:
        reg.counter("pt_serve_unexpected_compiles_total", "compiles",
                    ("fn",)).inc(compiles, fn="decode")
    return MetricsServer(reg, port=0).start()


def test_aggregator_serve_latency_queue_and_saturation_alarm():
    """Two serving replicas scraped over HTTP: merged p50/p99 from the
    summed bucket maps, fleet queue depth (sum + worst replica), the
    cross-rank unexpected-compile counter, and the saturation alarm
    (p99 >= PT_AGGREGATOR_SERVE_THRESHOLD -> healthz ok=False -> 503)."""
    import urllib.error
    import urllib.request

    # rank 0 fast, rank 1 saturated: merged p99 lands in the 5.0 bucket
    s0 = _serve_replica(0, [0.02] * 50, queue_depth=1)
    s1 = _serve_replica(1, [0.02] * 30 + [2.0] * 20, queue_depth=7,
                        compiles=2)
    agg = ClusterAggregator(
        endpoints={0: f"127.0.0.1:{s0.port}",
                   1: f"127.0.0.1:{s1.port}"},
        scrape_timeout=2.0, serve_threshold=1.0)
    srv = MetricsServer(metrics_cb=agg.prometheus_text,
                        health_cb=agg.healthz, port=0).start()
    try:
        agg.scrape_once()
        fams = parse_prometheus_text(agg.prometheus_text())

        def val(name, **labels):
            for f in fams.values():
                for sname, lbls, v in f["samples"]:
                    if sname == name and all(lbls.get(k) == x
                                             for k, x in labels.items()):
                        return v
            return None

        # 100 requests fleet-wide, 20 of them in the (1.0, 5.0] bucket:
        # p50 <= 0.05 while p99 is in the slow tail
        assert val("pt_cluster_serve_p50_seconds") <= 0.05
        p99 = val("pt_cluster_serve_p99_seconds")
        assert 1.0 < p99 <= 5.0
        assert val("pt_cluster_serve_queue_depth", stat="sum") == 8.0
        assert val("pt_cluster_serve_queue_depth", stat="max") == 7.0
        assert val("pt_cluster_serve_unexpected_compiles_total") == 2.0
        assert val("pt_cluster_serve_alarm") == 1.0

        health = agg.healthz()
        assert health["ok"] is False  # saturation -> 503
        assert health["serve"]["serve_alarm"] is True
        assert health["serve"]["requests_total"] == 100
        assert health["serve"]["queue_depth_sum"] == 8
        assert health["serve"]["queue_depth_max"] == 7
        assert health["serve"]["unexpected_compiles_total"] == 2
        assert health["serve"]["p99_seconds"] == pytest.approx(p99)

        # the re-served endpoint carries the 503 to the load balancer
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5)
        assert ei.value.code == 503
        assert json.loads(ei.value.read().decode())["serve"][
            "serve_alarm"] is True
    finally:
        srv.stop()
        agg.stop()
        s0.stop()
        s1.stop()


def test_aggregator_serve_quiet_fleet_and_below_threshold():
    # training-only rank: no serve families -> no serve series at all
    s0 = _serve_rank(0)
    agg = ClusterAggregator(endpoints={0: f"127.0.0.1:{s0.port}"},
                            serve_threshold=1.0)
    try:
        agg.scrape_once()
        text = agg.prometheus_text()
        assert "pt_cluster_serve_p99_seconds" not in text
        assert "pt_cluster_serve_queue_depth" not in text
        assert "pt_cluster_serve_unexpected_compiles_total" not in text
        assert "pt_cluster_serve_alarm 0" in text
        health = agg.healthz()
        assert health["ok"] is True
        assert health["serve"]["p99_seconds"] is None
        assert health["serve"]["queue_depth_sum"] is None
    finally:
        agg.stop()
        s0.stop()

    # healthy replica under the threshold: series present, no alarm
    s1 = _serve_replica(0, [0.02] * 40)
    agg2 = ClusterAggregator(endpoints={0: f"127.0.0.1:{s1.port}"},
                             serve_threshold=1.0)
    try:
        agg2.scrape_once()
        fams = parse_prometheus_text(agg2.prometheus_text())
        samples = [s for f in fams.values() for s in f["samples"]]
        p99 = [v for n, _, v in samples
               if n == "pt_cluster_serve_p99_seconds"]
        assert p99 and p99[0] <= 0.05
        assert agg2.healthz()["ok"] is True
        assert agg2.healthz()["serve"]["serve_alarm"] is False
    finally:
        agg2.stop()
        s1.stop()


# -- store key conventions ---------------------------------------------------

def test_obs_store_key_formats_pinned_equal():
    """core.store_server mirrors the aggregator's key formats without
    importing it (stdlib-only contract) — pin them equal forever."""
    from paddle_tpu.core import store_server as ss
    assert ss.obs_endpoint_key("run-x", 3) == endpoint_key("run-x", 3)
    assert ss.obs_world_key("run-x") == world_key("run-x")
    assert endpoint_key("r", 2) == "obs/r/endpoint/2"
    assert world_key("r") == "obs/r/world"


# -- identity: const labels, JSONL fields, filenames -------------------------

def test_identity_env_resolution(monkeypatch):
    monkeypatch.setenv("PT_PROCESS_INDEX", "7")
    monkeypatch.setenv("PT_RUN_ID", "envrun")
    obs.reset()
    tel = get_telemetry().enable(compile_watch=False)
    assert (tel.process_index, tel.run_id) == (7, "envrun")
    tel.observe_step(0.01)
    text = get_registry().prometheus_text()
    assert re.search(
        r'pt_steps_total\{mode="train",process_index="7",'
        r'run_id="envrun"\} 1\b', text)
    hz = tel.healthz()
    assert hz["process_index"] == 7 and hz["run_id"] == "envrun"


def test_paddle_trainer_id_fallback(monkeypatch):
    monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
    obs.reset()
    tel = get_telemetry()
    assert tel.process_index == 2 and tel.run_id == "local"


def test_event_sink_identity_filename_and_fields(tmp_path):
    sink = EventSink(str(tmp_path), run_id="abc/x", process_index=2)
    assert os.path.basename(sink.path) == "telemetry-abc_x-2.jsonl"
    sink.emit("step", idx=1)
    sink.close()
    (rec,) = [json.loads(l) for l in open(sink.path)]
    assert rec["process_index"] == 2 and rec["run_id"] == "abc/x"
    # legacy pid naming is untouched when identity is absent
    legacy = EventSink(str(tmp_path))
    assert f"-{os.getpid()}.jsonl" in legacy.path
    legacy.emit("e")
    legacy.close()
    (rec,) = [json.loads(l) for l in open(legacy.path)]
    assert "process_index" not in rec and "run_id" not in rec


# -- merge CLI ---------------------------------------------------------------

def _write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def test_merge_cli_orders_and_labels(tmp_path, capsys):
    d = str(tmp_path)
    _write_jsonl(os.path.join(d, "telemetry-run1-0.jsonl"), [
        {"ts": "2026-08-05T10:00:02.0", "event": "step", "step": 2},
        {"ts": "2026-08-05T10:00:04.0", "event": "step", "step": 4},
    ])
    _write_jsonl(os.path.join(d, "telemetry-run1-1.jsonl"), [
        {"ts": "2026-08-05T10:00:01.0", "event": "step", "step": 1,
         "process_index": 1, "run_id": "run1"},
        {"ts": "2026-08-05T10:00:03.0", "event": "step", "step": 3,
         "process_index": 1, "run_id": "run1"},
    ])
    # legacy pid-named file: identity stays null (a pid is NOT a rank)
    _write_jsonl(os.path.join(d, "telemetry-12345.jsonl"), [
        {"ts": "2026-08-05T10:00:00.5", "event": "boot"},
    ])
    # torn tail of a SIGKILLed rank: skipped, counted, never fatal
    with open(os.path.join(d, "telemetry-run1-0.jsonl"), "a") as f:
        f.write('{"ts": "2026-08-05T10:00:05.0", "event":')

    out = os.path.join(d, "merged.jsonl")
    rc = merge_cli.main([d, "--output", out])
    assert rc == 0
    assert "skipped 1" in capsys.readouterr().err
    recs = [json.loads(l) for l in open(out)]
    assert [r["ts"] for r in recs] == sorted(r["ts"] for r in recs)
    assert recs[0]["event"] == "boot"
    assert recs[0]["process_index"] is None  # legacy: no invented rank
    # filename-derived identity for rank 0, in-record for rank 1
    by_step = {r.get("step"): r for r in recs if "step" in r}
    assert by_step[2]["process_index"] == 0
    assert by_step[2]["run_id"] == "run1"
    assert by_step[1]["process_index"] == 1
    assert [by_step[i]["step"] for i in (1, 2, 3, 4)] == [1, 2, 3, 4]


def test_merge_cli_reads_rotated_generations_first(tmp_path):
    d = str(tmp_path)
    # rotated .1 file holds OLDER records with equal timestamps: the
    # stable (file, lineno) tiebreaker must keep it first
    _write_jsonl(os.path.join(d, "telemetry-r-0.jsonl.1"),
                 [{"ts": "2026-08-05T10:00:00", "event": "old"}])
    _write_jsonl(os.path.join(d, "telemetry-r-0.jsonl"),
                 [{"ts": "2026-08-05T10:00:00", "event": "new"}])
    files = merge_cli.discover_files([d])
    assert [os.path.basename(f) for f in files] == \
        ["telemetry-r-0.jsonl.1", "telemetry-r-0.jsonl"]
    records, skipped = merge_cli.merge_records(files)
    assert skipped == 0
    assert [r["event"] for r in records] == ["old", "new"]
    assert all(r["process_index"] == 0 and r["run_id"] == "r"
               for r in records)


def test_merge_cli_stdout_default(tmp_path, capsys):
    _write_jsonl(str(tmp_path / "telemetry-z-3.jsonl"),
                 [{"ts": "2026-08-05T11:00:00", "event": "e"}])
    rc = merge_cli.main([str(tmp_path)])
    assert rc == 0
    (line,) = [l for l in capsys.readouterr().out.splitlines() if l]
    assert json.loads(line)["process_index"] == 3
