"""paddle.distribution tests (ref: test/distribution/ test_distribution_*).

Oracles: closed-form moments, Monte-Carlo agreement between samples and
densities, and KL identities (KL(p,p)=0, KL vs numeric integral for 1-D).
"""
from __future__ import annotations

import math

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import distribution as D

SEED = 1234


def setup_module():
    pt.seed(SEED)


def mc_mean(dist, n=20000):
    return np.asarray(dist.sample([n]).numpy()).mean(axis=0)


class TestMoments:
    @pytest.mark.parametrize("dist,mean,var", [
        (lambda: D.Normal(1.5, 2.0), 1.5, 4.0),
        (lambda: D.Uniform(0.0, 4.0), 2.0, 16 / 12),
        (lambda: D.Bernoulli(probs=0.3), 0.3, 0.21),
        (lambda: D.Beta(2.0, 3.0), 0.4, 0.04),
        (lambda: D.Exponential(2.0), 0.5, 0.25),
        (lambda: D.Gamma(3.0, 2.0), 1.5, 0.75),
        (lambda: D.Laplace(0.5, 1.0), 0.5, 2.0),
        (lambda: D.Poisson(3.0), 3.0, 3.0),
        (lambda: D.Geometric(0.25), 3.0, 12.0),
        (lambda: D.LogNormal(0.0, 0.5),
         math.exp(0.125), (math.exp(0.25) - 1) * math.exp(0.25)),
    ])
    def test_mean_var(self, dist, mean, var):
        d = dist()
        np.testing.assert_allclose(float(d.mean.numpy()), mean, rtol=1e-5)
        np.testing.assert_allclose(float(d.variance.numpy()), var, rtol=1e-5)

    def test_sample_matches_mean(self):
        for d, m in [(D.Normal(1.0, 0.5), 1.0),
                     (D.Uniform(-1.0, 1.0), 0.0),
                     (D.Gumbel(0.0, 1.0), float(np.euler_gamma)),
                     (D.Cauchy(0.0, 1.0), None)]:
            s = np.asarray(d.sample([8000]).numpy())
            if m is not None:
                np.testing.assert_allclose(s.mean(), m, atol=0.08)


class TestLogProb:
    def test_normal_matches_formula(self):
        d = D.Normal(0.0, 1.0)
        x = np.linspace(-3, 3, 7).astype(np.float32)
        lp = np.asarray(d.log_prob(pt.to_tensor(x)).numpy())
        want = -0.5 * x ** 2 - 0.5 * math.log(2 * math.pi)
        np.testing.assert_allclose(lp, want, rtol=1e-5)

    def test_density_integrates_to_one(self):
        # numeric integral of prob over the support ≈ 1
        for d, lo, hi in [(D.Normal(0.3, 1.2), -8, 8),
                          (D.Gumbel(0.0, 1.0), -6, 20),
                          (D.Laplace(0.0, 2.0), -25, 25),
                          (D.Cauchy(0.0, 1.0), -2000, 2000),
                          (D.Gamma(2.0, 1.0), 1e-5, 40)]:
            x = np.linspace(lo, hi, 60001).astype(np.float64)
            p = np.asarray(d.prob(pt.to_tensor(
                x.astype(np.float32))).numpy()).astype(np.float64)
            integral = np.trapezoid(p, x)
            np.testing.assert_allclose(integral, 1.0, atol=5e-3), type(d)

    def test_categorical(self):
        logits = np.log(np.array([0.2, 0.3, 0.5], np.float32))
        d = D.Categorical(logits=logits)
        lp = np.asarray(d.log_prob(pt.to_tensor(
            np.array([0, 1, 2]))).numpy())
        np.testing.assert_allclose(np.exp(lp), [0.2, 0.3, 0.5], rtol=1e-5)
        s = np.asarray(d.sample([20000]).numpy())
        freq = np.bincount(s, minlength=3) / len(s)
        np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.02)

    def test_multinomial(self):
        d = D.Multinomial(10, np.array([0.5, 0.5], np.float32))
        s = np.asarray(d.sample([500]).numpy())
        assert s.shape == (500, 2)
        np.testing.assert_allclose(s.sum(-1), 10)
        lp = float(d.log_prob(pt.to_tensor(
            np.array([5.0, 5.0], np.float32))).numpy())
        want = math.log(math.comb(10, 5) * 0.5 ** 10)
        np.testing.assert_allclose(lp, want, rtol=1e-5)

    def test_dirichlet_event_shape(self):
        d = D.Dirichlet(np.array([1.0, 2.0, 3.0], np.float32))
        assert d.event_shape == [3]
        s = np.asarray(d.sample([64]).numpy())
        np.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-5)


class TestRsampleGrad:
    def test_normal_reparameterized(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.framework.random import next_key

        def f(mu):
            d = D.Normal(mu, 1.0)
            return d._rsample(jax.random.key(0), (1000,)).mean()

        g = jax.grad(f)(jnp.float32(2.0))
        np.testing.assert_allclose(float(g), 1.0, atol=1e-4)


class TestKL:
    def test_kl_self_zero(self):
        cases = [D.Normal(0.5, 2.0), D.Uniform(0., 1.),
                 D.Bernoulli(probs=0.4), D.Beta(2., 3.),
                 D.Exponential(1.5), D.Gamma(2., 2.),
                 D.Laplace(0., 1.), D.Poisson(2.0),
                 D.Gumbel(0.0, 1.0),
                 D.Categorical(logits=np.zeros(4, np.float32))]
        for d in cases:
            kl = float(np.asarray(D.kl_divergence(d, d).numpy()))
            np.testing.assert_allclose(kl, 0.0, atol=1e-5), type(d)

    @pytest.mark.parametrize("p,q,lo,hi", [
        (lambda: D.Normal(0.0, 1.0), lambda: D.Normal(1.0, 2.0), -10, 10),
        (lambda: D.Laplace(0.0, 1.0), lambda: D.Laplace(0.5, 2.0), -30, 30),
        (lambda: D.Gumbel(0.0, 1.0), lambda: D.Gumbel(0.5, 1.5), -8, 40),
        (lambda: D.Gamma(2.0, 1.0), lambda: D.Gamma(3.0, 2.0), 1e-4, 60),
        (lambda: D.Exponential(1.0), lambda: D.Exponential(2.5), 1e-6, 40),
    ])
    def test_kl_matches_numeric_integral(self, p, q, lo, hi):
        p, q = p(), q()
        kl = float(np.asarray(D.kl_divergence(p, q).numpy()))
        x = np.linspace(lo, hi, 200001).astype(np.float64)
        xp = pt.to_tensor(x.astype(np.float32))
        pp = np.asarray(p.prob(xp).numpy()).astype(np.float64)
        lpq = (np.asarray(p.log_prob(xp).numpy()).astype(np.float64)
               - np.asarray(q.log_prob(xp).numpy()).astype(np.float64))
        numeric = np.trapezoid(pp * lpq, x)
        np.testing.assert_allclose(kl, numeric, rtol=2e-3, atol=2e-3)

    def test_register_custom(self):
        class MyDist(D.Normal):
            pass

        @D.register_kl(MyDist, MyDist)
        def _kl_my(p, q):
            import jax.numpy as jnp
            return jnp.float32(42.0)

        assert float(D.kl_divergence(MyDist(0., 1.),
                                     MyDist(0., 1.)).numpy()) == 42.0


class TestTransforms:
    def test_affine_round_trip_and_ldj(self):
        t = D.AffineTransform(1.0, 3.0)
        x = np.array([0.5, -2.0], np.float32)
        y = np.asarray(t.forward(pt.to_tensor(x)).numpy())
        np.testing.assert_allclose(y, 1.0 + 3.0 * x)
        back = np.asarray(t.inverse(pt.to_tensor(y)).numpy())
        np.testing.assert_allclose(back, x, rtol=1e-6)
        ldj = np.asarray(t.forward_log_det_jacobian(
            pt.to_tensor(x)).numpy())
        np.testing.assert_allclose(ldj, np.log(3.0), rtol=1e-6)

    @pytest.mark.parametrize("t,x", [
        (D.ExpTransform(), np.array([0.1, 1.0], np.float32)),
        (D.SigmoidTransform(), np.array([-1.0, 2.0], np.float32)),
        (D.TanhTransform(), np.array([-0.5, 0.5], np.float32)),
        (D.PowerTransform(2.0), np.array([0.5, 2.0], np.float32)),
    ])
    def test_round_trip_and_numeric_ldj(self, t, x):
        y = np.asarray(t.forward(pt.to_tensor(x)).numpy())
        back = np.asarray(t.inverse(pt.to_tensor(y)).numpy())
        np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-5)
        # numeric jacobian
        eps = 1e-3
        dy = (np.asarray(t.forward(pt.to_tensor(x + eps)).numpy())
              - np.asarray(t.forward(pt.to_tensor(x - eps)).numpy())) / (
                  2 * eps)
        ldj = np.asarray(t.forward_log_det_jacobian(
            pt.to_tensor(x)).numpy())
        np.testing.assert_allclose(ldj, np.log(np.abs(dy)), atol=2e-3)

    def test_chain(self):
        chain = D.ChainTransform([D.AffineTransform(0.0, 2.0),
                                  D.ExpTransform()])
        x = np.array([0.3], np.float32)
        y = np.asarray(chain.forward(pt.to_tensor(x)).numpy())
        np.testing.assert_allclose(y, np.exp(2 * x), rtol=1e-6)
        ldj = np.asarray(chain.forward_log_det_jacobian(
            pt.to_tensor(x)).numpy())
        np.testing.assert_allclose(ldj, np.log(2.0) + 2 * x, rtol=1e-5)

    def test_stickbreaking(self):
        t = D.StickBreakingTransform()
        x = np.array([0.2, -0.5, 1.0], np.float32)
        y = np.asarray(t.forward(pt.to_tensor(x)).numpy())
        assert y.shape == (4,)
        np.testing.assert_allclose(y.sum(), 1.0, rtol=1e-5)
        back = np.asarray(t.inverse(pt.to_tensor(y)).numpy())
        np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-5)


class TestComposed:
    def test_transformed_distribution_lognormal(self):
        base = D.Normal(0.0, 0.5)
        td = D.TransformedDistribution(base, [D.ExpTransform()])
        ln = D.LogNormal(0.0, 0.5)
        x = np.array([0.5, 1.0, 2.0], np.float32)
        np.testing.assert_allclose(
            np.asarray(td.log_prob(pt.to_tensor(x)).numpy()),
            np.asarray(ln.log_prob(pt.to_tensor(x)).numpy()), rtol=1e-5)

    def test_independent(self):
        d = D.Independent(D.Normal(np.zeros((3, 4), np.float32),
                                   np.ones((3, 4), np.float32)), 1)
        assert d.batch_shape == [3] and d.event_shape == [4]
        x = np.zeros((3, 4), np.float32)
        lp = np.asarray(d.log_prob(pt.to_tensor(x)).numpy())
        assert lp.shape == (3,)
        np.testing.assert_allclose(
            lp, 4 * (-0.5 * math.log(2 * math.pi)), rtol=1e-5)
        kl = np.asarray(D.kl_divergence(d, d).numpy())
        np.testing.assert_allclose(kl, np.zeros(3), atol=1e-6)
