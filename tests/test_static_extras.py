"""Round-4 static extras: EMA, program (de)serialization, program state,
py_func/Print/metrics shims (ref: ``python/paddle/static/__init__.py``,
``static/io.py``)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import static


@pytest.fixture
def static_mode():
    pt.enable_static()
    yield
    pt.disable_static()


def _build_linear_prog():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 3], "float32")
        lin = pt.nn.Linear(3, 2)
        y = lin(x)
    return main, startup, x, y, lin


def test_ema_update_apply_restore(static_mode):
    main, startup, x, y, lin = _build_linear_prog()
    exe = static.Executor()
    exe.run(startup)
    feeds = {"x": np.ones((4, 3), "float32")}
    exe.run(main, feed=feeds, fetch_list=[y])

    ema = static.ExponentialMovingAverage(decay=0.5)
    scope = static.global_scope()
    wkey = lin.weight.name
    assert wkey in main.scope_tensors
    w0 = np.asarray(scope.find_var(wkey))
    ema.update(main)
    # shift the live weight, update again: shadow = 0.5*w0 + 0.5*(w0+1)
    scope.set(wkey, scope.find_var(wkey) + 1.0)
    ema.update(main)
    with ema.apply():
        now = np.asarray(scope.find_var(wkey))
        np.testing.assert_allclose(now, w0 + 0.5, atol=1e-5)
    back = np.asarray(scope.find_var(wkey))
    np.testing.assert_allclose(back, w0 + 1.0, atol=1e-6)


def test_serialize_roundtrip(tmp_path, static_mode):
    main, startup, x, y, lin = _build_linear_prog()
    exe = static.Executor()
    exe.run(startup)
    feeds = {"x": np.random.RandomState(0).rand(4, 3).astype("float32")}
    want = exe.run(main, feed=feeds, fetch_list=[y])[0]

    blob = static.serialize_program([x], [y], program=main)
    persist = static.serialize_persistables([x], [y], program=main)
    p1 = str(tmp_path / "prog.bin")
    static.save_to_file(p1, blob)
    loaded = static.deserialize_program(static.load_from_file(p1))
    params = static.deserialize_persistables(main, persist)
    out = loaded.call({k: v for k, v in params.items()}, feeds["x"])
    np.testing.assert_allclose(np.asarray(out[0]), want, atol=1e-5)
    with pytest.raises(TypeError):
        static.save_to_file(p1, "not-bytes")


def test_program_state_roundtrip(tmp_path, static_mode):
    main, startup, x, y, lin = _build_linear_prog()
    exe = static.Executor()
    exe.run(startup)
    path = str(tmp_path / "model")
    static.save(main, path)
    state = static.load_program_state(path)
    wkey = lin.weight.name
    assert wkey in state
    # zero the scope, restore from state
    scope = static.global_scope()
    orig = state[wkey].copy()
    scope.set(wkey, np.zeros_like(orig))
    static.set_program_state(main, state)
    np.testing.assert_allclose(np.asarray(scope.find_var(wkey)), orig)
    with pytest.raises(FileNotFoundError):
        static.load_program_state(str(tmp_path / "nope"))


def test_misc_shims(static_mode):
    assert len(static.cpu_places(2)) == 2
    g = static.create_global_var([2, 2], 1.5, "float32", persistable=True)
    np.testing.assert_allclose(g.numpy(), 1.5)
    bs = static.BuildStrategy()
    bs.fuse_elewise_add_act_ops = True
    assert bs.fuse_elewise_add_act_ops is True
    attr = static.WeightNormParamAttr(dim=0, name="w")
    assert attr.dim == 0 and attr.name == "w"
    sched = static.exponential_decay(0.1, 100, 0.9)
    assert abs(sched.get_lr() - 0.1) < 1e-9


def test_pyfunc_and_print_eager():
    pt.disable_static()
    x = pt.to_tensor(np.array([1.0, 2.0], "float32"))
    out = static.py_func(lambda a: np.asarray(a) * 3.0, x, x)
    np.testing.assert_allclose(out.numpy(), [3.0, 6.0])
    y = static.Print(x, message="dbg")
    np.testing.assert_allclose(y.numpy(), [1.0, 2.0])


def test_static_metrics():
    pt.disable_static()
    logits = pt.to_tensor(np.array([[0.1, 0.9], [0.8, 0.2]], "float32"))
    label = pt.to_tensor(np.array([[1], [0]], "int64"))
    acc = static.accuracy(logits, label)
    assert float(np.asarray(acc._data if hasattr(acc, "_data") else acc)) \
        == 1.0
    a = static.auc(pt.to_tensor(np.array([[0.2, 0.8], [0.7, 0.3],
                                          [0.4, 0.6]], "float32")),
                   pt.to_tensor(np.array([[1], [0], [1]], "int64")))
    assert 0.99 <= float(a.numpy()) <= 1.0


def test_exponential_decay_semantics():
    sched = static.exponential_decay(0.1, decay_steps=10, decay_rate=0.5,
                                     staircase=True)
    for _ in range(9):
        sched.step()
    assert abs(sched.get_lr() - 0.1) < 1e-9  # still in the first interval
    sched.step()
    assert abs(sched.get_lr() - 0.05) < 1e-9
    smooth = static.exponential_decay(0.1, decay_steps=10, decay_rate=0.5)
    for _ in range(5):
        smooth.step()
    assert abs(smooth.get_lr() - 0.1 * 0.5 ** 0.5) < 1e-9


def test_print_message_with_braces():
    pt.disable_static()
    x = pt.to_tensor(np.array([1.0], "float32"))
    y = static.Print(x, message="loss {step}")
    np.testing.assert_allclose(y.numpy(), [1.0])


def test_ema_injected_key_cleanup(static_mode):
    main, startup, x, y, lin = _build_linear_prog()
    exe = static.Executor()
    exe.run(startup)
    ema = static.ExponentialMovingAverage(0.9)
    ema.update(main)
    scope = static.global_scope()
    wkey = lin.weight.name
    # clear the scope var; apply must inject and restore must REMOVE it
    del scope.vars[wkey]
    with ema.apply():
        assert scope.find_var(wkey) is not None
    assert scope.find_var(wkey) is None


def test_serialize_persistables_not_stale(tmp_path, static_mode):
    """Checkpoint loop: serialize after a weight change must reflect the
    NEW values (the export memo must not serve stale params)."""
    import pickle
    main, startup, x, y, lin = _build_linear_prog()
    exe = static.Executor()
    exe.run(startup)
    p1 = static.serialize_persistables([x], [y], program=main)
    scope = static.global_scope()
    wkey = lin.weight.name
    scope.set(wkey, scope.find_var(wkey) * 0 + 7.0)
    p2 = static.serialize_persistables([x], [y], program=main)
    w2 = pickle.loads(p2)["params"][wkey]
    np.testing.assert_allclose(np.asarray(w2), 7.0)
    assert p1 != p2
