"""Round-5 API-surface closures: device stream shims, jit toggles,
check_numerics, Bilinear initializer, fused incubate layers, fleet
role-makers/data-generators/util, resnext variants, nn.quant.Stub
(refs in each implementation's docstring)."""
import io

import numpy as np
import pytest

import paddle_tpu as pt


class TestDeviceShims:
    def test_stream_event_surface(self):
        d = pt.device
        s = d.current_stream()
        e = s.record_event()
        assert e.query() and s.query()
        e.synchronize()
        s.wait_event(e)
        with d.stream_guard(d.Stream()) as g:
            assert d.current_stream() is g
        d.synchronize()
        assert d.get_cudnn_version() is None
        assert d.is_compiled_with_ipu() is False
        assert "cpu" in d.get_all_device_type()
        with pytest.raises(RuntimeError):
            d.IPUPlace()


def test_jit_toggles_and_eager_fallback():
    import paddle_tpu.jit as jit

    class M(pt.nn.Layer):
        def forward(self, x):
            return x * 3.0

    f = jit.to_static(M())
    x = pt.to_tensor(np.ones(2, np.float32))
    y_compiled = f(x).numpy()
    jit.enable_to_static(False)
    try:
        y_eager = f(x).numpy()
    finally:
        jit.enable_to_static(True)
    np.testing.assert_allclose(y_compiled, y_eager)
    jit.set_code_level(50)
    jit.set_verbosity(1)


def test_check_numerics_counts_and_abort():
    dbg = pt.amp.debugging
    t = pt.to_tensor(np.array([1.0, np.inf, 0.0, -2.0], np.float32))
    stats, values = dbg.check_numerics(t, "op", "x",
                                       dbg.DebugMode.CHECK_NAN_INF)
    assert np.asarray(stats._data).tolist() == [0, 1, 1]
    np.testing.assert_allclose(np.asarray(values._data),
                               [1.0, -2.0, -1.0 / 3.0], atol=1e-6)
    with pytest.raises(FloatingPointError):
        dbg.check_numerics(pt.to_tensor(np.array([np.nan], np.float32)),
                           "op", "x")


def test_bilinear_initializer_upsamples_exactly():
    init = pt.nn.initializer.Bilinear()
    w = init((1, 1, 4, 4))
    conv = pt.nn.Conv2DTranspose(1, 1, kernel_size=4, padding=1, stride=2,
                                 bias_attr=False)
    conv.weight.set_value(np.asarray(w))
    # a linear ramp upsamples to a linear ramp (interior exactness)
    x = np.arange(4, dtype=np.float32)[None, None, None, :].repeat(4, 2)
    y = conv(pt.to_tensor(x)).numpy()[0, 0]
    row = y[4]
    np.testing.assert_allclose(row[1:-1], np.arange(0.25, 3.26, 0.5)[:6],
                               atol=1e-5)


class TestFusedExtras:
    def test_fused_linear_matches_plain(self):
        from paddle_tpu.incubate.nn import FusedLinear
        pt.seed(0)
        fl = FusedLinear(6, 3)
        x = pt.to_tensor(np.random.RandomState(0)
                         .randn(4, 6).astype(np.float32))
        ref = x.numpy() @ fl.weight.numpy() + fl.bias.numpy()
        np.testing.assert_allclose(fl(x).numpy(), ref, atol=1e-5)
        flt = FusedLinear(6, 3, transpose_weight=True)
        assert tuple(flt.weight.shape) == (3, 6)
        assert tuple(flt(x).shape) == (4, 3)

    def test_fused_dropout_add_modes(self):
        from paddle_tpu.incubate.nn import FusedDropoutAdd
        a = pt.to_tensor(np.ones((8, 8), np.float32))
        b = pt.to_tensor(np.full((8, 8), 2.0, np.float32))
        da = FusedDropoutAdd(p=0.5)
        da.eval()
        np.testing.assert_allclose(da(a, b).numpy(), 3.0)
        da.train()
        out = da(a, b).numpy()
        assert set(np.unique(out.round(2))) <= {2.0, 4.0}
        di = FusedDropoutAdd(p=0.5, mode="downscale_in_infer")
        di.eval()
        np.testing.assert_allclose(di(a, b).numpy(), 2.5)
        with pytest.raises(ValueError):
            FusedDropoutAdd(mode="bogus")

    @pytest.mark.slow
    def test_fused_ec_moe_and_bias_dropout_ln(self):
        from paddle_tpu.incubate.nn import (
            FusedBiasDropoutResidualLayerNorm, FusedEcMoe)
        pt.seed(1)
        moe = FusedEcMoe(8, 16, 4, "gelu")
        x = pt.to_tensor(np.random.RandomState(1)
                         .randn(2, 3, 8).astype(np.float32))
        g = pt.to_tensor(np.random.RandomState(2)
                         .randn(2, 3, 4).astype(np.float32))
        g.stop_gradient = False
        out = moe(x, g)
        assert tuple(out.shape) == (2, 3, 8)
        out.sum().backward()
        assert g.grad is not None  # gate is differentiable
        ln = FusedBiasDropoutResidualLayerNorm(8, dropout_rate=0.0)
        r = pt.to_tensor(np.random.RandomState(3)
                         .randn(2, 3, 8).astype(np.float32))
        z = ln(x, r).numpy()
        assert abs(z.mean(-1)).max() < 1e-4
        with pytest.raises(ValueError):
            FusedEcMoe(8, 16, 4, "tanh")


class TestFleetRoleMakerUtil:
    def test_paddlecloud_role_from_env(self, monkeypatch):
        from paddle_tpu.distributed.fleet import (PaddleCloudRoleMaker,
                                                  Role)
        monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
        monkeypatch.setenv("PADDLE_TRAINER_ENDPOINTS",
                           "a:1,b:2,c:3,d:4")
        rm = PaddleCloudRoleMaker()
        assert rm.is_worker() and not rm.is_server()
        assert rm.worker_index() == 2 and rm.worker_num() == 4
        assert not rm.is_first_worker()
        assert len(rm.get_trainer_endpoints()) == 4
        assert Role.SERVER == 2

    def test_user_defined_role_and_file_shard(self):
        from paddle_tpu.distributed.fleet import (UserDefinedRoleMaker,
                                                  UtilBase)
        rm = UserDefinedRoleMaker(current_id=1, worker_num=3)
        util = UtilBase(rm)
        files = [f"f{i}" for i in range(8)]  # 8 files over 3 workers
        shard = util.get_file_shard(files)
        assert shard == ["f3", "f4", "f5"]
        all_files = []
        for wid in range(3):
            u = UtilBase(UserDefinedRoleMaker(current_id=wid,
                                              worker_num=3))
            all_files += u.get_file_shard(files)
        assert all_files == files  # partition: no loss, no overlap

    def test_data_generator_produces_dataset_food(self, tmp_path):
        from paddle_tpu.distributed.fleet import MultiSlotDataGenerator
        import paddle_tpu.distributed as dist

        class Gen(MultiSlotDataGenerator):
            def generate_sample(self, line):
                def local_iter():
                    a, b = line.split("|")
                    yield [("dense", [float(x) for x in a.split()]),
                           ("ids", [int(x) for x in b.split()])]
                return local_iter

        gen = Gen()
        buf = io.StringIO()
        gen._run(["1.0 2.0|7 8", "3.0 4.0|9"], out=buf)
        path = str(tmp_path / "gen.txt")
        with open(path, "w") as f:
            f.write(buf.getvalue())

        class _V:
            def __init__(self, name, dtype, shape=None):
                self.name, self.dtype, self.shape = name, dtype, shape

        ds = dist.QueueDataset()
        ds.init(batch_size=2, use_var=[_V("dense", "float32", [-1, 2]),
                                       _V("ids", "int64")],
                pipe_command="cat")
        ds.set_filelist([path])
        (batch,) = list(ds)
        np.testing.assert_allclose(batch["dense"],
                                   [[1.0, 2.0], [3.0, 4.0]])
        assert [a.tolist() for a in batch["ids"]] == [[7, 8], [9]]


def test_resnext_variant_names_resolve():
    for name in ("resnext50_64x4d", "resnext101_32x4d",
                 "resnext152_32x4d", "resnext152_64x4d"):
        assert callable(getattr(pt.vision.models, name))


@pytest.mark.slow
def test_resnext_variants_forward():
    for name in ("resnext50_64x4d", "resnext101_32x4d",
                 "resnext152_32x4d", "resnext152_64x4d"):
        assert hasattr(pt.vision.models, name)
    m = pt.vision.models.resnext50_64x4d(num_classes=7)
    out = m(pt.to_tensor(np.random.RandomState(0)
                         .randn(1, 3, 32, 32).astype(np.float32)))
    assert tuple(out.shape) == (1, 7)


def test_nn_quant_stub_identity():
    s = pt.nn.quant.Stub()
    x = pt.to_tensor(np.random.RandomState(0).randn(3).astype(np.float32))
    np.testing.assert_allclose(s(x).numpy(), x.numpy())


def test_reduce_lr_on_plateau_and_callbacks_export():
    import paddle_tpu.callbacks as cb
    assert hasattr(cb, "WandbCallback")
    r = cb.ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2,
                             verbose=0)

    class FakeOpt:
        def __init__(self):
            self.lr = 1.0

        def get_lr(self):
            return self.lr

        def set_lr(self, v):
            self.lr = v

    class FakeModel:
        _optimizer = FakeOpt()

    r.model = FakeModel()
    for loss in (1.0, 1.0, 1.0, 1.0):  # no improvement
        r.on_epoch_end(0, {"loss": loss})
    assert FakeModel._optimizer.lr < 1.0  # reduced after patience
    with pytest.raises(ValueError):
        cb.ReduceLROnPlateau(factor=1.5)


def test_inference_mixed_precision_conversion(tmp_path):
    import os
    import pickle
    import paddle_tpu.static as static
    pt.seed(0)
    pt.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [2, 4], "float32")
            w = pt.create_parameter([4, 3], "float32")
            y = pt.matmul(x, w)
        exe = static.Executor()
        exe.run(startup)
        pre = os.path.join(str(tmp_path), "m")
        static.save_inference_model(pre, [x], [y], exe)
        feed = {"x": np.ones((2, 4), np.float32)}
        ref = np.asarray(exe.run(main, feed=feed, fetch_list=[y])[0])
    finally:
        pt.disable_static()
    pt.inference.convert_to_mixed_precision(
        pre + ".pdmodel", pre + ".pdiparams",
        pre + "_bf16.pdmodel", pre + "_bf16.pdiparams")
    pp = pickle.load(open(pre + "_bf16.pdiparams", "rb"))
    assert all(np.asarray(v).dtype == "bfloat16"
               for v in pp["params"].values())
    cfg = pt.inference.Config(pre + "_bf16.pdmodel",
                              pre + "_bf16.pdiparams")
    pred = pt.inference.create_predictor(cfg)
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.copy_from_cpu(np.ones((2, 4), np.float32))
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, ref, atol=0.05, rtol=0.05)
    assert pt.inference.get_num_bytes_of_data_type("float32") == 4
    assert "version" in pt.inference.get_version()


def test_asp_add_supported_layer_and_misc_shims():
    import paddle_tpu.incubate.asp as asp

    class MyProj(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.weight = self.create_parameter([8, 8])

        def forward(self, x):
            return pt.matmul(x, self.weight)

    asp.add_supported_layer(MyProj)

    class Net(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.p = MyProj()

        def forward(self, x):
            return self.p(x)

    net = Net()
    asp.prune_model(net)
    w = net.p.weight.numpy()
    # 2:4 sparsity on the custom-registered layer's weight
    assert (np.count_nonzero(w.reshape(-1, 4), axis=1) <= 2).all()
    with pytest.raises(TypeError):
        asp.add_supported_layer(123)
    from paddle_tpu.incubate.optimizer import LBFGS  # noqa: F401
    from paddle_tpu.utils.cpp_extension import CUDAExtension
    with pytest.raises(RuntimeError, match="TPU build"):
        CUDAExtension(["x.cu"])


def test_reduce_lr_cooldown_suppresses_patience():
    import paddle_tpu.callbacks as cb
    r = cb.ReduceLROnPlateau(monitor="loss", factor=0.5, patience=1,
                             cooldown=3, verbose=0)

    class FakeOpt:
        lr = 1.0

        def get_lr(self):
            return self.lr

        def set_lr(self, v):
            FakeOpt.lr = v

    class FakeModel:
        _optimizer = FakeOpt()

    FakeOpt.lr = 1.0
    r.model = FakeModel()
    # e1 sets best; e2 plateau -> reduce to 0.5 + cooldown=3;
    # e3-e5 cool down (patience must NOT advance); e6 -> second cut
    for _ in range(5):
        r.on_epoch_end(0, {"loss": 1.0})
    assert FakeOpt.lr == 0.5, FakeOpt.lr  # cooldown held the counter
    r.on_epoch_end(0, {"loss": 1.0})
    assert FakeOpt.lr == 0.25, FakeOpt.lr  # patience after cooldown


def test_fit_passes_eval_logs_to_callbacks():
    import paddle_tpu.callbacks as cb
    seen = {}

    class Spy(cb.Callback):
        def on_epoch_end(self, epoch, logs=None):
            seen.update(logs or {})

    pt.seed(0)
    net = pt.nn.Linear(4, 2)
    model = pt.Model(net)
    model.prepare(pt.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters()),
                  pt.nn.CrossEntropyLoss())
    X = np.random.RandomState(0).randn(16, 4).astype(np.float32)
    Y = np.random.RandomState(1).randint(0, 2, (16,)).astype(np.int64)
    ds = pt.io.TensorDataset([pt.to_tensor(X), pt.to_tensor(Y)])
    model.fit(ds, eval_data=ds, batch_size=8, epochs=1, verbose=0,
              callbacks=[Spy()])
    assert any(k.startswith("eval_") for k in seen), seen


def test_asp_custom_pruning_func_is_used():
    import paddle_tpu.incubate.asp as asp
    calls = []

    def my_mask(weight, m, n, func_name, param_name):
        calls.append(param_name)
        mask = np.zeros_like(weight)
        mask[0, :] = 1.0  # keep only first row
        return mask

    class OddProj(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.weight = self.create_parameter([4, 8])

        def forward(self, x):
            return pt.matmul(x, self.weight)

    asp.add_supported_layer(OddProj, pruning_func=my_mask)

    class Net(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.p = OddProj()

        def forward(self, x):
            return self.p(x)

    net = Net()
    asp.prune_model(net)
    assert calls  # the custom fn actually ran
    w = net.p.weight.numpy()
    assert np.abs(w[1:]).max() == 0.0 and np.abs(w[0]).max() > 0.0


def test_convert_to_mixed_precision_rejects_bad_precision(tmp_path):
    with pytest.raises(ValueError, match="float16/bfloat16"):
        pt.inference.convert_to_mixed_precision(
            "a", "b", "c", "d", mixed_precision="int8")
