"""audio.datasets (ref: python/paddle/audio/datasets/): TESS and ESC50
over locally generated archives (zero-egress: download only fires when
the data directory is absent)."""
import csv
import os

import numpy as np
import pytest

import paddle_tpu as pt

SR = 16000


def _tone(i):
    return (0.1 * np.sin(2 * np.pi * 220 * (i + 1)
                         * np.arange(SR // 10) / SR)).astype(np.float32)


@pytest.fixture
def data_home(tmp_path, monkeypatch):
    # data_home() resolves the env var lazily, so a plain setenv is
    # enough — no module-attribute surgery
    home = str(tmp_path)
    monkeypatch.setenv("PADDLE_TPU_DATA_HOME", home)
    return home


@pytest.mark.slow
def test_tess_folds_and_features(data_home):
    d = os.path.join(data_home, "TESS_Toronto_emotional_speech_set")
    os.makedirs(d)
    emos = ["angry", "happy", "sad", "fear", "neutral", "ps", "disgust",
            "angry", "happy", "sad"]
    for i, emo in enumerate(emos):
        pt.audio.save(os.path.join(d, f"OAF_w{i}_{emo}.wav"),
                      pt.to_tensor(_tone(i)[None, :]), SR)
    train = pt.audio.datasets.TESS(mode="train", n_folds=5, split=1)
    dev = pt.audio.datasets.TESS(mode="dev", n_folds=5, split=1)
    assert len(train) + len(dev) == 10 and len(dev) == 2
    wav, label = train[0]
    assert wav.ndim == 1 and wav.dtype == np.float32
    assert 0 <= label < len(pt.audio.datasets.TESS.label_list)
    # feature extraction path
    mf = pt.audio.datasets.TESS(mode="dev", n_folds=5, split=1,
                                feat_type="mfcc", n_mfcc=13)
    feat, _ = mf[0]
    assert feat.shape[0] == 13
    with pytest.raises(AssertionError):
        pt.audio.datasets.TESS(n_folds=5, split=9)
    with pytest.raises(RuntimeError, match="feat_type"):
        pt.audio.datasets.AudioClassificationDataset([], [],
                                                     feat_type="bogus")


def test_esc50_meta_split(data_home):
    audio_dir = os.path.join(data_home, "ESC-50-master", "audio")
    meta_dir = os.path.join(data_home, "ESC-50-master", "meta")
    os.makedirs(audio_dir)
    os.makedirs(meta_dir)
    rows = [["filename", "fold", "target", "category", "esc10",
             "src_file", "take"]]
    for i in range(10):
        fn = f"1-{i}-A-{i % 3}.wav"
        pt.audio.save(os.path.join(audio_dir, fn),
                      pt.to_tensor(_tone(i)[None, :]), SR)
        rows.append([fn, str(i % 5 + 1), str(i % 3), "cat", "False",
                     "x", "A"])
    with open(os.path.join(meta_dir, "esc50.csv"), "w", newline="") as f:
        csv.writer(f).writerows(rows)
    tr = pt.audio.datasets.ESC50(mode="train", split=1)
    dv = pt.audio.datasets.ESC50(mode="dev", split=1)
    assert len(tr) == 8 and len(dv) == 2
    wav, label = dv[0]
    assert wav.ndim == 1 and 0 <= label < 3
    with pytest.raises(AssertionError):
        pt.audio.datasets.ESC50(split=7)
