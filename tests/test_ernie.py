"""ERNIE family — baseline config[4] recipe (pretraining, AMP O2 +
recompute) on the virtual mesh. Ref: PaddleNLP ErnieModel trained through
the in-repo AMP (auto_cast.py:646) + recompute (fleet/recompute/) stacks."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.train_step import build_train_step
from paddle_tpu.incubate.models import (
    ernie_tiny, ErnieModel, ErnieForPretraining, ErniePretrainingCriterion,
    ErnieForSequenceClassification)


@pytest.fixture(autouse=True)
def _reset():
    yield
    dist.set_mesh(None)
    dist.destroy_process_group()


def _data(rng, B=8, S=16, vocab=1024):
    ids = rng.randint(0, vocab, (B, S)).astype(np.int32)
    mlm_labels = rng.randint(0, vocab, (B, S)).astype(np.int32)
    sop = rng.randint(0, 2, (B,)).astype(np.int64)
    return ids, mlm_labels, sop


@pytest.mark.slow
def test_ernie_forward_shapes_and_task_embedding():
    pt.seed(0)
    cfg = ernie_tiny()
    model = ErnieModel(cfg)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 1024, (2, 16)).astype(np.int32)
    seq, pooled = model(pt.to_tensor(ids))
    assert seq.shape == [2, 16, 64] and pooled.shape == [2, 64]
    # task-type ids change the representation (ERNIE 2.0/3.0 embedding)
    task = np.ones((2, 16), np.int32)
    seq2, _ = model(pt.to_tensor(ids), task_type_ids=pt.to_tensor(task))
    assert not np.allclose(np.asarray(seq._data), np.asarray(seq2._data))


@pytest.mark.slow
def test_ernie_pretraining_amp_o2_recompute_loss_decreases():
    """The config[4] recipe end-to-end: MLM+SOP pretraining, bf16 O2
    params, per-block recompute, one compiled train step on a dp mesh."""
    dist.init_mesh({"dp": 8})
    pt.seed(1)
    cfg = ernie_tiny(use_recompute=True)
    cfg.hidden_dropout_prob = 0.0
    cfg.attention_probs_dropout_prob = 0.0
    model = ErnieForPretraining(cfg)
    pt.amp.decorate(model, level="O2", dtype="bfloat16")
    crit = ErniePretrainingCriterion()
    opt = pt.optimizer.AdamW(learning_rate=5e-3,
                             parameters=model.parameters(),
                             multi_precision=True)

    def loss_fn(out, mlm_labels, sop_labels):
        return crit(out[0], out[1], mlm_labels, sop_labels)

    step, state = build_train_step(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids, mlm, sop = _data(rng)
    losses = []
    for _ in range(6):
        loss, state = step(state, ids, mlm, sop)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses
    # O2: master weights exist; params are bf16
    assert state["opt"]["master"], "O2 master weights missing"


@pytest.mark.slow
def test_ernie_recompute_matches_plain():
    """Per-block jax.checkpoint must not change the math."""
    pt.seed(2)
    rng = np.random.RandomState(3)
    ids, mlm, sop = _data(rng, B=4)
    dist.init_mesh({"dp": 4})

    losses = {}
    for rc in (False, True):
        pt.seed(2)
        cfg = ernie_tiny(use_recompute=rc)
        cfg.hidden_dropout_prob = 0.0
        cfg.attention_probs_dropout_prob = 0.0
        model = ErnieForPretraining(cfg)
        crit = ErniePretrainingCriterion()
        opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())

        def loss_fn(out, a, b):
            return crit(out[0], out[1], a, b)

        step, state = build_train_step(model, loss_fn, opt)
        ls = []
        for _ in range(2):
            l, state = step(state, ids, mlm, sop)
            ls.append(float(l))
        losses[rc] = ls
    np.testing.assert_allclose(losses[False], losses[True],
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_ernie_finetune_classifier():
    pt.seed(3)
    cfg = ernie_tiny()
    cfg.hidden_dropout_prob = 0.0
    cfg.attention_probs_dropout_prob = 0.0
    model = ErnieForSequenceClassification(cfg, num_classes=2)
    opt = pt.optimizer.AdamW(learning_rate=1e-2,
                             parameters=model.parameters())
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 1024, (16, 12)).astype(np.int32)
    y = (ids.sum(-1) % 2).astype(np.int64)
    first = None
    for _ in range(25):
        loss = pt.nn.functional.cross_entropy(
            model(pt.to_tensor(ids)), pt.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        if first is None:
            first = float(loss)
    assert float(loss) < first
