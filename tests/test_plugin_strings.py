"""Custom-device plugin loading + StringTensor/SelectedRows analogs
(ref: custom_device.cc:1065 LoadCustomRuntimeLib, init.cc:144
CUSTOM_DEVICE_ROOT scan; phi/core/string_tensor.h, selected_rows.h)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.device import (load_custom_runtime_lib,
                               load_custom_device_plugins,
                               registered_plugins)
from paddle_tpu.framework import (StringTensor, SelectedRows,
                                  strings_lower, strings_upper)


class TestPluginLoading:
    def test_missing_library_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            load_custom_runtime_lib(str(tmp_path / "libnpu.so"))

    def test_empty_dir_raises_and_empty_root_noop(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            load_custom_runtime_lib(str(tmp_path))
        assert load_custom_device_plugins(root="") == []
        assert load_custom_device_plugins(root=str(tmp_path)) == []

    def test_registers_pjrt_plugin(self, tmp_path, monkeypatch):
        lib = tmp_path / "libpjrt_mynpu.so"
        lib.write_bytes(b"\x7fELF")
        calls = {}
        from jax._src import xla_bridge
        monkeypatch.setattr(
            xla_bridge, "register_plugin",
            lambda name, library_path=None, **kw: calls.setdefault(
                name, library_path))
        name = load_custom_runtime_lib(str(lib))
        assert name == "mynpu"
        assert calls == {"mynpu": str(lib)}
        assert registered_plugins()["mynpu"] == str(lib)

    def test_root_scan(self, tmp_path, monkeypatch):
        (tmp_path / "liba.so").write_bytes(b"\x7fELF")
        (tmp_path / "libb.so").write_bytes(b"\x7fELF")
        from jax._src import xla_bridge
        monkeypatch.setattr(xla_bridge, "register_plugin",
                            lambda name, library_path=None, **kw: None)
        names = load_custom_device_plugins(root=str(tmp_path))
        assert names == ["a", "b"]


class TestStringTensor:
    def test_case_convert(self):
        st = StringTensor([["Hello", "WORLD"], ["MiXeD", "ok"]])
        assert st.shape == [2, 2] and st.dtype == "pstring"
        low = st.lower()
        up = strings_upper(st)
        assert low.numpy()[0, 1] == "world"
        assert up.numpy()[1, 0] == "MIXED"
        assert strings_lower([["A"]]).numpy()[0, 0] == "a"
        assert st[0][1] == "WORLD"


class TestSelectedRows:
    def test_to_dense_merges_duplicates(self):
        sr = SelectedRows(rows=[1, 3, 1], value=np.ones((3, 2), np.float32),
                          height=5)
        dense = np.asarray(sr.to_dense())
        assert dense.shape == (5, 2)
        np.testing.assert_allclose(dense[1], [2, 2])  # duplicate merged
        np.testing.assert_allclose(dense[3], [1, 1])
        np.testing.assert_allclose(dense[0], [0, 0])

    def test_apply_to_updates_only_touched_rows(self):
        import jax.numpy as jnp
        w = jnp.zeros((6, 2), jnp.float32)
        sr = SelectedRows(rows=[2, 4], value=np.ones((2, 2), np.float32),
                          height=6)
        new_w = sr.apply_to(w, lambda rows, grads: rows - 0.5 * grads)
        got = np.asarray(new_w)
        np.testing.assert_allclose(got[2], [-0.5, -0.5])
        np.testing.assert_allclose(got[4], [-0.5, -0.5])
        assert np.abs(got[[0, 1, 3, 5]]).sum() == 0
