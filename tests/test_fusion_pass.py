"""Jaxpr pattern-matching fusion pass: matchers on synthetic graphs
(f32 and AMP-style bf16 lowerings), near-misses left alone, rewritten-
vs-unrewritten fwd+grad parity, env kill switch / per-pattern opt-out,
capture integration (one compile, rewrites recorded on the entry),
bf16-in/f32-acc parity for the block kernels, and the cost-model-guided
candidate generator + schema-bump invalidation in the autotuner.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops import autotune as at
from paddle_tpu.ops import fused_kernels as fk
from paddle_tpu.ops import fusion_pass as fp

QK = (((3,), (3,)), ((0, 1), (0, 1)))
PV = (((3,), (2,)), ((0, 1), (0, 1)))
DOT2 = (((1,), (0,)), ((), ()))

BF16_TOL = dict(rtol=3e-2, atol=3e-2)


@pytest.fixture(autouse=True)
def _clean_pass(monkeypatch):
    monkeypatch.delenv("PT_FUSION_PASS", raising=False)
    monkeypatch.delenv("PT_FUSION_DISABLE", raising=False)
    fp.reset_stats()
    yield
    fp.reset_stats()


def _rand(shape, seed=0, dtype=np.float32):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape)
                       .astype(dtype))


# ---------------------------------------------------------------------------
# synthetic graphs — written the way the models lower (jnp.mean inlines
# to reduce_sum/div, jnp.var stays a pjit[_var], jax.nn.softmax emits
# the reduce_max/stop_gradient/exp/sum soup)
# ---------------------------------------------------------------------------
def _ln(x, w, b):
    m = jnp.mean(x, axis=-1, keepdims=True)
    v = jnp.var(x, axis=-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + 1e-5) * w + b


def _res_ln(x, r, w, b):
    return _ln(x + r, w, b)


def _lnmm(x, w, b, mw, mb):
    return jax.lax.dot_general(_ln(x, w, b), mw, DOT2) + mb


def _gelu_tanh(z):
    return 0.5 * (1.0 + jnp.tanh(0.7978845608028654 *
                                 (z + 0.044715 * z ** 3))) * z


def _mbg(x, w, b):
    return _gelu_tanh(jax.lax.dot_general(x, w, DOT2) + b)


def _mbg_erf(x, w, b):
    z = jax.lax.dot_general(x, w, DOT2) + b
    return (z * 0.5) * jax.lax.erfc(-z * 0.7071067811865476)


def _attn(q, k, v, causal=False):
    s = jax.lax.dot_general(q, k, QK) * 0.125
    if causal:
        S = q.shape[2]
        s = jnp.where(jnp.tril(jnp.ones((S, S), bool)), s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jax.lax.dot_general(p, v, PV)


class _Args:
    """Shared small operands (f32)."""
    x = _rand((8, 32))
    r = _rand((8, 32), 3)
    w = _rand((32,), 1)
    b = _rand((32,), 2)
    mw = _rand((32, 48), 4)
    mb = _rand((48,), 5)
    q = _rand((2, 2, 16, 8), 6)
    k = _rand((2, 2, 16, 8), 7)
    v = _rand((2, 2, 16, 8), 8)


A = _Args


# ---------------------------------------------------------------------------
# matchers: every pattern kind, f32 graphs
# ---------------------------------------------------------------------------
class TestMatchers:

    def test_layer_norm(self):
        assert fp.count_patterns(_ln, A.x, A.w, A.b) == {"layer_norm": 1}

    def test_residual_ln(self):
        assert fp.count_patterns(_res_ln, A.x, A.r, A.w, A.b) == \
            {"residual_ln": 1}

    def test_ln_matmul(self):
        assert fp.count_patterns(_lnmm, A.x, A.w, A.b, A.mw, A.mb) == \
            {"ln_matmul": 1}

    def test_matmul_bias_gelu_tanh_and_erf(self):
        assert fp.count_patterns(_mbg, A.x, A.mw, A.mb) == \
            {"matmul_bias_gelu": 1}
        assert fp.count_patterns(_mbg_erf, A.x, A.mw, A.mb) == \
            {"matmul_bias_gelu": 1}

    @pytest.mark.parametrize("causal", [False, True])
    def test_attention_block(self, causal):
        assert fp.count_patterns(
            lambda q, k, v: _attn(q, k, v, causal), A.q, A.k, A.v) == \
            {"attention_block": 1}

    def test_mbg_claims_dot_before_ln_epilogue(self):
        # LN → matmul → gelu: the gelu cluster owns the dot, the LN
        # stays a bare layer_norm instead of ln_matmul (priority order)
        def f(x, w, b, mw, mb):
            return _gelu_tanh(
                jax.lax.dot_general(_ln(x, w, b), mw, DOT2) + mb)
        assert fp.count_patterns(f, A.x, A.w, A.b, A.mw, A.mb) == \
            {"layer_norm": 1, "matmul_bias_gelu": 1}


# ---------------------------------------------------------------------------
# matchers: AMP-style bf16 graphs (per-site converts, f32 stats island,
# bf16-rounded gelu literals, cast-wrapped softmax island)
# ---------------------------------------------------------------------------
class TestMatchersAMP:

    def test_amp_layer_norm(self):
        def f(x, w, b):
            m = jnp.mean(x.astype(jnp.float32), axis=-1, keepdims=True)
            v = jnp.var(x.astype(jnp.float32), axis=-1, keepdims=True)
            y = (x.astype(jnp.float32) - m) * \
                jax.lax.rsqrt(v + jnp.float32(1e-5))
            return y.astype(jnp.bfloat16) * w + b  # affine back in bf16
        xb = A.x.astype(jnp.bfloat16)
        assert fp.count_patterns(f, xb, A.w.astype(jnp.bfloat16),
                                 A.b.astype(jnp.bfloat16)) == \
            {"layer_norm": 1}

    def test_amp_gelu_rounded_literals(self):
        # bf16 graphs store sqrt(2/pi) as 0.796875 and the cubic
        # coefficient as 0.0446777 — _coef_close must accept both
        def f(x, w, b):
            z = jax.lax.dot_general(
                x, w, DOT2, preferred_element_type=jnp.bfloat16) + b
            return (jnp.bfloat16(0.5) * (jnp.bfloat16(1.0) + jnp.tanh(
                jnp.bfloat16(0.796875) *
                (z + jnp.bfloat16(0.0446777) * z ** 3))) * z)
        assert fp.count_patterns(
            f, A.x.astype(jnp.bfloat16), A.mw.astype(jnp.bfloat16),
            A.mb.astype(jnp.bfloat16)) == {"matmul_bias_gelu": 1}

    def test_amp_attention_cast_wrapped_softmax(self):
        def f(q, k, v):
            s = jax.lax.dot_general(
                q, k, QK, preferred_element_type=jnp.bfloat16)
            s = s.astype(jnp.float32) * 0.125
            p = jax.nn.softmax(s, axis=-1)
            return jax.lax.dot_general(p.astype(jnp.bfloat16), v, PV)
        qb, kb, vb = (t.astype(jnp.bfloat16) for t in (A.q, A.k, A.v))
        assert fp.count_patterns(f, qb, kb, vb) == {"attention_block": 1}

    def test_amp_rewrite_parity_exact(self):
        # the XLA mirror replays the convert placement of the matched
        # soup, so CPU fallback output is bit-identical
        def f(x, w, b):
            m = jnp.mean(x.astype(jnp.float32), axis=-1, keepdims=True)
            v = jnp.var(x.astype(jnp.float32), axis=-1, keepdims=True)
            y = (x.astype(jnp.float32) - m) * \
                jax.lax.rsqrt(v + jnp.float32(1e-5))
            return y.astype(jnp.bfloat16) * w + b
        xb = A.x.astype(jnp.bfloat16)
        wb = A.w.astype(jnp.bfloat16)
        bb = A.b.astype(jnp.bfloat16)
        base = f(xb, wb, bb)
        fused = fp.wrap(f)(xb, wb, bb)
        assert fp.summary()["rewrites"] == {"layer_norm": 1}
        np.testing.assert_array_equal(np.asarray(base), np.asarray(fused))


# ---------------------------------------------------------------------------
# near-misses must NOT match
# ---------------------------------------------------------------------------
class TestNearMisses:

    def test_var_with_ddof_not_layer_norm(self):
        def f(x, w, b):
            m = jnp.mean(x, axis=-1, keepdims=True)
            v = jnp.var(x, axis=-1, keepdims=True, ddof=1)
            return (x - m) * jax.lax.rsqrt(v + 1e-5) * w + b
        assert fp.count_patterns(f, A.x, A.w, A.b) == {}

    def test_escaping_interior_not_matched(self):
        # the mean escapes the cluster as a second output → not closed
        def f(x, w, b):
            m = jnp.mean(x, axis=-1, keepdims=True)
            v = jnp.var(x, axis=-1, keepdims=True)
            return (x - m) * jax.lax.rsqrt(v + 1e-5) * w + b, m
        assert fp.count_patterns(f, A.x, A.w, A.b) == {}

    def test_wrong_gelu_coefficient_not_matched(self):
        # 0.06 is outside the 1% reduced-precision tolerance on 0.044715
        def f(x, w, b):
            z = jax.lax.dot_general(x, w, DOT2) + b
            return 0.5 * (1.0 + jnp.tanh(0.7978845608028654 *
                                         (z + 0.06 * z ** 3))) * z
        assert fp.count_patterns(f, A.x, A.mw, A.mb) == {}

    def test_op_between_softmax_and_pv_not_matched(self):
        # dropout (here: any op on the probabilities) breaks the block
        def f(q, k, v):
            s = jax.lax.dot_general(q, k, QK) * 0.125
            p = jax.nn.softmax(s, axis=-1) * 0.9
            return jax.lax.dot_general(p, v, PV)
        assert fp.count_patterns(f, A.q, A.k, A.v) == {}

    def test_mean_over_wrong_axis_not_matched(self):
        def f(x, w, b):
            m = jnp.mean(x, axis=0, keepdims=True)
            v = jnp.var(x, axis=-1, keepdims=True)
            return (x - m) * jax.lax.rsqrt(v + 1e-5) * w + b
        assert fp.count_patterns(f, A.x, A.w, A.b) == {}


# ---------------------------------------------------------------------------
# rewritten vs unrewritten parity (CPU: every cluster dispatches to the
# inline XLA mirror, reason tpu_unreachable)
# ---------------------------------------------------------------------------
class TestRewriteParity:

    def _block(self, x, r, w, b, mw, mb):
        h = _mbg(_ln(x, w, b), mw, mb)            # ln + matmul_bias_gelu
        h = jax.lax.dot_general(h, mw.T, DOT2)    # back to width 32
        return _res_ln(h, r, w, b)                # residual_ln

    def test_forward_parity(self):
        args = (A.x, A.r, A.w, A.b, A.mw, A.mb)
        base = self._block(*args)
        fused = fp.wrap(self._block)(*args)
        s = fp.summary()
        assert s["rewrites"] == {"layer_norm": 1, "matmul_bias_gelu": 1,
                                 "residual_ln": 1}
        assert all(k.endswith(":tpu_unreachable")
                   for k in s["fallbacks"])
        assert float(jnp.max(jnp.abs(base - fused))) <= 1e-5

    def test_grad_parity(self):
        def loss(fn, *args):
            return jnp.sum(fn(*args) ** 2)
        args = (A.x, A.r, A.w, A.b, A.mw, A.mb)
        g0 = jax.grad(lambda *a: loss(self._block, *a),
                      argnums=(0, 1, 4))(*args)
        g1 = jax.grad(lambda *a: loss(fp.wrap(self._block), *a),
                      argnums=(0, 1, 4))(*args)
        for a, b in zip(g0, g1):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-5)

    def test_attention_parity_fwd_and_grad(self):
        f = lambda q, k, v: _attn(q, k, v, causal=True)
        base = f(A.q, A.k, A.v)
        fused = fp.wrap(f)(A.q, A.k, A.v)
        assert fp.summary()["rewrites"] == {"attention_block": 1}
        assert float(jnp.max(jnp.abs(base - fused))) <= 1e-5
        g0 = jax.grad(lambda q: jnp.sum(f(q, A.k, A.v) ** 2))(A.q)
        g1 = jax.grad(
            lambda q: jnp.sum(fp.wrap(f)(q, A.k, A.v) ** 2))(A.q)
        np.testing.assert_allclose(np.asarray(g0), np.asarray(g1),
                                   rtol=1e-5, atol=1e-5)

    def test_wrap_composes_with_jit(self):
        args = (A.x, A.r, A.w, A.b, A.mw, A.mb)
        base = self._block(*args)
        fused = jax.jit(fp.wrap(self._block))(*args)
        assert float(jnp.max(jnp.abs(base - fused))) <= 1e-5


# ---------------------------------------------------------------------------
# env gates
# ---------------------------------------------------------------------------
class TestEnvGates:

    def test_kill_switch(self, monkeypatch):
        monkeypatch.setenv("PT_FUSION_PASS", "0")
        out = fp.wrap(_ln)(A.x, A.w, A.b)
        assert fp.summary()["rewrites"] == {}
        assert fp.summary()["traces"] == 0
        np.testing.assert_array_equal(np.asarray(out),
                                      np.asarray(_ln(A.x, A.w, A.b)))

    def test_per_pattern_opt_out(self, monkeypatch):
        monkeypatch.setenv("PT_FUSION_DISABLE", "layer_norm,residual_ln")
        assert fp.count_patterns(_ln, A.x, A.w, A.b) == {}
        assert fp.count_patterns(_res_ln, A.x, A.r, A.w, A.b) == {}
        # other patterns stay live
        assert fp.count_patterns(_mbg, A.x, A.mw, A.mb) == \
            {"matmul_bias_gelu": 1}

    def test_opt_out_through_wrap(self, monkeypatch):
        monkeypatch.setenv("PT_FUSION_DISABLE", "matmul_bias_gelu")
        fp.wrap(_mbg)(A.x, A.mw, A.mb)
        assert fp.summary()["rewrites"] == {}


# ---------------------------------------------------------------------------
# telemetry counters
# ---------------------------------------------------------------------------
class TestTelemetry:

    def test_rewrite_and_fallback_counted(self):
        from paddle_tpu.observability import get_telemetry
        tel = get_telemetry()
        before = tel.snapshot()["fusion"]
        fp.wrap(_ln)(A.x, A.w, A.b)
        after = tel.snapshot()["fusion"]
        assert after["rewrites"].get("layer_norm", 0) == \
            before["rewrites"].get("layer_norm", 0) + 1
        key = "layer_norm:tpu_unreachable"
        assert after["fallbacks"].get(key, 0) == \
            before["fallbacks"].get(key, 0) + 1


# ---------------------------------------------------------------------------
# capture integration: one compile, rewrites recorded on the entry
# ---------------------------------------------------------------------------
class TestCaptureIntegration:

    def test_exactly_one_compile_with_rewrites(self):
        import paddle_tpu as pt
        import paddle_tpu.nn as nn
        np.random.seed(0)
        pt.seed(0)
        ln = nn.LayerNorm(16)
        fc = nn.Linear(16, 16)

        @pt.jit.capture_step
        def step(x):
            return fc(ln(x))

        x = pt.to_tensor(np.random.randn(8, 16).astype(np.float32))
        outs = [np.asarray(step(x)._data) for _ in range(3)]
        assert step.stats["compiles"] == 1
        assert step.stats["hits"] >= 2
        assert step.stats["fusion_rewrites"] >= 1
        assert step.stats["fusion_patterns"]
        eager = np.asarray(fc(ln(x))._data)
        np.testing.assert_allclose(outs[0], eager, rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(outs[0], outs[-1])


# ---------------------------------------------------------------------------
# block kernels: bf16 in, f32 accumulation (interpret mode)
# ---------------------------------------------------------------------------
class TestBlockKernelBf16:

    def test_ln_matmul_bf16(self):
        x = _rand((64, 96)).astype(jnp.bfloat16)
        w = _rand((96, 64), 1).astype(jnp.bfloat16)
        lw = _rand((96,), 2).astype(jnp.bfloat16)
        out = fk.fused_ln_matmul(x, w, lw, interpret=True)
        assert out.dtype == jnp.bfloat16
        ref = fk.ln_matmul_reference(x, w, lw)
        np.testing.assert_allclose(
            np.asarray(out.astype(jnp.float32)),
            np.asarray(ref.astype(jnp.float32)), **BF16_TOL)

    def test_matmul_bias_gelu_bf16(self):
        x = _rand((48, 64)).astype(jnp.bfloat16)
        w = _rand((64, 96), 1).astype(jnp.bfloat16)
        b = _rand((96,), 2).astype(jnp.bfloat16)
        out = fk.fused_matmul_bias_gelu(x, w, b, interpret=True)
        assert out.dtype == jnp.bfloat16
        ref = fk.matmul_bias_gelu_reference(x, w, b)
        np.testing.assert_allclose(
            np.asarray(out.astype(jnp.float32)),
            np.asarray(ref.astype(jnp.float32)), **BF16_TOL)

    def test_attention_block_bf16(self):
        q = _rand((1, 2, 32, 16)).astype(jnp.bfloat16)
        k = _rand((1, 2, 32, 16), 1).astype(jnp.bfloat16)
        v = _rand((1, 2, 32, 16), 2).astype(jnp.bfloat16)
        out = fk.fused_attention_block(q, k, v, causal=True,
                                       interpret=True)
        assert out.dtype == jnp.bfloat16
        ref = fk.attention_block_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out.astype(jnp.float32)),
            np.asarray(ref.astype(jnp.float32)), **BF16_TOL)


# ---------------------------------------------------------------------------
# autotuner: generated candidates, prune-before-time, schema bump
# ---------------------------------------------------------------------------
class TestCandidateGeneration:

    @pytest.fixture(autouse=True)
    def _clean_tuner(self):
        at.cache_clear()
        yield
        at.cache_clear()

    @staticmethod
    def _axes():
        return [("tile", 512, 8), ("tile", 512, 128), ("choice", (1, 0))]

    @staticmethod
    def _cost(cfg):
        br, bn, _par = cfg
        return {"flops": 1e6, "bytes": float(br * bn),
                "vmem_bytes": float(br * bn * 4),
                "mxu_underfill": br < 8 or bn < 128}

    def test_generates_from_axes_and_prunes(self):
        limit = 256 * 1024
        cands = at.generate_candidates(self._axes(), self._cost,
                                       vmem_limit=limit,
                                       max_candidates=5)
        assert 1 <= len(cands) <= 5
        for br, bn, par in cands:
            # every survivor is axis-derived (aligned pow-2 walk) and
            # inside the vmem budget
            assert br in (8, 16, 32, 64, 128, 256, 512)
            assert bn in (128, 256, 512)
            assert par in (1, 0)
            assert br * bn * 4 <= limit

    def test_all_pruned_raises(self):
        with pytest.raises(RuntimeError):
            at.generate_candidates(self._axes(), self._cost, vmem_limit=1)

    def test_search_never_times_pruned_configs(self):
        cands = at.generate_candidates(self._axes(), self._cost,
                                       vmem_limit=64 * 1024,
                                       max_candidates=32)
        timed = []

        def run(cfg):
            timed.append(cfg)
            assert self._cost(cfg)["vmem_bytes"] <= 64 * 1024

        at.search("fused_ln_matmul", ("gen", 1), run, cands,
                  cost=self._cost, vmem_limit=64 * 1024,
                  warmup=0, iters=1)
        assert timed and all(c[0] * c[1] * 4 <= 64 * 1024 for c in timed)

    def test_tune_ln_matmul_generates_and_caches(self):
        x = _rand((64, 96))
        w = _rand((96, 64), 1)
        best, timings = fk.tune_ln_matmul(x, w, interpret=True)
        assert timings                 # searched (configs were timed)
        best2, t2 = fk.tune_ln_matmul(x, w, interpret=True)
        assert tuple(best2) == tuple(best) and t2 == {}


class TestSchemaBump:

    @pytest.fixture(autouse=True)
    def _restore_schema(self):
        at.cache_clear()
        orig = dict(at.KERNEL_SCHEMA)
        yield
        at.KERNEL_SCHEMA.clear()
        at.KERNEL_SCHEMA.update(orig)
        at.cache_clear()

    def test_bump_invalidates_then_reloads_without_research(self, tmp_path):
        key = (64, 96, 64, "float32", True)
        path = str(tmp_path / "tune.json")
        timed = []

        def run(cfg):
            timed.append(cfg)

        def cost(cfg):
            return {"flops": 1.0, "bytes": 1.0, "vmem_bytes": 0.0}

        cands = [(128, 128, 1), (256, 256, 1)]
        os.environ["PT_AUTOTUNE_CACHE"] = path
        try:
            at.search("fused_ln_matmul", key, run, cands, cost=cost,
                      warmup=0, iters=1)
            n_first = len(timed)
            assert n_first >= 2        # both survivors timed

            # a kernel-layout change bumps the schema: every entry
            # written under the old version becomes invisible
            at.bump_schema("fused_ln_matmul")
            assert at.cache_get("fused_ln_matmul", key) is None
            at.cache_clear()
            at.load_cache(path)        # stale entries dropped on load
            assert at.cache_get("fused_ln_matmul", key) is None

            # re-search under the new schema, then reload in a clean
            # cache: the bumped entry answers without re-searching
            at.search("fused_ln_matmul", key, run, cands, cost=cost,
                      warmup=0, iters=1)
            n_second = len(timed)
            assert n_second > n_first
            at.cache_clear()
            at.load_cache(path)
            _, timings = at.search("fused_ln_matmul", key, run, cands,
                                   cost=cost, warmup=0, iters=1)
            assert timings == {}       # pure cache hit across the bump
            assert len(timed) == n_second
        finally:
            os.environ.pop("PT_AUTOTUNE_CACHE", None)
