"""Graph auditor: rule fixtures over synthetic jaxprs, the runtime
hook layer, baseline round-trips, and the tier-1 self-clean gate that
keeps every in-tree captured/served program free of new findings.

Mirrors test_tpu_lint.py's structure: each rule gets a violating
builder (must fire) and a clean builder encoding the idiom the rule
pushes toward (must stay silent), so an over-triggering rule fails
here before it ever gates a real capture.
"""
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.tools.audit import (
    AuditProgram, RULES, audit_enabled, default_rules, rule_catalog,
    run_rules, walk_jaxprs,
)
from paddle_tpu.tools.audit import runtime
from paddle_tpu.tools.audit.baseline import (
    default_baseline_path, diff_against_baseline, load_baseline,
    write_baseline,
)
from paddle_tpu.tools.audit.core import Finding

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def audit(prog, select=None):
    return run_rules([prog], default_rules(select))


def fired(findings, rule):
    return [f for f in findings if f.rule == rule]


@pytest.fixture
def audit_on():
    """Enable the auditor for one test and always clear the process
    ledger afterwards (runtime state is module-global)."""
    runtime.reset()
    runtime.enable()
    yield
    runtime.reset()


# -- rule fixtures: violating + clean jaxpr builders -------------------------

def test_aud001_fires_on_conflicting_constraints():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1,), ("mp",))

    def reshard(x):
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("mp", None)))
        x = x * 2.0
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(None, "mp")))

    jx = jax.make_jaxpr(reshard)(jnp.ones((4, 4)))
    hits = fired(audit(AuditProgram("reshard", jx)), "AUD001")
    assert hits and hits[0].severity == "error"
    assert "reshard[" in hits[0].provenance


def test_aud001_silent_on_consistent_constraints():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1,), ("mp",))

    def ok(x):
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("mp", None)))
        x = x * 2.0
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("mp", None)))

    jx = jax.make_jaxpr(ok)(jnp.ones((4, 4)))
    assert not fired(audit(AuditProgram("ok", jx)), "AUD001")


def test_aud001_warns_on_non_canon_axis():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1,), ("rogue",))

    def f(x):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("rogue")))

    jx = jax.make_jaxpr(f)(jnp.ones(4))
    hits = fired(audit(AuditProgram("rogue_axis", jx)), "AUD001")
    assert hits and hits[0].severity == "warning"
    assert "axis[" in hits[0].provenance


def test_aud002_fires_on_upcast_then_dot():
    def bad(a, b):
        return jnp.dot(a.astype(jnp.float32), b.astype(jnp.float32))

    jx = jax.make_jaxpr(bad)(jnp.ones((8, 8), jnp.bfloat16),
                             jnp.ones((8, 8), jnp.bfloat16))
    hits = fired(audit(AuditProgram("bad_amp", jx, kind="capture")),
                 "AUD002")
    assert hits and hits[0].severity == "error"
    assert "dot_general" in hits[0].provenance


def test_aud002_silent_on_preferred_element_type():
    # the accumulation contract: bf16 operands, f32 accumulation INSIDE
    # the dot — no standalone upcast, full MXU rate
    def good(a, b):
        return jax.lax.dot_general(
            a, b, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    jx = jax.make_jaxpr(good)(jnp.ones((8, 8), jnp.bfloat16),
                              jnp.ones((8, 8), jnp.bfloat16))
    assert not fired(audit(AuditProgram("good_amp", jx)), "AUD002")


def test_aud002_silent_on_native_f32_dot():
    # no narrow source anywhere: f32-in/f32-out is not a leak
    jx = jax.make_jaxpr(jnp.dot)(jnp.ones((8, 8)), jnp.ones((8, 8)))
    assert not fired(audit(AuditProgram("f32_dot", jx)), "AUD002")


def test_aud003_donation_both_ways():
    # the state-sized arg has a same-shape output: undonated -> the
    # buffer is allocated twice per step; donated -> aliased, silent
    def step(w, x):
        return w + 0.1 * x, jnp.sum(x)

    big = jnp.ones((512, 1024), jnp.float32)      # 2 MiB > 1 MiB floor
    jx = jax.make_jaxpr(step)(big, big)

    undonated = AuditProgram("step", jx, kind="capture",
                             arg_names=["w", "x"])
    hits = fired(audit(undonated), "AUD003")
    assert hits and hits[0].nbytes == 512 * 1024 * 4
    assert "undonated[w:" in hits[0].provenance

    donated = AuditProgram("step", jx, kind="capture", donated=[0],
                           arg_names=["w", "x"])
    assert not fired(audit(donated), "AUD003")


def test_aud003_small_buffers_below_floor_are_silent(monkeypatch):
    def step(w):
        return w * 2.0

    jx = jax.make_jaxpr(step)(jnp.ones((8, 8), jnp.float32))
    assert not fired(audit(AuditProgram("tiny", jx, kind="capture")),
                     "AUD003")
    # the floor is a lazily read env knob
    monkeypatch.setenv("PT_AUDIT_DONATION_MIN_BYTES", "1")
    assert fired(audit(AuditProgram("tiny", jx, kind="capture")),
                 "AUD003")


def test_aud004_callback_severity_tracks_program_kind():
    def with_cb(x):
        jax.debug.print("tok {}", x[0])
        return x * 2

    jx = jax.make_jaxpr(with_cb)(jnp.ones(4))
    # on the serving request path a host callback stalls a live
    # request: error.  In a training capture it is a warning.
    serve_hits = fired(audit(AuditProgram("dec", jx, kind="serve")),
                       "AUD004")
    assert serve_hits and serve_hits[0].severity == "error"
    cap_hits = fired(audit(AuditProgram("step", jx, kind="capture")),
                     "AUD004")
    assert cap_hits and cap_hits[0].severity == "warning"


def test_aud004_silent_on_pure_program():
    jx = jax.make_jaxpr(lambda x: x * 2)(jnp.ones(4))
    assert not fired(audit(AuditProgram("dec", jx, kind="serve")),
                     "AUD004")


def _ln_jaxpr():
    def ln(x, g, b):
        mu = jnp.mean(x, -1, keepdims=True)
        v = jnp.var(x, -1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(v + 1e-5) * g + b

    return jax.make_jaxpr(ln)(jnp.ones((4, 64)), jnp.ones(64),
                              jnp.ones(64))


def test_aud005_fires_when_expected_fusion_missing():
    prog = AuditProgram("ln_step", _ln_jaxpr(), kind="capture",
                        fusion_expected=True, fusion_rewrites={})
    hits = fired(audit(prog), "AUD005")
    assert hits
    assert any("layer_norm" in f.provenance for f in hits)


def test_aud005_silent_when_cluster_was_rewritten():
    prog = AuditProgram("ln_step", _ln_jaxpr(), kind="capture",
                        fusion_expected=True,
                        fusion_rewrites={"layer_norm": 1})
    assert not fired(audit(prog), "AUD005")


def test_aud005_silent_when_fusion_not_expected():
    # fusion pass off (flag, or a program it never saw): no indictment
    prog = AuditProgram("ln_step", _ln_jaxpr(), kind="capture",
                        fusion_expected=False, fusion_rewrites={})
    assert not fired(audit(prog), "AUD005")


def test_aud006_fires_on_shared_dequant():
    # one int8→f32 convert feeding two dots: the f32 copy outlives both
    def bad(w_q, x1, x2):
        w = w_q.astype(jnp.float32)
        return x1 @ w, x2 @ w

    jx = jax.make_jaxpr(bad)(jnp.ones((8, 8), jnp.int8),
                             jnp.ones((4, 8)), jnp.ones((4, 8)))
    hits = fired(audit(AuditProgram("srv", jx, kind="serve")), "AUD006")
    assert hits and hits[0].severity == "error"
    assert "dequant[" in hits[0].provenance and "x2]" in hits[0].provenance


def test_aud006_silent_on_per_dot_dequant():
    # the w8a16_matmul_reference form: one convert per dot, scale in
    # the epilogue — each upcast fuses into the dot it feeds
    def good(w_q, s, x1, x2):
        a = (x1 @ w_q.astype(jnp.float32)) * s
        b = (x2 @ w_q.astype(jnp.float32)) * s
        return a, b

    jx = jax.make_jaxpr(good)(jnp.ones((8, 8), jnp.int8), jnp.ones((8,)),
                              jnp.ones((4, 8)), jnp.ones((4, 8)))
    assert not fired(audit(AuditProgram("srv", jx, kind="serve")),
                     "AUD006")


def test_aud006_warning_outside_serve_and_follows_elementwise():
    # capture programs warn rather than error, and the walk follows the
    # scale multiply (dequant = convert * scale) to both dots
    def bad(w_q, s, x1, x2):
        w = w_q.astype(jnp.float32) * s
        return x1 @ w, x2 @ w

    jx = jax.make_jaxpr(bad)(jnp.ones((8, 8), jnp.int8), jnp.ones((8,)),
                             jnp.ones((4, 8)), jnp.ones((4, 8)))
    hits = fired(audit(AuditProgram("cap", jx, kind="capture")), "AUD006")
    assert hits and hits[0].severity == "warning"


def test_aud006_int8_serve_ladder_is_clean(audit_on):
    # the shipped int8 engine satisfies its own rule: every dequant in
    # the AOT ladder feeds exactly one dot
    from paddle_tpu.serving import ModelSpec, ServeConfig, init_params
    from paddle_tpu.serving.engine import ServingEngine
    spec = ModelSpec(vocab_size=64, hidden=32, layers=2, heads=2,
                     max_seq_len=64)
    cfg = ServeConfig(decode_buckets=(2,), prefill_buckets=(16,),
                      kv_pages=32, page_size=8, precision="int8")
    engine = ServingEngine(spec, init_params(spec, seed=0), cfg)
    engine.close()
    progs = runtime.snapshot()["programs"]
    assert any(p.endswith("_int8") for p in progs)
    assert not [f for f in runtime.findings()
                if f.program.endswith("_int8")]


# -- machinery ---------------------------------------------------------------

def test_catalog_covers_all_five_rule_classes():
    cat = rule_catalog()
    ids = {rid for rid, _, _ in cat}
    assert {"AUD001", "AUD002", "AUD003", "AUD004",
            "AUD005"} <= ids
    for rid, name, rationale in cat:
        assert rid.startswith("AUD") and len(rid) == 6
        assert name and rationale


def test_walk_jaxprs_descends_into_pjit_bodies():
    inner = jax.jit(lambda x: x * 2 + 1)

    def outer(x):
        return inner(x) + 3

    jx = jax.make_jaxpr(outer)(jnp.ones(4))
    paths = [p for _, p in walk_jaxprs(jx)]
    assert "" in paths
    assert any(p for p in paths if p)  # at least one nested body


def test_rules_detect_hazards_in_nested_bodies():
    # a callback buried in a jitted sub-function must still be found
    def cb_inner(x):
        jax.debug.print("x {}", x[0])
        return x

    inner = jax.jit(cb_inner)
    jx = jax.make_jaxpr(lambda x: inner(x) * 2)(jnp.ones(4))
    hits = fired(audit(AuditProgram("nested", jx, kind="serve")),
                 "AUD004")
    assert hits
    assert "pjit" in hits[0].message


def test_crashing_rule_becomes_finding_not_exception():
    class Broken:
        id = "AUD999"

        def check(self, prog):
            raise RuntimeError("boom")

    jx = jax.make_jaxpr(lambda x: x)(jnp.ones(2))
    out = run_rules([AuditProgram("p", jx)], [Broken()])
    assert len(out) == 1
    assert out[0].rule == "AUD999"
    assert out[0].provenance == "rule-error"
    assert "boom" in out[0].message


def test_select_and_env_disable_narrow_the_rule_set(monkeypatch):
    def bad(a, b):
        return jnp.dot(a.astype(jnp.float32), b.astype(jnp.float32))

    jx = jax.make_jaxpr(bad)(jnp.ones((8, 8), jnp.bfloat16),
                             jnp.ones((8, 8), jnp.bfloat16))
    prog = AuditProgram("bad_amp", jx, kind="capture")
    assert fired(audit(prog), "AUD002")
    # --select semantics: only the chosen rules instantiate
    assert not audit(prog, select=["AUD004"])
    with pytest.raises(KeyError):
        default_rules(["AUD999"])
    # PT_AUDIT_DISABLE is the hook-side (rule-level) suppression — the
    # IR has no line to hang a disable comment on
    monkeypatch.setenv("PT_AUDIT_DISABLE", "AUD002")
    assert not fired(run_rules([prog], default_rules()), "AUD002")


# -- baseline round-trips ----------------------------------------------------

def _finding(prov="dot_general[8x8<-bf16]"):
    return Finding(rule="AUD002", severity="error", program="step",
                   provenance=prov, message="leak")


def test_baseline_round_trip(tmp_path):
    bl = str(tmp_path / "baseline.txt")
    assert write_baseline(bl, [_finding()]) == 1
    new, old, stale = diff_against_baseline([_finding()],
                                            load_baseline(bl))
    assert new == [] and len(old) == 1 and stale == []


def test_baseline_catches_new_and_stale(tmp_path):
    bl = str(tmp_path / "baseline.txt")
    write_baseline(bl, [_finding()])
    fresh = _finding(prov="undonated[w:f32[512,1024]]")
    new, old, stale = diff_against_baseline([fresh], load_baseline(bl))
    assert len(new) == 1 and new[0] is fresh
    assert old == [] and len(stale) == 1


def test_baseline_is_a_multiset(tmp_path):
    # two identical findings need two baseline entries — the third is new
    bl = str(tmp_path / "baseline.txt")
    write_baseline(bl, [_finding(), _finding()])
    new, old, _ = diff_against_baseline(
        [_finding(), _finding(), _finding()], load_baseline(bl))
    assert len(old) == 2 and len(new) == 1


# -- runtime hooks -----------------------------------------------------------

def test_audit_off_by_default_and_knob_is_lazy(monkeypatch):
    runtime.reset()
    monkeypatch.delenv("PT_AUDIT", raising=False)
    assert not audit_enabled()
    monkeypatch.setenv("PT_AUDIT", "1")   # after import: still honored
    assert audit_enabled()
    monkeypatch.setenv("PT_AUDIT", "0")
    assert not audit_enabled()
    runtime.enable()
    assert audit_enabled()                # programmatic override wins
    runtime.reset()


def test_audit_program_ledgers_and_books_metric(audit_on):
    def bad(a, b):
        return jnp.dot(a.astype(jnp.float32), b.astype(jnp.float32))

    jx = jax.make_jaxpr(bad)(jnp.ones((8, 8), jnp.bfloat16),
                             jnp.ones((8, 8), jnp.bfloat16))
    found = runtime.audit_program(
        AuditProgram("bad_amp", jx, kind="capture"))
    assert found
    snap = runtime.snapshot()
    assert snap["enabled"] and snap["programs"] == ["bad_amp"]
    assert snap["by_rule"].get("AUD002", 0) >= 1
    assert snap["by_severity"].get("error", 0) >= 1
    from paddle_tpu.observability.metrics import get_registry
    text = get_registry().prometheus_text()
    assert "pt_audit_findings_total" in text
    assert 'rule="AUD002"' in text


def test_capture_hook_audits_first_replay_only(audit_on):
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    pt.seed(0)
    model = nn.Linear(8, 8)
    opt = pt.optimizer.SGD(learning_rate=0.1,
                           parameters=model.parameters())
    mse = nn.MSELoss()

    @pt.jit.capture_step
    def small_step(x, y):
        loss = mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = pt.to_tensor(np.ones((4, 8), np.float32))
    y = pt.to_tensor(np.zeros((4, 8), np.float32))
    for _ in range(3):
        small_step(x, y)

    snap = runtime.snapshot()
    audited = [p for p in snap["programs"] if "small_step" in p]
    assert len(audited) == 1, (
        "the audit must run once per signature at compile time, "
        f"never per replay: {snap['programs']}")
    # a tiny clean step: params are donated, everything under the
    # donation floor, no callbacks — zero error findings
    assert not [f for f in runtime.findings()
                if "small_step" in f.program and f.severity == "error"]


def test_serving_hook_audits_every_bucket_program(audit_on):
    import tempfile
    from paddle_tpu.serving import (ModelSpec, ServeConfig, init_params,
                                    load_engine, save_served_model)
    spec = ModelSpec(vocab_size=64, hidden=32, layers=2, heads=2,
                     max_seq_len=64)
    cfg = ServeConfig(decode_buckets=(4,), prefill_buckets=(16,),
                      kv_pages=32, page_size=4, max_inflight=16,
                      max_new_tokens=8)
    with tempfile.TemporaryDirectory() as root:
        save_served_model(root, spec, init_params(spec, seed=0),
                          config=cfg)
        engine = load_engine(root)
        engine.close()
    progs = runtime.snapshot()["programs"]
    assert any(p.startswith("serve_prefill_s") for p in progs)
    assert any(p.startswith("serve_decode_b") for p in progs)
    # the shipped engine satisfies its own auditor: zero findings of
    # any severity on the AOT ladder
    assert not [f for f in runtime.findings()
                if f.program.startswith("serve_")]


def test_disabled_audit_costs_nothing_on_capture():
    import paddle_tpu as pt
    runtime.reset()  # no enable(): default off

    @pt.jit.capture_step
    def mul_step(a, b):
        return a * b

    x = pt.to_tensor(np.ones((4, 4), np.float32))
    mul_step(x, x)
    assert runtime.snapshot()["programs"] == []


# -- the tier-1 self-clean gate ----------------------------------------------

def test_cli_gate_exits_zero():
    """Every in-tree reference program (bench GPT captured step + the
    served-engine AOT ladder) audits clean against the committed
    baseline — new IR-level hazards fail tier-1 from this commit on."""
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.tools.audit"],
        cwd=ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=420)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 new findings" in out.stdout


def test_cli_list_rules():
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.tools.audit", "--list-rules"],
        cwd=ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=120)
    assert out.returncode == 0
    for rid in ("AUD001", "AUD002", "AUD003", "AUD004", "AUD005"):
        assert rid in out.stdout


def test_cli_rejects_unknown_select():
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.tools.audit",
         "--select", "AUD999"],
        cwd=ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=120)
    assert out.returncode == 2


def test_committed_baseline_only_carries_known_nearmisses():
    """The grandfathered set stays tiny and understood: only the GPT
    backward-recompute gelu near-misses (bench.py documents why the
    grad-side clusters can't fuse).  Anything else must be fixed, not
    baselined."""
    bl = load_baseline(default_baseline_path())
    assert sum(bl.values()) <= 2
    for key in bl:
        assert "AUD005::nearmiss" in key, key
