"""Deterministic fault injection for the crash-consistent checkpoint layer.

The checkpoint commit protocol funnels every durable byte through two
module-level functions of ``paddle_tpu.distributed.checkpoint`` —
``_write_file`` (each shard / index / COMMIT marker) and ``_replace_dir``
(the atomic promote).  Patching exactly those two lets a test simulate a
SIGKILL at ANY point of a save without subprocesses or timing games:

    with FaultInjector(fail_after=2):
        mgr.save(step, state)          # raises KilledSave mid-save
    # disk now holds whatever a real crash would have left behind

``corrupt_file`` / ``truncate_file`` simulate post-commit bit-rot and
torn writes for the integrity-verification paths.  ``flip_bit`` is the
in-memory counterpart — a deterministic single-bit tensor corruption
for the SDC consensus drills and content-digest tests — and
``poison_shard`` plants a bit-flip in a committed shard file while
re-sealing the COMMIT manifest CRC over the corrupted bytes, modelling
corruption that happened *before* serialization: only the per-leaf
content digests can catch it.
"""
import os

from paddle_tpu.distributed import checkpoint as ckpt
# canonical fault primitives live in the drill package (the drill
# worker/runner cannot import tests/); re-exported here so unit tests
# and drills share ONE definition of each corruption
from paddle_tpu.distributed.drill.runner import poison_shard  # noqa: F401
from paddle_tpu.distributed.drill.worker import flip_bit  # noqa: F401

__all__ = ["KilledSave", "FaultInjector", "corrupt_file", "truncate_file",
           "data_files", "flip_bit", "poison_shard"]


class KilledSave(BaseException):
    """The injected "process died here" signal.

    Derives from BaseException on purpose: recovery code under test that
    does ``except Exception`` must not be able to swallow a simulated
    SIGKILL — a real one is not catchable either.
    """


class FaultInjector:
    """Kill a save deterministically after the Nth durable file write.

    Args:
        fail_after: number of file writes allowed to land; the next one
            raises :class:`KilledSave`.  0 kills before any byte hits
            disk.  ``None`` never kills on write (use with
            ``fail_before_rename``).
        partial_bytes: when set, the killing write first lands this many
            bytes of its payload — a torn write, the worst case for a
            crash mid-``write(2)``.
        fail_before_rename: let every write land, then kill between the
            staging directory becoming complete and the atomic rename —
            the narrowest crash window of the protocol.

    The patch is scoped to the ``with`` block and restores the original
    functions even when the injected kill propagates.
    """

    def __init__(self, fail_after=0, partial_bytes=None,
                 fail_before_rename=False):
        if fail_after is None and not fail_before_rename:
            raise ValueError("fail_after=None requires fail_before_rename")
        self.fail_after = fail_after
        self.partial_bytes = partial_bytes
        self.fail_before_rename = fail_before_rename
        self.writes = 0          # writes that actually landed

    def __enter__(self):
        self._orig_write = ckpt._write_file
        self._orig_replace = ckpt._replace_dir
        self.writes = 0

        def _write(path, data, durable=True):
            if (self.fail_after is not None
                    and self.writes >= self.fail_after):
                if self.partial_bytes is not None:
                    self._orig_write(path, data[:self.partial_bytes],
                                     durable=durable)
                raise KilledSave(
                    f"injected kill at write #{self.writes + 1} "
                    f"({os.path.basename(path)})")
            self.writes += 1
            return self._orig_write(path, data, durable=durable)

        def _replace(tmp, final):
            if self.fail_before_rename:
                raise KilledSave(
                    f"injected kill before atomic rename of {tmp}")
            return self._orig_replace(tmp, final)

        ckpt._write_file = _write
        ckpt._replace_dir = _replace
        return self

    def __exit__(self, *exc):
        ckpt._write_file = self._orig_write
        ckpt._replace_dir = self._orig_replace
        return False  # let KilledSave propagate to the test


def corrupt_file(path, offset=-1, flip=0xFF):
    """Flip one byte in place (CRC mismatch, size unchanged).

    ``offset`` < 0 counts from the end of the file.  XOR with ``flip``
    (default 0xFF) guarantees the byte changes.
    """
    with open(path, "r+b") as f:
        if offset < 0:
            f.seek(offset, os.SEEK_END)
        else:
            f.seek(offset)
        pos = f.tell()
        b = f.read(1)
        if not b:
            raise ValueError(f"offset {offset} out of range for {path}")
        f.seek(pos)
        f.write(bytes([b[0] ^ flip]))


def truncate_file(path, keep=None):
    """Drop bytes from the end (size mismatch — a torn/partial write).

    ``keep`` defaults to half the current size."""
    size = os.path.getsize(path)
    if keep is None:
        keep = size // 2
    with open(path, "r+b") as f:
        f.truncate(keep)


def data_files(ckpt_dir):
    """Sorted relative paths of every shard file under ``ckpt_dir``."""
    out = []
    data_root = os.path.join(ckpt_dir, "data")
    for root, _dirs, files in os.walk(data_root):
        for fn in files:
            out.append(os.path.relpath(os.path.join(root, fn), ckpt_dir))
    return sorted(out)


