"""Step-phase tracer tests: ring buffer, phase spans, overlap math,
Chrome export + cluster merge, analytic MFU, and the flight recorder.

Everything here follows the telemetry contract: disabled hooks are
no-ops, enable is explicit (or env-driven through ``get_tracer()``),
and nothing ever syncs the device or raises off the hot path.  The
multi-process half (per-rank exports stitched across real workers,
flight-on-SIGKILL) lives in ``tests/drills/test_trace_drills.py``.
"""
from __future__ import annotations

import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu.observability as obs
from paddle_tpu.observability.merge import (
    discover_trace_files, merge_traces,
)
from paddle_tpu.observability.trace import (
    PEAK_FLOPS, PHASES, Tracer, current_tracer, get_tracer, peak_flops,
    program_flops, reset_tracer,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    # env must never leak enablement into (or out of) a test
    for var in ("PT_TELEMETRY", "PT_TELEMETRY_DIR", "PT_METRICS_PORT",
                "PT_RECOMPILE_THRESHOLD", "PT_PROCESS_INDEX", "PT_RUN_ID",
                "PADDLE_TRAINER_ID", "PT_TRACE", "PT_TRACE_DIR",
                "PT_FLIGHT_RECORDER"):
        monkeypatch.delenv(var, raising=False)
    obs.reset()
    yield
    obs.reset()


# -- lifecycle / env enablement ---------------------------------------------

def test_singleton_disabled_by_default_and_hooks_noop(tmp_path):
    tr = get_tracer()
    assert tr.enabled is False
    assert current_tracer() is tr
    # every hook is a no-op while disabled
    with tr.phase("backward"):
        pass
    tr.phase_record("backward", 0, 10)
    tr.record_span("x", "compute", 0, 10)
    tr.on_step(0.1)
    assert tr.spans() == []
    assert tr.flight_dump() is None
    snap = tr.snapshot()
    assert snap["enabled"] is False
    assert snap["spans"] == 0


def test_env_pt_trace_auto_enables(monkeypatch, tmp_path):
    monkeypatch.setenv("PT_TRACE", "1")
    monkeypatch.setenv("PT_TRACE_DIR", str(tmp_path))
    tr = get_tracer()
    assert tr.enabled
    assert tr.trace_dir == str(tmp_path)
    assert tr.flight_path is None


def test_env_flight_recorder_implies_enable_and_arms(monkeypatch, tmp_path):
    flight = tmp_path / "flight"
    monkeypatch.setenv("PT_FLIGHT_RECORDER", str(flight))
    tr = get_tracer()
    assert tr.enabled
    assert tr.flight_path is not None
    # arming dumps immediately: a SIGKILL can land before the first
    # watchdog refresh and must still find a parseable file
    with open(tr.flight_path) as f:
        doc = json.load(f)
    assert doc["reason"] == "armed"
    assert doc["process_index"] == tr.process_index
    assert doc["run_id"] == tr.run_id


def test_enable_idempotent_and_identity_override(tmp_path):
    tr = Tracer()
    tr.enable(process_index=3, run_id="r9", trace_dir=str(tmp_path))
    tr.enable()  # second enable must not reset anything
    assert tr.process_index == 3 and tr.run_id == "r9"
    assert tr.default_trace_path().endswith("trace-r9-3.json")


# -- ring buffer + phase spans -----------------------------------------------

def test_ring_buffer_bounded_keeps_newest():
    tr = Tracer(capacity=8).enable()
    for i in range(20):
        tr.record_span(f"s{i}", "host", i, i + 1)
    spans = tr.spans()
    assert len(spans) == 8
    assert [s.name for s in spans] == [f"s{i}" for i in range(12, 20)]


def test_phase_ctx_manager_records_span_and_histogram():
    tr = Tracer().enable()
    with tr.phase("backward"):
        pass
    spans = tr.spans()
    assert len(spans) == 1
    assert spans[0].name == "backward" and spans[0].cat == "compute"
    assert spans[0].t1_ns >= spans[0].t0_ns
    assert "backward" in tr.phase_percentiles_ms()


def test_phase_span_skipped_inside_jax_trace():
    import jax

    tr = Tracer().enable()

    @jax.jit
    def f(x):
        with tr.phase("forward"):
            return x + 1

    f(np.ones(2, np.float32))
    # the trace ran the body, but wall-timing a tracer is meaningless:
    # no span may land
    assert tr.spans() == []


def test_phase_taxonomy_categories():
    tr = Tracer().enable()
    for p in PHASES:
        tr.phase_record(p, 0, 10)
    cats = {s.name: s.cat for s in tr.spans()}
    assert cats["forward"] == cats["backward"] == cats["optimizer"] \
        == "compute"
    assert cats["collective"] == "collective"
    assert cats["data_wait"] == cats["checkpoint"] == "host"


# -- overlap fraction --------------------------------------------------------

def test_overlap_fraction_math():
    tr = Tracer().enable()
    tr.record_span("bwd", "compute", 0, 100)
    tr.record_span("ar", "collective", 50, 150)
    assert tr.overlap_fraction() == pytest.approx(0.5)


def test_overlap_fraction_none_without_collectives():
    tr = Tracer().enable()
    tr.record_span("bwd", "compute", 0, 100)
    assert tr.overlap_fraction() is None


def test_overlap_fraction_merges_compute_and_caps_at_one():
    tr = Tracer().enable()
    # two overlapping compute spans must merge, not double-count
    tr.record_span("a", "compute", 0, 80)
    tr.record_span("b", "compute", 40, 120)
    tr.record_span("ar", "collective", 0, 100)
    assert tr.overlap_fraction() == pytest.approx(1.0)


# -- Chrome export + cluster merge -------------------------------------------

def test_export_chrome_without_path_raises():
    tr = Tracer().enable()
    with pytest.raises(ValueError):
        tr.export_chrome()


def test_chrome_export_roundtrips_through_merge(tmp_path):
    """Two standalone rank tracers export; ``merge --trace`` semantics
    stitch them into one timeline with pid = rank and a single
    process_name metadata event per rank."""
    trace_dir = str(tmp_path)
    for rank in (0, 1):
        tr = Tracer().enable(trace_dir=trace_dir, process_index=rank,
                             run_id="mergetest")
        tr.record_span("backward", "compute", 1000, 2000)
        tr.record_span("all_reduce", "collective", 1500, 2500)
        out = tr.export_chrome()
        assert out == os.path.join(trace_dir,
                                   f"trace-mergetest-{rank}.json")
    # a corrupt file must be skipped, never fatal
    with open(os.path.join(trace_dir, "trace-mergetest-2.json"), "w") as f:
        f.write("{not json")
    files = discover_trace_files([trace_dir])
    assert len(files) == 3
    doc, skipped = merge_traces(files)
    assert skipped == 1
    evs = doc["traceEvents"]
    meta = [e for e in evs if e.get("ph") == "M"]
    xs = [e for e in evs if e.get("ph") == "X"]
    assert {m["pid"] for m in meta} == {0, 1}
    assert {e["pid"] for e in xs} == {0, 1}
    assert len(xs) == 4
    ts = [e["ts"] for e in xs]
    assert ts == sorted(ts)
    for e in xs:
        assert set(e) >= {"name", "cat", "ts", "dur", "pid", "tid"}
        assert e["args"]["run_id"] == "mergetest"


def test_counter_events_export_as_chrome_counter_track():
    """record_counter lands ph:"C" events (the memory watermark track)
    carrying the series values in args, on the rank's pid."""
    tr = Tracer().enable(process_index=5)
    tr.record_counter("device_memory", 1_000_000,
                      {"bytes_in_use": 1024.0, "fragmentation": 64.0})
    tr.record_counter("device_memory", 2_000_000,
                      {"bytes_in_use": 2048.0, "fragmentation": 0.0})
    assert tr.snapshot()["counters"] == 2
    cs = [e for e in tr.chrome_events() if e["ph"] == "C"]
    assert len(cs) == 2
    for e in cs:
        assert e["name"] == "device_memory"
        assert e["pid"] == 5
        assert set(e["args"]) == {"bytes_in_use", "fragmentation"}
    assert cs[0]["ts"] < cs[1]["ts"]
    assert cs[1]["args"]["bytes_in_use"] == 2048.0
    # counters ride the ring-buffer clear like spans (the process_name
    # meta event survives by design)
    tr.clear()
    assert tr.counters() == []
    assert [e for e in tr.chrome_events() if e["ph"] != "M"] == []


def test_counter_hooks_noop_while_disabled():
    tr = Tracer()
    tr.record_counter("device_memory", 0, {"bytes_in_use": 1.0})
    assert tr.counters() == []


def test_merge_trace_stitches_counter_tracks_per_rank(tmp_path):
    """``merge --trace`` with counter events interleaved among duration
    spans: every rank's C events keep their pid (per-rank track
    identity), the merged stream stays ts-ordered across BOTH event
    kinds, and a corrupt per-rank file is skipped, never fatal."""
    trace_dir = str(tmp_path)
    for rank in (0, 1):
        tr = Tracer().enable(trace_dir=trace_dir, process_index=rank,
                             run_id="memtrack")
        # counters interleave INSIDE the span window on purpose
        tr.record_span("backward", "compute", 1000, 5000)
        tr.record_counter("device_memory", 2000,
                          {"bytes_in_use": float(100 * (rank + 1))})
        tr.record_counter("device_memory", 4000,
                          {"bytes_in_use": float(200 * (rank + 1))})
        tr.record_span("optimizer", "compute", 5000, 6000)
        assert tr.export_chrome() is not None
    with open(os.path.join(trace_dir, "trace-memtrack-7.json"),
              "w") as f:
        f.write("{torn")
    files = discover_trace_files([trace_dir])
    assert len(files) == 3
    doc, skipped = merge_traces(files)
    assert skipped == 1
    evs = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
    cs = [e for e in evs if e["ph"] == "C"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(cs) == 4 and len(xs) == 4
    # per-rank track identity: each rank's counter series survives on
    # its own pid with its own values
    for rank in (0, 1):
        mine = [e["args"]["bytes_in_use"] for e in cs
                if e["pid"] == rank]
        assert mine == [100.0 * (rank + 1), 200.0 * (rank + 1)]
    # one ts-ordered stream across spans AND counters
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)
    # and the counters really interleave among the duration events
    kinds = [e["ph"] for e in sorted(evs, key=lambda e: e["ts"])
             if e["pid"] == 0]
    assert kinds.index("C") > 0 and "X" in kinds[kinds.index("C"):]


# -- analytic MFU ------------------------------------------------------------

def test_peak_flops_prefix_matching():
    assert peak_flops("TPU v5 lite podslice") == PEAK_FLOPS["TPU v5 lite"]
    assert peak_flops("TPU v4") == PEAK_FLOPS["TPU v4"]
    assert peak_flops("cpu") == PEAK_FLOPS["cpu"]
    assert peak_flops("Banana9000") is None
    assert peak_flops(None) is None


def test_program_flops_and_mfu_on_cpu_jit():
    import jax
    import jax.numpy as jnp

    x = jnp.ones((64, 64), jnp.float32)
    jitted = jax.jit(lambda a: a @ a)
    flops = program_flops(jitted, x)
    assert flops and flops > 0
    tr = Tracer().enable()
    tr.record_program_flops("matmul", flops)
    assert tr.flops_per_step() == flops
    # backend is initialized by the lowering above, so device_kind is
    # the cpu backend's and the nominal cpu peak applies
    mfu = tr.mfu_analytic(step_seconds=0.01)
    assert mfu == pytest.approx(flops / (0.01 * PEAK_FLOPS["cpu"]))


def test_mfu_none_when_factors_missing():
    tr = Tracer().enable()
    assert tr.mfu_analytic(step_seconds=0.01) is None  # no flops
    tr.record_program_flops("p", 1e9)
    assert tr.mfu_analytic() is None  # no step time yet


def test_on_step_refreshes_overlap_and_mfu():
    tr = Tracer().enable()
    tr.record_span("bwd", "compute", 0, 100)
    tr.record_span("ar", "collective", 50, 150)
    tr.record_program_flops("p", 1e9)
    tr.on_step(0.25)
    assert tr._last_step_seconds == 0.25
    assert tr._last_overlap == pytest.approx(0.5)
    snap = tr.snapshot()
    assert snap["overlap_fraction"] == pytest.approx(0.5)
    assert snap["flops_per_step"] == 1e9


# -- flight recorder ---------------------------------------------------------

def test_flight_dump_document(tmp_path):
    tr = Tracer().enable(flight_dir=str(tmp_path), process_index=2,
                         run_id="fr")
    tr.record_span("bwd", "compute", 0, 100)
    path = tr.flight_dump(reason="manual")
    assert path == str(tmp_path / "flight-fr-2.json")
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "manual"
    assert doc["process_index"] == 2 and doc["run_id"] == "fr"
    assert {"ts", "pid", "last_step_seconds", "overlap_fraction",
            "mfu_analytic", "program_flops", "spans",
            "telemetry"} <= set(doc)
    assert doc["spans"][-1]["name"] == "bwd"


def test_flight_watchdog_refreshes_from_hot_path(tmp_path):
    import time

    tr = Tracer().enable(flight_dir=str(tmp_path))
    tr._flight_last_ns = 0  # force the cadence check to fire
    now = time.perf_counter_ns()
    tr.phase_record("backward", now - 100, now)
    with open(tr.flight_path) as f:
        doc = json.load(f)
    assert doc["reason"] == "watchdog"
    assert tr._flight_last_ns > 0


def test_excepthook_dumps_then_chains(tmp_path, monkeypatch):
    seen = []
    monkeypatch.setattr(sys, "excepthook",
                        lambda *a: seen.append(a))
    tr = Tracer().enable(flight_dir=str(tmp_path))
    assert sys.excepthook == tr._excepthook
    err = ValueError("boom")
    sys.excepthook(ValueError, err, None)
    with open(tr.flight_path) as f:
        assert json.load(f)["reason"] == "crash:ValueError"
    assert seen and seen[0][1] is err  # previous hook still ran
    tr.disable()
    assert sys.excepthook is not tr._excepthook  # restored


def test_flight_dump_never_raises_on_bad_dir(tmp_path):
    target = tmp_path / "blocked"
    target.write_text("a file, not a dir")
    tr = Tracer().enable()
    tr.flight_path = str(target / "flight-x-0.json")
    assert tr.flight_dump() is None
    assert tr.dropped == 1


# -- integration: RecordEvent / capture / hapi / telemetry -------------------

def test_record_event_feeds_tracer():
    from paddle_tpu.core import RecordEvent

    tr = get_tracer().enable()
    with RecordEvent("io_read"):
        pass
    spans = tr.spans()
    assert [s.name for s in spans] == ["io_read"]
    assert spans[0].cat == "host"


def test_capture_step_harvests_flops_and_compute_spans():
    import paddle_tpu as pt
    import paddle_tpu.nn as nn

    tr = get_tracer().enable()
    pt.seed(0)
    model = nn.Linear(4, 2)
    opt = pt.optimizer.SGD(learning_rate=0.1,
                           parameters=model.parameters())
    mse = nn.MSELoss()

    @pt.jit.capture_step
    def step(x, y):
        loss = mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = pt.to_tensor(np.random.randn(4, 4).astype(np.float32))
    y = pt.to_tensor(np.random.randn(4, 2).astype(np.float32))
    for _ in range(3):
        step(x, y)
    assert tr.flops_per_step() and tr.flops_per_step() > 0
    # the first call traces+compiles and is booked honestly as a
    # compile: host span (badput); the two replays are compute spans
    comp = [s for s in tr.spans() if s.cat == "compute"]
    assert len(comp) == 2
    compiles = [s for s in tr.spans()
                if s.cat == "host" and s.name.startswith("compile:")]
    assert len(compiles) == 1
    assert tr.mfu_analytic(step_seconds=1.0) is not None


def test_hapi_fit_records_step_phases():
    import paddle_tpu as pt
    from paddle_tpu.vision.datasets import FakeData

    tr = get_tracer().enable()
    net = pt.nn.Sequential(pt.nn.Flatten(), pt.nn.Linear(3 * 8 * 8, 4))
    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.SGD(learning_rate=0.01,
                                   parameters=net.parameters()),
        loss=pt.nn.CrossEntropyLoss())
    model.fit(FakeData(size=32, image_shape=(3, 8, 8), num_classes=4),
              epochs=1, batch_size=16, verbose=0)
    phases = set(tr.phase_percentiles_ms())
    assert {"backward", "optimizer"} <= phases


def test_collective_bytes_histogram():
    from paddle_tpu.observability import get_registry, get_telemetry

    tel = get_telemetry().enable()
    tel.collective_op("all_reduce", nbytes=4096)
    tel.collective_op("all_reduce", nbytes=8192)
    snap = get_registry().snapshot()
    hist = snap["pt_collective_bytes"]["series"]["op=all_reduce"]
    assert hist["count"] == 2
    assert hist["sum"] == 12288
    assert snap["pt_collective_bytes_total"]["series"]["op=all_reduce"] \
        == 12288
    text = get_registry().prometheus_text()
    assert "pt_collective_bytes_bucket" in text


def test_observe_step_feeds_tracer_gauges():
    from paddle_tpu.observability import get_telemetry

    tr = get_tracer().enable()
    tel = get_telemetry().enable()
    tr.record_span("bwd", "compute", 0, 100)
    tr.record_span("ar", "collective", 0, 100)
    tel.observe_step(0.125)
    assert tr._last_step_seconds == 0.125
    assert tr._last_overlap == pytest.approx(1.0)


def test_healthz_surfaces_flight_path(tmp_path):
    from paddle_tpu.observability import get_telemetry

    tr = get_tracer().enable(flight_dir=str(tmp_path))
    tel = get_telemetry().enable()
    doc = tel.healthz()
    assert doc["flight_recorder"] == tr.flight_path


# -- aggregator retention ----------------------------------------------------

def test_retention_buffer_evicts_and_downsamples():
    from paddle_tpu.observability.aggregator import RetentionBuffer

    buf = RetentionBuffer(retention=10.0, max_points=8)
    for t in range(12):
        buf.append(float(t), {"v": t})
    pts = buf.points()
    # ts=12-built window: points older than last-10s are gone, and the
    # cap forced at least one halving pass on the older half
    assert all(ts >= 11 - 10.0 for ts, _ in pts)
    assert len(pts) <= 8
    assert pts[-1][0] == 11.0
    assert buf.downsampled_total > 0
    s = buf.summary()
    assert s["retention_seconds"] == 10.0
    assert s["max_points"] == 8
    assert s["points"] == len(pts)
    assert s["downsampled_total"] == buf.downsampled_total
    assert s["span_seconds"] >= 0


def test_retention_buffer_keeps_recent_resolution():
    from paddle_tpu.observability.aggregator import RetentionBuffer

    buf = RetentionBuffer(retention=1e9, max_points=4)
    for t in range(8):
        buf.append(float(t), t)
    pts = buf.points()
    # the newest points always survive downsampling intact
    assert pts[-1] == (7.0, 7)
    assert pts[-2] == (6.0, 6)
