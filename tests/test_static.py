"""Static-graph tests (paddle_tpu.static).

Mirrors the reference's test strategy (SURVEY.md §4): op tests run through
BOTH dygraph and static paths and compare (the OpTest dual-execution
pattern, ref test/legacy_test/eager_op_test.py:2146 check_output), plus
executor/program/scope behavior tests (ref test/standalone_executor/) and
end-to-end static training (ref test/book/).
"""
from __future__ import annotations

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu import static


@pytest.fixture
def static_mode():
    pt.enable_static()
    yield
    pt.disable_static()


def _run_prog(build, feeds, fetch_names=None, n_steps=1, fetch=None):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        fetches = build()
    exe = static.Executor()
    exe.run(startup)
    outs = None
    for _ in range(n_steps):
        outs = exe.run(main, feed=feeds, fetch_list=list(fetches))
    return outs


class TestProgramBuild:
    def test_data_and_variable_meta(self, static_mode):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 16], "float32")
            assert x.shape == [-1, 16]
            eye = pt.to_tensor(np.eye(16, dtype=np.float32))
            y = pt.matmul(x, eye)
            assert isinstance(y, static.Variable)
        assert main.nodes

    def test_eval_shape_metadata_no_compute(self, static_mode):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4, 8], "float32")
            h = F.relu(pt.matmul(x, pt.transpose(x, [1, 0])))
            assert h.shape == [4, 4]
            assert isinstance(h, static.Variable)
            with pytest.raises(RuntimeError):
                h.numpy()

    def test_dynamic_dim_propagates(self, static_mode):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 16], "float32")
            eye = pt.to_tensor(np.eye(16, dtype=np.float32))
            y = pt.matmul(x, eye)
            assert y.shape == [-1, 16]
            s = F.relu(y).sum(axis=1)
            assert s.shape == [-1]

    def test_fc_num_flatten_dims(self, static_mode):
        main = static.Program()
        with static.program_guard(main):
            z = static.data("z", [2, 3, 4], "float32")
            out = static.nn.fc(z, 5, num_flatten_dims=2)
            assert out.shape == [2, 3, 5]
        Z = np.random.RandomState(0).randn(2, 3, 4).astype(np.float32)
        r, = static.Executor().run(main, feed={"z": Z}, fetch_list=[out])
        assert r.shape == (2, 3, 5)

    def test_clone_for_test_does_not_train(self, static_mode):
        pt.seed(0)
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [8, 4], "float32")
            loss = (pt.nn.Linear(4, 2)(x) ** 2).mean()
            pt.optimizer.SGD(learning_rate=0.5).minimize(loss)
        test_prog = main.clone(for_test=True)
        exe = static.Executor()
        exe.run(startup)
        X = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        key = next(iter(main.scope_tensors))
        l1, = exe.run(test_prog, feed={"x": X}, fetch_list=[loss])
        w1 = np.asarray(static.global_scope().find_var(key))
        l2, = exe.run(test_prog, feed={"x": X}, fetch_list=[loss])
        np.testing.assert_allclose(
            w1, np.asarray(static.global_scope().find_var(key)))
        np.testing.assert_allclose(l1, l2)
        # training program still updates
        l3, = exe.run(main, feed={"x": X}, fetch_list=[loss])
        l4, = exe.run(main, feed={"x": X}, fetch_list=[loss])
        assert float(l4) < float(l3)

    def test_empty_program_fetches_feed(self, static_mode):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [3], "float32")
        out, = static.Executor().run(
            main, feed={"x": np.arange(3, dtype=np.float32)},
            fetch_list=[x])
        np.testing.assert_allclose(out, [0, 1, 2])

    def test_fetch_by_name(self, static_mode):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [3], "float32")
            y = pt.exp(x)
        exe = static.Executor()
        out, = exe.run(main, feed={"x": np.zeros(3, np.float32)},
                       fetch_list=[y.name])
        np.testing.assert_allclose(out, np.ones(3), rtol=1e-6)


class TestDualPathParity:
    """The OpTest pattern: same computation, dygraph vs static executor."""

    CASES = [
        ("matmul+relu", lambda x: F.relu(pt.matmul(x, pt.transpose(x, [1, 0])))),
        ("softmax", lambda x: F.softmax(x, axis=-1)),
        ("mean+mul", lambda x: (x * 3.0 + 1.0).mean(axis=0)),
        ("layer_norm", lambda x: F.layer_norm(x, x.shape[-1])),
    ]

    @pytest.mark.parametrize("name,fn", CASES, ids=[c[0] for c in CASES])
    def test_parity(self, name, fn):
        rng = np.random.RandomState(7)
        X = rng.randn(4, 6).astype(np.float32)
        eager = fn(pt.to_tensor(X)).numpy()
        pt.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main):
                x = static.data("x", [4, 6], "float32")
                out = fn(x)
            res, = static.Executor().run(main, feed={"x": X},
                                         fetch_list=[out])
        finally:
            pt.disable_static()
        np.testing.assert_allclose(res, eager, rtol=1e-5, atol=1e-6)

    def test_layer_parity(self):
        rng = np.random.RandomState(3)
        X = rng.randn(5, 12).astype(np.float32)
        pt.seed(11)
        lin = pt.nn.Linear(12, 7)
        eager = lin(pt.to_tensor(X)).numpy()
        pt.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main):
                x = static.data("x", [5, 12], "float32")
                out = lin(x)  # same layer object, same params
            res, = static.Executor().run(main, feed={"x": X},
                                         fetch_list=[out])
        finally:
            pt.disable_static()
        np.testing.assert_allclose(res, eager, rtol=1e-5, atol=1e-6)


class TestBackward:
    def test_append_backward_matches_numeric(self, static_mode):
        rng = np.random.RandomState(0)
        X = rng.randn(6, 4).astype(np.float32)
        pt.seed(5)
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [6, 4], "float32")
            lin = pt.nn.Linear(4, 3)
            loss = (lin(x) ** 2).mean()
            grads = static.append_backward(loss)
        exe = static.Executor()
        exe.run(startup)
        fetch = [gv for (_, gv) in grads]
        outs = exe.run(main, feed={"x": X}, fetch_list=[loss] + fetch)
        loss0, gw = outs[0], outs[1]
        # numeric diff on the first weight element
        scope = static.global_scope()
        wkey = grads[0][0].name
        W = np.asarray(scope.find_var(wkey))
        eps = 1e-3
        Wp = W.copy()
        Wp.flat[0] += eps
        scope.set(wkey, pt.to_tensor(Wp)._data)
        lp = exe.run(main, feed={"x": X}, fetch_list=[loss])[0]
        scope.set(wkey, pt.to_tensor(W)._data)
        num = (lp - loss0) / eps
        np.testing.assert_allclose(gw.flat[0], num, rtol=2e-2, atol=2e-3)

    def test_gradients_wrt_input(self, static_mode):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [5], "float32")
            y = (x ** 3).sum()
            (gx,) = static.gradients([y], [x])
        X = np.arange(5, dtype=np.float32)
        res, = static.Executor().run(main, feed={"x": X}, fetch_list=[gx])
        np.testing.assert_allclose(res, 3 * X ** 2, rtol=1e-5)

    def test_gradients_multi_target_and_intermediate(self, static_mode):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [3], "float32")
            h = x * 2
            a = (x ** 2).sum()
            b = (x ** 3).sum()
            (gab,) = static.gradients([a, b], [x])     # d(a+b)/dx
            (gh,) = static.gradients([(h ** 2).sum()], [h])  # wrt intermediate
        X = np.array([1., 2., 3.], np.float32)
        ra, rh = static.Executor().run(main, feed={"x": X},
                                       fetch_list=[gab, gh])
        np.testing.assert_allclose(ra, 2 * X + 3 * X ** 2, rtol=1e-5)
        np.testing.assert_allclose(rh, 4 * X, rtol=1e-5)

    def test_gradients_cotangent_seed(self, static_mode):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [3], "float32")
            tg = static.data("tg", [3], "float32")
            y = x * x
            (gx,) = static.gradients([y], [x], target_gradients=[tg])
        X = np.array([1., 2., 3.], np.float32)
        T = np.array([5., 7., 11.], np.float32)
        res, = static.Executor().run(main, feed={"x": X, "tg": T},
                                     fetch_list=[gx])
        np.testing.assert_allclose(res, 2 * X * T, rtol=1e-5)

    @pytest.mark.slow
    def test_deep_program_no_recursion_limit(self, static_mode):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            v = x
            for _ in range(1500):
                v = v + 1.0
        out, = static.Executor().run(
            main, feed={"x": np.zeros(2, np.float32)}, fetch_list=[v])
        np.testing.assert_allclose(out, 1500)


class TestStaticTraining:
    def test_sgd_converges(self, static_mode):
        pt.seed(0)
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [64, 16], "float32")
            y = static.data("y", [64], "int64")
            h = F.relu(pt.nn.Linear(16, 32)(x))
            loss = F.cross_entropy(pt.nn.Linear(32, 2)(h), y)
            opt = pt.optimizer.SGD(learning_rate=0.5)
            opt.minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        X = rng.randn(64, 16).astype(np.float32)
        Y = (X @ rng.randn(16) > 0).astype(np.int64)
        losses = [float(exe.run(main, feed={"x": X, "y": Y},
                                fetch_list=[loss])[0]) for _ in range(40)]
        assert losses[-1] < losses[0] / 3

    def test_adam_state_in_scope_and_lr_scheduler(self, static_mode):
        pt.seed(0)
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [16, 8], "float32")
            loss = (pt.nn.Linear(8, 1)(x) ** 2).mean()
            sched = pt.optimizer.lr.StepDecay(learning_rate=0.1,
                                              step_size=1, gamma=0.5)
            opt = pt.optimizer.Adam(learning_rate=sched)
            opt.minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        scope = static.global_scope()
        assert any("@state@" in k for k in scope.vars), \
            "optimizer accumulators must live in the scope"
        X = np.random.RandomState(1).randn(16, 8).astype(np.float32)
        l0 = float(exe.run(main, feed={"x": X}, fetch_list=[loss])[0])
        sched.step()  # host-side LR change must NOT recompile (host input)
        exe2 = exe  # same cache
        n_cache = len(exe2._cache)
        l1 = float(exe.run(main, feed={"x": X}, fetch_list=[loss])[0])
        assert len(exe2._cache) == n_cache
        assert l1 < l0

    def test_shape_specialization_cache(self, static_mode):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 4], "float32")
            y = (x * 2).sum(axis=1)
        exe = static.Executor()
        for bs in (2, 8, 2):
            out, = exe.run(main, feed={"x": np.ones((bs, 4), np.float32)},
                           fetch_list=[y])
            np.testing.assert_allclose(out, np.full(bs, 8.0))
        assert len(exe._cache) == 2  # one executable per feed shape


class TestScopeAndIO:
    def test_scope_guard_isolation(self, static_mode):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [2, 3], "float32")
            out = pt.nn.Linear(3, 2)(x)
        exe = static.Executor()
        s1, s2 = static.Scope(), static.Scope()
        X = np.ones((2, 3), np.float32)
        with static.scope_guard(s1):
            exe.run(startup)
            r1, = exe.run(main, feed={"x": X}, fetch_list=[out])
        with static.scope_guard(s2):
            exe.run(startup)
            key = next(iter(main.scope_tensors))
            s2.set(key, s2.find_var(key) * 0)  # zero the weight here only
            r2, = exe.run(main, feed={"x": X}, fetch_list=[out])
        with static.scope_guard(s1):
            r1b, = exe.run(main, feed={"x": X}, fetch_list=[out])
        np.testing.assert_allclose(r1, r1b)
        assert not np.allclose(r1, r2)

    def test_save_load_round_trip(self, static_mode, tmp_path):
        pt.seed(2)
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4, 6], "float32")
            out = pt.nn.Linear(6, 3)(x)
        exe = static.Executor()
        exe.run(startup)
        X = np.random.RandomState(0).randn(4, 6).astype(np.float32)
        before, = exe.run(main, feed={"x": X}, fetch_list=[out])
        path = str(tmp_path / "model")
        static.save(main, path)
        scope = static.global_scope()
        key = next(iter(main.scope_tensors))
        scope.set(key, scope.find_var(key) * 0 + 7)
        static.load(main, path)
        after, = exe.run(main, feed={"x": X}, fetch_list=[out])
        np.testing.assert_allclose(before, after)

    def test_inference_model_export(self, static_mode, tmp_path):
        pt.seed(3)
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [2, 5], "float32")
            logits = pt.nn.Linear(5, 3)(x)
        exe = static.Executor()
        exe.run(startup)
        X = np.random.RandomState(1).randn(2, 5).astype(np.float32)
        want, = exe.run(main, feed={"x": X}, fetch_list=[logits])
        prefix = str(tmp_path / "infer")
        static.save_inference_model(prefix, [x], [logits], exe)
        prog, feed_names, fetch_names = static.load_inference_model(prefix)
        assert feed_names == ["x"]
        got = np.asarray(prog(pt.to_tensor(X)._data)[0])
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_inference_export_dynamic_batch(self, static_mode, tmp_path):
        """Dynamic feed dims export shape-polymorphically: the artifact must
        accept batch sizes other than the representative one."""
        pt.seed(4)
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 4], "float32")
            bn = pt.nn.BatchNorm1D(4)
            bn.eval()
            y = bn(x) * 2.0
        # eval-mode BN running stats are scope vars, not baked constants
        exe = static.Executor()
        exe.run(startup)
        assert len(main.scope_tensors) >= 4  # weight/bias/mean/variance
        prefix = str(tmp_path / "dyn")
        static.save_inference_model(prefix, [x], [y], exe)
        prog, _, _ = static.load_inference_model(prefix)
        for bs in (2, 5):
            out = np.asarray(prog(pt.to_tensor(
                np.ones((bs, 4), np.float32))._data)[0])
            assert out.shape == (bs, 4)
