"""Serving resilience: deadlines, cancellation, load shedding,
graceful drain, fault isolation, and the hang watchdog.

The load-bearing guarantees under test:

 - an abandoned request NEVER keeps decoding on borrowed KV pages:
   ``result(timeout)`` cancels on timeout and the pool returns to
   baseline even after a timeout storm (the page-leak regression pin);
 - deadlines are enforced at step boundaries — queued or active, an
   expired request is evicted with its pages released and resolves
   with ``DeadlineExceeded``;
 - the load shedder refuses infeasible work at admission (429-shaped
   ``RequestShed``) instead of queueing it to die;
 - one poisoned request — or one failed device step — fails alone;
   the step loop keeps serving and every page comes back;
 - SIGTERM's ``drain_gracefully`` finishes in-flight work inside the
   budget and sheds new admissions while draining;
 - a hung decode step trips the watchdog: ``hang_detected`` flips
   /healthz without needing the (held) scheduler lock.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from paddle_tpu.serving import (
    DeadlineExceeded, ModelSpec, PagePool, RequestCancelled, RequestShed,
    ServeConfig, ServingEngine, init_params)
from paddle_tpu.serving.scheduler import ContinuousScheduler

SPEC = ModelSpec(vocab_size=64, hidden=32, layers=2, heads=2,
                 max_seq_len=64)
CFG = ServeConfig(decode_buckets=(4,), prefill_buckets=(16,),
                  kv_pages=32, page_size=4, max_inflight=16,
                  max_new_tokens=8)


@pytest.fixture(scope="module")
def engine():
    eng = ServingEngine(SPEC, init_params(SPEC, seed=0), CFG)
    yield eng
    eng.close()


def _fresh(engine):
    """A scheduler with clean stats over the shared (pre-built) engine;
    the pool is shared, so every test must leave it at baseline."""
    return ContinuousScheduler(engine)


def _assert_pool_baseline(engine):
    snap = engine.pool.snapshot()
    assert snap["used_pages"] == 0, snap
    assert snap["reserved_pages"] == 0, snap
    engine.pool.check_consistency(expect_all_free=True)


# -- result(timeout) cancels: the page-leak regression pin -------------------

def test_result_timeout_cancels_queued_request(engine):
    sched = _fresh(engine)
    st = sched.submit([1, 2, 3])
    with pytest.raises(TimeoutError):
        st.result(timeout=0.01)
    assert st.cancel_cause == "timeout"
    assert sched.stats["cancelled"] == 1
    # the queue no longer owes this request any work
    assert sched.snapshot()["queue_depth"] == 0
    _assert_pool_baseline(engine)


def test_result_timeout_storm_releases_every_page(engine):
    """Six abandoned requests mid-decode: every page and reservation
    must come back — this is the leak ``result(timeout)`` used to
    have."""
    sched = _fresh(engine)
    streams = [sched.submit([1, 2, 3, 4], max_new_tokens=8)
               for _ in range(6)]
    sched.step()  # admit + first decode step: pages now allocated
    assert engine.pool.snapshot()["used_pages"] > 0
    for st in streams:
        with pytest.raises(TimeoutError):
            st.result(timeout=0.001)
    assert sched.stats["cancelled"] == 6
    _assert_pool_baseline(engine)
    # the loop is still healthy after the storm
    st = sched.submit([5, 6], max_new_tokens=4)
    sched.drain()
    assert len(st.result(timeout=5.0)) == 4
    _assert_pool_baseline(engine)


def test_cancel_api_queued_active_and_done(engine):
    sched = _fresh(engine)
    a = sched.submit([1, 2], max_new_tokens=4)
    b = sched.submit([3, 4], max_new_tokens=4)
    assert sched.cancel(a.request_id) is True          # queued
    with pytest.raises(RequestCancelled) as ei:
        a.result(timeout=1.0)
    assert ei.value.cause == "client"
    sched.step()                                       # admit b
    assert sched.cancel(b.request_id, cause="client") is True  # active
    with pytest.raises(RequestCancelled):
        b.result(timeout=1.0)
    c = sched.submit([5, 6], max_new_tokens=2)
    sched.drain()
    assert len(c.result(timeout=5.0)) == 2
    assert sched.cancel(c.request_id) is False         # already done
    _assert_pool_baseline(engine)


# -- deadlines ---------------------------------------------------------------

def test_deadline_evicts_mid_decode(engine, monkeypatch):
    """An active request whose deadline passes is evicted at the next
    step boundary with partial tokens and zero leaked pages."""
    sched = _fresh(engine)
    orig = engine.decode

    def slow_decode(*args, **kw):
        time.sleep(0.02)
        return orig(*args, **kw)

    monkeypatch.setattr(engine, "decode", slow_decode)
    st = sched.submit([1, 2, 3], max_new_tokens=8, deadline_ms=50)
    deadline = time.monotonic() + 10.0
    while not st.done() and time.monotonic() < deadline:
        sched.step()
    with pytest.raises(DeadlineExceeded):
        st.result(timeout=1.0)
    assert st.cancel_cause == "deadline"
    assert len(st.tokens) < 8          # partial: it was cut mid-decode
    assert sched.stats["deadline_exceeded"] == 1
    _assert_pool_baseline(engine)


def test_shed_infeasible_deadline_at_admission(engine):
    """Once throughput is measured, a deadline the backlog can't meet
    is refused at submit — not queued to die."""
    sched = _fresh(engine)
    sched._step_ewma = 0.05            # 50ms/step measured
    with pytest.raises(RequestShed) as ei:
        sched.submit([1, 2, 3], max_new_tokens=8, deadline_ms=10)
    assert ei.value.reason == "deadline_infeasible"
    assert sched.stats["shed"] == 1
    # before any throughput measurement the shedder admits
    # optimistically — the step-boundary sweep still backstops it
    sched2 = _fresh(engine)
    st = sched2.submit([1, 2, 3], max_new_tokens=8, deadline_ms=10)
    sched2.cancel(st.request_id)
    _assert_pool_baseline(engine)


def test_shed_queue_full_evicts_expired_first(engine, monkeypatch):
    monkeypatch.setattr(engine, "config", engine.config.replace(
        max_queue=2))
    sched = _fresh(engine)
    doomed = sched.submit([1, 2], max_new_tokens=4, deadline_ms=1)
    sched.submit([3, 4], max_new_tokens=4)
    time.sleep(0.005)                  # doomed's deadline passes
    # the bounded queue makes room by evicting the expired entry
    # (oldest first) instead of refusing fresh work
    st = sched.submit([5, 6], max_new_tokens=4)
    with pytest.raises(DeadlineExceeded):
        doomed.result(timeout=1.0)
    # now genuinely full: two live entries, no expired to evict
    with pytest.raises(RequestShed) as ei:
        sched.submit([7, 8], max_new_tokens=4)
    assert ei.value.reason == "queue_full"
    sched.drain()
    assert len(st.result(timeout=5.0)) == 4
    _assert_pool_baseline(engine)


def test_shed_while_draining_and_healthz(engine, monkeypatch):
    sched = _fresh(engine)
    monkeypatch.setattr(engine, "scheduler", sched)
    sched.begin_drain()
    with pytest.raises(RequestShed) as ei:
        sched.submit([1, 2])
    assert ei.value.reason == "draining"
    health = engine.healthz()
    assert health["ok"] is False and health["draining"] is True


# -- fault isolation ---------------------------------------------------------

def test_decode_failure_fails_batch_not_engine(engine, monkeypatch):
    """A failed device step fails every RESIDENT request — pages
    returned — and the loop keeps serving the next submission."""
    sched = _fresh(engine)
    streams = [sched.submit([1, 2, 3], max_new_tokens=8)
               for _ in range(3)]
    orig = engine.decode
    monkeypatch.setattr(
        engine, "decode",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")))
    sched.step()                       # admit + the poisoned step
    for st in streams:
        with pytest.raises(RuntimeError, match="boom"):
            st.result(timeout=1.0)
    assert sched.stats["failed"] == 3
    _assert_pool_baseline(engine)
    monkeypatch.setattr(engine, "decode", orig)
    st = sched.submit([4, 5], max_new_tokens=4)
    sched.drain()
    assert len(st.result(timeout=5.0)) == 4
    _assert_pool_baseline(engine)


def test_poisoned_row_fails_alone(engine):
    """Per-row isolation: one request whose post-step bookkeeping
    raises fails by itself; its batch neighbours decode to completion
    and its pages come back."""

    class _BoomTokens(list):
        def append(self, _x):
            raise RuntimeError("row poison")

    sched = _fresh(engine)
    victim = sched.submit([1, 2, 3], max_new_tokens=8)
    others = [sched.submit([4, 5, 6], max_new_tokens=8)
              for _ in range(2)]
    sched.step()                       # admit everyone (prefill token)
    victim.tokens = _BoomTokens(victim.tokens)
    sched.drain()
    with pytest.raises(RuntimeError, match="row poison"):
        victim.result(timeout=1.0)
    for st in others:
        assert len(st.result(timeout=5.0)) == 8
    assert sched.stats["failed"] == 1
    assert sched.stats["completed"] == 2
    _assert_pool_baseline(engine)


# -- graceful drain ----------------------------------------------------------

def test_drain_gracefully_finishes_inflight(engine):
    sched = _fresh(engine)
    streams = [sched.submit([1, 2], max_new_tokens=4) for _ in range(3)]
    clean = sched.drain_gracefully(budget_s=10.0)
    assert clean is True
    for st in streams:
        assert len(st.result(timeout=1.0)) == 4
    assert sched.stats["drain_seconds"] is not None
    assert sched.draining is True
    with pytest.raises(RequestShed):
        sched.submit([3, 4])
    _assert_pool_baseline(engine)


def test_drain_budget_cancels_leftovers(engine, monkeypatch):
    """A drain whose budget expires cancels the stragglers with
    ``cause="drain"`` — pages released, nothing hangs."""
    sched = _fresh(engine)
    orig = engine.decode

    def slow_decode(*args, **kw):
        time.sleep(0.05)
        return orig(*args, **kw)

    monkeypatch.setattr(engine, "decode", slow_decode)
    streams = [sched.submit([1, 2], max_new_tokens=8) for _ in range(2)]
    sched.step()                       # admitted, now mid-decode
    clean = sched.drain_gracefully(budget_s=0.0)
    assert clean is False
    for st in streams:
        with pytest.raises(RequestCancelled) as ei:
            st.result(timeout=1.0)
        assert ei.value.cause == "drain"
    _assert_pool_baseline(engine)


# -- hang watchdog -----------------------------------------------------------

def test_watchdog_trips_on_hung_step(engine, monkeypatch):
    monkeypatch.setenv("PT_SERVE_WATCHDOG", "1")
    monkeypatch.setenv("PT_SERVE_WATCHDOG_FLOOR_S", "0.2")
    sched = _fresh(engine)
    monkeypatch.setattr(engine, "scheduler", sched)
    orig = engine.decode

    def hung_decode(*args, **kw):
        time.sleep(1.0)
        return orig(*args, **kw)

    monkeypatch.setattr(engine, "decode", hung_decode)
    sched.start()
    try:
        st = sched.submit([1, 2, 3], max_new_tokens=4)
        deadline = time.monotonic() + 10.0
        while not sched.hang_detected and time.monotonic() < deadline:
            time.sleep(0.02)
        assert sched.hang_detected is True
        assert sched.stats["watchdog_trips"] == 1
        health = engine.healthz()
        assert health["ok"] is False and health["hang_detected"] is True
        st.result(timeout=10.0)        # the slow step does finish here
    finally:
        sched.stop(timeout=10.0)
    _assert_pool_baseline(engine)


def test_watchdog_stays_quiet_on_healthy_load(engine, monkeypatch):
    monkeypatch.setenv("PT_SERVE_WATCHDOG", "1")
    monkeypatch.setenv("PT_SERVE_WATCHDOG_FLOOR_S", "1.0")
    sched = _fresh(engine)
    sched.start()
    try:
        st = sched.submit([1, 2], max_new_tokens=8)
        assert len(st.result(timeout=10.0)) == 8
        assert sched.hang_detected is False
        assert sched.stats["watchdog_trips"] == 0
    finally:
        sched.stop(timeout=10.0)
    _assert_pool_baseline(engine)


# -- pool clean-slate proof --------------------------------------------------

def test_check_consistency_expect_all_free():
    pool = PagePool(layers=1, pages=8, page_size=4, heads=1, head_dim=4)
    got = pool.alloc(2)
    pool.check_consistency()           # internally consistent...
    with pytest.raises(AssertionError):
        pool.check_consistency(expect_all_free=True)  # ...but not empty
    pool.free(got)
    pool.check_consistency(expect_all_free=True)
    pool.reserve(1)
    with pytest.raises(AssertionError):
        pool.check_consistency(expect_all_free=True)
    pool.release_reservation(1)
    pool.check_consistency(expect_all_free=True)


# -- HTTP error mapping (kept last: the server owns the shared engine's
#    scheduler lifecycle) ----------------------------------------------------

def _post(base, path, obj, timeout=30.0):
    data = json.dumps(obj).encode()
    req = urllib.request.Request(
        base + path, data=data,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), e.headers


def test_http_resilience_status_mapping(engine):
    """429 + Retry-After (shed), 503 (draining), 504 (wall timeout),
    499 (client cancel via /v1/cancel) — the full refusal taxonomy over
    one server."""
    from paddle_tpu.serving.http import ServeHTTPServer

    srv = ServeHTTPServer(engine, port=0, request_timeout=0.5).start()
    base = f"http://{srv.host}:{srv.port}"
    sched = engine.scheduler
    hold = threading.Event()
    orig_step = sched.step

    def stalled_step():
        hold.wait(5.0)
        return orig_step()

    try:
        # -- 429 shed with a usable Retry-After ----------------------
        sched._step_ewma = 0.05
        status, body, hdrs = _post(base, "/v1/generate",
                                   {"tokens": [1, 2, 3],
                                    "max_new_tokens": 8,
                                    "deadline_ms": 10})
        assert status == 429
        assert body["reason"] == "deadline_infeasible"
        assert int(hdrs.get("Retry-After", 0)) >= 1
        sched._step_ewma = None

        # -- 503 while draining --------------------------------------
        sched.begin_drain()
        try:
            status, body, _h = _post(base, "/v1/generate",
                                     {"tokens": [1, 2]})
            assert status == 503 and body["reason"] == "draining"
            status, _b = _get_healthz(base)
            assert status == 503
        finally:
            sched._draining = False

        # -- 504: the handler's wall timeout cancels the request -----
        sched.step = stalled_step
        status, body, _h = _post(base, "/v1/generate",
                                 {"tokens": [1, 2], "max_new_tokens": 2})
        assert status == 504
        assert sched.snapshot()["queue_depth"] == 0  # cancelled, not left
        # -- 499: cancelled through /v1/cancel -----------------------
        results = []
        t = threading.Thread(
            target=lambda: results.append(
                _post(base, "/v1/generate",
                      {"tokens": [3, 4], "max_new_tokens": 4})))
        t.start()
        deadline = time.monotonic() + 5.0
        while not sched._queue and time.monotonic() < deadline:
            time.sleep(0.01)
        rid = sched._queue[0].request_id
        status, body, _h = _post(base, "/v1/cancel",
                                 {"request_id": rid})
        assert status == 200 and body["cancelled"] is True
        t.join(timeout=10.0)
        status, body, _h = results[0]
        assert status == 499 and body["cause"] == "client"
    finally:
        hold.set()
        sched.step = orig_step
        srv.stop()
    _assert_pool_baseline(engine)


def _get_healthz(base):
    try:
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())
