"""Silent-data-corruption sentry contract tests.

The same observability contract the numerics sentinels honor: inert
until enabled, the hot path never syncs the device (the fingerprint
packet inspected at a cadence boundary is the PREVIOUS one), and the
monitored captured step stays at exactly ONE compile with bit-identical
losses — the replica fingerprints ride inside the same program.

The consensus half is tested with a fake exchange (no store, no
subprocesses — the real multi-process proof is
tests/drills/test_sdc_drills.py): majority vote fingers the minority
rank, an even split names nobody, the first divergent digest index
names the first divergent tensor path, and a fingered self raises
``SdcHaltError`` only with halting armed.  The checkpoint half pins the
per-leaf content digests: a bit flip sealed UNDER the manifest CRC is
invisible to ``integrity="size"``/file-CRC verification and refused by
``integrity="full"`` naming the leaf.
"""
from __future__ import annotations

import os

import numpy as np
import pytest

import paddle_tpu.observability as obs
from paddle_tpu.observability.sdc import (
    SdcHaltError, fingerprint_outputs, get_monitor, reset_monitor,
    store_exchange,
)
from tests.fault_injection import flip_bit, poison_shard


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    for var in ("PT_SDC", "PT_SDC_CADENCE", "PT_SDC_HALT",
                "PT_NUMERICS", "PT_TELEMETRY", "PT_FLIGHT_RECORDER"):
        monkeypatch.delenv(var, raising=False)
    obs.reset()
    yield
    obs.reset()


# -- flip_bit: the canonical fault primitive ---------------------------------

def test_flip_bit_is_a_deterministic_single_bit_involution():
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    b = flip_bit(a, bit=3, index=5)
    assert b.shape == a.shape and b.dtype == a.dtype
    # exactly one element changed, and exactly one bit of it
    changed = np.nonzero(a != b)
    assert len(changed[0]) == 1
    xor = a.view(np.uint32) ^ b.view(np.uint32)
    assert np.count_nonzero(xor) == 1 and int(xor.max()) == 1 << 3
    # flipping the same bit again restores the original exactly
    assert flip_bit(b, bit=3, index=5).tobytes() == a.tobytes()
    # the input is never mutated
    assert a[1, 1] == 5.0


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32,
                                   np.int8, np.uint16])
def test_flip_bit_covers_dtypes_and_wraps_indices(dtype):
    a = np.ones(7, dtype=dtype)
    b = flip_bit(a, bit=0, index=7)  # wraps to index 0
    assert (a != b).sum() == 1 and a[0] != b[0]


# -- fingerprint_outputs: the in-graph half ----------------------------------

def test_fingerprint_changes_on_any_single_bit_flip():
    import jax

    a = np.arange(32, dtype=np.float32).reshape(4, 8)
    base = np.asarray(jax.jit(lambda x: fingerprint_outputs(
        {"w": x})[1])(a))
    for bit in (0, 13, 31):
        for index in (0, 17, 31):
            poisoned = np.asarray(jax.jit(lambda x: fingerprint_outputs(
                {"w": x})[1])(flip_bit(a, bit=bit, index=index)))
            assert poisoned.tobytes() != base.tobytes(), \
                f"bit {bit} at index {index} left the digest unchanged"


def test_fingerprint_distinguishes_bit_patterns_not_values():
    # -0.0 == +0.0 by value; a bit-pattern digest must tell them apart
    names, fp0 = fingerprint_outputs({"w": np.zeros(4, np.float32)})
    _, fp1 = fingerprint_outputs(
        {"w": np.array([0.0, -0.0, 0.0, 0.0], np.float32)})
    assert names == ("w",)
    assert np.asarray(fp0).tobytes() != np.asarray(fp1).tobytes()


def test_fingerprint_names_are_sorted_and_dtypes_covered():
    named = {
        "b::bool": np.array([True, False]),
        "a::f64": np.arange(3, dtype=np.float64),
        "c::i8": np.arange(4, dtype=np.int8),
        "d::f16": np.arange(5, dtype=np.float16),
    }
    names, fp = fingerprint_outputs(named)
    assert names == tuple(sorted(named))
    vec = np.asarray(fp)
    assert vec.shape == (4,) and vec.dtype == np.int32
    # each slot is sensitive to its own tensor's bits
    named["c::i8"] = flip_bit(named["c::i8"], bit=1, index=2)
    vec2 = np.asarray(fingerprint_outputs(named)[1])
    assert vec2[2] != vec[2]
    assert (np.delete(vec2, 2) == np.delete(vec, 2)).all()


# -- consensus vote over a fake exchange -------------------------------------

def _fake_exchange(peer_digests):
    """exchange(step, digest) that returns a scripted peer map."""
    def exchange(step, digest):
        return dict(peer_digests)
    return exchange


def _vec(*words):
    return np.asarray(words, dtype=np.int32).tobytes()


def test_majority_fingers_the_minority_and_names_the_tensor():
    reset_monitor()
    mon = get_monitor().enable(cadence=1, halt=False, rank=0)
    good, bad = _vec(1, 2, 3), _vec(1, 99, 3)
    mon.exchange = _fake_exchange({0: good, 1: bad, 2: good})
    mon.watch(0, ("pa", "pb", "pc"), np.frombuffer(good, np.int32))
    mon.flush()
    snap = mon.snapshot()
    assert snap["votes"] >= 1
    assert snap["divergences"] == {"1": 1}
    last = snap["last_divergence"]
    # index 1 is the first divergent digest slot -> second tensor name
    assert last["rank"] == 1 and last["tensor"] == "pb"
    assert last["world"] == 3
    reset_monitor()


def test_even_split_names_nobody():
    reset_monitor()
    mon = get_monitor().enable(cadence=1, halt=False, rank=0)
    a, b = _vec(1), _vec(2)
    mon.exchange = _fake_exchange({0: a, 1: b})
    mon.watch(0, ("p",), np.frombuffer(a, np.int32))
    mon.flush()
    snap = mon.snapshot()
    assert snap["votes"] >= 1
    assert snap["divergences_total"] == 0  # refuse to guess at 1 vs 1
    reset_monitor()


def test_fingered_self_halts_only_when_armed():
    reset_monitor()
    mon = get_monitor().enable(cadence=1, halt=False, rank=1)
    good, bad = _vec(7), _vec(8)
    mon.exchange = _fake_exchange({0: good, 1: bad, 2: good})
    mon.watch(0, ("p",), np.frombuffer(bad, np.int32))
    mon.flush()  # halt disarmed: books the verdict, keeps going
    assert mon.divergence_count(1) == 1
    mon.enable(halt=True)
    mon.watch(1, ("p",), np.frombuffer(bad, np.int32))
    with pytest.raises(SdcHaltError) as ei:
        mon.flush()
    assert "process_index 1" in str(ei.value)
    assert mon.divergence_count(1) == 2
    reset_monitor()


def test_watch_inspects_previous_packet_at_cadence():
    reset_monitor()
    seen = []

    def exchange(step, digest):
        seen.append(step)
        return {0: digest}  # no quorum: the vote is a no-op

    mon = get_monitor().enable(cadence=4, halt=False, rank=0)
    mon.exchange = exchange
    fp = np.asarray([5], np.int32)
    for s in range(10):
        mon.watch(s, ("p",), fp)
    # reads happen one dispatch behind, every 4th observed step
    assert seen == [0, 4, 8]
    mon.flush()
    assert seen == [0, 4, 8, 9]
    snap = mon.snapshot()
    assert snap["steps_observed"] == 10 and snap["reads"] == 4
    assert snap["last_fingerprint"] is not None
    reset_monitor()


def test_disabled_monitor_is_inert_and_exchange_failure_is_nonfatal():
    reset_monitor()
    mon = get_monitor()
    mon.watch(0, ("p",), np.asarray([1], np.int32))
    assert mon.snapshot()["steps_observed"] == 0

    def broken(step, digest):
        raise ConnectionError("store hiccup")

    mon.enable(cadence=1, halt=True, rank=0)
    mon.exchange = broken
    mon.watch(0, ("p",), np.asarray([1], np.int32))
    mon.flush()  # the exchange failure downgrades to a warning
    assert mon.divergence_count() == 0
    reset_monitor()


def test_env_enablement(monkeypatch):
    monkeypatch.setenv("PT_SDC", "1")
    monkeypatch.setenv("PT_SDC_CADENCE", "7")
    monkeypatch.setenv("PT_SDC_HALT", "0")
    reset_monitor()
    mon = get_monitor()
    assert mon.enabled and mon.cadence == 7 and mon.halt is False
    reset_monitor()


# -- store_exchange over the real TCPStore -----------------------------------

def test_store_exchange_all_gathers_digests():
    from paddle_tpu.core import TCPStore

    master = TCPStore("127.0.0.1", 0, is_master=True)
    try:
        ex0 = store_exchange(master, "run", 0, 2, timeout=10.0)
        ex1 = store_exchange(master, "run", 1, 2, timeout=10.0)
        d0, d1 = _vec(1, 2), _vec(1, 3)
        # publish rank 1 first so rank 0's bounded wait finds it
        import threading
        out1 = {}
        t = threading.Thread(
            target=lambda: out1.update(ex1(5, d1)))
        t.start()
        out0 = ex0(5, d0)
        t.join(timeout=30)
        assert out0 == {0: d0, 1: d1}
        assert out1 == {0: d0, 1: d1}
    finally:
        master.close()


# -- the captured-step contract: 1 compile, bit-identical loss ---------------

def _mlp(seed):
    import paddle_tpu as pt
    import paddle_tpu.nn as nn

    np.random.seed(seed)
    pt.seed(seed)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                parameters=model.parameters())
    return model, opt


def _run_10(fingerprinted, cadence=3):
    import paddle_tpu as pt
    import paddle_tpu.nn as nn

    reset_monitor()
    if fingerprinted:
        get_monitor().enable(cadence=cadence, halt=False)
    model, opt = _mlp(seed=7)
    mse = nn.MSELoss()

    @pt.jit.capture_step
    def step(x, y):
        loss = mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.RandomState(3)
    x = pt.to_tensor(rng.randn(4, 8).astype(np.float32))
    y = pt.to_tensor(rng.randn(4, 1).astype(np.float32))
    losses = [np.asarray(step(x, y)._data).tobytes() for _ in range(10)]
    return losses, step.stats


def test_fingerprinted_capture_bitwise_identical_one_compile():
    base, _ = _run_10(fingerprinted=False)
    fp_losses, stats = _run_10(fingerprinted=True)
    # the fingerprints ride inside the same program: one compile ever
    assert stats["compiles"] == 1 and stats["hits"] == 9
    assert not stats["fallback"]
    # and never perturb the math: losses are bit-identical
    assert fp_losses == base
    mon = get_monitor()
    snap = mon.snapshot()
    assert snap["reads"] >= 2
    assert snap["divergences_total"] == 0  # standalone mode: no vote
    assert snap["last_fingerprint"] is not None
    reset_monitor()


def test_fingerprint_slots_cover_params_and_optimizer_state():
    import paddle_tpu as pt
    import paddle_tpu.nn as nn

    reset_monitor()
    get_monitor().enable(cadence=1, halt=False)
    model, opt = _mlp(seed=2)
    mse = nn.MSELoss()

    @pt.jit.capture_step
    def step(x, y):
        loss = mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    rng = np.random.RandomState(6)
    x = pt.to_tensor(rng.randn(4, 8).astype(np.float32))
    y = pt.to_tensor(rng.randn(4, 1).astype(np.float32))
    step(x, y)
    entry = next(iter(step._cache.values()))
    names = entry.sdc_names[0]
    assert any(n.startswith("param::") for n in names)
    assert any(n.startswith("opt0::") for n in names)
    assert list(names) == sorted(names)
    reset_monitor()


# -- checkpoint content digests ----------------------------------------------

def _save_one(tmp_path):
    from paddle_tpu.distributed.checkpoint import save_sharded

    path = str(tmp_path / "step_00000003")
    state = {"w": np.arange(24, dtype=np.float32).reshape(4, 6),
             "bias": np.ones(4, dtype=np.float32)}
    save_sharded(state, path, process_index=0, world_size=1)
    return path, state


def test_content_digest_round_trip_and_verify_full(tmp_path):
    from paddle_tpu.distributed.checkpoint import (read_leaf,
                                                   verify_checkpoint)

    path, state = _save_one(tmp_path)
    verify_checkpoint(path, integrity="full")
    got = read_leaf(path, "w", integrity="full")
    assert got.tobytes() == state["w"].tobytes()


def test_poisoned_shard_passes_size_and_crc_but_fails_full(tmp_path):
    from paddle_tpu.distributed.checkpoint import (
        CheckpointCorruptError, read_leaf, verify_checkpoint)

    path, state = _save_one(tmp_path)
    rel = poison_shard(path, bit=2)
    leaf = rel.split(os.sep)[1]
    # the flip is sealed UNDER the manifest CRC: file-level checks pass
    verify_checkpoint(path, integrity="size")
    np.testing.assert_array_equal(
        read_leaf(path, leaf, integrity="size").shape,
        state[leaf].shape)
    # only the per-leaf content digest refuses, naming the leaf
    with pytest.raises(CheckpointCorruptError) as ei:
        verify_checkpoint(path, integrity="full")
    msg = str(ei.value)
    assert "content digest" in msg and f"'{leaf}'" in msg
    assert "silent corruption" in msg
    with pytest.raises(CheckpointCorruptError):
        read_leaf(path, leaf, integrity="full")
