"""Fast unit tests for the self-healing supervisor (no drills here —
the real-subprocess end-to-end proof lives in
tests/drills/test_supervisor_drills.py).

Workers are real (tiny ``sys.executable -c`` children, so Popen
semantics are honest) but exit codes are scripted per generation, and
the budget ledger is exercised directly with an injected fake clock so
the rolling window is tested to the second without sleeping.
"""
from __future__ import annotations

import os
import subprocess
import sys

import pytest

from paddle_tpu.distributed import exit_codes
from paddle_tpu.distributed import supervisor as sup_mod
from paddle_tpu.distributed.exit_codes import (EXIT_DRAIN, EXIT_SAVE_FAILED,
                                               EXIT_SDC, EXIT_STORE_LOST,
                                               EXIT_TEMPFAIL, EXIT_WATCHDOG)
from paddle_tpu.distributed.supervisor import (RestartBudgetExhausted,
                                               SpawnFailed, Supervisor,
                                               supervision_snapshot)


def _child(code=0):
    return subprocess.Popen(
        [sys.executable, "-c", f"import sys; sys.exit({int(code)})"])


def _scripted(plan):
    """spawn() whose exit codes follow ``plan[generation][rank]``
    (missing entries exit 0); also journals every call."""
    calls = []

    def spawn(rank, world, run_id, generation):
        calls.append((generation, rank, world, run_id))
        code = plan.get(generation, {}).get(rank, 0)
        return _child(code)

    spawn.calls = calls
    return spawn


def _fast(spawn, world, **kw):
    kw.setdefault("backoff_base", 0.0)
    kw.setdefault("backoff_max", 0.0)
    kw.setdefault("grace", 5.0)
    kw.setdefault("generation_timeout", 60.0)
    return Supervisor(spawn, world, **kw)


# -- exit-code taxonomy (satellite: one canonical module) --------------------

def test_exit_code_taxonomy_is_canonical():
    assert (EXIT_SAVE_FAILED, EXIT_STORE_LOST, EXIT_SDC, EXIT_WATCHDOG,
            EXIT_TEMPFAIL, EXIT_DRAIN) == (17, 19, 25, 70, 75, 143)
    assert exit_codes.classify(0) == "ok"
    assert exit_codes.classify(EXIT_DRAIN) == "drain"
    assert exit_codes.classify(EXIT_TEMPFAIL) == "tempfail"
    assert exit_codes.classify(EXIT_WATCHDOG) == "watchdog"
    assert exit_codes.classify(EXIT_STORE_LOST) == "store_lost"
    assert exit_codes.classify(EXIT_SDC) == "sdc"
    assert exit_codes.classify(-9) == "killed"
    assert exit_codes.classify(1) == "crash"
    assert "store" in exit_codes.describe(EXIT_STORE_LOST)
    # the SDC verdict blames the machine, not the program — the
    # description must steer the operator at the hardware
    assert "hardware" in exit_codes.describe(EXIT_SDC)
    assert "sdc" in exit_codes.RESTARTABLE_CAUSES


def test_exit_codes_have_one_home():
    # the magic numbers must come from distributed/exit_codes.py, not be
    # re-declared: every other in-package definition is an import/re-export
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for name in ("EXIT_STORE_LOST", "EXIT_SDC"):
        out = subprocess.run(
            ["grep", "-rn", rf"{name}\s*=\s*[0-9]", "paddle_tpu/"],
            cwd=repo, capture_output=True, text=True).stdout
        homes = [ln for ln in out.splitlines() if ln.strip()]
        assert homes and all("distributed/exit_codes.py" in ln
                             for ln in homes), \
            f"{name} literal re-declared outside exit_codes.py: {homes}"


# -- clean + single-restart paths --------------------------------------------

def test_clean_fleet_single_generation():
    sup = _fast(_scripted({}), 2)
    snap = sup.run()
    assert snap["final_rcs"] == {0: 0, 1: 0}
    assert snap["generations"] == 1
    assert snap["restarts_total"] == 0
    assert snap["quarantined_shards"] == []


def test_tempfail_costs_one_restart_with_fresh_run_id():
    spawn = _scripted({0: {1: EXIT_TEMPFAIL}})
    sup = _fast(spawn, 2, run_id_prefix="job")
    snap = sup.run()
    assert snap["generations"] == 2
    assert snap["restarts_by_cause"] == {"tempfail": 1}
    run_ids = sorted({c[3] for c in spawn.calls})
    assert run_ids == ["job-g0", "job-g1"]
    assert snap["restart_replay_seconds"] >= 0.0


def test_save_failed_peers_are_not_charged():
    # rank 0 is the root cause (watchdog); rank 1 exits the
    # EXIT_SAVE_FAILED consequence code — only rank 0's budget is hit
    spawn = _scripted({0: {0: EXIT_WATCHDOG, 1: EXIT_SAVE_FAILED}})
    sup = _fast(spawn, 2)
    snap = sup.run()
    assert snap["restarts_by_cause"] == {"watchdog": 1}
    assert list(sup._failures) == [0]


def test_diagnose_all_save_failed_falls_back_to_first_nonzero():
    rank, rc, cause = Supervisor._diagnose(
        {0: EXIT_SAVE_FAILED, 1: EXIT_SAVE_FAILED})
    assert (rank, rc) == (0, EXIT_SAVE_FAILED)
    rank, rc, cause = Supervisor._diagnose({0: 0, 1: -9, 2: EXIT_SAVE_FAILED})
    assert (rank, rc, cause) == (1, -9, "killed")


# -- restart budget / rolling window -----------------------------------------

def test_crash_loop_exhausts_budget_naming_rank():
    plan = {g: {1: 1} for g in range(10)}
    sup = _fast(_scripted(plan), 2, max_restarts=2)
    with pytest.raises(RestartBudgetExhausted) as ei:
        sup.run()
    assert ei.value.rank == 1
    assert ei.value.cause == "crash"
    assert "rank 1" in str(ei.value)
    assert "budget 2" in str(ei.value)


def test_rolling_window_prunes_old_failures():
    t = [1000.0]
    sup = Supervisor(_scripted({}), 2, max_restarts=2,
                     restart_window=60.0, clock=lambda: t[0],
                     sleep=lambda s: None)
    sup._charge(1, 1, "crash")
    t[0] += 59.0
    sup._charge(1, 1, "crash")  # 2 in window == budget: still alive
    t[0] += 59.0               # first failure now 118s old → pruned
    sup._charge(1, 1, "crash")
    t[0] += 1.0
    with pytest.raises(RestartBudgetExhausted):
        sup._charge(1, 1, "crash")  # 3 inside 60s > budget of 2


def test_store_lost_is_charged_to_the_store_not_a_rank():
    plan = {g: {0: EXIT_STORE_LOST} for g in range(10)}
    sup = _fast(_scripted(plan), 2, max_restarts=1)
    with pytest.raises(RestartBudgetExhausted) as ei:
        sup.run()
    assert ei.value.rank is None
    assert ei.value.cause == "store_lost"
    assert "store master" in str(ei.value)
    assert list(sup._failures) == ["store"]


# -- shard quarantine ---------------------------------------------------------

def test_correlated_crash_loop_quarantines_the_shard():
    plan = {g: {1: 1} for g in range(10)}
    sup = _fast(_scripted(plan), 2, max_restarts=2,
                shard_of=lambda r: f"shard-{r}", quarantine_threshold=2)
    with pytest.raises(RestartBudgetExhausted) as ei:
        sup.run()
    assert ei.value.shard == "shard-1"
    assert "shard-1" in str(ei.value)
    assert "quarantined" in str(ei.value)
    assert sup.quarantined_shards == {"shard-1"}


def test_uncorrelated_failures_do_not_quarantine():
    # failures alternate between rank 0's and rank 1's shards — no
    # single-shard correlation, so nothing is quarantined
    plan = {0: {0: 1}, 1: {1: 1}, 2: {0: 1}, 3: {1: 1}}
    sup = _fast(_scripted(plan), 2, max_restarts=3,
                shard_of=lambda r: f"shard-{r}", quarantine_threshold=2)
    snap = sup.run()
    assert snap["quarantined_shards"] == []
    assert snap["restarts_total"] == 4


# -- SDC hardware ledger / rank quarantine -----------------------------------

def test_sdc_verdicts_quarantine_without_touching_crash_budget():
    # rank 1 is fingered by replica consensus twice; with the code-crash
    # budget at ZERO the run must still reach quarantine + downsize —
    # proof the hardware ledger never shares a key with crash charges
    plan = {0: {1: EXIT_SDC}, 1: {1: EXIT_SDC}}
    sup = _fast(_scripted(plan), 2, max_restarts=0, min_world=1,
                sdc_quarantine_threshold=2)
    snap = sup.run()
    assert snap["quarantined_ranks"] == [1]
    assert snap["sdc_verdicts"] == {"1": 2}
    assert snap["restarts_by_cause"] == {"sdc": 2}
    assert snap["world"] == 1
    assert snap["final_rcs"] == {0: 0}
    resize = [rz for rz in snap["resizes"] if rz.get("quarantined")]
    assert resize and resize[0]["dead_ranks"] == [1]
    # the crash ledger never saw rank 1 — only the sdc:<rank> key did
    assert 1 not in sup._failures
    assert "sdc:1" in sup._failures


def test_sdc_restart_budget_exhausts_naming_the_hardware():
    plan = {g: {0: EXIT_SDC} for g in range(10)}
    sup = _fast(_scripted(plan), 1, sdc_max_restarts=1,
                sdc_quarantine_threshold=99)
    with pytest.raises(RestartBudgetExhausted) as ei:
        sup.run()
    assert ei.value.rank == 0
    assert ei.value.cause == "sdc"
    assert "hardware" in str(ei.value)


def test_sdc_quarantine_below_min_world_fails_loudly():
    plan = {g: {1: EXIT_SDC} for g in range(10)}
    sup = _fast(_scripted(plan), 2, min_world=2,
                sdc_quarantine_threshold=1)
    with pytest.raises(RestartBudgetExhausted) as ei:
        sup.run()
    assert ei.value.cause == "sdc"
    assert "min_world=2" in str(ei.value)


# -- lease expiry / elastic downsizing ---------------------------------------

def test_dead_rank_past_lease_downsizes_the_world():
    calls = []

    def spawn(rank, world, run_id, generation):
        calls.append((generation, rank, world))
        if generation == 0 and rank == 2:
            raise SpawnFailed("host gone")
        return _child(0)

    sup = _fast(spawn, 3, spawn_lease=0.2, min_world=1)
    snap = sup.run()
    assert snap["world"] == 2
    assert snap["final_rcs"] == {0: 0, 1: 0}
    assert snap["resizes"] == [{"generation": 0, "from_world": 3,
                                "to_world": 2, "dead_ranks": [2]}]
    assert snap["restarts_by_cause"] == {"lease_expired": 1}
    # generation 1 respawned everyone at the smaller world
    assert {(r, w) for g, r, w in calls if g == 1} == {(0, 2), (1, 2)}


def test_downsizing_below_min_world_fails_loudly():
    def spawn(rank, world, run_id, generation):
        raise SpawnFailed("cluster gone")

    sup = _fast(spawn, 2, spawn_lease=0.2, min_world=2)
    with pytest.raises(RestartBudgetExhausted) as ei:
        sup.run()
    assert ei.value.cause == "lease_expired"
    assert "min_world=2" in str(ei.value)


# -- snapshots ----------------------------------------------------------------

def test_supervision_snapshot_defaults_to_zero_block(monkeypatch):
    monkeypatch.setattr(sup_mod, "_LAST_SUPERVISOR", None)
    snap = supervision_snapshot()
    assert snap == {"world": 0, "generations": 0, "restarts_total": 0,
                    "restarts_by_cause": {}, "promotions": 0,
                    "quarantined_shards": [], "quarantined_ranks": [],
                    "sdc_verdicts": {}, "resizes": [],
                    "restart_replay_seconds": 0.0}


def test_supervision_snapshot_reflects_last_supervisor():
    sup = _fast(_scripted({0: {0: EXIT_DRAIN}}), 1)
    sup.run()
    snap = supervision_snapshot()
    assert snap["restarts_by_cause"] == {"drain": 1}
    assert snap["generations"] == 2
