"""``distributed.rpc`` round-trip tests (ref: ``test/rpc/test_rpc_base.py``
/ ``test_rpc.py``): sync/async calls, futures, serialization of
closures, error and timeout propagation, worker-info surface."""
import multiprocessing as mp
import socket
import time

import numpy as np
import pytest

import paddle_tpu.distributed.rpc as rpc


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _add(a, b):
    return a + b


def _np_mul(x, k):
    return (np.asarray(x) * k).tolist()


def _boom():
    raise ValueError("remote boom")


@pytest.fixture
def agent():
    info = rpc.init_rpc("worker0")
    yield info
    rpc.shutdown()


class TestSingleWorker:
    def test_sync_async_local(self, agent):
        assert rpc.rpc_sync("worker0", _add, args=(2, 3)) == 5
        fut = rpc.rpc_async("worker0", _add, args=(4, 5))
        assert fut.wait() == 9
        assert fut.done()
        assert fut.result() == 9

    def test_worker_info_surface(self, agent):
        me = rpc.get_current_worker_info()
        assert me.name == "worker0" and me.rank == 0
        assert rpc.get_worker_info("worker0") == me
        assert rpc.get_all_worker_infos() == [me]
        with pytest.raises(ValueError, match="unknown rpc worker"):
            rpc.get_worker_info("nobody")

    def test_socket_path_closure_and_errors(self, agent):
        # alias the local server under another name: calls take the real
        # wire path (serialize -> socket -> execute -> reply) in-process
        me = rpc.get_current_worker_info()
        rpc._state["workers"]["remote0"] = rpc.WorkerInfo(
            "remote0", 1, me.ip, me.port)
        assert rpc.rpc_sync("remote0", _add, args=(10, 20)) == 30
        # closures need cloudpickle — the reference's plain-pickle
        # PythonFunc cannot do this
        k = 7
        assert rpc.rpc_sync("remote0", lambda v: v * k, args=(6,)) == 42
        assert rpc.rpc_sync("remote0", _np_mul,
                            args=([1, 2, 3], 2)) == [2, 4, 6]
        with pytest.raises(ValueError, match="remote boom"):
            rpc.rpc_sync("remote0", _boom)
        fut = rpc.rpc_async("remote0", _boom)
        with pytest.raises(ValueError, match="remote boom"):
            fut.wait()

    def test_timeout_raises(self, agent):
        me = rpc.get_current_worker_info()
        rpc._state["workers"]["remote0"] = rpc.WorkerInfo(
            "remote0", 1, me.ip, me.port)
        with pytest.raises(OSError):  # socket.timeout is an OSError
            rpc.rpc_sync("remote0", time.sleep, args=(3,), timeout=0.3)
        # timeout <= 0 = infinite (reference default): must NOT raise
        assert rpc.rpc_sync("remote0", _add, args=(1, 1), timeout=-1) == 2


def _worker(rank, world_size, endpoint, q):
    import paddle_tpu.distributed.rpc as rpc
    rpc.init_rpc(f"worker{rank}", rank, world_size, endpoint)
    if rank == 1:
        got = rpc.rpc_sync("worker0", _add, args=(40, 2))
        fut = rpc.rpc_async("worker0", _np_mul, args=([5], 3))
        q.put((got, fut.wait()))
    else:
        # keep serving until the caller reports completion
        for _ in range(200):
            if not q.empty():
                break
            time.sleep(0.05)
    rpc.shutdown()


@pytest.mark.slow
def test_two_process_round_trip():
    """The reference's RpcTestBase pattern: N processes rendezvous on a
    master endpoint, worker1 calls into worker0, results via a queue."""
    endpoint = f"127.0.0.1:{_free_port()}"
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    ps = [ctx.Process(target=_worker, args=(r, 2, endpoint, q))
          for r in range(2)]
    for p in ps:
        p.start()
    got = q.get(timeout=120)
    q.put("done")  # let worker0 exit
    for p in ps:
        p.join(timeout=60)
        assert p.exitcode == 0
    assert got == (42, [15])
