"""Round-4 vision transform parity: geometric warps vs PIL, photometric
adjusts vs PIL ImageEnhance / colorsys, Random* classes
(ref: ``python/paddle/vision/transforms/transforms.py:1385,1836``,
``functional.py``)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.vision.transforms as T

Image = pytest.importorskip("PIL.Image")
from PIL import ImageEnhance  # noqa: E402

RNG = np.random.RandomState(0)
IMG = RNG.randint(0, 255, (16, 20, 3)).astype(np.uint8)
PIM = Image.fromarray(IMG)


@pytest.mark.parametrize("angle", [90, 37, -120, 180])
def test_rotate_matches_pil_exactly(angle):
    got = T.rotate(IMG, angle, interpolation="nearest")
    want = np.asarray(PIM.rotate(angle, resample=Image.NEAREST))
    np.testing.assert_array_equal(got, want)


def test_rotate_expand_matches_pil():
    got = T.rotate(IMG, 45, interpolation="nearest", expand=True)
    want = np.asarray(PIM.rotate(45, resample=Image.NEAREST, expand=True))
    assert got.shape == want.shape
    # allow a sliver of edge rounding difference
    assert (got != want).mean() < 0.02


def test_affine_identity_and_translate():
    np.testing.assert_array_equal(T.affine(IMG, 0), IMG)
    got = T.affine(IMG, 0, translate=(3, 2), interpolation="nearest")
    want = np.asarray(PIM.rotate(0, translate=(3, 2),
                                 resample=Image.NEAREST))
    np.testing.assert_array_equal(got, want)


def test_affine_scale_shear_runs():
    out = T.affine(IMG, 15, translate=(1, 1), scale=1.3, shear=(5, 5),
                   interpolation="bilinear")
    assert out.shape == IMG.shape and out.dtype == np.uint8


def test_perspective_identity_and_shift():
    corners = [(0, 0), (19, 0), (19, 15), (0, 15)]
    np.testing.assert_array_equal(
        T.perspective(IMG, corners, corners), IMG)
    # pure translation expressed as a perspective: shift right by 2
    end = [(x + 2, y) for x, y in corners]
    got = T.perspective(IMG, corners, end, interpolation="nearest")
    np.testing.assert_array_equal(got[:, 2:], IMG[:, :-2])


@pytest.mark.parametrize("factor", [0.4, 1.0, 1.7])
def test_photometric_vs_pil(factor):
    cases = [(T.adjust_brightness, ImageEnhance.Brightness),
             (T.adjust_contrast, ImageEnhance.Contrast),
             (T.adjust_saturation, ImageEnhance.Color)]
    for fn, enh in cases:
        got = fn(IMG, factor).astype(int)
        want = np.asarray(enh(PIM).enhance(factor)).astype(int)
        assert np.abs(got - want).max() <= 1, fn.__name__


def test_adjust_hue_vs_colorsys():
    import colorsys
    x = RNG.rand(64, 3).astype(np.float32)
    got = T.adjust_hue(x.reshape(64, 1, 3), 0.25).reshape(64, 3)
    want = np.array([
        colorsys.hsv_to_rgb((colorsys.rgb_to_hsv(*p)[0] + 0.25) % 1.0,
                            *colorsys.rgb_to_hsv(*p)[1:]) for p in x])
    np.testing.assert_allclose(got, want, atol=1e-5)
    with pytest.raises(ValueError):
        T.adjust_hue(IMG, 0.7)


def test_to_grayscale():
    g1 = T.to_grayscale(IMG)
    assert g1.shape == (16, 20, 1)
    g3 = T.to_grayscale(IMG, num_output_channels=3)
    assert g3.shape == IMG.shape
    want = np.asarray(PIM.convert("L"))
    assert np.abs(g1[..., 0].astype(int) - want.astype(int)).max() <= 1


def test_pad_modes():
    out = T.pad(IMG, 2)
    assert out.shape == (20, 24, 3) and out[0, 0, 0] == 0
    out = T.pad(IMG, (1, 2), fill=7)
    assert out.shape == (20, 22, 3) and out[0, 0, 0] == 7
    out = T.pad(IMG, (1, 2, 3, 4), padding_mode="reflect")
    assert out.shape == (22, 24, 3)
    with pytest.raises(ValueError):
        T.pad(IMG, 1, padding_mode="bogus")


def test_erase_hwc_and_chw():
    out = T.erase(IMG, 2, 3, 4, 5, 0)
    assert (out[2:6, 3:8] == 0).all() and (IMG[2:6, 3:8] != 0).any()
    t = pt.to_tensor(np.ones((3, 8, 8), "float32"))
    out = T.erase(t, 1, 1, 2, 2, 0.5)
    assert np.allclose(out.numpy()[:, 1:3, 1:3], 0.5)
    # inplace on tensor mutates in place
    T.erase(t, 0, 0, 1, 1, -1.0, inplace=True)
    assert float(t.numpy()[0, 0, 0]) == -1.0


def test_random_affine_class():
    tr = T.RandomAffine(degrees=20, translate=(0.1, 0.1),
                        scale=(0.8, 1.2), shear=10)
    out = tr(IMG)
    assert out.shape == IMG.shape and out.dtype == np.uint8
    with pytest.raises(ValueError):
        T.RandomAffine(10, translate=(1.5, 0))
    with pytest.raises(ValueError):
        T.RandomAffine(10, scale=(-1, 1))


def test_random_perspective_class():
    tr = T.RandomPerspective(prob=1.0, distortion_scale=0.4)
    out = tr(IMG)
    assert out.shape == IMG.shape
    tr0 = T.RandomPerspective(prob=0.0)
    np.testing.assert_array_equal(tr0(IMG), IMG)
    with pytest.raises(ValueError):
        T.RandomPerspective(prob=2.0)


def test_random_erasing_class():
    import random as pyrandom
    pyrandom.seed(3)
    tr = T.RandomErasing(prob=1.0, scale=(0.1, 0.3), value=0)
    src = np.ones((16, 16, 3), np.float32)
    out = tr(src)
    assert (out == 0).any() and src.shape == out.shape
    # CHW tensor path with value='random'
    trr = T.RandomErasing(prob=1.0, value="random")
    t = pt.to_tensor(np.zeros((3, 16, 16), "float32"))
    out = trr(t)
    assert out.shape == [3, 16, 16]
    with pytest.raises(ValueError):
        T.RandomErasing(value="bogus")


def test_random_rotation_arbitrary_angle():
    import random as pyrandom
    pyrandom.seed(0)
    tr = T.RandomRotation(30, interpolation="bilinear")
    out = tr(IMG)
    assert out.shape == IMG.shape


def test_hue_transform_uses_real_hsv():
    import random as pyrandom
    pyrandom.seed(1)
    tr = T.HueTransform(0.3)
    out = tr(IMG)
    assert out.shape == IMG.shape and out.dtype == np.uint8
    with pytest.raises(ValueError):
        T.HueTransform(0.9)


def test_review_fixes():
    # grayscale hue no-op
    g = np.zeros((4, 4), np.uint8)
    assert T.adjust_hue(g, 0.2) is g
    # per-channel pad fill
    out = T.pad(IMG, 2, fill=(255, 0, 0))
    assert out[0, 0, 0] == 255 and out[0, 0, 1] == 0
    # Pad class honors padding_mode
    out = T.Pad(2, padding_mode="edge")(IMG)
    assert out[0, 2, 0] == IMG[0, 0, 0]
    # RandomPerspective skip path returns input untouched
    src2d = np.zeros((5, 6), np.uint8)
    assert T.RandomPerspective(prob=0.0)(src2d) is src2d


def test_multiplex_cdist_validation():
    ins = [pt.to_tensor(np.ones((2, 3), "float32"))] * 2
    with pytest.raises(ValueError):
        pt.multiplex(ins, pt.to_tensor(np.array([[0], [1], [1]], "int32")))
    with pytest.raises(ValueError):
        pt.linalg.cdist(pt.to_tensor(np.ones((2, 2), "float32")),
                        pt.to_tensor(np.ones((2, 2), "float32")), p=-1.0)
