"""Ring attention / Ulysses context-parallel tests on the 8-device CPU
mesh. Oracle: dense attention over the full (gathered) sequence — the
same single-vs-distributed parity pattern the reference uses for its
hybrid-parallel tests (test/collective/fleet/hybrid_parallel_mp_model.py).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.distributed.fleet.meta_parallel.sequence_parallel import (
    gather_sequence, ring_attention, split_sequence, ulysses_attention)
from paddle_tpu.ops.pallas_ops import mha_reference
from paddle_tpu.distributed._jax_compat import shard_map as _shard_map, use_mesh as _use_mesh


def _mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ("sep",))


def _rand(shape, seed):
    return jnp.asarray(
        np.random.RandomState(seed).standard_normal(shape).astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    b, h, s, d = 2, 2, 64, 16
    n = 4
    q, k, v = (_rand((b, h, s, d), i) for i in range(3))
    ref = mha_reference(q, k, v, causal=causal)

    def f(q, k, v):
        return ring_attention(q, k, v, axis_name="sep", causal=causal)

    out = jax.jit(_shard_map(
        f, mesh=_mesh(n), in_specs=P(None, None, "sep", None),
        out_specs=P(None, None, "sep", None)))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_ring_attention_grads_match_dense(causal):
    b, h, s, d = 1, 2, 32, 8
    n = 4
    q, k, v = (_rand((b, h, s, d), 10 + i) for i in range(3))

    def loss_ring(q, k, v):
        def f(q, k, v):
            return ring_attention(q, k, v, axis_name="sep", causal=causal)
        o = _shard_map(f, mesh=_mesh(n),
                          in_specs=P(None, None, "sep", None),
                          out_specs=P(None, None, "sep", None))(q, k, v)
        return jnp.sum(o * jnp.sin(o))

    def loss_ref(q, k, v):
        o = mha_reference(q, k, v, causal=causal)
        return jnp.sum(o * jnp.sin(o))

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(causal):
    b, h, s, d = 2, 4, 64, 16
    n = 4
    q, k, v = (_rand((b, h, s, d), 20 + i) for i in range(3))
    ref = mha_reference(q, k, v, causal=causal)

    def f(q, k, v):
        return ulysses_attention(q, k, v, axis_name="sep", causal=causal)

    out = jax.jit(_shard_map(
        f, mesh=_mesh(n), in_specs=P(None, None, "sep", None),
        out_specs=P(None, None, "sep", None)))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_split_gather_roundtrip():
    x = _rand((2, 64, 8), 5)
    n = 4

    def f(x):
        lo = split_sequence(x, "sep", axis=1)
        assert lo.shape == (2, 16, 8)
        return gather_sequence(lo, "sep", axis=1)

    out = _shard_map(f, mesh=_mesh(n), in_specs=P(),
                        out_specs=P(), check_vma=False)(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_ring_attention_long_sequence_memory_shape():
    """8-way sep over S=256: each device only ever sees S/8=32 locally."""
    b, h, s, d = 1, 1, 256, 8
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("sep",))
    q, k, v = (_rand((b, h, s, d), 30 + i) for i in range(3))

    def f(q, k, v):
        assert q.shape == (b, h, s // 8, d)
        return ring_attention(q, k, v, axis_name="sep", causal=True)

    out = jax.jit(_shard_map(
        f, mesh=mesh, in_specs=P(None, None, "sep", None),
        out_specs=P(None, None, "sep", None)))(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_ring_attention_kernel_path_matches_xla(causal):
    """use_kernel=True (Pallas flash blocks, traced causal_shift,
    differentiable lse merge) == the XLA partial-softmax path."""
    b, h, s, d = 1, 2, 64, 16
    n = 4
    q, k, v = (_rand((b, h, s, d), 40 + i) for i in range(3))

    def run(use_kernel):
        def f(q, k, v):
            return ring_attention(q, k, v, axis_name="sep", causal=causal,
                                  use_kernel=use_kernel, interpret=True)
        # check_vma=False: the pallas HLO *interpreter* cannot propagate
        # sep-varying avals through its internal dynamic_slice (real-TPU
        # lowering does not take that path)
        return jax.jit(_shard_map(
            f, mesh=_mesh(n), in_specs=P(None, None, "sep", None),
            out_specs=P(None, None, "sep", None), check_vma=False))(q, k, v)

    np.testing.assert_allclose(np.asarray(run(True)),
                               np.asarray(run(False)),
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(run(True)),
                               np.asarray(mha_reference(q, k, v,
                                                        causal=causal)),
                               atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_ring_attention_kernel_path_grads():
    b, h, s, d = 1, 1, 64, 16
    n = 4
    q, k, v = (_rand((b, h, s, d), 50 + i) for i in range(3))

    def loss(use_kernel):
        def f(q, k, v):
            o = ring_attention(q, k, v, axis_name="sep", causal=True,
                               use_kernel=use_kernel, interpret=True)
            return o
        def l(q, k, v):
            o = _shard_map(
                f, mesh=_mesh(n), in_specs=P(None, None, "sep", None),
                out_specs=P(None, None, "sep", None),
                check_vma=False)(q, k, v)
            return (o ** 2).sum()
        return jax.grad(l, argnums=(0, 1, 2))(q, k, v)

    gk, gx = loss(True), loss(False)
    for a, b_ in zip(gk, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=3e-4, rtol=3e-4)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_ulysses_kernel_path_matches_xla(causal):
    b, h, s, d = 1, 4, 64, 16
    n = 4
    q, k, v = (_rand((b, h, s, d), 60 + i) for i in range(3))

    def run(use_kernel):
        def f(q, k, v):
            return ulysses_attention(q, k, v, axis_name="sep",
                                     causal=causal, use_kernel=use_kernel,
                                     interpret=True)
        return jax.jit(_shard_map(
            f, mesh=_mesh(n), in_specs=P(None, None, "sep", None),
            out_specs=P(None, None, "sep", None), check_vma=False))(q, k, v)

    np.testing.assert_allclose(np.asarray(run(True)),
                               np.asarray(run(False)),
                               atol=2e-4, rtol=2e-4)
