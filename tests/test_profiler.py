"""Profiler tests (ref: test/legacy_test/test_profiler.py family)."""
from __future__ import annotations

import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.profiler as profiler
from paddle_tpu.profiler import (Profiler, ProfilerState, ProfilerTarget,
                                 make_scheduler, export_chrome_tracing,
                                 RecordEvent, SortedKeys)


class TestScheduler:
    def test_states(self):
        sched = make_scheduler(closed=1, ready=1, record=2, repeat=1,
                               skip_first=1)
        states = [sched(i) for i in range(7)]
        assert states[0] == ProfilerState.CLOSED          # skip_first
        assert states[1] == ProfilerState.CLOSED
        assert states[2] == ProfilerState.READY
        assert states[3] == ProfilerState.RECORD
        assert states[4] == ProfilerState.RECORD_AND_RETURN
        assert states[5] == ProfilerState.CLOSED          # repeat exhausted
        assert states[6] == ProfilerState.CLOSED

    def test_repeat_zero_cycles_forever(self):
        sched = make_scheduler(closed=1, ready=1, record=2, repeat=0)
        period = [ProfilerState.CLOSED, ProfilerState.READY,
                  ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN]
        assert [sched(i) for i in range(12)] == period * 3

    def test_no_warmup_record_only(self):
        # closed=0, ready=0: recording from step 0, last step of each
        # window returns the trace
        sched = make_scheduler(closed=0, ready=0, record=3, repeat=1)
        assert [sched(i) for i in range(4)] == [
            ProfilerState.RECORD, ProfilerState.RECORD,
            ProfilerState.RECORD_AND_RETURN, ProfilerState.CLOSED]

    def test_record_window_of_one_always_returns(self):
        # a one-step record window never yields plain RECORD
        sched = make_scheduler(closed=1, ready=0, record=1, repeat=0)
        states = [sched(i) for i in range(8)]
        assert ProfilerState.RECORD not in states
        assert states[1] == ProfilerState.RECORD_AND_RETURN

    def test_skip_first_is_a_pure_offset(self):
        base = make_scheduler(closed=1, ready=1, record=2, repeat=2)
        offs = make_scheduler(closed=1, ready=1, record=2, repeat=2,
                              skip_first=3)
        for step in range(12):
            assert offs(step + 3) == base(step)
        assert all(offs(i) == ProfilerState.CLOSED for i in range(3))


class TestRecordEventNesting:
    def setup_method(self):
        import paddle_tpu.core as core
        core.tracer_disable()
        core.tracer_clear()

    def teardown_method(self):
        import paddle_tpu.core as core
        core.tracer_disable()

    def test_nested_spans_contained_in_parent(self):
        import paddle_tpu.core as core
        core.tracer_enable()
        with RecordEvent("outer"):
            with RecordEvent("inner"):
                sum(range(1000))
        spans = {n: (s, s + d) for (n, s, d, _tid) in core.tracer_events()
                 if n in ("outer", "inner")}
        assert set(spans) == {"outer", "inner"}
        (os_, oe), (is_, ie) = spans["outer"], spans["inner"]
        assert os_ <= is_ and ie <= oe, "inner span escapes outer span"
        assert oe - os_ >= ie - is_ >= 0

    def test_disabled_tracer_records_nothing(self):
        import paddle_tpu.core as core
        with RecordEvent("ghost"):
            pass
        assert "ghost" not in [e[0] for e in core.tracer_events()]

    def test_on_trace_ready_fires_once_per_repeat(self):
        fired = []
        p = Profiler(targets=[ProfilerTarget.CPU],
                     scheduler=make_scheduler(closed=0, ready=0, record=2,
                                              repeat=2),
                     on_trace_ready=lambda prof: fired.append(prof.step_num))
        p.start()
        for _ in range(4):
            with RecordEvent("w"):
                pass
            p.step()
        p.stop()
        assert len(fired) == 2


class TestProfiler:
    def setup_method(self):
        import paddle_tpu.core as core
        core.tracer_disable()
        core.tracer_clear()

    def test_record_and_export(self, tmp_path):
        out_dir = str(tmp_path / "prof")
        p = Profiler(targets=[ProfilerTarget.CPU],
                     scheduler=make_scheduler(closed=0, ready=0, record=3,
                                              repeat=1),
                     on_trace_ready=export_chrome_tracing(out_dir, "w0"))
        p.start()
        for step in range(3):
            with RecordEvent("train_step"):
                _ = (pt.to_tensor(np.ones((4, 4), np.float32)) * 2).numpy()
            p.step()
        p.stop()
        files = os.listdir(out_dir)
        assert files, "no trace exported"
        j = json.load(open(os.path.join(out_dir, files[0])))
        names = {e["name"] for e in j["traceEvents"]}
        assert "train_step" in names

    def test_summary_table(self):
        with Profiler(targets=[ProfilerTarget.CPU]) as p:
            for _ in range(5):
                with RecordEvent("stepA"):
                    pass
                with RecordEvent("stepB"):
                    pass
        table = p.summary(sorted_by=SortedKeys.Calls)
        assert "stepA" in table and "stepB" in table
        assert "Calls" in table

    def test_context_manager_and_scheduler_window(self, tmp_path):
        exported = []
        p = Profiler(scheduler=(1, 3),
                     on_trace_ready=lambda prof: exported.append(
                         prof.step_num))
        p.start()
        for _ in range(4):
            with RecordEvent("w"):
                pass
            p.step()
        p.stop()
        assert exported, "on_trace_ready never fired"

    def test_record_function_decorator(self):
        from paddle_tpu.profiler.utils import record_function
        import paddle_tpu.core as core
        core.tracer_clear()
        core.tracer_enable()

        @record_function("my_fn")
        def f(x):
            return x * 2

        assert f(21) == 42
        assert "my_fn" in [e[0] for e in core.tracer_events()]
        core.tracer_disable()

    def test_wrap_optimizers(self):
        from paddle_tpu.profiler.utils import wrap_optimizers
        import paddle_tpu.core as core
        wrap_optimizers()
        core.tracer_clear()
        core.tracer_enable()
        lin = pt.nn.Linear(4, 2)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
        loss = (lin(pt.to_tensor(np.ones((2, 4), np.float32))) ** 2).mean()
        loss.backward()
        opt.step()
        names = [e[0] for e in core.tracer_events()]
        assert any(n.startswith("Optimizer.step") for n in names)
        core.tracer_disable()
