"""Benchmark entry (driver-run on real TPU hardware).

Measures BASELINE.md config[0]: ResNet-50 training throughput on
CIFAR-10-shaped data (batch 256, 3x32x32), images/sec, single chip.

The whole train step (forward + backward + Adam/Momentum update) is one
jitted XLA program with bf16 AMP — the framework's designed fast path.
Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import sys
import time

BATCH = 256
WARMUP = 5
ITERS = 30


def main():
    import numpy as np
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.jit.api import functional_call
    from paddle_tpu.tensor import Tensor

    pt.seed(0)
    net = pt.vision.models.resnet50(num_classes=10)
    # bf16 params for MXU throughput; fp32 master weights live in opt state
    pt.amp.decorate(net, level="O2", dtype="bfloat16")
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                parameters=net.parameters(),
                                multi_precision=True)

    params = {k: p._data for k, p in net.named_parameters()}
    buffers = {k: b._data for k, b in net.named_buffers()}
    opt_state = opt.init_state_tree(params)
    fwd = getattr(net, "_orig_forward", net.forward)

    def train_step(params, buffers, opt_state, x, y):
        def loss_of(p):
            out, new_buffers = functional_call(
                net, p, buffers, (Tensor(x),), training=True, forward_fn=fwd)
            logits = out._data.astype(jnp.float32)
            logp = jax.nn.log_softmax(logits)
            loss = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
            return loss, new_buffers

        (loss, new_buffers), grads = jax.value_and_grad(
            loss_of, has_aux=True)(params)
        new_params, new_opt = opt.apply_gradients_tree(params, grads,
                                                       opt_state)
        return loss, new_params, new_buffers, new_opt

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))

    def _flops_per_step(compiled):
        """Model FLOPs per step from XLA's own cost analysis (None if n/a)."""
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0]
            return float(ca.get("flops", 0.0)) or None
        except Exception:
            return None

    # bf16 peak FLOP/s per chip by device kind (public spec sheets)
    _PEAK = {
        "TPU v4": 275e12, "TPU v5": 459e12, "TPU v5p": 459e12,
        "TPU v5e": 197e12, "TPU v5 lite": 197e12, "TPU v6e": 918e12,
        "TPU v6 lite": 918e12, "TPU v3": 123e12, "TPU v2": 45e12,
    }

    def _peak_flops():
        kind = jax.local_devices()[0].device_kind.lower()
        # longest prefix wins ("TPU v5 lite" must not match "TPU v5")
        for k in sorted(_PEAK, key=len, reverse=True):
            if kind.startswith(k.lower()):
                return _PEAK[k]
        return None

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(BATCH, 3, 32, 32).astype(np.float32)).astype(
        jnp.bfloat16)
    y = jnp.asarray(rng.randint(0, 10, BATCH).astype(np.int32))

    # one AOT compile; the timing loop runs the same executable
    compiled = step.lower(params, buffers, opt_state, x, y).compile()
    flops = _flops_per_step(compiled)

    for _ in range(WARMUP):
        loss, params, buffers, opt_state = compiled(params, buffers,
                                                    opt_state, x, y)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss, params, buffers, opt_state = compiled(params, buffers,
                                                    opt_state, x, y)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    ips = BATCH * ITERS / dt
    peak = _peak_flops()
    mfu = None
    if flops and peak:
        mfu = round(flops * (ITERS / dt) / peak, 4)
    print(json.dumps({
        "metric": "resnet50_cifar10_train_throughput",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": None,
        "mfu": mfu,
        "flops_per_step": flops,
        "device_kind": jax.local_devices()[0].device_kind,
    }))


if __name__ == "__main__":
    main()
